"""The single-op book transition: ADD (match + rest), DEL (cancel), NOP.

This one function replaces the reference's entire consumer hot path —
SetOrder/Match/MatchOrder/DeleteOrder (gomengine/engine/engine.go:56-198) and
all the Redis round trips behind them (SURVEY §3.2: ~6 + 2·levels + 4·fills
RTTs per order) — with a fixed number of O(cap) vector operations:

  match   = prefix mask + one exclusive cumsum + clip      (engine.go:118-198)
  removal = left-shift of the filled prefix                (nodelink.go:124-166)
  rest    = right-shift insert at the priority slot        (nodepool.go:31-46)
  cancel  = masked locate + left-shift                     (engine.go:87-116)

Everything is branch-free (ADD and DEL paths are both computed and selected
by mask) so the function vmaps cleanly across the symbol axis and compiles
to a static XLA graph — no data-dependent control flow, per the TPU design
rules.

TPU lowering discipline — the entire step is gather/scatter-free:

  * Side selection (`own` = the taker's side, `opp` = the opposing side) is
    NOT a dynamic index into the [2, cap] axis (under vmap that lowers to a
    per-row gather, and the write-back to a per-row scatter — both serialize
    badly on TPU). Both rows are read with static slices and selected
    elementwise by the side mask; write-back re-stacks two static rows.
  * The match compaction ("drop the fully-filled prefix of length n") is NOT
    a dynamic-offset gather. It is decomposed into log2(cap) static
    shift-by-2^k passes, each enabled by one bit of n — every pass is a
    static slice + pad + select, which XLA fuses into the surrounding
    elementwise work.
  * Insert/cancel shifts are static shift-by-one selects; the cancel-volume
    read is a masked sum, not a dynamic scalar index.

Scalar semantics are checked against the Python oracle in
tests/test_engine_step.py; the oracle is the spec (SURVEY §7 step 1).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..types import Action
from .book import BUY, BookConfig, BookState, DeviceOp, StepOutput

# Device-side action codes are the types.Action values (single source of
# truth; they mirror gomengine/main.go:14-18's iota consts).
ACTION_NOP = int(Action.NOP)
ACTION_ADD = int(Action.ADD)
ACTION_DEL = int(Action.DEL)


def _bsel(c, a, b):
    """Select `a` where `c` else `b`, for a scalar-per-lane bool `c` and
    vector operands. Written as an integer blend (m*a + (1-m)*b) instead of
    jnp.where: under vmap a scalar predicate broadcasts to a [B, cap] i1
    vector, and Mosaic (Pallas TPU) cannot relayout 1-bit vectors across
    the minor dims — the i32 mask broadcast is supported everywhere and
    fuses identically under XLA."""
    m = jnp.asarray(c, a.dtype)
    return m * a + (1 - m) * b


# Saturation ceiling for 32-bit depth prefix sums: with every addend
# clamped here, one Hillis-Steele add of two partials stays below 2^31.
# Exactness argument (int32 operating contract, per-order lots <= LOT_MAX):
# a fill only reads cum_excl through clip(volume - cum_excl, 0, lots), so
# any clamped value >= volume yields the same (zero) fill as the true sum,
# and partials below the clamp are exact.
SAT32_MAX = (1 << 30) - 1
LOT_MAX32 = SAT32_MAX  # documented int32-mode per-order lot ceiling


def _prefix_sum(a):
    """Inclusive prefix sum along the last axis via Hillis-Steele log-shift
    passes (static slice + pad + add). Used instead of jnp.cumsum because
    Mosaic (Pallas TPU) has no cumsum lowering; XLA fuses the passes into the
    surrounding elementwise work either way.

    32-bit inputs saturate at SAT32_MAX instead of wrapping — fills stay
    exact (see SAT32_MAX) no matter how deep the crossed book is."""
    n = a.shape[-1]
    sat = jnp.dtype(a.dtype).itemsize <= 4
    if sat:
        a = jnp.minimum(a, SAT32_MAX)
    k = 1
    while k < n:
        pad = [(0, 0)] * (a.ndim - 1) + [(k, 0)]
        a = a + jnp.pad(a[..., :-k], pad)
        if sat:
            a = jnp.minimum(a, SAT32_MAX)
        k *= 2
    return a


def _shl1(a):
    """Static shift-by-one toward index 0, zero-filling the tail."""
    return jnp.pad(a[1:], (0, 1))


def _shr1_last(a):
    """Shift-by-one away from index 0 along the LAST axis (any rank),
    zero-filling the head."""
    pad = [(0, 0)] * (a.ndim - 1) + [(1, 0)]
    return jnp.pad(a[..., :-1], pad)


def _shr1(a):
    """Static shift-by-one away from index 0, zero-filling the head."""
    return jnp.pad(a[:-1], (1, 0))


class _Side(NamedTuple):
    """One side's slot arrays (a row of each BookState array)."""

    price: jax.Array
    lots: jax.Array
    seq: jax.Array
    oid: jax.Array
    uid: jax.Array

    def shift_left(self, by, cap: int) -> "_Side":
        """Drop `by` leading slots (removals always form a prefix after a
        match; an arbitrary slot for cancels is handled by _remove).

        `by` is data-dependent, so a direct a[i + by] lowers to a per-lane
        gather under vmap. Instead: binary-decompose the shift into static
        shift-by-2^k slices, each selected by bit k of `by` — O(log cap)
        fused elementwise passes, no gather (SURVEY §7 hard part (a), done
        the XLA-friendly way).
        """
        out = list(self)
        k = 0
        while (1 << k) <= cap:
            sh = 1 << k
            on = ((by >> k) & 1) != 0

            def g(a, sh=sh, on=on):
                if sh >= cap:
                    # Whole-array shift: avoid the zero-size slice a[cap:]
                    # (Mosaic rejects 0-length vectors).
                    shifted = jnp.zeros_like(a)
                else:
                    shifted = jnp.pad(a[sh:], (0, sh))
                return _bsel(on, shifted, a)

            out = [g(a) for a in out]
            k += 1
        return _Side(*out)


def _match(
    config: BookConfig, opp: _Side, opp_count, side, price, volume, is_market
):
    """Fill the crossing prefix of the opposing side.

    Crossing rule (nodepool.go:86-115): BUY taker hits asks with price <=
    limit; SALE taker hits bids with price >= limit; MARKET (extension)
    hits every active order. Because the side is priority-sorted, crossing
    slots are a contiguous prefix, so "walk levels best-first, FIFO within
    level" (engine.go:118-136) degenerates to elementwise arithmetic.
    """
    cap = config.cap
    k = config.max_fills
    idx = jnp.arange(cap, dtype=jnp.int32)
    active = idx < opp_count
    # The side/market predicates are scalar-per-lane; combine them with the
    # [cap] masks through i32 blends (_bsel) — a scalar i1 broadcast against
    # a vector has no Mosaic relayout.
    le = (opp.price <= price).astype(jnp.int32)
    ge = (opp.price >= price).astype(jnp.int32)
    mkt = (is_market != 0).astype(jnp.int32)
    crosses = jnp.maximum(_bsel(side == BUY, le, ge), mkt)
    crossing = active & (crosses != 0)

    clots = jnp.where(crossing, opp.lots, 0)
    # Exclusive prefix = inclusive prefix of the shifted array — computed
    # directly (not incl - clots) so the 32-bit saturating scan stays
    # consistent: subtracting an unclamped addend from a clamped total
    # would under-report the depth ahead of a slot.
    cum_excl = _prefix_sum(_shr1_last(clots))
    fill = jnp.clip(volume - cum_excl, 0, clots)
    total = jnp.sum(fill)
    remaining = volume - total

    new_lots = opp.lots - fill
    fully_filled = (fill > 0) & (new_lots == 0)  # a prefix of the array
    n_removed = jnp.sum(fully_filled).astype(jnp.int32)
    n_fills = jnp.sum(fill > 0).astype(jnp.int32)

    # Fill records: fills occupy slots [0, n_fills) pre-compaction.
    rec = slice(0, k)
    taker_after = volume - (cum_excl[rec] + fill[rec])
    out = dict(
        fill_price=opp.price[rec],
        fill_qty=fill[rec],
        maker_oid=opp.oid[rec],
        maker_uid=opp.uid[rec],
        maker_prefill=opp.lots[rec],
        maker_remaining=new_lots[rec],
        taker_after=jnp.where(fill[rec] > 0, taker_after, 0),
        n_fills=n_fills,
        fill_overflow=jnp.maximum(n_fills - k, 0).astype(jnp.int32),
    )

    compacted = opp._replace(lots=new_lots).shift_left(n_removed, cap)
    return compacted, opp_count - n_removed, remaining, out


def _insert(config: BookConfig, own: _Side, own_count, entry: _Side, side):
    """Rest the remainder at its own limit price (engine.go:69-83): insert
    at the last slot whose priority beats or equals the new order — existing
    same-price orders keep time priority (nodelink.go:53-64)."""
    cap = config.cap
    idx = jnp.arange(cap, dtype=jnp.int32)
    active = idx < own_count
    ge = (own.price >= entry.price).astype(jnp.int32)
    le = (own.price <= entry.price).astype(jnp.int32)
    beats = _bsel(side == BUY, ge, le) != 0
    pos = jnp.sum(active & beats).astype(jnp.int32)
    overflow = own_count >= cap

    def ins(a, v):
        shifted = jnp.where(idx > pos, _shr1(a), a)
        return jnp.where(idx == pos, jnp.asarray(v, a.dtype), shifted)

    new = _Side(*(ins(a, v) for a, v in zip(own, entry)))
    new = jax.tree.map(lambda n, o: _bsel(overflow, o, n), new, own)
    return new, jnp.where(overflow, own_count, own_count + 1), overflow


def _remove(config: BookConfig, own: _Side, own_count, oid, price):
    """Cancel lookup + unlink (engine.go:87-116): requires the exact resting
    price (SURVEY §2.3.2 — the reference looks up S:link:P by price); no
    ownership check (uid is deliberately not compared)."""
    cap = config.cap
    idx = jnp.arange(cap, dtype=jnp.int32)
    active = idx < own_count
    hit = active & (own.oid == oid) & (own.price == price)
    # Integer reduction, not jnp.any: Mosaic lowers boolean reductions
    # through a float max, which is unsupported for some widths.
    found = jnp.sum(hit.astype(jnp.int32)) > 0
    # oids unique by contract, so the hit mask has at most one set slot:
    # masked sums replace the dynamic argmax-index reads (gather-free).
    pos = jnp.sum(jnp.where(hit, idx, 0)).astype(jnp.int32)
    volume = jnp.sum(jnp.where(hit, own.lots, 0))

    def rm(a):
        return jnp.where(idx >= pos, _shl1(a), a)

    removed = _Side(*(rm(a) for a in own))
    new = jax.tree.map(lambda n, o: _bsel(found, n, o), removed, own)
    return new, jnp.where(found, own_count - 1, own_count), found, volume


def step_rows_impl(
    config: BookConfig,
    buy: _Side,
    sale: _Side,
    buy_count,
    sale_count,
    next_seq,
    op: DeviceOp,
) -> tuple[_Side, _Side, jax.Array, jax.Array, jax.Array, StepOutput]:
    """Apply one op to one symbol's book, given as separate per-side rows.

    This is the core the Pallas kernel calls directly (per-side [cap] rows
    tile densely in VMEM; a [2, cap] side axis would stack/unstack every
    step). step_impl wraps it for the [2, cap] BookState representation.

    Both the ADD path (match + rest) and the DEL path (cancel) are computed
    unconditionally and mask-selected — under vmap over symbols `lax.cond`
    would degenerate to the same thing, and branch-free code keeps the XLA
    graph static (TPU design rule: no data-dependent control flow).
    """
    s = op.side
    is_add = op.action == ACTION_ADD
    is_del = op.action == ACTION_DEL
    is_buy = s == BUY

    own0 = _Side(*(_bsel(is_buy, b, a) for b, a in zip(buy, sale)))
    opp0 = _Side(*(_bsel(is_buy, a, b) for b, a in zip(buy, sale)))
    own_count0 = jnp.where(is_buy, buy_count, sale_count)
    opp_count0 = jnp.where(is_buy, sale_count, buy_count)

    # --- ADD: match against the opposing side -------------------------------
    opp1, opp_count1, remaining, fills = _match(
        config, opp0, opp_count0, s, op.price, op.volume, op.is_market
    )

    # --- ADD: rest the remainder (limit only; market remainder is dropped —
    # MARKET is our extension, the reference has no market orders) ----------
    do_rest = is_add & (remaining > 0) & (op.is_market == 0)
    entry = _Side(
        price=op.price,
        lots=remaining,
        seq=next_seq + 1,
        oid=op.oid,
        uid=op.uid,
    )
    own1, own_count1, overflow = _insert(config, own0, own_count0, entry, s)

    # --- DEL: cancel --------------------------------------------------------
    own2, own_count2, found, cancel_volume = _remove(
        config, own0, own_count0, op.oid, op.price
    )

    # --- select & write back ------------------------------------------------
    def sel(add_side, del_side, nop_side):
        return jax.tree.map(
            lambda a, d, n: _bsel(is_add, a, _bsel(is_del, d, n)),
            add_side,
            del_side,
            nop_side,
        )

    own_final = sel(
        jax.tree.map(lambda r, o_: _bsel(do_rest, r, o_), own1, own0),
        own2,
        own0,
    )
    own_count_final = jnp.where(
        is_add,
        jnp.where(do_rest, own_count1, own_count0),
        jnp.where(is_del, own_count2, own_count0),
    )
    opp_final = sel(opp1, opp0, opp0)
    opp_count_final = jnp.where(is_add, opp_count1, opp_count0)

    new_buy = _Side(
        *(_bsel(is_buy, o_, p) for o_, p in zip(own_final, opp_final))
    )
    new_sale = _Side(
        *(_bsel(is_buy, p, o_) for o_, p in zip(own_final, opp_final))
    )
    new_buy_count = jnp.where(is_buy, own_count_final, opp_count_final)
    new_sale_count = jnp.where(is_buy, opp_count_final, own_count_final)
    new_next_seq = jnp.where(do_rest, next_seq + 1, next_seq)

    zero = jnp.zeros((), config.dtype)
    out = StepOutput(
        fill_price=_bsel(is_add, fills["fill_price"], 0),
        fill_qty=_bsel(is_add, fills["fill_qty"], 0),
        maker_oid=_bsel(is_add, fills["maker_oid"], 0),
        maker_uid=_bsel(is_add, fills["maker_uid"], 0),
        maker_prefill=_bsel(is_add, fills["maker_prefill"], 0),
        maker_remaining=_bsel(is_add, fills["maker_remaining"], 0),
        taker_after=_bsel(is_add, fills["taker_after"], 0),
        n_fills=jnp.where(is_add, fills["n_fills"], 0),
        fill_overflow=jnp.where(is_add, fills["fill_overflow"], 0),
        taker_remaining=jnp.where(is_add, remaining, zero),
        rested=(do_rest & ~overflow).astype(jnp.int32),
        book_overflow=(do_rest & overflow).astype(jnp.int32),
        cancel_found=(is_del & found).astype(jnp.int32),
        cancel_volume=jnp.where(is_del, cancel_volume, zero),
    )
    return new_buy, new_sale, new_buy_count, new_sale_count, new_next_seq, out


def step_impl(
    config: BookConfig, book: BookState, op: DeviceOp
) -> tuple[BookState, StepOutput]:
    """Apply one op to one symbol's [2, cap] BookState. Pure, jittable,
    vmap-able. Thin wrapper over step_rows_impl: unstack the side axis with
    static slices, run the rows core, restack (the stack is XLA-only — the
    Pallas kernel keeps per-side rows and never pays it)."""
    buy = _Side(*(getattr(book, n)[0] for n in _Side._fields))
    sale = _Side(*(getattr(book, n)[1] for n in _Side._fields))
    new_buy, new_sale, nb, ns, nseq, out = step_rows_impl(
        config, buy, sale, book.count[0], book.count[1], book.next_seq, op
    )
    new_book = BookState(
        price=jnp.stack([new_buy.price, new_sale.price]),
        lots=jnp.stack([new_buy.lots, new_sale.lots]),
        seq=jnp.stack([new_buy.seq, new_sale.seq]),
        oid=jnp.stack([new_buy.oid, new_sale.oid]),
        uid=jnp.stack([new_buy.uid, new_sale.uid]),
        count=jnp.stack([nb, ns]),
        next_seq=nseq,
    )
    return new_book, out


# Jitted entry point for single-op use (tests, debugging). Batched execution
# nests step_impl under scan/vmap instead (gome_tpu.engine.batch). The book
# is donated (gomelint GL601): callers thread it through (`book, out =
# step(config, book, op)`), so the input book is dead on return — without
# donation every single-op step double-buffers the book. The scalar op is
# NOT donated: its leaves mostly cannot alias an output (XLA would warn
# "donated buffers were not usable" on every compile) and the win is a few
# bytes. Do NOT reuse a book object across step calls (gomelint GL603
# flags it; donation-supporting backends raise "Array has been deleted").
step = functools.partial(jax.jit, static_argnums=0,
                         donate_argnums=(1,))(step_impl)

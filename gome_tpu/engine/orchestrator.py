"""The engine facade: pre-pool admission + batched device matching.

This is the TPU framework's equivalent of the reference's `engine` package
surface — the layer the gateway and the order consumer talk to
(gomengine/engine/engine.go:35-54 + the pre-pool protocol,
gomengine/engine/nodepool.go:14-28, gomengine/main.go:44-45):

  gateway side   mark(order)      — HSET S:comparison S:U:O 1 (main.go:44-45)
  consumer side  process(orders)  — the consumer loop body (engine.go:46-54):
                   ADD: consumed only if still marked, else dropped
                        (engine.go:58-62; the cancel-before-consume race,
                        SURVEY §2.3.3)
                   DEL: clears the mark first so a still-queued ADD dies
                        (engine.go:88-90), then cancels on the book

The pre-pool is shared state between gateway and consumer (Redis in the
reference); here it is an in-process set — single-binary deployments share
the MatchEngine instance. Deployments that need the race semantics to
survive restart snapshot `pre_pool` alongside the books via the durability
layer (gome_tpu.persist).
"""

from __future__ import annotations

from ..types import Action, MatchResult, Order
from .batch import BatchEngine, EngineStats
from .book import BookConfig
from .prepool import consume_batch_of, make_prepool


class MatchEngine:
    """Admission + matching for one engine shard (a set of symbol lanes).

    Orders enter twice, like the reference's two process hops: `mark()` when
    the gateway accepts an ADD (before it is queued), `process()` when the
    consumer drains a micro-batch from the queue. Cancels are never marked
    (main.go:54-64 sets no pre-pool entry).
    """

    def __init__(
        self,
        config: BookConfig | None = None,
        n_slots: int = 1024,
        max_t: int = 32,
        auto_grow: bool = True,
        kernel: str = "scan",
        **batch_kw,
    ):
        """batch_kw passes through to BatchEngine (mesh, dense,
        dense_t_max, max_slots, max_cap, pallas_interpret)."""
        self.batch = BatchEngine(
            config or BookConfig(),
            n_slots,
            max_t=max_t,
            auto_grow=auto_grow,
            kernel=kernel,
            **batch_kw,
        )
        # The marker store shared with the gateway. In-process by default
        # (C++-backed when the toolchain allows — prepool.NativePrePool);
        # split-process deployments assign a prepool.RespPrePool here (and
        # in the gateway process) so the markers live in a Redis-compatible
        # server exactly as the reference's do (nodepool.go:14-28).
        self.pre_pool = make_prepool()

    # -- gateway side ------------------------------------------------------
    def mark(self, order: Order) -> None:
        """Record "submitted, not yet consumed/cancelled" for an ADD
        (nodepool.go:14-16). No-op for other actions."""
        if order.action is Action.ADD:
            self.pre_pool.add(self._prekey(order))

    def unmark(self, order: Order) -> None:
        """Discard an order's pre-pool entry without processing it — the
        consumer's dead-letter path uses this so a poisoned ADD's restored
        mark does not linger forever (and leak into snapshots)."""
        self.pre_pool.discard(self._prekey(order))

    def mark_frame(self, cols: dict) -> None:  # gomelint: hotpath
        """Bulk mark for the columnar admit path: one fused pass over an
        ORDER block's columns (ADD rows only — the pool implementations
        share that contract with mark())."""
        self.pre_pool.mark_frame(cols)

    def unmark_frame(self, cols: dict) -> None:
        """Bulk undo of mark_frame — the columnar emit-failure path."""
        self.pre_pool.unmark_frame(cols)

    # -- consumer side -----------------------------------------------------
    def process(self, orders: list[Order]) -> list[MatchResult]:
        """Apply one micro-batch in arrival order; returns the MatchResult
        event stream in the reference's global emission order. Admission
        (the pre-pool check, engine.go:58-62) drops ADDs cancelled before
        consumption without touching the book."""
        return [
            ev
            for _, evs in self.process_indexed(list(enumerate(orders)))
            for ev in evs
        ]

    def process_indexed(
        self, indexed: list[tuple[int, Order]]
    ) -> list[tuple[int, list[MatchResult]]]:
        """process() keyed by caller-assigned arrival tags (see
        BatchEngine.process_indexed) — admission applies identically; tags
        of dropped ADDs simply emit no group."""
        admitted, consumed = self._admit(indexed)
        try:
            return self.batch.process_indexed(admitted)
        except Exception:
            self.pre_pool |= consumed
            raise

    def process_one(self, order: Order) -> list[MatchResult]:
        return self.process([order])

    def process_columnar(self, orders: list[Order]):
        """process() with the vectorized decode path: same admission, same
        event content/order, but returns a columnar EventBatch
        (gome_tpu.engine.events) — the shape the consumer publishes from
        without building per-event objects."""
        admitted, consumed = self._admit(list(enumerate(orders)))
        try:
            return self.batch.process_columnar([o for _, o in admitted])
        except Exception:
            self.pre_pool |= consumed
            raise

    def _admit(
        self, indexed: list[tuple[int, Order]]
    ) -> tuple[list[tuple[int, Order]], set]:
        """Apply admission over (tag, order) items; also returns the
        pre-pool keys this batch consumed so a FAILED batch can restore them
        (process/_columnar do) — the at-least-once consumer replays failed
        batches, and a replayed ADD must not die as unmarked just because
        the failed attempt already popped its key."""
        sel: list[tuple[int, Order]] = []
        keys: list[tuple[str, str, str]] = []
        for item in indexed:
            action = item[1].action
            if action is Action.ADD or action is Action.DEL:
                sel.append(item)
                keys.append(self._prekey(item[1]))
            # NOP padding never reaches the device.
        existed = consume_batch_of(self.pre_pool, keys)
        admitted: list[tuple[int, Order]] = []
        consumed: set[tuple[str, str, str]] = set()
        for item, key, ex in zip(sel, keys, existed):
            if item[1].action is Action.ADD:
                if not ex:
                    self.stats.dropped_no_prepool += 1
                    continue
                consumed.add(key)
            elif ex:
                consumed.add(key)
            admitted.append(item)
        return admitted, consumed

    # -- views -------------------------------------------------------------
    @property
    def stats(self) -> EngineStats:
        """Single source of truth: the BatchEngine's counters (the facade
        adds only dropped_no_prepool to the same object)."""
        return self.batch.stats

    @property
    def config(self) -> BookConfig:
        return self.batch.config

    @property
    def books(self):
        return self.batch.books

    def process_frame(self, cols: dict, fast: bool = True):
        """Columnar-frame ingestion (bus.colwire ORDER frames): admission
        semantics identical to process() — unmarked ADDs drop, DELs clear
        their marks — applied by filtering the columns, then the
        zero-per-order-Python frame path (engine.frames) runs the batch.
        Returns an EventBatch. fast=True uses the device-side
        event-compaction path (one fetch per frame; transparently falls
        back to the exact escalating path when a device budget trips).
        For cross-frame pipelining use engine.pipeline.FramePipeline."""
        from . import frames

        cols, consumed = self.admit_frame(cols)
        run = frames.apply_frame_fast if fast else frames.process_frame
        try:
            return run(self.batch, cols)
        except Exception:
            self.pre_pool |= consumed
            raise

    def admit_frame(self, cols: dict) -> tuple[dict, set]:
        """Frame admission: returns (filtered columns, the consumed marks)
        — the caller restores `consumed` (pre_pool |= consumed) if the
        batch later fails (at-least-once replay must not drop re-admitted
        ADDs)."""
        import numpy as np

        consume_frame = getattr(self.pre_pool, "consume_frame", None)
        if consume_frame is not None:
            # Fused native pass: compose keys + pop markers + masks in C++.
            keep, consumed = consume_frame(cols)
            dropped = int(
                ((cols["action"] == int(Action.ADD)) & ~keep).sum()
            )
            self.stats.dropped_no_prepool += dropped
            if not keep.all():
                cols = dict(
                    cols,
                    n=int(keep.sum()),
                    **{
                        k: np.ascontiguousarray(cols[k][keep])
                        for k in (
                            "action", "side", "kind", "price", "volume",
                            "symbol_idx", "uuid_idx", "oids",
                        )
                    },
                )
            return cols, consumed

        n = int(cols["n"])
        action = cols["action"].tolist()
        syms, uuids = cols["symbols"], cols["uuids"]
        sidx, uidx = cols["symbol_idx"].tolist(), cols["uuid_idx"].tolist()
        oid_list = [o.decode() for o in cols["oids"].tolist()]
        consumed: set[tuple[str, str, str]] = set()
        ADD, DEL = int(Action.ADD), int(Action.DEL)
        # Key construction at C speed: list-comp indexing + zip tuples;
        # symbol/uuid string objects are shared (hashes cached), only the
        # oid hash is fresh per order. Marks consume through ONE batched
        # call — a single pipelined round trip when the pool is remote.
        keys = list(
            zip((syms[k] for k in sidx), (uuids[k] for k in uidx), oid_list)
        )
        sel = [i for i, a in enumerate(action) if a == ADD or a == DEL]
        existed = consume_batch_of(
            self.pre_pool,
            keys if len(sel) == n else [keys[i] for i in sel],
        )
        keep = np.zeros(n, bool)  # NOP padding never reaches the device
        dropped = 0
        for i, ex in zip(sel, existed):
            if action[i] == ADD:
                if ex:
                    keep[i] = True
                    consumed.add(keys[i])
                else:
                    dropped += 1
            else:  # DEL: always admitted; a consumed mark kills a queued ADD
                keep[i] = True
                if ex:
                    consumed.add(keys[i])
        self.stats.dropped_no_prepool += dropped
        if not keep.all():
            cols = dict(
                cols,
                n=int(keep.sum()),
                **{
                    k: np.ascontiguousarray(cols[k][keep])
                    for k in (
                        "action", "side", "kind", "price", "volume",
                        "symbol_idx", "uuid_idx", "oids",
                    )
                },
            )
        return cols, consumed

    # -- geometry persistence ----------------------------------------------
    def save_geometry(self, path: str) -> None:
        """Persist the flow's shape manifest (grow-only geometry floors +
        every dispatched fast-path shape combo) as JSON. A later process
        load_geometry()s it so its first live frame runs with zero
        first-seen traces — the deployment-side answer to 'per-process
        re-traces amortizing out' (pairs with the XLA persistent compile
        cache, which covers compiles but not traces)."""
        import json
        import os

        m = self.batch.shape_manifest()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(m, f)
        os.replace(tmp, path)  # atomic: readers never see a torn file

    def load_geometry(
        self, path: str, precompile: bool = True, presize_cap: bool = True
    ) -> int:
        """Load a persisted shape manifest: prewarm the grow-only floors
        (so this process CHOOSES the recorded shapes) and, by default,
        replay the recorded combos with all-padding inputs (so they are
        traced+compiled before live traffic). Returns the number of combos
        replayed (0 with precompile=False or an absent/invalid file —
        loading is best-effort: geometry is a performance hint, never
        state)."""
        import json

        from . import frames

        try:
            try:
                f = open(path)
            except FileNotFoundError:
                return 0  # no manifest yet: the normal first-boot case
            with f:
                m = json.load(f)
            floors = m["floors"]
            combos = m["combos"]
            as_int = lambda d: {int(k): int(v) for k, v in d.items()}
            # Pre-size storage to the flow's recorded stationary cap:
            # boots pay ONE up-front grow instead of a mid-traffic
            # escalate+replay, and the deep-cap combos become replayable.
            # presize_cap=False keeps boot storage (shallow flows through
            # the same engine then run at their own cheaper cap; combos
            # above it are skipped and compile from the persistent cache
            # when escalation genuinely happens).
            if presize_cap and floors.get("cap"):
                # Clamp to this engine's max_cap: a manifest from a
                # bigger deployment must degrade (shallower presize,
                # deep combos skipped), never abort the whole load.
                self.batch.ensure_cap(
                    min(int(floors["cap"]), self.batch.max_cap)
                )
            self.batch.prewarm_geometry(
                rows_floor=as_int(floors.get("rows_floor", {})),
                t_floor=as_int(floors.get("t_floor", {})),
                fills_buf=as_int(floors.get("fills_buf", {})),
                cancels_buf=as_int(floors.get("cancels_buf", {})),
            )
            if not precompile:
                for combo in combos:
                    self.batch.record_combo(combo)
                return 0
            return frames.precompile_combos(self.batch, combos)
        except Exception as e:
            # Best-effort end to end: a stale manifest (combo layout from
            # an older version, shapes recorded before an n_slots growth)
            # must never stop a boot — it is a performance hint, never
            # state. Whatever floors merged before the failure stand
            # (grow-only, still valid). But never SILENTLY: a swallowed
            # failure here cost two full bench rounds of mid-region
            # compiles before anyone noticed.
            from ..utils.logging import get_logger

            get_logger("engine").warning(
                "geometry manifest %s not applied: %s", path, e
            )
            return 0

    @staticmethod
    def _prekey(order: Order) -> tuple[str, str, str]:
        """S:comparison field = S:U:O (ordernode.go:89-92)."""
        return (order.symbol, order.uuid, order.oid)

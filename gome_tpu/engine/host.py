"""Host-side plumbing between string-keyed Orders and the integer device ops,
plus reconstruction of the reference MatchResult event stream from
StepOutputs.

The reference's string ids (api/order.proto:11-12) and Redis key-name
machinery (ordernode.go:89-117) never reach the device: the host interns
strings to dense integer handles, ships fixed-shape integer ops, and decodes
fixed-shape fill records back into events byte-equivalent (field-for-field)
with engine.go:24-28's MatchResult.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..types import Action, MatchResult, Order, OrderType, snapshot_of
from .book import DeviceOp, StepOutput
from .step import LOT_MAX32


class Interner:
    """Bidirectional string <-> dense int id table. Id 0 is reserved for
    "none" (empty slots in device arrays)."""

    def __init__(self) -> None:
        self._to_id: dict[str, int] = {}
        self._to_str: list[str] = [""]

    def intern(self, s: str) -> int:
        i = self._to_id.get(s)
        if i is None:
            i = len(self._to_str)
            self._to_id[s] = i
            self._to_str.append(s)
        return i

    def get(self, s: str) -> int | None:
        """Read-only lookup; None if never interned."""
        return self._to_id.get(s)

    def lookup(self, i: int) -> str:
        return self._to_str[i]

    @property
    def table(self) -> list[str]:
        """id -> string table including the reserved "" at id 0 (the shape
        columnar decode indexes by raw interner id)."""
        return self._to_str

    def __len__(self) -> int:
        return len(self._to_str)

    # -- snapshot support ----------------------------------------------------
    def to_list(self) -> list[str]:
        """All interned strings in id order (excluding the reserved 0)."""
        return list(self._to_str[1:])

    @classmethod
    def from_list(cls, strs: list[str]) -> "Interner":
        it = cls()
        for s in strs:
            it.intern(s)
        return it


@dataclasses.dataclass
class OpContext:
    """What the host must remember about a dispatched op to decode its
    StepOutput into events (the device echoes none of this)."""

    order: Order


def encode_op(
    order: Order,
    oids: Interner,
    uids: Interner,
    dtype=np.int64,
    price_base: int = 0,
) -> DeviceOp:
    """Order -> scalar DeviceOp (numpy scalars; cheap to batch later).
    dtype must match BookConfig.dtype so the device writeback needs no cast.
    price_base: the lane's rebasing offset (32-bit books store prices
    relative to it; see BatchEngine._prepare_bases)."""
    if order.action is Action.ADD and order.volume <= 0:
        raise ValueError(
            f"volume must be positive, got {order.volume} (oid={order.oid}); "
            "volume<=0 is out of contract (see gome_tpu.oracle docstring)"
        )
    if np.dtype(dtype).itemsize <= 4 and order.volume > LOT_MAX32:
        raise ValueError(
            f"volume {order.volume} exceeds the int32-mode per-order lot "
            f"ceiling {LOT_MAX32} (oid={order.oid}); use coarser lot "
            "units or an int64 BookConfig"
        )
    val = np.dtype(dtype).type
    is_market = order.order_type is OrderType.MARKET
    # MARKET price is documented-ignored: encode 0 so an arbitrary client
    # price can never overflow the lane's rebased int32 window.
    return DeviceOp(
        action=np.int32(int(order.action)),  # Action values == device codes
        side=np.int32(int(order.side)),
        is_market=np.int32(is_market),
        price=val(0 if is_market else order.price - price_base),
        volume=val(order.volume),
        oid=val(oids.intern(order.oid)),
        uid=val(uids.intern(order.uuid)),
    )


def decode_events(
    ctx: OpContext,
    out: StepOutput,
    oids: Interner,
    uids: Interner,
    price_base: int = 0,
) -> list[MatchResult]:
    """StepOutput -> the MatchResult events this op produced, in the
    reference's emission order (best level first, FIFO within level —
    exactly the device's fill-record order).

    The caller (BatchEngine._run_exact) escalates device budgets before
    decoding, so `out` always carries complete records; tripped budgets here
    mean an engine bug, not an input condition."""
    order = ctx.order
    events: list[MatchResult] = []
    if order.action is Action.ADD:
        if int(out.book_overflow):
            raise RuntimeError(
                f"op {order.oid}: resting insert dropped (side full) reached "
                "decode — cap escalation should have replayed this grid"
            )
        n = int(out.n_fills)
        if n > len(out.fill_qty):
            raise RuntimeError(
                f"op {order.oid}: {n} fills > {len(out.fill_qty)} records "
                "reached decode — fill-record escalation should have re-run "
                "this lane"
            )
        for j in range(n):
            qty = int(out.fill_qty[j])
            remaining = int(out.maker_remaining[j])
            maker_volume = int(out.maker_prefill[j]) if remaining == 0 else remaining
            maker = snapshot_of(
                Order(
                    uuid=uids.lookup(int(out.maker_uid[j])),
                    oid=oids.lookup(int(out.maker_oid[j])),
                    symbol=order.symbol,
                    side=order.side.opposite,
                    price=int(out.fill_price[j]) + price_base,
                    volume=maker_volume,
                )
            )
            taker = snapshot_of(order, int(out.taker_after[j]))
            events.append(
                MatchResult(node=taker, match_node=maker, match_volume=qty)
            )
    elif order.action is Action.DEL and int(out.cancel_found):
        snap = snapshot_of(order, int(out.cancel_volume))
        events.append(MatchResult(node=snap, match_node=snap, match_volume=0))
    return events

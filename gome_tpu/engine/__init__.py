from .book import BookConfig, BookState, DeviceOp, StepOutput, init_book
from .step import step

__all__ = [
    "BookConfig",
    "BookState",
    "DeviceOp",
    "StepOutput",
    "init_book",
    "step",
]

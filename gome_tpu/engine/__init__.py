from .batch import BatchEngine, CapacityError, EngineStats, batch_step
from .book import BookConfig, BookState, DeviceOp, StepOutput, init_book, init_books
from .orchestrator import MatchEngine
from .step import step, step_impl

__all__ = [
    "BatchEngine",
    "EngineStats",
    "MatchEngine",
    "BookConfig",
    "BookState",
    "DeviceOp",
    "StepOutput",
    "batch_step",
    "init_book",
    "init_books",
    "step",
    "step_impl",
]

"""Pre-pool marker stores — the shared state between gateway and consumer.

The reference keeps the pre-pool in Redis so its three processes agree on
which ADDs are still live: the gateway marks at accept
(main.go:44-45 -> nodepool.go:14-16, HSET S:comparison S:U:O 1), the
consumer consumes the mark at SetOrder (engine.go:58-62, exists+delete)
and a cancel clears it first (engine.go:88-90) — that is what makes the
cancel-before-consume race drop the queued ADD (SURVEY §2.3.3).

Two implementations of one contract:

  LocalPrePool — a set subclass; single-process deployments (gateway and
      consumer sharing the MatchEngine) need nothing more.
  RespPrePool  — the markers live in a Redis-compatible server via the
      dependency-free RESP client (persist.resp), under the reference's
      EXACT schema, so (a) split-process topologies get reference
      semantics, and (b) a live gome deployment's S:comparison hashes are
      directly this pool's state during migration.

The contract the engine uses (beyond set-ish add/discard/contains/iter):

  consume_batch(keys) -> list[bool]   pop each (symbol, uuid, oid) key in
      order; True where the key existed. Admission consumes marks through
      this — ONE pipelined round trip per frame for the RESP pool instead
      of 2 RTTs per order (the reference's exists+delete pair collapses to
      HDEL's return value, same observable semantics single-consumer).
"""

from __future__ import annotations

import numpy as np

from ..types import Action

Key = tuple[str, str, str]  # (symbol, uuid, oid) — S:U:O, ordernode.go:89-92


class LocalPrePool(set):
    """In-process marker store: a plain set of (symbol, uuid, oid)."""

    def consume_batch(self, keys: list[Key]) -> list[bool]:
        out = []
        discard = self.discard
        for k in keys:
            if k in self:
                discard(k)
                out.append(True)
            else:
                out.append(False)
        return out

    def _frame_keys(self, cols: dict):
        """Key tuples of the frame's ADD rows (the numpy fallback of the
        native marker's fused pass: one vectorized row select, then
        C-speed zip/update — no per-order Python function calls)."""
        act = np.ascontiguousarray(cols["action"])
        sel = np.nonzero(act == int(Action.ADD))[0]
        if not len(sel):
            return None
        syms, uuids = cols["symbols"], cols["uuids"]
        sidx = np.asarray(cols["symbol_idx"])[sel].tolist()
        uidx = np.asarray(cols["uuid_idx"])[sel].tolist()
        oids = np.asarray(cols["oids"])[sel].tolist()
        return zip(
            map(syms.__getitem__, sidx),
            map(uuids.__getitem__, uidx),
            (o.decode() for o in oids),
        )

    def mark_frame(self, cols: dict) -> None:  # gomelint: hotpath
        """Gateway-side bulk marking of a built ORDER block's ADDs
        (main.go:42-45 for a whole frame) — the columnar admit path's
        numpy fallback when native host ops are unavailable."""
        keys = self._frame_keys(cols)
        if keys is not None:
            self.update(keys)

    def unmark_frame(self, cols: dict) -> None:
        """Undo mark_frame (emit failed after marking: the frame never
        entered the pipeline, so no marker may dangle)."""
        keys = self._frame_keys(cols)
        if keys is not None:
            self.difference_update(keys)


def consume_batch_of(pool, keys: list[Key]) -> list[bool]:
    """consume_batch for any pool object — uses the pool's own batched
    implementation when present, else the generic set-protocol fallback
    (covers plain sets assigned by older persistence snapshots)."""
    consume = getattr(pool, "consume_batch", None)
    if consume is not None:
        return consume(keys)
    return LocalPrePool.consume_batch(pool, keys)  # set-protocol fallback


class RespPrePool:
    """Markers in a Redis-compatible server, reference schema:
    hash `S:comparison`, field `S:U:O`, value "1" (nodepool.go:14-28).

    Implements enough of the set protocol for the engine's rollback
    (`pool |= consumed`), the persistence layer's snapshot (iteration) and
    restore (clear/update), plus the batched consume the admission hot
    path uses.

    With a persist.resp.SupervisedRespClient, a store restart mid-traffic
    reconnects + retries under the hood: mark_frame/add/__ior__ (HSET) are
    idempotent under retry; consume_batch (HDEL) inherits the lost-reply
    ambiguity window every Redis deployment has (documented on the
    client), which maps onto the consumer's at-least-once replay."""

    def __init__(self, client):
        self.client = client  # resp.RespClient / SupervisedRespClient / redis-py

    def resilience(self) -> dict | None:
        """The supervised client's state snapshot (breaker, reconnects,
        time degraded) for health surfaces; None for a raw client."""
        sup = getattr(self.client, "supervisor", None)
        return sup().snapshot() if sup is not None else None

    # -- schema ------------------------------------------------------------
    @staticmethod
    def _loc(key: Key) -> tuple[str, str]:
        symbol, uuid, oid = key
        return f"{symbol}:comparison", f"{symbol}:{uuid}:{oid}"

    # -- set protocol ------------------------------------------------------
    def add(self, key: Key) -> None:
        k, f = self._loc(key)
        self.client.execute_command("HSET", k, f, "1")

    def discard(self, key: Key) -> None:
        k, f = self._loc(key)
        self.client.execute_command("HDEL", k, f)

    def __contains__(self, key: Key) -> bool:
        k, f = self._loc(key)
        return self.client.execute_command("HEXISTS", k, f) == 1

    def __ior__(self, keys):
        cmds = []
        for key in keys:
            k, f = self._loc(key)
            cmds.append(("HSET", k, f, "1"))
        if cmds:
            self._check(self.client.pipeline(cmds))
        return self

    def update(self, keys) -> None:
        self.__ior__(keys)

    def __iter__(self):
        for hkey in self.client.keys("*:comparison"):
            symbol = hkey[: -len(":comparison")]
            for field in self.client.hgetall(hkey):
                rest = field[len(symbol) + 1 :]  # strip "S:"
                uuid, _, oid = rest.partition(":")
                yield (symbol, uuid, oid)

    def __len__(self) -> int:
        return sum(
            self.client.execute_command("HLEN", k)
            for k in self.client.keys("*:comparison")
        )

    def clear(self) -> None:
        keys = self.client.keys("*:comparison")
        if keys:
            self.client.execute_command("DEL", *keys)

    # -- the admission hot path -------------------------------------------
    def consume_batch(self, keys: list[Key]) -> list[bool]:
        cmds = []
        for key in keys:
            k, f = self._loc(key)
            cmds.append(("HDEL", k, f))
        replies = self._check(self.client.pipeline(cmds))
        return [r == 1 for r in replies]

    def mark_frame(self, cols: dict) -> None:
        """Gateway-side bulk marking of a decoded/built ORDER frame's ADDs
        (main.go:42-45): one pipelined round trip, fields grouped into one
        variadic HSET per symbol hash key (same keyspace effect as
        per-mark HSETs; ~10x fewer commands for the server to parse)."""
        syms, uuids = cols["symbols"], cols["uuids"]
        sidx = cols["symbol_idx"].tolist()
        uidx = cols["uuid_idx"].tolist()
        oids = cols["oids"].tolist()
        ADD = int(Action.ADD)
        by_key: dict[str, list[str]] = {}
        for a, k, u, o in zip(cols["action"].tolist(), sidx, uidx, oids):
            if a != ADD:
                continue
            sym = syms[k]
            fv = by_key.setdefault(f"{sym}:comparison", [])
            fv.append(f"{sym}:{uuids[u]}:{o.decode()}")
            fv.append("1")
        if by_key:
            self._check(
                self.client.pipeline(
                    [("HSET", k, *fv) for k, fv in by_key.items()]
                )
            )

    def unmark_frame(self, cols: dict) -> None:
        """Undo mark_frame for the frame's ADD rows (columnar emit failed
        after marking): one pipelined round trip of HDELs — the bulk
        mirror of the gateway's per-order unmark."""
        syms, uuids = cols["symbols"], cols["uuids"]
        sidx = cols["symbol_idx"].tolist()
        uidx = cols["uuid_idx"].tolist()
        oids = cols["oids"].tolist()
        ADD = int(Action.ADD)
        cmds = []
        for a, k, u, o in zip(cols["action"].tolist(), sidx, uidx, oids):
            if a != ADD:
                continue
            sym = syms[k]
            cmds.append((
                "HDEL", f"{sym}:comparison",
                f"{sym}:{uuids[u]}:{o.decode()}",
            ))
        if cmds:
            self._check(self.client.pipeline(cmds))

    @staticmethod
    def _check(replies: list) -> list:
        """An error reply must RAISE, never read as 'mark absent': treating
        a store error (-LOADING, -OOM, -WRONGTYPE) as a missing mark would
        silently drop acknowledged ADDs; raising lets the at-least-once
        consumer replay the batch once the store recovers. Likewise a
        failed mark RESTORE (__ior__) must not pass silently — the replay
        depends on those marks being back."""
        for r in replies:
            if isinstance(r, Exception):
                raise r
        return replies


class NativeConsumed:
    """The marks one frame admission consumed, represented compactly: the
    frame's columns plus the per-row consumed mask — restoring them
    (`pool |= consumed`, the failed-batch rollback) replays the same fused
    C++ pass in mark mode instead of materializing per-order key tuples."""

    __slots__ = ("cols", "sel")

    def __init__(self, cols: dict, sel):
        self.cols = cols
        self.sel = sel  # uint8[n]: 1 where this row's mark was consumed

    def __len__(self) -> int:
        return int(self.sel.sum())

    def __iter__(self):
        """Key tuples of the consumed rows (snapshot/debug; not hot)."""
        import numpy as np

        c = self.cols
        syms, uuids = c["symbols"], c["uuids"]
        for i in np.nonzero(self.sel)[0].tolist():
            yield (
                syms[int(c["symbol_idx"][i])],
                uuids[int(c["uuid_idx"][i])],
                c["oids"][i].decode(),
            )


class NativePrePool:
    """In-process marker store backed by the C++ set (native/hostops.cc):
    same semantics as LocalPrePool, but admission of a whole decoded ORDER
    frame is ONE C call (compose key + pop marker + keep/existed masks)
    instead of a per-order Python loop — the difference between ~1.5 and
    ~0.1 us/order on the consumer hot path. Construction raises when the
    native library is unavailable (callers fall back to LocalPrePool)."""

    SEP = "\x1f"  # ASCII unit separator; ids on the reference JSON wire
    #               contract never contain control bytes

    def __init__(self):
        from . import nativehost

        self._nh = nativehost
        self._lib = nativehost.load()
        if self._lib is None:
            raise RuntimeError("native host ops unavailable")
        import ctypes

        self._h = ctypes.c_void_p(self._lib.gp_new())
        # String-list -> packed (data, offs) for the C call, keyed by list
        # identity: the wire decoder returns the same list object for a
        # repeated dictionary (bus.colwire), so a stable symbol universe
        # encodes its 10K+ strings once, not once per frame. Decoded
        # dictionaries are shared/immutable by contract (colwire).
        from ..utils.cache import IdentityCache

        self._packed_cache = IdentityCache()

    def __del__(self):
        h, self._h = self._h, None
        if h and getattr(self, "_lib", None) is not None:
            self._lib.gp_free(h)

    # -- set protocol ------------------------------------------------------
    def _ckey(self, key: Key) -> bytes:
        return self.SEP.join(key).encode()

    def add(self, key: Key) -> None:
        b = self._ckey(key)
        self._lib.gp_add(self._h, b, len(b))

    def discard(self, key: Key) -> None:
        b = self._ckey(key)
        self._lib.gp_discard(self._h, b, len(b))

    def __contains__(self, key: Key) -> bool:
        b = self._ckey(key)
        return bool(self._lib.gp_contains(self._h, b, len(b)))

    def __len__(self) -> int:
        return int(self._lib.gp_len(self._h))

    def __iter__(self):
        import ctypes

        need = self._lib.gp_dump(self._h, None, 0)
        buf = ctypes.create_string_buffer(max(int(need), 1))
        got = self._lib.gp_dump(self._h, buf, need)
        if got != need:
            # A concurrent mark grew the pool between the size probe and
            # the fill (each takes the C mutex separately). RuntimeError is
            # the set-mutated-during-iteration contract the snapshot layer
            # retries on (persist/snapshot.py) — never yield garbage.
            raise RuntimeError("pre-pool changed size during iteration")
        pos = 0
        raw = buf.raw
        while pos < need:
            ln = int.from_bytes(raw[pos : pos + 4], "little")
            pos += 4
            yield tuple(raw[pos : pos + ln].decode().split(self.SEP))
            pos += ln

    def clear(self) -> None:
        self._lib.gp_clear(self._h)

    def __eq__(self, other):
        if isinstance(other, (set, frozenset, NativePrePool, RespPrePool)):
            return set(self) == set(other)
        return NotImplemented

    def __ne__(self, other):
        eq = self.__eq__(other)
        return NotImplemented if eq is NotImplemented else not eq

    def __ior__(self, other):
        if isinstance(other, NativeConsumed):
            self._frame(other.cols, mode=2, sel=other.sel)
        else:
            for key in other:
                self.add(key)
        return self

    def update(self, keys) -> None:
        self.__ior__(keys)

    def consume_batch(self, keys: list[Key]) -> list[bool]:
        lib, h = self._lib, self._h
        out = []
        for key in keys:
            b = self._ckey(key)
            out.append(bool(lib.gp_discard(h, b, len(b))))
        return out

    # -- fused frame passes ------------------------------------------------
    def _packed(self, strs):
        ent = self._packed_cache.get(strs)
        if ent is None:
            ent = self._packed_cache.put(strs, self._nh.pack_strlist(strs))
        return ent

    def _frame(self, cols: dict, mode: int, sel=None):
        import ctypes

        nh = self._nh
        n = int(cols["n"])
        action = np.ascontiguousarray(cols["action"], np.uint8)
        sym_data, sym_offs = self._packed(cols["symbols"])
        uuid_data, uuid_offs = self._packed(cols["uuids"])
        sym_idx = np.ascontiguousarray(cols["symbol_idx"], np.uint32)
        uuid_idx = np.ascontiguousarray(cols["uuid_idx"], np.uint32)
        # The C pass indexes the offset tables unchecked; a frame whose
        # index column exceeds its dictionary must fail HERE, loudly.
        if n and (
            int(sym_idx.max()) >= len(cols["symbols"])
            or int(uuid_idx.max()) >= len(cols["uuids"])
        ):
            raise ValueError(
                "ORDER frame index column exceeds its dictionary "
                f"(symbols {len(cols['symbols'])}, uuids "
                f"{len(cols['uuids'])})"
            )
        oids = np.ascontiguousarray(cols["oids"])
        keep = np.empty(n, np.uint8) if mode == 0 else None
        existed = sel if sel is not None else (
            np.empty(n, np.uint8) if mode == 0 else None
        )
        c_void = ctypes.c_void_p
        as_p = lambda a: a.ctypes.data_as(c_void) if a is not None else None
        rc = self._lib.gp_frame(
            self._h, n, as_p(action),
            sym_data, sym_offs.ctypes.data_as(nh._p_i64), as_p(sym_idx),
            uuid_data, uuid_offs.ctypes.data_as(nh._p_i64), as_p(uuid_idx),
            as_p(oids), oids.dtype.itemsize,
            int(Action.ADD), int(Action.DEL),
            as_p(keep), as_p(existed), mode,
        )
        if rc != 0:
            raise RuntimeError("native pre-pool frame pass failed")
        return keep, existed

    def consume_frame(self, cols: dict):
        """Fused frame admission: returns (keep mask (bool[n]), consumed) —
        the engine.go:58-62/88-90 semantics in one native pass."""
        keep, existed = self._frame(cols, mode=0)
        return keep.view(np.bool_), NativeConsumed(cols, existed)

    def mark_frame(self, cols: dict) -> None:
        """Gateway-side bulk marking (main.go:42-45 for a whole frame)."""
        self._frame(cols, mode=1)

    def unmark_frame(self, cols: dict) -> None:
        """Undo mark_frame for the frame's ADD rows. Emit-failure path
        (rare by construction), so a per-row gp_discard loop is fine —
        no fused C mode needed."""
        act = np.ascontiguousarray(cols["action"])
        sel = np.nonzero(act == int(Action.ADD))[0]
        if not len(sel):
            return
        syms, uuids = cols["symbols"], cols["uuids"]
        sidx = np.asarray(cols["symbol_idx"])[sel].tolist()
        uidx = np.asarray(cols["uuid_idx"])[sel].tolist()
        oids = np.asarray(cols["oids"])[sel].tolist()
        lib, h = self._lib, self._h
        for s, u, o in zip(sidx, uidx, oids):
            b = self._ckey((syms[s], uuids[u], o.decode()))
            lib.gp_discard(h, b, len(b))


def make_prepool():
    """A NativePrePool when the toolchain allows, else LocalPrePool."""
    try:
        return NativePrePool()
    except RuntimeError:
        return LocalPrePool()


def make_marker(pool):
    """Gateway-side mark callable for a pool NOT attached to an engine —
    the split-process gateway's equivalent of MatchEngine.mark
    (main.go:42-45: ADDs mark, cancels never do)."""

    def mark(order) -> None:
        if order.action is Action.ADD:
            pool.add((order.symbol, order.uuid, order.oid))

    return mark

"""Pre-pool marker stores — the shared state between gateway and consumer.

The reference keeps the pre-pool in Redis so its three processes agree on
which ADDs are still live: the gateway marks at accept
(main.go:44-45 -> nodepool.go:14-16, HSET S:comparison S:U:O 1), the
consumer consumes the mark at SetOrder (engine.go:58-62, exists+delete)
and a cancel clears it first (engine.go:88-90) — that is what makes the
cancel-before-consume race drop the queued ADD (SURVEY §2.3.3).

Two implementations of one contract:

  LocalPrePool — a set subclass; single-process deployments (gateway and
      consumer sharing the MatchEngine) need nothing more.
  RespPrePool  — the markers live in a Redis-compatible server via the
      dependency-free RESP client (persist.resp), under the reference's
      EXACT schema, so (a) split-process topologies get reference
      semantics, and (b) a live gome deployment's S:comparison hashes are
      directly this pool's state during migration.

The contract the engine uses (beyond set-ish add/discard/contains/iter):

  consume_batch(keys) -> list[bool]   pop each (symbol, uuid, oid) key in
      order; True where the key existed. Admission consumes marks through
      this — ONE pipelined round trip per frame for the RESP pool instead
      of 2 RTTs per order (the reference's exists+delete pair collapses to
      HDEL's return value, same observable semantics single-consumer).
"""

from __future__ import annotations

from ..types import Action

Key = tuple[str, str, str]  # (symbol, uuid, oid) — S:U:O, ordernode.go:89-92


class LocalPrePool(set):
    """In-process marker store: a plain set of (symbol, uuid, oid)."""

    def consume_batch(self, keys: list[Key]) -> list[bool]:
        out = []
        discard = self.discard
        for k in keys:
            if k in self:
                discard(k)
                out.append(True)
            else:
                out.append(False)
        return out


def consume_batch_of(pool, keys: list[Key]) -> list[bool]:
    """consume_batch for any pool object — uses the pool's own batched
    implementation when present, else the generic set-protocol fallback
    (covers plain sets assigned by older persistence snapshots)."""
    consume = getattr(pool, "consume_batch", None)
    if consume is not None:
        return consume(keys)
    return LocalPrePool.consume_batch(pool, keys)  # set-protocol fallback


class RespPrePool:
    """Markers in a Redis-compatible server, reference schema:
    hash `S:comparison`, field `S:U:O`, value "1" (nodepool.go:14-28).

    Implements enough of the set protocol for the engine's rollback
    (`pool |= consumed`), the persistence layer's snapshot (iteration) and
    restore (clear/update), plus the batched consume the admission hot
    path uses."""

    def __init__(self, client):
        self.client = client  # persist.resp.RespClient (or redis-py)

    # -- schema ------------------------------------------------------------
    @staticmethod
    def _loc(key: Key) -> tuple[str, str]:
        symbol, uuid, oid = key
        return f"{symbol}:comparison", f"{symbol}:{uuid}:{oid}"

    # -- set protocol ------------------------------------------------------
    def add(self, key: Key) -> None:
        k, f = self._loc(key)
        self.client.execute_command("HSET", k, f, "1")

    def discard(self, key: Key) -> None:
        k, f = self._loc(key)
        self.client.execute_command("HDEL", k, f)

    def __contains__(self, key: Key) -> bool:
        k, f = self._loc(key)
        return self.client.execute_command("HEXISTS", k, f) == 1

    def __ior__(self, keys):
        cmds = []
        for key in keys:
            k, f = self._loc(key)
            cmds.append(("HSET", k, f, "1"))
        if cmds:
            self._check(self.client.pipeline(cmds))
        return self

    def update(self, keys) -> None:
        self.__ior__(keys)

    def __iter__(self):
        for hkey in self.client.keys("*:comparison"):
            symbol = hkey[: -len(":comparison")]
            for field in self.client.hgetall(hkey):
                rest = field[len(symbol) + 1 :]  # strip "S:"
                uuid, _, oid = rest.partition(":")
                yield (symbol, uuid, oid)

    def __len__(self) -> int:
        return sum(
            self.client.execute_command("HLEN", k)
            for k in self.client.keys("*:comparison")
        )

    def clear(self) -> None:
        keys = self.client.keys("*:comparison")
        if keys:
            self.client.execute_command("DEL", *keys)

    # -- the admission hot path -------------------------------------------
    def consume_batch(self, keys: list[Key]) -> list[bool]:
        cmds = []
        for key in keys:
            k, f = self._loc(key)
            cmds.append(("HDEL", k, f))
        replies = self._check(self.client.pipeline(cmds))
        return [r == 1 for r in replies]

    @staticmethod
    def _check(replies: list) -> list:
        """An error reply must RAISE, never read as 'mark absent': treating
        a store error (-LOADING, -OOM, -WRONGTYPE) as a missing mark would
        silently drop acknowledged ADDs; raising lets the at-least-once
        consumer replay the batch once the store recovers. Likewise a
        failed mark RESTORE (__ior__) must not pass silently — the replay
        depends on those marks being back."""
        for r in replies:
            if isinstance(r, Exception):
                raise r
        return replies


def make_marker(pool):
    """Gateway-side mark callable for a pool NOT attached to an engine —
    the split-process gateway's equivalent of MatchEngine.mark
    (main.go:42-45: ADDs mark, cancels never do)."""

    def mark(order) -> None:
        if order.action is Action.ADD:
            pool.add((order.symbol, order.uuid, order.oid))

    return mark

"""Fixed-shape, array-resident order-book state for one symbol.

This is the TPU re-expression of the reference's Redis schema (SURVEY §2.1):
the S:BUY/S:SALE price zsets, the S:depth volume hash, and the S:link:P
hash-encoded FIFO linked lists (gomengine/engine/nodepool.go,
gomengine/engine/nodelink.go) all collapse into five [2, CAP] integer arrays
kept sorted in *priority order* per side:

  * side 0 (BUY bids):  descending price, FIFO (ascending seq) within price
  * side 1 (SALE asks): ascending price,  FIFO (ascending seq) within price

Active orders occupy a contiguous prefix of length ``count[side]``; slot 0 is
always the best-priority resting order. Keeping the invariant "sorted,
prefix-packed" turns the reference's O(levels x orders) pointer-chasing match
loop (engine.go:118-198) into branch-free vector ops: a crossing mask is a
prefix, fill quantities are one exclusive cumsum, removals are a left-shift
gather, and inserts are a right-shift gather — no `lax.while_loop`, no
data-dependent shapes, fully `vmap`-able across thousands of symbols.

Prices and volumes are scaled integer ticks/lots (see gome_tpu.fixed);
oid/uid are integer handles interned by the host bridge (the string ids of
api/order.proto:11-12 never reach the device).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

BUY = 0
SALE = 1


@dataclasses.dataclass(frozen=True)
class BookConfig:
    """Static (compile-time) book geometry.

    cap      — max resting orders per side per symbol. The reference's book
               is unbounded (Redis); fixed capacity is the §5.7 "windowed
               ladder" trade: overflow is reported and spilled to the host
               slow path, never silently dropped.
    max_fills — fill records emitted per op (K). An op crossing more than K
               resting orders still mutates the book exactly; records beyond
               K are counted in `fill_overflow` and recovered by the host
               slow path (SURVEY §7 hard part (c)).
    dtype    — lot/price dtype. int64 (default) matches the reference's
               exact-integer envelope at accuracy=8 (SURVEY §2.2); int32 is
               available when tick/lot ranges allow, halving HBM traffic.
    """

    cap: int = 256
    max_fills: int = 16
    dtype: jnp.dtype = jnp.int64

    @property
    def seq_dtype(self):
        return jnp.int32


class BookState(NamedTuple):
    """One symbol's book. All arrays [2, cap] except count [2] and the
    per-symbol arrival counter next_seq [] (the time-priority stamp that the
    reference keeps implicitly as linked-list position, nodelink.go:53-64)."""

    price: jax.Array
    lots: jax.Array  # remaining lots; 0 <=> slot empty (beyond count)
    seq: jax.Array
    oid: jax.Array
    uid: jax.Array
    count: jax.Array
    next_seq: jax.Array


class DeviceOp(NamedTuple):
    """One operation in device form (the OrderNode fields that matter on
    device; ordernode.go:9-36 minus the Redis key plumbing). Scalars here;
    batched versions carry leading axes."""

    action: jax.Array  # i32: 0=NOP, 1=ADD, 2=DEL (gomengine/main.go:14-18)
    side: jax.Array  # i32: 0=BUY, 1=SALE (api/order.proto:4-7)
    is_market: jax.Array  # i32 bool: MARKET extension (BASELINE config 5)
    price: jax.Array  # dtype ticks
    volume: jax.Array  # dtype lots
    oid: jax.Array  # dtype interned order id
    uid: jax.Array  # dtype interned user id


#: DeviceOp fields carried as int32 regardless of the book value dtype.
#: Grid packers (the numpy path in engine.frames and the native
#: nativehost.pack_grid) share this rule so both produce identically
#: typed DeviceOp grids.
GRID_I32_FIELDS = ("action", "side", "is_market")


class StepOutput(NamedTuple):
    """Fixed-shape per-op result — everything the host needs to reconstruct
    the reference's MatchResult event stream (SURVEY §3.4) for this op.

    Fill j (j < min(n_fills, K)) reconstructs to one fill event:
      maker volume field = maker_prefill[j] if maker_remaining[j]==0 (full
      fill, engine.go:154,171) else maker_remaining[j] (partial,
      engine.go:190); taker volume field = taker_after[j].
    """

    fill_price: jax.Array  # [K] maker level price (the fill price)
    fill_qty: jax.Array  # [K] traded lots
    maker_oid: jax.Array  # [K]
    maker_uid: jax.Array  # [K]
    maker_prefill: jax.Array  # [K] maker lots before this fill
    maker_remaining: jax.Array  # [K] maker lots after this fill
    taker_after: jax.Array  # [K] taker remaining after fill j
    n_fills: jax.Array  # i32 total fills (may exceed K)
    fill_overflow: jax.Array  # i32 fills not captured in records
    taker_remaining: jax.Array  # taker lots left after matching
    rested: jax.Array  # i32 bool: remainder rested in the book
    book_overflow: jax.Array  # i32 bool: rest dropped, side full
    cancel_found: jax.Array  # i32 bool: DEL matched a resting order
    cancel_volume: jax.Array  # lots remaining at cancel (engine.go:100)


def ensure_dtype_usable(dtype) -> None:
    """int64 books silently degrade to int32 when jax's x64 mode is off —
    wrong matching arithmetic (depth prefix sums overflow), not an error.
    Enable x64 on the user's behalf (with a warning, since it is global
    config) rather than let that happen.

    Exception: once the Pallas kernel module has traced anything, flipping
    jax_enable_x64 mid-process can send a later retrace into infinite
    recursion through the dtype-promotion cache (documented in
    scripts/fuzz.py, observed on TPU). In that state the flip is refused
    with an actionable error instead — set JAX_ENABLE_X64=1 before startup."""
    if jnp.dtype(dtype).itemsize == 8 and not jax.config.jax_enable_x64:
        import sys

        if "gome_tpu.ops.pallas_match" in sys.modules:
            raise RuntimeError(
                "BookConfig dtype is 64-bit but jax_enable_x64 is off, and "
                "the Pallas kernel module is already loaded — flipping x64 "
                "now can corrupt jax's trace caches. Set JAX_ENABLE_X64=1 "
                "before process start (or use an int32 BookConfig)."
            )
        import warnings

        warnings.warn(
            "BookConfig dtype is 64-bit but jax_enable_x64 is off; enabling "
            "it globally (set JAX_ENABLE_X64=1 or use an int32 BookConfig "
            "to silence this)",
            stacklevel=3,
        )
        jax.config.update("jax_enable_x64", True)


def init_book(config: BookConfig) -> BookState:
    ensure_dtype_usable(config.dtype)
    shape = (2, config.cap)
    # One jnp.zeros call PER field: sharing a single zeros array across
    # leaves would alias their device buffers, and a donated book (the
    # single-op `step` entry donates its input, gomelint GL6xx) then trips
    # XLA's "attempt to donate the same buffer twice".
    z = lambda: jnp.zeros(shape, config.dtype)
    return BookState(
        price=z(),
        lots=z(),
        seq=jnp.zeros(shape, config.seq_dtype),
        oid=z(),
        uid=z(),
        count=jnp.zeros((2,), jnp.int32),
        next_seq=jnp.zeros((), config.seq_dtype),
    )


def init_books(config: BookConfig, n_symbols: int) -> BookState:
    """A stacked [n_symbols, ...] book pytree (leading symbol axis — the
    vmap/sharding axis; SURVEY §2.1 "symbol isolation")."""
    one = init_book(config)
    return jax.tree.map(
        lambda x: jnp.broadcast_to(x, (n_symbols,) + x.shape), one
    )


def grow_books(books: BookState, new_cap: int) -> BookState:
    """Widen the slot axis of a book (or stacked-book) pytree to `new_cap`,
    zero-padding the tail. Active slots are a prefix (book.py invariant), so
    padding on the right preserves every book exactly — this is the host
    "spill" escape hatch for the fixed-width ladder (SURVEY §5.7): when a
    side fills up (`book_overflow`), the engine re-runs the batch from the
    pre-batch snapshot on grown books instead of dropping the insert.
    """
    cap = books.price.shape[-1]
    if new_cap < cap:
        raise ValueError(f"cannot shrink cap {cap} -> {new_cap}")
    if new_cap == cap:
        return books
    pad = [(0, 0)] * (books.price.ndim - 1) + [(0, new_cap - cap)]

    def widen(a):
        return jnp.pad(a, pad)

    return books._replace(
        price=widen(books.price),
        lots=widen(books.lots),
        seq=widen(books.seq),
        oid=widen(books.oid),
        uid=widen(books.uid),
    )


def grow_lanes(books: BookState, n_lanes: int) -> BookState:
    """Append empty symbol lanes to a stacked [S, ...] book pytree (used when
    more distinct symbols arrive than the engine was provisioned for —
    the reference has no such limit because Redis keys are dynamic)."""
    s = books.count.shape[0]
    if n_lanes < s:
        raise ValueError(f"cannot shrink lanes {s} -> {n_lanes}")
    if n_lanes == s:
        return books
    return jax.tree.map(
        lambda a: jnp.pad(a, [(0, n_lanes - s)] + [(0, 0)] * (a.ndim - 1)),
        books,
    )


def book_depth(book: BookState, side: int, max_levels: int):
    """Aggregate [price, volume] depth view, best-first — the observable
    equivalent of the reference's S:BUY/S:SALE zset + S:depth hash
    (nodepool.go:61-83). Returns (prices[max_levels], volumes[max_levels],
    n_levels) as int64 numpy arrays; unused slots are zero.

    A host-side view: the caller typically passes a
    BatchEngine.lane_books() book whose price leaf is already absolute
    int64 — running this through jnp with x64 off would silently truncate
    rebased-absolute prices back to 32 bits. Device-resident books are
    pulled host-side in one transfer up front.
    """
    count, price, lots = jax.device_get(
        (book.count[side], book.price[side], book.lots[side])
    )
    n_active = int(count)
    price = np.asarray(price[:n_active], dtype=np.int64)
    lots = np.asarray(lots[:n_active], dtype=np.int64)
    prices = np.zeros(max_levels, np.int64)
    volumes = np.zeros(max_levels, np.int64)
    # slots are priority-sorted, so equal prices are contiguous runs
    n = 0
    i = 0
    while i < n_active and n < max_levels:
        j = i
        while j < n_active and price[j] == price[i]:
            j += 1
        prices[n] = price[i]
        volumes[n] = lots[i:j].sum()
        n += 1
        i = j
    # n is clipped to max_levels: a book with more distinct levels than
    # max_levels is truncated (best-first).
    return prices, volumes, np.int32(n)

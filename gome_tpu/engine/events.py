"""Columnar event batches: the high-throughput decode path.

The object decoder (host.decode_events) builds one MatchResult dataclass per
fill — exact, but Python-object construction caps end-to-end throughput at
a few hundred thousand events/sec, far below what the device side sustains
(gome_tpu.ops.pallas_match). This module decodes a whole grid's StepOutputs
into numpy columns in O(vector ops), deferring (or skipping) object
construction:

  * `EventBatch` — one numpy column per MatchResult field, in the exact
    reference emission order (arrival order of the taker op; best level
    first, FIFO within level, within an op — SURVEY §3.4).
  * `EventBatch.to_results()` — materialize the same `list[MatchResult]`
    the object decoder produces (used by the compatibility wrapper and the
    parity tests that pin the two paths together).
  * `EventBatch.to_json_lines()` — serialize straight from columns in the
    matchOrder wire shape, never constructing per-event objects.

The reference has no analogue (its event "decode" is `json.Marshal` of one
Go struct per fill, engine.go:149-158); this layer exists because one host
process must keep pace with ~10M device fills/sec.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..types import Action, MatchResult, Order, OrderType, Side, snapshot_of

_COLUMNS = (
    # (name, dtype) — int64 columns regardless of book dtype: decode is
    # host-side, width costs nothing compared to object churn.
    ("arrival", np.int64),  # arrival index of the taker op in the batch
    ("is_cancel", np.bool_),
    ("symbol_id", np.int64),  # engine lane (symbols interner id - 1)
    ("taker_uid", np.int64),  # interner ids; strings resolved lazily
    ("taker_oid", np.int64),
    ("taker_side", np.int8),
    ("taker_price", np.int64),
    ("taker_volume", np.int64),  # taker remaining AFTER this fill / cancel
    ("maker_uid", np.int64),
    ("maker_oid", np.int64),
    ("fill_price", np.int64),
    ("maker_volume", np.int64),  # reference semantics: prefill if fully
    #                              filled else post-fill remaining
    ("match_volume", np.int64),  # 0 <=> cancel notice
    ("is_market", np.bool_),
)


@dataclasses.dataclass
class EventBatch:
    """A batch of MatchResult events as parallel numpy columns, plus the
    interner tables needed to resolve string ids on demand."""

    columns: dict[str, np.ndarray]
    symbols: list[str]  # lane -> symbol string
    oid_table: list[str]  # interner id -> oid string ("" at 0)
    uid_table: list[str]
    # Matchfeed base sequence number: event i is seq ``seq0 + i``. None on
    # unstamped batches (pre-ISSUE-11 wire compat; GCE1 frames).
    seq0: int | None = None

    def __len__(self) -> int:
        return len(self.columns["arrival"])

    def to_results(self) -> list[MatchResult]:
        """Materialize MatchResult objects (identical to the per-op object
        decoder's output, same order)."""
        c = self.columns
        out: list[MatchResult] = []
        oid_t, uid_t, syms = self.oid_table, self.uid_table, self.symbols
        seq0 = self.seq0
        for i in range(len(self)):
            seq = None if seq0 is None else seq0 + i
            symbol = syms[c["symbol_id"][i]]
            side = Side(int(c["taker_side"][i]))
            kind = (
                OrderType.MARKET if c["is_market"][i] else OrderType.LIMIT
            )
            taker = snapshot_of(
                Order(
                    uuid=uid_t[c["taker_uid"][i]],
                    oid=oid_t[c["taker_oid"][i]],
                    symbol=symbol,
                    side=side,
                    price=int(c["taker_price"][i]),
                    volume=int(c["taker_volume"][i]),
                    order_type=kind,
                )
            )
            if c["is_cancel"][i]:
                out.append(
                    MatchResult(
                        node=taker, match_node=taker, match_volume=0, seq=seq
                    )
                )
                continue
            maker = snapshot_of(
                Order(
                    uuid=uid_t[c["maker_uid"][i]],
                    oid=oid_t[c["maker_oid"][i]],
                    symbol=symbol,
                    side=side.opposite,
                    price=int(c["fill_price"][i]),
                    volume=int(c["maker_volume"][i]),
                )
            )
            out.append(
                MatchResult(
                    node=taker,
                    match_node=maker,
                    match_volume=int(c["match_volume"][i]),
                    seq=seq,
                )
            )
        return out

    def to_json_lines(self, seq0: int | None = None) -> list[bytes]:
        """Wire-shape serialization straight from columns — byte-identical
        to bus.codec.encode_match_result for every event. Only the ids this
        batch references are JSON-escaped (the interner tables grow without
        bound over a process lifetime; escaping whole tables per batch would
        be quadratic on the consumer hot path).

        With ``seq0`` (defaults to the batch's own stamp) each line gains a
        trailing ``"Seq"`` extension field — absent on unstamped batches so
        reference-shaped output is unchanged, ignored by a reference
        decoder otherwise (the Trace-field precedent, bus.codec)."""
        import json

        if seq0 is None:
            seq0 = self.seq0
        c = self.columns

        def esc(table, *id_cols):
            ids = np.unique(np.concatenate([c[n] for n in id_cols])) if id_cols else []
            return {int(i): json.dumps(table[int(i)]) for i in ids}

        oid_t = esc(self.oid_table, "taker_oid", "maker_oid")
        uid_t = esc(self.uid_table, "taker_uid", "maker_uid")
        syms = esc(list(self.symbols), "symbol_id")
        lines = []
        for i in range(len(self)):
            symbol = syms[c["symbol_id"][i]]
            t_u, t_o = uid_t[c["taker_uid"][i]], oid_t[c["taker_oid"][i]]
            side = int(c["taker_side"][i])
            if c["is_cancel"][i]:
                m_u, m_o = t_u, t_o
                m_side, m_price, m_vol = side, int(c["taker_price"][i]), int(
                    c["taker_volume"][i]
                )
            else:
                m_u, m_o = uid_t[c["maker_uid"][i]], oid_t[c["maker_oid"][i]]
                m_side = 1 - side
                m_price = int(c["fill_price"][i])
                m_vol = int(c["maker_volume"][i])
            body = (
                '{"Node":{"Uuid":%s,"Oid":%s,"Symbol":%s,'
                '"Transaction":%d,"Price":%d,"Volume":%d},'
                '"MatchNode":{"Uuid":%s,"Oid":%s,"Symbol":%s,'
                '"Transaction":%d,"Price":%d,"Volume":%d},'
                '"MatchVolume":%d'
                % (
                    t_u, t_o, symbol, side,
                    int(c["taker_price"][i]), int(c["taker_volume"][i]),
                    m_u, m_o, symbol, m_side, m_price, m_vol,
                    int(c["match_volume"][i]),
                )
            )
            if seq0 is not None:
                body += ',"Seq":%d' % (seq0 + i)
            lines.append((body + "}").encode())
        return lines


def empty_batch(symbols, oid_table, uid_table) -> EventBatch:
    return EventBatch(
        columns={n: np.zeros(0, dt) for n, dt in _COLUMNS},
        symbols=symbols,
        oid_table=oid_table,
        uid_table=uid_table,
    )


def decode_grid_columnar(ops_meta: dict, outs_at) -> dict[str, np.ndarray]:
    """Vectorized decode of one grid's worth of op results into raw event
    columns (no tables attached — the caller assembles the final EventBatch
    once per micro-batch, not per grid).

    ops_meta: parallel numpy arrays describing the ops that were packed into
    the grid — lane (the engine lane, for symbol ids), row (the grid row —
    equal to lane on full grids, the compact dense-grid row otherwise), t,
    arrival, side, price, is_market, action, oid_id, uid_id (all [N] for N
    packed ops).
    outs_at(field, rows, ts) -> numpy values of StepOutput `field` at those
    (row, t) coordinates ([N] or [N, K]); indirection so the caller can
    splice in per-row escalation re-runs.

    Returns columns sorted by (arrival, fill index) — the reference's global
    emission order.
    """
    lane = ops_meta["lane"]
    row = ops_meta.get("row", lane)
    t = ops_meta["t"]
    arrival = ops_meta["arrival"]
    action = ops_meta["action"]

    is_add = action == int(Action.ADD)
    is_del = action == int(Action.DEL)

    # --- fills: one event per (ADD op, record j < n_fills) ---------------
    n_fills = np.where(is_add, outs_at("n_fills", row, t), 0)  # [N]
    k = int(n_fills.max()) if len(n_fills) else 0
    if k:
        rec = lambda f: outs_at(f, row, t)[:, :k]  # [N, K']
        jj = np.arange(k)
        mask = jj[None, :] < n_fills[:, None]  # [N, K']
        src, j = np.nonzero(mask)  # event -> (op row, record j), arrival-major
        fill_qty = rec("fill_qty")[src, j]
        maker_remaining = rec("maker_remaining")[src, j]
        maker_prefill = rec("maker_prefill")[src, j]
        maker_volume = np.where(maker_remaining == 0, maker_prefill, maker_remaining)
        # Device prices are rebased per lane (32-bit books); events carry
        # absolute ticks.
        base = ops_meta.get("price_base")
        fill_price = rec("fill_price")[src, j].astype(np.int64)
        if base is not None:
            fill_price = fill_price + base[src]
        fills = {
            "arrival": arrival[src],
            "is_cancel": np.zeros(len(src), np.bool_),
            "symbol_id": lane[src],
            "taker_uid": ops_meta["uid_id"][src],
            "taker_oid": ops_meta["oid_id"][src],
            "taker_side": ops_meta["side"][src].astype(np.int8),
            "taker_price": ops_meta["price"][src],
            "taker_volume": rec("taker_after")[src, j],
            "maker_uid": rec("maker_uid")[src, j],
            "maker_oid": rec("maker_oid")[src, j],
            "fill_price": fill_price,
            "maker_volume": maker_volume,
            "match_volume": fill_qty,
            "is_market": ops_meta["is_market"][src].astype(np.bool_),
        }
    else:
        fills = {n: np.zeros(0, dt) for n, dt in _COLUMNS}

    # --- cancels: one event per found DEL --------------------------------
    found = is_del & (outs_at("cancel_found", row, t) != 0)
    (csrc,) = np.nonzero(found)
    cancels = {
        "arrival": arrival[csrc],
        "is_cancel": np.ones(len(csrc), np.bool_),
        "symbol_id": lane[csrc],
        "taker_uid": ops_meta["uid_id"][csrc],
        "taker_oid": ops_meta["oid_id"][csrc],
        "taker_side": ops_meta["side"][csrc].astype(np.int8),
        "taker_price": ops_meta["price"][csrc],
        "taker_volume": outs_at("cancel_volume", row, t)[csrc],
        "maker_uid": ops_meta["uid_id"][csrc],
        "maker_oid": ops_meta["oid_id"][csrc],
        "fill_price": ops_meta["price"][csrc],
        "maker_volume": outs_at("cancel_volume", row, t)[csrc],
        "match_volume": np.zeros(len(csrc), np.int64),
        "is_market": np.zeros(len(csrc), np.bool_),
    }

    columns = {
        n: np.concatenate(
            [np.asarray(fills[n], dt), np.asarray(cancels[n], dt)]
        )
        for n, dt in _COLUMNS
    }
    # Global emission order: arrival index, then record order within the op
    # (np.nonzero already yields row-major = record order; a stable sort on
    # arrival preserves it).
    order = np.argsort(columns["arrival"], kind="stable")
    return {n: v[order] for n, v in columns.items()}

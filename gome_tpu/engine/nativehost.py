"""ctypes bindings for the native host ops (native/hostops.cc): the C++
string interner and pre-pool used on the frame hot path.

Loads the same libgome_native.so the bus backends build (sha-pinned,
native/build.py); everything degrades to the pure-Python implementations
(engine.host.Interner, engine.prepool.LocalPrePool) when no toolchain is
available — behavior is identical, throughput is not (~2.6 us/order of
Python hash loops vs ~0.15 us in C++ at the 262K-order frame shape).

Threading: PrePool calls are mutex-guarded in C++ (gateway gRPC threads
mark concurrently with consumer admission); the Interner is only ever
touched from the consumer thread (BatchEngine is single-consumer by
design, SURVEY §5.2).
"""

from __future__ import annotations

import ctypes

import numpy as np

_lib = None
_tried = False

_i64 = ctypes.c_int64
_p_char = ctypes.c_char_p
_p_u8 = ctypes.POINTER(ctypes.c_uint8)
_p_u32 = ctypes.POINTER(ctypes.c_uint32)
_p_i64 = ctypes.POINTER(ctypes.c_int64)


def load():
    """The shared library with gi_*/gp_* prototypes set, or None."""
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    from ..bus.native import _load

    lib = _load()
    if lib is None:
        return None
    lib.gi_new.restype = ctypes.c_void_p
    lib.gi_free.argtypes = [ctypes.c_void_p]
    lib.gi_len.restype = _i64
    lib.gi_len.argtypes = [ctypes.c_void_p]
    lib.gi_max_len.restype = _i64
    lib.gi_max_len.argtypes = [ctypes.c_void_p]
    lib.gi_intern_one.restype = _i64
    lib.gi_intern_one.argtypes = [ctypes.c_void_p, _p_char, _i64]
    lib.gi_get.restype = _i64
    lib.gi_get.argtypes = [ctypes.c_void_p, _p_char, _i64]
    lib.gi_intern_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, _i64, _i64, _p_i64,
    ]
    lib.gi_lookup.restype = _i64
    lib.gi_lookup.argtypes = [ctypes.c_void_p, _i64, ctypes.c_void_p, _i64]
    lib.gi_gather.restype = _i64
    lib.gi_gather.argtypes = [
        ctypes.c_void_p, _p_i64, _i64, ctypes.c_void_p, _i64,
    ]
    lib.gi_gather_width.restype = _i64
    lib.gi_gather_width.argtypes = [ctypes.c_void_p, _p_i64, _i64]
    lib.gi_export.restype = _i64
    lib.gi_export.argtypes = [ctypes.c_void_p, ctypes.c_void_p, _i64]
    lib.gi_import.restype = _i64
    lib.gi_import.argtypes = [ctypes.c_void_p, _p_char, _i64, _i64]

    lib.gp_new.restype = ctypes.c_void_p
    lib.gp_free.argtypes = [ctypes.c_void_p]
    lib.gp_len.restype = _i64
    lib.gp_len.argtypes = [ctypes.c_void_p]
    for f in (lib.gp_add, lib.gp_discard, lib.gp_contains):
        f.restype = _i64
        f.argtypes = [ctypes.c_void_p, _p_char, _i64]
    lib.gp_clear.argtypes = [ctypes.c_void_p]
    lib.gp_dump.restype = _i64
    lib.gp_dump.argtypes = [ctypes.c_void_p, ctypes.c_void_p, _i64]
    lib.gp_frame.restype = _i64
    lib.gp_frame.argtypes = [
        ctypes.c_void_p, _i64, ctypes.c_void_p,  # h, n, action
        _p_char, _p_i64, ctypes.c_void_p,  # sym data/offs/idx
        _p_char, _p_i64, ctypes.c_void_p,  # uuid data/offs/idx
        ctypes.c_void_p, _i64,  # oids, width
        _i64, _i64,  # add_val, del_val
        ctypes.c_void_p, ctypes.c_void_p, _i64,  # keep, existed, mode
    ]
    lib.go_occurrences.argtypes = [
        _p_i64, ctypes.c_void_p, _i64, _i64, _p_i64,
    ]
    lib.go_pack_grid.restype = _i64
    lib.go_pack_grid.argtypes = (
        [_i64, _p_i64]  # n_sub, idx
        + [_p_i64, _p_i64, _p_i64, _i64, _i64, _i64]  # row_of..n_rows
        + [_p_i64] * 8  # action..bases
        + [_i64, _i64]  # market_val, add_val
        + [ctypes.c_void_p, ctypes.c_void_p, _i64, _i64]  # cols/flat/stride/itemsize
        + [_p_i64] * 11  # meta outputs
    )
    lib.go_decode_compact.restype = _i64
    lib.go_decode_compact.argtypes = (
        [_i64] * 6
        + [_p_i64] * 7  # fills
        + [_p_i64] * 2  # cancels
        + [_i64] + [_p_i64] * 10  # meta
        + [
            _p_i64, ctypes.c_void_p, _p_i64, _p_i64, _p_i64,
            ctypes.c_void_p, _p_i64, _p_i64, _p_i64, _p_i64, _p_i64,
            _p_i64, _p_i64, ctypes.c_void_p,
        ]  # outputs
    )
    _lib = lib
    return lib


def decode_compact(meta: dict, t_len: int, k: int, nf: int, nc: int,
                   fills: dict, cancels: dict) -> dict:
    """One grid's compacted device events -> final event columns in the
    reference's global emission order (C++ join + stable counting sort).
    Mirrors the numpy path in engine.frames._decode_compact exactly."""
    lib = load()
    ne = nf + nc

    def i64(a):
        return np.ascontiguousarray(a, np.int64)

    f = {name: i64(fills[name][:nf]) for name in (
        "src", "fill_price", "fill_qty", "maker_oid", "maker_uid",
        "maker_volume", "taker_after",
    )}
    c = {name: i64(cancels[name][:nc]) for name in ("src", "volume")}
    ms = {name: i64(meta[name]) for name in (
        "row", "t", "arrival", "lane", "uid_id", "oid_id", "side",
        "price", "price_base", "is_market",
    )}
    m = len(ms["row"])
    frame_n = int(ms["arrival"].max()) + 1 if m else 0

    out = {
        "arrival": np.empty(ne, np.int64),
        "is_cancel": np.empty(ne, np.bool_),
        "symbol_id": np.empty(ne, np.int64),
        "taker_uid": np.empty(ne, np.int64),
        "taker_oid": np.empty(ne, np.int64),
        "taker_side": np.empty(ne, np.int8),
        "taker_price": np.empty(ne, np.int64),
        "taker_volume": np.empty(ne, np.int64),
        "maker_uid": np.empty(ne, np.int64),
        "maker_oid": np.empty(ne, np.int64),
        "fill_price": np.empty(ne, np.int64),
        "maker_volume": np.empty(ne, np.int64),
        "match_volume": np.empty(ne, np.int64),
        "is_market": np.empty(ne, np.bool_),
    }
    p = lambda a: a.ctypes.data_as(_p_i64)
    v = lambda a: a.ctypes.data_as(ctypes.c_void_p)
    rc = lib.go_decode_compact(
        int(meta["_n_rows"]), t_len, k, nf, nc, frame_n,
        p(f["src"]), p(f["fill_price"]), p(f["fill_qty"]),
        p(f["maker_oid"]), p(f["maker_uid"]), p(f["maker_volume"]),
        p(f["taker_after"]),
        p(c["src"]), p(c["volume"]),
        m, p(ms["row"]), p(ms["t"]), p(ms["arrival"]), p(ms["lane"]),
        p(ms["uid_id"]), p(ms["oid_id"]), p(ms["side"]), p(ms["price"]),
        p(ms["price_base"]), p(ms["is_market"]),
        p(out["arrival"]), v(out["is_cancel"]), p(out["symbol_id"]),
        p(out["taker_uid"]), p(out["taker_oid"]), v(out["taker_side"]),
        p(out["taker_price"]), p(out["taker_volume"]), p(out["maker_uid"]),
        p(out["maker_oid"]), p(out["fill_price"]), p(out["maker_volume"]),
        p(out["match_volume"]), v(out["is_market"]),
    )
    if rc != 0:
        raise RuntimeError("native compact decode failed (corrupt grid)")
    return out


_META_NAMES = (
    "lane", "row", "t", "arrival", "action", "side", "is_market",
    "price", "price_base", "oid_id", "uid_id",
)


def pack_grid(
    a: dict, idx: np.ndarray, row_of: np.ndarray, t_off: int, t_grid: int,
    n_rows: int, m_pad: int, val_dtype, market_val: int, add_val: int,
) -> tuple[np.ndarray, np.ndarray, dict]:
    """One grid's column pack + meta extraction in a single native pass
    (the C++ form of frames.pack_frame_grids' inner loop). `a` is the
    _frame_arrays dict; `idx` the candidate op indices still alive at
    this grid's time offset (a train's later grids pass shrinking
    subsets); `row_of` the [n_slots] lane -> grid-row map (identity for
    full grids); `m_pad` the pow2-padded column count (padding columns
    carry the out-of-grid sentinel flat index and scatter-drop on
    device). Returns (cols [7, m_pad] in DeviceOp field order, flat
    [m_pad] int32 grid positions, meta dict of [m] int64 columns;
    meta['arrival'] carries original frame indices)."""
    lib = load()
    i64 = lambda x: np.ascontiguousarray(x, np.int64)
    idx = i64(idx)
    row_of = i64(row_of)
    t = i64(a["t"])
    t_sub = t[idx]
    m = int(np.count_nonzero((t_sub >= t_off) & (t_sub < t_off + t_grid)))
    assert m <= m_pad, (m, m_pad)
    val_dtype = np.dtype(val_dtype)
    cols = np.empty((7, m_pad), val_dtype)
    flat = np.full(m_pad, n_rows * t_grid, np.int32)  # sentinel: drop
    meta = {name: np.empty(m, np.int64) for name in _META_NAMES}
    p = lambda arr: arr.ctypes.data_as(_p_i64)
    v = lambda arr: arr.ctypes.data_as(ctypes.c_void_p)
    got = lib.go_pack_grid(
        len(idx), p(idx), p(row_of), p(i64(a["lanes"])), p(t), t_off,
        t_grid, n_rows,
        p(i64(a["action"])), p(i64(a["side"])), p(i64(a["kind"])),
        p(i64(a["price"])), p(i64(a["volume"])), p(i64(a["oid_ids"])),
        p(i64(a["uid_ids"])), p(i64(a["bases"])), market_val, add_val,
        v(cols), v(flat), m_pad, val_dtype.itemsize,
        *(p(meta[name]) for name in _META_NAMES),
    )
    if got != m:
        raise RuntimeError(f"native grid pack failed (packed {got} != {m})")
    return cols, flat, meta


def occurrences(lanes: np.ndarray, keep, n_lanes: int) -> np.ndarray:
    """t[i] = occurrence index of row i within its lane over kept rows in
    arrival order (-1 where keep is False). keep=None means all kept."""
    lib = load()
    lanes = np.ascontiguousarray(lanes, np.int64)
    out = np.empty(len(lanes), np.int64)
    if keep is not None:
        keep = np.ascontiguousarray(keep, np.uint8)
    lib.go_occurrences(
        lanes.ctypes.data_as(_p_i64),
        keep.ctypes.data_as(ctypes.c_void_p) if keep is not None else None,
        len(lanes), n_lanes, out.ctypes.data_as(_p_i64),
    )
    return out


def available() -> bool:
    return load() is not None


def _ptr(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.c_void_p)


def pack_strlist(strs) -> tuple[bytes, np.ndarray]:
    """Concatenate a list of strings for the C side: (bytes, offsets[n+1])."""
    bs = [s.encode() if isinstance(s, str) else s for s in strs]
    offs = np.zeros(len(bs) + 1, np.int64)
    if bs:
        np.cumsum(
            np.fromiter(map(len, bs), np.int64, len(bs)), out=offs[1:]
        )
    return b"".join(bs), offs


def _parse_len_prefixed(buf: bytes, n: int) -> list[str]:
    out = []
    pos = 0
    for _ in range(n):
        ln = int.from_bytes(buf[pos : pos + 4], "little")
        pos += 4
        out.append(buf[pos : pos + ln].decode())
        pos += ln
    return out


class _LazyTable:
    """id -> string view over a NativeInterner, quacking like the Python
    Interner's list table (indexing, len, iteration). Hot paths never
    materialize strings from it — colwire's id-table packer uses
    gather_padded instead."""

    __slots__ = ("_interner",)

    def __init__(self, interner: "NativeInterner"):
        self._interner = interner

    def __getitem__(self, i: int) -> str:
        return self._interner.lookup(int(i))

    def __len__(self) -> int:
        return len(self._interner)

    def __iter__(self):
        for i in range(len(self)):
            yield self._interner.lookup(i)

    def gather_padded(self, ids: np.ndarray) -> np.ndarray:
        return self._interner.gather_padded(ids)


class NativeInterner:
    """Drop-in for engine.host.Interner backed by the C++ table, plus the
    batch ops the frame path uses (intern_batch, gather_padded)."""

    def __init__(self):
        self._lib = load()
        if self._lib is None:
            raise RuntimeError("native host ops unavailable")
        self._h = ctypes.c_void_p(self._lib.gi_new())
        self._table = _LazyTable(self)

    def __del__(self):
        h, self._h = self._h, None
        if h and getattr(self, "_lib", None) is not None:
            self._lib.gi_free(h)

    # -- Interner API ------------------------------------------------------
    def intern(self, s: str) -> int:
        b = s.encode()
        return self._lib.gi_intern_one(self._h, b, len(b))

    def get(self, s: str) -> int | None:
        b = s.encode()
        i = self._lib.gi_get(self._h, b, len(b))
        return None if i == 0 else i

    def lookup(self, i: int) -> str:
        if i == 0:
            return ""
        cap = max(self._lib.gi_max_len(self._h), 1)
        buf = ctypes.create_string_buffer(cap)
        ln = self._lib.gi_lookup(self._h, i, buf, cap)
        if ln < 0:
            raise IndexError(f"interner id {i} out of range")
        return buf.raw[:ln].decode()

    @property
    def table(self) -> _LazyTable:
        return self._table

    def __len__(self) -> int:
        # Python Interner len counts the reserved "" at id 0 too.
        return int(self._lib.gi_len(self._h)) + 1

    def to_list(self) -> list[str]:
        n = int(self._lib.gi_len(self._h))
        need = self._lib.gi_export(self._h, None, 0)
        buf = ctypes.create_string_buffer(max(int(need), 1))
        self._lib.gi_export(self._h, buf, need)
        return _parse_len_prefixed(buf.raw[:need], n)

    @classmethod
    def from_list(cls, strs: list[str]):
        self = cls()
        parts = []
        for s in strs:
            b = s.encode()
            parts.append(len(b).to_bytes(4, "little"))
            parts.append(b)
        blob = b"".join(parts)
        if self._lib.gi_import(self._h, blob, len(blob), len(strs)) != 0:
            raise ValueError("interner import failed")
        return self

    # -- batch ops (the frame hot path) ------------------------------------
    def intern_batch(self, arr: np.ndarray) -> np.ndarray:
        """Intern a numpy 'S'-dtype column; returns int64 ids."""
        arr = np.ascontiguousarray(arr)
        assert arr.dtype.kind == "S", arr.dtype
        n = len(arr)
        out = np.empty(n, np.int64)
        self._lib.gi_intern_batch(
            self._h, _ptr(arr), n, arr.dtype.itemsize,
            out.ctypes.data_as(_p_i64),
        )
        return out

    def gather_padded(self, ids: np.ndarray) -> np.ndarray:
        ids = np.ascontiguousarray(ids, np.int64)
        # Pad to the max over the REQUESTED ids, not the process-lifetime
        # max — one long id must not inflate every later frame's tables.
        width = self._lib.gi_gather_width(
            self._h, ids.ctypes.data_as(_p_i64), len(ids)
        )
        if width < 0:
            raise IndexError("gather: interner id out of range")
        width = max(int(width), 1)
        out = np.empty(len(ids), dtype=f"S{width}")
        rc = self._lib.gi_gather(
            self._h, ids.ctypes.data_as(_p_i64), len(ids), _ptr(out), width
        )
        if rc != 0:
            raise IndexError("gather: interner id out of range")
        return out


def make_interner(from_list=None):
    """A NativeInterner when the toolchain allows, else the Python one."""
    from .host import Interner

    if available():
        return (
            NativeInterner.from_list(from_list)
            if from_list is not None
            else NativeInterner()
        )
    return (
        Interner.from_list(from_list) if from_list is not None else Interner()
    )

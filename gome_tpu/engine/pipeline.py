"""Cross-frame pipelining: overlap frame k+1's host work (decode, intern,
pack, dispatch) with frame k's device execution and device->host fetch.

The single-frame fast path (frames.apply_frame_fast) already collapses a
frame to one overlapped fetch, but a synchronous consumer still serializes
[host k] -> [fetch k] -> [host k+1] -> ... . submit_frame advances
eng.books at dispatch time, so a later frame can be SUBMITTED before an
earlier one is RESOLVED — sequential matching semantics hold because the
device executes the dispatched grids in order; only the host-side
resolution (fetch + decode + publish) trails behind. Steady-state
throughput becomes max(host_time, fetch_time) per frame instead of their
sum.

Recovery keeps the transactional story:

  * a device budget tripped in frame k (detected at resolve): rewind the
    engine to k's checkpoint, re-run k on the exact escalating path, then
    RESUBMIT every later in-flight frame on top (their columns are
    retained; their pre-pool admission is not repeated — the marks were
    already consumed at feed time and stay consumed);
  * a hard failure: rewind to k's checkpoint, restore every in-flight
    frame's consumed pre-pool marks, clear the pipeline, re-raise — the
    at-least-once consumer replays all of them from the uncommitted
    offset.
"""

from __future__ import annotations

from collections import deque

from . import frames
from .orchestrator import MatchEngine


class FramePipeline:
    """Depth-D pipelined ORDER-frame executor over one MatchEngine.

    feed(cols, token) submits a frame (admission included) and returns any
    frames that resolved as a list of (token, EventBatch); flush() drains
    the rest. Tokens let the caller (the consumer) commit each frame's bus
    offset only after ITS events resolved and published."""

    def __init__(self, engine: MatchEngine, depth: int = 2):
        if depth < 1:
            raise ValueError("pipeline depth must be >= 1")
        self.engine = engine
        self.depth = depth
        self._q: deque = deque()  # (pending, consumed, token)

    def feed(self, cols: dict, token=None) -> list[tuple]:  # gomelint: hotpath
        eng = self.engine.batch
        fcols, consumed = self.engine.admit_frame(cols)
        try:
            pend = frames.submit_frame(eng, fcols)
        except Exception:
            # submit rolled the engine back; this frame's marks restore
            # here, in-flight frames are untouched (they precede it).
            self.engine.pre_pool |= consumed
            raise
        self._q.append((pend, consumed, token))
        out = []
        while len(self._q) > self.depth:
            out.append(self._resolve_oldest())
        return out

    def flush(self) -> list[tuple]:
        out = []
        while self._q:
            out.append(self._resolve_oldest())
        return out

    # gomelint: hotpath
    def step(self):
        """Resolve the oldest in-flight frame, or None if nothing is in
        flight — the consumer's make-progress primitive when the order
        queue is momentarily empty."""
        if not self._q:
            return None
        return self._resolve_oldest()

    def abort(self) -> None:
        """Discard every in-flight frame: rewind the engine to the oldest
        frame's checkpoint and restore all consumed pre-pool marks, so the
        at-least-once consumer can replay from its uncommitted offset. Used
        when a failure OUTSIDE the pipeline (e.g. the match-queue publish of
        an already-resolved frame) forces the consumer to restart a span
        whose later frames are still in flight."""
        if not self._q:
            return
        eng = self.engine.batch
        eng._restore(self._q[0][0].checkpoint)
        for _pend, consumed, _token in self._q:
            self.engine.pre_pool |= consumed
        self._q.clear()

    def _resolve_oldest(self):
        eng = self.engine.batch
        pend, consumed, token = self._q.popleft()
        try:
            return (token, frames.resolve_frame(eng, pend))
        except frames._NeedExact:
            eng.stats.frame_fallbacks += 1
            # Budget tripped: rewind THROUGH every later in-flight frame
            # (they were submitted on top of the bad state), replay this
            # frame exactly, then resubmit the later ones.
            eng._restore(pend.checkpoint)
            later = list(self._q)
            self._q.clear()
            try:
                batch = frames.apply_frame(eng, pend.cols)
            except Exception:
                # The exact re-run itself failed (e.g. the overflow that
                # tripped the budget exceeds max_cap). _run_exact commits
                # books per grid, so partial state may be applied: rewind
                # to the checkpoint and restore this frame's AND every
                # later in-flight frame's consumed pre-pool marks — the
                # at-least-once consumer replays all of them from the
                # uncommitted offset (mirrors apply_frame_fast's fallback).
                eng._restore(pend.checkpoint)
                self.engine.pre_pool |= consumed
                for _lp, lc, _lt in later:
                    self.engine.pre_pool |= lc
                raise
            try:
                for lp, lc, lt in later:
                    self._q.append(
                        (frames.submit_frame(eng, lp.cols), lc, lt)
                    )
            except Exception:
                # A resubmit failed AFTER the exact re-run committed this
                # frame. Returning nothing would lose the frame's events
                # (its marks are consumed, so the replay would drop its
                # ADDs): treat the whole span as a hard failure instead —
                # rewind THROUGH the exact re-run to this frame's
                # checkpoint, restore its and every later frame's marks,
                # and let the at-least-once replay regenerate everything.
                eng._restore(pend.checkpoint)
                self.engine.pre_pool |= consumed
                for _lp2, lc2, _lt2 in later:
                    self.engine.pre_pool |= lc2
                self._q.clear()
                raise
            return (token, batch)
        except Exception:
            # Hard failure: no trace of this frame or anything after it.
            eng._restore(pend.checkpoint)
            self.engine.pre_pool |= consumed
            for _lp, lc, _lt in self._q:
                self.engine.pre_pool |= lc
            self._q.clear()
            raise

    def __len__(self) -> int:
        return len(self._q)

"""Cross-frame pipelining: overlap frame k+1's host work (decode, intern,
pack, dispatch) with frame k's device execution and device->host fetch.

The single-frame fast path (frames.apply_frame_fast) already collapses a
frame to one overlapped fetch, but a synchronous consumer still serializes
[host k] -> [fetch k] -> [host k+1] -> ... . submit_frame advances
eng.books at dispatch time, so a later frame can be SUBMITTED before an
earlier one is RESOLVED — sequential matching semantics hold because the
device executes the dispatched grids in order; only the host-side
resolution (fetch + decode + publish) trails behind. Steady-state
throughput becomes max(host_time, fetch_time) per frame instead of their
sum.

Recovery keeps the transactional story:

  * a device budget tripped in frame k (detected at resolve): rewind the
    engine to k's checkpoint, re-run k on the exact escalating path, then
    RESUBMIT every later in-flight frame on top (their columns are
    retained; their pre-pool admission is not repeated — the marks were
    already consumed at feed time and stay consumed);
  * a hard failure: rewind to k's checkpoint, restore every in-flight
    frame's consumed pre-pool marks, clear the pipeline, re-raise — the
    at-least-once consumer replays all of them from the uncommitted
    offset.
"""

from __future__ import annotations

from collections import deque

from . import frames
from .orchestrator import MatchEngine


class FramePipeline:
    """Depth-D pipelined ORDER-frame executor over one MatchEngine.

    feed(cols, token) submits a frame (admission included) and returns any
    frames that resolved as a list of (token, EventBatch); flush() drains
    the rest. Tokens let the caller (the consumer) commit each frame's bus
    offset only after ITS events resolved and published."""

    def __init__(self, engine: MatchEngine, depth: int = 2):
        if depth < 1:
            raise ValueError("pipeline depth must be >= 1")
        self.engine = engine
        self.depth = depth
        self._q: deque = deque()  # (pending, consumed, token)

    def feed(self, cols: dict, token=None) -> list[tuple]:
        eng = self.engine.batch
        fcols, consumed = self.engine.admit_frame(cols)
        try:
            pend = frames.submit_frame(eng, fcols)
        except Exception:
            # submit rolled the engine back; this frame's marks restore
            # here, in-flight frames are untouched (they precede it).
            self.engine.pre_pool |= consumed
            raise
        self._q.append((pend, consumed, token))
        out = []
        while len(self._q) > self.depth:
            out.append(self._resolve_oldest())
        return out

    def flush(self) -> list[tuple]:
        out = []
        while self._q:
            out.append(self._resolve_oldest())
        return out

    def _resolve_oldest(self):
        eng = self.engine.batch
        pend, consumed, token = self._q.popleft()
        try:
            return (token, frames.resolve_frame(eng, pend))
        except frames._NeedExact:
            # Budget tripped: rewind THROUGH every later in-flight frame
            # (they were submitted on top of the bad state), replay this
            # frame exactly, then resubmit the later ones.
            eng._restore(pend.checkpoint)
            batch = frames.apply_frame(eng, pend.cols)
            later = list(self._q)
            self._q.clear()
            try:
                for lp, lc, lt in later:
                    self._q.append(
                        (frames.submit_frame(eng, lp.cols), lc, lt)
                    )
            except Exception:
                # The failed resubmit rolled itself back; it and anything
                # after it fall out of the pipeline — restore their marks
                # so the consumer's replay re-admits them.
                for _lp2, lc2, _lt2 in later[len(self._q) :]:
                    self.engine.pre_pool |= lc2
                raise
            return (token, batch)
        except Exception:
            # Hard failure: no trace of this frame or anything after it.
            eng._restore(pend.checkpoint)
            self.engine.pre_pool |= consumed
            for _lp, lc, _lt in self._q:
                self.engine.pre_pool |= lc
            self._q.clear()
            raise

    def __len__(self) -> int:
        return len(self._q)

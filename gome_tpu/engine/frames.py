"""Frame path: apply a columnar order batch with ZERO per-order Python.

The object path (BatchEngine.process_columnar) builds one `Order` per
message and walks a Python loop per op to intern ids and fill the device
grid — ~1-2 µs/order of host time, a 10x gap to the 1M orders/sec
north-star once the device no longer bottlenecks. This module applies a
decoded ORDER frame (gome_tpu.bus.colwire) straight from numpy columns:

  * interning is vectorized: `np.unique` reduces each string column to its
    per-batch uniques, the interner dict is touched once per UNIQUE value,
    and a take() broadcasts ids back to all N orders;
  * the rebasing envelope, the unrepresentable-DEL drop mask, and the
    per-lane time-slot assignment are all numpy (sort/segment tricks);
  * grid packing reuses the object path's geometry decision
    (BatchEngine._grid_geometry: dense gather/scatter grids vs full
    grids) and the SAME _run_exact / decode_grid_columnar machinery, so
    escalations and event decoding are shared — the frame path changes
    how ops get INTO a grid, nothing about what a grid means.

Two execution strategies:

  * `apply_frame` — exact, synchronous: each grid runs through
    BatchEngine._run_exact (device budgets escalate in-line). One device
    round trip per grid.
  * `apply_frame_fast` — the production hot path: every grid of the frame
    is DISPATCHED back-to-back with a device-side event-compaction kernel
    (compact_accum) appended, then ONE async fetch resolves the
    whole frame. The compaction reduces the transfer from O(S*T*K) record
    tensors (~500 B/order, seconds over a tunneled link) to O(events)
    (~30 B/order). If any device budget tripped (book overflow, record
    truncation, compaction buffer), the frame transactionally rolls back
    and re-runs on the exact path — rare by construction, never wrong.

Event content and ordering are pinned to the object path by differential
tests (tests/test_frames.py).
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..obs.compile_journal import JOURNAL, frame_combo_detail
from ..obs.timeline import TIMELINE
from ..types import Action, OrderType
from ..utils.trace import TRACER
from .batch import BatchEngine, _next_pow2, _next_pow4, splice_outs
from .book import GRID_I32_FIELDS, DeviceOp
from .step import ACTION_ADD, LOT_MAX32

#: Cumulative wall-clock seconds apply_frame_fast spent BLOCKED on the
#: device->host fetch of compacted events. On a tunneled dev TPU this link
#: runs at single-digit MB/s and dominates end-to-end service time; the
#: service bench subtracts it to report the pipeline's capability on
#: production (PCIe-attached) hardware alongside the measured number.
FETCH_SECONDS = 0.0

ACTION_DEL = int(Action.DEL)
MARKET = int(OrderType.MARKET)

_GRID_FIELDS = DeviceOp._fields  # one canonical field list + order

#: Per-grid record-tensor element budget (T*K*R per record array; 5 record
#: arrays x 4 B => 16M elements ~ 320 MB of step outputs). Bounds the
#: rows-x-depth product of dense grids so deep time axes are reserved for
#: few-row (hot-lane) grids.
_REC_ELEM_BUDGET = 1 << 24

#: Hard per-frame op ceiling (wire contract, enforced in _frame_arrays).
#: This is what makes the m_pad / e_fills / e_cancels / totals_len combo
#: dimensions FINITE: every one of them is a quantized function of the
#: frame's op count, so bounding the op count bounds the compile surface
#: (analysis.surface GL905 derives the committed combo universe from it).
#: 1M ops/frame is ~100x the largest replay burst; a frame this large is
#: a producer bug, not traffic.
MAX_FRAME_OPS = 1 << 20

#: The frame-dispatch combo key, field by field, in tuple order. This is
#: the spine of the gomesurface GL902 site-agreement check: the build
#: tuple (submit_frame), every replay unpack (precompile_combos,
#: obs.compile_journal.frame_combo_detail), and the persisted manifest
#: (BatchEngine.shape_manifest) must all agree with THIS declaration —
#: adding a dimension means updating every site in one commit, and lint
#: fails until they line up.
COMBO_FIELDS = (
    "n_rows",      # grid rows (live-lane bucket or full n_slots)
    "t_grid",      # grid time-axis depth (packed-train class)
    "cap_g",       # book capacity class dispatched against
    "dense",       # full-grid (False) vs compact gather/scatter (True=
                   # lane_ids present) dispatch path
    "m_pad",       # packed-op axis length (pow4 of the frame op count)
    "k_rec",       # step record depth min(max_fills, cap)
    "e_fills",     # fills compaction buffer width (pow2 + grow-only floor)
    "e_cancels",   # cancels compaction buffer width
    "totals_len",  # per-grid totals buffer length
)


def _lane_map(eng: BatchEngine, symbols) -> np.ndarray:
    """symbol-dictionary -> lane-id array, cached by dictionary identity.

    The wire decoder (bus.colwire) returns the SAME list object for a
    dictionary region it has seen before, so a stable symbol universe
    resolves its per-unique interner walk once, not once per frame. Lane
    ids are permanent (the interner is grow-only), BUT a cached map is
    only usable while every lane fits the CURRENT book stack: _lane()'s
    side effect is auto-growing n_slots, and a transactional rollback
    (_restore after a failed/overflowed frame) shrinks n_slots back — a
    blind cache hit on the retry would skip the re-growth and index past
    the restored books. Hence the max-lane revalidation; a stale hit
    recomputes, re-growing exactly as the first attempt did. The cache
    resets when the engine's interners are replaced (import_state)."""
    ent = eng._lane_map_cache.get(symbols)
    if ent is not None and ent[1] < eng.n_slots:
        return ent[0]
    lane_of_sym = np.empty(len(symbols), np.int64)
    for i, s in enumerate(symbols):
        lane_of_sym[i] = eng._lane(s)  # may auto-grow the book stack
    max_lane = int(lane_of_sym.max()) if len(lane_of_sym) else -1
    eng._lane_map_cache.put(symbols, (lane_of_sym, max_lane))
    return lane_of_sym


def intern_column(interner, uniques) -> np.ndarray:
    """Intern a column's per-batch unique strings; returns int64 ids
    aligned with `uniques`. The only Python loop is over uniques."""
    ids = np.empty(len(uniques), np.int64)
    intern = interner.intern
    for i, s in enumerate(uniques):
        ids[i] = intern(s if isinstance(s, str) else s.decode())
    return ids


def _frame_arrays(eng: BatchEngine, cols: dict) -> dict:
    """Stage 1: vectorized interning, contract checks, envelope/drop mask,
    and per-lane slot assignment. Returns the arrays grid packing needs."""
    n = int(cols["n"])
    if n > MAX_FRAME_OPS:
        raise ValueError(
            f"frame has {n} ops, above the MAX_FRAME_OPS contract ceiling "
            f"({MAX_FRAME_OPS}); split the frame — the compile-surface "
            "bound (analysis/combo_universe.json) is derived from this "
            "limit"
        )
    action = np.ascontiguousarray(cols["action"], np.int64)
    side = np.ascontiguousarray(cols["side"], np.int64)
    kind = np.ascontiguousarray(cols["kind"], np.int64)
    price = np.ascontiguousarray(cols["price"], np.int64)
    volume = np.ascontiguousarray(cols["volume"], np.int64)

    lane_of_sym = _lane_map(eng, cols["symbols"])
    lanes = lane_of_sym[cols["symbol_idx"]]

    uid_of = intern_column(eng.uids, cols["uuids"])
    uid_ids = uid_of[cols["uuid_idx"]]
    # oids are raw per-order strings and typically (in exchange flow)
    # almost all NEW — a dedup sort would cost more than it saves; intern
    # directly (the interner handles repeats). One native call when the
    # C++ interner backs eng.oids.
    intern_batch = getattr(eng.oids, "intern_batch", None)
    if intern_batch is not None:
        oid_ids = intern_batch(cols["oids"])
    else:
        intern = eng.oids.intern
        oid_ids = np.fromiter(
            (intern(o.decode()) for o in cols["oids"].tolist()), np.int64, n
        )

    is_add = action == ACTION_ADD
    bad = is_add & (volume <= 0)
    if bad.any():
        i = int(np.nonzero(bad)[0][0])
        raise ValueError(
            f"volume must be positive, got {volume[i]}; volume<=0 is out "
            "of contract"
        )
    if np.dtype(eng.config.dtype).itemsize <= 4:
        over = is_add & (volume > LOT_MAX32)
        if over.any():
            i = int(np.nonzero(over)[0][0])
            raise ValueError(
                f"volume {volume[i]} exceeds the int32-mode per-order lot "
                f"ceiling {LOT_MAX32}; use coarser lot units or an int64 "
                "BookConfig"
            )

    drop = _prepare_bases_vec(eng, lanes, action, kind, price)
    bases = eng.price_base[lanes]

    # Occurrence index of each op within its lane, in arrival order. One
    # native linear pass when available; else the numpy stable-sort trick
    # (sort by lane groups each lane's ops contiguously with arrival order
    # preserved; index-in-group = arange minus the group's start).
    keep = ~drop
    from . import nativehost

    if nativehost.available():
        t = nativehost.occurrences(
            lanes, None if keep.all() else keep, eng.n_slots
        )
    else:
        t = np.full(n, -1, np.int64)
        if keep.any():
            ki = np.nonzero(keep)[0]
            order = np.argsort(lanes[ki], kind="stable")
            sorted_lanes = lanes[ki][order]
            starts = np.concatenate(
                ([0], np.nonzero(np.diff(sorted_lanes))[0] + 1)
            )
            group_start = np.zeros(len(sorted_lanes), np.int64)
            group_start[starts] = starts
            group_start = np.maximum.accumulate(group_start)
            occ = np.arange(len(sorted_lanes)) - group_start
            t[ki[order]] = occ

    # count_ub upkeep (cap-class selection, batch.py): every kept limit
    # ADD may rest at most once. The increment happens at PACK time — the
    # classes chosen below then cover this frame's own worst case.
    rest_mask = keep & is_add & (kind != MARKET)
    add_counts = np.bincount(
        lanes[rest_mask], minlength=eng.n_slots
    ).astype(np.int64)
    eng.note_packed_adds(add_counts)

    return dict(
        n=n, action=action, side=side, kind=kind, price=price,
        volume=volume, lanes=lanes, uid_ids=uid_ids, oid_ids=oid_ids,
        keep=keep, t=t, bases=bases,
        dels_total=int((action == ACTION_DEL).sum()),
        add_counts=add_counts,
    )


@functools.lru_cache(maxsize=256)  # a cap-class train set (rows x depth
# classes x caps) can exceed 64 live shapes; eviction = silent re-trace
def _scatter_grid_fn(dtype_name: str, n_rows: int, t_grid: int):
    """Jitted device-side grid builder for one (dtype, R, T) shape:
    packed op columns [7, m_pad] + flat positions [m_pad] -> a padded
    DeviceOp grid. The host uploads O(ops) bytes regardless of the
    grid's occupancy — a Zipf train's deep tail grids are ~1% occupied,
    and shipping their NOP padding over the device link cost more than
    the matching itself. Padding columns carry flat == R*T and drop."""
    dtype = jnp.dtype(dtype_name)
    rt = n_rows * t_grid

    @jax.jit
    def scatter(cols, flat):
        fields = {}
        for i, name in enumerate(_GRID_FIELDS):
            want = jnp.int32 if name in GRID_I32_FIELDS else dtype
            fields[name] = (
                jnp.zeros((rt,), want)
                .at[flat]
                .set(cols[i].astype(want), mode="drop")
                .reshape(n_rows, t_grid)
            )
        return DeviceOp(**fields)

    return scatter


def _class_partitions(eng: BatchEngine, a: dict, active_idx):
    """Split a frame's kept ops into per-cap-class partitions by LANE
    (VERDICT r4 #2: stop taxing 10K shallow lanes for one hot lane's
    escalated cap). A lane's class is the smallest ladder cap covering its
    resting-count upper bound — count_ub already includes this frame's
    packed ADDs (note_packed_adds runs at pack time), so within-frame
    growth is covered and a well-estimated lane can never overflow its
    class. Same-lane ops stay in one partition: per-symbol FIFO is
    preserved exactly as in a single train.

    Returns [(cap_class, active_idx_subset), ...], ascending by class;
    a single-class ladder (storage cap <= CAP_CLASS_MIN) or disabled
    dense packing degenerates to one partition at the storage cap."""
    from .batch import _cap_ladder

    ladder = _cap_ladder(eng.config.cap)
    if len(ladder) == 1 or not eng.dense:
        return [(eng.config.cap, active_idx)]
    lad = np.asarray(ladder, np.int64)
    need = eng.count_ub()[a["lanes"][active_idx]]
    cls_i = np.minimum(np.searchsorted(lad, need), len(ladder) - 1)
    out = []
    for ci in np.unique(cls_i):
        out.append((ladder[int(ci)], active_idx[cls_i == ci]))
    return out


def pack_frame_grids(eng: BatchEngine, a: dict) -> list[tuple]:
    """Stage 2: split the frame into per-cap-class grid trains (lanes
    deeper than a grid's time axis roll into the next grid — FIFO by
    construction), pack each grid's ops as columns, and DISPATCH the
    device-side scatter that rebuilds the padded grid on device. Returns
    [(ops, meta, lane_ids, cap_g), ...] with ops already device-resident.

    Each train's loop carries a SHRINKING active-op index set: each grid
    touches only the ops still alive at its time offset, so a G-grid
    train (a Zipf flow draining hot lanes) costs O(sum of survivors), not
    O(G * frame) — with 27 grids per frame the latter was the consumer's
    dominant host cost."""
    keep, t = a["keep"], a["t"]
    grids: list[tuple] = []
    kept_idx = np.nonzero(keep)[0]
    if not len(kept_idx):
        return grids
    for cap_g, part_idx in _class_partitions(eng, a, kept_idx):
        _pack_class_train(eng, a, part_idx, t[part_idx], cap_g, grids)
    return grids


def _pack_class_train(eng: BatchEngine, a: dict, active_idx, t_sub,
                      cap_g: int, grids: list) -> None:
    """Pack one cap class's grid train (the loop body of the original
    single-train pack_frame_grids, with geometry ratchets keyed by the
    class)."""
    lanes, t = a["lanes"], a["t"]
    t_off = 0
    while len(active_idx):
        live = np.unique(lanes[active_idx])
        first = t_off == 0
        use_dense, n_rows, lane_ids, row_of = eng._grid_geometry(
            live, first=first, cls=cap_g
        )
        if use_dense:
            # Depth ratchet, like the row bucket in _grid_geometry — and
            # like it, only the train's FIRST dense grid consults or
            # advances the floor (a deep floor would stretch every small
            # tail grid to the full depth; see _grid_geometry). Depth is
            # additionally budgeted against the grid's ROW count: the
            # step's record tensors are [T, K, R], so a wide grid must
            # stay shallow (2048 rows x 8192 deep x K=16 is a 10+ GB
            # allocation) while a few-row hot-lane tail can run
            # dense_t_max deep — the same rows-vs-depth trade the device
            # bench's packer applies.
            t_mem = max(
                eng.max_t,
                _next_pow2(
                    _REC_ELEM_BUDGET
                    // max(n_rows * eng.config.max_fills, 1)
                    + 1
                )
                // 2,
            )
            cap_t = max(8, min(max(eng.dense_t_max, eng.max_t), t_mem))
            need = int(t_sub.max()) - t_off + 1
            if first:
                t_floor = eng._dense_t_floor.get(cap_g, 8)
                t_grid = min(max(_next_pow2(need), t_floor), cap_t)
                # Grow-only; a mem-clamped wide grid leaves the floor for
                # future narrower (deeper-capable) first grids.
                eng._dense_t_floor[cap_g] = max(t_floor, t_grid)
            else:
                # Train tails snap to FOUR fixed depth classes (shallow /
                # 8x-shallow / quarter-ceiling / ceiling): every distinct
                # (rows, depth) is a compiled shape, and a hot lane's
                # per-frame depth noise would otherwise keep minting new
                # buckets for the life of the process (~1s of host
                # re-trace each). The 8x-shallow class plugs the geometric
                # hole between max_t and cap_t//4 (padding stays <=8x);
                # NOP-padded steps on an 8-row tail grid are far cheaper
                # than re-traces.
                cands = sorted({
                    min(max(8, eng.max_t), cap_t),
                    min(max(8, 8 * eng.max_t), cap_t),
                    min(max(8, cap_t // 4), cap_t),
                    cap_t,
                })
                t_grid = next(
                    (c for c in cands if c >= min(need, cap_t)), cap_t
                )
        else:
            # Full grid: row == lane (identity map).
            row_of = np.arange(eng.n_slots, dtype=np.int64)
            t_grid = eng.max_t

        from . import nativehost

        in_window = t_sub < t_off + t_grid
        m = int(np.count_nonzero(in_window))
        m_pad = _next_pow4(max(m, 64))
        if nativehost.available():
            # Column pack + the 11 meta extractions in ONE native pass
            # (the numpy form below is ~15 separate mask passes).
            cols, flat, meta = nativehost.pack_grid(
                a, active_idx, row_of, t_off, t_grid, n_rows, m_pad,
                eng.config.dtype, MARKET, ACTION_ADD,
            )
        else:
            sel = active_idx[in_window]
            dt = np.dtype(eng.config.dtype)
            cols = np.empty((7, m_pad), dt)
            flat = np.full(m_pad, n_rows * t_grid, np.int32)
            pr, pt = row_of[lanes[sel]], t[sel] - t_off
            flat[:m] = (pr * t_grid + pt).astype(np.int32)
            is_mkt = (a["kind"][sel] == MARKET) & (
                a["action"][sel] == ACTION_ADD
            )
            for i, (_name, val) in enumerate(
                (
                    ("action", a["action"][sel]),
                    ("side", a["side"][sel]),
                    ("is_market", is_mkt),
                    ("price", np.where(
                        is_mkt, 0, a["price"][sel] - a["bases"][sel]
                    )),
                    ("volume", a["volume"][sel]),
                    ("oid", a["oid_ids"][sel]),
                    ("uid", a["uid_ids"][sel]),
                )
            ):
                cols[i, :m] = val
            meta = {
                "lane": lanes[sel],
                "row": pr,
                "t": pt,
                "arrival": sel.astype(np.int64),
                "action": a["action"][sel],
                "side": a["side"][sel],
                "is_market": is_mkt.astype(np.int64),
                "price": a["price"][sel],
                "price_base": a["bases"][sel],
                "oid_id": a["oid_ids"][sel],
                "uid_id": a["uid_ids"][sel],
            }
        ops = _scatter_grid_fn(
            np.dtype(eng.config.dtype).name, n_rows, t_grid
        )(cols, flat)
        meta["_m_pad"] = m_pad  # host-only: shape-combo recording
        grids.append((ops, meta, lane_ids, cap_g))

        t_off += t_grid
        alive = t_sub >= t_off
        active_idx = active_idx[alive]
        t_sub = t_sub[alive]


def _tables(eng):
    return dict(
        symbols=eng.symbols.to_list(),
        oid_table=eng.oids.table,
        uid_table=eng.uids.table,
    )


def _assemble(eng, a, batches):
    from .events import EventBatch, empty_batch

    # Timeline flow counters (obs.timeline): _assemble runs exactly once
    # per applied frame on BOTH execution paths (apply_frame directly,
    # the fast path via resolve_frame), so it is the one spot where a
    # frame count cannot double on an exact-path fallback. Disabled
    # sampler = one attribute check, zero allocations.
    TIMELINE.note_frame(a["n"])
    eng.stats.orders += a["n"]
    if not batches:
        eng.stats.cancels_missed += a["dels_total"]
        return empty_batch(**_tables(eng))
    out_cols = {
        name: np.concatenate([b[name] for b in batches])
        for name in batches[0]
    }
    order = np.argsort(out_cols["arrival"], kind="stable")
    out_cols = {name: v[order] for name, v in out_cols.items()}
    batch = EventBatch(columns=out_cols, **_tables(eng))
    cancels = int(batch.columns["is_cancel"].sum())
    eng.stats.cancels += cancels
    eng.stats.fills += len(batch) - cancels
    eng.stats.cancels_missed += a["dels_total"] - cancels
    return batch


def apply_frame(eng: BatchEngine, cols: dict):
    """Exact synchronous frame application (one _run_exact per grid);
    returns an EventBatch identical to process_columnar on the same
    orders. Caller guarantees admission was already applied."""
    from .events import decode_grid_columnar

    with TRACER.stage("pad_pack"):
        a = _frame_arrays(eng, cols)
        grids = pack_frame_grids(eng, a)
    batches = []
    for ops, meta, lane_ids, cap_g in grids:
        contexts = {
            (int(r), int(tt)): None for r, tt in zip(meta["row"], meta["t"])
        }
        outs, overrides = eng._run_exact(ops, contexts, lane_ids, cap_g)
        batches.append(
            decode_grid_columnar(meta, splice_outs(outs, overrides))
        )
    # Synchronous path, nothing in flight: re-anchor count_ub exactly so
    # the grow-only ADD increments cannot drift classes upward forever.
    # Only when cap classes are live (a fetch per frame is wasted work —
    # and tunnel latency — for single-class engines).
    from .batch import _cap_ladder

    if len(_cap_ladder(eng.config.cap)) > 1 and eng._ub_extra.any():
        counts = np.asarray(jax.device_get(eng.books.count))
        eng._note_exact_counts(counts.max(axis=1))
    return _assemble(eng, a, batches)


def process_frame(eng: BatchEngine, cols: dict):
    """Transactional wrapper (same rollback contract as process_columnar)."""
    cp = eng._checkpoint()
    try:
        return apply_frame(eng, cols)
    except Exception:
        eng._restore(cp)
        raise


# --- device-side event compaction (the fast path) -----------------------


#: Row order of the packed compaction matrices (fetch layout).
_FILL_FIELDS = (
    "src", "fill_price", "fill_qty", "maker_oid", "maker_uid",
    "maker_volume", "taker_after",
)
_CANCEL_FIELDS = ("src", "volume")


def _decode_compact(eng, meta, shape, fetched) -> dict:
    """Host-side decode of one grid's compacted events into raw event
    columns (decode_grid_columnar's output shape, same ordering rule)."""
    from .events import _COLUMNS

    t_len, k = shape
    totals, fills, cancels = fetched
    nf, nc = int(totals[0]), int(totals[1])

    from . import nativehost

    if nativehost.available():
        return nativehost.decode_compact(
            meta, t_len, k, nf, nc, fills, cancels
        )

    # (row, t) -> packed-op index join table.
    n_rows = int(meta["_n_rows"])
    op_index = np.full((n_rows, t_len), -1, np.int64)
    op_index[meta["row"], meta["t"]] = np.arange(len(meta["row"]))

    src = fills["src"][:nf].astype(np.int64)
    rr = src // (t_len * k)
    tt = (src // k) % t_len
    pos = op_index[rr, tt]  # every fill belongs to a packed ADD
    base = meta["price_base"][pos]
    fill_cols = {
        "arrival": meta["arrival"][pos],
        "is_cancel": np.zeros(nf, np.bool_),
        "symbol_id": meta["lane"][pos],
        "taker_uid": meta["uid_id"][pos],
        "taker_oid": meta["oid_id"][pos],
        "taker_side": meta["side"][pos].astype(np.int8),
        "taker_price": meta["price"][pos],
        "taker_volume": fills["taker_after"][:nf].astype(np.int64),
        "maker_uid": fills["maker_uid"][:nf].astype(np.int64),
        "maker_oid": fills["maker_oid"][:nf].astype(np.int64),
        "fill_price": fills["fill_price"][:nf].astype(np.int64) + base,
        "maker_volume": fills["maker_volume"][:nf].astype(np.int64),
        "match_volume": fills["fill_qty"][:nf].astype(np.int64),
        "is_market": meta["is_market"][pos].astype(np.bool_),
    }

    csrc = cancels["src"][:nc].astype(np.int64)
    cpos = op_index[csrc // t_len, csrc % t_len]
    cvol = cancels["volume"][:nc].astype(np.int64)
    cancel_cols = {
        "arrival": meta["arrival"][cpos],
        "is_cancel": np.ones(nc, np.bool_),
        "symbol_id": meta["lane"][cpos],
        "taker_uid": meta["uid_id"][cpos],
        "taker_oid": meta["oid_id"][cpos],
        "taker_side": meta["side"][cpos].astype(np.int8),
        "taker_price": meta["price"][cpos],
        "taker_volume": cvol,
        "maker_uid": meta["uid_id"][cpos],
        "maker_oid": meta["oid_id"][cpos],
        "fill_price": meta["price"][cpos],
        "maker_volume": cvol,
        "match_volume": np.zeros(nc, np.int64),
        "is_market": np.zeros(nc, np.bool_),
    }
    columns = {
        name: np.concatenate(
            [np.asarray(fill_cols[name], dt), np.asarray(cancel_cols[name], dt)]
        )
        for name, dt in _COLUMNS
    }
    # Global emission order: arrival, then record order within the op. The
    # fill src values are (r, t, k)-ascending, so records within an op are
    # already in order; a stable sort on arrival preserves that (cancels
    # have no records).
    order = np.argsort(columns["arrival"], kind="stable")
    return {name: v[order] for name, v in columns.items()}


@functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(2, 3, 4))
def compact_accum(config, outs, fills_acc, cancels_acc, totals_acc, g):
    """Append one grid's compacted events into the FRAME-level buffers.

    Like compact_step_outputs, but events land at the frame's running
    offsets (the sums of earlier grids' counts in totals_acc) instead of
    per-grid buffers — the whole frame then resolves with ONE fetch of
    three arrays. On a tunneled dev link each fetched array pays ~tens of
    ms of fixed cost, and a Zipf frame's grid TRAIN (dozens of grids)
    made the fetch COUNT, not the bytes, the end-to-end ceiling: 3*G
    arrays -> 3. The accumulators are donated, so the train appends in
    place with no host sync; totals_acc[g] records this grid's TRUE
    fill/cancel counts (+ overflow flag + max n_fills), which is also
    how the host later splits the flat buffers back into grids."""
    e_fills = fills_acc.shape[1]
    e_cancels = cancels_acc.shape[1]
    wide = fills_acc.dtype
    off_f = jnp.sum(totals_acc[:, 0])
    off_c = jnp.sum(totals_acc[:, 1])
    fq = outs.fill_qty  # [R, T, K]
    r, t_len, k = fq.shape
    mask = (fq > 0).reshape(-1)
    idx = jnp.cumsum(mask.astype(jnp.int32)) - 1
    tgt = jnp.where(mask, off_f + idx, e_fills)
    maker_volume = jnp.where(
        outs.maker_remaining == 0, outs.maker_prefill, outs.maker_remaining
    )
    fill_src = dict(
        src=jnp.arange(r * t_len * k, dtype=jnp.int32),
        fill_price=outs.fill_price,
        fill_qty=fq,
        maker_oid=outs.maker_oid,
        maker_uid=outs.maker_uid,
        maker_volume=maker_volume,
        taker_after=outs.taker_after,
    )
    vals = jnp.stack(
        [fill_src[f].reshape(-1).astype(wide) for f in _FILL_FIELDS]
    )
    fills_acc = fills_acc.at[:, tgt].set(vals, mode="drop")

    cmask = (outs.cancel_found != 0).reshape(-1)  # [R*T]
    cidx = jnp.cumsum(cmask.astype(jnp.int32)) - 1
    ctgt = jnp.where(cmask, off_c + cidx, e_cancels)
    cancel_src = dict(
        src=jnp.arange(r * t_len, dtype=jnp.int32),
        volume=outs.cancel_volume,
    )
    cvals = jnp.stack(
        [cancel_src[f].reshape(-1).astype(wide) for f in _CANCEL_FIELDS]
    )
    cancels_acc = cancels_acc.at[:, ctgt].set(cvals, mode="drop")
    totals_acc = totals_acc.at[g].set(
        jnp.stack(
            [
                jnp.sum(mask.astype(jnp.int32)),
                jnp.sum(cmask.astype(jnp.int32)),
                jnp.sum(outs.book_overflow).astype(jnp.int32),
                jnp.max(outs.n_fills).astype(jnp.int32),
            ]
        ).astype(jnp.int32)  # x64 promotes int32 sums to int64
    )
    return fills_acc, cancels_acc, totals_acc


class PendingFrame:
    """A frame whose grids are dispatched (device side in flight) but not
    yet resolved: everything resolve_frame needs, plus the checkpoint that
    makes a tripped budget or failure transactionally recoverable."""

    __slots__ = ("cols", "arrays", "checkpoint", "items", "compact",
                 "n_kept")

    def __init__(self, cols, arrays, checkpoint, items, compact, n_kept):
        self.cols = cols
        self.arrays = arrays  # incl. add_counts for the count_ub handoff
        self.checkpoint = checkpoint
        self.items = items  # [(meta, (t_grid, K))]
        # (totals_acc, fills_acc, cancels_acc, counts_max)|None — counts_max
        # is the post-frame per-lane max-side resting count, riding the
        # frame's single fetch to re-anchor count_ub (cap classes).
        self.compact = compact
        self.n_kept = n_kept


# gomesurface: combo(build)
def submit_frame(eng: BatchEngine, cols: dict) -> PendingFrame:
    """Dispatch every grid of the frame + its device-side compaction
    back-to-back (no host sync) and start the async device->host copy of
    the frame-level event buffers. Advances eng.books — a later
    submit_frame builds on this frame's result, so frames pipeline while
    preserving sequential semantics. Raises (with rollback) only on
    host-side errors; device budget trips surface at resolve_frame."""
    cp = eng._checkpoint()
    try:
        with TRACER.stage("pad_pack"):
            a = _frame_arrays(eng, cols)
            grids = pack_frame_grids(eng, a)
        books = eng.books
        items = []
        compact = None
        n_kept = int(np.count_nonzero(a["keep"]))
        if grids:
            e_fills, e_cancels = _compact_sizes(
                eng, n_kept, a["dels_total"]
            )
            wide = jnp.result_type(jnp.int32, eng.config.dtype)
            fills_acc = jnp.zeros((len(_FILL_FIELDS), e_fills), wide)
            cancels_acc = jnp.zeros((len(_CANCEL_FIELDS), e_cancels), wide)
            totals_acc = jnp.zeros(
                (max(_next_pow2(len(grids)), 8), 4), jnp.int32
            )
        for g_i, (ops, meta, lane_ids, cap_g) in enumerate(grids):
            t_disp = TRACER.clock() if TRACER.enabled else 0.0
            t_disp_j = JOURNAL.clock() if JOURNAL.enabled else 0.0
            with TRACER.annotation("grid_dispatch"):
                books, outs = eng._step(books, ops, lane_ids, cap_g)
                eng.stats.device_calls += 1
                n_rows, t_grid = ops.action.shape
                fills_acc, cancels_acc, totals_acc = compact_accum(
                    eng.config, outs, fills_acc, cancels_acc, totals_acc,
                    np.int32(g_i),
                )
            meta["_n_rows"] = n_rows
            # The record axis K comes from the ARRAY, never from
            # config.max_fills: with cap < max_fills the step's record
            # slice clamps to cap (step.py `rec`), so the decode's flat
            # src arithmetic — and the truncation check in resolve_frame —
            # must use the K the records were actually emitted with
            # (fuzz-found: seed 9087, cap=4 K=8 mis-decoded fills and
            # would have silently dropped records of >K-fill ops).
            k_rec = int(outs.fill_qty.shape[-1])
            items.append((meta, (t_grid, k_rec)))
            # Record the full dispatch combo (grid geometry x frame
            # buffers) for shape_manifest/precompile_combos: this tuple
            # determines every jit trace the dispatch just performed.
            combo = (
                n_rows, t_grid, int(cap_g), lane_ids is not None,
                int(meta["_m_pad"]), k_rec,
                int(fills_acc.shape[1]), int(cancels_acc.shape[1]),
                int(totals_acc.shape[0]),
            )
            if TRACER.enabled:
                # Dispatch cost split by whether this shape combo had
                # been traced+compiled before: a first-seen combo pays
                # the synchronous jit trace + XLA compile right here
                # (dispatch itself is async), which is exactly the
                # invisible-latency-cliff the span taxonomy calls out.
                TRACER.observe_span(
                    "compile_hit" if eng.combo_seen(combo)
                    else "compile_miss",
                    t_disp, TRACER.clock(),
                )
            if JOURNAL.enabled and not eng.combo_seen(combo):
                # Compile journal: the SAME miss path, but recording the
                # combo itself (plus its analytic cost block) — the
                # histogram can only say a compile happened, the journal
                # says which shape and what it costs per dispatch. The
                # detail block runs only here, where a full trace+compile
                # was just paid.
                JOURNAL.record(
                    "frame_dispatch", combo,
                    JOURNAL.clock() - t_disp_j,
                    detail=frame_combo_detail(
                        np.dtype(eng.config.dtype).name, combo
                    ),
                )
            eng.record_combo(combo)
        eng.books = books
        if grids:
            from .batch import _cap_ladder

            compact = (totals_acc, fills_acc, cancels_acc)
            if len(_cap_ladder(eng.config.cap)) > 1:
                # The count_ub re-anchor rides the frame's totals fetch —
                # but only multi-class engines ever read it; single-class
                # ones skip the [S]-wide reduction and transfer.
                compact += (jnp.max(books.count, axis=-1),)
            # Phase-1 fetch starts now: totals (+counts_max) are tiny and
            # resolve needs them FIRST — the event matrices are fetched
            # as used-prefix slices sized from the totals (resolve_frame),
            # so the transfer scales with the frame's EVENTS, not with
            # the pow2-margined buffer capacity (7-8x the events on a
            # margined mixed flow; the delta is wall on a PCIe host but
            # wall AND deserialize CPU on a tunneled link).
            compact[0].copy_to_host_async()
            if len(compact) > 3:
                compact[3].copy_to_host_async()
        return PendingFrame(cols, a, cp, items, compact, n_kept)
    except Exception:
        eng._restore(cp)
        raise


@functools.lru_cache(maxsize=256)
def _prefix_slice_fn(n_fields: int, length: int):
    """Jitted used-prefix slice [F, e] -> [F, length]: phase 2 of the
    two-phase frame fetch transfers only the events that exist, not the
    pow2-margined buffer capacity. length is pow2-bucketed by the caller
    so the compiled-shape set stays logarithmic."""

    @jax.jit
    def take(mat):
        return jax.lax.slice(mat, (0, 0), (n_fields, length))

    return take


def resolve_frame(eng: BatchEngine, pend: PendingFrame):
    """Fetch + decode a submitted frame — TWO-phase device->host fetch:

      1. the [G, 4] totals (+ the [S] count_ub re-anchor), tiny and
         already in flight since submit;
      2. the USED PREFIX of the fill/cancel event matrices, pow2-bucketed
         from the totals — a margined mixed-flow buffer is 7-8x its
         actual events, and on a tunneled dev link that delta is seconds
         of wall AND deserialize CPU per frame (PCIe: microseconds).

    Raises _NeedExact when a device budget tripped — the CALLER owns the
    recovery (rewind to pend.checkpoint, exact-run, resubmit anything
    submitted after); the single-frame wrapper apply_frame_fast and the
    pipelined executor (engine.pipeline.FramePipeline) both do."""
    if pend.compact is None:
        return _assemble(eng, pend.arrays, [])
    global FETCH_SECONDS
    t0 = time.perf_counter()
    ts0 = TRACER.clock() if TRACER.enabled else 0.0
    with TRACER.annotation("frame_fetch_totals"):
        totals_dev, fills_dev, cancels_dev = pend.compact[:3]
        totals = jax.device_get(totals_dev)
        counts_max = (
            jax.device_get(pend.compact[3]) if len(pend.compact) > 3
            else None
        )
    FETCH_SECONDS += time.perf_counter() - t0
    if TRACER.enabled:
        # The totals fetch is the frame's completion barrier: blocking
        # here drains every dispatched grid, so this IS the
        # device-execute wait. (Span clock = the tracer's, which tests
        # may script; FETCH_SECONDS stays on perf_counter.)
        TRACER.observe_span("device_execute", ts0, TRACER.clock())
    g = len(pend.items)
    nf_g = totals[:g, 0].astype(np.int64)
    nc_g = totals[:g, 1].astype(np.int64)
    total_f = int(nf_g.sum())
    total_c = int(nc_g.sum())
    # A fills-buffer overflow ratchets the grow-only floor (keyed by the
    # FRAME's kept-op class) BEFORE the exact fallback, so the next frame
    # fits — one slow frame per ratchet step, not a recurring tax. The
    # totals are TRUE counts (appends past the buffer drop but the mask
    # sums fully), so one step reaches the right size.
    tripped = False
    if total_f > fills_dev.shape[1]:
        cls = eng._buf_class(pend.n_kept)
        eng._fills_buf_floor[cls] = max(
            eng._fills_buf_floor.get(cls, 0), _next_pow2(total_f)
        )
        tripped = True
    if (
        tripped
        or int(totals[:g, 2].sum()) > 0  # book overflow: state is wrong
        # Records truncated: an op produced more fills than the K its
        # grid's record arrays were emitted with.
        or any(
            int(totals[i, 3]) > shape[1]
            for i, (_, shape) in enumerate(pend.items)
        )
        # Unreachable by construction (cancels <= the frame's DEL count,
        # which sizes the buffer) — defensive only.
        or total_c > cancels_dev.shape[1]
    ):
        raise _NeedExact()
    # Phase 2: fetch the used prefixes (pow2-bucketed, clamped to the
    # buffer) now the true counts are known.
    t0 = time.perf_counter()
    ts0 = TRACER.clock() if TRACER.enabled else 0.0
    f_len = min(_next_pow2(max(total_f, 64)), int(fills_dev.shape[1]))
    c_len = min(_next_pow2(max(total_c, 64)), int(cancels_dev.shape[1]))
    fills_mat = jax.device_get(
        _prefix_slice_fn(int(fills_dev.shape[0]), f_len)(fills_dev)
    )
    cancels_mat = jax.device_get(
        _prefix_slice_fn(int(cancels_dev.shape[0]), c_len)(cancels_dev)
    )
    FETCH_SECONDS += time.perf_counter() - t0
    if TRACER.enabled:
        TRACER.observe_span("device_execute", ts0, TRACER.clock())
    # Re-anchor count_ub from this frame's true post-frame counts (the
    # pipeline resolves FIFO, so extra minus THIS frame's increments is
    # exactly the still-in-flight sum; a trip above skips this and the
    # rollback restores the checkpointed estimate instead). None for
    # single-class engines, which never read count_ub.
    if counts_max is not None:
        eng._note_exact_counts(counts_max, pend.arrays["add_counts"])
    off_f = np.concatenate(([0], np.cumsum(nf_g)))
    off_c = np.concatenate(([0], np.cumsum(nc_g)))
    batches = []
    with TRACER.stage("decode"):
        for i, (meta, shape) in enumerate(pend.items):
            fills = {
                f: fills_mat[j, off_f[i] : off_f[i + 1]]
                for j, f in enumerate(_FILL_FIELDS)
            }
            cancels = {
                f: cancels_mat[j, off_c[i] : off_c[i + 1]]
                for j, f in enumerate(_CANCEL_FIELDS)
            }
            batches.append(
                _decode_compact(
                    eng, meta, shape, (totals[i], fills, cancels)
                )
            )
        return _assemble(eng, pend.arrays, batches)


def apply_frame_fast(eng: BatchEngine, cols: dict):
    """Production hot path, single-frame form: submit + resolve with one
    overlapped fetch; falls back — transactionally — to the exact path
    when any device budget tripped. Semantics identical to apply_frame.
    Runs under a mesh too: the compaction is elementwise + one cumsum
    over the sharded record axis, and the fetch gathers per-chip blocks."""
    try:
        pend = submit_frame(eng, cols)
    except Exception:
        raise
    try:
        return resolve_frame(eng, pend)
    except _NeedExact:
        eng.stats.frame_fallbacks += 1
        eng._restore(pend.checkpoint)
        try:
            return apply_frame(eng, cols)
        except Exception:
            eng._restore(pend.checkpoint)
            raise
    except Exception:
        eng._restore(pend.checkpoint)
        raise


# gomesurface: quantizer
def _compact_sizes(eng, n_ops: int, n_dels: int) -> tuple[int, int]:
    """Compaction buffer sizes for a grid of n_ops packed ops (n_dels of
    them DELs). Sizes MUST be pow2-bucketed: every distinct size is a
    fresh kernel compile. But the buffers are also the frame's device->
    host transfer, and on a tunneled dev TPU that link is the end-to-end
    ceiling — so they start TIGHT and ratchet up instead of paying 2x+
    headroom forever:

      fills   — next_pow2(n_ops) (<=1 fill/op average) or the engine's
                grow-only floor, whichever is larger;
      cancels — next_pow2 of the grid's actual DEL count (the exact upper
                bound for its cancel events; a pure-ADD stream fetches a
                64-slot stub instead of an n_ops-sized buffer of zeros).

    Called once per FRAME (n_ops = the frame's kept ops; the whole
    frame's grids append into one buffer pair via compact_accum). Sizes
    are grow-only ratchets KEYED BY the pow2 op-count class
    (BatchEngine._fills_buf_floor): within a class, a frame that needs a
    larger buffer raises the floor so later frames reuse one compiled
    shape instead of oscillating (data-dependent sizes would recompile
    whenever a DEL count straddled a pow2 boundary); across classes,
    floors stay independent so small frames never fetch a big frame's
    buffer. A frame whose FILL count overflows its buffer transactionally
    re-runs on the exact path (resolve_frame) AND raises its class's
    floor, so that costs one slow frame per ratchet step, not a recurring
    tax; cancel events can never overflow (cancels <= n_dels by
    construction, step.py cancel_found). Deployments that know their flow
    pre-warm the floors (BatchEngine.prewarm_geometry)."""
    cls = eng._buf_class(n_ops)
    fills = max(cls, eng._fills_buf_floor.get(cls, 0))
    cancels = max(
        _next_pow2(max(n_dels, 64)), eng._cancels_buf_floor.get(cls, 0)
    )
    eng._fills_buf_floor[cls] = fills
    eng._cancels_buf_floor[cls] = cancels
    return fills, cancels


# gomesurface: combo(replay), precompile
def precompile_combos(eng: BatchEngine, combos) -> int:
    """Replay recorded fast-path shape combos (BatchEngine.shape_manifest
    "combos") with ALL-PADDING inputs, forcing every jit trace+compile the
    live flow will need — scatter, step (dense or full, at the combo's cap
    class), and frame-level compaction — before real traffic arrives.

    All-padding means: scatter positions at the drop sentinel (R*T), so
    the DeviceOp grid is all NOPs; dense lane_ids at the n_slots sentinel
    (gathered as zero books, scattered nowhere). Book state is read but
    results are DISCARDED — replay never mutates the engine (the step jits
    don't donate their inputs; compact_accum donates only the dummy
    buffers built here). Floors should be prewarmed first
    (prewarm_geometry) so the live flow also CHOOSES these shapes.

    Returns the number of combos replayed. Cost: one compile each on a
    cold XLA cache (tens of seconds on a tunneled dev TPU), milliseconds
    each warm — vs ~0.3-1s of un-hideable host TRACE time per shape if it
    first appears mid-traffic (the XLA persistent cache covers compiles
    only; traces are per-process)."""
    wide = jnp.result_type(jnp.int32, eng.config.dtype)
    dt = np.dtype(eng.config.dtype)
    combos = sorted(set(map(tuple, combos)))
    replayed = 0
    failed = 0
    for combo in combos:
        # Per-combo isolation: one stale manifest combo (wrong tuple arity
        # from an older layout, a full-grid n_rows that no longer equals
        # n_slots after growth) must not abort every remaining replayable
        # combo — the documented best-effort contract holds at combo
        # granularity, not manifest granularity.
        try:
            (
                n_rows, t_grid, cap_g, dense, m_pad, k_rec,
                e_fills, e_cancels, totals_len,
            ) = combo
            if cap_g > eng.config.cap:
                # Recorded after a storage-cap escalation this engine
                # hasn't done (caller can eng.ensure_cap() first —
                # load_geometry does). Unreplayable as-is; skip rather
                # than crash.
                continue
            cols = np.zeros((7, m_pad), dt)
            flat = np.full(m_pad, n_rows * t_grid, np.int32)
            ops = _scatter_grid_fn(dt.name, n_rows, t_grid)(cols, flat)
            lane_ids = (
                np.full(n_rows, eng.n_slots, np.int64) if dense else None
            )
            _books, outs = eng._step(eng.books, ops, lane_ids, cap_g)
            fills_acc = jnp.zeros((len(_FILL_FIELDS), e_fills), wide)
            cancels_acc = jnp.zeros((len(_CANCEL_FIELDS), e_cancels), wide)
            totals_acc = jnp.zeros((totals_len, 4), jnp.int32)
            out = compact_accum(
                eng.config, outs, fills_acc, cancels_acc, totals_acc,
                np.int32(0),
            )
            # Serialize: each replay holds a transient books-sized output;
            # blocking frees it before the next combo allocates.
            jax.block_until_ready(out)
        except Exception:
            failed += 1
            continue
        eng.record_combo(combo)
        replayed += 1
    if failed:
        from ..utils.logging import get_logger

        get_logger("frames").warning(
            "precompile_combos: %d stale combo(s) skipped, %d replayed",
            failed, replayed,
        )
    from .batch import _cap_ladder

    if len(_cap_ladder(eng.config.cap)) > 1:
        # The count_ub re-anchor reduction that rides every frame fetch.
        jax.block_until_ready(jnp.max(eng.books.count, axis=-1))
    # Phase-2 prefix-slice kernels (resolve_frame): warm the plausible
    # pow2 lengths for every recorded buffer size so a boundary-crossing
    # event count never compiles mid-traffic. Tiny graphs, but a compile
    # is a compile.
    wide_zeros = {}
    for combo in combos:
        try:  # same per-combo isolation as the replay loop above
            for n_fields, e in (
                (len(_FILL_FIELDS), combo[6]),
                (len(_CANCEL_FIELDS), combo[7]),
            ):
                key = (n_fields, e)
                if key not in wide_zeros:
                    wide_zeros[key] = jnp.zeros((n_fields, e), wide)
                length = e
                while length >= 64:
                    jax.block_until_ready(
                        _prefix_slice_fn(n_fields, length)(wide_zeros[key])
                    )
                    length //= 2
        except Exception:
            continue
    return replayed


class _NeedExact(Exception):
    """Internal: a device budget tripped on the fast path — roll back and
    re-run the frame on the exact escalating path."""


def orders_from_frame(cols: dict):
    """Decode an ORDER frame into Order objects — the compatibility path
    for engines without a native frame pipeline (e.g. the in-process
    ShardedEngine facade; sharded deployments route frames per shard
    upstream instead, so this loop is never on a hot path)."""
    from ..types import Action, Order, OrderType, Side

    syms, uuids = cols["symbols"], cols["uuids"]
    sidx, uidx = cols["symbol_idx"].tolist(), cols["uuid_idx"].tolist()
    traces = cols.get("trace")  # GCO3 frames carry per-order contexts
    traces = traces.tolist() if traces is not None else None
    out = []
    for i, (a, s, k, p, v, o) in enumerate(
        zip(
            cols["action"].tolist(), cols["side"].tolist(),
            cols["kind"].tolist(), cols["price"].tolist(),
            cols["volume"].tolist(), cols["oids"].tolist(),
        )
    ):
        trace = None
        if traces is not None and traces[i]:
            trace = traces[i].decode()
        out.append(
            Order(
                uuid=uuids[uidx[i]], oid=o.decode(), symbol=syms[sidx[i]],
                side=Side(int(s)), price=int(p), volume=int(v),
                action=Action(int(a)), order_type=OrderType(int(k)),
                trace=trace,
            )
        )
    return out


def _prepare_bases_vec(eng, lanes, action, kind, price) -> np.ndarray:
    """Vectorized _prepare_bases: same semantics as the object path
    (ADD-limit-only grow-only envelope; commit after checks; unrepresentable
    DELs dropped as misses), with numpy segment min/max and a Python loop
    only over the UNIQUE lanes admitting prices this batch."""
    n = len(lanes)
    drop = np.zeros(n, bool)
    if not eng._rebase:
        return drop
    adm = (action == ACTION_ADD) & (kind != MARKET)
    if adm.any():
        al = lanes[adm]
        ap = price[adm]
        # Steady-state fast path: prices already inside their lane's
        # admitted envelope AND within REBASE_LIMIT of its base need no
        # work at all — only the violating lanes run the (ufunc.at +
        # Python) admission below. The base-distance check matters: after
        # asymmetric growth a price can sit inside [env_lo, env_hi] yet
        # far enough from the base that _admit_lane_range would RECENTER
        # (batch.py REBASE_LIMIT); skipping that would leave price_base
        # stale and drop later DELs near the far envelope edge.
        inside = (
            eng._base_set[al]
            & (ap >= eng._env_lo[al])
            & (ap <= eng._env_hi[al])
            & (np.abs(ap - eng.price_base[al]) <= eng.REBASE_LIMIT)
        )
        if not inside.all():
            viol = ~inside
            al, ap = al[viol], ap[viol]
            uniq = np.unique(al)
            lo = np.full(eng.n_slots, np.iinfo(np.int64).max)
            hi = np.full(eng.n_slots, np.iinfo(np.int64).min)
            np.minimum.at(lo, al, ap)
            np.maximum.at(hi, al, ap)
            # Vectorized widen for lanes that only need their envelope
            # stretched (base already set, no recenter): the Python
            # _admit_lane_range loop is ~3 us/lane and steady flows admit
            # thousands of new per-lane extremes per frame while their
            # envelopes converge. Seeding and recentering stay on the
            # exact scalar path (rare).
            b = eng.price_base[uniq]
            easy = eng._base_set[uniq] & (
                np.maximum(np.abs(lo[uniq] - b), np.abs(hi[uniq] - b))
                <= eng.REBASE_LIMIT
            )
            ez = uniq[easy]
            eng._env_lo[ez] = np.minimum(eng._env_lo[ez], lo[ez])
            eng._env_hi[ez] = np.maximum(eng._env_hi[ez], hi[ez])
            for lane in uniq[~easy].tolist():
                eng._admit_lane_range(int(lane), int(lo[lane]), int(hi[lane]))
    dels = action == ACTION_DEL
    if dels.any():
        dl = lanes[dels]
        drop[dels] = (
            np.abs(price[dels] - eng.price_base[dl]) > eng._INT32_SAFE
        )
    return drop

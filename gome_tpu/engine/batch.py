"""Batched execution: scan over time within a symbol, vmap across symbols.

This is the execution model that replaces the reference's one-order-at-a-time
consumer loop (rabbitmq.go:116-125): the host packs a micro-batch of orders
into a dense [S, T] op grid — S symbol lanes, T time slots, NOP-padded — and
the device applies all of it in one compiled call:

    books'[s], outs[s, :] = scan(step, books[s], ops[s, :])   for all s (vmap)

Two invariants make this exactly equivalent to sequential processing:
  * same-symbol operations never split across concurrent lanes and keep
    arrival order within the lane (SURVEY §5.2: the serialized-per-symbol
    invariant, the reference's correctness-by-single-threadedness);
  * symbols share nothing (SURVEY §2.1), so cross-symbol interleaving is
    irrelevant to book state — the host re-sorts decoded events by original
    arrival index to reproduce the reference's global emission order.

The [S] symbol axis is also the sharding axis: lanes are independent, so
pjit partitions the whole grid across chips with zero collectives
(gome_tpu.parallel).
"""

from __future__ import annotations

import functools

import jax
import numpy as np

from ..types import Action, MatchResult, Order
from .book import BookConfig, BookState, DeviceOp, StepOutput, init_books
from .host import Interner, OpContext, decode_events, encode_op
from .step import step_impl


@functools.partial(jax.jit, static_argnums=0)
def batch_step(
    config: BookConfig, books: BookState, ops: DeviceOp
) -> tuple[BookState, StepOutput]:
    """books: [S, ...] stacked BookState; ops: DeviceOp with [S, T] leaves.
    Returns updated books and [S, T]-shaped StepOutputs."""

    def per_symbol(book, ops_lane):
        return jax.lax.scan(
            lambda b, op: step_impl(config, b, op), book, ops_lane
        )

    return jax.vmap(per_symbol)(books, ops)


def _nop_grid(config: BookConfig, n_slots: int, t: int) -> dict[str, np.ndarray]:
    i32 = lambda: np.zeros((n_slots, t), np.int32)
    val = lambda: np.zeros((n_slots, t), np.dtype(config.dtype))
    return dict(
        action=i32(), side=i32(), is_market=i32(),
        price=val(), volume=val(), oid=val(), uid=val(),
    )


class BatchOverflowError(Exception):
    """One or more ops in a micro-batch overflowed fixed device budgets
    (fill records or book capacity). The batch's book mutations are already
    committed on device; everything recoverable is attached:

      events   — the full decoded event stream for every non-overflowing op
      failures — [(order, reason), ...] for the overflowing ops
    """

    def __init__(self, events, failures):
        self.events = events
        self.failures = failures
        super().__init__(
            f"{len(failures)} op(s) overflowed device budgets: "
            + "; ".join(f"{o.oid}: {r}" for o, r in failures[:3])
        )


class BatchEngine:
    """Host-side driver for the batched device engine.

    Owns the device-resident [S] book stack, the symbol->lane mapping, and
    the id interners; packs order lists into op grids and decodes StepOutputs
    back into the global MatchResult event stream.

    This layer assumes orders already passed admission (pre-pool checks live
    in the orchestrator above — gome_tpu.bridge); every ADD given here hits
    the book.
    """

    def __init__(self, config: BookConfig, n_slots: int, max_t: int = 32):
        self.config = config
        self.n_slots = n_slots
        self.max_t = max_t
        self.books = init_books(config, n_slots)
        self.symbols = Interner()  # symbol -> lane id + 1 offset handled below
        self.oids = Interner()
        self.uids = Interner()

    def _lane(self, symbol: str) -> int:
        lane = self.symbols.intern(symbol) - 1  # Interner ids start at 1
        if lane >= self.n_slots:
            raise ValueError(
                f"symbol {symbol!r} needs lane {lane} but engine has "
                f"n_slots={self.n_slots}"
            )
        return lane

    def process(self, orders: list[Order]) -> list[MatchResult]:
        """Apply a micro-batch. Symbols with more than max_t ops are drained
        over several device calls (order preserved); returns all events in
        original arrival order.

        Raises BatchOverflowError (with all other ops' events attached) if
        any op exceeded the fill-record or book-capacity budget — the device
        book state is exact either way; only that op's event records (or its
        resting remainder) need the host slow path."""
        pending = [(i, o) for i, o in enumerate(orders)]
        decoded: list[tuple[int, list[MatchResult]]] = []
        failures: list[tuple[Order, str]] = []
        while pending:
            pending = self._one_grid(pending, decoded, failures)
        decoded.sort(key=lambda kv: kv[0])
        events = [ev for _, evs in decoded for ev in evs]
        if failures:
            raise BatchOverflowError(events, failures)
        return events

    def _one_grid(self, pending, decoded, failures):
        grid = _nop_grid(self.config, self.n_slots, self.max_t)
        contexts: dict[tuple[int, int], tuple[int, Order]] = {}
        fill_level: dict[int, int] = {}
        leftover: list[tuple[int, Order]] = []
        blocked: set[int] = set()  # lanes whose FIFO order must not be broken

        for arrival, order in pending:
            lane = self._lane(order.symbol)
            t = fill_level.get(lane, 0)
            if lane in blocked or t >= self.max_t:
                blocked.add(lane)
                leftover.append((arrival, order))
                continue
            op = encode_op(order, self.oids, self.uids, self.config.dtype)
            for name, arr in grid.items():
                arr[lane, t] = getattr(op, name)
            contexts[(lane, t)] = (arrival, order)
            fill_level[lane] = t + 1

        ops = DeviceOp(**{k: v for k, v in grid.items()})
        self.books, outs = batch_step(self.config, self.books, ops)
        outs = jax.device_get(outs)
        for (lane, t), (arrival, order) in contexts.items():
            out = jax.tree.map(lambda a: a[lane, t], outs)
            try:
                decoded.append(
                    (
                        arrival,
                        decode_events(
                            OpContext(order), out, self.config, self.oids, self.uids
                        ),
                    )
                )
            except OverflowError as exc:
                # Don't lose unrelated ops' events over one overflow; the
                # caller gets everything recoverable via BatchOverflowError.
                failures.append((order, str(exc)))
        return leftover

    # -- views -------------------------------------------------------------
    def lane_books(self) -> BookState:
        return jax.device_get(self.books)

    def symbol_lane(self, symbol: str) -> int:
        return self._lane(symbol)

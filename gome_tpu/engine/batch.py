"""Batched execution: scan over time within a symbol, vmap across symbols.

This is the execution model that replaces the reference's one-order-at-a-time
consumer loop (rabbitmq.go:116-125): the host packs a micro-batch of orders
into a dense [S, T] op grid — S symbol lanes, T time slots, NOP-padded — and
the device applies all of it in one compiled call:

    books'[s], outs[s, :] = scan(step, books[s], ops[s, :])   for all s (vmap)

Two invariants make this exactly equivalent to sequential processing:
  * same-symbol operations never split across concurrent lanes and keep
    arrival order within the lane (SURVEY §5.2: the serialized-per-symbol
    invariant, the reference's correctness-by-single-threadedness);
  * symbols share nothing (SURVEY §2.1), so cross-symbol interleaving is
    irrelevant to book state — the host re-sorts decoded events by original
    arrival index to reproduce the reference's global emission order.

Fixed device budgets (book capacity, K fill records) never cost exactness:
the engine keeps the pre-batch book snapshot and, when a budget trips,
escalates — grows the book slot axis and re-runs the whole grid, or re-runs
one lane with a larger record budget — before decoding (SURVEY §7 hard
parts (a)/(c): overflow is recovered, never silently dropped; the reference
has no budgets because Redis is unbounded).

The [S] symbol axis is also the sharding axis: lanes are independent, so
pjit partitions the whole grid across chips with zero collectives
(gome_tpu.parallel).
"""

from __future__ import annotations

import dataclasses
import functools
import warnings

import jax
import jax.numpy as jnp
import numpy as np

from ..obs.placement import PLACEMENT
from ..obs.profiler import PROFILER
from ..types import KERNELS, Action, MatchResult, Order
from ..utils.metrics import REGISTRY
from ..utils.trace import TRACER
from .book import (
    BUY,
    BookConfig,
    BookState,
    DeviceOp,
    StepOutput,
    grow_books,
    grow_lanes,
    init_books,
)
from .host import Interner, OpContext, decode_events, encode_op
from .step import ACTION_ADD, _Side, step_rows_impl

# The donating twins donate the whole ops pytree; XLA reuses most of its
# buffers for the [S, T] outputs but (depending on layout/CSE) not all,
# and warns "Some donated buffers were not usable" once per compiled
# shape. That partial reuse is the intended trade (jax FAQ: filter the
# warning when donation is deliberate); the unusable buffers are simply
# freed.
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable"
)

#: Dense-dispatch skew telemetry (ROADMAP open item 2): each dense grid
#: observes dispatched-rows / live-lanes — the row-padding tax the pow2
#: bucketing (and, under a mesh, the per-shard MAX bucketing that
#: `scripts/mesh_overhead.py --skew` measures at 3.7x for D=8 Zipf) makes
#: the device pay. The p50 gauge is the placement target the ROADMAP sets
#: (<= 2.0); the histogram carries the tail. Ratio buckets, not seconds.
_ROWS_PER_LANE_BUCKETS = (
    1.0, 1.25, 1.5, 2.0, 2.5, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 24.0, 32.0,
)
_rows_per_live_lane = REGISTRY.histogram(
    "gome_dispatched_rows_per_live_lane",
    "dense-grid dispatched rows per live lane (row-padding/skew tax)",
    buckets=_ROWS_PER_LANE_BUCKETS,
)
REGISTRY.callback_gauge(
    "gome_dispatched_rows_per_live_lane_p50",
    "median dispatched-rows/live-lane across dense dispatches "
    "(ROADMAP open item 2 targets <= 2.0)",
    lambda: _rows_per_live_lane.quantile(0.5),
)
#: Per-shard skew companion (measured axis of the same open item): each
#: dense MESH dispatch observes max-shard-live / mean-shard-live — 1.0 is
#: perfectly balanced; the per-shard MAX bucketing makes dispatched rows
#: (and so device time) scale with this ratio, not with total live work.
_dense_shard_skew = REGISTRY.histogram(
    "gome_dense_shard_skew",
    "dense mesh dispatch max/mean live lanes per shard (1.0 = balanced)",
    buckets=_ROWS_PER_LANE_BUCKETS,
)
REGISTRY.callback_gauge(
    "gome_dense_shard_skew_p50",
    "median per-shard live-lane skew across dense mesh dispatches",
    lambda: _dense_shard_skew.quantile(0.5),
)


def _book_to_rows(book: BookState):
    """BookState -> per-side rows carry (static slices, done ONCE per grid).
    The scan carries rows so no step pays the [2, cap] side-axis restack
    (5 jnp.stack materializations per step in the naive form)."""
    buy = _Side(*(getattr(book, n)[..., 0, :] for n in _Side._fields))
    sale = _Side(*(getattr(book, n)[..., 1, :] for n in _Side._fields))
    return (buy, sale, book.count[..., 0], book.count[..., 1], book.next_seq)


def _rows_to_book(rows) -> BookState:
    buy, sale, nb, ns, nseq = rows
    pair = lambda b, a: jnp.stack([b, a], axis=-2)
    return BookState(
        price=pair(buy.price, sale.price),
        lots=pair(buy.lots, sale.lots),
        seq=pair(buy.seq, sale.seq),
        oid=pair(buy.oid, sale.oid),
        uid=pair(buy.uid, sale.uid),
        count=jnp.stack([nb, ns], axis=-1),
        next_seq=nseq,
    )


def _lane_scan_impl(config: BookConfig, book: BookState, ops_lane: DeviceOp):
    """One symbol's op sequence on one (unstacked) book — the single shared
    scan body for both the full grid (under vmap) and escalation re-runs."""

    def body(rows, op):
        buy, sale, nb, ns, nseq = rows
        buy, sale, nb, ns, nseq, out = step_rows_impl(
            config, buy, sale, nb, ns, nseq, op
        )
        return (buy, sale, nb, ns, nseq), out

    rows, outs = jax.lax.scan(body, _book_to_rows(book), ops_lane)
    return _rows_to_book(rows), outs


def _batch_step_impl(
    config: BookConfig, books: BookState, ops: DeviceOp
) -> tuple[BookState, StepOutput]:
    """books: [S, ...] stacked BookState; ops: DeviceOp with [S, T] leaves.
    Returns updated books and [S, T]-shaped StepOutputs.

    The stack's slot axis may be WIDER than config.cap (a per-grid cap
    class, VERDICT r4 #2): the step then runs on the [.., :cap] slice —
    per-step cost tracks the grid's own depth class, not the storage cap
    one hot lane escalated — and writes the slice back. Exactness is
    guarded by _guard_capped."""
    cap = config.cap
    sub = _slice_books_cap(books, cap)
    pre_counts = books.count
    sub, outs = jax.vmap(lambda b, o: _lane_scan_impl(config, b, o))(sub, ops)
    outs = _guard_capped(outs, pre_counts, cap, ops)
    if books.price.shape[-1] == cap:
        return sub, outs
    return _writeback_full_cap(books, sub, cap), outs


# Two jit wrappers per entry, one trace cache each (both precompiled the
# same way — shape combos are recorded per wrapper identity):
#
#   * the PUBLIC entry donates nothing: parity tests/benches replay the
#     same books/ops through several kernels, and the books argument is
#     retained by _run_exact for escalation replay and by _checkpoint for
#     the transactional rollback (the "double-buffer" the GL6xx audit
#     flags IS the transaction mechanism — see ARCHITECTURE.md);
#   * the `_donating` twin donates the ops-grid transfer buffers. _step
#     dispatches to it exactly when the grid is HOST-sourced (numpy —
#     the object-path packers): every dispatch then re-transfers, so the
#     device copy is provably dead and XLA reuses it for the [S, T]
#     outputs instead of allocating fresh ones. Device-built scatter
#     grids (frames.pack_frame_grids) stay undonated: the escalation
#     path re-dispatches the same arrays.
batch_step = functools.partial(  # gomelint: disable=GL601 — see note above
    jax.jit, static_argnums=0
)(_batch_step_impl)
batch_step_donating = functools.partial(  # gomelint: disable=GL601 — see above
    jax.jit, static_argnums=0, donate_argnums=(2,)
)(_batch_step_impl)


lane_scan = functools.partial(  # gomelint: disable=GL601 — parity entry
    jax.jit, static_argnums=0
)(_lane_scan_impl)
#: Escalation re-runs (_run_exact phase 2) build a fresh one-lane book
#: slice and op row per call — both dead on return, so both donate.
lane_scan_donating = functools.partial(
    jax.jit, static_argnums=0, donate_argnums=(1, 2)
)(_lane_scan_impl)


def _dense_batch_step_impl(
    config: BookConfig, books: BookState, lane_ids, ops: DeviceOp
):
    """Gather→scan→scatter over a compact set of LIVE lanes.

    Skewed real-world flow (BASELINE config 4: Zipf arrivals over 10K
    symbols) leaves most of a full [S, T] grid as NOP padding — the device
    would spend >99% of its work stepping idle books. This step instead
    gathers the R live lanes' books into a dense [R, ...] sub-stack, scans
    a compact [R, T] op grid (T can be much deeper than the full-grid
    max_t, amortizing dispatch for hot symbols — the config 1-2 latency
    path), and scatters the sub-stack back. Cost: one O(S) copy for the
    scatter (XLA preserves the un-donated input) plus O(R·T) matching work,
    vs O(S·T) matching work for the full grid.

    lane_ids: [R] int32, padded to the compile-bucketed row count with an
    out-of-range sentinel (>= S). Sentinel rows gather zero books
    (mode="fill"), scan pure-NOP op rows (the packer guarantees this), and
    are dropped by the scatter (mode="drop") — no aliasing, no branches.

    Like batch_step, the gather restricts the slot axis to config.cap —
    the grid's cap class — so tail-lane grids never pay a hot lane's
    escalated storage depth (_guard_capped covers mis-classed lanes).
    """
    cap = config.cap
    base = _slice_books_cap(books, cap)
    sub = jax.tree.map(
        lambda a: jnp.take(a, lane_ids, axis=0, mode="fill", fill_value=0),
        base,
    )
    pre_counts = sub.count
    sub, outs = jax.vmap(lambda b, o: _lane_scan_impl(config, b, o))(sub, ops)
    outs = _guard_capped(outs, pre_counts, cap, ops)
    new_books = _scatter_books_cap(books, lane_ids, sub, cap)
    return new_books, outs


dense_batch_step = functools.partial(  # gomelint: disable=GL601 — see batch_step
    jax.jit, static_argnums=0
)(_dense_batch_step_impl)
dense_batch_step_donating = functools.partial(  # gomelint: disable=GL601 — ibid.
    jax.jit, static_argnums=0, donate_argnums=(3,)
)(_dense_batch_step_impl)


def _dense_kernel_step_impl(
    config: BookConfig,
    books: BookState,
    lane_ids,
    ops: DeviceOp,
    block_s: int,
    interpret: bool = False,
):
    """dense_batch_step with the VMEM-resident Pallas kernel as the inner
    step (gome_tpu.ops.pallas_match) instead of scan x vmap. For few-lane
    deep grids this is the difference between ~40us/op (every scan step
    pays XLA kernel-launch overhead on a sequential dependency chain) and
    the in-kernel fori_loop running entirely out of VMEM — the single-hot-
    symbol latency path lives here. Row count must satisfy the kernel's
    blocking rule (the packer pads rows to >= 8, a power of two).

    Cap-class slicing as in dense_batch_step; a shallower class also
    shrinks the kernel's VMEM book tile, letting wider lane blocks fit."""
    from ..ops import pallas_batch_step

    cap = config.cap
    base = _slice_books_cap(books, cap)
    sub = jax.tree.map(
        lambda a: jnp.take(a, lane_ids, axis=0, mode="fill", fill_value=0),
        base,
    )
    pre_counts = sub.count
    sub, outs = pallas_batch_step(
        config, sub, ops, block_s=block_s, interpret=interpret
    )
    outs = _guard_capped(outs, pre_counts, cap, ops)
    new_books = _scatter_books_cap(books, lane_ids, sub, cap)
    return new_books, outs


dense_kernel_step = functools.partial(  # gomelint: disable=GL601 — see batch_step
    jax.jit, static_argnums=(0, 4, 5)
)(_dense_kernel_step_impl)
dense_kernel_step_donating = functools.partial(  # gomelint: disable=GL601 — ibid.
    jax.jit, static_argnums=(0, 4, 5), donate_argnums=(3,)
)(_dense_kernel_step_impl)


def _full_kernel_step_impl(
    config: BookConfig,
    books: BookState,
    ops: DeviceOp,
    block_s: int,
    interpret: bool = False,
):
    """Full-grid (row == lane) Pallas step with the cap-class slice/guard/
    write-back of batch_step — pallas_batch_step itself requires the book
    arrays at exactly config.cap."""
    from ..ops import pallas_batch_step

    cap = config.cap
    sub = _slice_books_cap(books, cap)
    pre_counts = books.count
    sub, outs = pallas_batch_step(
        config, sub, ops, block_s=block_s, interpret=interpret
    )
    outs = _guard_capped(outs, pre_counts, cap, ops)
    if books.price.shape[-1] == cap:
        return sub, outs
    return _writeback_full_cap(books, sub, cap), outs


full_kernel_step = functools.partial(  # gomelint: disable=GL601 — see batch_step
    jax.jit, static_argnums=(0, 3, 4)
)(_full_kernel_step_impl)
full_kernel_step_donating = functools.partial(  # gomelint: disable=GL601 — ibid.
    jax.jit, static_argnums=(0, 3, 4), donate_argnums=(2,)
)(_full_kernel_step_impl)


def _nop_grid(config: BookConfig, n_slots: int, t: int) -> dict[str, np.ndarray]:
    i32 = lambda: np.zeros((n_slots, t), np.int32)
    val = lambda: np.zeros((n_slots, t), np.dtype(config.dtype))
    return dict(
        action=i32(), side=i32(), is_market=i32(),
        price=val(), volume=val(), oid=val(), uid=val(),
    )


# gomesurface: quantizer
def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


#: Smallest per-grid cap class. Below this the fixed per-step cost dominates
#: (the roofline in ARCHITECTURE.md prices slot work at ~11 cycles/slot past
#: 128 and ~nothing below), so finer classes would only multiply compiled
#: shapes. Also keeps every class >= the default max_fills record budget.
CAP_CLASS_MIN = 64


# gomesurface: quantizer
def _cap_ladder(cap: int) -> list[int]:
    """The per-grid cap classes available under a storage cap: pow4 steps
    from CAP_CLASS_MIN (64, 256, 1024, ...) strictly below `cap`, plus
    `cap` itself. Pow4 bounds the compiled-shape count at <=4x padding —
    the same trade _next_pow4 makes for train-grid rows. A storage cap at
    or below CAP_CLASS_MIN yields a single class (today's behavior:
    every grid runs at the storage cap)."""
    if cap <= CAP_CLASS_MIN:
        return [cap]
    out = []
    c = CAP_CLASS_MIN
    while c < cap:
        out.append(c)
        c *= 4
    out.append(cap)
    return out


def _slice_books_cap(books: BookState, cap: int) -> BookState:
    """Restrict the slot axis to the leading `cap` slots (no-op at the
    storage width). Exact for every lane whose resting count <= cap —
    active slots are a prefix — and _guard_capped turns any deeper lane
    into a book_overflow so the escalation machinery re-runs the grid at
    a deeper class instead of silently dropping its tail."""
    if books.price.shape[-1] == cap:
        return books
    cut = lambda a: a[..., :cap]
    return books._replace(
        price=cut(books.price), lots=cut(books.lots), seq=cut(books.seq),
        oid=cut(books.oid), uid=cut(books.uid),
    )


def _guard_capped(outs: StepOutput, pre_counts, cap: int,
                  ops: DeviceOp) -> StepOutput:
    """Flag rows whose PRE-step resting count exceeds the grid's cap class:
    their books were truncated by the slice, so the grid's result for them
    is not trustworthy. Folding the flag into book_overflow reuses the
    exact escalation/fallback path — a stale host-side depth estimate
    costs a re-run, never correctness. (Growth DURING the grid past cap is
    the ordinary insert overflow and needs no guard.)

    Rows with no real op are exempt: NOPs never read or write book slots,
    so a deep lane riding a shallow-class grid as padding is exact — in a
    class-partitioned full grid (engine.frames._class_partitions) every
    OTHER class's lanes are exactly such rows."""
    touched = jnp.any(ops.action != 0, axis=-1)
    bad = (
        touched & (jnp.max(pre_counts, axis=-1) > cap)
    ).astype(outs.book_overflow.dtype)
    return outs._replace(
        book_overflow=jnp.maximum(outs.book_overflow, bad[:, None])
    )


def _writeback_full_cap(books: BookState, sub: BookState, cap: int):
    """Write a cap-sliced full-grid result back into the storage-width
    stack (row == lane; slots beyond `cap` were untouched by the grid)."""
    put = lambda a, s: a.at[..., :cap].set(s)
    return books._replace(
        price=put(books.price, sub.price), lots=put(books.lots, sub.lots),
        seq=put(books.seq, sub.seq), oid=put(books.oid, sub.oid),
        uid=put(books.uid, sub.uid), count=sub.count,
        next_seq=sub.next_seq,
    )


def _scatter_books_cap(books: BookState, lane_ids, sub: BookState, cap: int):
    """Scatter a dense grid's sub-stack back, writing only the leading
    `cap` slots of each touched lane (sentinel rows drop). Lanes in a
    cap-class grid hold nothing beyond `cap` (guarded above), so the
    untouched tail slots stay zero and every book invariant holds."""
    if books.price.shape[-1] == cap:
        return jax.tree.map(
            lambda a, s: a.at[lane_ids].set(s, mode="drop"), books, sub
        )
    put3 = lambda a, s: a.at[lane_ids, :, :cap].set(s, mode="drop")
    put = lambda a, s: a.at[lane_ids].set(s, mode="drop")
    return books._replace(
        price=put3(books.price, sub.price), lots=put3(books.lots, sub.lots),
        seq=put3(books.seq, sub.seq), oid=put3(books.oid, sub.oid),
        uid=put3(books.uid, sub.uid), count=put(books.count, sub.count),
        next_seq=put(books.next_seq, sub.next_seq),
    )


# gomesurface: quantizer
def _next_pow4(n: int) -> int:
    """Coarser shape bucket for a frame's train grids: every distinct
    compiled shape costs a trace, and the train's later grids see
    stochastic live counts/depths — pow4 classes (8, 32, 128, ...) visit
    4x fewer shapes for at most 4x padding on SMALL grids."""
    p = 1
    while p < n:
        p *= 4
    return p


def _merge_buf_floor(dst: dict, src) -> None:
    """Raise per-class buffer floors: src is {pow2 class: slots} or an
    int (interpreted as a floor for its own pow2 class)."""
    items = (
        src.items() if isinstance(src, dict)
        else [(_next_pow2(max(int(src), 64)), int(src))]
    )
    for b, v in items:
        v = _next_pow2(max(int(v), 64))
        dst[b] = max(dst.get(b, 0), v)


def splice_outs(outs, overrides):
    """Build the `outs_at(field, rows, ts)` accessor decode_grid_columnar
    needs: reads StepOutput columns at packed (row, t) coordinates and
    splices in per-row escalation re-runs (each with its own record budget
    K', padded to align). Shared by the object packer and the frame path."""

    def outs_at(field, rows, ts):
        base = np.asarray(getattr(outs, field))[rows, ts]
        for r, src in overrides.items():
            m = rows == r
            if not m.any():
                continue
            ov = np.asarray(getattr(src, field))[ts[m]]
            if base.ndim > 1:
                k_base, k_ov = base.shape[1], ov.shape[1]
                if k_ov > k_base:
                    base = np.pad(base, [(0, 0), (0, k_ov - k_base)])
                elif k_ov < k_base:
                    ov = np.pad(ov, [(0, 0), (0, k_base - k_ov)])
            base[m] = ov
        return base

    return outs_at


class CapacityError(RuntimeError):
    """A configured growth ceiling (max_slots / max_cap) was hit. The book
    state is unchanged for the op that tripped it; callers may shed load or
    re-shard rather than exhaust device memory."""


class BookInvariantError(RuntimeError):
    """verify_books found device book state violating a structural
    invariant — an engine bug or external state corruption, never a
    recoverable input condition."""


@dataclasses.dataclass
class EngineStats:
    """Host-side engine counters (new instrumentation — the reference has
    none, SURVEY §5.5). Escalations are exact-but-slow events worth watching:
    frequent cap growth means the configured book geometry is undersized."""

    orders: int = 0
    fills: int = 0
    cancels: int = 0
    cancels_missed: int = 0
    dropped_no_prepool: int = 0  # incremented by the orchestrator facade
    device_calls: int = 0
    cap_escalations: int = 0
    # Confined escalations: one GRID's cap class deepened (re-sliced from
    # the same storage) without growing the [S]-wide stack — the cheap
    # recovery per-grid cap classes buy (cap_escalations = storage grew).
    grid_cap_escalations: int = 0
    fill_record_escalations: int = 0
    frame_fallbacks: int = 0  # fast-path frames re-run on the exact path
    lane_growths: int = 0


class BatchEngine:
    """Host-side driver for the batched device engine.

    Owns the device-resident [S] book stack, the symbol->lane mapping, and
    the id interners; packs order lists into op grids and decodes StepOutputs
    back into the global MatchResult event stream.

    This layer assumes orders already passed admission (pre-pool checks live
    in the orchestrator above — gome_tpu.engine.orchestrator); every ADD
    given here hits the book.
    """

    def __init__(
        self,
        config: BookConfig,
        n_slots: int,
        max_t: int = 32,
        auto_grow: bool = True,
        max_slots: int = 1 << 16,
        max_cap: int = 1 << 14,
        kernel: str = "scan",
        pallas_interpret: bool = False,
        mesh=None,
        dense: bool = True,
        dense_t_max: int = 1024,
    ):
        """max_slots / max_cap bound auto-grow (symbol lanes / per-side book
        capacity). Growth past a ceiling raises CapacityError instead of
        exhausting HBM — explicit backpressure the caller can surface
        (the reference has no such ceiling because Redis pages to disk).

        kernel: "scan" (XLA scan x vmap) or "pallas" (VMEM-resident Pallas
        grid kernel, gome_tpu.ops.pallas_match). "pallas" silently uses the
        scan path whenever the compiled kernel cannot run (off-TPU, int64
        books, unblockable lane counts) — identical semantics either way, so
        the choice is purely a performance one. pallas_interpret=True forces
        the (slow) Pallas interpreter instead of that fallback; it exists so
        CPU tests can exercise the kernel's code path.

        dense: allow the columnar path to pack batches touching few symbols
        into compact gather/scatter grids over just the live lanes
        (dense_batch_step) instead of the full [n_slots, max_t] grid —
        throughput then tracks APPLIED ops, not provisioned lanes (Zipf
        flows), and a hot symbol's stream can run dense_t_max deep per
        device call (the single-symbol latency path). Semantics identical.

        mesh: an optional 1-D jax.sharding.Mesh (gome_tpu.parallel.make_mesh)
        partitioning the symbol-lane axis across chips. Matching needs zero
        collectives (symbols share nothing, SURVEY §2.1), so the sharded
        step is the same graph with shardings pinned; lane counts stay
        multiples of the mesh size (growth rounds up). kernel="pallas"
        under a mesh runs the compiled VMEM kernel per chip inside a
        shard_map (gome_tpu.parallel.mesh.sharded_batch_step), preserving
        the kernel's throughput win at multi-chip scale."""
        if kernel not in KERNELS:
            raise ValueError(f"kernel must be one of {KERNELS}, got {kernel!r}")
        if config.cap > max_cap:
            raise ValueError(f"cap {config.cap} exceeds max_cap {max_cap}")
        if n_slots > max_slots:
            raise ValueError(f"n_slots {n_slots} exceeds max_slots {max_slots}")
        self.config = config
        self.n_slots = n_slots
        self.max_t = max_t
        self.auto_grow = auto_grow
        self.max_slots = max_slots
        self.max_cap = max_cap
        self.kernel = kernel
        self._pallas_interpret = pallas_interpret
        self.mesh = mesh
        self.dense = dense
        self.dense_t_max = dense_t_max
        # Grow-only geometry ratchets (see _grid_geometry / frame packing):
        # compiled grid shapes must not oscillate across pow2 buckets.
        # Keyed by CAP CLASS (_cap_ladder): each class runs its own grid
        # train with its own row/depth profile — the tail class's 10K-row
        # floor must never inflate the hot class's 8-row grids (and vice
        # versa for depth).
        self._dense_rows_floor: dict[int, int] = {}
        self._dense_t_floor: dict[int, int] = {}
        # Per-lane resting-count upper bound, the host-side input to cap-
        # class selection (frames._class_partitions): ub = _ub_base (true
        # per-lane max-side counts at the last device fetch) + _ub_extra
        # (limit-ADDs packed since — each can rest at most once, and
        # nothing else ever raises a count, so base+extra is provably an
        # upper bound). It is a PERFORMANCE hint only: an underestimate is
        # caught on device by _guard_capped and re-run deeper.
        self._ub_base = np.zeros(n_slots, np.int64)
        self._ub_extra = np.zeros(n_slots, np.int64)
        # Compaction-buffer ratchets (frames._compact_sizes): grow-only
        # fetch-buffer sizes, keyed by the grid's pow2 op-count class. A
        # frame can contain grids of wildly different sizes (a Zipf flow
        # packs one 256K-op full grid plus a train of small deep dense
        # grids), so a single global floor would make every small grid
        # fetch the big grid's buffer; per-class floors keep each grid's
        # transfer proportional to its ops while still pinning compiled
        # shapes within a class. The fills floor additionally grows when
        # a grid's fill count overflows its buffer (the exact-path
        # fallback keeps that safe).
        self._fills_buf_floor: dict[int, int] = {}
        self._cancels_buf_floor: dict[int, int] = {}
        # Every fast-path (grid geometry, compact-buffer) shape combo this
        # engine has DISPATCHED (frames.submit_frame records; tuples of
        # (n_rows, t_grid, cap_g, dense, m_pad, k_rec, e_fills, e_cancels,
        # totals_len)). A deployment persists these alongside the floors
        # (shape_manifest / orchestrator.save_geometry) and replays them
        # with all-padding inputs at boot (frames.precompile_combos), so
        # the very first live frame runs fully traced+compiled — the
        # trace cost (which the XLA persistent cache does NOT cover: it
        # caches compiles, not traces) moves off every hot path.
        self._seen_combos: set[tuple] = set()
        if mesh is not None:
            # Every place n_slots can be set (init, growth, restore) must
            # produce a mesh multiple; enforcing the two static bounds here
            # and rounding growth up lets _place assume divisibility.
            for name, v in (("n_slots", n_slots), ("max_slots", max_slots)):
                if v % mesh.size != 0:
                    raise ValueError(
                        f"{name} {v} must be a multiple of the mesh size "
                        f"{mesh.size}"
                    )
        self._sharded_steppers: dict = {}  # BookConfig -> jitted step
        self._sharded_dense_steppers: dict = {}  # BookConfig -> dense step
        self.books = self._place(init_books(config, n_slots))
        from .nativehost import make_interner

        from ..utils.cache import IdentityCache

        self.symbols = Interner()  # symbol -> lane id + 1 offset handled below
        # symbol-dictionary object -> (lane-id array, max lane); hits are
        # revalidated against n_slots (frames._lane_map).
        self._lane_map_cache = IdentityCache()
        # oids are the one per-order-unique string column — interned in C++
        # when the toolchain allows (nativehost; ~10x the dict loop).
        self.oids = make_interner()
        self.uids = Interner()
        self.stats = EngineStats()
        # Price rebasing (32-bit books only): device prices are stored
        # relative to a per-lane int64 base, so absolute tick magnitudes are
        # unbounded while each symbol's ACTIVE window is +-2^31 ticks — the
        # windowed-ladder re-centering of SURVEY §5.7, done at the host
        # boundary where it costs one subtract. int64 books keep base 0.
        self._rebase = jnp.dtype(config.dtype).itemsize <= 4
        self.price_base = np.zeros(n_slots, np.int64)
        self._base_set = np.zeros(n_slots, bool)
        # Conservative absolute-price envelope per lane (grows only): the
        # recenter check proves every price the lane has EVER admitted still
        # fits the int32 window under a new base, without a device scan.
        self._env_lo = np.zeros(n_slots, np.int64)
        self._env_hi = np.zeros(n_slots, np.int64)

    # Admission window around the current base; recenter when exceeded.
    REBASE_LIMIT = 1 << 30
    _INT32_SAFE = (1 << 31) - 2

    def _place(self, books: BookState) -> BookState:
        """Pin the lane axis across the mesh (no-op without one)."""
        if self.mesh is None:
            return books
        from ..parallel.mesh import shard_batch

        return shard_batch(self.mesh, books)

    def _grow_base_arrays(self, new_slots: int) -> None:
        pad = new_slots - len(self.price_base)
        self.price_base = np.pad(self.price_base, (0, pad))
        self._base_set = np.pad(self._base_set, (0, pad))
        self._env_lo = np.pad(self._env_lo, (0, pad))
        self._env_hi = np.pad(self._env_hi, (0, pad))
        self._ub_base = np.pad(self._ub_base, (0, pad))
        self._ub_extra = np.pad(self._ub_extra, (0, pad))

    # -- resting-count upper bound (cap-class selection) -------------------
    def count_ub(self) -> np.ndarray:
        """Current per-lane upper bound on max-side resting count."""
        return self._ub_base + self._ub_extra

    def note_packed_adds(self, add_counts: np.ndarray) -> None:
        """Record a packed batch's per-lane limit-ADD counts (each may rest
        at most once, keeping count_ub an upper bound). add_counts is
        [n_slots] at pack time; callers keep it for _note_exact_counts."""
        self._ub_extra[: len(add_counts)] += add_counts

    def _note_exact_counts(self, counts_max, resolved_adds=None) -> None:
        """Reset the estimate from a device fetch of true per-lane max-side
        counts (taken AFTER some batch B executed). resolved_adds = B's own
        note_packed_adds increments when later batches are already packed
        on top (the frame pipeline resolves FIFO, so extra minus B's share
        is exactly the still-in-flight sum); None asserts nothing is in
        flight and zeroes extra."""
        n = self.n_slots
        base = np.zeros(n, np.int64)
        m = min(len(counts_max), n)
        base[:m] = np.asarray(counts_max[:m], np.int64)
        self._ub_base = base
        if resolved_adds is None:
            self._ub_extra = np.zeros(n, np.int64)
        else:
            extra = self._ub_extra.copy()
            m = min(len(resolved_adds), n)
            extra[:m] -= np.asarray(resolved_adds[:m], np.int64)
            np.maximum(extra, 0, out=extra)
            self._ub_extra = extra

    def _prepare_bases(self, pending, lanes) -> np.ndarray:
        """Set / recenter per-lane price bases so every ADMITTED price in
        `pending` is representable on device. Runs before packing;
        recentering shifts the lane's resting prices on device (rare — only
        when flow drifts more than REBASE_LIMIT ticks from the current
        base).

        Returns a boolean drop mask aligned with `pending`: True marks a
        DEL whose price is unrepresentable under the lane's (possibly just
        recentred) base. Only ADD limit prices feed the grow-only envelope —
        a DEL price is a lookup key, not an admission (a wrong-price cancel
        is in-contract and must miss, engine.go:92-98; the stock delorder
        client hardcodes price 0.5). Since every RESTING price always fits
        the window, an unrepresentable DEL provably matches nothing, so it
        is dropped host-side as a missed cancel instead of widening the
        envelope and poisoning the lane forever."""
        n = len(pending)
        drop = np.zeros(n, bool)
        if not self._rebase:
            return drop
        from ..types import OrderType

        lo: dict[int, int] = {}
        hi: dict[int, int] = {}
        for (_, o), lane in zip(pending, lanes):
            if o.action is not Action.ADD or o.order_type is OrderType.MARKET:
                # MARKET prices are documented-ignored (encoded 0); DEL/NOP
                # prices never admit a resting order. Neither may widen the
                # envelope.
                continue
            p = o.price
            l = lo.get(lane)
            if l is None:
                lo[lane] = hi[lane] = p
            else:
                if p < l:
                    lo[lane] = p
                elif p > hi[lane]:
                    hi[lane] = p
        for lane, l in lo.items():
            self._admit_lane_range(lane, l, hi[lane])
        for i, ((_, o), lane) in enumerate(zip(pending, lanes)):
            if o.action is Action.DEL and (
                abs(o.price - int(self.price_base[lane])) > self._INT32_SAFE
            ):
                drop[i] = True
        return drop

    # Buffer-floor helpers (shared with frames._compact_sizes): floors
    # are {pow2 op-class: slot count}; an int means "this size, in its
    # own class".
    # gomesurface: quantizer
    @staticmethod
    def _buf_class(n: int) -> int:
        return _next_pow2(max(n, 64))

    def prewarm_geometry(
        self,
        rows_floor: int | None = None,
        t_floor: int | None = None,
        fills_buf: int | None = None,
        cancels_buf: int | None = None,
    ) -> None:
        """Pre-set the grow-only shape ratchets to known steady-state
        values (each rounds up to a power of two; existing floors never
        shrink). fills_buf/cancels_buf accept an int (a floor for its own
        pow2 op-class) or a {pow2 op-class: slots} dict as returned by
        geometry_floors(). Every distinct compiled shape costs a
        trace+compile the first time it appears; a deployment that knows
        its flow's geometry (from a previous run or a staging soak)
        pre-warms here so every shape compiles during warmup instead of
        mid-traffic. Purely a performance knob — untouched ratchets grow
        on demand exactly as before.

        rows_floor/t_floor accept an int (a floor for the storage-cap
        class — the pre-cap-class behavior) or a {cap class: floor} dict
        as returned by geometry_floors()."""

        def merge(dst: dict, src, cap: int) -> None:
            """Merge grow-only, clamped to `cap`: a floor beyond the
            usable range (rows past n_slots, depth past the dense
            ceiling) carries no information — it just forces every grid
            to the degenerate fallback — and persisting it would let a
            compounding margin (e.g. 2x per run through a saved
            manifest) poison geometry forever."""
            items = (
                src.items() if isinstance(src, dict)
                else [(self.config.cap, src)]
            )
            for c, v in items:
                v = min(_next_pow2(max(int(v), 8)), cap)
                dst[c] = max(dst.get(c, 8), v)

        if rows_floor is not None:
            merge(
                self._dense_rows_floor, rows_floor, _next_pow2(self.n_slots)
            )
        if t_floor is not None:
            merge(
                self._dense_t_floor, t_floor,
                _next_pow2(max(self.dense_t_max, self.max_t)),
            )
        if fills_buf is not None:
            _merge_buf_floor(self._fills_buf_floor, fills_buf)
        if cancels_buf is not None:
            _merge_buf_floor(self._cancels_buf_floor, cancels_buf)

    def reset_geometry_floors(self, combos: bool = False) -> None:
        """Forget every grow-only geometry ratchet (rows/depth floors,
        compaction-buffer floors). Correctness-neutral — floors are
        performance hints — but sometimes necessary for performance:
        ratchets latched during a WARMUP TRANSIENT (e.g. count_ub
        overestimates while books fill from empty send hundreds of lanes
        into a deep cap class exactly once) would otherwise pin a
        pathologically wide-and-deep grid for the life of the process. A
        warmup loop calls this once the flow reaches steady state, lets
        the next frames re-ratchet from honest geometry, and THEN pins
        margins / saves the manifest.

        combos=True also forgets the recorded shape combos: the transient
        frames' shapes would otherwise ride save_geometry into the
        manifest and every later boot would precompile grids the
        steady-state flow never dispatches."""
        self._dense_rows_floor.clear()
        self._dense_t_floor.clear()
        self._fills_buf_floor.clear()
        self._cancels_buf_floor.clear()
        if combos:
            self._seen_combos.clear()

    def ensure_cap(self, cap: int) -> None:
        """Pre-size book storage to `cap` slots/side (pow2-snapped,
        grow-only, bounded by max_cap) — a deployment that knows its
        flow's stationary depth (e.g. from a persisted geometry manifest)
        escalates ONCE at boot instead of paying the mid-traffic
        grow+replay, and makes deep-cap shape combos replayable by
        precompile_combos."""
        cap = _next_pow2(max(int(cap), self.config.cap))
        if cap == self.config.cap:
            return
        if cap > self.max_cap:
            raise CapacityError(
                f"ensure_cap({cap}) exceeds max_cap={self.max_cap}"
            )
        self.books = self._place(grow_books(self.books, cap))
        self.config = dataclasses.replace(self.config, cap=cap)

    def geometry_floors(self) -> dict:
        """The current grow-only shape ratchets (see prewarm_geometry) —
        what a warmup loop watches to decide the flow's compiled shapes
        have stabilized, and what a deployment records to pre-warm the
        next process. rows_floor/t_floor are {cap class: floor} dicts, the
        buffer floors {pow2 op-class: slots} dicts; everything is copied
        (safe to hold across further frames)."""
        return dict(
            rows_floor=dict(self._dense_rows_floor),
            t_floor=dict(self._dense_t_floor),
            fills_buf=dict(self._fills_buf_floor),
            cancels_buf=dict(self._cancels_buf_floor),
            cap=self.config.cap,
        )

    # gomesurface: combo(persist)
    def shape_manifest(self) -> dict:
        """Everything a future process needs to run this flow's fast path
        with ZERO first-seen traces: the grow-only floors (so the same
        shapes are CHOSEN) plus every dispatched shape combo (so they are
        TRACED+COMPILED off-clock via frames.precompile_combos). The XLA
        persistent cache already makes compiles one-time across processes;
        traces are per-process and this closes that gap."""
        return dict(
            floors=self.geometry_floors(),
            combos=self.combos(),
        )

    # Dispatch-combo chokepoint: the ONLY writer of the recorded shape
    # set. Everything outside this class — the frame dispatch, geometry
    # replay, observability probes, benches — goes through these four
    # accessors; gomesurface GL902 flags any `_seen_combos` reach-through
    # so a new reader/writer can't silently fork the combo bookkeeping
    # the steady-state (zero-recompile) contract hangs off.
    def record_combo(self, combo) -> bool:
        """Record one dispatched shape combo (tuple-ified). Returns True
        when the combo is first-seen — i.e. the dispatch that produced it
        just paid (or, for precompile replay, just prepaid) a jit
        trace+compile."""
        combo = tuple(combo)
        if combo in self._seen_combos:
            return False
        self._seen_combos.add(combo)
        return True

    def combo_seen(self, combo) -> bool:
        """Whether this shape combo has already been traced+compiled."""
        return tuple(combo) in self._seen_combos

    def combo_count(self) -> int:
        """How many distinct dispatch shape combos this engine compiled —
        the number the perf ratchet gates for the scripted drill."""
        return len(self._seen_combos)

    def combos(self) -> list:
        """The recorded dispatch combos, sorted (stable across runs for
        manifests and tests)."""
        return sorted(self._seen_combos)

    def _grid_geometry(self, live: np.ndarray, first: bool = True,
                       cls: int | None = None):
        """Grid geometry decision, shared by the object packer and the
        frame path (engine.frames): when the batch touches few of the
        provisioned lanes, pack a compact grid over just the live lanes
        (row -> lane indirection, executed by dense_batch_step /
        parallel.mesh.sharded_dense_step); rows bucket to powers of two
        (min 8 — the Pallas kernel's sublane floor; sentinel padding rows
        are free) to bound compile shapes.

        `first` marks the first dense grid of a frame's train. Only it
        consults/advances the grow-only row ratchet: the train's DEEPER
        grids (lanes outliving earlier grids' time axes — a Zipf flow
        drains its hot lanes through a geometrically shrinking train)
        use raw pow2 buckets, because pinning them to the first grid's
        floor would run every tail grid at the head grid's width —
        hundreds of times the live work. Their shapes converge to a
        small set (the shrink is geometric), each compiled once.

        Under a mesh the row axis is laid out PER SHARD: shard d's live
        lanes occupy the contiguous row block [d*R_s, (d+1)*R_s), so the
        standard symbol-axis sharding of the [D*R_s, T] grid hands each
        chip exactly the rows naming its own lanes — the dense gather
        stays shard-local and needs zero collectives (per-symbol key
        isolation, ordernode.go:89-117). R_s buckets to the max per-shard
        live count, so the dense win shrinks as skew concentrates on one
        shard — which is the true cost surface on hardware.

        `cls` keys the grow-only floors by the grid's cap class (per-class
        trains have independent row/depth profiles); None = the storage
        cap class (the single-class behavior).

        Returns (use_dense, n_rows, lane_ids, row_of): lane_ids [n_rows]
        GLOBAL lane ids with sentinel n_slots on padding rows (the device
        step localizes under a mesh); row_of [n_slots] maps live lane ->
        row (valid only at live positions). Both None for full grids."""
        if not (self.dense and len(live) > 0):
            return False, self.n_slots, None, None
        cls = self.config.cap if cls is None else cls
        floor = self._dense_rows_floor.get(cls, 8) if first else 8
        bucket = _next_pow2 if first else _next_pow4
        if self.mesh is None:
            n_rows = max(8, bucket(len(live)), floor)
            if n_rows >= self.n_slots:
                return False, self.n_slots, None, None
            # Grow-only row bucket ("ratchet"): live-lane counts hovering
            # at a pow2 boundary would otherwise flip the compiled grid
            # shape frame to frame — and one fresh XLA compile costs more
            # than thousands of frames of matching.
            if first:
                self._dense_rows_floor[cls] = n_rows
            lane_ids = np.full(n_rows, self.n_slots, np.int64)
            lane_ids[: len(live)] = live
            rows_for_live = np.arange(len(live), dtype=np.int64)
            # Occupancy ledger (obs.placement): dispatched-vs-live rows
            # for the unsharded dense grid, values already in hand.
            PLACEMENT.note_dispatch(n_rows, live)
        else:
            d = self.mesh.size
            local = self.n_slots // d
            shard = live // local  # live is sorted (np.unique upstream)
            counts = np.bincount(shard, minlength=d)
            # Uniform R_s = global max is structural for now: shard_map's
            # even split hands every chip the same [R_s, T] block, so one
            # hot shard pads ALL shards (MULTICHIP_r06 skew 3.64).
            # Per-shard geometry is ROADMAP item 2's refactor.
            r_s = max(8, bucket(int(counts.max())), floor)  # gomelint: disable=GL802 — owning workstream: ROADMAP item 2 (per-shard geometry)
            if r_s * d >= self.n_slots:
                return False, self.n_slots, None, None
            if first:
                self._dense_rows_floor[cls] = r_s
            n_rows = r_s * d
            lane_ids = np.full(n_rows, self.n_slots, np.int64)
            starts = np.zeros(d, np.int64)
            np.cumsum(counts[:-1], out=starts[1:])
            rank = np.arange(len(live), dtype=np.int64) - starts[shard]
            rows_for_live = shard * r_s + rank
            lane_ids[rows_for_live] = live
            # Per-shard telemetry (always-on histogram + the armed
            # profiler's dispatch ring) from values already in hand.
            _dense_shard_skew.observe(int(counts.max()) * d / len(live))
            PROFILER.note_shard_dispatch(d, r_s, counts)
            PLACEMENT.note_dispatch(n_rows, live, counts, r_s)
        row_of = np.empty(self.n_slots, np.int64)
        row_of[live] = rows_for_live
        # Skew telemetry: what row padding (pow2 bucket, grow-only floor,
        # and per-shard MAX bucketing under a mesh) costs THIS dispatch.
        _rows_per_live_lane.observe(n_rows / len(live))
        return True, n_rows, lane_ids, row_of

    def _admit_lane_range(self, lane: int, l: int, h: int) -> None:
        """Admit the ADD-limit price range [l, h] into `lane`'s grow-only
        envelope, seeding or recentering the base as needed. Shared by the
        object packer (_prepare_bases) and the vectorized frame path
        (engine.frames). Raises CapacityError — committing NOTHING — when
        the admitted envelope cannot fit an int32 window."""
        if not self._base_set[lane]:
            nb = (l + h) // 2
            if max(h - nb, nb - l) > self._INT32_SAFE:
                raise CapacityError(
                    f"lane {lane}: batch price range [{l}, {h}] spans "
                    "more than 2^31 ticks — int32 books cannot window "
                    "it; use coarser ticks or an int64 BookConfig"
                )
            self.price_base[lane] = nb
            self._base_set[lane] = True
            self._env_lo[lane] = l
            self._env_hi[lane] = h
            return
        el = min(int(self._env_lo[lane]), l)
        eh = max(int(self._env_hi[lane]), h)
        b = int(self.price_base[lane])
        if max(abs(l - b), abs(h - b)) > self.REBASE_LIMIT:
            nb = (el + eh) // 2
            if max(eh - nb, nb - el) > self._INT32_SAFE:
                raise CapacityError(
                    f"lane {lane}: admitted price range [{el}, {eh}] "
                    "spans more than 2^31 ticks — int32 books cannot "
                    "window it; use coarser ticks or an int64 BookConfig"
                )
            self._shift_lane_prices(lane, b - nb)
            self.price_base[lane] = nb
        # Commit the envelope only after every check passed: a raised
        # batch leaves no trace (the device books are unchanged too), so
        # retrying without the offending order cannot inherit a widened
        # window.
        self._env_lo[lane] = el
        self._env_hi[lane] = eh

    def _shift_lane_prices(self, lane: int, delta: int) -> None:
        """Recenter: stored rebased price -> absolute - new_base =
        stored + (old_base - new_base). Inactive slots shift too, harmlessly
        (matching masks everything beyond count; inserts overwrite)."""
        d = jnp.asarray(delta, self.config.dtype)
        self.books = self.books._replace(
            price=self.books.price.at[lane].add(d)
        )

    def _lane(self, symbol: str) -> int:
        lane = self.symbols.intern(symbol) - 1  # Interner ids start at 1
        if lane >= self.n_slots:
            if not self.auto_grow:
                raise CapacityError(
                    f"symbol {symbol!r} needs lane {lane} but engine has "
                    f"n_slots={self.n_slots} (auto_grow disabled)"
                )
            new_slots = min(max(self.n_slots * 2, lane + 1), self.max_slots)
            if self.mesh is not None:
                m = self.mesh.size
                new_slots = min(((new_slots + m - 1) // m) * m, self.max_slots)
            if lane >= new_slots:
                raise CapacityError(
                    f"symbol {symbol!r} needs lane {lane} but max_slots="
                    f"{self.max_slots}; raise max_slots or shard symbols "
                    "across more engines"
                )
            self.books = self._place(grow_lanes(self.books, new_slots))
            self._grow_base_arrays(new_slots)
            self.n_slots = new_slots
            self.stats.lane_growths += 1
        return lane

    def _checkpoint(self):
        """Everything a failed batch must roll back: the device book stack
        (immutable on device — retaining the reference is free) plus the
        host-side rebasing state and geometry that packing mutates. Interner
        growth is deliberately NOT rolled back (grow-only and idempotent:
        a replay re-interns the same strings to the same ids, and restored
        books only reference ids that already existed)."""
        return (
            self.books, self.config, self.n_slots,
            self.price_base.copy(), self._base_set.copy(),
            self._env_lo.copy(), self._env_hi.copy(),
            self._ub_base.copy(), self._ub_extra.copy(),
        )

    def _restore(self, cp) -> None:
        """Restore MUST copy the mutable arrays: a checkpoint may be
        restored more than once (restore -> exact re-run mutates rebasing
        state in place -> re-run fails -> restore the SAME checkpoint
        again, e.g. FramePipeline's recovery); assigning by reference would
        let the interim mutations corrupt the checkpoint itself."""
        (
            self.books, self.config, self.n_slots,
            price_base, base_set, env_lo, env_hi, ub_base, ub_extra,
        ) = cp
        self.price_base = price_base.copy()
        self._base_set = base_set.copy()
        self._env_lo = env_lo.copy()
        self._env_hi = env_hi.copy()
        self._ub_base = ub_base.copy()
        self._ub_extra = ub_extra.copy()

    def process(self, orders: list[Order]) -> list[MatchResult]:
        """Apply a micro-batch. Symbols with more than max_t ops are drained
        over several device calls (order preserved); returns all events in
        original arrival order. Device-budget overflows are escalated
        internally (see module docstring) — results are always exact.

        Transactional: a raised batch rolls the engine back to its pre-batch
        state (multi-grid batches commit device books per grid — without the
        rollback, replaying a batch that failed on grid 2 would double-apply
        grid 1's orders)."""
        return [
            ev
            for _, evs in self.process_indexed(list(enumerate(orders)))
            for ev in evs
        ]

    # gomelint: hotpath
    def process_indexed(
        self, indexed: list[tuple[int, Order]]
    ) -> list[tuple[int, list[MatchResult]]]:
        """process() keyed by caller-assigned arrival tags: each input item
        is (tag, order) and the result is (tag, events) groups sorted by
        tag. The sharded engine (gome_tpu.parallel.router) passes GLOBAL
        arrival indices here so per-shard results merge back into the exact
        single-FIFO emission order of the reference consumer
        (rabbitmq.go:116-125). Same transactional rollback as process()."""
        cp = self._checkpoint()
        try:
            return self._process_indexed(indexed)
        except Exception:
            self._restore(cp)
            raise

    def _process_indexed(self, indexed):
        pending = list(indexed)
        decoded: list[tuple[int, list[MatchResult]]] = []
        while pending:
            pending = self._one_grid(pending, decoded)
        decoded.sort(key=lambda kv: kv[0])
        self.stats.orders += len(indexed)
        for _, evs in decoded:
            for ev in evs:
                if ev.is_cancel:
                    self.stats.cancels += 1
                else:
                    self.stats.fills += 1
        return decoded

    def _pack_grid(self, pending):
        """Pack a pending (arrival, order) list into one [S, max_t] op grid.
        Returns (ops, contexts, leftover): contexts maps (lane, t) -> the
        packed (arrival, order); leftover holds deferred ops from lanes
        whose time axis filled (FIFO within a symbol is never split)."""
        # Resolve lanes first (this may auto-grow the book stack), so the
        # grid is allocated once at the final lane count and newly created
        # lanes pack into THIS grid rather than deferring to an extra
        # device call.
        lanes = [self._lane(order.symbol) for _, order in pending]
        drop = self._prepare_bases(pending, lanes)
        grid = _nop_grid(self.config, self.n_slots, self.max_t)
        contexts: dict[tuple[int, int], tuple[int, Order]] = {}
        fill_level: dict[int, int] = {}
        leftover: list[tuple[int, Order]] = []
        blocked: set[int] = set()  # lanes whose FIFO order must not be broken

        for (arrival, order), lane, dropped in zip(pending, lanes, drop):
            if dropped:
                # Unrepresentable DEL price (see _prepare_bases): provably a
                # miss; never reaches the device.
                self.stats.cancels_missed += 1
                continue
            t = fill_level.get(lane, 0)
            if lane in blocked or t >= self.max_t:
                # Lane's time axis is full: defer, and block the lane so
                # same-symbol ops never reorder (SURVEY §5.2).
                blocked.add(lane)
                leftover.append((arrival, order))
                continue
            op = encode_op(
                order,
                self.oids,
                self.uids,
                self.config.dtype,
                price_base=int(self.price_base[lane]),
            )
            for name, arr in grid.items():
                arr[lane, t] = getattr(op, name)
            contexts[(lane, t)] = (arrival, order)
            fill_level[lane] = t + 1
            if order.action is Action.ADD and not op.is_market:
                self._ub_extra[lane] += 1  # count_ub upper-bound upkeep
        return DeviceOp(**grid), contexts, leftover

    def process_columnar(self, orders: list[Order]):  # gomelint: hotpath
        """Apply a micro-batch and return events as a columnar EventBatch
        (gome_tpu.engine.events) instead of MatchResult objects — the
        vectorized decode path that keeps the host in step with the device
        kernel's throughput. Identical event content and global order to
        process(); stats are updated the same way. Transactional like
        process(): a raised batch rolls back to pre-batch state."""
        cp = self._checkpoint()
        try:
            return self._process_columnar(orders)
        except Exception:
            self._restore(cp)
            raise

    def _process_columnar(self, orders: list[Order]):
        from .events import EventBatch, empty_batch

        pending = [(i, o) for i, o in enumerate(orders)]
        dels = sum(1 for o in orders if o.action is Action.DEL)
        batches: list[dict] = []  # per-grid column dicts
        while pending:
            pending = self._one_grid_columnar(pending, batches)
        self.stats.orders += len(orders)

        tables = dict(
            symbols=self.symbols.to_list(),
            oid_table=self.oids.table,
            uid_table=self.uids.table,
        )
        if not batches:
            # Nothing reached the device (e.g. every op was a dropped
            # unrepresentable DEL): they are all missed cancels.
            self.stats.cancels_missed += dels
            return empty_batch(**tables)
        cols = {
            n: np.concatenate([b[n] for b in batches]) for n in batches[0]
        }
        # Leftover grids hold deferred ops whose arrivals interleave with
        # the first grid's: restore the global emission order.
        order_ix = np.argsort(cols["arrival"], kind="stable")
        cols = {n: v[order_ix] for n, v in cols.items()}
        batch = EventBatch(columns=cols, **tables)
        cancels = int(batch.columns["is_cancel"].sum())
        self.stats.cancels += cancels
        self.stats.fills += len(batch) - cancels
        self.stats.cancels_missed += dels - cancels
        return batch

    def _pack_grid_vectorized(self, pending):
        """Columnar-path packing: one Python pass extracts per-op fields into
        a [N, 8] int table; lane/slot assignment and the grid writes are
        numpy scatters. ~10x cheaper per op than _pack_grid's per-field
        scalar stores (the decode side is vectorized too, so packing would
        otherwise dominate the host budget)."""
        from ..types import OrderType

        n = len(pending)
        lanes = np.fromiter(
            (self._lane(o.symbol) for _, o in pending), np.int64, n
        )
        drop = self._prepare_bases(pending, lanes)
        bases = self.price_base[lanes]  # [N] int64
        # Slot within the lane = occurrence index (FIFO by construction:
        # occurrence order == arrival order, and every op past the grid's
        # time depth defers, so a lane's stream never reorders or splits
        # across grids). Dropped DELs (unrepresentable price,
        # _prepare_bases) consume no slot and are neither packed nor
        # deferred — the columnar missed-cancel accounting (dels - cancel
        # events) covers them.
        t = np.full(n, -1, np.int64)
        level: dict[int, int] = {}
        for i, lane in enumerate(lanes):
            if drop[i]:
                continue
            c = level.get(lane, 0)
            t[i] = c
            level[lane] = c + 1

        live = (
            np.unique(lanes[~drop]) if bool((~drop).any())
            else np.zeros(0, np.int64)
        )
        use_dense, n_rows, lane_ids, row_of = self._grid_geometry(live)
        if use_dense:
            row = row_of[lanes]
            from .frames import _REC_ELEM_BUDGET

            # Depth budgeted against rows (record tensors are [T, K, R];
            # see frames.pack_frame_grids for the rationale).
            t_mem = max(
                self.max_t,
                _next_pow2(
                    _REC_ELEM_BUDGET
                    // max(n_rows * self.config.max_fills, 1)
                    + 1
                )
                // 2,
            )
            t_floor = self._dense_t_floor.get(self.config.cap, 8)
            t_grid = min(
                max(_next_pow2(max(level.values())), t_floor),
                max(self.dense_t_max, self.max_t),
                t_mem,
            )
            self._dense_t_floor[self.config.cap] = max(t_floor, t_grid)
        else:
            row = lanes
            t_grid = self.max_t
        packed = (t >= 0) & (t < t_grid)

        oids, uids = self.oids, self.uids
        table = np.empty((n, 7), np.int64)
        for i, (_, o) in enumerate(pending):
            rec = table[i]
            rec[0] = int(o.action)
            rec[1] = int(o.side)
            rec[2] = o.order_type is OrderType.MARKET
            rec[3] = o.price
            rec[4] = o.volume
            rec[5] = oids.intern(o.oid)
            rec[6] = uids.intern(o.uuid)
        adds = packed & (table[:, 0] == int(Action.ADD))
        # Keep count_ub an upper bound across paths: every packed limit ADD
        # may rest once (the frame path's increments live in
        # frames._frame_arrays; this is the object-path equivalent).
        rest_candidates = adds & (table[:, 2] == 0)
        if rest_candidates.any():
            self._ub_extra += np.bincount(
                lanes[rest_candidates], minlength=self.n_slots
            )
        bad = adds & (table[:, 4] <= 0)
        if bad.any():
            i = int(np.nonzero(bad)[0][0])
            raise ValueError(
                f"volume must be positive, got {table[i, 4]} "
                f"(oid={pending[i][1].oid}); volume<=0 is out of contract"
            )
        if np.dtype(self.config.dtype).itemsize <= 4:
            from .step import LOT_MAX32

            over = adds & (table[:, 4] > LOT_MAX32)
            if over.any():
                i = int(np.nonzero(over)[0][0])
                raise ValueError(
                    f"volume {table[i, 4]} exceeds the int32-mode per-order "
                    f"lot ceiling {LOT_MAX32} (oid={pending[i][1].oid}); "
                    "use coarser lot units or an int64 BookConfig"
                )

        grid = _nop_grid(self.config, n_rows, t_grid)
        pl, pt = row[packed], t[packed]
        for col, name in enumerate(
            ("action", "side", "is_market", "price", "volume", "oid", "uid")
        ):
            vals = table[packed, col]
            if name == "price":
                # Device sees rebased ticks; MARKET prices are documented-
                # ignored and encode as 0 (they are excluded from the
                # envelope, so rebasing them could overflow).
                vals = np.where(
                    table[packed, 2] != 0, 0, vals - bases[packed]
                )
            grid[name][pl, pt] = vals
        meta = {
            "lane": lanes[packed],
            "row": pl,
            "t": pt,
            "arrival": np.fromiter(
                (a for (a, _), p in zip(pending, packed) if p),
                np.int64,
            ),
            "action": table[packed, 0],
            "side": table[packed, 1],
            "is_market": table[packed, 2],
            "price": table[packed, 3],  # absolute (events carry these)
            "price_base": bases[packed],
            "oid_id": table[packed, 5],
            "uid_id": table[packed, 6],
        }
        leftover = [pending[i] for i in np.nonzero(~packed & ~drop)[0]]
        return DeviceOp(**grid), meta, leftover, lane_ids

    def _one_grid_columnar(self, pending, batches):
        from .events import decode_grid_columnar

        with TRACER.stage("pad_pack"):
            ops, meta, leftover, lane_ids = self._pack_grid_vectorized(
                pending
            )
        if len(meta["arrival"]) == 0:
            # Everything dropped (unrepresentable DELs): nothing to run.
            return leftover
        # _run_exact keys escalation bookkeeping by (row, t); give it the
        # packed coordinates.
        contexts = {
            (int(r), int(tt)): None for r, tt in zip(meta["row"], meta["t"])
        }
        outs, lane_overrides = self._run_exact(ops, contexts, lane_ids)
        with TRACER.stage("decode"):
            batches.append(
                decode_grid_columnar(
                    meta, splice_outs(outs, lane_overrides)
                )
            )
        return leftover

    def _one_grid(self, pending, decoded):
        ops, contexts, leftover = self._pack_grid(pending)
        if not contexts:
            # Everything dropped (unrepresentable DELs): nothing to run.
            return leftover
        outs, lane_overrides = self._run_exact(ops, contexts)
        for (lane, t), (arrival, order) in contexts.items():
            src = lane_overrides.get(lane)
            if src is not None:
                out = jax.tree.map(lambda a: a[t], src)
            else:
                out = jax.tree.map(lambda a: a[lane, t], outs)
            events = decode_events(
                OpContext(order),
                out,
                self.oids,
                self.uids,
                price_base=int(self.price_base[lane]),
            )
            if order.action is Action.DEL and not events:
                self.stats.cancels_missed += 1
            decoded.append((arrival, events))
        return leftover

    def _run_exact(self, ops: DeviceOp, contexts, lane_ids=None,
                   cap_g: int | None = None):
        """Run one grid, escalating device budgets until nothing overflowed.

        Returns (outs, lane_overrides): the committed [R, T] outputs plus,
        for rows whose fill records were truncated at the grid's K, a
        re-decoded [T] StepOutput with a large-enough record budget.

        lane_ids: for a dense grid, the [R] row -> lane mapping (sentinel
        >= n_slots on padding rows); None for full grids (row == lane).

        cap_g: the grid's cap class (None = the storage cap). Overflow
        first deepens the CLASS — a re-slice of the same storage, confined
        to this grid — and only grows the [S]-wide storage once the grid
        already runs at the full storage cap.
        """
        books_before = self.books  # immutable on device; cheap to retain
        if cap_g is None:
            cap_g = self.config.cap

        def lane_of(row: int) -> int:
            return row if lane_ids is None else int(lane_ids[row])

        # Phase 1: book capacity. A tripped `book_overflow` means a resting
        # insert was dropped (or the grid's cap class sliced away a lane's
        # resting tail — _guard_capped) — the result is NOT what the
        # sequential semantics require, so deepen and replay the whole grid
        # from the snapshot (exact: active slots are a prefix; padding is
        # invisible to matching). The new cap targets the host-side bound
        # (current resting count plus the ADDs packed into the lane) but
        # grows at most 4x per replay — see the clamp below — so deep
        # grids converge in a few exact replays instead of one wildly
        # oversized jump.
        while True:
            # One stage span per attempt: dispatch + the blocking overflow
            # fetch (the fetch drains the step, so this is the device
            # wait); the annotation aligns it with jax.profiler traces.
            with TRACER.stage("device_execute"):
                new_books, outs = self._step(
                    books_before, ops, lane_ids, cap_g
                )
                self.stats.device_calls += 1
                host_flags = np.asarray(jax.device_get(outs.book_overflow))
            if not host_flags.any():
                break
            counts = np.asarray(jax.device_get(books_before.count))  # [S, 2]
            adds_per_row = np.sum(
                np.asarray(ops.action) == ACTION_ADD, axis=1
            )  # [R]
            if lane_ids is None:
                row_counts = counts.max(axis=1)
            else:
                ids = np.asarray(lane_ids)
                valid = ids < counts.shape[0]
                row_counts = np.where(
                    valid,
                    counts.max(axis=1)[np.clip(ids, 0, counts.shape[0] - 1)],
                    0,
                )
            bound = int((row_counts + adds_per_row).max())
            if cap_g < self.config.cap:
                # Confined escalation: this grid re-runs on a deeper slice
                # of the SAME storage; the other grids and the stack are
                # untouched. Snap to the class ladder so the replay reuses
                # a compiled shape.
                self.stats.grid_cap_escalations += 1
                target = max(min(bound, 4 * cap_g), cap_g + 1)
                cap_g = next(
                    (c for c in _cap_ladder(self.config.cap) if c >= target),
                    self.config.cap,
                )
                continue
            # The bound assumes EVERY packed ADD rests — with deep dense
            # grids (thousands of ADDs on a hot row) that overshoots the
            # true requirement by orders of magnitude, and cap is global
            # across all S lanes (one 16K-cap escalation on a 10K-lane
            # stack is gigabytes). Grow at most 4x per escalation: the
            # replay loop converges in log4 steps to the smallest
            # sufficient pow2, each step exact.
            self.stats.cap_escalations += 1
            new_cap = _next_pow2(
                max(min(bound, 4 * self.config.cap), self.config.cap + 1)
            )
            if new_cap > self.max_cap:
                raise CapacityError(
                    f"book cap escalation to {new_cap} exceeds max_cap="
                    f"{self.max_cap} (a side is holding >{self.config.cap} "
                    "resting orders); raise max_cap or shed load"
                )
            books_before = self._place(grow_books(books_before, new_cap))
            self.config = dataclasses.replace(self.config, cap=new_cap)
            cap_g = new_cap
        self.books = new_books
        outs = jax.device_get(outs)

        # Phase 2: fill records. n_fills > K truncated this op's *records*
        # only — the book transition is exact either way — so re-run just the
        # affected rows from the snapshot with K' >= max fills observed.
        # n_fills <= resting orders crossed <= cap, so K' <= cap and the
        # set of escalated compile shapes is bounded by log2(cap).
        lane_overrides: dict[int, StepOutput] = {}
        n_fills = np.asarray(outs.n_fills)
        overflowed = sorted(
            {
                row
                for (row, t) in contexts
                if n_fills[row, t] > self.config.max_fills
            }
        )
        for row in overflowed:
            self.stats.fill_record_escalations += 1
            k = min(_next_pow2(int(n_fills[row].max())), self.config.cap)
            big = dataclasses.replace(self.config, max_fills=k)
            lane = lane_of(row)
            lane_book = jax.tree.map(lambda a: a[lane], books_before)
            lane_ops = jax.tree.map(lambda a: a[row], ops)
            # Donating twin: the one-lane book slice and op row are built
            # fresh above and dead after this call on both grid paths.
            _, lane_out = lane_scan_donating(big, lane_book, lane_ops)
            self.stats.device_calls += 1
            lane_overrides[row] = jax.device_get(lane_out)
        return outs, lane_overrides

    def _step(self, books: BookState, ops: DeviceOp, lane_ids=None,
              cap_g: int | None = None):
        """Run one [R, T] grid with the configured kernel. lane_ids selects
        the dense gather/scatter step (compact grid over live lanes; under
        a mesh the rows are laid out per shard and the gather runs inside
        shard_map — parallel.mesh.sharded_dense_step). The Pallas path
        requires S % block_s == 0 (n_slots growth keeps powers of two) and
        interprets off-TPU; escalation re-runs (lane_scan) stay on the scan
        path — they are rare and per-lane.

        cap_g: the grid's cap class (None/equal = storage cap). Every step
        variant slices the slot axis to it, so the per-step cost tracks
        this grid's own depth class."""
        cfg = self.config
        if cap_g is not None and cap_g != cfg.cap:
            cfg = dataclasses.replace(cfg, cap=cap_g)
        # Donation policy (GL6xx): a HOST-sourced grid (numpy — the
        # object-path packers) re-transfers on every dispatch, so its
        # device buffers are dead after the call and the donating twins
        # let XLA reuse them for the outputs. Device-built grids
        # (frames._scatter_grid_fn) must NOT donate: escalation replays
        # re-dispatch the same arrays (_run_exact's phase-1 loop).
        donate = isinstance(ops.action, np.ndarray)
        _batch = batch_step_donating if donate else batch_step
        _dense = dense_batch_step_donating if donate else dense_batch_step
        _densek = dense_kernel_step_donating if donate else dense_kernel_step
        _fullk = full_kernel_step_donating if donate else full_kernel_step
        if lane_ids is not None and self.mesh is not None:
            from ..parallel.mesh import shard_batch, sharded_dense_step

            # Localize: global lane -> shard-local index (each chip's row
            # block names only its own lanes, so lane % local IS the
            # local index); sentinel rows map to `local` (out of range on
            # every chip: gathered as zero books, dropped by the scatter).
            local = self.n_slots // self.mesh.size
            ids_np = np.asarray(lane_ids)
            ids_local = np.where(
                ids_np >= self.n_slots, local, ids_np % local
            ).astype(np.int32)
            stepper = self._sharded_dense_steppers.get(cfg)
            if stepper is None:
                stepper = sharded_dense_step(
                    cfg,
                    self.mesh,
                    kernel=self.kernel,
                    pallas_interpret=self._pallas_interpret,
                )
                self._sharded_dense_steppers[cfg] = stepper
            return stepper(
                books,
                shard_batch(self.mesh, jnp.asarray(ids_local)),
                shard_batch(self.mesh, ops),
            )
        if lane_ids is not None:
            ids = jnp.asarray(lane_ids, jnp.int32)
            if self.kernel == "pallas":
                from ..ops import (
                    default_block_s,
                    interpret_block_s,
                    pallas_available,
                )

                r = ops.action.shape[0]
                block_s = default_block_s(r, cfg.cap)
                if self._pallas_interpret and block_s is None:
                    block_s = interpret_block_s(r)
                if block_s is not None and (
                    pallas_available(cfg.dtype)
                    or self._pallas_interpret
                ):
                    return _densek(
                        cfg, books, ids, ops, block_s,
                        not pallas_available(cfg.dtype),
                    )
            return _dense(cfg, books, ids, ops)
        if self.mesh is not None:
            from ..parallel.mesh import shard_batch, sharded_batch_step

            stepper = self._sharded_steppers.get(cfg)
            if stepper is None:
                stepper = sharded_batch_step(
                    cfg,
                    self.mesh,
                    kernel=self.kernel,
                    pallas_interpret=self._pallas_interpret,
                )
                self._sharded_steppers[cfg] = stepper
            return stepper(books, shard_batch(self.mesh, ops))
        if self.kernel == "pallas":
            from ..ops import (
                default_block_s,
                interpret_block_s,
                pallas_available,
            )

            s = ops.action.shape[0]
            block_s = default_block_s(s, cfg.cap)
            if self._pallas_interpret and block_s is None:
                block_s = interpret_block_s(s)
            if block_s is not None and (
                pallas_available(cfg.dtype) or self._pallas_interpret
            ):
                return _fullk(
                    cfg, books, ops, block_s,
                    not pallas_available(cfg.dtype),
                )
            # int64 books, off-TPU, or lane counts the kernel cannot block:
            # the scan path has identical semantics at full speed (the
            # interpreter is a test vehicle, not a production fallback).
        return _batch(cfg, books, ops)

    # -- snapshot support ----------------------------------------------------
    def export_state(self) -> dict:
        """Host-side copy of all mutable engine state (books + interners +
        geometry) for the durability layer (gome_tpu.persist)."""
        books = jax.device_get(self.books)
        return {
            "books": {k: np.asarray(v) for k, v in books._asdict().items()},
            "symbols": self.symbols.to_list(),
            "oids": self.oids.to_list(),
            "uids": self.uids.to_list(),
            "cap": self.config.cap,
            "max_fills": self.config.max_fills,
            "dtype": np.dtype(self.config.dtype).name,
            "n_slots": self.n_slots,
            "max_t": self.max_t,
            # JSON-safe lists: the durability layer folds everything but
            # "books" into its (JSON) manifest.
            "price_base": self.price_base.tolist(),
            "base_set": self._base_set.astype(int).tolist(),
            "env_lo": self._env_lo.tolist(),
            "env_hi": self._env_hi.tolist(),
        }

    def import_state(self, state: dict) -> None:
        """Restore a state exported by export_state (snapshot recovery).
        Replaces books, interners, and geometry; stats are NOT restored
        (counters describe a process lifetime, not book state)."""
        self.config = dataclasses.replace(
            self.config,
            cap=int(state["cap"]),
            max_fills=int(state["max_fills"]),
            dtype=jnp.dtype(state["dtype"]),
        )
        # Restoring an int64 snapshot into a process that never built an
        # int64 book would silently device_put int32 arrays (x64 off) —
        # the exact failure ensure_dtype_usable exists to prevent.
        from .book import ensure_dtype_usable

        ensure_dtype_usable(self.config.dtype)
        self.n_slots = int(state["n_slots"])
        if self.mesh is not None and self.n_slots % self.mesh.size != 0:
            raise ValueError(
                f"snapshot n_slots {self.n_slots} is not a multiple of the "
                f"mesh size {self.mesh.size}; restore into a non-mesh "
                "engine or re-snapshot from a mesh-aligned one"
            )
        self.max_t = int(state["max_t"])
        b = state["books"]
        books = BookState(**b)
        # _place device_puts with the mesh sharding directly from host
        # arrays; an inner device_put first would materialize the whole
        # stack on one chip (the OOM the mesh exists to avoid).
        self.books = (
            self._place(books) if self.mesh is not None
            else jax.device_put(books)
        )
        from .nativehost import make_interner

        self.symbols = Interner.from_list(list(state["symbols"]))
        self._lane_map_cache.clear()  # lane ids come from the new interner
        self.oids = make_interner(from_list=list(state["oids"]))
        self.uids = Interner.from_list(list(state["uids"]))
        self._rebase = jnp.dtype(self.config.dtype).itemsize <= 4
        n = self.n_slots
        # count_ub restarts exact from the restored books (nothing in
        # flight after a restore).
        self._ub_base = np.asarray(b["count"], np.int64).max(axis=1)
        self._ub_extra = np.zeros(n, np.int64)
        if "price_base" in state:
            self.price_base = np.asarray(state["price_base"], np.int64).copy()
            self._base_set = np.asarray(state["base_set"], bool).copy()
            self._env_lo = np.asarray(state["env_lo"], np.int64).copy()
            self._env_hi = np.asarray(state["env_hi"], np.int64).copy()
        else:
            # Pre-rebasing snapshot: stored prices are absolute, i.e. base 0.
            # Lanes holding resting orders MUST be marked base-set at 0 —
            # otherwise the next batch seeds a fresh base and encodes takers
            # relative to it while the restored book stays absolute (silent
            # non-matching). Envelope from the restored books themselves.
            self.price_base = np.zeros(n, np.int64)
            counts = np.asarray(b["count"])  # [S, 2]
            occupied = counts.sum(axis=1) > 0
            self._base_set = occupied.copy()
            prices = np.asarray(b["price"]).astype(np.int64)  # [S, 2, cap]
            cap = prices.shape[-1]
            slot = np.arange(cap)
            active = slot[None, None, :] < counts[:, :, None]
            self._env_lo = np.where(
                occupied,
                np.where(active, prices, np.iinfo(np.int64).max).min((1, 2)),
                0,
            )
            self._env_hi = np.where(
                occupied, np.where(active, prices, 0).max((1, 2)), 0
            )

    def verify_books(self) -> None:
        """Check every lane against the book invariants (priority-sorted
        slots, positive resting lots, zeroed tails, FIFO seq within price
        levels). O(S*cap) host work — a debug/ops API, not a hot-path check
        (the reference's equivalent was panics sprinkled through the
        linked-list code, nodelink.go:132-157). Raises BookInvariantError
        with the offending lane/side on violation (explicit raises, not
        asserts — python -O must not strip an ops check)."""

        def check(cond, lane, side, what):
            if not cond:
                raise BookInvariantError(
                    f"lane {lane} side {side}: {what}"
                )

        books = jax.device_get(self.books)
        price = np.asarray(books.price)
        lots = np.asarray(books.lots)
        seq = np.asarray(books.seq)
        counts = np.asarray(books.count)
        cap = price.shape[-1]
        for lane in range(counts.shape[0]):
            for side in (0, 1):
                n = int(counts[lane, side])
                check(0 <= n <= cap, lane, side, f"count {n} out of range")
                p, l, s = (a[lane, side] for a in (price, lots, seq))
                check(bool((l[:n] > 0).all()), lane, side, "empty slot in prefix")
                check(bool((l[n:] == 0).all()), lane, side, "lots beyond count")
                if n > 1:
                    dp = np.diff(p[:n].astype(np.int64))
                    ordered = (dp <= 0) if side == BUY else (dp >= 0)
                    check(bool(ordered.all()), lane, side, "priority order broken")
                    same = dp == 0
                    check(
                        bool((np.diff(s[:n])[same] > 0).all()),
                        lane, side, "FIFO seq order broken",
                    )

    # -- views -------------------------------------------------------------
    def lane_books(self) -> BookState:
        """Host copy of the books with ABSOLUTE prices (per-lane rebasing
        offsets added back; the price leaf widens to int64 when bases are in
        play). Consumers of raw device state use export_state instead."""
        books = jax.device_get(self.books)
        if self._rebase and self._base_set.any():
            price = np.asarray(books.price).astype(np.int64)
            price = price + self.price_base[:, None, None]
            books = books._replace(price=price)
        return books

    def symbol_lane(self, symbol: str) -> int:
        """Read-only lookup: the lane owning `symbol`. Raises KeyError for a
        symbol the engine has never processed (unlike _lane, this never
        interns or grows device state)."""
        i = self.symbols.get(symbol)
        if i is None:
            raise KeyError(f"unknown symbol {symbol!r}")
        return i - 1

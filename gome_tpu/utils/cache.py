"""Identity-keyed memo for shared immutable objects on hot paths.

The columnar wire decoder returns the SAME list object for a dictionary
region it has seen before (bus.colwire), which lets downstream stages memo
per-dictionary derived values (packed key bytes, lane maps, encoded
regions) by object identity instead of re-deriving them every frame. The
subtlety this class centralizes: id() values are reused after garbage
collection, so every entry pins the key object with a strong reference
and every hit re-verifies `is`.
"""

from __future__ import annotations


class IdentityCache:
    """Maps a shared, immutable-by-contract object to a derived value.

    `get` returns None on miss (values must not be None); `put` returns
    the value for call-chaining. The whole cache clears past `cap`
    entries — the expected working set is a handful of long-lived
    dictionary objects, so wholesale eviction is simpler than LRU and
    never wrong."""

    __slots__ = ("cap", "_d")

    def __init__(self, cap: int = 32):
        self.cap = cap
        self._d: dict = {}

    def get(self, obj):
        ent = self._d.get(id(obj))
        if ent is not None and ent[0] is obj:
            return ent[1]
        return None

    def put(self, obj, value):
        if len(self._d) >= self.cap:
            self._d.clear()
        self._d[id(obj)] = (obj, value)
        return value

    def clear(self) -> None:
        self._d.clear()

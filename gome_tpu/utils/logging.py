"""Logging — the reference's util/logger.go:9-23 re-expressed on stdlib
logging: `Info`/`Error` writers multi-targeting order.log + stderr, plus
structured extras the reference lacks (level filtering, per-module names,
and an optional JSON-lines mode that stamps every record with the current
order trace id so log lines join against flight-recorder spans).
"""

from __future__ import annotations

import json
import logging
import os
import sys

_CONFIGURED = False
LOG_FILE = "order.log"  # logger.go:14 — same default file name

#: Env switch for the JSON-lines formatter (configure(json_lines=None)
#: reads it): any of 1/true/yes/on enables.
JSON_ENV = "GOME_LOG_JSON"

#: Env override for WHERE order.log lands (configure(log_dir=None) reads
#: it). The reference drops the file in the CWD; that kept re-littering
#: this repo's root whenever a test or script booted a service from it.
DIR_ENV = "GOME_LOG_DIR"


def _default_log_dir() -> str:
    """Directory for the log file when the caller names none: the
    GOME_LOG_DIR env override first; under pytest, the system tmp dir;
    when the CWD is a source checkout (a `.git` or `pyproject.toml`
    marker), the system tmp dir again — the pytest guard alone kept
    missing scripts/ entry points run from the repo root, and every such
    run re-littered the checkout with a stray order.log; otherwise the
    CWD (empty string — reference behavior, logger.go:14)."""
    d = os.environ.get(DIR_ENV)
    if d:
        return d
    if "PYTEST_CURRENT_TEST" in os.environ or "pytest" in sys.modules:
        import tempfile

        return tempfile.gettempdir()
    if os.path.exists(".git") or os.path.exists("pyproject.toml"):
        import tempfile

        return tempfile.gettempdir()
    return ""


class JsonLineFormatter(logging.Formatter):
    """One JSON object per line: ts (unix seconds), level, logger, msg —
    plus trace_id when the record was emitted inside a traced request
    (utils.trace.current_trace_id, bound by the gateway handlers), so a
    grep for a trace id surfaces both its spans and its log lines."""

    def format(self, record: logging.LogRecord) -> str:
        from .trace import current_trace_id

        d = {
            "ts": round(record.created, 6),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        tid = current_trace_id()
        if tid is not None:
            d["trace_id"] = tid
        if record.exc_info:
            d["exc"] = self.formatException(record.exc_info)
        return json.dumps(d, separators=(",", ":"), default=str)


def _json_enabled(json_lines: bool | None) -> bool:
    if json_lines is not None:
        return json_lines
    return os.environ.get(JSON_ENV, "").lower() in ("1", "true", "yes", "on")


def configure(
    log_file: str | None = LOG_FILE,
    level: int = logging.INFO,
    json_lines: bool | None = None,
    log_dir: str | None = None,
) -> None:
    """Idempotent root setup: file + stderr handlers (logger.go:17-22's
    io.MultiWriter). Call once at process start; get_logger works either
    way (falls back to stderr-only if never configured). json_lines
    selects the JSON-lines formatter (None: the GOME_LOG_JSON env var
    decides) — each record then carries the current trace id. log_dir
    places the file (None: GOME_LOG_DIR env, then tmp under pytest,
    then CWD — _default_log_dir); the directory is created if needed."""
    global _CONFIGURED
    if _CONFIGURED:
        return
    root = logging.getLogger("gome_tpu")
    root.setLevel(level)
    if _json_enabled(json_lines):
        fmt: logging.Formatter = JsonLineFormatter()
    else:
        fmt = logging.Formatter(
            "%(asctime)s [%(levelname)s] %(name)s: %(message)s"
        )
    stderr = logging.StreamHandler(sys.stderr)
    stderr.setFormatter(fmt)
    root.addHandler(stderr)
    if log_file:
        d = log_dir if log_dir is not None else _default_log_dir()
        path = log_file
        if d:
            os.makedirs(d, exist_ok=True)
            path = os.path.join(d, log_file)
        fh = logging.FileHandler(path)
        fh.setFormatter(fmt)
        root.addHandler(fh)
    _CONFIGURED = True


def get_logger(name: str) -> logging.Logger:
    return logging.getLogger(f"gome_tpu.{name}")

"""Logging — the reference's util/logger.go:9-23 re-expressed on stdlib
logging: `Info`/`Error` writers multi-targeting order.log + stderr, plus
structured extras the reference lacks (level filtering, per-module names).
"""

from __future__ import annotations

import logging
import sys

_CONFIGURED = False
LOG_FILE = "order.log"  # logger.go:14 — same default file name


def configure(log_file: str | None = LOG_FILE, level: int = logging.INFO) -> None:
    """Idempotent root setup: file + stderr handlers (logger.go:17-22's
    io.MultiWriter). Call once at process start; get_logger works either
    way (falls back to stderr-only if never configured)."""
    global _CONFIGURED
    if _CONFIGURED:
        return
    root = logging.getLogger("gome_tpu")
    root.setLevel(level)
    fmt = logging.Formatter(
        "%(asctime)s [%(levelname)s] %(name)s: %(message)s"
    )
    stderr = logging.StreamHandler(sys.stderr)
    stderr.setFormatter(fmt)
    root.addHandler(stderr)
    if log_file:
        fh = logging.FileHandler(log_file)
        fh.setFormatter(fmt)
        root.addHandler(fh)
    _CONFIGURED = True


def get_logger(name: str) -> logging.Logger:
    return logging.getLogger(f"gome_tpu.{name}")

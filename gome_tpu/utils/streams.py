"""Synthetic order-stream generators for tests and benchmarks.

Models the reference's only load driver (gomengine/doorder.go:37-59: 1,999
pseudo-random limit orders, random side, 2-decimal price/volume in (0,1],
fixed uuid, one symbol) plus the BASELINE.json configs the reference lacks:
100-symbol Poisson flow (config 3), 10K-symbol Zipf-skewed flow (config 4),
and mixed streams with cancels (config 2) / market orders (config 5).
"""

from __future__ import annotations

import random

from ..fixed import scale
from ..types import Action, Order, OrderType, Side


def doorder_stream(
    n: int = 1999,
    symbol: str = "eth2usdt",
    seed: int = 0,
    accuracy: int = 8,
    uuid: str = "2",
) -> list[Order]:
    """doorder.go-style stream: random BUY/SALE, price/volume uniform in
    (0,1] rounded to 2 decimals (doorder.go:38-47), oid = loop index."""
    rng = random.Random(seed)
    orders = []
    for i in range(1, n + 1):
        price = round(rng.uniform(0.01, 1.0), 2)
        volume = round(rng.uniform(0.01, 1.0), 2)
        orders.append(
            Order(
                uuid=uuid,
                oid=str(i),
                symbol=symbol,
                side=Side(rng.randrange(2)),
                price=scale(price, accuracy),
                volume=scale(volume, accuracy),
            )
        )
    return orders


def mixed_stream(
    n: int = 2000,
    symbol: str = "eth2usdt",
    seed: int = 0,
    accuracy: int = 8,
    cancel_prob: float = 0.2,
    market_prob: float = 0.0,
    n_users: int = 8,
    price_range: tuple[float, float] = (0.90, 1.10),
) -> list[Order]:
    """Mixed add/cancel (and optionally market) stream — BASELINE configs 2/5.

    Cancels target a random still-open prior order with its exact resting
    price and side (the reference's cancel contract, SURVEY §2.3.2).
    """
    rng = random.Random(seed)
    orders: list[Order] = []
    open_orders: list[Order] = []
    oid = 0
    for _ in range(n):
        if open_orders and rng.random() < cancel_prob:
            target = open_orders.pop(rng.randrange(len(open_orders)))
            orders.append(
                Order(
                    uuid=target.uuid,
                    oid=target.oid,
                    symbol=symbol,
                    side=target.side,
                    price=target.price,
                    volume=target.volume,
                    action=Action.DEL,
                )
            )
            continue
        oid += 1
        is_market = rng.random() < market_prob
        price = round(rng.uniform(*price_range), 2)
        volume = round(rng.uniform(0.01, 2.0), 2)
        order = Order(
            uuid=str(rng.randrange(n_users)),
            oid=f"o{oid}",
            symbol=symbol,
            side=Side(rng.randrange(2)),
            price=scale(price, accuracy),
            volume=scale(volume, accuracy),
            order_type=OrderType.MARKET if is_market else OrderType.LIMIT,
        )
        orders.append(order)
        if not is_market:
            open_orders.append(order)
            if len(open_orders) > 256:
                open_orders.pop(0)
    return orders


def multi_symbol_stream(
    n: int,
    n_symbols: int,
    seed: int = 0,
    accuracy: int = 8,
    zipf_a: float | None = None,
    cancel_prob: float = 0.0,
    price_range: tuple[float, float] = (0.90, 1.10),
) -> list[Order]:
    """Multi-symbol flow — BASELINE configs 3 (uniform ≈ Poisson merge) and 4
    (zipf_a set ⇒ Zipf-skewed per-symbol arrival rates)."""
    rng = random.Random(seed)
    if zipf_a is not None:
        weights = [1.0 / (k + 1) ** zipf_a for k in range(n_symbols)]
    else:
        weights = [1.0] * n_symbols
    symbols = [f"sym{k}" for k in range(n_symbols)]
    open_by_symbol: dict[str, list[Order]] = {s: [] for s in symbols}
    orders: list[Order] = []
    oid = 0
    choices = rng.choices(range(n_symbols), weights=weights, k=n)
    for k in choices:
        sym = symbols[k]
        opens = open_by_symbol[sym]
        if opens and rng.random() < cancel_prob:
            target = opens.pop(rng.randrange(len(opens)))
            orders.append(
                Order(
                    uuid=target.uuid,
                    oid=target.oid,
                    symbol=sym,
                    side=target.side,
                    price=target.price,
                    volume=target.volume,
                    action=Action.DEL,
                )
            )
            continue
        oid += 1
        order = Order(
            uuid=str(rng.randrange(8)),
            oid=f"o{oid}",
            symbol=sym,
            side=Side(rng.randrange(2)),
            price=scale(round(rng.uniform(*price_range), 2), accuracy),
            volume=scale(round(rng.uniform(0.01, 2.0), 2), accuracy),
        )
        orders.append(order)
        opens.append(order)
        if len(opens) > 64:
            opens.pop(0)
    return orders

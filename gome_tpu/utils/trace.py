"""Order-lifecycle tracing — trace ids, stage spans, per-stage latency
histograms, and a flight recorder (SURVEY §5.1/§5.5: the reference is fully
async and publishes no latency numbers; CoinTossX makes per-stage latency
percentiles the headline deliverable of a matching engine, and on an XLA
stack the dominant costs — batch-wait, padding, compile, device dispatch —
are invisible without explicit instrumentation, JAX-LOB §4).

Three cooperating pieces, all dependency-free:

  * **Trace context** — every order is assigned a trace id at the gateway
    (`Tracer.new_trace`). The wire form is ``"<id>@<t>"`` where ``t`` is
    the publisher's clock at the hop (`encode_context`/`decode_context`):
    the receiver turns the carried timestamp into a `bus_transit` /
    `batch_wait` span without any clock negotiation (same-process clocks;
    cross-process spans are documented as same-host-only). The context
    rides the JSON order codec (``Trace`` field — reference-shaped
    messages decode unchanged), the columnar ORDER frame (GCO3 trace
    column), and AMQP basic-properties headers (``x-trace``).

  * **Stage spans** — named, timestamped intervals at each pipeline stage
    (STAGES below). Closing a span observes the per-stage latency
    `Histogram` (one ``gome_stage_seconds{stage=...}`` family in the
    shared REGISTRY, so /metrics exposes p50/p95/p99 per stage) and, when
    trace ids are attached, appends the span to those orders' journeys in
    the flight recorder. Batch-scoped stages (pad_pack, compile,
    device_execute, decode, publish) attribute to every traced order in
    the current batch via `Tracer.batch(...)`.

  * **FlightRecorder** — a bounded ring buffer holding the last N
    COMPLETE order journeys plus every journey exceeding a configurable
    slow-order threshold, exported as Chrome trace-event JSON
    (`chrome_trace`; loadable in chrome://tracing or Perfetto) via the
    ops endpoint's ``/trace``.

Hot-path contract: with no recorder installed (the default) every hook is
a shared no-op — `Tracer.span`/`stage` return a module-level singleton
context manager and `new_trace` returns None, so the frame hot path pays
one attribute check and ZERO allocations (asserted by the no-op-recorder
guard in tests/test_trace.py).
"""

from __future__ import annotations

import contextvars
import itertools
import os
import threading
import time
from collections import OrderedDict, deque

from .metrics import REGISTRY, Registry

#: The span taxonomy, in pipeline order. `compile_miss`/`compile_hit`
#: split the device-dispatch cost by whether the shape combo had been
#: traced+compiled before (engine.frames.submit_frame keys on
#: BatchEngine.combo_seen).
STAGES = (
    "ingress",        # gateway: validate + pre-pool mark
    "enqueue",        # gateway: hand-off to the batcher / order queue
    "batch_wait",     # batcher: buffered waiting for the frame to close
    "bus_transit",    # publish -> consumer receipt (from the carried ts)
    "pad_pack",       # host: frame arrays + grid packing (NOP padding)
    "compile_miss",   # dispatch of a first-seen shape combo (trace+compile)
    "compile_hit",    # dispatch of an already-compiled combo
    "device_execute", # blocking device fetch (execution drain)
    "decode",         # device outputs -> event columns
    "publish",        # event publish to the matchOrder queue
)


# --- trace context (the wire form) ---------------------------------------


def encode_context(trace_id: str, t: float) -> str:
    """Wire form of one hop's trace context: ``"<id>@<t>"`` with ``t``
    the sender's clock reading at the hop (seconds, same epoch as the
    tracer clock)."""
    return f"{trace_id}@{t:.9f}"


def decode_context(ctx: str) -> tuple[str, float]:
    """Inverse of encode_context; a bare id (no ``@``) carries t=0.0."""
    trace_id, _, ts = ctx.partition("@")
    return trace_id, (float(ts) if ts else 0.0)


# --- flight recorder -----------------------------------------------------


class FlightRecorder:
    """Bounded journey store: open journeys accumulate spans keyed by
    trace id; `complete()` moves a journey into the last-N ring, and into
    the slow ring too when it exceeded `slow_threshold_s` end to end.
    Everything is O(1) per span and strictly bounded: at most `max_open`
    open journeys (oldest evicted — a lost publish must not leak memory
    forever) and `keep_n` entries per ring."""

    def __init__(
        self,
        keep_n: int = 64,
        slow_threshold_s: float | None = None,
        max_open: int = 4096,
    ):
        self.keep_n = keep_n
        self.slow_threshold_s = slow_threshold_s
        self.max_open = max_open
        self._lock = threading.Lock()
        self._open: OrderedDict[str, list] = OrderedDict()  # guarded by self._lock
        self._done: deque = deque(maxlen=keep_n)  # guarded by self._lock
        self._slow: deque = deque(maxlen=keep_n)  # guarded by self._lock
        self.dropped_open = 0  # guarded by self._lock (evictions)

    def record(
        self, trace_id: str, stage: str, t0: float, t1: float, meta=None
    ) -> None:
        with self._lock:
            spans = self._open.get(trace_id)
            if spans is None:
                if len(self._open) >= self.max_open:
                    self._open.popitem(last=False)
                    self.dropped_open += 1
                spans = self._open[trace_id] = []
            spans.append((stage, t0, t1, meta))

    def complete(self, trace_id: str) -> None:
        with self._lock:
            spans = self._open.pop(trace_id, None)
            if not spans:
                return
            start = min(s[1] for s in spans)
            end = max(s[2] for s in spans)
            j = {
                "trace_id": trace_id,
                "spans": spans,
                "start": start,
                "end": end,
                "duration_s": end - start,
            }
            self._done.append(j)
            if (
                self.slow_threshold_s is not None
                and j["duration_s"] > self.slow_threshold_s
            ):
                self._slow.append(j)

    def journeys(self) -> list[dict]:
        """Complete journeys, last-N ring first, then the slow ring's
        extras (entries already in the last-N ring are not repeated)."""
        with self._lock:
            done = list(self._done)
            slow = list(self._slow)
        seen = {id(j) for j in done}
        return done + [j for j in slow if id(j) not in seen]

    def export(self, include_open: bool = True) -> dict:
        """Wire export for cross-process stitching (obs.fleet): every
        journey this process knows, tagged with the recorder's pid. Open
        journeys are included by default — a gateway process never sees
        the consumer-side `complete()`, so its half of every journey
        lives in `_open` forever; the aggregator joins the halves by
        trace id. Spans serialize as [stage, t0, t1, meta] lists (JSON
        round-trip keeps them list-shaped on the far side)."""
        out = []
        for j in self.journeys():
            out.append(
                {
                    "trace_id": j["trace_id"],
                    "spans": [list(s) for s in j["spans"]],
                    "start": j["start"],
                    "end": j["end"],
                    "duration_s": j["duration_s"],
                    "open": False,
                }
            )
        if include_open:
            with self._lock:
                open_items = [
                    (tid, list(spans)) for tid, spans in self._open.items()
                ]
            for tid, spans in open_items:
                if not spans:
                    continue
                start = min(s[1] for s in spans)
                end = max(s[2] for s in spans)
                out.append(
                    {
                        "trace_id": tid,
                        "spans": [list(s) for s in spans],
                        "start": start,
                        "end": end,
                        "duration_s": end - start,
                        "open": True,
                    }
                )
        return {"pid": os.getpid(), "journeys": out}

    def journey(self, trace_id: str) -> dict | None:
        for j in self.journeys():
            if j["trace_id"] == trace_id:
                return j
        return None

    def chrome_trace(self) -> dict:
        """The recorder's contents as Chrome trace-event JSON (the
        ``traceEvents`` array format chrome://tracing and Perfetto load).
        One tid per journey (named by its trace id via metadata events);
        spans are complete ``"ph": "X"`` events in microseconds."""
        events = []
        for tid_ix, j in enumerate(self.journeys()):
            events.append(
                {
                    "ph": "M",
                    "name": "thread_name",
                    "pid": 1,
                    "tid": tid_ix,
                    "args": {"name": f"order {j['trace_id']}"},
                }
            )
            for stage, t0, t1, meta in j["spans"]:
                ev = {
                    "name": stage,
                    "cat": "order",
                    "ph": "X",
                    "pid": 1,
                    "tid": tid_ix,
                    "ts": t0 * 1e6,
                    "dur": max(t1 - t0, 0.0) * 1e6,
                    "args": {"trace_id": j["trace_id"]},
                }
                if meta:
                    ev["args"].update(meta)
                events.append(ev)
        return {"traceEvents": events, "displayTimeUnit": "ms"}


# --- spans ---------------------------------------------------------------


class _NoopSpan:
    """Shared do-nothing context manager: the disabled-tracer fast path.
    A module-level singleton — entering/exiting it allocates nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


NOOP_SPAN = _NoopSpan()


class _Span:
    """One timed stage interval; exit observes the stage histogram and
    records into the recorder for the explicit trace id and/or the
    tracer's current batch ids."""

    __slots__ = ("_tracer", "stage", "trace_id", "t0")

    def __init__(self, tracer: "Tracer", stage: str, trace_id: str | None):
        self._tracer = tracer
        self.stage = stage
        self.trace_id = trace_id

    def __enter__(self):
        self.t0 = self._tracer.clock()
        return self

    def __exit__(self, exc_type, exc, tb):
        self._tracer.observe_span(
            self.stage, self.t0, self._tracer.clock(), self.trace_id
        )
        return False


class _AnnotatedSpan(_Span):
    """_Span + a jax.profiler.TraceAnnotation over the same interval, so
    the host-side stage span lands on the device trace timeline too
    (utils.tracing.annotate; jax.profiler.trace captures both)."""

    __slots__ = ("_ann",)

    def __enter__(self):
        from .tracing import annotate

        self._ann = annotate(f"gome:{self.stage}")
        self._ann.__enter__()
        return super().__enter__()

    def __exit__(self, exc_type, exc, tb):
        try:
            self._ann.__exit__(exc_type, exc, tb)
        finally:
            return super().__exit__(exc_type, exc, tb)


class _Batch:
    """Context manager attaching a set of trace ids to every batch-scoped
    span closed inside it (thread-local: the consumer thread owns its
    batch)."""

    __slots__ = ("_tracer", "_ids", "_prev")

    def __init__(self, tracer: "Tracer", ids):
        self._tracer = tracer
        self._ids = ids

    def __enter__(self):
        local = self._tracer._local
        self._prev = getattr(local, "batch_ids", None)
        local.batch_ids = self._ids
        return self

    def __exit__(self, exc_type, exc, tb):
        self._tracer._local.batch_ids = self._prev
        return False


# --- logging join --------------------------------------------------------

_current_trace: contextvars.ContextVar = contextvars.ContextVar(
    "gome_trace_id", default=None
)


def current_trace_id() -> str | None:
    """The trace id bound to the current context (utils.logging's JSON
    formatter injects it into every record emitted under `Tracer.bind`)."""
    return _current_trace.get()


class _Bind:
    __slots__ = ("_tid", "_tok")

    def __init__(self, tid):
        self._tid = tid

    def __enter__(self):
        self._tok = _current_trace.set(self._tid)
        return self

    def __exit__(self, exc_type, exc, tb):
        _current_trace.reset(self._tok)
        return False


# --- tracer --------------------------------------------------------------


class Tracer:
    """Process-wide tracing facade. Disabled (no recorder) by default:
    every hook degrades to a no-op singleton / None, so instrumented hot
    paths cost one attribute check. `install()` arms it — typically once
    at service boot (service.app wires it from the ops config) or per
    test/bench run with a private Registry."""

    def __init__(
        self,
        recorder: FlightRecorder | None = None,
        registry: Registry | None = None,
        clock=time.perf_counter,
        new_id=None,
    ):
        self.clock = clock  # single-writer: install() caller (boot/test)
        self.recorder = None  # single-writer: install()/disable() caller
        self._new_id = new_id  # single-writer: install() caller (boot/test)
        self._counter = itertools.count(1)
        self._prefix = f"{os.getpid() & 0xFFFF:04x}"
        self._hist: dict[str, object] = {}  # single-writer: install() caller
        self._local = threading.local()
        if recorder is not None:
            self.install(recorder, registry=registry, clock=clock,
                         new_id=new_id)

    # -- lifecycle ---------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self.recorder is not None

    def install(
        self,
        recorder: FlightRecorder,
        registry: Registry | None = None,
        clock=None,
        new_id=None,
    ) -> "Tracer":
        """Arm the tracer: journeys land in `recorder`, stage histograms
        in `registry` (the process REGISTRY by default; benches pass a
        private one so runs do not pollute each other). `clock` and
        `new_id` are injectable for deterministic tests (scripted clock,
        scripted ids)."""
        registry = registry or REGISTRY
        self._hist = {
            stage: registry.histogram(
                "gome_stage_seconds",
                "per-stage order pipeline latency (order-lifecycle tracing)",
                labels={"stage": stage},
            )
            for stage in STAGES
        }
        if clock is not None:
            self.clock = clock
        if new_id is not None:
            self._new_id = new_id
        self.recorder = recorder
        return self

    def disable(self) -> None:
        """Back to the zero-overhead state (hooks become no-ops again)."""
        self.recorder = None

    # -- trace ids ---------------------------------------------------------
    def new_trace(self) -> str | None:
        """A fresh trace id, or None while disabled (callers gate all
        per-order work on the None)."""
        if self.recorder is None:
            return None
        if self._new_id is not None:
            return self._new_id()
        return f"{self._prefix}-{next(self._counter):08x}"

    def context(self, trace_id: str) -> str:
        """Wire context for a hop happening NOW."""
        return encode_context(trace_id, self.clock())

    def bind(self, trace_id: str | None):
        """Bind `trace_id` as the logging context (current_trace_id) for
        the duration; no-op singleton for None."""
        if trace_id is None:
            return NOOP_SPAN
        return _Bind(trace_id)

    # -- spans -------------------------------------------------------------
    def span(self, stage: str, trace_id: str | None = None):
        """Timed span CM; shared no-op while disabled."""
        if self.recorder is None:
            return NOOP_SPAN
        return _Span(self, stage, trace_id)

    def stage(self, stage: str, trace_id: str | None = None):
        """span() + jax.profiler TraceAnnotation (host/device timeline
        alignment) — for stages bracketing device work."""
        if self.recorder is None:
            return NOOP_SPAN
        return _AnnotatedSpan(self, stage, trace_id)

    def annotation(self, name: str):
        """Bare jax.profiler TraceAnnotation gated on the tracer (no
        histogram) — for regions whose stage label is only known after
        the fact (compile miss vs hit: the shape-combo key needs the
        dispatched outputs' shapes)."""
        if self.recorder is None:
            return NOOP_SPAN
        from .tracing import annotate

        return annotate(f"gome:{name}")

    def batch(self, trace_ids):
        """Attach `trace_ids` to batch-scoped spans closed inside the
        with-block (pad_pack/compile/device_execute/decode/publish record
        one histogram observation and one journey span per id)."""
        if self.recorder is None or not trace_ids:
            return NOOP_SPAN
        return _Batch(self, trace_ids)

    # -- recording ---------------------------------------------------------
    def observe(self, stage: str, dt: float) -> None:
        """Histogram-only observation (no journey attribution)."""
        if self.recorder is None:
            return
        h = self._hist.get(stage)
        if h is not None:
            h.observe(dt)

    def observe_span(
        self, stage: str, t0: float, t1: float, trace_id: str | None = None
    ) -> None:
        """One closed span: histogram once, journey record for the
        explicit id and every current batch id."""
        rec = self.recorder
        if rec is None:
            return
        h = self._hist.get(stage)
        if h is not None:
            h.observe(t1 - t0)
        if trace_id is not None:
            rec.record(trace_id, stage, t0, t1)
        ids = getattr(self._local, "batch_ids", None)
        if ids:
            for tid in ids:
                if tid != trace_id:
                    rec.record(tid, stage, t0, t1)

    def add_span(
        self, trace_id: str | None, stage: str, t0: float, t1: float,
        meta=None,
    ) -> None:
        """Record an explicitly-timed span (spans reconstructed from a
        carried context timestamp: batch_wait, bus_transit)."""
        rec = self.recorder
        if rec is None:
            return
        h = self._hist.get(stage)
        if h is not None:
            h.observe(t1 - t0)
        if trace_id is not None:
            rec.record(trace_id, stage, t0, t1, meta)

    def complete(self, trace_id: str | None) -> None:
        rec = self.recorder
        if rec is not None and trace_id is not None:
            rec.complete(trace_id)

    # -- views -------------------------------------------------------------
    def stage_summary(self) -> dict:
        """{stage: Histogram.value()} for every stage with observations —
        what bench.py --latency folds into the BENCH payload."""
        return {
            stage: h.value()
            for stage, h in self._hist.items()
            if h.value()["count"]
        }

    def stage_percentiles(self, qs=(0.5, 0.9, 0.99)) -> dict:
        """{stage: {"count", "mean", "p50", "p90", "p99", ...}} at the
        requested quantiles — the measured per-stage latency block the
        soak/latency reports publish (scripts/soak.py)."""
        out = {}
        for stage, h in self._hist.items():
            v = h.value()
            if not v["count"]:
                continue
            row = {"count": v["count"], "mean": v["mean"]}
            row.update(h.percentiles(qs))
            out[stage] = row
        return out


#: Process-global tracer (disabled until something installs a recorder).
TRACER = Tracer()

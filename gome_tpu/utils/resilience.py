"""Connection resilience — supervised reconnect with backoff, bounded retry
budgets, and circuit breakers for every external dependency.

The reference simply dies on transport faults (SURVEY §5: log.Fatalf on MQ
errors, per-message AMQP connections, no error recovery), and the first cut
of this port only guaranteed clients *fail loudly* — bus/amqp.py fails the
connection on any protocol desync and documents "callers reconnect fresh",
but no caller did. This module is that caller, shared by every external
connection (AMQP bus, RESP marker/snapshot store):

  backoff_delays  — exponential backoff with DECORRELATED jitter
                    (the AWS-architecture-blog variant: each delay is
                    uniform in [base, prev*3], clamped to max). Decorrelated
                    beats full jitter here because reconnect storms against
                    a just-restarted broker are the failure mode — a fleet
                    of consumers must not re-dial in lockstep.
  RetryBudget     — a bounded token budget for retries so a hard-down
                    dependency degrades to fail-fast instead of every
                    caller burning its full backoff schedule.
  CircuitBreaker  — the classic three-state machine (CLOSED → OPEN after
                    N consecutive failures; OPEN → HALF_OPEN after a
                    cooldown; HALF_OPEN admits probe calls and goes CLOSED
                    on success, back OPEN on failure). While OPEN, calls
                    fail in microseconds with CircuitOpenError instead of
                    stacking up behind connect timeouts.
  Supervised      — a connection supervisor owning one live connection of
                    type T behind a factory: call() runs an operation,
                    classifies ConnectionError/OSError as connection
                    faults, tears the connection down, reconnects under
                    backoff + breaker, fires on-reconnect re-setup hooks,
                    and retries the operation. Per-connection state
                    (breaker state, retry/reconnect counts, time degraded)
                    is registered in utils.metrics.REGISTRY and in a
                    module-level table that service/health.py snapshots
                    into /healthz.

Everything is deterministic under test: the clock, sleeper, and RNG are
injectable (tests drive breaker cooldowns and jitter bounds without real
sleeping).
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field

from .logging import get_logger
from .metrics import REGISTRY

log = get_logger("resilience")

__all__ = [
    "BackoffPolicy",
    "backoff_delays",
    "RetryBudget",
    "RetryBudgetExceeded",
    "CircuitBreaker",
    "CircuitOpenError",
    "Supervised",
    "resilience_snapshot",
    "CONNECTION_FAULTS",
]

#: Exception types every supervisor treats as "the connection is gone" —
#: socket-layer faults and the protocol clients' documented ConnectionError
#: surface (amqp.py / resp.py raise nothing rawer than these).
CONNECTION_FAULTS = (ConnectionError, OSError)


class RetryBudgetExceeded(ConnectionError):
    """Retries exhausted their budget; the dependency is treated as down."""


class CircuitOpenError(ConnectionError):
    """Fail-fast reject: the breaker is OPEN and the cooldown has not
    elapsed. Subclasses ConnectionError so callers' existing fault
    handling (gateway rejects, consumer replay) applies unchanged."""


@dataclass(frozen=True)
class BackoffPolicy:
    """Exponential backoff with decorrelated jitter, bounded by a budget.

    base_s/max_s bound each individual delay; max_retries and budget_s
    bound the whole schedule (whichever trips first) — a supervisor never
    blocks a caller longer than ~budget_s before declaring the dependency
    down and failing fast."""

    base_s: float = 0.05
    max_s: float = 2.0
    max_retries: int = 8
    budget_s: float = 15.0

    def __post_init__(self):
        if self.base_s <= 0 or self.max_s < self.base_s:
            raise ValueError("need 0 < base_s <= max_s")
        if self.max_retries < 1 or self.budget_s <= 0:
            raise ValueError("max_retries and budget_s must be positive")


def backoff_delays(policy: BackoffPolicy, rng: random.Random | None = None):
    """Yield up to policy.max_retries delays with decorrelated jitter:
    d0 = base; d(n+1) ~ Uniform(base, 3*d(n)), clamped to max_s. Every
    delay is guaranteed within [base_s, max_s]."""
    rng = rng or random
    prev = policy.base_s
    for _ in range(policy.max_retries):
        yield prev
        prev = min(policy.max_s, rng.uniform(policy.base_s, prev * 3.0))


class RetryBudget:
    """Token-bucket retry budget (Finagle-style): `rate` tokens accrue per
    second up to `burst`; each retry spends one. When empty, try_spend()
    refuses — callers fail fast instead of amplifying load on a dependency
    that is hard-down. Thread-safe."""

    def __init__(
        self, rate: float = 10.0, burst: float = 20.0, clock=time.monotonic
    ):
        if rate <= 0 or burst <= 0:
            raise ValueError("rate and burst must be positive")
        self.rate = rate
        self.burst = burst
        self._clock = clock
        self._tokens = burst  # guarded by self._lock
        self._last = clock()  # guarded by self._lock
        self._lock = threading.Lock()

    def try_spend(self, n: float = 1.0) -> bool:
        with self._lock:
            now = self._clock()
            self._tokens = min(
                self.burst, self._tokens + (now - self._last) * self.rate
            )
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def tokens(self) -> float:
        with self._lock:
            now = self._clock()
            return min(
                self.burst, self._tokens + (now - self._last) * self.rate
            )


# Breaker states (exported as the gauge value — keep the encoding stable,
# dashboards key on it).
CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"
_STATE_CODE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitBreaker:
    """Three-state circuit breaker. Thread-safe; clock injectable.

    CLOSED:    calls flow; `failure_threshold` CONSECUTIVE failures trip
               it OPEN (a success resets the streak).
    OPEN:      allow() refuses until `reset_timeout_s` elapses, then the
               next allow() transitions to HALF_OPEN and admits probes.
    HALF_OPEN: up to `half_open_max` concurrent probes; one success closes
               the breaker, one failure re-opens it (cooldown restarts).
    """

    def __init__(
        self,
        failure_threshold: int = 5,
        reset_timeout_s: float = 5.0,
        half_open_max: int = 1,
        clock=time.monotonic,
        on_transition=None,
    ):
        if failure_threshold < 1 or half_open_max < 1:
            raise ValueError("thresholds must be >= 1")
        self.failure_threshold = failure_threshold
        self.reset_timeout_s = reset_timeout_s
        self.half_open_max = half_open_max
        self._clock = clock
        self._on_transition = on_transition  # callable(old, new) | None
        self._lock = threading.Lock()
        self._state = CLOSED  # guarded by self._lock
        self._failures = 0  # guarded by self._lock (consecutive, CLOSED)
        self._opened_at = 0.0  # guarded by self._lock
        self._probes = 0  # guarded by self._lock (in-flight HALF_OPEN probes)
        self.transitions: list[tuple[str, str]] = []  # guarded by self._lock
        self.opened_total = 0  # guarded by self._lock

    @property
    def state(self) -> str:
        with self._lock:
            return self._peek_locked()

    def _peek_locked(self) -> str:
        """Current state with the OPEN→HALF_OPEN cooldown applied (read
        path must see the same state allow() would act on)."""
        if (
            self._state == OPEN
            and self._clock() - self._opened_at >= self.reset_timeout_s
        ):
            return HALF_OPEN
        return self._state

    def _transition_locked(self, new: str) -> None:
        old, self._state = self._state, new
        if old != new:
            if len(self.transitions) < 64:  # bounded: tests/healthz only
                self.transitions.append((old, new))
            if new == OPEN:
                self.opened_total += 1
                self._opened_at = self._clock()
            if new == HALF_OPEN:
                self._probes = 0
            if new == CLOSED:
                self._failures = 0
            cb = self._on_transition
            if cb is not None:
                try:
                    cb(old, new)
                except Exception:
                    log.exception("breaker transition callback failed")

    def allow(self) -> bool:
        """May a call proceed right now? HALF_OPEN admission counts the
        caller as a probe — pair every allow()==True with exactly one
        record_success()/record_failure()."""
        with self._lock:
            state = self._peek_locked()
            if state != self._state:
                self._transition_locked(state)
            if self._state == CLOSED:
                return True
            if self._state == HALF_OPEN:
                if self._probes < self.half_open_max:
                    self._probes += 1
                    return True
                return False
            return False

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            if self._state == HALF_OPEN:
                self._transition_locked(CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            state = self._peek_locked()
            if state != self._state:
                self._transition_locked(state)
            if self._state == HALF_OPEN:
                self._transition_locked(OPEN)
            elif self._state == CLOSED:
                self._failures += 1
                if self._failures >= self.failure_threshold:
                    self._transition_locked(OPEN)
            else:  # OPEN: failure while open restarts nothing; stays open
                pass

    def state_code(self) -> int:
        return _STATE_CODE[self.state]


# Module-level supervisor table: service/health.py snapshots this into
# /healthz so every supervised connection in the process self-reports.
_SUPERVISORS: dict[str, "Supervised"] = {}
_SUPERVISORS_LOCK = threading.Lock()


def resilience_snapshot() -> dict:
    """{name: state-dict} for every live Supervised in this process."""
    with _SUPERVISORS_LOCK:
        sups = list(_SUPERVISORS.values())
    return {s.name: s.snapshot() for s in sups}


def _metric_name(name: str) -> str:
    return "".join(c if c.isalnum() else "_" for c in name.lower())


class Supervised:
    """One supervised connection of type T behind a zero-arg factory.

    call(fn) runs fn(conn) against the live connection. A CONNECTION_FAULTS
    exception tears the connection down and, breaker and retry budget
    permitting, reconnects under the backoff policy, fires every
    on-reconnect hook with the fresh connection (topology re-declare,
    AUTH/SELECT replay, consume resume), and retries fn ONCE per fresh
    connection. Exhausted backoff/budget or an open breaker surfaces as a
    ConnectionError subclass, so callers keep their existing fault
    handling.

    retry_op=False turns off the operation retry (reconnect still
    happens): for non-idempotent operations the caller owns replay —
    e.g. a bus commit whose at-least-once contract already covers it.
    """

    def __init__(
        self,
        name: str,
        factory,
        policy: BackoffPolicy | None = None,
        breaker: CircuitBreaker | None = None,
        budget: RetryBudget | None = None,
        on_reconnect=(),
        close=lambda conn: conn.close(),
        clock=time.monotonic,
        sleep=time.sleep,
        rng: random.Random | None = None,
    ):
        self.name = name
        self.factory = factory
        self.policy = policy or BackoffPolicy()
        self.breaker = breaker or CircuitBreaker(clock=clock)
        self.budget = budget or RetryBudget(clock=clock)
        self.on_reconnect = list(on_reconnect)
        self._close = close
        self._clock = clock
        self._sleep = sleep
        self._rng = rng
        self._lock = threading.RLock()
        self._conn = None  # guarded by self._lock
        self.connects_total = 0  # guarded by self._lock (successful dials)
        self.retries_total = 0  # guarded by self._lock (op retries)
        self.faults_total = 0  # guarded by self._lock (faults observed)
        self._degraded_since: float | None = None  # guarded by self._lock
        self.degraded_seconds_total = 0.0  # guarded by self._lock
        with _SUPERVISORS_LOCK:
            _SUPERVISORS[name] = self
        m = _metric_name(name)
        self._g_state = REGISTRY.gauge(
            f"gome_conn_breaker_state_{m}",
            f"breaker state for {name} (0 closed, 1 half-open, 2 open)",
        )
        self._c_reconnects = REGISTRY.counter(
            f"gome_conn_reconnects_total_{m}", f"reconnects for {name}"
        )
        self._c_retries = REGISTRY.counter(
            f"gome_conn_retries_total_{m}", f"operation retries for {name}"
        )
        self._g_degraded = REGISTRY.gauge(
            f"gome_conn_degraded_seconds_{m}",
            f"seconds {name} has been degraded (0 when healthy)",
        )

    # -- state -------------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            now = self._clock()
            degraded_s = (
                now - self._degraded_since if self._degraded_since else 0.0
            )
            return dict(
                breaker=self.breaker.state,
                connected=self._conn is not None,
                connects_total=self.connects_total,
                retries_total=self.retries_total,
                faults_total=self.faults_total,
                degraded_s=degraded_s,
                degraded_seconds_total=self.degraded_seconds_total
                + degraded_s,
                breaker_opened_total=self.breaker.opened_total,
            )

    def _enter_degraded_locked(self) -> None:
        if self._degraded_since is None:
            self._degraded_since = self._clock()

    def _exit_degraded_locked(self) -> None:
        if self._degraded_since is not None:
            self.degraded_seconds_total += (
                self._clock() - self._degraded_since
            )
            self._degraded_since = None
        self._g_degraded.set(0.0)

    def _export_locked(self) -> None:
        self._g_state.set(self.breaker.state_code())
        if self._degraded_since is not None:
            self._g_degraded.set(self._clock() - self._degraded_since)

    # -- connection lifecycle ----------------------------------------------
    def get(self):
        """The live connection, dialing (under backoff + breaker) if there
        is none. Raises a ConnectionError subclass when the dependency is
        down/refused."""
        with self._lock:
            if self._conn is not None:
                return self._conn
            return self._reconnect_locked()

    def prime(self):
        """Dial ONCE, no backoff: boot-time construction wants a fast
        loud failure (make_bus falls back to the memory backend on it),
        not a full reconnect schedule. Runs the on-reconnect hooks so a
        primed connection is indistinguishable from a reconnected one."""
        with self._lock:
            if self._conn is not None:
                return self._conn
            conn = self.factory()
            self.breaker.record_success()
            self.connects_total += 1
            self._c_reconnects.inc()
            self._exit_degraded_locked()
            self._conn = conn
            self._export_locked()
            for hook in self.on_reconnect:
                hook(conn)
            return conn

    def invalidate(self, exc: BaseException | None = None) -> None:
        """Tear the current connection down (observed dead elsewhere, e.g.
        a background reader). The next call()/get() reconnects."""
        with self._lock:
            self._fault_locked(exc)

    def _fault_locked(self, exc) -> None:
        self.faults_total += 1
        self.breaker.record_failure()
        self._enter_degraded_locked()
        conn, self._conn = self._conn, None
        if conn is not None:
            try:
                self._close(conn)
            except Exception:
                pass
        self._export_locked()
        if exc is not None:
            log.warning("%s: connection fault: %s", self.name, exc)

    def _reconnect_locked(self):
        """Dial a fresh connection under the backoff schedule. Every
        attempt passes through the breaker; an OPEN breaker fails fast."""
        last: BaseException | None = None
        deadline = self._clock() + self.policy.budget_s
        for i, delay in enumerate(
            backoff_delays(self.policy, self._rng)
        ):
            if not self.breaker.allow():
                raise CircuitOpenError(
                    f"{self.name}: circuit open (dependency down; "
                    f"retry after ~{self.breaker.reset_timeout_s:.1f}s)"
                )
            if i > 0 and not self.budget.try_spend():
                raise RetryBudgetExceeded(
                    f"{self.name}: retry budget exhausted"
                )
            try:
                conn = self.factory()
            except CONNECTION_FAULTS as e:
                last = e
                self.breaker.record_failure()
                self.faults_total += 1
                self._enter_degraded_locked()
                self._export_locked()
                if self._clock() + delay > deadline:
                    break
                self._sleep(delay)
                continue
            self.breaker.record_success()
            self.connects_total += 1
            self._c_reconnects.inc()
            self._exit_degraded_locked()
            self._conn = conn
            self._export_locked()
            for hook in self.on_reconnect:
                try:
                    hook(conn)
                except CONNECTION_FAULTS as e:
                    # Hook hit a dead connection: treat like a dial fault
                    # and keep backing off.
                    last = e
                    self._fault_locked(e)
                    break
            else:
                if self.connects_total > 1:
                    log.info(
                        "%s: reconnected (attempt %d)", self.name, i + 1
                    )
                return conn
        raise RetryBudgetExceeded(
            f"{self.name}: reconnect failed after backoff budget "
            f"({self.policy.max_retries} tries/{self.policy.budget_s}s): "
            f"{last}"
        ) from last

    # -- the operation surface ---------------------------------------------
    def call(self, fn, retry_op: bool = True):
        """Run fn(conn) with supervised reconnect. One retry per fresh
        connection, bounded overall by the backoff budget (reconnect
        itself does the waiting). With retry_op=False a connection fault
        still tears down + reconnects but the original exception is
        re-raised — callers whose contract already replays (at-least-once
        consumers) keep exactly-one-application semantics."""
        attempts = self.policy.max_retries + 1
        for attempt in range(attempts):
            conn = self.get()
            try:
                out = fn(conn)
            except CONNECTION_FAULTS as e:
                with self._lock:
                    # Only fault the connection fn actually used — a
                    # concurrent caller may already have reconnected.
                    if self._conn is conn:
                        self._fault_locked(e)
                if not retry_op or attempt + 1 >= attempts:
                    raise
                with self._lock:
                    # Unlocked, concurrent callers' += lost updates (the
                    # read-modify-write interleaves); snapshot() reads it
                    # under the lock and deserves the true count.
                    self.retries_total += 1
                self._c_retries.inc()
                continue
            self.breaker.record_success()
            with self._lock:
                self._export_locked()
            return out

    def close(self) -> None:
        with self._lock:
            conn, self._conn = self._conn, None
            if conn is not None:
                try:
                    self._close(conn)
                except Exception:
                    pass
        with _SUPERVISORS_LOCK:
            if _SUPERVISORS.get(self.name) is self:
                del _SUPERVISORS[self.name]

"""Metrics — counters, gauges, and latency histograms with a Prometheus-style
text exposition.

The reference has no metrics at all (SURVEY §5.5 — logging only); the
BASELINE.json throughput metric (orders/sec matched across N symbols) needs
first-class instrumentation. Kept dependency-free and cheap: a metric update
is a dict lookup + add under a lock shared per-registry.

Labeled series: `counter(name, labels={"stage": "ingress"})` returns one
child of a FAMILY registered under `name` — every child renders into the
same exposition family (`name{stage="ingress"} 3`), which is how per-stage
/ per-symbol series avoid the `stage_x_latency` name-mangling a flat
registry forces. A name is either flat or a family, never both.
"""

from __future__ import annotations

import bisect
import re
import threading
import time


def _label_str(labels: dict | None, extra: dict | None = None) -> str:
    """'{k="v",...}' with sorted keys (deterministic exposition), or ''.
    `extra` pairs (e.g. histogram `le`) render after the sorted labels,
    matching Prometheus convention."""
    items = sorted((labels or {}).items())
    if extra:
        items += list(extra.items())
    if not items:
        return ""
    return "{" + ",".join(f'{k}="{v}"' for k, v in items) + "}"


class Registry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}  # guarded by self._lock

    def counter(
        self, name: str, help: str = "", labels: dict | None = None
    ) -> "Counter":
        if labels is None:
            return self._get(name, lambda: Counter(name, help))
        fam = self._family(
            name, help, "counter", lambda lb: Counter(name, help, labels=lb)
        )
        return fam.child(labels)

    def gauge(
        self, name: str, help: str = "", labels: dict | None = None
    ) -> "Gauge":
        if labels is None:
            return self._get(name, lambda: Gauge(name, help))
        fam = self._family(name, help, "gauge", lambda lb: Gauge(name, help, labels=lb))
        return fam.child(labels)

    def histogram(
        self,
        name: str,
        help: str = "",
        buckets: tuple = None,
        labels: dict | None = None,
    ) -> "Histogram":
        if labels is None:
            return self._get(name, lambda: Histogram(name, help, buckets))
        fam = self._family(
            name, help, "histogram",
            lambda lb: Histogram(name, help, buckets, labels=lb),
        )
        return fam.child(labels)

    def callback_gauge(
        self, name: str, help: str, fn, labels: dict | None = None
    ) -> "CallbackGauge":
        """A gauge whose value is read from `fn()` at scrape time — for
        state that already lives somewhere (spill depth, breaker state)
        and would otherwise need push updates on every change. Re-
        registering the same name rebinds the callback (components are
        rebuilt across service restarts in tests). With `labels`, the
        name is a family like the other metric kinds (one child per
        label set, e.g. per-subsystem HBM residency gauges)."""
        if labels is None:
            g = self._get(name, lambda: CallbackGauge(name, help, fn))
            g._fn = fn
            return g
        fam = self._family(
            name, help, "gauge",
            lambda lb: CallbackGauge(name, help, fn, labels=lb),
        )
        g = fam.child(labels)
        g._fn = fn
        return g

    def _get(self, name, factory):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = factory()
            return m

    def _family(self, name, help, typ, child_factory) -> "Family":
        fam = self._get(name, lambda: Family(name, help, typ, child_factory))
        if not isinstance(fam, Family):
            raise ValueError(
                f"metric {name!r} is already registered WITHOUT labels; a "
                "name is either a flat metric or a labeled family, not both"
            )
        return fam

    def render(self) -> str:
        """Prometheus text-format-ish exposition of every metric."""
        with self._lock:
            metrics = list(self._metrics.values())
        return "\n".join(m.render() for m in metrics) + "\n"

    def snapshot(self) -> dict:
        with self._lock:
            return {
                name: m.value() for name, m in self._metrics.items()
            }


class Family:
    """All children of one labeled metric name: one HELP/TYPE header, one
    sample block per label set. child() is get-or-create keyed by the
    sorted label items, so re-registering the same labels returns the
    SAME child (modules grab their series at import time, tests rebuild
    components — both must land on one series)."""

    def __init__(self, name: str, help: str, typ: str, child_factory):
        self.name = name
        self.help = help
        self.typ = typ
        self._factory = child_factory
        self._children: dict[tuple, object] = {}  # guarded by self._lock
        self._lock = threading.Lock()

    def child(self, labels: dict):
        key = tuple(sorted((str(k), str(v)) for k, v in labels.items()))
        with self._lock:
            c = self._children.get(key)
            if c is None:
                c = self._children[key] = self._factory(dict(key))
            return c

    def children(self) -> list:
        with self._lock:
            return list(self._children.values())

    def value(self) -> dict:
        return {
            _label_str(c.labels): c.value() for c in self.children()
        }

    def render(self) -> str:
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} {self.typ}",
        ]
        for c in self.children():
            lines.extend(c.render_samples())
        return "\n".join(lines)


class Counter:
    def __init__(self, name: str, help: str = "", labels: dict | None = None):
        self.name = name
        self.help = help
        self.labels = labels
        self._v = 0  # guarded by self._lock
        self._lock = threading.Lock()

    def inc(self, by: int = 1) -> None:
        with self._lock:
            self._v += by

    def value(self):
        with self._lock:
            return self._v

    def render_samples(self) -> list[str]:
        return [f"{self.name}{_label_str(self.labels)} {self.value()}"]

    def render(self) -> str:
        return (
            f"# HELP {self.name} {self.help}\n# TYPE {self.name} counter\n"
            + "\n".join(self.render_samples())
        )


class Gauge:
    def __init__(self, name: str, help: str = "", labels: dict | None = None):
        self.name = name
        self.help = help
        self.labels = labels
        self._v = 0.0  # guarded by self._lock
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._v = v

    def value(self):
        with self._lock:
            return self._v

    def render_samples(self) -> list[str]:
        return [f"{self.name}{_label_str(self.labels)} {self.value()}"]

    def render(self) -> str:
        return (
            f"# HELP {self.name} {self.help}\n# TYPE {self.name} gauge\n"
            + "\n".join(self.render_samples())
        )


class CallbackGauge:
    """Gauge evaluated at scrape time (see Registry.callback_gauge). A
    failing callback scrapes as 0 rather than breaking the whole /metrics
    exposition."""

    def __init__(self, name: str, help: str, fn, labels: dict | None = None):
        self.name = name
        self.help = help
        self.labels = labels
        self._fn = fn

    def value(self):
        try:
            return float(self._fn())
        except Exception:
            return 0.0

    def render_samples(self) -> list[str]:
        return [f"{self.name}{_label_str(self.labels)} {self.value()}"]

    def render(self) -> str:
        return (
            f"# HELP {self.name} {self.help}\n# TYPE {self.name} gauge\n"
            + "\n".join(self.render_samples())
        )


_DEFAULT_BUCKETS = (
    1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4,
    1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2, 0.1, 0.25, 0.5, 1.0, 2.5,
)


class Histogram:
    """Fixed-bucket histogram (seconds by convention) with quantile
    estimation by linear interpolation inside the winning bucket."""

    def __init__(
        self,
        name: str,
        help: str = "",
        buckets: tuple = None,
        labels: dict | None = None,
    ):
        self.name = name
        self.help = help
        self.labels = labels
        self.buckets = tuple(buckets or _DEFAULT_BUCKETS)
        self._counts = [0] * (len(self.buckets) + 1)  # guarded by self._lock
        self._sum = 0.0  # guarded by self._lock
        self._n = 0  # guarded by self._lock
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._n += 1

    def time(self) -> "_Timer":
        return _Timer(self)

    def value(self) -> dict:
        with self._lock:
            return {
                "count": self._n,
                "sum": self._sum,
                "mean": self._sum / self._n if self._n else 0.0,
                "p50": self._quantile_locked(0.50),
                "p95": self._quantile_locked(0.95),
                "p99": self._quantile_locked(0.99),
            }

    def quantile(self, q: float) -> float:
        with self._lock:
            return self._quantile_locked(q)

    def percentiles(self, qs=(0.5, 0.9, 0.99)) -> dict:
        """{"p50": ..., "p90": ..., ...} for the requested quantiles,
        read under ONE lock acquisition (a concurrent observe between
        per-quantile reads would make e.g. p90 < p50 possible). The
        latency reports (scripts/soak.py, bench --latency) use this."""
        with self._lock:
            return {
                f"p{q * 100:g}": self._quantile_locked(q) for q in qs
            }

    def _quantile_locked(self, q: float) -> float:
        if self._n == 0:
            return 0.0
        target = q * self._n
        cum = 0
        for i, c in enumerate(self._counts):
            if cum + c >= target:
                lo = self.buckets[i - 1] if i > 0 else 0.0
                hi = (
                    self.buckets[i]
                    if i < len(self.buckets)
                    else self.buckets[-1] * 2
                )
                frac = (target - cum) / c if c else 0.0
                return lo + (hi - lo) * frac
            cum += c
        return self.buckets[-1] * 2

    def render_samples(self) -> list[str]:
        # counts/sum/n must come from ONE lock acquisition: a concurrent
        # observe between reads would make the +Inf line smaller than a
        # finite bucket's cumulative count (invalid Prometheus data).
        with self._lock:
            counts = list(self._counts)
            total = self._n
            total_sum = self._sum
        lines = []
        cum = 0
        for b, c in zip(self.buckets, counts):
            cum += c
            ls = _label_str(self.labels, {"le": b})
            lines.append(f"{self.name}_bucket{ls} {cum}")
        ls = _label_str(self.labels, {"le": "+Inf"})
        lines.append(f"{self.name}_bucket{ls} {total}")
        base = _label_str(self.labels)
        lines.append(f"{self.name}_sum{base} {total_sum}")
        lines.append(f"{self.name}_count{base} {total}")
        return lines

    def render(self) -> str:
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} histogram",
        ]
        lines.extend(self.render_samples())
        return "\n".join(lines)


class _Timer:
    """Context manager recording one observation; exposes `elapsed` after
    exit so callers reuse the same clock reading."""

    elapsed: float = 0.0

    def __init__(self, hist: Histogram):
        self._hist = hist

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.elapsed = time.perf_counter() - self._t0
        self._hist.observe(self.elapsed)
        return False


# Process-global default registry (modules grab metrics from here).
REGISTRY = Registry()


# -- exposition parse + merge (fleet federation) -----------------------------
#
# The FleetAggregator (gome_tpu.obs.fleet) scrapes N member processes'
# /metrics text and serves ONE merged exposition: counters sum, same-bucket
# histograms merge, gauges union under a new `proc` label. The parser below
# reads exactly the dialect Registry.render() writes (HELP line, TYPE line,
# sample lines with sorted labels and `le` last), so parse -> render is
# byte-identical — the lossless-merge contract tests pin.

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})? (.+)$"
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"')


class Sample:
    """One exposition sample line, structured. `labels` preserves the
    source order (the registry writes sorted keys with `le` appended
    last, so re-rendering in insertion order reproduces the line);
    `value_str` keeps the exact source text so a parse -> render round
    trip never reformats numbers (`3` stays `3`, `0.0` stays `0.0`)."""

    __slots__ = ("name", "labels", "value_str")

    def __init__(self, name: str, labels: dict, value_str: str):
        self.name = name
        self.labels = labels
        self.value_str = value_str

    @property
    def value(self) -> float:
        return float(self.value_str)

    def line(self) -> str:
        if not self.labels:
            return f"{self.name} {self.value_str}"
        inner = ",".join(f'{k}="{v}"' for k, v in self.labels.items())
        return f"{self.name}{{{inner}}} {self.value_str}"


class ParsedFamily:
    """One metric family parsed back from exposition text: the HELP/TYPE
    header plus its sample lines (for histograms that includes the
    `_bucket`/`_sum`/`_count` suffixed samples)."""

    def __init__(self, name: str, help: str = "", typ: str = "untyped"):
        self.name = name
        self.help = help
        self.typ = typ
        self.samples: list[Sample] = []

    def render(self) -> str:
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} {self.typ}",
        ]
        lines.extend(s.line() for s in self.samples)
        return "\n".join(lines)


def parse_exposition(text: str) -> dict[str, ParsedFamily]:
    """Parse Prometheus text exposition into {family name: ParsedFamily},
    preserving family and sample order. Sample lines attach to the most
    recent HELP/TYPE header (which is how histogram `_bucket` suffixes
    stay with their base family); a sample before any header is a format
    error."""
    families: dict[str, ParsedFamily] = {}
    current: ParsedFamily | None = None
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            parts = line.split(" ", 3)
            name = parts[2]
            fam = families.get(name)
            if fam is None:
                fam = families[name] = ParsedFamily(name)
            fam.help = parts[3] if len(parts) > 3 else ""
            current = fam
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ", 3)
            name = parts[2]
            fam = families.get(name)
            if fam is None:
                fam = families[name] = ParsedFamily(name)
            fam.typ = parts[3] if len(parts) > 3 else "untyped"
            current = fam
            continue
        if line.startswith("#"):
            continue  # comment — not part of the registry dialect
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"unparseable exposition line {lineno}: {line!r}")
        if current is None:
            raise ValueError(
                f"exposition line {lineno} has no preceding HELP/TYPE "
                f"header: {line!r}"
            )
        name, labelstr, value_str = m.groups()
        labels = (
            dict(_LABEL_RE.findall(labelstr)) if labelstr else {}
        )
        current.samples.append(Sample(name, labels, value_str))
    return families


def render_exposition(families: dict[str, ParsedFamily]) -> str:
    """Re-render parsed families in order — the inverse of
    parse_exposition and byte-identical to the Registry.render() dialect."""
    return "\n".join(f.render() for f in families.values()) + "\n"


def _fmt_merged(total: float, value_strs: list[str]) -> str:
    """Render a merged numeric total in the narrowest format the inputs
    used: all-int inputs stay int (`3`), any float input renders via
    repr (`0.0`) — so merged counters keep the counter dialect."""
    if all(re.fullmatch(r"-?\d+", v) for v in value_strs):
        return str(int(total))
    return repr(float(total))


def _label_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


def _merge_counter(name: str, per_member: list[ParsedFamily]) -> ParsedFamily:
    out = ParsedFamily(name, per_member[0].help, "counter")
    order: list[tuple] = []
    acc: dict[tuple, tuple[str, dict, float, list]] = {}
    for fam in per_member:
        for s in fam.samples:
            key = (s.name, _label_key(s.labels))
            if key not in acc:
                order.append(key)
                acc[key] = (s.name, s.labels, s.value, [s.value_str])
            else:
                n, lb, tot, strs = acc[key]
                acc[key] = (n, lb, tot + s.value, strs + [s.value_str])
    for key in order:
        n, lb, tot, strs = acc[key]
        out.samples.append(Sample(n, lb, _fmt_merged(tot, strs)))
    return out


def _merge_gauge(
    name: str, members: list[tuple[str, ParsedFamily]]
) -> ParsedFamily:
    """Gauges don't sum meaningfully across processes (each is a local
    reading), so member samples union under a new `proc` label — labels
    re-sorted so `proc` lands in deterministic exposition position."""
    out = ParsedFamily(name, members[0][1].help, members[0][1].typ)
    for proc, fam in members:
        for s in fam.samples:
            labels = dict(sorted({**s.labels, "proc": proc}.items()))
            out.samples.append(Sample(s.name, labels, s.value_str))
    return out


def _merge_histogram(
    name: str, per_member: list[ParsedFamily]
) -> ParsedFamily:
    """Merge same-bucket histograms: per base label set (labels minus
    `le`), the cumulative bucket counts, `_sum`, and `_count` sum across
    members. Members whose `le` sequences differ can't merge losslessly —
    that's a hard ValueError, not a silent drop."""
    out = ParsedFamily(name, per_member[0].help, "histogram")
    # base label key -> {"les": [...], "buckets": {le: total},
    #                    "sum": (tot, strs), "count": (tot, strs)}
    order: list[tuple] = []
    acc: dict[tuple, dict] = {}
    for fam in per_member:
        per_base_les: dict[tuple, list[str]] = {}
        for s in fam.samples:
            if s.name == f"{name}_bucket":
                base = {k: v for k, v in s.labels.items() if k != "le"}
                key = _label_key(base)
                per_base_les.setdefault(key, []).append(s.labels["le"])
                ent = acc.get(key)
                if ent is None:
                    order.append(key)
                    ent = acc[key] = {
                        "base": base, "les": None, "buckets": {},
                        "sum": (0.0, []), "count": (0, []),
                    }
                le = s.labels["le"]
                ent["buckets"][le] = ent["buckets"].get(le, 0) + s.value
            elif s.name in (f"{name}_sum", f"{name}_count"):
                key = _label_key(s.labels)
                ent = acc.get(key)
                if ent is None:
                    order.append(key)
                    ent = acc[key] = {
                        "base": s.labels, "les": None, "buckets": {},
                        "sum": (0.0, []), "count": (0, []),
                    }
                which = "sum" if s.name.endswith("_sum") else "count"
                tot, strs = ent[which]
                ent[which] = (tot + s.value, strs + [s.value_str])
            else:
                raise ValueError(
                    f"histogram family {name!r} has unexpected sample "
                    f"{s.name!r}"
                )
        for key, les in per_base_les.items():
            ent = acc[key]
            if ent["les"] is None:
                ent["les"] = les
            elif ent["les"] != les:
                raise ValueError(
                    f"histogram {name!r} bucket mismatch across members: "
                    f"{ent['les']} vs {les} — same-bucket histograms only"
                )
    for key in order:
        ent = acc[key]
        base = ent["base"]
        for le in ent["les"] or []:
            labels = dict(base)
            labels["le"] = le  # after the sorted base labels, registry-style
            out.samples.append(
                Sample(f"{name}_bucket", labels, str(int(ent["buckets"][le])))
            )
        tot, strs = ent["sum"]
        out.samples.append(Sample(f"{name}_sum", dict(base), _fmt_merged(tot, strs)))
        tot, strs = ent["count"]
        out.samples.append(
            Sample(f"{name}_count", dict(base), _fmt_merged(tot, strs))
        )
    return out


def merge_expositions(
    members: dict[str, str | dict]
) -> dict[str, ParsedFamily]:
    """Merge N member expositions into one fleet view: counters SUM per
    label set, histograms merge per base label set (identical bucket
    sequences required), gauges (and untyped families) UNION under a new
    `proc="<member>"` label. `members` maps member name -> exposition
    text (or an already-parsed family dict). Conflicting TYPEs for one
    family name across members raise ValueError — a lossy merge is a
    bug, never a best-effort."""
    parsed: list[tuple[str, dict[str, ParsedFamily]]] = [
        (proc, parse_exposition(fams) if isinstance(fams, str) else fams)
        for proc, fams in members.items()
    ]
    name_order: list[str] = []
    seen: set[str] = set()
    for _, fams in parsed:
        for name in fams:
            if name not in seen:
                seen.add(name)
                name_order.append(name)
    out: dict[str, ParsedFamily] = {}
    for name in name_order:
        present = [(proc, fams[name]) for proc, fams in parsed if name in fams]
        typs = {fam.typ for _, fam in present}
        if len(typs) > 1:
            raise ValueError(
                f"family {name!r} has conflicting types across members: "
                f"{sorted(typs)}"
            )
        typ = typs.pop()
        if typ == "counter":
            out[name] = _merge_counter(name, [fam for _, fam in present])
        elif typ == "histogram":
            out[name] = _merge_histogram(name, [fam for _, fam in present])
        else:
            out[name] = _merge_gauge(name, present)
    return out


def family_total(fam: ParsedFamily) -> float:
    """One scalar per family for the lossless-merge audit: histograms
    total their `_count` samples, counters/gauges total every sample.
    sum(member totals) == merged total is the invariant tests assert."""
    if fam.typ == "histogram":
        return sum(
            s.value for s in fam.samples if s.name == f"{fam.name}_count"
        )
    return sum(s.value for s in fam.samples)

"""Metrics — counters, gauges, and latency histograms with a Prometheus-style
text exposition.

The reference has no metrics at all (SURVEY §5.5 — logging only); the
BASELINE.json throughput metric (orders/sec matched across N symbols) needs
first-class instrumentation. Kept dependency-free and cheap: a metric update
is a dict lookup + add under a lock shared per-registry.

Labeled series: `counter(name, labels={"stage": "ingress"})` returns one
child of a FAMILY registered under `name` — every child renders into the
same exposition family (`name{stage="ingress"} 3`), which is how per-stage
/ per-symbol series avoid the `stage_x_latency` name-mangling a flat
registry forces. A name is either flat or a family, never both.
"""

from __future__ import annotations

import bisect
import threading
import time


def _label_str(labels: dict | None, extra: dict | None = None) -> str:
    """'{k="v",...}' with sorted keys (deterministic exposition), or ''.
    `extra` pairs (e.g. histogram `le`) render after the sorted labels,
    matching Prometheus convention."""
    items = sorted((labels or {}).items())
    if extra:
        items += list(extra.items())
    if not items:
        return ""
    return "{" + ",".join(f'{k}="{v}"' for k, v in items) + "}"


class Registry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}  # guarded by self._lock

    def counter(
        self, name: str, help: str = "", labels: dict | None = None
    ) -> "Counter":
        if labels is None:
            return self._get(name, lambda: Counter(name, help))
        fam = self._family(
            name, help, "counter", lambda lb: Counter(name, help, labels=lb)
        )
        return fam.child(labels)

    def gauge(
        self, name: str, help: str = "", labels: dict | None = None
    ) -> "Gauge":
        if labels is None:
            return self._get(name, lambda: Gauge(name, help))
        fam = self._family(name, help, "gauge", lambda lb: Gauge(name, help, labels=lb))
        return fam.child(labels)

    def histogram(
        self,
        name: str,
        help: str = "",
        buckets: tuple = None,
        labels: dict | None = None,
    ) -> "Histogram":
        if labels is None:
            return self._get(name, lambda: Histogram(name, help, buckets))
        fam = self._family(
            name, help, "histogram",
            lambda lb: Histogram(name, help, buckets, labels=lb),
        )
        return fam.child(labels)

    def callback_gauge(
        self, name: str, help: str, fn, labels: dict | None = None
    ) -> "CallbackGauge":
        """A gauge whose value is read from `fn()` at scrape time — for
        state that already lives somewhere (spill depth, breaker state)
        and would otherwise need push updates on every change. Re-
        registering the same name rebinds the callback (components are
        rebuilt across service restarts in tests). With `labels`, the
        name is a family like the other metric kinds (one child per
        label set, e.g. per-subsystem HBM residency gauges)."""
        if labels is None:
            g = self._get(name, lambda: CallbackGauge(name, help, fn))
            g._fn = fn
            return g
        fam = self._family(
            name, help, "gauge",
            lambda lb: CallbackGauge(name, help, fn, labels=lb),
        )
        g = fam.child(labels)
        g._fn = fn
        return g

    def _get(self, name, factory):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = factory()
            return m

    def _family(self, name, help, typ, child_factory) -> "Family":
        fam = self._get(name, lambda: Family(name, help, typ, child_factory))
        if not isinstance(fam, Family):
            raise ValueError(
                f"metric {name!r} is already registered WITHOUT labels; a "
                "name is either a flat metric or a labeled family, not both"
            )
        return fam

    def render(self) -> str:
        """Prometheus text-format-ish exposition of every metric."""
        with self._lock:
            metrics = list(self._metrics.values())
        return "\n".join(m.render() for m in metrics) + "\n"

    def snapshot(self) -> dict:
        with self._lock:
            return {
                name: m.value() for name, m in self._metrics.items()
            }


class Family:
    """All children of one labeled metric name: one HELP/TYPE header, one
    sample block per label set. child() is get-or-create keyed by the
    sorted label items, so re-registering the same labels returns the
    SAME child (modules grab their series at import time, tests rebuild
    components — both must land on one series)."""

    def __init__(self, name: str, help: str, typ: str, child_factory):
        self.name = name
        self.help = help
        self.typ = typ
        self._factory = child_factory
        self._children: dict[tuple, object] = {}  # guarded by self._lock
        self._lock = threading.Lock()

    def child(self, labels: dict):
        key = tuple(sorted((str(k), str(v)) for k, v in labels.items()))
        with self._lock:
            c = self._children.get(key)
            if c is None:
                c = self._children[key] = self._factory(dict(key))
            return c

    def children(self) -> list:
        with self._lock:
            return list(self._children.values())

    def value(self) -> dict:
        return {
            _label_str(c.labels): c.value() for c in self.children()
        }

    def render(self) -> str:
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} {self.typ}",
        ]
        for c in self.children():
            lines.extend(c.render_samples())
        return "\n".join(lines)


class Counter:
    def __init__(self, name: str, help: str = "", labels: dict | None = None):
        self.name = name
        self.help = help
        self.labels = labels
        self._v = 0  # guarded by self._lock
        self._lock = threading.Lock()

    def inc(self, by: int = 1) -> None:
        with self._lock:
            self._v += by

    def value(self):
        with self._lock:
            return self._v

    def render_samples(self) -> list[str]:
        return [f"{self.name}{_label_str(self.labels)} {self.value()}"]

    def render(self) -> str:
        return (
            f"# HELP {self.name} {self.help}\n# TYPE {self.name} counter\n"
            + "\n".join(self.render_samples())
        )


class Gauge:
    def __init__(self, name: str, help: str = "", labels: dict | None = None):
        self.name = name
        self.help = help
        self.labels = labels
        self._v = 0.0  # guarded by self._lock
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._v = v

    def value(self):
        with self._lock:
            return self._v

    def render_samples(self) -> list[str]:
        return [f"{self.name}{_label_str(self.labels)} {self.value()}"]

    def render(self) -> str:
        return (
            f"# HELP {self.name} {self.help}\n# TYPE {self.name} gauge\n"
            + "\n".join(self.render_samples())
        )


class CallbackGauge:
    """Gauge evaluated at scrape time (see Registry.callback_gauge). A
    failing callback scrapes as 0 rather than breaking the whole /metrics
    exposition."""

    def __init__(self, name: str, help: str, fn, labels: dict | None = None):
        self.name = name
        self.help = help
        self.labels = labels
        self._fn = fn

    def value(self):
        try:
            return float(self._fn())
        except Exception:
            return 0.0

    def render_samples(self) -> list[str]:
        return [f"{self.name}{_label_str(self.labels)} {self.value()}"]

    def render(self) -> str:
        return (
            f"# HELP {self.name} {self.help}\n# TYPE {self.name} gauge\n"
            + "\n".join(self.render_samples())
        )


_DEFAULT_BUCKETS = (
    1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4,
    1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2, 0.1, 0.25, 0.5, 1.0, 2.5,
)


class Histogram:
    """Fixed-bucket histogram (seconds by convention) with quantile
    estimation by linear interpolation inside the winning bucket."""

    def __init__(
        self,
        name: str,
        help: str = "",
        buckets: tuple = None,
        labels: dict | None = None,
    ):
        self.name = name
        self.help = help
        self.labels = labels
        self.buckets = tuple(buckets or _DEFAULT_BUCKETS)
        self._counts = [0] * (len(self.buckets) + 1)  # guarded by self._lock
        self._sum = 0.0  # guarded by self._lock
        self._n = 0  # guarded by self._lock
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._n += 1

    def time(self) -> "_Timer":
        return _Timer(self)

    def value(self) -> dict:
        with self._lock:
            return {
                "count": self._n,
                "sum": self._sum,
                "mean": self._sum / self._n if self._n else 0.0,
                "p50": self._quantile_locked(0.50),
                "p95": self._quantile_locked(0.95),
                "p99": self._quantile_locked(0.99),
            }

    def quantile(self, q: float) -> float:
        with self._lock:
            return self._quantile_locked(q)

    def percentiles(self, qs=(0.5, 0.9, 0.99)) -> dict:
        """{"p50": ..., "p90": ..., ...} for the requested quantiles,
        read under ONE lock acquisition (a concurrent observe between
        per-quantile reads would make e.g. p90 < p50 possible). The
        latency reports (scripts/soak.py, bench --latency) use this."""
        with self._lock:
            return {
                f"p{q * 100:g}": self._quantile_locked(q) for q in qs
            }

    def _quantile_locked(self, q: float) -> float:
        if self._n == 0:
            return 0.0
        target = q * self._n
        cum = 0
        for i, c in enumerate(self._counts):
            if cum + c >= target:
                lo = self.buckets[i - 1] if i > 0 else 0.0
                hi = (
                    self.buckets[i]
                    if i < len(self.buckets)
                    else self.buckets[-1] * 2
                )
                frac = (target - cum) / c if c else 0.0
                return lo + (hi - lo) * frac
            cum += c
        return self.buckets[-1] * 2

    def render_samples(self) -> list[str]:
        # counts/sum/n must come from ONE lock acquisition: a concurrent
        # observe between reads would make the +Inf line smaller than a
        # finite bucket's cumulative count (invalid Prometheus data).
        with self._lock:
            counts = list(self._counts)
            total = self._n
            total_sum = self._sum
        lines = []
        cum = 0
        for b, c in zip(self.buckets, counts):
            cum += c
            ls = _label_str(self.labels, {"le": b})
            lines.append(f"{self.name}_bucket{ls} {cum}")
        ls = _label_str(self.labels, {"le": "+Inf"})
        lines.append(f"{self.name}_bucket{ls} {total}")
        base = _label_str(self.labels)
        lines.append(f"{self.name}_sum{base} {total_sum}")
        lines.append(f"{self.name}_count{base} {total}")
        return lines

    def render(self) -> str:
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} histogram",
        ]
        lines.extend(self.render_samples())
        return "\n".join(lines)


class _Timer:
    """Context manager recording one observation; exposes `elapsed` after
    exit so callers reuse the same clock reading."""

    elapsed: float = 0.0

    def __init__(self, hist: Histogram):
        self._hist = hist

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.elapsed = time.perf_counter() - self._t0
        self._hist.observe(self.elapsed)
        return False


# Process-global default registry (modules grab metrics from here).
REGISTRY = Registry()

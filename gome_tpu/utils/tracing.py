"""Profiling hooks — jax.profiler integration (SURVEY §5.1: the reference
has no tracing/profiling at all; the TPU build gets device-level traces
nearly for free and exposes them as first-class knobs).

  trace(dir)        — context manager around jax.profiler.trace; produces a
                      TensorBoard-loadable trace of every device op inside.
  annotate(name)    — TraceAnnotation wrapper for host-side phases so batch
                      packing/decoding shows up on the trace alongside XLA
                      work.
  maybe_trace(dir)  — no-op unless dir is set (config/env-driven).
"""

from __future__ import annotations

import contextlib


@contextlib.contextmanager
def trace(log_dir: str):
    import jax

    with jax.profiler.trace(log_dir):
        yield


def annotate(name: str):
    import jax

    return jax.profiler.TraceAnnotation(name)


@contextlib.contextmanager
def maybe_trace(log_dir: str | None):
    if not log_dir:
        yield
        return
    with trace(log_dir):
        yield

"""Deterministic, seeded fault injection for crash-consistency testing.

The registry follows the tracer/journal contract (see utils/trace.py):
a module-level ``FAULTS`` singleton that is disabled by default, where
the hot-path hook — ``FAULTS.fire("point")`` — costs one attribute
check and ZERO allocations when no plan is installed.  Production code
threads named injection points through the service and persist layers;
tests and ``scripts/chaos.py`` arm the registry with a ``FaultPlan``
(seed + schedule) so every crash is a reproducible artifact.

Injection points are plain strings.  The catalogue lives in
ARCHITECTURE.md ("Crash consistency & fault injection"); the load-bearing
ones are:

    consumer.frame    -- fired once per consumed order message; ``exit``
                         mode here is the classic kill-between-frames.
    consumer.commit   -- fired between matchfeed publish and order-queue
                         commit: the at-least-once window.
    filelog.append    -- fired at the top of FileQueue.publish; ``torn``
                         mode writes a prefix of the record and hard-exits.
    filelog.offset    -- fired in FileQueue._write_offset; ``torn`` mode
                         leaves a truncated decimal in the sidecar.
    snapshot.rename   -- fired before SnapshotStore's atomic rename;
                         ``exit`` crashes pre-publish, ``torn`` publishes
                         a snapshot with a truncated manifest.

Trigger semantics per spec: the hit counter for a point is 1-based and
monotonic for the life of the plan; a spec triggers when the hit is in
``at``, or ``every`` divides it, or a seeded coin with ``prob`` comes up.
``times`` bounds how often a spec may trigger (-1 = unbounded).  Modes:

    exit   -- os._exit(EXIT_CODE): a real, unclean process death.  No
              atexit handlers, no flushes — the point.
    raise  -- raise FaultInjected (for in-process tests).
    torn   -- return a seeded positive int; the call site interprets it
              as a cut position (``cut % len(payload)``) and performs
              its own torn write + hard exit.  fire() returning 0 means
              "no fault"; call sites must treat 0 as the clean path.
    call   -- invoke a handler registered via FAULTS.handler(name, fn);
              ties counted points to environmental faults like broker
              kill_connections or RESP restarts.

Determinism: every spec gets its own ``random.Random`` seeded from
``plan.seed ^ crc32(point:index)`` — stable across processes (unlike
``hash``, which is salted per interpreter).
"""

from __future__ import annotations

import json
import os
import random
import threading
import zlib
from dataclasses import dataclass
from typing import Any, Callable

# Chaos children exit with this code on an injected death so the parent
# can tell an injected kill from a genuine crash (which would be a bug).
EXIT_CODE = 86

_MODES = ("exit", "raise", "torn", "call")


class FaultInjected(RuntimeError):
    """Raised by ``raise``-mode faults."""


@dataclass(frozen=True)
class FaultSpec:
    """One scheduled fault at one named injection point."""

    point: str
    mode: str = "exit"
    at: tuple[int, ...] = ()
    every: int = 0
    prob: float = 0.0
    times: int = -1
    handler: str = ""

    def __post_init__(self) -> None:
        if self.mode not in _MODES:
            raise ValueError(f"fault mode must be one of {_MODES}: {self.mode!r}")
        if self.mode == "call" and not self.handler:
            raise ValueError("call-mode fault needs a handler name")
        if not self.point:
            raise ValueError("fault point must be non-empty")

    def to_dict(self) -> dict[str, Any]:
        return {
            "point": self.point,
            "mode": self.mode,
            "at": list(self.at),
            "every": self.every,
            "prob": self.prob,
            "times": self.times,
            "handler": self.handler,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "FaultSpec":
        return cls(
            point=str(d["point"]),
            mode=str(d.get("mode", "exit")),
            at=tuple(int(x) for x in d.get("at", ())),
            every=int(d.get("every", 0)),
            prob=float(d.get("prob", 0.0)),
            times=int(d.get("times", -1)),
            handler=str(d.get("handler", "")),
        )


@dataclass(frozen=True)
class FaultPlan:
    """A reproducible fault schedule: seed + specs.

    The whole plan round-trips through JSON so a chaos run can pin the
    exact schedule it executed into its verdict artifact.
    """

    seed: int = 0
    faults: tuple[FaultSpec, ...] = ()

    def to_dict(self) -> dict[str, Any]:
        return {"seed": self.seed, "faults": [f.to_dict() for f in self.faults]}

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "FaultPlan":
        return cls(
            seed=int(d.get("seed", 0)),
            faults=tuple(FaultSpec.from_dict(f) for f in d.get("faults", ())),
        )

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True)

    @classmethod
    def from_json(cls, s: str) -> "FaultPlan":
        return cls.from_dict(json.loads(s))


@dataclass
class _Armed:
    """Mutable per-spec trigger state (exists only while a plan is live)."""

    spec: FaultSpec
    rng: random.Random
    fired: int = 0


class FaultRegistry:
    """Module singleton; see module docstring for the contract.

    ``fire(point) -> int`` returns 0 on the clean path.  A positive
    return is a torn-mode cut hint.  ``exit`` mode never returns.
    """

    def __init__(self) -> None:
        # The ONLY attribute the disabled hot path reads — see fire().
        self.enabled = False  # guarded by self._lock
        self._lock = threading.Lock()
        self._plan: FaultPlan | None = None  # guarded by self._lock
        self._by_point: dict[str, list[_Armed]] = {}  # guarded by self._lock
        self._hits: dict[str, int] = {}  # guarded by self._lock
        self._fired_log: list[dict[str, Any]] = []  # guarded by self._lock
        self._handlers: dict[str, Callable[[], None]] = {}  # guarded by self._lock
        # Injectable for tests; chaos children die through this.
        self._exit: Callable[[int], None] = os._exit

    # -- arming ---------------------------------------------------------

    def install(self, plan: FaultPlan) -> None:
        with self._lock:
            self._plan = plan
            self._by_point = {}
            self._hits = {}
            self._fired_log = []
            for i, spec in enumerate(plan.faults):
                salt = zlib.crc32(f"{spec.point}:{i}".encode())
                armed = _Armed(spec=spec, rng=random.Random(plan.seed ^ salt))
                self._by_point.setdefault(spec.point, []).append(armed)
            self.enabled = True

    def disable(self) -> None:
        with self._lock:
            self.enabled = False
            self._plan = None
            self._by_point = {}

    def handler(self, name: str, fn: Callable[[], None]) -> None:
        """Register (or replace) a call-mode handler. Safe while disabled."""
        with self._lock:
            self._handlers[name] = fn

    # -- hot path -------------------------------------------------------

    def fire(self, point: str) -> int:
        # gomelint: disable=GL402 — benign stale read: a bool load is one
        # bytecode under the GIL (merely stale, never torn), and install()
        # happens-before the first armed fire in every harness.
        if not self.enabled:  # gomelint: hotpath  # gomelint: disable=GL402
            return 0
        return self._fire_armed(point)

    def _fire_armed(self, point: str) -> int:
        with self._lock:
            hit = self._hits.get(point, 0) + 1
            self._hits[point] = hit
            pending: list[_Armed] = []
            for armed in self._by_point.get(point, ()):
                spec = armed.spec
                if spec.times >= 0 and armed.fired >= spec.times:
                    continue
                trig = (
                    hit in spec.at
                    or (spec.every > 0 and hit % spec.every == 0)
                    or (spec.prob > 0.0 and armed.rng.random() < spec.prob)
                )
                if trig:
                    armed.fired += 1
                    self._fired_log.append(
                        {"point": point, "mode": spec.mode, "hit": hit}
                    )
                    pending.append(armed)
            handlers = [
                self._handlers.get(a.spec.handler)
                for a in pending
                if a.spec.mode == "call"
            ]
        # Act outside the lock: handlers may call back into the bus, and
        # exit/raise must not hold it.
        cut = 0
        for armed in pending:
            mode = armed.spec.mode
            if mode == "exit":
                self._exit(EXIT_CODE)
            elif mode == "raise":
                raise FaultInjected(f"{point} (hit {hit})")
            elif mode == "torn":
                cut = 1 + armed.rng.randrange(1 << 20)
        for fn in handlers:
            if fn is not None:
                fn()
        return cut

    # -- helpers for call sites ----------------------------------------

    def hard_exit(self) -> None:
        """Die now, uncleanly (used by torn-write call sites after the cut)."""
        self._exit(EXIT_CODE)

    # -- introspection --------------------------------------------------

    def report(self) -> dict[str, Any]:
        with self._lock:
            return {
                "enabled": self.enabled,
                "plan": self._plan.to_dict() if self._plan is not None else None,
                "hits": dict(self._hits),
                "fired": list(self._fired_log),
            }


FAULTS = FaultRegistry()

// Native host-path operations for the frame pipeline: string interning and
// pre-pool admission.
//
// Why: the consumer's host path is the end-to-end throughput ceiling once
// the device and the fetch overlap (engine/pipeline.py). Profiling the
// 262K-order frame shape shows ~2.6 us/order spent in two pure-Python
// loops: per-order (symbol, uuid, oid) tuple construction + set ops for
// pre-pool admission (the reference's ExistsPrePool/DeletePrePool pair,
// engine.go:58-62), and per-order oid dict interning. std::unordered_*
// (node mallocs, chained buckets) still costs ~0.5-0.8 us/op at this
// shape, so both tables here are open-addressing flat tables (power-of-2
// capacity, linear probing, 64-bit FNV-1a-mix hashes) over append-only
// byte arenas — one memcpy and ~2 cache lines per op, no per-entry
// allocation.
//
// Two objects behind a C ABI (ctypes, no pybind11 in this image):
//
//   Interner  — append-only string -> dense id table (ids from 1; 0 is
//               the reserved "none" of the device arrays). Batch intern
//               over a numpy 'S'-dtype column (fixed width, NUL-padded),
//               padded gather for the event-frame id tables, len-prefixed
//               export/import for snapshots.
//   PrePool   — the marker set (engine/prepool.py contract), keys
//               composed as "symbol\x1Fuuid\x1Foid" ('\x1F' = ASCII unit
//               separator; the ids round-trip the reference's JSON wire
//               contract and never contain control bytes). One fused call
//               admits a whole decoded ORDER frame: compose key, pop
//               marker, emit keep/existed masks — mode 1 marks (the
//               gateway side, nodepool.go:14-16), mode 2 restores a
//               consumed selection (failed-batch rollback). Erasure uses
//               tombstones; rehash compacts live keys into a fresh arena,
//               so long-running churn (mark+consume per order) does not
//               grow memory unboundedly.
//
// Thread-safety: PrePool ops take a mutex (the gateway's gRPC threads mark
// while the consumer admits). The Interner is single-consumer-thread by
// design (documented in engine/host.py) and unlocked.

#include <cstdint>
#include <cstring>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

namespace {

inline uint64_t hash_bytes(const char* p, size_t n) {
  uint64_t h = 1469598103934665603ull;  // FNV-1a 64
  for (size_t i = 0; i < n; ++i) {
    h ^= static_cast<unsigned char>(p[i]);
    h *= 1099511628211ull;
  }
  // Final avalanche (splitmix64 tail): FNV alone clusters low bits.
  h ^= h >> 30;
  h *= 0xbf58476d1ce4e5b9ull;
  h ^= h >> 27;
  h *= 0x94d049bb133111ebull;
  h ^= h >> 31;
  return h ? h : 1;  // 0 is the empty-slot sentinel
}

struct Arena {
  std::vector<std::unique_ptr<char[]>> chunks;
  size_t cap = 0, used = 0;

  const char* put(const char* p, size_t n) {
    if (used + n > cap) {
      cap = n > (1u << 20) ? n : (1u << 20);
      chunks.emplace_back(new char[cap]);
      used = 0;
    }
    char* dst = chunks.back().get() + used;
    std::memcpy(dst, p, n);
    used += n;
    return dst;
  }
};

struct StrRef {
  const char* p;
  uint32_t len;
};

inline std::pair<const char*, int64_t> trim_padded(const char* p,
                                                   int64_t width) {
  int64_t len = width;
  while (len > 0 && p[len - 1] == '\0') --len;
  return {p, len};
}

// ---------------------------------------------------------------- Interner
struct Interner {
  // Interleaved {hash, id} slots: one prefetched cache line serves both
  // the hash compare and the id deref (split arrays cost two misses).
  struct Slot {
    uint64_t h;  // 0 = empty
    int64_t id;
  };
  std::vector<Slot> slots;
  size_t mask = 0, count = 0;
  Arena arena;
  std::vector<StrRef> strs;  // id-1 -> bytes
  int64_t max_len = 0;

  Interner() { rehash(1 << 12); }

  void rehash(size_t new_cap) {
    std::vector<Slot> s2(new_cap, Slot{0, 0});
    size_t m2 = new_cap - 1;
    for (size_t i = 0; i <= mask && !slots.empty(); ++i) {
      if (!slots[i].h) continue;
      size_t j = slots[i].h & m2;
      while (s2[j].h) j = (j + 1) & m2;
      s2[j] = slots[i];
    }
    slots.swap(s2);
    mask = m2;
  }

  int64_t intern(const char* p, size_t n) {
    return intern_hashed(p, n, hash_bytes(p, n));
  }

  int64_t intern_hashed(const char* p, size_t n, uint64_t h) {
    size_t i = h & mask;
    while (slots[i].h) {
      if (slots[i].h == h) {
        const StrRef& s = strs[static_cast<size_t>(slots[i].id - 1)];
        if (s.len == n && std::memcmp(s.p, p, n) == 0) return slots[i].id;
      }
      i = (i + 1) & mask;
    }
    const char* stored = arena.put(p, n);
    strs.push_back({stored, static_cast<uint32_t>(n)});
    int64_t id = static_cast<int64_t>(strs.size());
    slots[i] = {h, id};
    if (static_cast<int64_t>(n) > max_len) max_len = static_cast<int64_t>(n);
    if (++count * 4 > (mask + 1) * 3) rehash((mask + 1) * 2);
    return id;
  }

  int64_t get(const char* p, size_t n) const {
    uint64_t h = hash_bytes(p, n);
    size_t i = h & mask;
    while (slots[i].h) {
      if (slots[i].h == h) {
        const StrRef& s = strs[static_cast<size_t>(slots[i].id - 1)];
        if (s.len == n && std::memcmp(s.p, p, n) == 0) return slots[i].id;
      }
      i = (i + 1) & mask;
    }
    return 0;
  }
};

// ---------------------------------------------------------------- PrePool
struct PrePool {
  // Interleaved {hash, ref} slots (one prefetched line serves both).
  // ref: 0 = empty, -1 = tombstone, else index+1 into keys.
  struct Slot {
    uint64_t h;
    int64_t ref;
  };
  std::vector<Slot> slots;
  size_t mask = 0, live = 0, tombs = 0;
  Arena arena;
  std::vector<StrRef> keys;       // append-only; dead entries len = 0
  std::vector<uint8_t> key_live;  // parallel liveness for rehash compaction
  std::mutex mu;

  PrePool() { rehash(1 << 12); }

  void rehash(size_t new_cap) {
    // Compact: copy only LIVE keys into a fresh arena so churn (mark +
    // consume per order) cannot grow memory without bound.
    Arena a2;
    std::vector<StrRef> k2;
    std::vector<uint8_t> l2;
    std::vector<Slot> s2(new_cap, Slot{0, 0});
    size_t m2 = new_cap - 1;
    k2.reserve(live);
    for (size_t i = 0; i <= mask && !slots.empty(); ++i) {
      if (!slots[i].h || slots[i].ref <= 0) continue;
      const StrRef& s = keys[static_cast<size_t>(slots[i].ref - 1)];
      const char* stored = a2.put(s.p, s.len);
      k2.push_back({stored, s.len});
      l2.push_back(1);
      size_t j = slots[i].h & m2;
      while (s2[j].h) j = (j + 1) & m2;
      s2[j] = {slots[i].h, static_cast<int64_t>(k2.size())};
    }
    slots.swap(s2);
    arena = std::move(a2);
    keys.swap(k2);
    key_live.swap(l2);
    mask = m2;
    tombs = 0;
  }

  void maybe_grow() {
    if ((live + tombs) * 4 > (mask + 1) * 3)
      rehash(live * 4 > (mask + 1) ? (mask + 1) * 2 : mask + 1);
  }

  // returns slot index holding the key, or SIZE_MAX.
  size_t find(const char* p, size_t n, uint64_t h) const {
    size_t i = h & mask;
    while (slots[i].h || slots[i].ref == -1) {
      if (slots[i].h == h && slots[i].ref > 0) {
        const StrRef& s = keys[static_cast<size_t>(slots[i].ref - 1)];
        if (s.len == n && std::memcmp(s.p, p, n) == 0) return i;
      }
      i = (i + 1) & mask;
    }
    return SIZE_MAX;
  }

  bool insert(const char* p, size_t n) {
    return insert_hashed(p, n, hash_bytes(p, n));
  }

  bool insert_hashed(const char* p, size_t n, uint64_t h) {
    if (find(p, n, h) != SIZE_MAX) return false;
    size_t i = h & mask;
    while (slots[i].h && slots[i].ref != -1) i = (i + 1) & mask;
    if (slots[i].ref == -1) --tombs;
    const char* stored = arena.put(p, n);
    keys.push_back({stored, static_cast<uint32_t>(n)});
    key_live.push_back(1);
    slots[i] = {h, static_cast<int64_t>(keys.size())};
    ++live;
    maybe_grow();
    return true;
  }

  bool erase(const char* p, size_t n) {
    return erase_hashed(p, n, hash_bytes(p, n));
  }

  bool erase_hashed(const char* p, size_t n, uint64_t h) {
    size_t i = find(p, n, h);
    if (i == SIZE_MAX) return false;
    key_live[static_cast<size_t>(slots[i].ref - 1)] = 0;
    slots[i] = {0, -1};  // tombstone keeps probe chains intact
    --live;
    ++tombs;
    if (tombs * 2 > mask + 1) rehash(mask + 1);
    return true;
  }

  bool contains(const char* p, size_t n) {
    return find(p, n, hash_bytes(p, n)) != SIZE_MAX;
  }
};

constexpr char kSep = '\x1F';

struct StrList {
  const char* data;
  const int64_t* offs;
};

}  // namespace

extern "C" {

// ---------------------------------------------------------------- Interner
void* gi_new() { return new Interner(); }
void gi_free(void* h) { delete static_cast<Interner*>(h); }

int64_t gi_len(void* h) {
  return static_cast<int64_t>(static_cast<Interner*>(h)->strs.size());
}

int64_t gi_max_len(void* h) { return static_cast<Interner*>(h)->max_len; }

int64_t gi_intern_one(void* h, const char* p, int64_t len) {
  return static_cast<Interner*>(h)->intern(p, static_cast<size_t>(len));
}

int64_t gi_get(void* h, const char* p, int64_t len) {
  return static_cast<Interner*>(h)->get(p, static_cast<size_t>(len));
}

void gi_intern_batch(void* h, const char* data, int64_t n, int64_t width,
                     int64_t* out_ids) {
  auto& in = *static_cast<Interner*>(h);
  // Ensure no rehash mid-batch (so prefetched slots stay valid) and
  // block-prefetch: hash a block, prefetch its slots, then probe — the
  // probes are independent DRAM misses, so overlapping them across the
  // block hides most of the latency.
  if ((in.count + static_cast<size_t>(n)) * 4 > (in.mask + 1) * 3) {
    size_t cap = in.mask + 1;
    while ((in.count + static_cast<size_t>(n)) * 4 > cap * 3) cap *= 2;
    in.rehash(cap);
  }
  constexpr int64_t B = 32;
  uint64_t hs[B];
  for (int64_t base = 0; base < n; base += B) {
    int64_t m = n - base < B ? n - base : B;
    for (int64_t j = 0; j < m; ++j) {
      auto [p, len] = trim_padded(data + (base + j) * width, width);
      hs[j] = hash_bytes(p, static_cast<size_t>(len));
      __builtin_prefetch(&in.slots[hs[j] & in.mask]);
    }
    for (int64_t j = 0; j < m; ++j) {
      auto [p, len] = trim_padded(data + (base + j) * width, width);
      out_ids[base + j] =
          in.intern_hashed(p, static_cast<size_t>(len), hs[j]);
    }
  }
}

int64_t gi_lookup(void* h, int64_t id, char* out, int64_t cap) {
  auto& in = *static_cast<Interner*>(h);
  if (id == 0) return 0;
  if (id < 0 || id > static_cast<int64_t>(in.strs.size())) return -1;
  const StrRef& s = in.strs[static_cast<size_t>(id - 1)];
  if (static_cast<int64_t>(s.len) > cap) return -1;
  std::memcpy(out, s.p, s.len);
  return static_cast<int64_t>(s.len);
}

// Max string length over just the requested ids (so gathered id tables
// pad to the BATCH max, not the process-lifetime max — one long id must
// not inflate every later frame). Returns -1 on an out-of-range id.
int64_t gi_gather_width(void* h, const int64_t* ids, int64_t n) {
  auto& in = *static_cast<Interner*>(h);
  int64_t sz = static_cast<int64_t>(in.strs.size());
  int64_t w = 0;
  for (int64_t i = 0; i < n; ++i) {
    int64_t id = ids[i];
    if (id == 0) continue;
    if (id < 0 || id > sz) return -1;
    int64_t len =
        static_cast<int64_t>(in.strs[static_cast<size_t>(id - 1)].len);
    if (len > w) w = len;
  }
  return w;
}

int64_t gi_gather(void* h, const int64_t* ids, int64_t n, char* out,
                  int64_t width) {
  auto& in = *static_cast<Interner*>(h);
  int64_t sz = static_cast<int64_t>(in.strs.size());
  for (int64_t i = 0; i < n; ++i) {
    char* cell = out + i * width;
    std::memset(cell, 0, static_cast<size_t>(width));
    int64_t id = ids[i];
    if (id == 0) continue;
    if (id < 0 || id > sz) return -1;
    const StrRef& s = in.strs[static_cast<size_t>(id - 1)];
    if (static_cast<int64_t>(s.len) > width) return -1;
    std::memcpy(cell, s.p, s.len);
  }
  return 0;
}

int64_t gi_export(void* h, char* out, int64_t cap) {
  auto& in = *static_cast<Interner*>(h);
  int64_t need = 0;
  for (const auto& s : in.strs) need += 4 + static_cast<int64_t>(s.len);
  if (cap < need) return need;
  char* p = out;
  for (const auto& s : in.strs) {
    uint32_t len = s.len;
    std::memcpy(p, &len, 4);
    p += 4;
    std::memcpy(p, s.p, s.len);
    p += s.len;
  }
  return need;
}

int64_t gi_import(void* h, const char* data, int64_t nbytes, int64_t n) {
  auto& in = *static_cast<Interner*>(h);
  if (!in.strs.empty()) return -1;
  const char* p = data;
  const char* end = data + nbytes;
  for (int64_t i = 0; i < n; ++i) {
    if (p + 4 > end) return -1;
    uint32_t len;
    std::memcpy(&len, p, 4);
    p += 4;
    if (p + len > end) return -1;
    in.intern(p, len);
    p += len;
  }
  return 0;
}

// ---------------------------------------------------------------- PrePool
void* gp_new() { return new PrePool(); }
void gp_free(void* h) { delete static_cast<PrePool*>(h); }

int64_t gp_len(void* h) {
  auto& pp = *static_cast<PrePool*>(h);
  std::lock_guard<std::mutex> g(pp.mu);
  return static_cast<int64_t>(pp.live);
}

int64_t gp_add(void* h, const char* p, int64_t len) {
  auto& pp = *static_cast<PrePool*>(h);
  std::lock_guard<std::mutex> g(pp.mu);
  return pp.insert(p, static_cast<size_t>(len)) ? 1 : 0;
}

int64_t gp_discard(void* h, const char* p, int64_t len) {
  auto& pp = *static_cast<PrePool*>(h);
  std::lock_guard<std::mutex> g(pp.mu);
  return pp.erase(p, static_cast<size_t>(len)) ? 1 : 0;
}

int64_t gp_contains(void* h, const char* p, int64_t len) {
  auto& pp = *static_cast<PrePool*>(h);
  std::lock_guard<std::mutex> g(pp.mu);
  return pp.contains(p, static_cast<size_t>(len)) ? 1 : 0;
}

void gp_clear(void* h) {
  auto& pp = *static_cast<PrePool*>(h);
  std::lock_guard<std::mutex> g(pp.mu);
  pp.slots.assign(pp.mask + 1, PrePool::Slot{0, 0});
  pp.arena = Arena();
  pp.keys.clear();
  pp.key_live.clear();
  pp.live = pp.tombs = 0;
}

int64_t gp_dump(void* h, char* out, int64_t cap) {
  auto& pp = *static_cast<PrePool*>(h);
  std::lock_guard<std::mutex> g(pp.mu);
  int64_t need = 0;
  for (size_t k = 0; k < pp.keys.size(); ++k)
    if (pp.key_live[k]) need += 4 + static_cast<int64_t>(pp.keys[k].len);
  if (cap < need) return need;
  char* p = out;
  for (size_t k = 0; k < pp.keys.size(); ++k) {
    if (!pp.key_live[k]) continue;
    uint32_t len = pp.keys[k].len;
    std::memcpy(p, &len, 4);
    p += 4;
    std::memcpy(p, pp.keys[k].p, len);
    p += len;
  }
  return need;
}

// The fused frame pass — see engine/prepool.py NativePrePool._frame for
// the calling convention. mode 0 = consume (admission, engine.go:58-62 +
// 88-90), mode 1 = mark ADDs (gateway, main.go:42-45), mode 2 = restore
// rows selected by `existed` (failed-batch rollback).
int64_t gp_frame(void* h, int64_t n, const uint8_t* action,
                 const char* sym_data, const int64_t* sym_offs,
                 const uint32_t* sym_idx, const char* uuid_data,
                 const int64_t* uuid_offs, const uint32_t* uuid_idx,
                 const char* oids, int64_t oid_width, int64_t add_val,
                 int64_t del_val, uint8_t* keep, uint8_t* existed,
                 int64_t mode) {
  auto& pp = *static_cast<PrePool*>(h);
  StrList syms{sym_data, sym_offs};
  StrList uuids{uuid_data, uuid_offs};
  std::lock_guard<std::mutex> g(pp.mu);
  if (mode != 0) {
    // Insert modes can rehash; presize once up front.
    size_t want = pp.live + pp.tombs + static_cast<size_t>(n);
    if (want * 4 > (pp.mask + 1) * 3) {
      size_t cap = pp.mask + 1;
      while (want * 4 > cap * 3) cap *= 2;
      pp.rehash(cap);
    }
  }
  // Block pass: compose keys into a scratch buffer, hash + prefetch the
  // slots, then probe — overlaps the table's DRAM misses across the block.
  constexpr int64_t B = 32;
  std::vector<char> scratch;
  scratch.reserve(B * 96);
  int64_t rows[B];
  uint32_t offs[B + 1];
  uint64_t hs[B];
  for (int64_t base = 0; base < n; base += B) {
    int64_t lim = base + B < n ? base + B : n;
    int64_t m = 0;
    scratch.clear();
    offs[0] = 0;
    for (int64_t i = base; i < lim; ++i) {
      int64_t a = action[i];
      bool is_add = a == add_val, is_del = a == del_val;
      if (mode == 0 && !is_add && !is_del) {
        keep[i] = 0;
        existed[i] = 0;
        continue;
      }
      if (mode == 1 && !is_add) continue;  // cancels never mark
      if (mode == 2 && !existed[i]) continue;
      uint32_t si = sym_idx[i], ui = uuid_idx[i];
      scratch.insert(scratch.end(), syms.data + syms.offs[si],
                     syms.data + syms.offs[si + 1]);
      scratch.push_back(kSep);
      scratch.insert(scratch.end(), uuids.data + uuids.offs[ui],
                     uuids.data + uuids.offs[ui + 1]);
      scratch.push_back(kSep);
      auto [op, olen] = trim_padded(oids + i * oid_width, oid_width);
      scratch.insert(scratch.end(), op, op + olen);
      rows[m] = i;
      offs[m + 1] = static_cast<uint32_t>(scratch.size());
      ++m;
    }
    for (int64_t j = 0; j < m; ++j) {
      hs[j] = hash_bytes(scratch.data() + offs[j], offs[j + 1] - offs[j]);
      __builtin_prefetch(&pp.slots[hs[j] & pp.mask]);
    }
    // Staged speculative prefetch along the expected hit path: the slot
    // line is in flight from the loop above; touch it to prefetch the
    // StrRef entry it references, then the key bytes that entry points
    // at. Each stage runs across the whole block, so the three dependent
    // misses of a probe overlap block-wide instead of serializing
    // per key. Pure hints — stage 3's erase/insert re-probes for real
    // (tombstoning or a rehash mid-block only wastes a prefetch).
    const StrRef* krefs[B];
    for (int64_t j = 0; j < m; ++j) {
      const PrePool::Slot& s = pp.slots[hs[j] & pp.mask];
      if (s.h == hs[j] && s.ref > 0) {
        krefs[j] = &pp.keys[static_cast<size_t>(s.ref - 1)];
        __builtin_prefetch(krefs[j]);
      } else {
        krefs[j] = nullptr;
      }
    }
    for (int64_t j = 0; j < m; ++j) {
      if (krefs[j]) __builtin_prefetch(krefs[j]->p);
    }
    for (int64_t j = 0; j < m; ++j) {
      const char* kp = scratch.data() + offs[j];
      size_t kn = offs[j + 1] - offs[j];
      int64_t i = rows[j];
      if (mode != 0) {
        pp.insert_hashed(kp, kn, hs[j]);
      } else {
        bool ex = pp.erase_hashed(kp, kn, hs[j]);
        existed[i] = ex ? 1 : 0;
        keep[i] = (action[i] == del_val) ? 1 : (ex ? 1 : 0);
      }
    }
  }
  return 0;
}

// -------------------------------------------------------------- utilities

// Decode one grid's device-compacted events into final event columns in
// the reference's global emission order (arrival index, then record order
// within the op) — the C++ form of frames._decode_compact + its sort.
// All inputs are int64 host arrays (the Python side slices the fetched
// device buffers to [nf]/[nc] and widens); outputs are preallocated
// [nf+nc] columns. Stable two-pass counting sort over arrival (bounded by
// the frame's order count) replaces the numpy argsort.
int64_t go_decode_compact(
    int64_t n_rows, int64_t t_len, int64_t k, int64_t nf, int64_t nc,
    int64_t frame_n,
    // fills [nf]
    const int64_t* f_src, const int64_t* f_price, const int64_t* f_qty,
    const int64_t* f_moid, const int64_t* f_muid, const int64_t* f_mvol,
    const int64_t* f_after,
    // cancels [nc]
    const int64_t* c_src, const int64_t* c_vol,
    // packed-op meta [m]
    int64_t m, const int64_t* op_row, const int64_t* op_t,
    const int64_t* op_arrival, const int64_t* op_lane,
    const int64_t* op_uid, const int64_t* op_oid, const int64_t* op_side,
    const int64_t* op_price, const int64_t* op_base,
    const int64_t* op_is_market,
    // outputs [nf+nc]
    int64_t* arrival, uint8_t* is_cancel, int64_t* symbol_id,
    int64_t* taker_uid, int64_t* taker_oid, int8_t* taker_side,
    int64_t* taker_price, int64_t* taker_volume, int64_t* maker_uid,
    int64_t* maker_oid, int64_t* fill_price, int64_t* maker_volume,
    int64_t* match_volume, uint8_t* is_market) {
  // (row, t) -> packed-op index join table.
  std::vector<int32_t> op_index(
      static_cast<size_t>(n_rows) * static_cast<size_t>(t_len), -1);
  for (int64_t i = 0; i < m; ++i)
    op_index[static_cast<size_t>(op_row[i] * t_len + op_t[i])] =
        static_cast<int32_t>(i);

  // The op meta arrives as 10 parallel column arrays; per-event access by
  // `pos` is random, so gather the 7 fields an event needs into one
  // 64-byte struct first (sequential pass) — each event then touches ONE
  // meta cache line instead of seven.
  struct OpMeta {
    int64_t arrival, lane, uid, oid, side, price, base;
    int64_t mkt;
  };
  std::vector<OpMeta> om(static_cast<size_t>(m));
  for (int64_t i = 0; i < m; ++i)
    om[static_cast<size_t>(i)] = {op_arrival[i], op_lane[i],  op_uid[i],
                                  op_oid[i],     op_side[i],  op_price[i],
                                  op_base[i],    op_is_market[i]};

  int64_t ne = nf + nc;
  std::vector<int64_t> ev_pos(static_cast<size_t>(ne));   // op index
  std::vector<int64_t> ev_arr(static_cast<size_t>(ne));   // arrival
  std::vector<int64_t> counts(static_cast<size_t>(frame_n) + 1, 0);
  constexpr int64_t PF = 12;  // software prefetch distance
  for (int64_t e = 0; e < nf; ++e) {
    int64_t src = f_src[e];
    int64_t pos = op_index[static_cast<size_t>(src / k)];
    if (pos < 0) return -1;  // fill without a packed ADD: corrupt
    ev_pos[static_cast<size_t>(e)] = pos;
  }
  for (int64_t e = 0; e < nc; ++e) {
    int64_t pos = op_index[static_cast<size_t>(c_src[e])];
    if (pos < 0) return -1;
    ev_pos[static_cast<size_t>(nf + e)] = pos;
  }
  for (int64_t e = 0; e < ne; ++e) {
    if (e + PF < ne)
      __builtin_prefetch(&om[static_cast<size_t>(ev_pos[e + PF])]);
    int64_t a = om[static_cast<size_t>(ev_pos[e])].arrival;
    ev_arr[static_cast<size_t>(e)] = a;
    ++counts[static_cast<size_t>(a)];
  }
  int64_t run = 0;
  for (size_t a = 0; a < counts.size(); ++a) {
    int64_t c = counts[a];
    counts[a] = run;
    run += c;
  }
  // Counting-sort permutation, then emit in DESTINATION order: the 14
  // output columns become pure sequential streams (the random side —
  // event + meta structs — is prefetched ahead), instead of 14 random
  // cache-line RFOs per event.
  std::vector<int64_t> src_of(static_cast<size_t>(ne));
  for (int64_t e = 0; e < ne; ++e)
    src_of[static_cast<size_t>(
        counts[static_cast<size_t>(ev_arr[static_cast<size_t>(e)])]++)] = e;
  for (int64_t dst = 0; dst < ne; ++dst) {
    if (dst + PF < ne) {
      int64_t en = src_of[static_cast<size_t>(dst + PF)];
      __builtin_prefetch(&ev_pos[en]);
      if (en < nf) {
        __builtin_prefetch(&f_price[en]);
        __builtin_prefetch(&f_qty[en]);
      }
    }
    if (dst + PF / 2 < ne) {
      int64_t en = src_of[static_cast<size_t>(dst + PF / 2)];
      __builtin_prefetch(&om[static_cast<size_t>(ev_pos[en])]);
    }
    int64_t e = src_of[static_cast<size_t>(dst)];
    bool cancel = e >= nf;
    const OpMeta& o = om[static_cast<size_t>(ev_pos[e])];
    arrival[dst] = o.arrival;
    is_cancel[dst] = cancel ? 1 : 0;
    symbol_id[dst] = o.lane;
    taker_uid[dst] = o.uid;
    taker_oid[dst] = o.oid;
    taker_side[dst] = static_cast<int8_t>(o.side);
    taker_price[dst] = o.price;
    if (cancel) {
      int64_t e2 = e - nf;
      int64_t vol = c_vol[e2];
      taker_volume[dst] = vol;
      maker_uid[dst] = o.uid;
      maker_oid[dst] = o.oid;
      fill_price[dst] = o.price;
      maker_volume[dst] = vol;
      match_volume[dst] = 0;
      is_market[dst] = 0;
    } else {
      taker_volume[dst] = f_after[e];
      maker_uid[dst] = f_muid[e];
      maker_oid[dst] = f_moid[e];
      fill_price[dst] = f_price[e] + o.base;
      maker_volume[dst] = f_mvol[e];
      match_volume[dst] = f_qty[e];
      is_market[dst] = o.mkt ? 1 : 0;
    }
  }
  return 0;
}

// Fused grid pack: one linear pass selects the frame ops landing in this
// grid's time window and emits (a) the DEVICE-UPLOAD columns — a [7, m]
// field matrix plus the [m] flat grid index each op scatters to ON
// DEVICE — and (b) the packed-op meta columns the event decoder needs,
// replacing ~20 separate numpy mask/scatter passes in
// frames.pack_frame_grids. Emitting columns instead of padded [R, T]
// grids keeps the host->device transfer O(ops): a Zipf frame's deep
// tail grids are ~1% occupied, and uploading their padding cost more
// than the matching (the device rebuilds the padded grid with one
// scatter — frames._scatter_grid).
//
// The pass walks `idx` (n_sub candidate op indices into the frame-global
// field arrays): a frame that splits into a train of grids hands each
// grid only the ops still alive at its time offset, so a G-grid train
// costs O(sum of survivors), not O(G * frame). cols is [7, m] in
// _GRID_FIELDS order (action, side, is_market, price, volume, oid, uid),
// int32 or int64 (val_itemsize). Meta outputs are int64 [m] where
// m = |{j : t_off <= t[idx[j]] < t_off+t_grid}| (the caller sizes them
// with one count pass); meta arrival carries the ORIGINAL frame index
// idx[j]. Returns the number packed (must equal m) or -1 on a row/t out
// of grid bounds (corrupt input).
int64_t go_pack_grid(
    int64_t n_sub, const int64_t* idx, const int64_t* row_of,
    const int64_t* lanes, const int64_t* t,
    int64_t t_off, int64_t t_grid, int64_t n_rows,
    const int64_t* action, const int64_t* side, const int64_t* kind,
    const int64_t* price, const int64_t* volume, const int64_t* oid_ids,
    const int64_t* uid_ids, const int64_t* bases, int64_t market_val,
    int64_t add_val,
    void* cols, void* flat_idx, int64_t stride, int64_t val_itemsize,
    int64_t* m_lane, int64_t* m_row, int64_t* m_t, int64_t* m_arrival,
    int64_t* m_action, int64_t* m_side, int64_t* m_market, int64_t* m_price,
    int64_t* m_base, int64_t* m_oid, int64_t* m_uid) {
  // `stride` = the cols matrix's padded column count (a pow2 class, so
  // upload shapes stay compile-stable); rows are written at [f*stride+j].
  bool wide = val_itemsize == 8;
  int64_t m = stride;
  int64_t j = 0;
  for (int64_t s = 0; s < n_sub; ++s) {
    int64_t i = idx[s];
    int64_t ti = t[i];
    if (ti < t_off || ti >= t_off + t_grid) continue;
    int64_t tt = ti - t_off;
    int64_t r = row_of[lanes[i]];  // lane -> grid row (identity when full)
    if (r < 0 || r >= n_rows) return -1;
    int64_t flat = r * t_grid + tt;
    int64_t a = action[i];
    bool is_mkt = kind[i] == market_val && a == add_val;
    int64_t p_dev = is_mkt ? 0 : price[i] - bases[i];
    int64_t vals[7] = {a,         side[i],     is_mkt ? 1 : 0, p_dev,
                       volume[i], oid_ids[i],  uid_ids[i]};
    if (wide) {
      auto* c = static_cast<int64_t*>(cols);
      for (int f = 0; f < 7; ++f) c[f * m + j] = vals[f];
    } else {
      auto* c = static_cast<int32_t*>(cols);
      for (int f = 0; f < 7; ++f)
        c[f * m + j] = static_cast<int32_t>(vals[f]);
    }
    static_cast<int32_t*>(flat_idx)[j] = static_cast<int32_t>(flat);
    m_lane[j] = lanes[i];
    m_row[j] = r;
    m_t[j] = tt;
    m_arrival[j] = i;
    m_action[j] = a;
    m_side[j] = side[i];
    m_market[j] = is_mkt ? 1 : 0;
    m_price[j] = price[i];
    m_base[j] = bases[i];
    m_oid[j] = oid_ids[i];
    m_uid[j] = uid_ids[i];
    ++j;
  }
  return j;
}

// Per-lane occurrence index in arrival order: out_t[i] = number of earlier
// kept rows with the same lane (-1 for dropped rows). Replaces the numpy
// stable-argsort/segment trick in frames._frame_arrays (O(n log n) and
// ~0.1 us/order at frame shape) with one linear pass.
void go_occurrences(const int64_t* lanes, const uint8_t* keep, int64_t n,
                    int64_t n_lanes, int64_t* out_t) {
  std::vector<int64_t> cnt(static_cast<size_t>(n_lanes), 0);
  for (int64_t i = 0; i < n; ++i) {
    if (keep && !keep[i]) {
      out_t[i] = -1;
      continue;
    }
    out_t[i] = cnt[static_cast<size_t>(lanes[i])]++;
  }
}

}  // extern "C"

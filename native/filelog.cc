// Native durable file-log queue — the C++ runtime backend for
// gome_tpu.bus.FileQueue (same on-disk format: 4-byte big-endian length
// prefix per record in <name>.log + ASCII committed offset in
// <name>.offset, so the Python and native backends are interchangeable on
// the same files).
//
// Why native: the bus publish path is the per-order host hot loop (the role
// the reference delegates to compiled Go + RabbitMQ, rabbitmq.go:60-84).
// Python-side, each publish costs interpreter overhead comparable to the
// I/O itself; here publish_batch amortizes one syscall+fsync across a
// micro-batch. Exposed via a minimal C ABI consumed with ctypes
// (gome_tpu/bus/native.py) — no pybind11 in this image.
//
// Concurrency contract: one process owns a queue directory (same as the
// Python backend); within a process, calls are serialized by a mutex.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fcntl.h>
#include <mutex>
#include <string>
#include <sys/stat.h>
#include <unistd.h>
#include <vector>

namespace {

struct Queue {
  std::mutex mu;
  std::string log_path;
  std::string off_path;
  int fd = -1;          // append handle for the log
  bool do_fsync = true;
  std::vector<uint64_t> positions;  // record start offsets (byte pos)
  uint64_t tail = 0;                // byte length of valid log prefix
  uint64_t committed = 0;           // consumer offset (record index)
};

uint32_t load_be32(const unsigned char* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

void store_be32(unsigned char* p, uint32_t v) {
  p[0] = v >> 24;
  p[1] = v >> 16;
  p[2] = v >> 8;
  p[3] = v;
}

// Scan an existing log, building the position index and truncating a torn
// tail record (crash mid-append), mirroring FileQueue._scan_existing.
bool scan_log(Queue* q) {
  FILE* f = fopen(q->log_path.c_str(), "rb");
  if (f == nullptr) return true;  // no log yet
  fseek(f, 0, SEEK_END);
  long size = ftell(f);
  fseek(f, 0, SEEK_SET);
  std::vector<unsigned char> data(size > 0 ? size : 0);
  if (size > 0 && fread(data.data(), 1, size, f) != size_t(size)) {
    fclose(f);
    return false;
  }
  fclose(f);
  uint64_t pos = 0;
  uint64_t valid_end = 0;
  while (pos + 4 <= uint64_t(size)) {
    uint32_t n = load_be32(data.data() + pos);
    if (pos + 4 + n > uint64_t(size)) break;  // torn tail
    q->positions.push_back(pos);
    pos += 4 + n;
    valid_end = pos;
  }
  q->tail = valid_end;
  if (valid_end < uint64_t(size)) {
    if (truncate(q->log_path.c_str(), off_t(valid_end)) != 0) return false;
  }
  return true;
}

uint64_t read_committed(const Queue* q) {
  FILE* f = fopen(q->off_path.c_str(), "rb");
  if (f == nullptr) return 0;
  char buf[32] = {0};
  size_t n = fread(buf, 1, sizeof(buf) - 1, f);
  fclose(f);
  (void)n;
  return strtoull(buf, nullptr, 10);
}

bool write_committed(Queue* q, uint64_t offset) {
  std::string tmp = q->off_path + ".tmp";
  int fd = open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return false;
  char buf[32];
  int len = snprintf(buf, sizeof(buf), "%llu", (unsigned long long)offset);
  bool ok = write(fd, buf, len) == len && fsync(fd) == 0;
  close(fd);
  if (!ok) return false;
  return rename(tmp.c_str(), q->off_path.c_str()) == 0;
}

}  // namespace

extern "C" {

// Returns an opaque handle, or null on failure.
void* gq_open(const char* path_base, int do_fsync) {
  auto* q = new Queue();
  q->log_path = std::string(path_base) + ".log";
  q->off_path = std::string(path_base) + ".offset";
  q->do_fsync = do_fsync != 0;
  if (!scan_log(q)) {
    delete q;
    return nullptr;
  }
  q->fd = open(q->log_path.c_str(), O_WRONLY | O_CREAT | O_APPEND, 0644);
  if (q->fd < 0) {
    delete q;
    return nullptr;
  }
  q->committed = read_committed(q);
  return q;
}

void gq_close(void* h) {
  auto* q = static_cast<Queue*>(h);
  if (q == nullptr) return;
  if (q->fd >= 0) close(q->fd);
  delete q;
}

// Append n records in ONE writev-style buffer + one fsync.
// bodies: concatenated payload bytes; lengths[i]: payload sizes.
// Returns the offset of the FIRST appended record, or -1 on failure.
int64_t gq_publish_batch(void* h, const unsigned char* bodies,
                         const uint32_t* lengths, uint32_t n) {
  auto* q = static_cast<Queue*>(h);
  std::lock_guard<std::mutex> lock(q->mu);
  size_t total = 0;
  for (uint32_t i = 0; i < n; i++) total += 4 + size_t(lengths[i]);
  std::vector<unsigned char> buf(total);
  size_t w = 0;
  const unsigned char* src = bodies;
  std::vector<uint64_t> new_positions;
  new_positions.reserve(n);
  uint64_t pos = q->tail;
  for (uint32_t i = 0; i < n; i++) {
    store_be32(buf.data() + w, lengths[i]);
    memcpy(buf.data() + w + 4, src, lengths[i]);
    new_positions.push_back(pos);
    pos += 4 + lengths[i];
    w += 4 + lengths[i];
    src += lengths[i];
  }
  ssize_t written = write(q->fd, buf.data(), buf.size());
  if (written != ssize_t(buf.size()) || (q->do_fsync && fsync(q->fd) != 0)) {
    // Partial append (disk full/quota) or unconfirmed durability: roll the
    // file back to the last consistent tail so positions never point into
    // garbage and a reopen's scan cannot misparse orphan bytes.
    if (ftruncate(q->fd, off_t(q->tail)) != 0) {
      // Can't even restore consistency: poison the handle (fail-stop).
      close(q->fd);
      q->fd = -1;
    }
    return -1;
  }
  int64_t first = int64_t(q->positions.size());
  q->positions.insert(q->positions.end(), new_positions.begin(),
                      new_positions.end());
  q->tail = pos;
  return first;
}

int64_t gq_end_offset(void* h) {
  auto* q = static_cast<Queue*>(h);
  std::lock_guard<std::mutex> lock(q->mu);
  return int64_t(q->positions.size());
}

int64_t gq_committed(void* h) {
  auto* q = static_cast<Queue*>(h);
  std::lock_guard<std::mutex> lock(q->mu);
  return int64_t(q->committed);
}

// Read up to max_n records starting at `offset` into caller buffers.
// out_bodies receives concatenated payloads (capacity out_cap bytes),
// out_lengths[i] their sizes. Returns the number of records read;
// -1 = buffer too small (caller grows and retries); -2 = I/O error.
int64_t gq_read_from(void* h, uint64_t offset, uint32_t max_n,
                     unsigned char* out_bodies, uint64_t out_cap,
                     uint32_t* out_lengths) {
  auto* q = static_cast<Queue*>(h);
  uint64_t start_pos, end_pos, n;
  {
    // Snapshot the byte range under the lock, then do the file I/O outside
    // it so long reads (recovery replay) never stall the publish hot path.
    // Records are immutable once indexed (truncate_to only removes whole
    // records above the committed offset), so the snapshot stays valid.
    std::lock_guard<std::mutex> lock(q->mu);
    uint64_t end = q->positions.size();
    if (offset >= end) return 0;
    n = end - offset;
    if (n > max_n) n = max_n;
    start_pos = q->positions[offset];
    end_pos =
        (offset + n < q->positions.size()) ? q->positions[offset + n] : q->tail;
  }
  FILE* f = fopen(q->log_path.c_str(), "rb");
  if (f == nullptr) return -2;
  uint64_t span = end_pos - start_pos;
  std::vector<unsigned char> raw(span);
  bool ok = fseek(f, long(start_pos), SEEK_SET) == 0 &&
            fread(raw.data(), 1, span, f) == span;
  fclose(f);
  if (!ok) return -2;
  uint64_t w = 0, r = 0;
  for (uint64_t i = 0; i < n; i++) {
    uint32_t len = load_be32(raw.data() + r);
    if (w + len > out_cap) return -1;  // caller buffer too small
    memcpy(out_bodies + w, raw.data() + r + 4, len);
    out_lengths[i] = len;
    w += len;
    r += 4 + len;
  }
  return int64_t(n);
}

// Commit / rollback / truncate mirror the Python backend's contracts.
int gq_commit(void* h, uint64_t offset) {
  auto* q = static_cast<Queue*>(h);
  std::lock_guard<std::mutex> lock(q->mu);
  if (offset < q->committed || offset > q->positions.size()) return -1;
  if (!write_committed(q, offset)) return -2;
  q->committed = offset;
  return 0;
}

int gq_rollback(void* h, uint64_t offset) {
  auto* q = static_cast<Queue*>(h);
  std::lock_guard<std::mutex> lock(q->mu);
  if (offset > q->committed) return -1;
  if (!write_committed(q, offset)) return -2;
  q->committed = offset;
  return 0;
}

int gq_truncate_to(void* h, uint64_t offset) {
  auto* q = static_cast<Queue*>(h);
  std::lock_guard<std::mutex> lock(q->mu);
  if (offset < q->committed) return -1;
  if (offset >= q->positions.size()) return 0;
  uint64_t pos = q->positions[offset];
  // reopen append fd after truncation so the file position is correct
  close(q->fd);
  if (truncate(q->log_path.c_str(), off_t(pos)) != 0) return -2;
  q->fd = open(q->log_path.c_str(), O_WRONLY | O_CREAT | O_APPEND, 0644);
  if (q->fd < 0) return -2;
  q->positions.resize(offset);
  q->tail = pos;
  return 0;
}

}  // extern "C"

"""Build the native runtime library (g++ -shared) with a content-hash cache.

Invoked lazily by gome_tpu.bus.native on first use; safe to run directly:
    python native/build.py
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
SOURCES = ["filelog.cc", "ordercodec.cc", "hostops.cc"]
LIB = "libgome_native.so"


def build(verbose: bool = False) -> str | None:
    """Compile if needed; returns the .so path or None when no toolchain."""
    srcs = [os.path.join(HERE, s) for s in SOURCES]
    h = hashlib.sha256()
    for s in srcs:
        with open(s, "rb") as f:
            h.update(f.read())
    out_dir = os.path.join(HERE, "build")
    os.makedirs(out_dir, exist_ok=True)
    stamp = os.path.join(out_dir, "source.sha256")
    lib = os.path.join(out_dir, LIB)
    digest = h.hexdigest()
    if os.path.exists(lib) and os.path.exists(stamp):
        with open(stamp) as f:
            if f.read().strip() == digest:
                return lib
    cmd = [
        "g++", "-O2", "-fPIC", "-shared", "-std=c++17",
        "-o", lib, *srcs,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=not verbose)
    except (OSError, subprocess.CalledProcessError) as e:
        if verbose:
            print(f"native build failed: {e}", file=sys.stderr)
        return None
    with open(stamp, "w") as f:
        f.write(digest)
    return lib


if __name__ == "__main__":
    path = build(verbose=True)
    print(path or "BUILD FAILED")
    sys.exit(0 if path else 1)

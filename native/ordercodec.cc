// Native batch parser for the doOrder wire format (bus/codec.py
// encode_order): one flat JSON object per message with a fixed key set —
//   {"Action":N,"Uuid":s,"Oid":s,"Symbol":s,"Transaction":N,
//    "Price":N,"Volume":N[,"Kind":N]}
// (key order not assumed). The consumer decodes every inbound message on
// its hot path; parsing a whole micro-batch in one native call replaces a
// per-message json.loads. String values are returned as (offset, length)
// views into the caller's buffer — zero copies here; Python slices and
// interns them.
//
// Scope: exactly the subset of JSON our own codec emits — no nested
// objects/arrays, no floats, no unicode escapes. A message that does not
// conform (e.g. a string containing a backslash escape) stops the scan and
// the Python side falls back to json.loads for the remainder, so this is a
// fast path, never a different-semantics path.

#include <cstdint>
#include <cstring>

namespace {

struct View {
  const char* p;
  const char* end;
};

inline void skip_ws(View& v) {
  while (v.p < v.end &&
         (*v.p == ' ' || *v.p == '\t' || *v.p == '\n' || *v.p == '\r'))
    ++v.p;
}

// Parses a JSON string WITHOUT escapes; returns false on any backslash or
// raw control character (both of which json.loads treats differently —
// never silently diverge from the fallback path).
inline bool parse_string(View& v, int64_t* off, int64_t* len,
                         const char* base) {
  if (v.p >= v.end || *v.p != '"') return false;
  ++v.p;
  const char* start = v.p;
  while (v.p < v.end && *v.p != '"') {
    unsigned char c = static_cast<unsigned char>(*v.p);
    if (c == '\\' || c < 0x20) return false;  // -> python fallback
    ++v.p;
  }
  if (v.p >= v.end) return false;
  *off = start - base;
  *len = v.p - start;
  ++v.p;  // closing quote
  return true;
}

inline bool parse_int(View& v, int64_t* out) {
  skip_ws(v);
  bool neg = false;
  if (v.p < v.end && *v.p == '-') {
    neg = true;
    ++v.p;
  }
  if (v.p >= v.end || *v.p < '0' || *v.p > '9') return false;
  // JSON forbids leading zeros ("007"); json.loads rejects them, so we
  // must decline rather than decode a different value.
  if (*v.p == '0' && v.p + 1 < v.end && v.p[1] >= '0' && v.p[1] <= '9')
    return false;
  constexpr int64_t kMax = INT64_MAX;
  int64_t x = 0;
  while (v.p < v.end && *v.p >= '0' && *v.p <= '9') {
    int d = *v.p - '0';
    if (x > (kMax - d) / 10) return false;  // would overflow -> fallback
    x = x * 10 + d;
    ++v.p;
  }
  *out = neg ? -x : x;
  return true;
}

}  // namespace

extern "C" {

// Returns the count of successfully parsed leading messages (== n on full
// success). Message i spans buf[offs[i], offs[i+1]). All output arrays have
// length n. kind defaults to 0 and action to 1 (ADD) when absent, matching
// decode_order's d.get defaults.
int64_t gome_parse_orders(const char* buf, const int64_t* offs, int64_t n,
                          int64_t* action, int64_t* transaction,
                          int64_t* price, int64_t* volume, int64_t* kind,
                          int64_t* u_off, int64_t* u_len, int64_t* o_off,
                          int64_t* o_len, int64_t* s_off, int64_t* s_len) {
  for (int64_t i = 0; i < n; ++i) {
    View v{buf + offs[i], buf + offs[i + 1]};
    skip_ws(v);
    if (v.p >= v.end || *v.p != '{') return i;
    ++v.p;
    action[i] = 1;  // Action.ADD default (codec.py decode_order)
    kind[i] = 0;    // OrderType.LIMIT default
    transaction[i] = price[i] = volume[i] = 0;
    u_off[i] = u_len[i] = o_off[i] = o_len[i] = s_off[i] = s_len[i] = -1;
    bool done = false;
    while (!done) {
      skip_ws(v);
      int64_t koff, klen;
      if (!parse_string(v, &koff, &klen, buf)) return i;
      skip_ws(v);
      if (v.p >= v.end || *v.p != ':') return i;
      ++v.p;
      skip_ws(v);
      const char* key = buf + koff;
      bool ok;
      if (klen == 4 && !memcmp(key, "Uuid", 4)) {
        ok = parse_string(v, &u_off[i], &u_len[i], buf);
      } else if (klen == 3 && !memcmp(key, "Oid", 3)) {
        ok = parse_string(v, &o_off[i], &o_len[i], buf);
      } else if (klen == 6 && !memcmp(key, "Symbol", 6)) {
        ok = parse_string(v, &s_off[i], &s_len[i], buf);
      } else if (klen == 6 && !memcmp(key, "Action", 6)) {
        ok = parse_int(v, &action[i]);
      } else if (klen == 11 && !memcmp(key, "Transaction", 11)) {
        ok = parse_int(v, &transaction[i]);
      } else if (klen == 5 && !memcmp(key, "Price", 5)) {
        ok = parse_int(v, &price[i]);
      } else if (klen == 6 && !memcmp(key, "Volume", 6)) {
        ok = parse_int(v, &volume[i]);
      } else if (klen == 4 && !memcmp(key, "Kind", 4)) {
        ok = parse_int(v, &kind[i]);
      } else {
        return i;  // unknown key -> python fallback
      }
      if (!ok) return i;
      skip_ws(v);
      if (v.p < v.end && *v.p == ',') {
        ++v.p;
      } else if (v.p < v.end && *v.p == '}') {
        ++v.p;
        done = true;
      } else {
        return i;
      }
    }
    if (u_off[i] < 0 || o_off[i] < 0 || s_off[i] < 0) return i;
    skip_ws(v);
    if (v.p != v.end) return i;  // trailing garbage
  }
  return n;
}

}  // extern "C"

"""Fleet fault tolerance (round 12): partition routing, health gating,
exactly-once failover, gateway admission control, adaptive frame
sizing, client retry of the retryable status — and the committed
FLEET_CHAOS_r01 verdict pin.

Unit layers first (pure router math, the claim/recover/commit protocol,
admission thresholds, batcher interpolation), then the PR 11
deterministic interleaver driving the failover claim race across seeded
schedules, then the pinned multi-process chaos verdict.
"""

from __future__ import annotations

import json
import random
from pathlib import Path
from types import SimpleNamespace

import pytest

from gome_tpu.analysis.interleave import Interleaver, SteppingLock
from gome_tpu.clients.doorder import (
    CODE_RETRYABLE,
    RETRY_AFTER_RE,
    send_batch_retrying,
)
from gome_tpu.fleet.router import (
    FailoverController,
    HealthGate,
    PartitionMap,
    PartitionRouter,
    RouteUnavailable,
    partition_of,
)
from gome_tpu.obs.fleet import FleetAggregator
from gome_tpu.service.admission import AdmissionController, Decision
from gome_tpu.service.batcher import FrameBatcher
from gome_tpu.types import Action, Order, Side
from gome_tpu.utils.metrics import Registry
from gome_tpu.utils.resilience import BackoffPolicy

REPO = Path(__file__).resolve().parent.parent


# ---------------------------------------------------------------------------
# partition_of / PartitionMap


def test_partition_of_stable_and_validates():
    # Stable across calls (fnv1a, not salted hash()) and in range.
    for sym in ("eth2usdt", "btc2usdt", "sol2usdt", "", "x" * 64):
        p = partition_of(sym, 4)
        assert 0 <= p < 4
        assert partition_of(sym, 4) == p
    with pytest.raises(ValueError):
        partition_of("eth2usdt", 0)


def test_partition_map_validation():
    with pytest.raises(ValueError, match="unassigned"):
        PartitionMap(2, {0: "m0"})
    with pytest.raises(ValueError, match="out of range"):
        PartitionMap(1, {0: "m0", 1: "m1"})
    with pytest.raises(ValueError, match="empty member"):
        PartitionMap(1, {0: ""})
    with pytest.raises(ValueError):
        PartitionMap(0, {})
    with pytest.raises(ValueError, match="at least one member"):
        PartitionMap.even(2, [])


def test_partition_map_even_and_reassign_bumps_epoch():
    pmap = PartitionMap.even(4, ["m0", "m1"])
    assert pmap.epoch == 0
    assert pmap.partitions_of("m0") == [0, 2]
    assert pmap.partitions_of("m1") == [1, 3]
    assert pmap.members() == ["m0", "m1"]
    e = pmap.reassign([0, 2], "s0")
    assert e == 1 and pmap.epoch == 1
    assert pmap.owner(0) == "s0" and pmap.owner(1) == "m1"
    snap = pmap.snapshot()
    assert snap["epoch"] == 1
    assert snap["assignments"] == {"0": "s0", "1": "m1", "2": "s0", "3": "m1"}
    with pytest.raises(KeyError):
        pmap.reassign([9], "s0")
    p, owner = pmap.owner_of_symbol("eth2usdt")
    assert p == partition_of("eth2usdt", 4)
    assert owner == pmap.owner(p)


# ---------------------------------------------------------------------------
# HealthGate


def test_health_gate_debounce_and_snapback():
    gate = HealthGate(suspect_after=2, down_after=4)
    assert gate.state("m0") == "up"  # never polled = up
    assert gate.record("m0", False) == "up"  # one failure is noise
    assert gate.record("m0", False) == "suspect"
    assert gate.record("m0", False) == "suspect"
    assert gate.record("m0", False) == "down"
    assert gate.is_down("m0")
    assert gate.record("m0", True) == "up"  # any success snaps back
    assert not gate.is_down("m0")
    snap = gate.snapshot()
    assert snap["m0"]["polls"] == 5
    assert snap["m0"]["consecutive_failures"] == 0


def test_health_gate_mark_down_skips_debounce():
    gate = HealthGate()
    gate.mark_down("m0")  # observed process exit: ground truth
    assert gate.is_down("m0")
    with pytest.raises(ValueError):
        HealthGate(suspect_after=0)
    with pytest.raises(ValueError):
        HealthGate(suspect_after=5, down_after=4)


# ---------------------------------------------------------------------------
# PartitionRouter


def test_router_routes_and_sheds_down_owner():
    pmap = PartitionMap.even(2, ["m0", "m1"])
    gate = HealthGate()
    router = PartitionRouter(pmap, gate)
    sym = "eth2usdt"
    p = router.partition(sym)
    assert router.route(sym) == pmap.owner(p)
    gate.mark_down(pmap.owner(p))
    with pytest.raises(RouteUnavailable) as ei:
        router.route(sym)
    # Retryable by construction: the degraded-path handlers key on
    # ConnectionError, so no new plumbing is needed to shed code 14.
    assert isinstance(ei.value, ConnectionError)
    assert ei.value.partition == p
    # After failover commits the reassignment, routing resumes.
    pmap.reassign([p], "s0")
    gate.record("s0", True)
    assert router.route(sym) == "s0"
    assert router.route_partition(p) == "s0"


# ---------------------------------------------------------------------------
# FailoverController protocol


def _dead_fleet():
    pmap = PartitionMap.even(2, ["m0", "m1"])
    gate = HealthGate()
    gate.mark_down("m0")
    return pmap, gate


def test_failover_claim_is_exclusive_and_gated():
    pmap, gate = _dead_fleet()
    fc = FailoverController(pmap, gate)
    assert fc.claim("m1", "s0") is None  # m1 is not down
    c = fc.claim("m0", "s0")
    assert c is not None and c.partitions == (0,)
    assert fc.claim("m0", "s1") is None  # already claimed
    fc.release("m0", "s1")  # wrong standby: no-op
    assert fc.claim("m0", "s1") is None
    fc.release("m0", "s0")  # claimant aborts: claim re-opens
    assert fc.claim("m0", "s1") is not None


def test_failover_commit_voids_on_epoch_move():
    pmap, gate = _dead_fleet()
    fc = FailoverController(pmap, gate)
    assert fc.claim("m0", "s0") is not None
    pmap.reassign([0], "rebalanced")  # map moved under the claim
    assert fc.commit("m0", "s0") is None  # stale claim is void, not applied
    assert pmap.owner(0) == "rebalanced"
    assert fc.history() == []


def test_failover_full_protocol_reassigns_after_recovery():
    pmap, gate = _dead_fleet()
    fc = FailoverController(pmap, gate)
    seen = []
    epoch = fc.failover("m0", "s0", lambda dead, parts: seen.append((dead, parts)))
    assert epoch == 1
    assert seen == [("m0", (0,))]  # recover ran, with the claimed set
    assert pmap.owner(0) == "s0"
    (h,) = fc.history()
    assert h == {"dead": "m0", "standby": "s0", "partitions": [0], "epoch": 1}
    # Second attempt: nothing left to take over.
    assert fc.failover("m0", "s1", lambda d, p: None) is None


def test_failover_recovery_failure_releases_claim():
    pmap, gate = _dead_fleet()
    fc = FailoverController(pmap, gate)

    def bad_recover(dead, parts):
        raise RuntimeError("snapshot restore failed")

    with pytest.raises(RuntimeError, match="restore failed"):
        fc.failover("m0", "s0", bad_recover)
    assert pmap.owner(0) == "m0"  # map untouched: crash-between-phases safe
    assert pmap.epoch == 0
    # The claim was released — another standby completes the handoff.
    assert fc.failover("m0", "s1", lambda d, p: None) == 1
    assert pmap.owner(0) == "s1"


# ---------------------------------------------------------------------------
# Deterministic interleaving: the failover claim race (PR 11 Interleaver)


def _race_failover(seed: int):
    """Two standbys race the full claim/recover/commit protocol for the
    same dead member under one seeded schedule. Recovery replays a fake
    WAL above the exactly-once match_seq cursor and yields mid-recovery
    — the widest possible claim window."""
    pmap = PartitionMap.even(2, ["m0", "m1"])
    gate = HealthGate()
    gate.mark_down("m0")
    it = Interleaver(seed=seed, timeout_s=30.0)
    fc = FailoverController(pmap, gate, lock=SteppingLock(it.step))
    wal = [(s, f"order{s}") for s in range(1, 9)]
    cursor = 3  # durable match_seq: replay must start at 4
    replayed: dict[str, list[int]] = {}

    def contender(name):
        def recover(dead, parts):
            out = replayed.setdefault(name, [])
            for s, _ in wal:
                it.step()  # recovery runs off-lock: the race window
                if s <= cursor:
                    continue  # exactly-once: below the cursor is replayed
                out.append(s)

        def fn(step):
            step()
            return fc.failover("m0", name, recover)

        return fn

    it.run(contender("s0"), contender("s1"))
    assert it.errors == [None, None]
    return it, pmap, fc, replayed


@pytest.mark.parametrize("seed", range(8))
def test_interleaved_failover_exactly_one_winner(seed):
    it, pmap, fc, replayed = _race_failover(seed)
    winners = [r for r in it.results if r is not None]
    assert len(winners) == 1, f"expected one epoch winner, got {it.results}"
    assert winners[0] == 1  # single reassignment: epoch 0 -> 1
    (h,) = fc.history()
    # Exactly one member consumed the reassigned partition: the loser's
    # claim failed BEFORE recovery, so it never touched the WAL.
    assert list(replayed) == [h["standby"]]
    assert replayed[h["standby"]] == [4, 5, 6, 7, 8]
    assert pmap.owner(0) == h["standby"]
    assert pmap.owner(1) == "m1"  # unrelated partition never moves


def test_interleaved_failover_replay_identical_across_schedules():
    replays = set()
    for seed in range(12):
        _, _, fc, replayed = _race_failover(seed)
        (h,) = fc.history()
        replays.add(tuple(replayed[h["standby"]]))
    # Whoever wins under whatever schedule, the replayed match_seqs are
    # the same — the cursor, not the schedule, decides what re-emits.
    assert replays == {(4, 5, 6, 7, 8)}


# ---------------------------------------------------------------------------
# AdmissionController


def _admission(depth, **kw):
    kw.setdefault("cache_s", 0.0)  # sample depth_fn on every admit
    kw.setdefault("registry", Registry())
    return AdmissionController(depth, **kw)


def test_admission_admits_below_ceiling():
    a = _admission(lambda: 10, max_depth=100)
    d = a.admit(5)
    assert d.ok and d.depth == 10
    assert a.admit(90).ok  # 10 + 90 == ceiling: still admitted


def test_admission_sheds_on_depth_with_scaled_hint():
    a = _admission(
        lambda: 200, max_depth=100, retry_after_s=0.05, retry_after_max_s=2.0
    )
    d = a.admit(1)
    assert not d.ok and d.reason == "depth" and d.depth == 200
    # Hint scales with overshoot: (200+1)/100 ~ 2x ceiling -> ~2x base.
    assert d.retry_after_s == pytest.approx(0.05 * 201 / 100)
    m = RETRY_AFTER_RE.search(d.message())
    assert m is not None  # clients parse the hint out of the message
    assert float(m.group(1)) == pytest.approx(d.retry_after_s, abs=1e-3)
    assert "queue depth 200" in d.message()


def test_admission_hint_clamps_to_max():
    a = _admission(
        lambda: 10_000_000, max_depth=100, retry_after_s=0.05,
        retry_after_max_s=2.0,
    )
    assert a.admit(1).retry_after_s == 2.0
    # And never below the base, however shallow the queue reads.
    b = _admission(lambda: 0, max_depth=100, retry_after_s=0.05)
    assert b._hint(0) == 0.05


def test_admission_sheds_on_tight_deadline_first():
    # Deadline shed fires even with an empty queue — the reply would be
    # DEADLINE_EXCEEDED garbage, so zero pipeline work is spent on it.
    a = _admission(lambda: 0, max_depth=100, min_deadline_s=0.5)
    d = a.admit(1, time_remaining_s=0.1)
    assert not d.ok and d.reason == "deadline"
    assert "deadline too tight" in d.message()
    assert a.admit(1, time_remaining_s=0.5).ok  # at the bound: admitted
    assert a.admit(1, time_remaining_s=None).ok  # no deadline set


def test_admission_counters_and_validation():
    reg = Registry()
    calls = []

    def depth():
        calls.append(1)
        return 101

    a = AdmissionController(
        depth, max_depth=100, cache_s=0.0, registry=reg
    )
    a.admit(3)
    a.admit(2, time_remaining_s=-1.0)  # min_deadline_s=0.0 > -1.0
    text = reg.render()
    assert 'gome_gateway_shed_total{reason="depth"} 3' in text
    assert 'gome_gateway_shed_total{reason="deadline"} 2' in text
    assert "gome_gateway_admission_depth 101" in text
    with pytest.raises(ValueError):
        _admission(lambda: 0, max_depth=0)
    with pytest.raises(ValueError):
        _admission(lambda: 0, retry_after_s=0.5, retry_after_max_s=0.1)


def test_admission_depth_cache_window():
    calls = []

    def depth():
        calls.append(1)
        return 0

    a = AdmissionController(
        depth, max_depth=100, cache_s=60.0, registry=Registry()
    )
    for _ in range(5):
        assert a.admit(1).ok
    assert len(calls) == 1  # hot path: one sample per cache window


# ---------------------------------------------------------------------------
# FrameBatcher adaptive sizing


class _Sink:
    def __init__(self):
        self.frames: list[bytes] = []

    def publish(self, data, headers=None):
        self.frames.append(data)
        return len(self.frames)


def _order(i):
    return Order(
        uuid="u", oid=f"o{i}", symbol="btc2usdt", side=Side.BUY,
        price=100 + i, volume=5, action=Action.ADD,
    )


def _adaptive(depth_fn, **kw):
    kw.setdefault("max_n", 100)
    kw.setdefault("min_n", 10)
    kw.setdefault("depth_low", 100)
    kw.setdefault("depth_high", 1100)
    kw.setdefault("resize_interval_s", 0.0)  # resample every call
    kw.setdefault("max_wait_s", 60.0)
    return FrameBatcher(_Sink(), depth_fn=depth_fn, **kw)


def test_adaptive_bound_interpolates_and_clamps():
    depth = [0]
    b = _adaptive(lambda: depth[0])
    try:
        assert b.effective_max_n() == 10  # shallow: latency mode
        depth[0] = 100
        assert b.effective_max_n() == 10  # at depth_low: still min_n
        depth[0] = 600  # midpoint of the band
        assert b.effective_max_n() == 55
        depth[0] = 1100
        assert b.effective_max_n() == 100  # at depth_high: throughput mode
        depth[0] = 10**9
        assert b.effective_max_n() == 100  # clamped above the band
        depth[0] = -50
        assert b.effective_max_n() == 10  # clamped below it
        st = b.stats()
        assert st["adaptive"] is True and st["effective_max_n"] == 10
    finally:
        b.close()


def test_adaptive_depth_fn_failure_falls_back_to_max_n():
    def boom():
        raise RuntimeError("bus gone")

    b = _adaptive(boom)
    try:
        # Throughput-safe fallback: an unreadable lag reads as "deep",
        # so the batcher amortizes instead of shrinking frames blind.
        assert b.effective_max_n() == 100
    finally:
        b.close()


def test_adaptive_flushes_at_effective_bound():
    depth = [0]
    b = _adaptive(lambda: depth[0], max_n=8, min_n=2, depth_low=10,
                  depth_high=20)
    try:
        for i in range(4):
            b.submit(_order(i))
        # Shallow queue -> effective bound 2 -> two frames of two.
        assert len(b.queue.frames) == 2
        depth[0] = 1000  # deep: bound grows to max_n=8
        for i in range(4, 10):
            b.submit(_order(i))
        assert len(b.queue.frames) == 2  # six buffered, bound now 8
        b.submit(_order(10))
        b.submit(_order(11))
        assert len(b.queue.frames) == 3  # flushed at 8
    finally:
        b.close()


def test_adaptive_validation_and_fixed_mode():
    with pytest.raises(ValueError, match="1 <= min_n <= max_n"):
        _adaptive(lambda: 0, min_n=0)
    with pytest.raises(ValueError, match="1 <= min_n <= max_n"):
        _adaptive(lambda: 0, min_n=101, max_n=100)
    with pytest.raises(ValueError, match="depth_low < depth_high"):
        _adaptive(lambda: 0, depth_low=5, depth_high=5)
    # min_n without depth_fn (or vice versa) = the fixed bound of <= r11.
    b = FrameBatcher(_Sink(), max_n=7, min_n=3, max_wait_s=60.0)
    try:
        assert b.effective_max_n() == 7
        assert b.stats()["adaptive"] is False
    finally:
        b.close()


# ---------------------------------------------------------------------------
# Fleet aggregator liveness (last-poll age / stale / member_up)


def _scripted_fetch(down: set):
    def fetch(url, timeout_s):
        proc, _, path = url.partition("://")[2].partition("/")
        if proc in down:
            raise ConnectionError("connection refused")
        path = "/" + path
        if path == "/healthz":
            return json.dumps({"healthy": True, "detail": {}})
        if path == "/metrics":
            return "# empty\n"
        if path == "/durability":
            return json.dumps({"matchfeed": {
                "last_seq": 1, "observed": 2, "dupes": 0, "gaps": 0,
            }})
        if path.startswith("/timeline"):
            return json.dumps({"samples": []})
        raise AssertionError(url)

    return fetch


def test_aggregator_staleness_and_member_up():
    now = [100.0]
    down: set = set()
    reg = Registry()
    agg = FleetAggregator()
    agg.install(
        {"a": "inproc://a", "b": "inproc://b"},
        interval_s=1.0, stale_after_s=5.0, clock=lambda: now[0],
        fetch=_scripted_fetch(down), registry=reg,
    )
    try:
        assert agg.poll_age_s("a") is None  # never scraped yet
        assert not agg.member_up("a")
        agg.poll()
        assert agg.poll_age_s("a") == 0.0
        assert agg.member_up("a") and agg.member_up("b")
        assert 'gome_fleet_member_up{proc="a"} 1' in reg.render()
        payload = agg.payload()
        assert payload["unreachable"] == []
        assert payload["stale_after_s"] == 5.0
        assert payload["members"]["a"]["up"] is True
        assert payload["members"]["a"]["stale"] is False

        # b stops answering: its poll age keeps growing while a's resets.
        down.add("b")
        now[0] += 3.0
        agg.poll()
        assert agg.member_up("a")
        assert not agg.member_up("b")  # latest scrape errored
        payload = agg.payload()
        assert payload["unreachable"] == ["b"]
        assert payload["members"]["b"]["error"] is not None
        assert payload["members"]["b"]["poll_age_s"] == 3.0

        # Past stale_after_s without a successful scrape: STALE, down.
        now[0] += 3.0
        agg.poll()
        assert agg.poll_age_s("b") == 6.0
        payload = agg.payload()
        assert payload["members"]["b"]["stale"] is True
        text = reg.render()
        assert 'gome_fleet_member_up{proc="a"} 1' in text
        assert 'gome_fleet_member_up{proc="b"} 0' in text

        # Recovery: one good scrape snaps b back up.
        down.discard("b")
        now[0] += 1.0
        agg.poll()
        assert agg.member_up("b")
        assert agg.payload()["unreachable"] == []
    finally:
        agg.disable()


def test_aggregator_stale_after_validation_and_default():
    agg = FleetAggregator()
    with pytest.raises(ValueError, match="stale_after_s"):
        agg.install({"a": "inproc://a"}, stale_after_s=0.0)
    agg.install(
        {"a": "inproc://a"}, interval_s=2.0, registry=Registry(),
        fetch=_scripted_fetch(set()),
    )
    try:
        assert agg.stale_after_s == 6.0  # default: 3x the poll interval
    finally:
        agg.disable()


# ---------------------------------------------------------------------------
# Client retry of the retryable status (code 14)


def _resp(code=0, accepted=0, reject_index=(), message=""):
    return SimpleNamespace(
        code=code, accepted=accepted, reject_index=list(reject_index),
        message=message,
    )


def test_send_batch_retrying_resubmits_only_the_tail():
    orders = [f"o{i}" for i in range(6)]
    cancels = [f"c{i}" for i in range(6)]
    seen = []
    sleeps = []
    script = [
        _resp(code=CODE_RETRYABLE, accepted=2, reject_index=[2],
              message="overloaded, queue depth 9 (retry-after=0.123s)"),
        _resp(code=0, accepted=3),
    ]

    def send(orders, cancel):
        seen.append((list(orders), list(cancel)))
        return script.pop(0)

    out = send_batch_retrying(
        send, orders, cancels, policy=BackoffPolicy(base_s=0.001, max_s=0.001),
        rng=random.Random(0), sleep=sleeps.append,
    )
    assert out == {"ok": 5, "rejected": 1, "aborted": 0, "retries": 1}
    # Remainder contract: consumed prefix = accepted + len(reject_index),
    # so the retry resubmitted exactly the unconsumed tail — both lists.
    assert seen[1] == (["o3", "o4", "o5"], ["c3", "c4", "c5"])
    assert len(sleeps) == 1
    assert sleeps[0] >= 0.123  # server hint is a floor under the jitter


def test_send_batch_retrying_budget_exhaustion_aborts_tail():
    def send(orders, cancel):
        return _resp(code=CODE_RETRYABLE, accepted=1,
                     message="overloaded, queue depth 9 (retry-after=0.001s)")

    out = send_batch_retrying(
        send, [f"o{i}" for i in range(10)], None,
        policy=BackoffPolicy(base_s=0.0001, max_s=0.0001, max_retries=2),
        rng=random.Random(0), sleep=lambda s: None,
    )
    # 3 sends (initial + 2 retries), 1 accepted each; the rest aborts
    # loudly instead of hammering a drowning gateway forever.
    assert out["ok"] == 3 and out["retries"] == 2 and out["aborted"] == 7


def test_send_batch_retrying_permanent_abort_not_resubmitted():
    sends = []

    def send(orders, cancel):
        sends.append(len(orders))
        return _resp(code=3, accepted=2, message="batch aborted at entry 2")

    out = send_batch_retrying(send, [f"o{i}" for i in range(5)], None,
                              sleep=lambda s: None)
    assert sends == [5]  # permanent code: never resubmitted
    assert out == {"ok": 2, "rejected": 0, "aborted": 3, "retries": 0}


# ---------------------------------------------------------------------------
# The committed fleet chaos verdict


def test_fleet_chaos_verdict_pinned_green():
    """FLEET_CHAOS_r01.json is the committed proof that the 2x2 fleet
    survives rotating member kills: injected deaths only, exactly-once
    across the fleet, bit-exact books vs the uninterrupted oracle,
    bounded recovery, and a throughput floor while a member is down.
    Regenerate with scripts/fleet_chaos.py; a red verdict must never be
    committed."""
    path = REPO / "FLEET_CHAOS_r01.json"
    doc = json.loads(path.read_text())
    assert doc["schema"] == "gome-fleet-chaos-verdict-v1"
    assert doc["pass"] is True
    assert all(doc["checks"].values()), {
        k: v for k, v in doc["checks"].items() if not v
    }

    # >= 3 kill/restart cycles covering all three fault classes.
    cycles = doc["cycles"]
    classes = {c["class"] for c in cycles}
    assert classes == {"consumer-kill", "gateway-kill", "bus-disconnect"}
    kills = [c for c in cycles if c["class"] != "bus-disconnect"]
    assert len(kills) >= 2 and len(cycles) >= 3

    # Every partition: zero dupes/gaps at first_seq=0, books bit-exact
    # against the oracle, and the full match stream byte-identical.
    for part in doc["partitions"]:
        audit = part["seq_audit"]
        assert audit["dupes"] == 0 and audit["gaps"] == 0
        assert audit["observed"] == audit["last_seq"] + 1
        assert part["digest_match"] is True
        assert part["book_digest"] == part["oracle_digest"]
        assert part["match_stream_identical"] is True
        assert part["feed"]["dupes"] == 0 and part["feed"]["gaps"] == 0

    # Deaths were ours alone, and every consumer kill failed over
    # through the claim/recover/commit protocol (epoch advanced).
    assert doc["checks"]["injected_deaths_only"]
    consumer_kills = [c for c in cycles if c["class"] == "consumer-kill"]
    for c in consumer_kills:
        assert c["failover"]["epoch"] is not None
    assert len(doc["router"]["failovers"]) == len(consumer_kills)

    # Recovery bounded, degraded throughput above the floor.
    rec = doc["recovery"]
    assert len(rec["samples_s"]) == len(kills)
    assert rec["p99_s"] <= doc["config"]["recovery_bound_s"]
    floor = doc["throughput"]["floor_orders_per_s"]
    assert len(doc["throughput"]["degraded_windows"]) == len(kills)
    for w in doc["throughput"]["degraded_windows"].values():
        assert w["orders_per_s"] >= floor

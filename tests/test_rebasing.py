"""Per-lane price rebasing (32-bit books): absolute tick magnitudes beyond
int32 (e.g. BTC at accuracy 8 ~ 1e13 ticks) match exactly, recentering
shifts resting books without disturbing state, and bases survive
snapshot/restore."""

import numpy as np
import pytest

import jax.numpy as jnp

from gome_tpu.engine import BatchEngine, BookConfig
from gome_tpu.engine.batch import CapacityError
from gome_tpu.oracle import OracleEngine
from gome_tpu.types import Action, Order, Side

BTC = 10_000_000_000_000  # 1e13 ticks = $100k at accuracy 8


def _cfg32(**kw):
    return BookConfig(cap=32, max_fills=8, dtype=jnp.int32, **kw)


def test_btc_scale_prices_match_oracle():
    rng = np.random.default_rng(5)
    orders = []
    for i in range(120):
        is_del = i > 20 and rng.random() < 0.15
        ref = rng.integers(1, i) if is_del else i
        orders.append(
            Order(
                uuid="u", oid=str(ref if is_del else i), symbol="btc2usdt",
                side=Side(int(rng.integers(0, 2))),
                price=BTC + int(rng.integers(-500_000, 500_000)),
                volume=int(rng.integers(1, 50)),
                action=Action.DEL if is_del else Action.ADD,
            )
        )
    oracle = OracleEngine()
    expected = []
    for o in orders:
        expected.extend(oracle.process(o))
    for use_columnar in (False, True):
        eng = BatchEngine(_cfg32(), n_slots=2, max_t=64)
        got = []
        for i in range(0, len(orders), 48):
            chunk = orders[i : i + 48]
            if use_columnar:
                got.extend(eng.process_columnar(chunk).to_results())
            else:
                got.extend(eng.process(chunk))
        assert got == expected, f"columnar={use_columnar}"
        assert all(e.match_node.price > (1 << 31) for e in got if not e.is_cancel)


def test_recentering_preserves_resting_book():
    """Rest an order, drift the flow by > REBASE_LIMIT ticks (forces a
    recenter + device price shift), then cancel the original order at its
    absolute price: the cancel must still find it."""
    eng = BatchEngine(_cfg32(), n_slots=2, max_t=32)
    drift = BatchEngine.REBASE_LIMIT + 50_000
    rest = Order(uuid="u", oid="r", symbol="s", side=Side.BUY,
                 price=BTC, volume=7)
    far = Order(uuid="u", oid="f", symbol="s", side=Side.SALE,
                price=BTC + drift, volume=3)
    assert eng.process([rest]) == []
    base0 = int(eng.price_base[0])
    assert eng.process([far]) == []  # far ask rests; triggers recenter
    assert int(eng.price_base[0]) != base0
    # the resting bid survived the shift at its absolute price
    cancel = Order(uuid="u", oid="r", symbol="s", side=Side.BUY,
                   price=BTC, volume=0, action=Action.DEL)
    events = eng.process([cancel])
    assert len(events) == 1 and events[0].is_cancel
    assert events[0].node.volume == 7
    assert events[0].node.price == BTC


def test_window_exhaustion_raises():
    eng = BatchEngine(_cfg32(), n_slots=2, max_t=32)
    eng.process([Order(uuid="u", oid="a", symbol="s", side=Side.BUY,
                       price=BTC, volume=1)])
    with pytest.raises(CapacityError, match="2\\^31 ticks"):
        eng.process([Order(uuid="u", oid="b", symbol="s", side=Side.BUY,
                           price=BTC + (1 << 33), volume=1)])


def test_wide_first_batch_rejected_not_corrupted():
    """A first micro-batch whose price span exceeds the int32 window must
    raise CapacityError (regression: it used to seed an unchecked base and
    silently wrap prices on the columnar path)."""
    for use_columnar in (False, True):
        eng = BatchEngine(_cfg32(), n_slots=2, max_t=32)
        orders = [
            Order(uuid="u", oid="a", symbol="s", side=Side.SALE,
                  price=1, volume=1),
            Order(uuid="u", oid="b", symbol="s", side=Side.BUY,
                  price=BTC, volume=1),
        ]
        fn = eng.process_columnar if use_columnar else eng.process
        with pytest.raises(CapacityError, match="2\\^31 ticks"):
            fn(orders)


def test_market_price_ignored_by_envelope():
    """A MARKET order with Price:0 (in contract: price is ignored for
    MARKET) must neither widen the lane's price envelope nor overflow the
    rebased encoding (regression: it permanently poisoned the lane)."""
    from gome_tpu.types import OrderType

    oracle = OracleEngine()
    orders = [
        Order(uuid="u", oid="a", symbol="s", side=Side.SALE,
              price=BTC, volume=5),
        Order(uuid="u", oid="m", symbol="s", side=Side.BUY,
              price=0, volume=3, order_type=OrderType.MARKET),
        Order(uuid="u", oid="b", symbol="s", side=Side.SALE,
              price=BTC + 10, volume=2),
    ]
    expected = []
    for o in orders:
        expected.extend(oracle.process(o))
    for use_columnar in (False, True):
        eng = BatchEngine(_cfg32(), n_slots=2, max_t=32)
        fn = (
            (lambda os_: eng.process_columnar(os_).to_results())
            if use_columnar
            else eng.process
        )
        got = fn(orders)
        assert got == expected, f"columnar={use_columnar}"
        assert len(got) == 1 and got[0].match_node.price == BTC


def test_pre_rebasing_snapshot_restores_base_zero():
    """Restoring a snapshot without rebasing metadata (older format) must
    mark occupied lanes base-set at 0 so absolute stored prices keep
    matching (regression: flow after restore silently stopped matching)."""
    eng = BatchEngine(_cfg32(), n_slots=2, max_t=32)
    # int32-representable absolute prices, as a pre-rebasing snapshot had
    rest = Order(uuid="u", oid="a", symbol="s", side=Side.SALE,
                 price=1_000_000, volume=5)
    eng.process([rest])
    state = eng.export_state()
    for k in ("price_base", "base_set", "env_lo", "env_hi"):
        del state[k]
    # the old format stored absolute prices (base 0 everywhere)
    state["books"]["price"] = (
        np.asarray(state["books"]["price"]).astype(np.int64)
        + eng.price_base[:, None, None]
    ).astype(np.int32)
    fresh = BatchEngine(_cfg32(), n_slots=2, max_t=32)
    fresh.import_state(state)
    taker = Order(uuid="u", oid="t", symbol="s", side=Side.BUY,
                  price=1_000_100, volume=5)
    events = fresh.process([taker])
    assert len(events) == 1 and events[0].match_node.price == 1_000_000


def test_bases_survive_snapshot_roundtrip():
    eng = BatchEngine(_cfg32(), n_slots=2, max_t=32)
    eng.process([Order(uuid="u", oid="a", symbol="s", side=Side.SALE,
                       price=BTC + 10, volume=5)])
    state = eng.export_state()
    fresh = BatchEngine(_cfg32(), n_slots=2, max_t=32)
    fresh.import_state(state)
    np.testing.assert_array_equal(fresh.price_base, eng.price_base)
    taker = Order(uuid="u", oid="t", symbol="s", side=Side.BUY,
                  price=BTC + 100, volume=5)
    ev1 = eng.process([taker])
    ev2 = fresh.process([taker])
    assert ev1 == ev2
    assert len(ev1) == 1 and ev1[0].match_node.price == BTC + 10


def test_lane_books_view_absolute():
    eng = BatchEngine(_cfg32(), n_slots=2, max_t=32)
    eng.process([Order(uuid="u", oid="a", symbol="s", side=Side.SALE,
                       price=BTC + 42, volume=5)])
    books = eng.lane_books()
    lane = eng.symbol_lane("s")
    assert int(books.price[lane, 1, 0]) == BTC + 42

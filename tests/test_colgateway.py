"""Round 11 columnar front door: wire-format pins, cross-version decode
compat, scalar/columnar admission parity, and the batcher's mixed buffer.

The columnar admit core (service/gateway._apply_columnar) promises
per-row semantics IDENTICAL to the scalar loop — same accept/reject
decisions, same reject codes and byte-for-byte messages, same pre-pool
contents, same decoded orders on the wire — while never running
per-order Python on the accept path. These tests hold it to that:

  * golden byte pins: the GCO2/GCO3 encodings of a fixed 64-order
    fixture are pinned by sha256, so any writer-side layout drift is a
    loud test failure, not a silent wire break;
  * cross-version decode: a hand-built GCO1 (pre-cache dict layout) and
    GCO4 frames (single- and multi-block) decode to exactly the GCO2
    columns — all four layouts normalize to one contract;
  * parity: seeded batches mixing good, malformed, suspect-range and
    cancel rows go through a scalar-pinned gateway (columnar=False) and
    a columnar one side by side, comparing every response field, the
    pool, and the decoded wire;
  * abort parity: closed-batcher and degraded-bus failures produce the
    same code/message/accepted and leave no dangling marks on either
    path (block-granular unwind — MIGRATION.md round 11);
  * FrameBatcher.submit_block: closed/backpressure contracts, mixed
    Order+block buffers flushing as frames in arrival order.
"""

from __future__ import annotations

import hashlib
import struct

import numpy as np
import pytest

from gome_tpu.api import order_pb2 as pb
from gome_tpu.bus import MemoryQueue, QueueBus
from gome_tpu.bus.codec import decode_order
from gome_tpu.bus.colwire import (
    ORDER_MAGIC,
    ORDER_MAGIC_BLOCKS,
    decode_order_frame,
    encode_order_frame_blocks,
    encode_orders,
)
from gome_tpu.engine.prepool import LocalPrePool
from gome_tpu.service.batcher import Backpressure, FrameBatcher
from gome_tpu.service.gateway import OrderGateway, orders_from_columns
from gome_tpu.types import Action, Order, OrderType, Side

# ---------------------------------------------------------------------------
# The 64-order golden fixture: every enum value, dict-column reuse
# (5 uuids / 7 symbols cycling), mixed ADD/DEL and LIMIT/MARKET, and —
# in the traced variant — a sparse trace column (every 6th order).


def mk(i: int, traced: bool = False) -> Order:
    return Order(
        uuid=f"u{i % 5}",
        oid=f"o-{i}",
        symbol=f"sym{i % 7}",
        side=Side.BUY if i % 2 else Side.SALE,
        price=100_0000 + i * 13,
        volume=1 + (i % 9),
        action=Action.DEL if i % 8 == 7 else Action.ADD,
        order_type=OrderType.MARKET if i % 5 == 4 else OrderType.LIMIT,
        trace=(f"t{i}@{i}.5" if i % 6 == 0 else None) if traced else None,
    )


FIXTURE = [mk(i) for i in range(64)]
FIXTURE_TRACED = [mk(i, traced=True) for i in range(64)]

# Writer-side layout pins. If encode changes these on purpose, that is a
# WIRE VERSION BUMP (new magic), not a re-pin: deployed consumers sniff
# the magic and decode by it, so same-magic bytes must never move.
GCO2_SHA = "5b3772efcee1dbf2ca8e68ba2714a289fe3979147c68a3c0d5b2d130e6dee2b6"
GCO3_SHA = "94180ec9a3891f2f1ed9851f69f573bf936dba9e2100a5f114ac46193279ac30"


def _cols_equal(a: dict, b: dict) -> None:
    assert a["n"] == b["n"]
    for key in ("action", "side", "kind", "price", "volume"):
        np.testing.assert_array_equal(a[key], b[key])
    for values_key, idx_key in (
        ("symbols", "symbol_idx"),
        ("uuids", "uuid_idx"),
    ):
        # Dictionaries may be permuted across layouts; compare the
        # materialized per-row strings, not the dictionary order.
        av = [a[values_key][j] for j in np.asarray(a[idx_key]).tolist()]
        bv = [b[values_key][j] for j in np.asarray(b[idx_key]).tolist()]
        assert av == bv
    assert (
        np.asarray(a["oids"]).tolist() == np.asarray(b["oids"]).tolist()
    )


class TestGoldenWire:
    def test_gco2_bytes_pinned(self):
        frame = encode_orders(FIXTURE)
        assert frame[:4] == ORDER_MAGIC
        assert hashlib.sha256(frame).hexdigest() == GCO2_SHA

    def test_gco3_bytes_pinned(self):
        frame = encode_orders(FIXTURE_TRACED)
        assert frame[:4] == b"GCO3"
        assert hashlib.sha256(frame).hexdigest() == GCO3_SHA

    def test_roundtrip_recovers_fixture(self):
        cols = decode_order_frame(encode_orders(FIXTURE))
        assert orders_from_columns(cols) == FIXTURE

    def test_gco4_single_block_is_a_gco2_body(self):
        """GCO4 is pure framing: one block's bytes ARE a GCO2 body, so
        the gateway's per-batch block prefixed with ORDER_MAGIC would be
        a valid GCO2 frame, and the GCO4 frame is magic + header +
        exactly those bytes."""
        gco2 = encode_orders(FIXTURE)
        body = gco2[4:]
        frame = encode_order_frame_blocks([body])
        assert frame == ORDER_MAGIC_BLOCKS + struct.pack("<II", 64, 1) + body
        _cols_equal(decode_order_frame(frame), decode_order_frame(gco2))

    def test_gco1_decode_compat(self):
        """A hand-built v1 frame (dict columns WITHOUT the region-length
        prefix GCO2 added for the decode cache) still decodes to the
        same columns — deployed pre-cache producers keep working."""
        ref = decode_order_frame(encode_orders(FIXTURE))

        def dict_v1(values, idx):
            parts = [struct.pack("<I", len(values))]
            for s in values:
                b = s.encode()
                parts.append(struct.pack("<H", len(b)) + b)
            parts.append(np.ascontiguousarray(idx, np.uint32).tobytes())
            return b"".join(parts)

        oids = np.asarray(ref["oids"])
        v1 = b"".join(
            [
                b"GCO1",
                struct.pack("<I", ref["n"]),
                np.ascontiguousarray(ref["action"], np.uint8).tobytes(),
                np.ascontiguousarray(ref["side"], np.uint8).tobytes(),
                np.ascontiguousarray(ref["kind"], np.uint8).tobytes(),
                np.ascontiguousarray(ref["price"], np.int64).tobytes(),
                np.ascontiguousarray(ref["volume"], np.int64).tobytes(),
                dict_v1(ref["symbols"], ref["symbol_idx"]),
                dict_v1(ref["uuids"], ref["uuid_idx"]),
                struct.pack("<H", oids.dtype.itemsize) + oids.tobytes(),
            ]
        )
        _cols_equal(decode_order_frame(v1), ref)

    def test_gco4_multi_block_merges_dictionaries(self):
        """Blocks with overlapping symbol/uuid universes merge into one
        deduplicated dictionary with remapped index columns; row order
        is block order."""
        splits = [FIXTURE[:20], FIXTURE[20:45], FIXTURE[45:]]
        bodies = [encode_orders(part)[4:] for part in splits]
        frame = encode_order_frame_blocks(bodies)
        cols = decode_order_frame(frame)
        assert orders_from_columns(cols) == FIXTURE
        assert len(cols["symbols"]) == len(set(cols["symbols"])) == 7
        assert len(cols["uuids"]) == len(set(cols["uuids"])) == 5

    def test_gco4_header_count_mismatch_raises(self):
        frame = bytearray(encode_order_frame_blocks([encode_orders(FIXTURE)[4:]]))
        frame[4:8] = struct.pack("<I", 63)  # lie about the total
        with pytest.raises(ValueError, match="GCO4 header count"):
            decode_order_frame(bytes(frame))

    def test_not_an_order_frame_raises(self):
        with pytest.raises(ValueError, match="not an ORDER frame"):
            decode_order_frame(b"GCXX" + b"\x00" * 16)

    def test_empty_blocks_raise(self):
        with pytest.raises(ValueError, match="at least one block"):
            encode_order_frame_blocks([])


# ---------------------------------------------------------------------------
# Scalar/columnar admission parity.


class _FailingQueue:
    """A bus order queue whose publish always fails (degraded broker)."""

    supports_headers = False

    def publish(self, body, headers=None):
        raise ConnectionError("broker down for the drill")


def _make_gateway(columnar: bool, queue=None, batcher=None, max_volume=None):
    queue = queue if queue is not None else MemoryQueue("doOrder")
    bus = QueueBus(queue, MemoryQueue("matchOrder"))
    pool = LocalPrePool()
    gw = OrderGateway(
        bus,
        accuracy=8,
        mark=lambda o: pool.add((o.symbol, o.uuid, o.oid)),
        unmark=lambda o: pool.discard((o.symbol, o.uuid, o.oid)),
        mark_frame=pool.mark_frame if columnar else None,
        unmark_frame=pool.unmark_frame if columnar else None,
        max_volume=max_volume,
        batcher=batcher,
        columnar=columnar,
    )
    return gw, pool, bus


def _emitted_orders(bus) -> list[Order]:
    """Decode everything the gateway published — per-order JSON from the
    scalar path, GCO4 frames from the columnar one — into Order lists
    (trace excluded from Order equality by the dataclass)."""
    out: list[Order] = []
    for msg in bus.order_queue.read_from(0, 10_000):
        if msg.body[:1] == b"G":
            out.extend(orders_from_columns(decode_order_frame(msg.body)))
        else:
            out.append(decode_order(msg.body))
    return out


def _req(uuid, oid, symbol, side, price, vol, kind=0):
    return pb.OrderRequest(
        uuid=uuid, oid=oid, symbol=symbol, transaction=side,
        price=price, volume=vol, kind=kind,
    )


def _seeded_batches(seed: int, n_batches: int, rows: int):
    """Batches mixing clean rows with every edge the admit masks must
    catch: bad enums, non-positive volumes, sub-tick prices, zero-price
    limits (but zero-price markets are FINE), lot-ceiling breaches,
    suspect >2**51-tick magnitudes that force the scalar recheck, and
    random cancel rows."""
    import random

    rng = random.Random(seed)
    batches = []
    for b in range(n_batches):
        reqs, cancel = [], []
        for r in range(rows):
            uuid = f"u{rng.randrange(6)}"
            oid = f"b{b}r{r}"
            sym = f"s{rng.randrange(4)}"
            side = rng.randrange(2)
            price, vol, kind = 1.0 + rng.randrange(100) / 4.0, float(
                rng.randrange(1, 50)
            ), 0
            is_cancel = False
            roll = rng.random()
            if roll < 0.06:
                side = 7  # invalid enum
            elif roll < 0.12:
                kind = 9  # invalid enum
            elif roll < 0.18:
                vol = float(-rng.randrange(0, 3))  # <= 0
            elif roll < 0.24:
                price = 1.000000001  # sub-tick at accuracy 8
            elif roll < 0.30:
                price, kind = 0.0, rng.randrange(2)  # limit rejects, market ok
            elif roll < 0.36:
                vol = 200_000.0  # over the 1e12-lot ceiling below
            elif roll < 0.42:
                price = 50_000_000.0 + rng.randrange(5)  # suspect range
            elif roll < 0.55:
                is_cancel = True
                if rng.random() < 0.5:
                    vol = 0.0  # cancels may carry zero volume
            reqs.append(_req(uuid, oid, sym, side, price, vol, kind))
            cancel.append(is_cancel)
        batches.append((reqs, cancel))
    return batches


def _assert_resp_equal(rs, rc):
    assert rs.code == rc.code
    assert rs.message == rc.message
    assert rs.accepted == rc.accepted
    assert list(rs.reject_index) == list(rc.reject_index)
    assert [(x.code, x.message) for x in rs.rejects] == [
        (x.code, x.message) for x in rc.rejects
    ]


class TestScalarColumnarParity:
    def test_batch_parity_on_seeded_mixed_streams(self):
        gs, pool_s, bus_s = _make_gateway(False, max_volume=10**12)
        gc, pool_c, bus_c = _make_gateway(True, max_volume=10**12)
        saw_reject = saw_cancel = 0
        for reqs, cancel in _seeded_batches(seed=1234, n_batches=6, rows=80):
            breq = pb.OrderBatchRequest(orders=reqs, cancel=cancel)
            rs = gs.DoOrderBatch(breq, None)
            rc = gc.DoOrderBatch(breq, None)
            _assert_resp_equal(rs, rc)
            saw_reject += len(rs.reject_index)
            saw_cancel += sum(cancel)
        assert saw_reject > 50 and saw_cancel > 50  # the mix actually mixed
        assert pool_s == pool_c
        assert _emitted_orders(bus_s) == _emitted_orders(bus_c)

    def test_batch_parity_all_clean_fast_path(self):
        """m == n skips the keep-mask gather — pin that branch too."""
        gs, pool_s, bus_s = _make_gateway(False)
        gc, pool_c, bus_c = _make_gateway(True)
        reqs = [
            _req(f"u{i % 3}", f"o{i}", "s", i % 2, 1.25 + i, 2.0)
            for i in range(32)
        ]
        rs = gs.DoOrderBatch(pb.OrderBatchRequest(orders=reqs), None)
        rc = gc.DoOrderBatch(pb.OrderBatchRequest(orders=reqs), None)
        _assert_resp_equal(rs, rc)
        assert rs.accepted == 32
        assert pool_s == pool_c and len(pool_c) == 32
        assert _emitted_orders(bus_s) == _emitted_orders(bus_c)

    def test_stream_parity(self):
        gs, pool_s, bus_s = _make_gateway(False, max_volume=10**12)
        gc, pool_c, bus_c = _make_gateway(True, max_volume=10**12)
        reqs = []
        for batch, _cancel in _seeded_batches(seed=77, n_batches=3, rows=50):
            reqs.extend(batch)
        rs = gs.DoOrderStream(iter(reqs), None)
        rc = gc.DoOrderStream(iter(reqs), None)
        _assert_resp_equal(rs, rc)
        assert pool_s == pool_c
        assert _emitted_orders(bus_s) == _emitted_orders(bus_c)

    def test_cancel_mask_length_reject_parity(self):
        for columnar in (False, True):
            gw, pool, _bus = _make_gateway(columnar)
            resp = gw.DoOrderBatch(
                pb.OrderBatchRequest(
                    orders=[_req("u", "o", "s", 0, 1.0, 1.0)],
                    cancel=[False, True],
                ),
                None,
            )
            assert resp.code == 3 and resp.accepted == 0
            assert "cancel mask length 2 != orders length 1" in resp.message
            assert not pool

    def test_closed_batcher_abort_parity(self):
        """Both paths: a leading per-row reject keeps its row status, the
        abort anchors at the first ACCEPTED entry, and no mark dangles
        (the columnar block unwinds wholesale)."""
        responses, pools = [], []
        for columnar in (False, True):
            batcher = FrameBatcher(
                MemoryQueue("doOrder"), max_n=64, max_wait_s=60
            )
            batcher.close()
            gw, pool, _bus = _make_gateway(columnar, batcher=batcher)
            resp = gw.DoOrderBatch(
                pb.OrderBatchRequest(
                    orders=[
                        _req("u1", "bad", "s", 7, 1.0, 1.0),  # enum reject
                        _req("u1", "a", "s", 0, 1.0, 1.0),
                        _req("u2", "b", "s", 1, 1.0, 2.0),
                    ]
                ),
                None,
            )
            responses.append(resp)
            pools.append(pool)
        rs, rc = responses
        _assert_resp_equal(rs, rc)
        assert rc.code == 3 and rc.accepted == 0
        assert (
            "batch aborted at entry 1: FrameBatcher is closed" in rc.message
        )
        assert list(rc.reject_index) == [0]
        assert pools[0] == pools[1] == set()

    def test_degraded_bus_abort_parity(self):
        responses, pools = [], []
        for columnar in (False, True):
            gw, pool, _bus = _make_gateway(columnar, queue=_FailingQueue())
            resp = gw.DoOrderBatch(
                pb.OrderBatchRequest(
                    orders=[
                        _req("u1", "a", "s", 0, 1.0, 1.0),
                        _req("u2", "b", "s", 1, 1.0, 2.0),
                    ]
                ),
                None,
            )
            responses.append(resp)
            pools.append(pool)
        rs, rc = responses
        _assert_resp_equal(rs, rc)
        assert rc.code == 14 and rc.accepted == 0  # retryable
        assert "batch aborted at entry 0: broker down" in rc.message
        assert pools[0] == pools[1] == set()

    def test_columnar_rejects_beyond_i64_wire_range(self):
        """Documented divergence (MIGRATION.md round 11): ticks that do
        not fit the i64 wire columns are rejected at the edge on the
        columnar path instead of crashing later in the encoder."""
        gw, pool, bus = _make_gateway(True)
        resp = gw.DoOrderBatch(
            pb.OrderBatchRequest(
                orders=[_req("u", "o", "s", 0, 1e15, 1.0)]  # 1e23 ticks
            ),
            None,
        )
        assert resp.accepted == 0 and list(resp.reject_index) == [0]
        assert "64-bit wire range" in resp.rejects[0].message
        assert not pool and not bus.order_queue.read_from(0, 10)


# ---------------------------------------------------------------------------
# FrameBatcher.submit_block and the mixed Order/block buffer.


class TestBatcherBlocks:
    def _block(self, orders: list[Order]):
        frame = encode_orders(orders)
        assert frame[:4] == ORDER_MAGIC  # untraced fixture only
        return frame[4:], len(orders)

    def test_submit_block_after_close_raises(self):
        batcher = FrameBatcher(MemoryQueue("doOrder"), max_n=64, max_wait_s=60)
        batcher.close()
        block, n = self._block(FIXTURE[:3])
        with pytest.raises(RuntimeError, match="closed; order not accepted"):
            batcher.submit_block(block, n)

    def test_submit_block_backpressure_when_spill_full(self):
        batcher = FrameBatcher(
            _FailingQueue(),
            max_n=1000,
            max_wait_s=60,
            spill_max_frames=1,
            retry_interval_s=60,
        )
        try:
            batcher.submit(FIXTURE[0])
            batcher.flush()  # frame lands in the spill (bus down)
            assert batcher.stats()["spill_depth"] == 1
            assert batcher.degraded
            block, n = self._block(FIXTURE[:2])
            with pytest.raises(Backpressure, match="spill full"):
                batcher.submit_block(block, n)
            with pytest.raises(Backpressure, match="spill full"):
                batcher.submit(FIXTURE[1])
        finally:
            batcher.close()  # logs the undelivered spill, loudly

    def test_mixed_buffer_flushes_runs_in_arrival_order(self):
        queue = MemoryQueue("doOrder")
        batcher = FrameBatcher(queue, max_n=10_000, max_wait_s=60)
        try:
            a1, a2, a3 = FIXTURE[0], FIXTURE[1], FIXTURE[2]
            b1, n1 = self._block(FIXTURE[8:11])
            b2, n2 = self._block(FIXTURE[11:13])
            batcher.submit(a1)
            batcher.submit(a2)
            batcher.submit_block(b1, n1)
            assert batcher.stats()["buffered"] == 2 + n1
            batcher.submit(a3)
            batcher.submit_block(b2, n2)
            assert batcher.flush() == 3 + n1 + n2
            msgs = queue.read_from(0, 10)
            assert [m.body[:4] for m in msgs] == [
                b"GCO2", b"GCO4", b"GCO2", b"GCO4"
            ]
            decoded = []
            for m in msgs:
                decoded.extend(
                    orders_from_columns(decode_order_frame(m.body))
                )
            assert decoded == (
                [a1, a2] + FIXTURE[8:11] + [a3] + FIXTURE[11:13]
            )
        finally:
            batcher.close()

    def test_consecutive_blocks_join_into_one_gco4_frame(self):
        queue = MemoryQueue("doOrder")
        batcher = FrameBatcher(queue, max_n=10_000, max_wait_s=60)
        try:
            b1, n1 = self._block(FIXTURE[:5])
            b2, n2 = self._block(FIXTURE[5:7])
            batcher.submit_block(b1, n1)
            batcher.submit_block(b2, n2)
            batcher.flush()
            msgs = queue.read_from(0, 10)
            assert len(msgs) == 1
            n_total, n_blocks = struct.unpack_from("<II", msgs[0].body, 4)
            assert (n_total, n_blocks) == (n1 + n2, 2)
            assert (
                orders_from_columns(decode_order_frame(msgs[0].body))
                == FIXTURE[:7]
            )
        finally:
            batcher.close()

    def test_block_counts_trip_the_size_bound(self):
        queue = MemoryQueue("doOrder")
        batcher = FrameBatcher(queue, max_n=4, max_wait_s=60)
        try:
            block, n = self._block(FIXTURE[:5])  # 5 orders >= max_n=4
            batcher.submit_block(block, n)
            msgs = queue.read_from(0, 10)  # flushed on the submit itself
            assert len(msgs) == 1 and msgs[0].body[:4] == b"GCO4"
            assert batcher.stats()["buffered"] == 0
        finally:
            batcher.close()

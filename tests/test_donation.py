"""Buffer donation on the jitted engine entries (gomelint GL6xx applied):
the `_donating` twins are configured with the audited donate_argnums, they
produce results identical to the public (reuse-safe) entries, donated
inputs actually die on donation-supporting backends, and the engine's
host-sourced dispatch path survives escalation replays with donation on.
"""

import ast
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gome_tpu.engine import BatchEngine, BookConfig, batch_step, init_books
from gome_tpu.engine.batch import (
    batch_step_donating,
    dense_batch_step,
    dense_batch_step_donating,
    lane_scan,
    lane_scan_donating,
)
from gome_tpu.engine.book import DeviceOp
from gome_tpu.types import Order, Side

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CFG = BookConfig(cap=8, max_fills=4)


def _grid(config, s=2, t=4, seed=7):
    rng = np.random.default_rng(seed)
    d = np.dtype(config.dtype)
    g = dict(
        action=np.ones((s, t), np.int32),  # all ADDs
        side=rng.integers(0, 2, (s, t)).astype(np.int32),
        is_market=np.zeros((s, t), np.int32),
        price=(100 + rng.integers(0, 5, (s, t))).astype(d),
        volume=(1 + rng.integers(0, 3, (s, t))).astype(d),
        oid=np.arange(1, s * t + 1, dtype=d).reshape(s, t),
        uid=np.ones((s, t), d),
    )
    return DeviceOp(**g)


def _donation_effective() -> bool:
    """Does this backend actually consume donated buffers? (The test
    contract: assert semantics everywhere, assert deletion only where
    the platform implements donation — elsewhere it is a silent no-op.)"""
    import functools

    f = functools.partial(jax.jit, donate_argnums=(0,))(lambda x: x + 1)
    probe = jnp.ones((4,), jnp.int32)
    f(probe)
    return probe.is_deleted()


def _spec(wrapper: str):
    from gome_tpu.analysis.donation import wrapper_jit_spec

    path = os.path.join(ROOT, "gome_tpu", "engine", "batch.py")
    with open(path, encoding="utf-8") as fh:
        tree = ast.parse(fh.read())
    return wrapper_jit_spec(tree, wrapper)


# --- configuration: the audited donate_argnums are actually declared ----


def test_donating_twins_are_configured():
    """Tier-1, platform-independent: the donation the GL6xx audit signed
    off on is present in the source (a regressed donate_argnums would
    resurrect the double-buffer silently)."""
    assert _spec("batch_step")[1] == ()
    assert _spec("batch_step_donating")[1] == (2,)
    assert _spec("dense_batch_step_donating")[1] == (3,)
    assert _spec("dense_kernel_step_donating")[1] == (3,)
    assert _spec("full_kernel_step_donating")[1] == (2,)
    assert _spec("lane_scan_donating")[1] == (1, 2)

    from gome_tpu.analysis.donation import wrapper_jit_spec

    with open(os.path.join(ROOT, "gome_tpu", "engine", "step.py"),
              encoding="utf-8") as fh:
        step_tree = ast.parse(fh.read())
    assert wrapper_jit_spec(step_tree, "step")[1] == (1,)


# --- semantics: donating twins == public entries ------------------------


def test_batch_step_donating_matches_public():
    books = init_books(CFG, 2)
    ops = _grid(CFG)
    ref_books, ref_outs = batch_step(CFG, books, ops)
    don_books, don_outs = batch_step_donating(CFG, init_books(CFG, 2), ops)
    jax.tree.map(np.testing.assert_array_equal, ref_books, don_books)
    jax.tree.map(np.testing.assert_array_equal, ref_outs, don_outs)


def test_dense_step_donating_matches_public():
    books = init_books(CFG, 4)
    ops = _grid(CFG, s=2, t=4)
    ids = np.array([1, 3], np.int32)
    ref = dense_batch_step(CFG, books, jnp.asarray(ids), ops)
    don = dense_batch_step_donating(
        CFG, init_books(CFG, 4), jnp.asarray(ids), ops
    )
    jax.tree.map(np.testing.assert_array_equal, ref, don)


def test_lane_scan_donating_matches_public():
    books = init_books(CFG, 1)
    one = jax.tree.map(lambda a: a[0], books)
    ops = jax.tree.map(lambda a: a[0], _grid(CFG, s=1))
    ref = lane_scan(CFG, one, ops)
    don = lane_scan_donating(
        CFG, jax.tree.map(lambda a: a[0], init_books(CFG, 1)), ops
    )
    jax.tree.map(np.testing.assert_array_equal, ref, don)


# --- donation is live: inputs die (skip where the backend no-ops) -------


def test_donated_ops_buffers_die():
    if not _donation_effective():
        pytest.skip("backend does not implement buffer donation (no-op)")
    books = init_books(CFG, 2)
    ops_dev = jax.device_put(_grid(CFG))  # device copy: donation visible
    batch_step_donating(CFG, books, ops_dev)
    assert ops_dev.action.is_deleted()
    # the UNdonated books survive (escalation/rollback liveness contract)
    assert not books.price.is_deleted()


def test_public_entry_never_donates():
    books = init_books(CFG, 2)
    ops_dev = jax.device_put(_grid(CFG))
    batch_step(CFG, books, ops_dev)
    assert not ops_dev.action.is_deleted()
    assert not books.price.is_deleted()


def test_single_op_step_donates_book():
    if not _donation_effective():
        pytest.skip("backend does not implement buffer donation (no-op)")
    from gome_tpu.engine.book import init_book
    from gome_tpu.engine.step import step

    book = init_book(CFG)
    op = jax.tree.map(lambda a: a[0, 0], jax.device_put(_grid(CFG)))
    new_book, _out = step(CFG, book, op)
    assert book.price.is_deleted()  # donated: book was threaded through
    assert not new_book.price.is_deleted()


# --- the engine's dispatch path with donation + escalation --------------


def _orders(n, symbol="BTC", side=Side.SALE):
    return [
        Order(action=1, symbol=symbol, oid=f"o{i}", uuid="u",
              price=1.0 + i / 100, volume=1.0, side=side)
        for i in range(n)
    ]


def test_engine_escalation_replays_with_donation():
    """cap-2 engine + 6 resting orders: phase-1 escalation replays the
    SAME numpy grid through the donating twin — host-sourced grids
    re-transfer per dispatch, so donation must never break the replay."""
    eng = BatchEngine(BookConfig(cap=2, max_fills=2), n_slots=1, max_t=8,
                      dense=False)
    events = eng.process(_orders(6))
    assert eng.stats.cap_escalations >= 1
    assert events == []  # same-side adds: everything rests, no fills
    counts = np.asarray(jax.device_get(eng.books.count))
    assert counts[0, int(Side.SALE)] == 6
    eng.verify_books()


def test_engine_process_columnar_roundtrip_with_donation():
    eng = BatchEngine(BookConfig(cap=8, max_fills=4), n_slots=2, max_t=8)
    eng.process_columnar(_orders(4))
    batch = eng.process_columnar(
        [Order(action=1, symbol="BTC", oid="t", uuid="u", price=2.0,
               volume=2.0, side=Side.BUY)]
    )
    assert len(batch) == 2  # crosses the two cheapest asks
    eng.verify_books()

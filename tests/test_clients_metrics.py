"""Client drivers (doorder/delorder ports) + metrics/tracing tests."""

from concurrent import futures

import grpc
import pytest

from gome_tpu.api.service import add_order_servicer
from gome_tpu.clients import cancel_client, load_client
from gome_tpu.config import Config, EngineConfig
from gome_tpu.oracle import OracleEngine
from gome_tpu.service import EngineService
from gome_tpu.utils.metrics import Registry
from gome_tpu.utils.streams import doorder_stream


@pytest.fixture
def served():
    svc = EngineService(
        Config(engine=EngineConfig(cap=512, n_slots=4, max_t=2048))
    )
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
    add_order_servicer(server, svc.gateway)
    port = server.add_insecure_port("127.0.0.1:0")
    server.start()
    yield svc, f"127.0.0.1:{port}"
    server.stop(grace=None)


def test_load_client_drives_service(served):
    """The doorder.go-shaped blaster (seeded) produces the same books as the
    oracle fed the equivalent stream — full-stack parity under load."""
    svc, target = served
    stats = load_client(target, n=400, seed=123)
    assert stats["sent"] == 399 and stats["rejected"] == 0
    n = svc.pump()
    assert n == 399

    # Oracle referee: same RNG sequence as the client (mirrored generator).
    import random

    from gome_tpu.fixed import scale
    from gome_tpu.types import Order, Side

    rng = random.Random(123)
    oracle = OracleEngine()
    for i in range(1, 400):
        side = Side(rng.randrange(2))
        price = round(rng.uniform(0.01, 1.0), 2)
        volume = round(rng.uniform(0.01, 1.0), 2)
        oracle.process(
            Order(
                uuid="2", oid=str(i), symbol="eth2usdt", side=side,
                price=scale(price), volume=scale(volume),
            )
        )
    # Compare event streams via the match queue
    from gome_tpu.bus import decode_match_result

    mq = svc.bus.match_queue
    got = [decode_match_result(m.body) for m in mq.read_from(0, mq.end_offset())]
    assert got == oracle.events


def test_cancel_client(served):
    svc, target = served
    from gome_tpu.api import order_pb2 as pb
    from gome_tpu.api.service import OrderStub

    with grpc.insecure_channel(target) as ch:
        OrderStub(ch).DoOrder(
            pb.OrderRequest(
                uuid="2", oid="11", symbol="eth2usdt",
                transaction=pb.SALE, price=0.5, volume=1.0,
            )
        )
    svc.pump()
    resp = cancel_client(target, transaction=1)  # delorder.go's hardcoded op
    assert resp.code == 0
    svc.pump()
    books = svc.engine.batch.lane_books()
    assert int(books.count.sum()) == 0


def test_metrics_registry():
    reg = Registry()
    c = reg.counter("x_total", "things")
    c.inc()
    c.inc(4)
    assert c.value() == 5
    g = reg.gauge("g", "level")
    g.set(2.5)
    h = reg.histogram("lat_seconds", "latency")
    for v in (0.001, 0.002, 0.003, 0.2):
        h.observe(v)
    v = h.value()
    assert v["count"] == 4 and v["sum"] == pytest.approx(0.206)
    assert 0.0005 < v["p50"] < 0.01
    assert reg.counter("x_total") is c  # same instance by name
    text = reg.render()
    assert "x_total 5" in text and "# TYPE lat_seconds histogram" in text
    assert 'lat_seconds_bucket{le="+Inf"} 4' in text
    snap = reg.snapshot()
    assert snap["g"] == 2.5


def test_histogram_timer():
    reg = Registry()
    h = reg.histogram("t_seconds")
    with h.time():
        pass
    assert h.value()["count"] == 1


def test_consumer_updates_metrics():
    from gome_tpu.bus import encode_order
    from gome_tpu.utils.metrics import REGISTRY

    before = REGISTRY.counter("gome_orders_consumed_total").value()
    svc = EngineService(Config(engine=EngineConfig(cap=32, n_slots=4, max_t=8)))
    for o in doorder_stream(n=20):
        svc.engine.mark(o)
        svc.bus.order_queue.publish(encode_order(o))
    svc.pump()
    assert REGISTRY.counter("gome_orders_consumed_total").value() == before + 20
    assert REGISTRY.gauge("gome_orders_per_second").value() > 0


def test_tracing_annotations_are_usable():
    # host annotation + maybe_trace no-op path (full device trace exercised
    # in bench/profiling runs, not unit tests)
    from gome_tpu.utils.tracing import annotate, maybe_trace

    with maybe_trace(None):
        with annotate("unit-test-phase"):
            x = 1 + 1
    assert x == 2

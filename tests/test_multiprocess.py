"""Multi-process deployment over the file bus: a producer process (gateway
role) publishes orders into the shared bus directory; the consumer process
(this one) drains them through the device engine and publishes MatchResults
— the reference's three-process topology with the file bus standing in for
RabbitMQ (MIGRATION.md 'process topology')."""

import os
import subprocess
import sys

import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from gome_tpu.bus import decode_match_result, make_bus
from gome_tpu.config import BusConfig
from gome_tpu.engine.orchestrator import MatchEngine
from gome_tpu.engine.book import BookConfig
from gome_tpu.oracle import OracleEngine
from gome_tpu.service.consumer import OrderConsumer
from gome_tpu.utils.streams import doorder_stream

_PRODUCER = r"""
import sys
sys.path.insert(0, {repo!r})
from gome_tpu.bus import encode_order, make_bus
from gome_tpu.config import BusConfig
from gome_tpu.utils.streams import doorder_stream

bus = make_bus(BusConfig(backend="file", dir={busdir!r}))
orders = list(doorder_stream(n=120))
bus.order_queue.publish_batch([encode_order(o) for o in orders])
print(len(orders))
"""


def test_cross_process_file_bus_pipeline(tmp_path):
    busdir = str(tmp_path / "bus")
    out = subprocess.run(
        [sys.executable, "-c", _PRODUCER.format(repo=_REPO, busdir=busdir)],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    n_published = int(out.stdout.strip())

    orders = list(doorder_stream(n=120))  # same stream the producer sent
    oracle = OracleEngine()
    expected = []
    for o in orders:
        expected.extend(oracle.process(o))

    bus = make_bus(BusConfig(backend="file", dir=busdir))
    engine = MatchEngine(BookConfig(cap=64, max_fills=8), n_slots=4)
    for o in orders:
        engine.mark(o)  # gateway-side marks (shared-process pre-pool model)
    consumer = OrderConsumer(engine, bus, batch_n=64)
    drained = consumer.drain()
    assert drained == n_published == len(orders)

    msgs = bus.match_queue.read_from(0, 10_000)
    events = [decode_match_result(m.body) for m in msgs]
    assert events == expected
    engine.batch.verify_books()


_AMQP_PRODUCER = r"""
import sys
sys.path.insert(0, {repo!r})
from gome_tpu.bus import encode_order
from gome_tpu.bus.amqp import AmqpQueue
from gome_tpu.utils.streams import doorder_stream

q = AmqpQueue("doOrder", port={port})
orders = list(doorder_stream(n=120))
for o in orders:
    q.publish(encode_order(o))
q.close()
print(len(orders))
"""


def test_cross_process_amqp_pipeline():
    """The reference's ACTUAL topology: separate producer process speaking
    AMQP 0-9-1 over TCP to the broker; this process consumes, matches, and
    publishes MatchResults back over AMQP — the full rabbitmq.go story with
    the fake broker standing in for RabbitMQ."""
    from gome_tpu.bus import QueueBus
    from gome_tpu.bus.amqp import AmqpQueue
    from gome_tpu.bus.fakebroker import FakeBroker

    broker = FakeBroker().start()
    try:
        out = subprocess.run(
            [
                sys.executable, "-c",
                _AMQP_PRODUCER.format(repo=_REPO, port=broker.port),
            ],
            capture_output=True, text=True, timeout=120,
        )
        assert out.returncode == 0, out.stderr
        n_published = int(out.stdout.strip())

        orders = list(doorder_stream(n=120))
        oracle = OracleEngine()
        expected = []
        for o in orders:
            expected.extend(oracle.process(o))

        bus = QueueBus(
            AmqpQueue("doOrder", port=broker.port),
            AmqpQueue("matchOrder", port=broker.port),
        )
        engine = MatchEngine(BookConfig(cap=64, max_fills=8), n_slots=4)
        for o in orders:
            engine.mark(o)
        consumer = OrderConsumer(engine, bus, batch_n=64)
        drained = 0
        import time as _time

        deadline = _time.monotonic() + 30
        while drained < n_published and _time.monotonic() < deadline:
            drained += consumer.run_once()
        assert drained == n_published == len(orders)

        msgs = bus.match_queue.read_from(0, 10_000)
        events = [decode_match_result(m.body) for m in msgs]
        assert events == expected
        engine.batch.verify_books()
        bus.order_queue.close()
        bus.match_queue.close()
    finally:
        broker.stop()


def test_verify_books_catches_corruption():
    import jax
    import numpy as np
    import pytest

    engine = MatchEngine(BookConfig(cap=16, max_fills=4), n_slots=2)
    for o in doorder_stream(n=60):
        engine.mark(o)
        engine.process([o])
    from gome_tpu.engine.batch import BookInvariantError

    engine.batch.verify_books()  # healthy book passes
    # corrupt: swap the top two bid slots' prices on the device copy
    books = jax.device_get(engine.batch.books)
    lane = engine.batch.symbol_lane("eth2usdt")
    assert int(books.count[lane, 0]) >= 2, "stream must leave >=2 resting bids"
    price = np.asarray(books.price).copy()
    price[lane, 0, 0], price[lane, 0, 1] = (
        price[lane, 0, 1] - 1,
        price[lane, 0, 0] + 1,
    )
    engine.batch.books = jax.device_put(books._replace(price=price))
    with pytest.raises(BookInvariantError):
        engine.batch.verify_books()


_RESP_GATEWAY = r"""
import sys
sys.path.insert(0, {repo!r})
from gome_tpu.bus import encode_order, make_bus
from gome_tpu.config import BusConfig
from gome_tpu.engine.prepool import RespPrePool, make_marker
from gome_tpu.persist.resp import RespClient
from gome_tpu.types import Action, Order, Side
from gome_tpu.utils.streams import doorder_stream

pool = RespPrePool(RespClient(port={resp_port}))
mark = make_marker(pool)
bus = make_bus(BusConfig(backend="file", dir={busdir!r}))

orders = list(doorder_stream(n=80))
# The race (SURVEY 2.3.3): the gateway ACCEPTED raced:oid=race (marked it)
# but its DoOrder publish lost the race to a concurrent DeleteOrder
# publish, so the DEL lands in doOrder first.
add = Order(uuid="u9", oid="race", symbol="raced", side=Side.BUY,
            price=3_000_000, volume=7)
delete = Order(uuid="u9", oid="race", symbol="raced", side=Side.BUY,
               price=3_000_000, volume=0, action=Action.DEL)
mark(add)                      # gateway handler marked at accept
for o in orders:
    mark(o)                    # main.go:44-45 (ADDs only)
payloads = [encode_order(delete), encode_order(add)]
payloads += [encode_order(o) for o in orders]
bus.order_queue.publish_batch(payloads)
print(len(payloads))
"""


def test_three_process_prepool_reference_topology(tmp_path):
    """The reference's deployment shape with reference semantics: a marker
    server process (fake Redis speaking RESP2), a gateway process that
    marks the pre-pool THERE and publishes to the file bus, and this
    consumer process which never calls engine.mark — admission state flows
    exclusively through the shared marker store, and the
    cancel-before-consume race drops the queued ADD exactly as
    engine.go:58-62 does."""
    from gome_tpu.engine.prepool import RespPrePool
    from gome_tpu.persist.resp import RespClient
    from gome_tpu.types import Action, Order, Side

    busdir = str(tmp_path / "bus")
    srv = subprocess.Popen(
        [sys.executable, "-m", "gome_tpu.persist.respserver", "--port", "0"],
        stdout=subprocess.PIPE, text=True, cwd=_REPO,
    )
    try:
        ready = srv.stdout.readline().split()
        assert ready and ready[0] == "READY", ready
        resp_port = int(ready[1])

        out = subprocess.run(
            [
                sys.executable, "-c",
                _RESP_GATEWAY.format(
                    repo=_REPO, busdir=busdir, resp_port=resp_port
                ),
            ],
            capture_output=True, text=True, timeout=120,
        )
        assert out.returncode == 0, out.stderr
        n_published = int(out.stdout.strip())

        # Consumer process (this one): NO engine.mark anywhere — admission
        # reads the marker server the gateway wrote.
        bus = make_bus(BusConfig(backend="file", dir=busdir))
        engine = MatchEngine(BookConfig(cap=64, max_fills=8), n_slots=4)
        engine.pre_pool = RespPrePool(RespClient(port=resp_port))
        consumer = OrderConsumer(engine, bus, batch_n=64)
        drained = consumer.drain()
        assert drained == n_published

        # Expected stream from the oracle under the same race interleaving.
        oracle = OracleEngine()
        add = Order(uuid="u9", oid="race", symbol="raced", side=Side.BUY,
                    price=3_000_000, volume=7)
        delete = Order(uuid="u9", oid="race", symbol="raced", side=Side.BUY,
                       price=3_000_000, volume=0, action=Action.DEL)
        oracle.pre_pool.add(("raced", "u9", "race"))
        oracle.queue.append(delete)
        oracle.queue.append(add)
        for o in doorder_stream(n=80):
            oracle.submit(o)
        expected = oracle.drain()

        msgs = bus.match_queue.read_from(0, 10_000)
        events = [decode_match_result(m.body) for m in msgs]
        assert events == expected
        # The raced ADD was dropped by admission: never rested anywhere.
        assert engine.stats.dropped_no_prepool == 1
        assert oracle.stats.dropped_no_prepool == 1
        lane = engine.batch.symbol_lane("raced")
        books = engine.batch.lane_books()
        assert int(np.asarray(books.count)[lane].sum()) == 0
        engine.batch.verify_books()
    finally:
        srv.terminate()
        srv.wait(timeout=10)


_CRASH_CONSUMER = r"""
import os
import sys
sys.path.insert(0, {repo!r})
mesh_n = {mesh_n}
if mesh_n:
    # Virtual CPU devices: flag spelling for older jax (read at backend
    # init), config option for newer — same dance as tests/conftest.py.
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
import jax
jax.config.update("jax_platforms", "cpu")
if mesh_n:
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        pass
from gome_tpu.bus import make_bus
from gome_tpu.config import BusConfig, PersistConfig
from gome_tpu.engine.book import BookConfig
from gome_tpu.engine.orchestrator import MatchEngine
from gome_tpu.engine.prepool import RespPrePool
from gome_tpu.persist.resp import RespClient
from gome_tpu.persist.snapshot import Persister
from gome_tpu.service.consumer import OrderConsumer

bus = make_bus(BusConfig(backend="file", dir={busdir!r}))
mesh = None
if mesh_n:
    from gome_tpu.parallel import make_mesh
    mesh = make_mesh(mesh_n)
engine = MatchEngine(BookConfig(cap=64, max_fills=8), n_slots=8, mesh=mesh)
engine.pre_pool = RespPrePool(RespClient(port={resp_port}))
persist = Persister(PersistConfig(dir={snapdir!r}, every_n_batches=1))
persist.attach(engine, bus)
consumer = OrderConsumer(
    engine, bus, batch_n=1, batch_wait_s=0, match_wire="frame",
    pipeline_depth=2, on_batch=persist.on_batch,
)
phase = {phase!r}
if phase == "crash":
    # Drain the first span (2 frames) -> consistent cut -> snapshot.
    consumer.drain()
    assert persist.snapshots_taken >= 1, "no snapshot at the cut"
    print("SNAPSHOTTED", flush=True)
    # Now feed two more frames WITHOUT resolving (pipeline depth 2 keeps
    # them in flight: books advanced, marks consumed in the EXTERNAL
    # store, offsets uncommitted, events unpublished) — then die hard.
    consumer.run_once()
    consumer.run_once()
    os.kill(os.getpid(), 9)
else:
    restored = persist.restore_latest()
    print(f"RESTORED {{restored}}", flush=True)
    consumer.drain()
    print("DRAINED", flush=True)
"""


@pytest.mark.parametrize("mesh_n", [0, 4])
def test_cross_process_crash_drill_external_marker_store(tmp_path, mesh_n):
    """VERDICT r3 weak #7 (+r4 #4: mesh_n=4 runs the same drill with the
    consumer's books MESH-SHARDED over 4 virtual devices — snapshot taken
    while sharded, restore into a sharded engine): kill -9 a shard
    consumer mid-pipelined-frame — marker store external (RESP server),
    order log durable (file bus) — restart, and the matchOrder stream
    must be EXACTLY the oracle's.

    The hard part this pins: the dead consumer had already consumed the
    in-flight frames' pre-pool marks in the external store (admission
    HDELs them at feed time), so recovery must re-mark the queued tail
    from the durable order log (Persistence._reconstruct_marks) or the
    replayed ADDs would silently drop as unmarked."""
    import time as _time

    from gome_tpu.bus.colwire import decode_event_frame, encode_orders
    from gome_tpu.engine.prepool import RespPrePool
    from gome_tpu.persist.resp import RespClient
    from gome_tpu.utils.streams import multi_symbol_stream

    busdir = str(tmp_path / "bus")
    snapdir = str(tmp_path / "snaps")
    srv = subprocess.Popen(
        [sys.executable, "-m", "gome_tpu.persist.respserver", "--port", "0"],
        stdout=subprocess.PIPE, text=True, cwd=_REPO,
    )
    try:
        ready = srv.stdout.readline().split()
        assert ready and ready[0] == "READY", ready
        resp_port = int(ready[1])

        # Gateway role (this process): mark every ADD in the external
        # store, publish 5 ORDER frames of mixed flow (cancels included).
        orders = list(
            multi_symbol_stream(n=250, n_symbols=6, seed=33, cancel_prob=0.2)
        )
        pool = RespPrePool(RespClient(port=resp_port))
        from gome_tpu.types import Action

        for o in orders:
            if o.action is Action.ADD:
                pool.add((o.symbol, o.uuid, o.oid))
        bus = make_bus(BusConfig(backend="file", dir=busdir))
        frames = [orders[i : i + 50] for i in range(0, 250, 50)]
        # First span: frames 1-2 (consumed clean + snapshotted).
        for fr in frames[:2]:
            bus.order_queue.publish(encode_orders(fr))

        env = dict(os.environ, JAX_PLATFORMS="cpu")
        crash = subprocess.Popen(
            [
                sys.executable, "-c",
                _CRASH_CONSUMER.format(
                    repo=_REPO, busdir=busdir, resp_port=resp_port,
                    snapdir=snapdir, phase="crash", mesh_n=mesh_n,
                ),
            ],
            stdout=subprocess.PIPE, text=True, cwd=_REPO, env=env,
        )
        line = crash.stdout.readline().strip()
        assert line == "SNAPSHOTTED", line
        # Second span arrives; the consumer feeds 2 frames into the device
        # pipeline and dies mid-flight (frame 5 still queued).
        for fr in frames[2:]:
            bus.order_queue.publish(encode_orders(fr))
        crash.wait(timeout=120)
        assert crash.returncode == -9, crash.returncode

        # Fresh handle: the file bus caches the committed marker at open.
        bus2 = make_bus(BusConfig(backend="file", dir=busdir))
        committed_at_crash = bus2.order_queue.committed()
        assert committed_at_crash == 2, committed_at_crash

        restart = subprocess.run(
            [
                sys.executable, "-c",
                _CRASH_CONSUMER.format(
                    repo=_REPO, busdir=busdir, resp_port=resp_port,
                    snapdir=snapdir, phase="restart", mesh_n=mesh_n,
                ),
            ],
            capture_output=True, text=True, timeout=300, cwd=_REPO, env=env,
        )
        assert restart.returncode == 0, restart.stderr
        assert "RESTORED True" in restart.stdout
        assert "DRAINED" in restart.stdout

        # The full matchOrder stream equals the oracle's, exactly once.
        oracle = OracleEngine()
        for o in orders:
            oracle.submit(o)
        expected = oracle.drain()
        bus3 = make_bus(BusConfig(backend="file", dir=busdir))
        got = []
        for m in bus3.match_queue.read_from(0, 10_000):
            got.extend(decode_event_frame(m.body).to_results())
        assert got == expected
        assert bus3.order_queue.committed() == 5
    finally:
        srv.terminate()
        srv.wait(timeout=10)

"""Config system tests (gome_tpu.config vs the reference's conf.go semantics)."""

import pytest

from gome_tpu.config import Config, load_config


def test_defaults_without_file(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)  # no config.yaml in CWD
    cfg = load_config()
    assert cfg.engine.accuracy == 8  # config.yaml.example:24 default
    assert cfg.bus.order_queue == "doOrder"
    assert cfg.bus.match_queue == "matchOrder"
    assert not cfg.store.enabled


def test_reference_shaped_yaml_loads(tmp_path):
    # The exact section/key shape of config.yaml.example:1-25 (incl. the
    # dead mysql block and string ports, conf.go's all-string fields).
    p = tmp_path / "config.yaml"
    p.write_text(
        """
grpc:
  host: gome
  port: 8088
redis:
  host: redis
  port: 6379
  password: "123456"
rabbitmq:
  host: rabbitmq
  port: 5672
  username: root
  password: "123456"
mysql:
  host: 127.0.0.1
  port: 3306
  database: dbname
  username: root
  password: "123456"
gomengine:
  accuracy: 8
"""
    )
    cfg = load_config(str(p))
    assert cfg.grpc.host == "gome" and cfg.grpc.port == 8088
    assert cfg.store.enabled and cfg.store.host == "redis"
    assert cfg.bus.backend == "amqp" and cfg.bus.host == "rabbitmq"
    assert cfg.engine.accuracy == 8


def test_engine_extension_section(tmp_path):
    p = tmp_path / "config.yaml"
    p.write_text(
        """
engine:
  cap: 64
  n_slots: 16
  dtype: int32
bus:
  backend: file
  dir: /tmp/busdir
"""
    )
    cfg = load_config(str(p))
    assert cfg.engine.cap == 64 and cfg.engine.dtype == "int32"
    assert cfg.bus.backend == "file" and cfg.bus.dir == "/tmp/busdir"
    import jax.numpy as jnp

    assert cfg.engine.book_config().dtype == jnp.int32


def test_validation_rejects_bad_values(tmp_path):
    p = tmp_path / "config.yaml"
    p.write_text("engine:\n  cap: -1\n")
    with pytest.raises(ValueError, match="cap"):
        load_config(str(p))
    p.write_text("bus:\n  backend: zeromq\n")
    with pytest.raises(ValueError, match="backend"):
        load_config(str(p))
    p.write_text("nosuch:\n  a: 1\n")
    with pytest.raises(ValueError, match="unknown config sections"):
        load_config(str(p))
    p.write_text("grpc:\n  hostt: x\n")
    with pytest.raises(ValueError, match="unknown key"):
        load_config(str(p))


def test_defaults_object():
    cfg = Config()
    assert cfg.engine.book_config().cap == 256


def test_sim_section(tmp_path):
    p = tmp_path / "config.yaml"
    p.write_text(
        """
sim:
  n_lanes: 32
  zipf_a: 1.4
  cap: 32
  dtype: int64
"""
    )
    cfg = load_config(str(p))
    assert cfg.sim.n_lanes == 32 and cfg.sim.zipf_a == 1.4
    env_config = cfg.sim.env_config()
    import jax.numpy as jnp

    assert env_config.flow.n_lanes == 32
    assert env_config.flow.zipf_a == 1.4
    assert env_config.book.cap == 32
    assert env_config.book.dtype == jnp.int64
    # Hawkes stability gates at load time, before any jax import.
    p.write_text("sim:\n  excite_self: 0.9\n  excite_cross: 0.3\n")
    with pytest.raises(ValueError, match="unstable"):
        load_config(str(p))

"""Crash consistency under fire (ISSUE 11): the deterministic fault-
injection registry (utils.faults), torn-write hardening of the file
queue, exactly-once matchfeed seq numbers across failures and restarts,
the /durability surface, and the committed chaos verdict
(CHAOS_r01.json, produced by scripts/chaos.py)."""

import json
import os
import random
import sys

import numpy as np
import pytest

from gome_tpu.bus import decode_match_result, encode_order, make_bus
from gome_tpu.bus.colwire import (
    EVENT_MAGIC,
    EVENT_MAGIC_SEQ,
    decode_event_frame,
    encode_event_frame,
)
from gome_tpu.bus.filelog import FileQueue
from gome_tpu.config import (
    BusConfig,
    Config,
    EngineConfig,
    FaultsConfig,
    PersistConfig,
)
from gome_tpu.engine import BookConfig, MatchEngine
from gome_tpu.persist import DictRedis, Persister, restore_from_redis
from gome_tpu.persist.redis_schema import export_to_redis
from gome_tpu.service import EngineService
from gome_tpu.service.matchfeed import SeqTracker
from gome_tpu.types import Action, Order, Side
from gome_tpu.utils.faults import (
    EXIT_CODE,
    FAULTS,
    FaultInjected,
    FaultPlan,
    FaultRegistry,
    FaultSpec,
)
from gome_tpu.utils.metrics import Registry
from gome_tpu.utils.streams import mixed_stream

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _disarm_faults():
    """The FAULTS singleton must never leak an armed plan across tests."""
    yield
    FAULTS.disable()


# -- the committed chaos verdict --------------------------------------------


def test_chaos_verdict_pinned_green():
    """CHAOS_r01.json is the committed machine-checked verdict of the
    seeded kill/restart soak (scripts/chaos.py). This pin fails if the
    artifact regresses — regenerate it with the script, never hand-edit."""
    with open(os.path.join(REPO, "CHAOS_r01.json")) as f:
        v = json.load(f)
    assert v["schema"] == "gome-chaos-verdict-v1"
    assert v["pass"] is True
    assert all(v["checks"].values()), v["checks"]
    # >= 3 injected kill/restart cycles, every death the injected one
    assert v["config"]["kills"] >= 3
    assert len(v["cycles"]) == v["config"]["kills"]
    assert all(c["exit_code"] == EXIT_CODE for c in v["cycles"])
    # every cycle's plan names a real fault point (reproducibility)
    for c in v["cycles"]:
        assert c["plan"]["faults"], c
    # bit-exact book digest vs the uninterrupted oracle
    assert v["oracle"]["book_digest"] == v["final"]["book_digest"]
    assert v["oracle"]["book_digest"]
    # queue-level match stream: exactly-once after all recoveries
    audit = v["matchfeed"]["seq_audit"]
    assert audit["dupes"] == 0 and audit["gaps"] == 0
    assert v["matchfeed"]["stamped"] == v["matchfeed"]["events"] > 0
    # measured recovery percentiles over >= kills restart samples
    rec = v["recovery"]
    assert rec["p50_s"] is not None and rec["p99_s"] is not None
    assert len(rec["samples_s"]) >= v["config"]["kills"]
    assert rec["wal_replay_frames_total"] > 0


# -- fault registry ----------------------------------------------------------


def test_disabled_fire_is_zero_alloc():
    """The disabled hot path is one attribute check, zero allocations —
    the same sys.getallocatedblocks guard as the tracer/journal/timeline
    singletons."""
    r = FaultRegistry()  # never installed
    assert not r.enabled

    def drill(n):
        i = 0
        while i < n:
            if r.fire("consumer.frame") != 0:
                raise AssertionError("unreachable")
            i += 1

    drill(64)  # warm lazy caches
    before = sys.getallocatedblocks()
    drill(200)
    after = sys.getallocatedblocks()
    assert after - before <= 2, f"disabled fire() allocated {after - before}"


def test_fault_spec_validation():
    with pytest.raises(ValueError):
        FaultSpec("p", mode="explode")
    with pytest.raises(ValueError):
        FaultSpec("p", mode="call")  # call needs a handler name
    with pytest.raises(ValueError):
        FaultSpec("")


def test_fault_plan_json_roundtrip():
    plan = FaultPlan(seed=42, faults=(
        FaultSpec("consumer.commit", mode="exit", at=(1, 5)),
        FaultSpec("filelog.offset", mode="torn", every=3, times=2),
        FaultSpec("bus.step", mode="call", prob=0.5, handler="broker.kill"),
    ))
    assert FaultPlan.from_json(plan.to_json()) == plan


def test_trigger_semantics_at_every_times():
    r = FaultRegistry()
    r.install(FaultPlan(seed=1, faults=(
        FaultSpec("a", mode="raise", at=(3,)),
        FaultSpec("b", mode="raise", every=2, times=2),
    )))
    assert r.fire("a") == 0 and r.fire("a") == 0
    with pytest.raises(FaultInjected):
        r.fire("a")  # hit 3
    assert r.fire("a") == 0  # and never again

    for hit in (1, 2, 3, 4, 5, 6):
        if hit in (2, 4):  # every=2, capped at times=2
            with pytest.raises(FaultInjected):
                r.fire("b")
        else:
            assert r.fire("b") == 0
    report = r.report()
    assert report["hits"] == {"a": 4, "b": 6}
    assert [f["hit"] for f in report["fired"] if f["point"] == "b"] == [2, 4]


def test_exit_mode_uses_injected_exit():
    r = FaultRegistry()
    died = []
    r._exit = lambda code: died.append(code)
    r.install(FaultPlan(faults=(FaultSpec("x", mode="exit", at=(1,)),)))
    r.fire("x")
    assert died == [EXIT_CODE]
    r.hard_exit()
    assert died == [EXIT_CODE, EXIT_CODE]


def test_torn_cuts_deterministic_across_installs():
    plan = FaultPlan(seed=7, faults=(
        FaultSpec("filelog.append", mode="torn", every=1),
    ))

    def cuts():
        r = FaultRegistry()
        r.install(plan)
        return [r.fire("filelog.append") for _ in range(8)]

    first, second = cuts(), cuts()
    assert first == second  # seeded per-spec RNG, process-stable
    assert all(c > 0 for c in first)
    other = FaultRegistry()
    other.install(FaultPlan(seed=8, faults=plan.faults))
    assert [other.fire("filelog.append") for _ in range(8)] != first


def test_call_mode_resp_restart_handler():
    """A counted fault point can trigger a REAL environmental fault: the
    RESP store restarts on schedule and the supervised client recovers —
    the kill_connections/restart hooks are wired through FAULTS.handler."""
    from gome_tpu.persist.resp import SupervisedRespClient
    from gome_tpu.persist.respserver import FakeRedisServer

    with FakeRedisServer() as srv:
        client = SupervisedRespClient("127.0.0.1", srv.port, name="t:chaos")
        assert client.ping()
        restarts = []
        FAULTS.handler("resp.restart", lambda: restarts.append(srv.restart()))
        FAULTS.install(FaultPlan(faults=(
            FaultSpec("store.op", mode="call", at=(2,),
                      handler="resp.restart"),
        )))
        assert FAULTS.fire("store.op") == 0
        assert FAULTS.fire("store.op") == 0  # handler runs, returns clean
        assert len(restarts) == 1
        assert client.ping()  # supervised session survived the restart
        client.close()


def test_call_mode_broker_kill_handler():
    """Same schedule mechanism against the AMQP broker: kill_connections
    severs live connections at the counted point; the supervised queue
    reconnects and the publish lands."""
    from gome_tpu.bus.fakebroker import FakeBroker

    broker = FakeBroker().start()
    try:
        bus = make_bus(BusConfig(backend="amqp", port=broker.port))
        bus.order_queue.publish(b"before")
        FAULTS.handler("broker.kill", broker.kill_connections)
        FAULTS.install(FaultPlan(faults=(
            FaultSpec("bus.step", mode="call", at=(1,),
                      handler="broker.kill"),
        )))
        FAULTS.fire("bus.step")
        assert FAULTS.report()["fired"]
        bus.order_queue.publish(b"after")  # supervised reconnect
        msgs = bus.order_queue.read_from(0, 10)
        assert [m.body for m in msgs] == [b"before", b"after"]
        bus.order_queue.close()
        bus.match_queue.close()
    finally:
        broker.stop()


# -- torn-write hardening (FileQueue) ----------------------------------------


def test_filequeue_recovers_from_random_torn_tail_and_sidecar(tmp_path):
    """Property test: random truncation of the log tail AND the offset
    sidecar must always recover to a consistent prefix — committed <=
    end <= published, and every readable record byte-identical."""
    rng = random.Random(11)
    for trial in range(25):
        base = str(tmp_path / f"q{trial}" / "doOrder")
        q = FileQueue("doOrder", base)
        bodies = [
            bytes([trial % 251, i]) * (1 + rng.randrange(40))
            for i in range(12)
        ]
        for b in bodies:
            q.publish(b)
        q.commit(rng.randrange(len(bodies) + 1))
        q.close()

        log_path = base + ".log"
        with open(log_path, "rb+") as f:
            f.truncate(rng.randrange(os.path.getsize(log_path) + 1))
        off_path = base + ".offset"
        with open(off_path, "rb") as f:
            side = f.read()
        with open(off_path, "wb") as f:
            f.write(side[: rng.randrange(len(side) + 1)])

        q2 = FileQueue("doOrder", base)
        end, committed = q2.end_offset(), q2.committed()
        assert 0 <= committed <= end <= len(bodies)
        assert [m.body for m in q2.read_from(0, end)] == bodies[:end]
        # the queue keeps working after recovery
        q2.publish(b"post-recovery")
        assert q2.read_from(end, 1)[0].body == b"post-recovery"
        q2.close()


def test_sidecar_garbage_and_overrun_clamped(tmp_path):
    base = str(tmp_path / "doOrder")
    q = FileQueue("doOrder", base)
    q.publish(b"one")
    q.publish(b"two")
    q.commit(2)
    q.close()
    # garbage sidecar -> full replay from 0
    with open(base + ".offset", "w") as f:
        f.write("not-a-number")
    q2 = FileQueue("doOrder", base)
    assert q2.committed() == 0
    q2.close()
    # sidecar ahead of a truncated log -> clamped to end
    with open(base + ".offset", "w") as f:
        f.write("999")
    q3 = FileQueue("doOrder", base)
    assert q3.committed() == q3.end_offset() == 2
    q3.close()


# -- seq wire format ---------------------------------------------------------


def _crossing_batch():
    eng = MatchEngine(
        config=BookConfig(cap=8, max_fills=4), n_slots=4, max_t=4
    )
    orders = [
        Order(uuid="u1", oid="a", symbol="s0", side=Side.BUY,
              price=100, volume=5),
        Order(uuid="u2", oid="b", symbol="s0", side=Side.SALE,
              price=100, volume=3),
        Order(uuid="u1", oid="a", symbol="s0", side=Side.BUY,
              price=100, volume=0, action=Action.DEL),
    ]
    for o in orders:
        eng.mark(o)
    return eng.process_columnar(orders)


def test_gce2_roundtrip_and_gce1_compat():
    batch = _crossing_batch()
    assert len(batch) >= 2  # a fill and a cancel

    stamped = encode_event_frame(batch, seq0=7)
    assert stamped[:4] == EVENT_MAGIC_SEQ
    out = decode_event_frame(stamped)
    assert out.seq0 == 7
    assert [r.seq for r in out.to_results()] == list(
        range(7, 7 + len(batch))
    )
    lines = out.to_json_lines()
    assert all(b'"Seq":' in ln for ln in lines)
    # decoded columns identical to the unstamped wire's
    plain = encode_event_frame(batch)
    assert plain[:4] == EVENT_MAGIC
    unstamped = decode_event_frame(plain)
    assert unstamped.seq0 is None
    assert all(r.seq is None for r in unstamped.to_results())
    assert all(b'"Seq"' not in ln for ln in unstamped.to_json_lines())
    # seq is metadata, not identity: results compare equal without it
    assert unstamped.to_results() == out.to_results()


def test_json_wire_carries_trailing_seq():
    batch = _crossing_batch()
    lines = batch.to_json_lines(seq0=3)
    for i, ln in enumerate(lines):
        doc = json.loads(ln)
        assert doc["Seq"] == 3 + i
        mr = decode_match_result(ln)
        assert mr.seq == 3 + i
    # unstamped lines stay byte-identical to the pre-seq wire
    assert all(b'"Seq"' not in ln for ln in batch.to_json_lines())


# -- SeqTracker / feed suppression -------------------------------------------


def test_seq_tracker_semantics():
    t = SeqTracker()  # mid-stream attach: baseline at first observe
    assert t.observe(5) and t.gaps == 0
    assert t.observe(6)
    assert not t.observe(6)  # dupe, suppressed
    assert not t.observe(2)  # late replay, suppressed
    assert t.observe(9)
    assert t.state() == {
        "last_seq": 9, "observed": 5, "dupes": 2, "gaps": 2
    }
    t0 = SeqTracker(first_seq=0)  # anchored full-stream audit
    assert t0.observe(1)
    assert t0.gaps == 1  # seq 0 missing counts


def test_feed_suppresses_replayed_seqs():
    """A queue-level duplicate (at-least-once replay window) carries the
    same seqs; the feed suppresses it before fan-out so subscribers see
    each event exactly once."""
    svc = EngineService(Config(
        bus=BusConfig(match_wire="frame"),
        engine=EngineConfig(cap=16, n_slots=4, max_t=4),
    ))
    batch = _crossing_batch()
    frame = encode_event_frame(batch, seq0=0)
    svc.bus.match_queue.publish(frame)
    svc.bus.match_queue.publish(frame)  # replayed duplicate
    svc.feed.drain()
    assert svc.feed.events_seen == len(batch)
    assert svc.feed.suppressed == len(batch)
    state = svc.feed.seq_state()
    assert state["dupes"] == len(batch) and state["gaps"] == 0


def test_failed_step_replays_with_identical_seqs(tmp_path):
    """raise-mode fault in the at-least-once window (after publish,
    before commit): the replay must regenerate the SAME seqs so the
    queue-level duplicate is suppressible downstream."""
    cfg = Config(
        bus=BusConfig(backend="file", dir=str(tmp_path / "bus"),
                      match_wire="frame"),
        engine=EngineConfig(cap=32, n_slots=8, max_t=8),
    )
    svc = EngineService(cfg)
    orders = mixed_stream(n=40, seed=13, cancel_prob=0.25)
    for o in orders:
        svc.engine.mark(o)
        svc.bus.order_queue.publish(encode_order(o))

    FAULTS.install(FaultPlan(faults=(
        FaultSpec("consumer.commit", mode="raise", at=(1,)),
    )))
    assert svc.consumer.step_with_policy() == 0  # injected failure
    assert svc.consumer.match_seq == 0  # rolled back to last commit
    FAULTS.disable()
    svc.consumer.drain()

    mq = svc.bus.match_queue
    seqs = []
    for m in mq.read_from(0, mq.end_offset()):
        b = decode_event_frame(m.body)
        seqs.extend(range(b.seq0, b.seq0 + len(b)))
    # the first batch's seqs appear twice (publish + replay), then the
    # stream continues gap-free
    assert seqs[0] == 0
    dupes = len(seqs) - len(set(seqs))
    assert dupes > 0
    assert sorted(set(seqs)) == list(range(len(set(seqs))))
    svc.feed.drain()
    assert svc.feed.suppressed == dupes
    assert svc.feed.seq_state()["gaps"] == 0
    assert svc.feed.events_seen == len(set(seqs))


# -- seq recovery across restarts --------------------------------------------


def _make_svc(tmp_path, every_n=1, **eng):
    cfg = Config(
        bus=BusConfig(backend="file", dir=str(tmp_path / "bus")),
        engine=EngineConfig(cap=32, n_slots=8, max_t=8, **eng),
        persist=PersistConfig(
            dir=str(tmp_path / "snaps"), every_n_batches=every_n
        ),
    )
    return EngineService(cfg, persist=Persister(cfg.persist))


def _feed(svc, orders):
    for o in orders:
        svc.engine.mark(o)
        svc.bus.order_queue.publish(encode_order(o))


def _stream(svc):
    mq = svc.bus.match_queue
    return [
        decode_match_result(m.body) for m in mq.read_from(0, mq.end_offset())
    ]


def test_recovery_rebases_and_regenerates_seqs(tmp_path):
    """Crash after a snapshot with an unsnapshotted tail: the restored
    consumer rebases match_seq from the manifest and WAL replay
    regenerates the truncated match tail with the SAME seqs — the full
    stream is gap-free, dupe-free, and equal to an uninterrupted run."""
    orders = mixed_stream(n=160, seed=9, cancel_prob=0.25)
    ref = EngineService(Config(engine=EngineConfig(cap=32, n_slots=8, max_t=8)))
    _feed(ref, orders)
    ref.pump()
    expected = [(mr, mr.seq) for mr in _stream(ref)]
    assert expected and all(s is not None for _, s in expected)

    svc = _make_svc(tmp_path, every_n=10**9)
    svc.persist.restore_latest()
    _feed(svc, orders[:80])
    svc.consumer.drain()
    svc.persist.snapshot()
    seq_at_cut = svc.consumer.match_seq
    _feed(svc, orders[80:])
    svc.consumer.drain()  # unsnapshotted tail the "crash" throws away

    svc2 = _make_svc(tmp_path, every_n=10**9)
    assert svc2.persist.restore_latest()
    assert svc2.consumer.match_seq == seq_at_cut  # rebased from manifest
    svc2.consumer.drain()
    got = [(mr, mr.seq) for mr in _stream(svc2)]
    assert got == expected
    assert [s for _, s in got] == list(range(len(got)))


def test_redis_import_composes_with_crash_recovery(tmp_path):
    """Satellite: reference-schema import + chaos recovery. Import the
    same Redis book into two services, crash one mid-tail, and require
    the recovered run to match the uninterrupted one exactly."""
    rng = np.random.default_rng(23)

    def stream(n, oid0):
        out = []
        for i in range(n):
            out.append(Order(
                uuid=f"u{int(rng.integers(0, 3))}",
                oid=str(oid0 + i),
                symbol=f"sym{int(rng.integers(0, 4))}",
                side=Side(int(rng.integers(0, 2))),
                price=100_000_000 + int(rng.integers(-500, 500)),
                volume=int(rng.integers(1, 20)),
            ))
        return out

    seeded = MatchEngine(
        config=BookConfig(cap=32, max_fills=8), n_slots=8, max_t=8
    )
    for o in stream(80, 0):
        seeded.mark(o)
        seeded.process([o])
    store = DictRedis()
    export_to_redis(seeded, client=store)

    def boot(name):
        svc = _make_svc(tmp_path / name, every_n=10**9)
        restore_from_redis(svc.engine, store)
        svc.persist.snapshot()  # durable baseline of the import
        return svc

    tail = stream(90, 1000)
    ref = boot("ref")
    _feed(ref, tail)
    ref.consumer.drain()

    crashed = boot("crash")
    _feed(crashed, tail)
    crashed.consumer.run_once()  # consume part of the tail, then die
    assert crashed.bus.order_queue.committed() > 0

    recovered = _make_svc(tmp_path / "crash", every_n=10**9)
    assert recovered.persist.restore_latest()
    recovered.consumer.drain()
    assert _stream(recovered) == _stream(ref)
    a = ref.engine.batch.export_state()
    b = recovered.engine.batch.export_state()
    assert a["symbols"] == b["symbols"] and a["oids"] == b["oids"]
    for leaf in ("lots", "count", "price"):
        assert (a["books"][leaf] == b["books"][leaf]).all()


# -- durability surface ------------------------------------------------------


def test_durability_payload_and_persist_telemetry(tmp_path):
    from gome_tpu.service.ops import OpsServer

    svc = _make_svc(tmp_path, every_n=1)
    svc.persist.restore_latest()
    _feed(svc, mixed_stream(n=40, seed=4, cancel_prob=0.2))
    svc.pump()
    assert svc.persist.snapshots_taken > 0

    payload = OpsServer(svc).durability_payload()
    assert payload["faults"]["enabled"] is False
    assert payload["persist"]["snapshots_taken"] == svc.persist.snapshots_taken
    assert payload["persist"]["last_restore"] == "none"  # fresh boot
    assert 0 <= payload["persist"]["snapshot_age_s"]
    assert payload["matchfeed"]["gaps"] == 0
    assert payload["consumer"]["match_seq"] == svc.consumer.match_seq
    q = payload["queues"]["order_queue"]
    assert q["end"] == q["committed"] > 0

    reg = Registry()
    svc.persist.export_metrics(registry=reg)
    text = reg.render()
    for name in (
        "gome_snapshot_age_seconds",
        "gome_snapshot_bytes",
        "gome_snapshots_taken_total",
        "gome_recovery_seconds",
        "gome_wal_replay_frames",
    ):
        assert name in text

    probe = svc.persist.probe()
    assert set(probe) == {
        "snapshots_taken", "snapshot_age_s", "snapshot_bytes",
        "last_restore", "recovery_s", "wal_replay_frames",
    }


def test_timeline_registers_persist_probe(tmp_path):
    from gome_tpu.obs.timeline import TIMELINE, service_timeline

    svc = _make_svc(tmp_path, every_n=1)
    TIMELINE.install(registry=Registry())
    try:
        service_timeline(svc)
        sample = TIMELINE.sample()
        assert sample["persist"]["last_restore"] == "never"
        assert sample["persist"]["snapshots_taken"] == 0
    finally:
        TIMELINE.disable()


# -- faults config block -----------------------------------------------------


def test_faults_config_defaults_off_and_inline_points(tmp_path):
    assert Config().faults.enabled is False
    cfg_path = tmp_path / "config.yaml"
    cfg_path.write_text(
        "faults:\n"
        "  seed: 5\n"
        "  points:\n"
        "    - {point: consumer.commit, mode: raise, at: [2]}\n"
    )
    from gome_tpu.config import load_config

    cfg = load_config(str(cfg_path))
    assert cfg.faults.enabled is True  # a faults: section arms by default
    plan = cfg.faults.fault_plan()
    assert plan.seed == 5
    assert plan.faults == (
        FaultSpec("consumer.commit", mode="raise", at=(2,)),
    )
    with pytest.raises(ValueError):
        FaultsConfig(plan="x.json", points=({"point": "a"},))


def test_service_arms_faults_from_config():
    cfg = Config(
        engine=EngineConfig(cap=16, n_slots=4, max_t=4),
        faults=FaultsConfig(
            enabled=True, seed=3,
            points=({"point": "consumer.frame", "mode": "raise",
                     "at": [1]},),
        ),
    )
    svc = EngineService(cfg)
    assert FAULTS.enabled
    svc.bus.order_queue.publish(
        encode_order(Order(uuid="u", oid="o1", symbol="s", side=Side.BUY,
                           price=100, volume=1))
    )
    assert svc.consumer.step_with_policy() == 0  # injected, absorbed
    assert FAULTS.report()["fired"]

"""Capacity observatory (ISSUE 17): the coordinated-omission-safe
LogHistogram (bounded relative error, byte-stable serialize, exactly
associative cross-process merge), the open-loop arrival schedule, knee
detection + attribution helpers, the CAPACITY singleton + /capacity ops
payload, and the committed fleet sweep verdict (CAPACITY_r01.json,
produced by ``scripts/capacity.py --fleet``)."""

import json
import math
import os
import random

import pytest

from gome_tpu.obs.capacity import (
    CAPACITY,
    SCHEMA,
    CapacityObservatory,
    LogHistogram,
    OpenLoopSchedule,
    attribution_check,
    find_knee,
    load_verdict,
    monotone_ladder,
    saturated_stage,
)
from gome_tpu.utils.metrics import Registry

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- LogHistogram: bounded relative error ------------------------------------


def test_relative_error_bound_property():
    """The histogram's core contract: for every in-range value, the
    bucket estimate (geometric mean of the bucket bounds) is within the
    configured relative error — estimate/v in (1/(1+e), 1+e]."""
    rel_err = 0.01
    h = LogHistogram(rel_err=rel_err, min_value=1e-6, max_value=600.0)
    rng = random.Random(17)
    for _ in range(20_000):
        # log-uniform across the full dynamic range
        v = 10 ** rng.uniform(-6, math.log10(600.0) - 1e-9)
        est = h.bucket_estimate(h.index(v))
        ratio = est / v
        assert 1.0 / (1.0 + rel_err) < ratio <= 1.0 + rel_err, (v, est)


def test_underflow_and_clamp_buckets():
    h = LogHistogram(rel_err=0.05, min_value=1e-3, max_value=10.0)
    assert h.index(0.0) == 0
    assert h.index(-1.0) == 0
    assert h.index(float("nan")) == 0
    assert h.index(1e-9) == 0
    # overflow clamps to the top bucket, whose estimate is >= max_value
    top = h.index(1e9)
    assert top == h.index(10.0 * 1.2)
    h.record(1e9)
    assert h.percentile(0.5) >= 10.0


def test_mean_tracks_true_mean_within_rel_err():
    h = LogHistogram(rel_err=0.01, min_value=1e-6, max_value=600.0)
    rng = random.Random(7)
    vals = [rng.uniform(0.001, 2.0) for _ in range(5000)]
    for v in vals:
        h.record(v)
    true_mean = sum(vals) / len(vals)
    assert abs(h.mean() - true_mean) / true_mean < 0.01


# -- LogHistogram: merge + serialize -----------------------------------------


def test_cross_process_merge_equals_single_recording():
    """Split one recording across two histograms (as two processes
    would), merge, and the result must be EXACTLY the single-process
    recording — same counts, same percentiles, same bytes. Integer
    bucket counts make merge associative; a float accumulator would
    break byte equality on fold order."""
    rng = random.Random(23)
    vals = [10 ** rng.uniform(-5, 2) for _ in range(4096)]
    single = LogHistogram()
    a, b = LogHistogram(), LogHistogram()
    for v in vals:
        single.record(v)
    for v in vals[:1500]:
        a.record(v)
    for v in vals[1500:]:
        b.record(v)
    a.merge(b)
    assert a.count == single.count == len(vals)
    assert a.to_bytes() == single.to_bytes()
    assert a.percentiles() == single.percentiles()
    assert a.mean() == single.mean()


def test_merge_rejects_geometry_mismatch():
    a = LogHistogram(rel_err=0.01)
    b = LogHistogram(rel_err=0.02)
    with pytest.raises(ValueError):
        a.merge(b)


def test_serialize_roundtrip_and_byte_pin():
    """to_bytes is the cross-process wire format: the exact bytes of a
    fixed small recording are pinned — any geometry or layout change
    must show up here as a deliberate pin update."""
    h = LogHistogram(rel_err=0.05, min_value=1e-3, max_value=10.0)
    for v in (0.0005, 0.001, 0.004, 0.02, 0.02, 0.5, 2.0, 9.0, 50.0):
        h.record(v)
    blob = h.to_bytes()
    assert blob.hex() == (
        "474348319a9999999999a93ffca9f1d24d62503f000000000000244009000000"
        "0000000008000000000000000100000000000000010000000100000000000000"
        "0f00000001000000000000001f000000020000000000000040000000010000000"
        "00000004e00000001000000000000005e00000001000000000000005f00000001"
        "00000000000000"
    )
    h2 = LogHistogram.from_bytes(blob)
    assert h2.to_bytes() == blob
    assert h2.count == h.count
    assert h2.percentiles() == h.percentiles()


def test_from_bytes_rejects_corrupt_blobs():
    h = LogHistogram()
    h.record(1.0)
    blob = h.to_bytes()
    with pytest.raises(ValueError):
        LogHistogram.from_bytes(b"XXXX" + blob[4:])
    with pytest.raises(ValueError):
        LogHistogram.from_bytes(blob[:-3])


# -- coordinated omission ----------------------------------------------------


def test_coordinated_omission_golden_stalled_consumer():
    """THE reason this module exists: a consumer that stalls mid-run.

    Closed-loop measurement (each request sent only after the previous
    completes, latency = completion - actual send) sees the stall as ONE
    slow sample — every request queued behind it was simply never sent,
    so the p99 stays flat. The corrected recorder charges every order
    from its INTENDED send time on the fixed open-loop schedule, so the
    stall's queueing delay lands on every affected order and the p99
    explodes. Deterministic golden: 10 s at 100/s, 1 ms service,
    consumer frozen for 4 s in the middle."""
    rate, service, n = 100.0, 0.001, 1000
    stall_at, stall_len = 5.0, 4.0
    sched = OpenLoopSchedule(rate, t0=0.0)
    corrected = LogHistogram()
    closed = LogHistogram()

    def serve(start: float) -> float:
        # the server is frozen over [stall_at, stall_at + stall_len)
        if stall_at <= start < stall_at + stall_len:
            start = stall_at + stall_len
        return start + service

    # closed loop: next send happens when the previous completes, so
    # only ONE sample ever overlaps the frozen window
    send = 0.0
    for _ in range(n):
        done = serve(send)
        closed.record(done - send)
        send = done  # closed loop: sender waits for completion

    # open loop: arrivals on the schedule regardless of the server; the
    # ~400 orders intended during the freeze all queue behind it
    free_at = 0.0
    for i in range(n):
        t = sched.intended(i)
        done = serve(max(t, free_at))
        corrected.record(done - t)
        free_at = done

    closed_p99 = closed.percentile(0.99)
    corrected_p99 = corrected.percentile(0.99)
    # closed loop hides the stall: p99 stays at service time scale
    assert closed_p99 < 0.1, closed_p99
    # corrected charges the queue: p99 shows seconds of stall
    assert corrected_p99 > 1.0, corrected_p99
    assert corrected_p99 > 10 * closed_p99


def test_record_corrected_backfills_missing_intervals():
    """HDR-style correction at record time: a 1 s observation at a
    100 ms expected interval implies 9 missed sends behind it."""
    h = LogHistogram()
    h.record_corrected(1.0, expected_interval=0.1)
    assert h.count == 10
    assert h.percentile(1.0) >= 0.9


# -- OpenLoopSchedule --------------------------------------------------------


def test_open_loop_schedule_arithmetic():
    s = OpenLoopSchedule(100.0, t0=50.0)
    assert s.intended(0) == pytest.approx(50.01)
    assert s.intended(99) == pytest.approx(51.0)
    assert s.batch_due(0, 10) == s.intended(9)
    # mean accumulation wait for a batch assembled at rate r
    assert s.accumulation_mean(11) == pytest.approx(10 / (2 * 100.0))
    with pytest.raises(ValueError):
        OpenLoopSchedule(0.0)


# -- knee + attribution helpers ----------------------------------------------


def _pt(offered, delivered, p99, rows=None):
    return {
        "offered_per_sec": offered,
        "delivered_per_sec": delivered,
        "corrected": {"p99_s": p99},
        "attribution": {"rows": rows or []},
    }


def test_find_knee_on_delivered_ratio():
    pts = [_pt(100, 99.9, 0.01), _pt(200, 199, 0.02), _pt(400, 250, 0.5)]
    idx, reason = find_knee(pts, delivered_floor=0.98)
    assert idx == 2
    assert "delivered/offered" in reason
    assert monotone_ladder(pts)


def test_find_knee_on_p99_budget():
    pts = [_pt(100, 100, 0.01), _pt(200, 200, 2.0), _pt(400, 400, 3.0)]
    idx, reason = find_knee(pts, delivered_floor=0.5, p99_budget_s=1.0)
    assert idx == 1
    assert "p99" in reason


def test_find_knee_none_when_healthy():
    pts = [_pt(100, 100, 0.01), _pt(200, 199, 0.02)]
    assert find_knee(pts) == (None, None)
    assert not monotone_ladder([_pt(200, 1, 1), _pt(100, 1, 1)])


def test_attribution_check_and_saturated_stage():
    rows = [
        {"stage": "a", "seconds_per_order": 0.06, "utilization": 0.9},
        {"stage": "b", "seconds_per_order": 0.03, "utilization": 0.2},
        {"stage": "wait", "seconds_per_order": 0.012, "utilization": None},
    ]
    chk = attribution_check(rows, e2e_mean_s=0.1, tol=0.05)
    assert chk["within_tol"] and chk["frac_err"] == pytest.approx(0.02)
    assert saturated_stage(rows) == "a"
    bad = attribution_check(rows, e2e_mean_s=0.2, tol=0.05)
    assert not bad["within_tol"]


# -- CAPACITY singleton + payload --------------------------------------------


def _mini_verdict():
    rows = [
        {"stage": "admit", "seconds_per_order": 0.05, "utilization": 0.95},
    ]
    return {
        "schema": SCHEMA,
        "mode": "single",
        "config": {},
        "ladder": [
            dict(_pt(100, 100, 0.01), corrected={
                "count": 500, "mean_s": 0.01, "p50_s": 0.008,
                "p99_s": 0.01,
            }),
            dict(_pt(400, 250, 0.6, rows), corrected={
                "count": 900, "mean_s": 0.3, "p50_s": 0.25, "p99_s": 0.6,
            }),
        ],
        "knee": {
            "found": True, "index": 1, "reason": "delivered",
            "offered_per_sec": 400, "delivered_per_sec": 250,
            "saturated_stage": "admit",
        },
        "checks": {"knee_found": True},
        "pass": True,
    }


def test_capacity_singleton_disabled_by_default():
    obs = CapacityObservatory()
    assert not obs.enabled
    assert obs.payload() == {"enabled": False}


def test_capacity_install_serves_payload_and_gauges():
    obs = CapacityObservatory()
    reg = Registry()
    obs.install(_mini_verdict(), registry=reg)
    try:
        payload = obs.payload()
        assert payload["enabled"] is True
        assert payload["schema"] == SCHEMA
        assert payload["points"] == 2
        assert payload["knee"]["saturated_stage"] == "admit"
        text = reg.render()
        assert "gome_capacity_points 2" in text
        assert "gome_capacity_knee_offered_per_sec 400" in text
        assert "gome_capacity_corrected_p99_s_at_knee 0.6" in text
    finally:
        obs.disable()
    assert obs.payload() == {"enabled": False}


def test_capacity_install_rejects_wrong_schema():
    obs = CapacityObservatory()
    bad = dict(_mini_verdict(), schema="nope-v0")
    with pytest.raises(ValueError):
        obs.install(bad, registry=Registry())
    assert not obs.enabled


def test_global_capacity_singleton_unarmed():
    assert CAPACITY.payload() == {"enabled": False}


# -- committed verdict pin ---------------------------------------------------


def test_capacity_verdict_pin():
    """CAPACITY_r01.json (committed, regenerated by ``scripts/capacity.py
    --fleet``) stays green and keeps its shape: a >=5 point ladder
    against the real 2x2 fleet, a detected knee with a named saturated
    stage, corrected p50/p99 at every point, exactly-once at every
    point, and the attribution sum within 5% of the measured e2e mean
    at the knee."""
    verdict = load_verdict(os.path.join(ROOT, "CAPACITY_r01.json"))
    assert verdict["schema"] == SCHEMA
    assert verdict["mode"] == "fleet"
    assert verdict["pass"] is True
    assert all(verdict["checks"].values()), verdict["checks"]
    assert set(verdict["checks"]) >= {
        "monotone_ladder", "ladder_has_5_points", "knee_found",
        "exactly_once_all_points", "corrected_recorded_all_points",
        "attribution_rows_nonempty", "attribution_within_tol_at_knee",
    }
    ladder = verdict["ladder"]
    assert len(ladder) >= 5
    offered = [p["offered_per_sec"] for p in ladder]
    assert offered == sorted(offered) and len(set(offered)) == len(offered)
    for p in ladder:
        for key in ("p50_s", "p99_s", "count", "mean_s"):
            assert key in p["corrected"]
        assert p["corrected"]["count"] == p["sent"]
        assert "p50_s" in p["closed_loop"]
        assert p["exactly_once"]["dupes"] == 0
        assert p["exactly_once"]["gaps"] == 0
        assert p["attribution"]["rows"]
    knee = verdict["knee"]
    assert knee["found"] is True
    assert knee["saturated_stage"]
    assert knee["attribution_frac_err"] <= 0.05
    kp = ladder[knee["index"]]
    assert kp["offered_per_sec"] == knee["offered_per_sec"]
    stages = {r["stage"] for r in kp["attribution"]["rows"]}
    assert knee["saturated_stage"] in stages


def test_fleet_verdict_notes_drive_rate():
    """The regenerated FLEET_r01.json records its CHOSEN drive rate so
    the drill's orders/sec can never again read as a capacity figure
    (ISSUE 17 satellite)."""
    with open(os.path.join(ROOT, "FLEET_r01.json")) as f:
        verdict = json.load(f)
    drive = verdict["config"]["drive"]
    assert drive["mode"] == "open-loop"
    assert drive["rate_per_sec"] > 0
    assert "capacity" in drive["note"].lower() or "CAPACITY" in drive["note"]

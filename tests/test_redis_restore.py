"""Redis import/restore (persist.redis_restore): export->import round trips
bit-identically, a restored engine continues matching with oracle parity,
and raw reference-style stores (float formatting, leaked link entries,
depth residue) import correctly."""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from gome_tpu.engine import BookConfig, MatchEngine
from gome_tpu.oracle import OracleEngine
from gome_tpu.persist import DictRedis, restore_from_redis
from gome_tpu.persist.redis_schema import export_to_redis
from gome_tpu.types import Action, Order, Side


def _run_marked(engine, orders):
    out = []
    for o in orders:
        engine.mark(o)
        out.extend(engine.process([o]))
    return out


def _books_semantically_equal(a, b):
    """Compare lane_books through the interner tables (interner id
    assignment order differs between a fresh engine and a restored one)."""
    ba, bb = a.batch.lane_books(), b.batch.lane_books()
    la = {
        a.batch.symbols.lookup(i + 1): i
        for i in range(len(a.batch.symbols.to_list()))
    }
    lb = {
        b.batch.symbols.lookup(i + 1): i
        for i in range(len(b.batch.symbols.to_list()))
    }
    assert set(la) == set(lb)
    for sym, ia in la.items():
        ib = lb[sym]
        np.testing.assert_array_equal(
            np.asarray(ba.count[ia]), np.asarray(bb.count[ib]), err_msg=sym
        )
        for side in (0, 1):
            n = int(np.asarray(ba.count[ia, side]))
            for leaf, table_a, table_b in (
                ("price", None, None),
                ("lots", None, None),
                ("oid", a.batch.oids, b.batch.oids),
                ("uid", a.batch.uids, b.batch.uids),
            ):
                va = np.asarray(getattr(ba, leaf)[ia, side][:n])
                vb = np.asarray(getattr(bb, leaf)[ib, side][:n])
                if table_a is None:
                    np.testing.assert_array_equal(va, vb, err_msg=f"{sym} {leaf}")
                else:
                    sa = [table_a.lookup(int(x)) for x in va]
                    sb = [table_b.lookup(int(x)) for x in vb]
                    assert sa == sb, f"{sym} {leaf}"


@pytest.mark.parametrize("dtype", ["int64", "int32"])
def test_export_import_round_trip_and_continued_matching(dtype):
    """Run a stream, export to the reference schema, restore into a fresh
    engine, then apply an identical continuation stream to both engines
    AND the oracle: books equal after restore, events identical after."""
    dt = jnp.int32 if dtype == "int32" else jnp.int64
    base = 10_000_000_000_000 if dtype == "int32" else 100_000_000
    rng = np.random.default_rng(17)

    def stream(n, oid0):
        out = []
        for i in range(n):
            is_del = i > 10 and rng.random() < 0.15
            out.append(
                Order(
                    uuid=f"u{int(rng.integers(0, 3))}",
                    oid=str(int(rng.integers(oid0, oid0 + i)) if is_del else oid0 + i),
                    symbol=f"sym{int(rng.integers(0, 4))}",
                    side=Side(int(rng.integers(0, 2))),
                    price=base + int(rng.integers(-500, 500)),
                    volume=int(rng.integers(1, 20)),
                    action=Action.DEL if is_del else Action.ADD,
                )
            )
        return out

    cfg = lambda: BookConfig(cap=32, max_fills=8, dtype=dt)
    a = MatchEngine(config=cfg(), n_slots=8, max_t=8)
    head = stream(150, 0)
    oracle = OracleEngine()
    for o in head:
        oracle.process(o)
    _run_marked(a, head)

    store = DictRedis()
    export_to_redis(a, client=store)

    b = MatchEngine(config=cfg(), n_slots=8, max_t=8)
    n = restore_from_redis(b, store)
    assert n == sum(int(x) for x in np.asarray(a.books.count).ravel())
    _books_semantically_equal(a, b)
    b.batch.verify_books()

    # identical continuation stream: a, b, and the oracle agree exactly
    tail = stream(120, 1000)
    expected = []
    for o in tail:
        expected.extend(oracle.process(o))
    ev_a = _run_marked(a, tail)
    ev_b = _run_marked(b, tail)
    assert ev_a == ev_b == expected
    _books_semantically_equal(a, b)


def test_pre_pool_marks_restore():
    a = MatchEngine(config=BookConfig(cap=16, max_fills=4), n_slots=8)
    queued = Order(uuid="u9", oid="queued", symbol="sym0", side=Side.BUY,
                   price=100, volume=5)
    a.mark(queued)  # marked but not yet consumed
    store = DictRedis()
    export_to_redis(a, client=store)
    b = MatchEngine(config=BookConfig(cap=16, max_fills=4), n_slots=8)
    restore_from_redis(b, store)
    assert ("sym0", "u9", "queued") in b.pre_pool
    # the queued ADD is admitted post-restore (the race marker survived)
    assert b.process([queued]) == []
    assert b.stats.dropped_no_prepool == 0


def test_reference_style_store_with_quirks():
    """Hand-built store the way a REAL gome Redis looks: float-formatted
    numerics, a leaked unreachable link entry (SURVEY §2.3.1), and depth
    residue (§2.3: HIncrByFloat leftovers) — the restore trusts the FIFO
    walk and warns on the depth mismatch."""
    store = DictRedis()
    sym = "eth2usdt"
    store.execute_command("ZADD", f"{sym}:SALE", 1e8, "100000000")
    link_key = f"{sym}:link:100000000"
    node = lambda oid, vol, prev, nxt: json.dumps(
        {
            "Uuid": "u1", "Oid": oid, "Symbol": sym, "Transaction": 1,
            "Price": 1e8, "Volume": float(vol),
            "NodeName": f"{sym}:node:{oid}",
            "IsFirst": prev is None, "IsLast": nxt is None,
            "PrevNode": f"{sym}:node:{prev}" if prev else "",
            "NextNode": f"{sym}:node:{nxt}" if nxt else "",
        }
    )
    store.execute_command("HSET", link_key, "f", f"{sym}:node:a")
    store.execute_command("HSET", link_key, "l", f"{sym}:node:b")
    store.execute_command("HSET", link_key, f"{sym}:node:a", node("a", 5e8, None, "b"))
    store.execute_command("HSET", link_key, f"{sym}:node:b", node("b", 3e8, "a", None))
    # leaked entry: unlinked but never HDel'd (the reference's delete bug)
    store.execute_command(
        "HSET", link_key, f"{sym}:node:leak", node("leak", 7e8, "a", "b")
    )
    # depth residue: says more than the list holds
    store.execute_command(
        "HSET", f"{sym}:depth", f"{sym}:depth:100000000", "800000001"
    )

    eng = MatchEngine(config=BookConfig(cap=16, max_fills=4), n_slots=8)
    with pytest.warns(RuntimeWarning, match="depth hash"):
        n = restore_from_redis(eng, store)
    assert n == 2  # the leaked entry is unreachable from f -> not imported
    eng.batch.verify_books()
    # FIFO preserved: a crossing BUY fills a (5) before b (3)
    taker = Order(uuid="t", oid="t1", symbol=sym, side=Side.BUY,
                  price=100000000, volume=800000000)
    eng.mark(taker)
    events = eng.process([taker])
    assert [e.match_node.oid for e in events] == ["a", "b"]
    assert [e.match_volume for e in events] == [500000000, 300000000]


def test_restore_grows_geometry():
    """An imported book deeper than the engine's cap (or wider than its
    lanes) grows the geometry instead of failing."""
    a = MatchEngine(config=BookConfig(cap=64, max_fills=8), n_slots=32)
    orders = [
        Order(uuid="u", oid=str(i), symbol=f"s{i % 20}", side=Side.SALE,
              price=100 + i, volume=1)
        for i in range(400)  # 20 resting asks on each of 20 symbols
    ]
    _run_marked(a, orders)
    store = DictRedis()
    export_to_redis(a, client=store)
    b = MatchEngine(config=BookConfig(cap=8, max_fills=8), n_slots=4)
    restore_from_redis(b, store)
    assert b.batch.config.cap >= 20
    assert b.batch.n_slots >= 20
    _books_semantically_equal(a, b)

"""Unit tests for the resilience layer (gome_tpu.utils.resilience):
backoff/jitter bounds, retry budgets, circuit-breaker state transitions
(fake clock — no real sleeping), and the Supervised connection wrapper's
reconnect + re-setup-hook + retry semantics."""

import random

import pytest

from gome_tpu.utils.resilience import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    BackoffPolicy,
    CircuitBreaker,
    CircuitOpenError,
    RetryBudget,
    RetryBudgetExceeded,
    Supervised,
    backoff_delays,
    resilience_snapshot,
)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# --- backoff --------------------------------------------------------------


def test_backoff_delays_within_bounds():
    pol = BackoffPolicy(base_s=0.05, max_s=2.0, max_retries=50)
    rng = random.Random(7)
    delays = list(backoff_delays(pol, rng))
    assert len(delays) == 50
    assert delays[0] == pol.base_s
    for d in delays:
        assert pol.base_s <= d <= pol.max_s


def test_backoff_decorrelated_jitter_growth():
    """Each delay is Uniform(base, 3*prev) clamped — so the sequence can
    grow past a pure-exponential schedule's early steps but never past
    max_s, and two seeds give different schedules (that is the point)."""
    pol = BackoffPolicy(base_s=0.1, max_s=10.0, max_retries=20)
    a = list(backoff_delays(pol, random.Random(1)))
    b = list(backoff_delays(pol, random.Random(2)))
    assert a != b
    for prev, nxt in zip(a, a[1:]):
        assert nxt <= max(3.0 * prev, pol.base_s) + 1e-9


def test_backoff_policy_validation():
    with pytest.raises(ValueError):
        BackoffPolicy(base_s=0)
    with pytest.raises(ValueError):
        BackoffPolicy(base_s=1.0, max_s=0.5)
    with pytest.raises(ValueError):
        BackoffPolicy(max_retries=0)


# --- retry budget ---------------------------------------------------------


def test_retry_budget_spends_and_refills():
    clock = FakeClock()
    b = RetryBudget(rate=1.0, burst=2.0, clock=clock)
    assert b.try_spend() and b.try_spend()
    assert not b.try_spend()  # empty
    clock.advance(1.0)  # one token accrues
    assert b.try_spend()
    assert not b.try_spend()
    clock.advance(100.0)  # caps at burst
    assert b.tokens() == pytest.approx(2.0)


# --- circuit breaker ------------------------------------------------------


def test_breaker_full_cycle():
    clock = FakeClock()
    br = CircuitBreaker(
        failure_threshold=3, reset_timeout_s=5.0, clock=clock
    )
    assert br.state == CLOSED and br.allow()
    br.record_failure()
    br.record_failure()
    assert br.state == CLOSED  # under threshold
    br.record_failure()
    assert br.state == OPEN
    assert not br.allow()  # fail fast while open
    clock.advance(4.9)
    assert not br.allow()
    clock.advance(0.2)  # cooldown elapsed
    assert br.state == HALF_OPEN
    assert br.allow()  # one probe admitted
    assert not br.allow()  # half_open_max=1: second probe refused
    br.record_failure()  # probe failed -> re-open, cooldown restarts
    assert br.state == OPEN
    clock.advance(5.1)
    assert br.allow()
    br.record_success()  # probe succeeded -> closed
    assert br.state == CLOSED
    assert (CLOSED, OPEN) in br.transitions
    assert (HALF_OPEN, CLOSED) in br.transitions
    assert br.opened_total == 2


def test_breaker_success_resets_failure_streak():
    br = CircuitBreaker(failure_threshold=2, clock=FakeClock())
    br.record_failure()
    br.record_success()
    br.record_failure()
    assert br.state == CLOSED  # streak broken; not 2 consecutive


# --- Supervised -----------------------------------------------------------


class FlakyConn:
    def __init__(self, fail_ops=0):
        self.fail_ops = fail_ops
        self.ops = 0
        self.closed = False

    def op(self):
        self.ops += 1
        if self.fail_ops > 0:
            self.fail_ops -= 1
            raise ConnectionError("flaky op")
        return "ok"

    def close(self):
        self.closed = True


def _sup(name, factory, clock=None, **kw):
    clock = clock or FakeClock()
    kw.setdefault("policy", BackoffPolicy(base_s=0.001, max_s=0.01,
                                          max_retries=5, budget_s=100))
    return Supervised(
        name, factory, clock=clock, sleep=lambda s: None,
        rng=random.Random(3), **kw
    )


def test_supervised_reconnects_and_retries_op():
    conns = []

    def factory():
        c = FlakyConn()
        conns.append(c)
        return c

    sup = _sup("t:retry", factory)
    first = sup.get()
    first.fail_ops = 1  # next op faults once
    assert sup.call(lambda c: c.op()) == "ok"
    assert len(conns) == 2  # faulted conn replaced
    assert conns[0].closed  # torn down, not leaked
    assert sup.retries_total == 1
    sup.close()


def test_supervised_retry_op_false_reraises_but_reconnects():
    conns = []

    def factory():
        c = FlakyConn()
        conns.append(c)
        return c

    sup = _sup("t:noretry", factory)
    sup.get().fail_ops = 1
    with pytest.raises(ConnectionError):
        sup.call(lambda c: c.op(), retry_op=False)
    # the NEXT call runs on a fresh connection
    assert sup.call(lambda c: c.op()) == "ok"
    assert len(conns) == 2
    sup.close()


def test_supervised_on_reconnect_hooks_fire():
    seen = []

    sup = _sup("t:hooks", FlakyConn, on_reconnect=[seen.append])
    c1 = sup.get()
    assert seen == [c1]  # prime runs hooks too
    sup.invalidate()
    c2 = sup.get()
    assert seen == [c1, c2] and c2 is not c1
    sup.close()


def test_supervised_dial_failure_exhausts_backoff():
    attempts = []

    def factory():
        attempts.append(1)
        raise ConnectionRefusedError("nobody home")

    sup = _sup("t:down", factory)
    with pytest.raises(RetryBudgetExceeded):
        sup.get()
    assert len(attempts) > 1  # actually retried under backoff
    sup.close()


def test_supervised_breaker_opens_and_fails_fast():
    clock = FakeClock()
    breaker = CircuitBreaker(
        failure_threshold=2, reset_timeout_s=60.0, clock=clock
    )

    def factory():
        raise ConnectionRefusedError("down hard")

    sup = _sup("t:breaker", factory, clock=clock, breaker=breaker)
    with pytest.raises(ConnectionError):
        sup.get()
    assert breaker.state == OPEN
    # breaker open: the next get fails in one shot, no dial attempts
    with pytest.raises(CircuitOpenError):
        sup.get()
    # cooldown -> half-open probe is admitted again (and fails -> open)
    clock.advance(61.0)
    with pytest.raises(ConnectionError):
        sup.get()
    assert breaker.state == OPEN
    sup.close()


def test_supervised_snapshot_and_registry():
    sup = _sup("t:snap", FlakyConn)
    sup.get()
    snap = sup.snapshot()
    assert snap["breaker"] == CLOSED
    assert snap["connected"] and snap["connects_total"] == 1
    assert "t:snap" in resilience_snapshot()
    sup.close()
    assert "t:snap" not in resilience_snapshot()


def test_supervised_metrics_exported():
    from gome_tpu.utils.metrics import REGISTRY

    sup = _sup("t:metrics", FlakyConn)
    sup.get()
    text = REGISTRY.render()
    assert "gome_conn_breaker_state_t_metrics" in text
    assert "gome_conn_reconnects_total_t_metrics" in text
    sup.close()


def test_supervised_retry_count_mutates_under_lock():
    """Regression (found by gomelint GL401): Supervised.call() bumped
    retries_total OUTSIDE self._lock — a read-modify-write racing every
    concurrent caller (lost updates), while snapshot() reads the counter
    under the lock expecting the true value. The instrumentation below is
    deterministic: an owner-tracking lock + a __setattr__ probe raise at
    the exact off-lock write, instead of hoping a thread hammer happens
    to interleave."""
    import threading

    class OwnedRLock:
        def __init__(self):
            self._rlock = threading.RLock()
            self._owner = None
            self._depth = 0

        def acquire(self, blocking=True, timeout=-1):
            got = self._rlock.acquire(blocking, timeout)
            if got:
                self._owner = threading.get_ident()
                self._depth += 1
            return got

        def release(self):
            self._depth -= 1
            if self._depth == 0:
                self._owner = None
            self._rlock.release()

        def __enter__(self):
            self.acquire()
            return self

        def __exit__(self, *exc):
            self.release()
            return False

        def held_by_me(self):
            return self._owner == threading.get_ident()

    conns = []

    def factory():
        c = FlakyConn()
        conns.append(c)
        return c

    sup = _sup("t:retry-lock", factory)
    lock = OwnedRLock()
    object.__setattr__(sup, "_lock", lock)

    violations = []

    class Probe(type(sup)):
        def __setattr__(self, name, value):
            if name == "retries_total" and not lock.held_by_me():
                violations.append(name)
            super().__setattr__(name, value)

    object.__setattr__(sup, "__class__", Probe)

    first = sup.get()
    first.fail_ops = 1  # one fault -> one reconnect -> one retry
    assert sup.call(lambda c: c.op()) == "ok"
    assert sup.retries_total == 1
    assert violations == [], (
        f"retries_total written off-lock {len(violations)} time(s)"
    )
    sup.close()

"""Native batch order codec: exact parity with the json path, graceful
fallback on inputs the native parser declines."""

import json
import time

import pytest

from gome_tpu.bus import decode_orders_batch, encode_order
from gome_tpu.bus.codec import decode_order
from gome_tpu.bus.ordercodec import _load
from gome_tpu.types import Action, Order, OrderType, Side
from gome_tpu.utils.streams import mixed_stream


def test_batch_decode_matches_json_path():
    orders = mixed_stream(n=300, seed=8, cancel_prob=0.2, market_prob=0.15)
    bodies = [encode_order(o) for o in orders]
    assert decode_orders_batch(bodies) == [decode_order(b) for b in bodies]


def test_batch_decode_fallback_cases():
    """Escaped strings, unknown keys, missing optional keys, whitespace —
    every message must decode exactly, native or fallback."""
    bodies = [
        encode_order(Order(uuid="u", oid="1", symbol="s", side=Side.BUY,
                           price=5, volume=7)),
        # escaped quote in oid -> native declines, json handles
        json.dumps({"Uuid": "u", "Oid": 'o"x', "Symbol": "s",
                    "Transaction": 1, "Price": 3, "Volume": 2}).encode(),
        # unknown extra key -> native declines
        b'{"Uuid":"a","Oid":"b","Symbol":"c","Transaction":0,"Price":1,'
        b'"Volume":1,"Extra":9}',
        # defaults: no Action, no Kind
        b'{"Uuid":"x","Oid":"y","Symbol":"z","Transaction":1,"Price":10,'
        b'"Volume":20}',
        # whitespace + reordered keys + Kind
        b'{ "Kind": 1 , "Volume": 4, "Price": 8, "Transaction": 0, '
        b'"Symbol": "w", "Oid": "q", "Uuid": "e", "Action": 1 }',
    ]
    got = decode_orders_batch(bodies)
    want = [decode_order(b) for b in bodies]
    assert got == want
    assert want[3].action is Action.ADD
    assert want[3].order_type is OrderType.LIMIT
    assert want[4].order_type is OrderType.MARKET


def test_malformed_json_declines_to_fallback():
    """Leading-zero ints, control chars in strings, int64 overflow: the
    native parser must decline so behavior matches json.loads exactly."""
    leading_zero = (
        b'{"Uuid":"u","Oid":"o","Symbol":"s","Transaction":0,"Price":007,'
        b'"Volume":1}'
    )
    ctrl = (
        b'{"Uuid":"u\nx","Oid":"o","Symbol":"s","Transaction":0,"Price":1,'
        b'"Volume":1}'
    )
    huge = (
        b'{"Uuid":"u","Oid":"o","Symbol":"s","Transaction":0,'
        b'"Price":99999999999999999999,"Volume":1}'
    )
    for body in (leading_zero, ctrl, huge):
        try:
            got = decode_orders_batch([body])
        except Exception as e:
            got = type(e).__name__
        try:
            want = [decode_order(body)]
        except Exception as e:
            want = type(e).__name__
        assert got == want, body


def test_out_of_range_enum_raises_like_json_path():
    bad = (
        b'{"Uuid":"u","Oid":"o","Symbol":"s","Transaction":7,"Price":1,'
        b'"Volume":1}'
    )
    with pytest.raises(ValueError):
        decode_orders_batch([bad])
    with pytest.raises(ValueError):
        decode_order(bad)


def test_non_ascii_falls_back_exactly():
    body = json.dumps({"Uuid": "u", "Oid": "o", "Symbol": "сим",
                       "Transaction": 0, "Price": 1, "Volume": 1}).encode()
    assert decode_orders_batch([body]) == [decode_order(body)]


@pytest.mark.skipif(_load() is None, reason="no native toolchain")
def test_native_path_is_faster():
    orders = mixed_stream(n=4000, seed=1, cancel_prob=0.1)
    bodies = [encode_order(o) for o in orders]
    decode_orders_batch(bodies)  # warm lib
    t0 = time.perf_counter()
    decode_orders_batch(bodies)
    native = time.perf_counter() - t0
    t0 = time.perf_counter()
    [decode_order(b) for b in bodies]
    js = time.perf_counter() - t0
    # loose bound: just prove the native call isn't a slower path in disguise
    assert native < js * 1.5, (native, js)

"""Native host ops (native/hostops.cc via engine.nativehost): differential
parity with the pure-Python implementations — the C++ interner against
engine.host.Interner, the C++ pre-pool against LocalPrePool, including the
fused frame-admission pass, rollback restore, and snapshot iteration."""

import numpy as np
import pytest

from gome_tpu.engine.host import Interner
from gome_tpu.engine.nativehost import NativeInterner, available
from gome_tpu.engine.prepool import (
    LocalPrePool,
    NativeConsumed,
    NativePrePool,
)

pytestmark = pytest.mark.skipif(
    not available(), reason="native toolchain unavailable"
)


def test_interner_parity_randomized():
    rng = np.random.default_rng(3)
    py, nat = Interner(), NativeInterner()
    words = [f"w{int(rng.integers(0, 500))}" for _ in range(2_000)]
    for w in words:
        assert py.intern(w) == nat.intern(w)
    assert len(py) == len(nat)
    assert py.to_list() == nat.to_list()
    for i in range(len(py)):
        assert py.lookup(i) == nat.lookup(i)
    assert py.get("w0") == nat.get("w0")
    assert py.get("missing") is None and nat.get("missing") is None
    # batch intern matches one-by-one interning
    more = np.array(
        [f"x{int(rng.integers(0, 100))}".encode() for _ in range(500)],
        dtype="S8",
    )
    ids_nat = nat.intern_batch(more)
    ids_py = np.array([py.intern(b.decode()) for b in more.tolist()])
    np.testing.assert_array_equal(ids_nat, ids_py)
    # gather round-trips
    some = np.array([1, 5, 0, len(py) - 1], np.int64)
    got = [s.decode() for s in nat.gather_padded(some).tolist()]
    want = [py.lookup(int(i)) for i in some]
    assert got == want
    # table view quacks like the list
    assert nat.table[3] == py.table[3]
    assert list(nat.table) == list(py.table)
    # from_list round trip
    nat2 = NativeInterner.from_list(py.to_list())
    assert nat2.to_list() == py.to_list()
    with pytest.raises(IndexError):
        nat.lookup(10_000_000)


def _frame_cols(rng, n, n_syms=5, n_uuids=3, nop_prob=0.1, del_prob=0.2):
    symbols = [f"sym{i}" for i in range(n_syms)]
    uuids = [f"u{i}" for i in range(n_uuids)]
    action = np.where(
        rng.random(n) < nop_prob,
        0,
        np.where(rng.random(n) < del_prob, 2, 1),
    ).astype(np.uint8)
    return {
        "n": n,
        "action": action,
        "symbols": symbols,
        "symbol_idx": rng.integers(0, n_syms, n).astype(np.uint32),
        "uuids": uuids,
        "uuid_idx": rng.integers(0, n_uuids, n).astype(np.uint32),
        "oids": np.array(
            [f"o{int(rng.integers(0, n))}".encode() for i in range(n)],
            dtype="S8",
        ),
    }


def _keys_of(cols):
    return [
        (
            cols["symbols"][int(cols["symbol_idx"][i])],
            cols["uuids"][int(cols["uuid_idx"][i])],
            cols["oids"][i].decode(),
        )
        for i in range(cols["n"])
    ]


def _local_admit(pool: LocalPrePool, cols):
    """The Python-path admission semantics, spelled out as the oracle."""
    keep = np.zeros(cols["n"], bool)
    consumed = set()
    for i, (a, key) in enumerate(zip(cols["action"].tolist(), _keys_of(cols))):
        if a == 1:  # ADD
            if key in pool:
                pool.discard(key)
                consumed.add(key)
                keep[i] = True
        elif a == 2:  # DEL
            keep[i] = True
            if key in pool:
                pool.discard(key)
                consumed.add(key)
    return keep, consumed


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_prepool_frame_admission_parity(seed):
    rng = np.random.default_rng(seed)
    cols = _frame_cols(rng, 400)
    keys = _keys_of(cols)
    # Mark a random subset (some ADDs marked, some not; some DELs racing).
    marked = [k for k in keys if rng.random() < 0.7]
    local = LocalPrePool(marked)
    native = NativePrePool()
    native |= marked
    assert native == set(local)

    keep_l, consumed_l = _local_admit(local, cols)
    keep_n, consumed_n = native.consume_frame(cols)
    np.testing.assert_array_equal(np.asarray(keep_n), keep_l)
    assert isinstance(consumed_n, NativeConsumed)
    assert set(consumed_n) == consumed_l
    assert len(consumed_n) == len(consumed_l)
    assert native == set(local)  # post-admission pool state identical

    # Rollback: restoring consumed marks converges the two pools again.
    local |= consumed_l
    native |= consumed_n
    assert native == set(local)


def test_prepool_mark_frame_matches_per_order_marks():
    rng = np.random.default_rng(9)
    cols = _frame_cols(rng, 300)
    a = NativePrePool()
    a.mark_frame(cols)
    b = LocalPrePool()
    for key, act in zip(_keys_of(cols), cols["action"].tolist()):
        if act == 1:  # ADDs only (main.go:42-45)
            b.add(key)
    assert a == set(b)


def test_prepool_set_protocol():
    p = NativePrePool()
    k = ("eth2usdt", "u1", "42")
    assert k not in p
    p.add(k)
    p.add(k)  # idempotent
    assert k in p and len(p) == 1
    p.discard(("nope",) * 3)  # no-op
    assert sorted(p) == [k]
    p.update([("a", "b", "c")])
    assert len(p) == 2
    p.clear()
    assert len(p) == 0 and list(p) == []
    assert p.consume_batch([k]) == [False]


def test_prepool_concurrent_mark_and_consume():
    """The gateway's gRPC threads mark WHILE the consumer admits (the C++
    mutex's reason to exist): a producer thread marks each frame's keys
    then hands the frame over; the consumer thread admits it. Every ADD
    must be admitted (its mark was written strictly before hand-off) and
    the pool must end empty."""
    import queue
    import threading

    import numpy as np

    rng = np.random.default_rng(21)
    pool = NativePrePool()
    frames = [
        _frame_cols(rng, 200, nop_prob=0.0, del_prob=0.0) for _ in range(30)
    ]
    handoff: queue.Queue = queue.Queue()

    def gateway():
        for cols in frames:
            pool.mark_frame(cols)
            handoff.put(cols)
        handoff.put(None)

    admitted = 0
    dropped = 0
    t = threading.Thread(target=gateway)
    t.start()
    while True:
        cols = handoff.get()
        if cols is None:
            break
        keep, consumed = pool.consume_frame(cols)
        admitted += int(np.asarray(keep).sum())
        dropped += cols["n"] - int(np.asarray(keep).sum())
        # Exercise iteration/len under concurrent marking too (retry on
        # the documented changed-size error).
        try:
            len(pool)
        except RuntimeError:
            pass
    t.join()
    # oids repeat across frames (_frame_cols draws from a shared range):
    # a repeated key's second mark can be consumed by the first frame's
    # admission... so count via totals: every mark written was consumed
    # exactly once — the pool ends empty and admitted == marks written.
    assert len(pool) == 0
    total_unique_marks = sum(
        len({k for k, a in zip(_keys_of(c), c["action"].tolist()) if a == 1})
        for c in frames
    )
    assert admitted + dropped == sum(c["n"] for c in frames)
    assert admitted <= total_unique_marks + dropped

"""Fleet observability (round 10): the Prometheus-exposition parse/merge
engine (utils.metrics), cross-process trace stitching + the FleetAggregator
singleton (obs.fleet), the /fleet ops endpoint, FileQueue cross-process
tailing, per-queue depth gauges, and the committed fleet verdict
(FLEET_r01.json, produced by scripts/fleet_drill.py)."""

import json
import os
import sys
import urllib.request

import pytest

from gome_tpu.bus.filelog import FileQueue
from gome_tpu.config import Config, EngineConfig, FleetConfig, OpsConfig
from gome_tpu.obs.fleet import (
    FLEET,
    FleetAggregator,
    estimate_offsets,
    stitch_journeys,
    stitched_chrome_trace,
)
from gome_tpu.utils.metrics import (
    Registry,
    family_total,
    merge_expositions,
    parse_exposition,
    render_exposition,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- exposition parse / merge ------------------------------------------------


def _member_registry(orders: int, rss: float, queue_depth: int) -> Registry:
    """One member's metric surface: a counter, a labeled gauge, a plain
    gauge, and a labeled histogram — every shape Registry.render()
    emits."""
    reg = Registry()
    c = reg.counter("gome_orders_consumed_total", "orders drained")
    for _ in range(orders):
        c.inc()
    reg.gauge("gome_rss_bytes", "resident set size").set(rss)
    reg.gauge(
        "gome_bus_depth", "queue depth", labels={"queue": "doOrder"}
    ).set(queue_depth)
    h = reg.histogram(
        "gome_stage_seconds", "per-stage latency",
        buckets=(0.001, 0.01, 0.1), labels={"stage": "ingress"},
    )
    for v in (0.0005, 0.005, 0.05, 0.5):
        h.observe(v)
    return reg


def test_parse_render_roundtrip_is_byte_identical():
    """parse -> re-render must reproduce Registry.render() output
    byte-for-byte: the merged fleet exposition is a real scrape
    target, not a lossy summary."""
    text = _member_registry(7, 12345678.0, 3).render()
    fams = parse_exposition(text)
    assert render_exposition(fams) == text
    # And a second round trip is a fixed point.
    assert render_exposition(parse_exposition(render_exposition(fams))) == (
        text
    )


def test_merge_is_lossless_and_labels_procs():
    a = _member_registry(10, 100.0, 2).render()
    b = _member_registry(32, 200.0, 5).render()
    merged = merge_expositions({"gw0": a, "c0": b})

    # Counters sum per label set.
    assert family_total(merged["gome_orders_consumed_total"]) == 42
    assert (
        family_total(parse_exposition(a)["gome_orders_consumed_total"])
        + family_total(parse_exposition(b)["gome_orders_consumed_total"])
        == 42
    )
    # Histograms merge bucket-wise: counts sum, bucket edges survive.
    stage = merged["gome_stage_seconds"]
    count_samples = [
        s for s in stage.samples if s.name == "gome_stage_seconds_count"
    ]
    assert [s.value for s in count_samples] == [8.0]
    les = [
        s.labels["le"] for s in stage.samples
        if s.name == "gome_stage_seconds_bucket"
    ]
    assert les == ["0.001", "0.01", "0.1", "+Inf"]
    # Gauges union under a new proc label — both members' values survive.
    rss = merged["gome_rss_bytes"]
    assert {s.labels["proc"]: s.value for s in rss.samples} == {
        "gw0": 100.0, "c0": 200.0,
    }
    depth = merged["gome_bus_depth"]
    assert {
        (s.labels["proc"], s.labels["queue"]): s.value
        for s in depth.samples
    } == {("gw0", "doOrder"): 2.0, ("c0", "doOrder"): 5.0}
    # The merged document re-renders as a valid, stable exposition.
    text = render_exposition(merged)
    assert render_exposition(parse_exposition(text)) == text


def test_merge_rejects_bucket_mismatch_and_type_conflict():
    reg_a = Registry()
    reg_a.histogram("h", "x", buckets=(1.0, 2.0)).observe(1.5)
    reg_b = Registry()
    reg_b.histogram("h", "x", buckets=(1.0, 4.0)).observe(1.5)
    with pytest.raises(ValueError, match="bucket"):
        merge_expositions({"a": reg_a.render(), "b": reg_b.render()})

    reg_c = Registry()
    reg_c.counter("m", "x").inc()
    reg_d = Registry()
    reg_d.gauge("m", "x").set(1.0)
    with pytest.raises(ValueError, match="conflicting types"):
        merge_expositions({"a": reg_c.render(), "b": reg_d.render()})


def test_parse_rejects_garbage():
    with pytest.raises(ValueError):
        parse_exposition("not a metric line at all {{{\n")


# -- trace stitching ---------------------------------------------------------


def _two_process_fixture(offset: float = 100.0, transit: float = 0.002):
    """A scripted gateway + consumer export pair whose clocks differ by
    a KNOWN offset: the consumer's perf_counter reads `offset` seconds
    ahead of the gateway's. bus_transit t0 is sender-clock (carried in
    the wire context); everything else in the consumer export is
    consumer-clock."""
    gw = {"pid": 101, "journeys": [
        {"trace_id": "t1", "open": True,
         "spans": [["ingress", 1.000, 1.001, None],
                   ["enqueue", 1.001, 1.002, None]]},
        {"trace_id": "t2", "open": True,
         "spans": [["ingress", 2.000, 2.001, None],
                   ["enqueue", 2.001, 2.002, None]]},
    ]}
    con = {"pid": 202, "journeys": [
        {"trace_id": "t1", "open": False,
         "spans": [["bus_transit", 1.002, 1.002 + transit + offset, None],
                   ["device_execute",
                    1.010 + offset, 1.015 + offset, None]]},
        {"trace_id": "t2", "open": False,
         "spans": [["bus_transit",
                    2.002, 2.002 + transit + offset + 0.001, None],
                   ["device_execute",
                    2.010 + offset, 2.014 + offset, None]]},
    ]}
    return {"gw": gw, "con": con}


def test_estimate_offsets_uses_min_transit():
    exports = _two_process_fixture(offset=100.0, transit=0.002)
    offsets = estimate_offsets(exports)
    # min over t1 - t0 of bus_transit: the t1 clock is off by +100 s,
    # so the estimate is offset + fastest transit.
    assert offsets == {("gw", "con"): pytest.approx(100.002)}


def test_stitch_aligns_receiver_spans_onto_sender_clock():
    exports = _two_process_fixture(offset=100.0, transit=0.002)
    stitch = stitch_journeys(exports)
    assert stitch["traces"] == 2 and stitch["joined"] == 2
    assert stitch["offsets"] == {"gw->con": pytest.approx(100.002)}
    j1 = next(j for j in stitch["journeys"] if j["trace_id"] == "t1")
    assert j1["sender"] == "gw"
    assert j1["procs"] == ["con", "gw"]
    by_stage = {s["stage"]: s for s in j1["spans"]}
    # bus_transit: t0 already sender-clock, only t1 shifted.
    assert by_stage["bus_transit"]["t0"] == pytest.approx(1.002)
    assert by_stage["bus_transit"]["t1"] == pytest.approx(1.002)
    # device_execute shifted fully onto the sender clock.
    assert by_stage["device_execute"]["t0"] == pytest.approx(1.008)
    # Spans are time-ordered and the journey spans the whole pipeline.
    t0s = [s["t0"] for s in j1["spans"]]
    assert t0s == sorted(t0s)
    assert j1["start"] == pytest.approx(1.000)
    # end = device_execute t1 shifted by the offset ESTIMATE (true offset
    # + fastest transit), so 1.015 - 0.002 relative to the sender clock.
    assert j1["duration_s"] == pytest.approx(0.013, abs=1e-6)


def test_stitched_chrome_trace_tracks_per_process():
    stitch = stitch_journeys(_two_process_fixture())
    doc = stitched_chrome_trace(stitch)
    names = [
        ev["args"]["name"] for ev in doc["traceEvents"]
        if ev.get("ph") == "M"
    ]
    assert sorted(names) == ["con", "gw"]
    xs = [ev for ev in doc["traceEvents"] if ev.get("ph") == "X"]
    assert xs and all(ev["ts"] >= 0 for ev in xs)
    assert len({ev["pid"] for ev in xs}) == 2


def test_stitch_skips_single_process_traces():
    exports = _two_process_fixture()
    del exports["con"]["journeys"][0]  # t1 now gateway-only
    stitch = stitch_journeys(exports)
    assert stitch["traces"] == 2 and stitch["joined"] == 1
    assert stitch["journeys"][0]["trace_id"] == "t2"


# -- the aggregator singleton ------------------------------------------------


def test_disabled_poll_is_zero_alloc():
    """The unarmed aggregator is one attribute check, zero allocations —
    the same sys.getallocatedblocks guard as the tracer/journal/
    timeline/faults singletons."""
    agg = FleetAggregator()  # never installed
    assert not agg.enabled

    def drill(n):
        i = 0
        while i < n:
            if agg.poll() is not None:
                raise AssertionError("unreachable")
            i += 1

    drill(64)  # warm lazy caches
    before = sys.getallocatedblocks()
    drill(200)
    after = sys.getallocatedblocks()
    assert after - before <= 2, f"disabled poll() allocated {after - before}"


def test_aggregator_polls_scripted_members():
    surfaces = {
        "a": _member_registry(3, 1.0, 0),
        "b": _member_registry(4, 2.0, 1),
    }

    def fetch(url, timeout_s):
        proc, _, path = url.partition("://")[2].partition("/")
        path = "/" + path
        if path == "/metrics":
            return surfaces[proc].render()
        if path == "/healthz":
            return json.dumps({"healthy": True, "detail": {}})
        if path == "/durability":
            return json.dumps({"matchfeed": {
                "last_seq": 6, "observed": 7, "dupes": 0, "gaps": 0,
            }})
        if path.startswith("/timeline"):
            return json.dumps({"samples": []})
        raise AssertionError(url)

    reg = Registry()
    agg = FleetAggregator()
    agg.install(
        {"a": "inproc://a", "b": "inproc://b"}, registry=reg, fetch=fetch
    )
    try:
        snap = agg.poll()
        assert snap["a"]["healthy"] and not snap["a"]["degraded"]
        payload = agg.payload()
        assert payload["enabled"]
        assert set(payload["members"]) == {"a", "b"}
        assert payload["seq"]["fleet"]["observed"] == 14
        fams = payload["metrics"]["families"]
        assert fams["gome_orders_consumed_total"]["total"] == 7
        text = payload["metrics"]["exposition"]
        assert render_exposition(parse_exposition(text)) == text
        roll = agg.rollup()
        assert roll["polls"] >= 1 and roll["unhealthy_polls"] == 0
        assert reg.render().count("gome_fleet_members 2") == 1
    finally:
        agg.disable()
    assert agg.poll() is None


def test_fleet_http_endpoint_serves_merged_view():
    """/fleet over real HTTP on a live service: the singleton aggregator
    federates the service's own ops endpoint and the payload's merged
    exposition is scrape-valid."""
    svc = None
    try:
        from gome_tpu.service.app import EngineService

        svc = EngineService(Config(
            engine=EngineConfig(cap=32, n_slots=16, max_t=8, dtype="int32"),
            ops=OpsConfig(
                enabled=True, port=0, profile=False, hostprof=False,
            ),
        ))
        svc.ops.start()
        base = f"http://127.0.0.1:{svc.ops.port}"
        FLEET.install({"self": base}, interval_s=5.0)
        FLEET.poll()
        with urllib.request.urlopen(base + "/fleet", timeout=5) as resp:
            assert resp.status == 200
            doc = json.loads(resp.read().decode())
        assert doc["enabled"] and set(doc["members"]) == {"self"}
        text = doc["metrics"]["exposition"]
        assert render_exposition(parse_exposition(text)) == text
        assert "gome_bus_depth" in text
    finally:
        FLEET.disable()
        if svc is not None:
            svc.stop()


def test_fleet_config_member_map():
    fc = FleetConfig(
        enabled=True, members=("gw0=http://h:1", {"c0": "http://h:2"})
    )
    assert fc.member_map() == {"gw0": "http://h:1", "c0": "http://h:2"}
    with pytest.raises(ValueError, match="duplicate"):
        FleetConfig(
            enabled=True, members=("x=http://h:1", "x=http://h:2")
        ).member_map()
    with pytest.raises(ValueError):
        FleetConfig(enabled=True, members=())
    with pytest.raises(ValueError):
        FleetConfig(members=("nourl",))


# -- cross-process file-queue tailing ---------------------------------------


def test_filelog_reader_tails_external_appends(tmp_path):
    """A reader FileQueue instance sees records appended through a
    DIFFERENT instance (the fleet's live gateway-writer / consumer-
    reader split over one log)."""
    base = str(tmp_path / "doOrder")
    reader = FileQueue("doOrder", base)
    writer = FileQueue("doOrder", base)
    assert reader.end_offset() == 0
    writer.publish(b"one")
    writer.publish(b"two")
    assert reader.end_offset() == 2
    msgs = reader.read_from(0, 10)
    assert [m.body for m in msgs] == [b"one", b"two"]
    assert [m.offset for m in msgs] == [0, 1]
    writer.publish(b"three")
    assert [m.body for m in reader.read_from(2, 10)] == [b"three"]
    writer.close()
    reader.close()


def test_filelog_tail_skips_incomplete_record_without_truncating(tmp_path):
    """A torn tail mid-append by the live writer is SKIPPED by the
    tailing reader (never truncated — the writer finishes it); the
    record becomes visible once complete."""
    import struct

    base = str(tmp_path / "q")
    writer = FileQueue("q", base)
    writer.publish(b"whole")
    reader = FileQueue("q", base)
    assert reader.end_offset() == 1
    # Simulate the writer mid-append: length prefix + partial payload.
    record = struct.pack(">I", 6) + b"par"
    with open(base + ".log", "ab") as f:
        f.write(record)
    assert reader.end_offset() == 1  # incomplete tail not indexed
    size_before = os.path.getsize(base + ".log")
    with open(base + ".log", "ab") as f:
        f.write(b"tia")  # writer completes the record
    assert os.path.getsize(base + ".log") == size_before + 3
    assert reader.end_offset() == 2
    assert reader.read_from(1, 1)[0].body == b"partia"
    writer.close()
    reader.close()


def test_queue_depth_gauges_export(tmp_path):
    from gome_tpu.bus.base import export_queue_metrics
    from gome_tpu.bus.memory import MemoryQueue

    reg = Registry()
    q = MemoryQueue("doOrder")
    export_queue_metrics(q, registry=reg)
    q.publish(b"a")
    q.publish(b"b")
    q.commit(1)
    text = reg.render()
    assert 'gome_bus_depth{queue="doOrder"} 1' in text
    assert 'gome_bus_end_offset{queue="doOrder"} 2' in text
    assert 'gome_bus_committed_offset{queue="doOrder"} 1' in text


# -- the committed verdict ---------------------------------------------------


def test_fleet_verdict_pin():
    """FLEET_r01.json (committed, regenerated by scripts/fleet_drill.py)
    stays green and keeps its schema: the aggregate table, the stitch
    section, the lossless-merge proof, and every check passing."""
    path = os.path.join(ROOT, "FLEET_r01.json")
    with open(path) as f:
        verdict = json.load(f)
    assert verdict["schema"] == "gome-fleet-verdict-v1"
    assert verdict["pass"] is True
    assert all(verdict["checks"].values()), verdict["checks"]
    assert set(verdict["checks"]) >= {
        "all_members_healthy", "zero_degradations", "exactly_once_fleet",
        "stitched_per_partition", "merge_roundtrip", "merge_lossless",
    }
    table = verdict["table"]
    assert table["fleet"]["orders_per_sec"] > 0
    assert table["e2e_latency_ms"]["p50"] > 0
    assert len(
        [p for p in table["procs"].values() if p["role"] == "gateway"]
    ) == verdict["config"]["partitions"]
    assert all(n >= 1 for n in verdict["stitch"]["per_partition"])
    merge = verdict["merge"]
    assert merge["roundtrip_identical"] is True
    assert (
        merge["orders_consumed_total"]["merged"]
        == merge["orders_consumed_total"]["sum_of_members"]
        == merge["orders_consumed_total"]["grpc_accepted"]
    )
    for part in verdict["seq"]["partitions"]:
        assert part["seq_audit"]["dupes"] == 0
        assert part["seq_audit"]["gaps"] == 0

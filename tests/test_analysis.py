"""gomelint golden fixtures: every rule family fires on seeded-bad input,
stays silent on the idiomatic good twin, honors suppressions — and the
whole tree comes back clean (the same gate CI's analysis job enforces)."""

from __future__ import annotations

import os
import subprocess
import sys
import threading

import jax
import jax.numpy as jnp
import pytest

from gome_tpu.analysis import run_source
from gome_tpu.analysis.core import rule_catalogue, run_paths
from gome_tpu.analysis.envelope import check_engine_envelope, check_jaxpr
from gome_tpu.analysis.runtime import (
    LockDisciplineError,
    OwnedLock,
    instrument,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def rules_of(findings):
    return sorted({f.rule for f in findings})


# --- GL1xx trace-safety ---------------------------------------------------


BAD_TRACE = '''
import jax
import numpy as np

@jax.jit
def f(x, y):
    if x > 0:
        y = y + 1
    v = float(x)
    w = x.item()
    z = np.asarray(y)
    for row in x:
        z = z + 1
    return v + w
'''


def test_trace_safety_flags_bad_fixture():
    findings = run_source(BAD_TRACE)
    assert rules_of(findings) == ["GL101", "GL102", "GL103", "GL104"]
    # the `if` and the `for` are two distinct GL103 sites
    assert sum(f.rule == "GL103" for f in findings) == 2


def test_trace_safety_propagates_through_call_graph():
    src = '''
import jax

def helper(a):
    return int(a)

@jax.jit
def g(x):
    return helper(x)
'''
    findings = run_source(src)
    assert rules_of(findings) == ["GL101"]
    assert "helper" in findings[0].message


def test_trace_safety_scan_body_is_traced():
    src = '''
import jax

@jax.jit
def g(xs):
    def body(carry, x):
        return carry, float(x)
    return jax.lax.scan(body, 0, xs)
'''
    assert rules_of(run_source(src)) == ["GL101"]


GOOD_TRACE = '''
import functools
import jax
import jax.numpy as jnp

@functools.partial(jax.jit, static_argnums=0)
def f(config, x):
    n = x.shape[-1]
    if config.cap > 4:          # static arg: host branching is fine
        x = x + 1
    k = 1
    while k < n:                # shape-derived bound: static under trace
        x = x + jnp.pad(x[..., :-k], [(0, 0)] * (x.ndim - 1) + [(k, 0)])
        k *= 2
    if jnp.dtype(x.dtype).itemsize <= 4:
        x = jnp.minimum(x, 7)
    return x

def host_only(a):
    return float(a.sum())       # not reachable from any jit entry
'''


def test_trace_safety_good_twin_is_clean():
    assert run_source(GOOD_TRACE) == []


def test_trace_safety_identity_test_is_static():
    # `x is None` never concretizes a tracer — branching on it is host-
    # static (the bench's mixed full/dense round-chain relies on this)
    src = '''
import jax

@jax.jit
def f(x, ids):
    if ids is None:
        return x
    return x + 1
'''
    assert run_source(src) == []


def test_trace_safety_namedtuple_unroll_idiom_is_clean():
    # the engine/step.py idiom: iterate a host container of tracers
    src = '''
import jax

@jax.jit
def f(own, entry):
    out = list(own)
    for a in out:
        a = a + 1
    pairs = [a + v for a, v in zip(own, entry)]
    return pairs
'''
    assert run_source(src) == []


def test_trace_safety_line_suppression():
    src = '''
import jax

@jax.jit
def f(x):
    return float(x)  # gomelint: disable=GL101 — fixture-sanctioned
'''
    assert run_source(src) == []
    assert rules_of(run_source(src, keep_suppressed=True)) == ["GL101"]


def test_file_suppression():
    src = '''
# gomelint: disable-file=GL101
import jax

@jax.jit
def f(x):
    return float(x)
'''
    assert run_source(src) == []


# --- GL2xx int32-envelope (jaxpr) ----------------------------------------


def test_envelope_flags_float_and_width_creep():
    x = jnp.zeros((4,), jnp.int32)
    f32 = jax.make_jaxpr(lambda v: v.astype(jnp.float32) * 2.5)(x)
    assert rules_of(check_jaxpr(f32, "int32", "fixture")) == ["GL202"]

    with jax.experimental.enable_x64():
        i64 = jax.make_jaxpr(
            lambda v: v.astype(jnp.int64) + 1
        )(jnp.zeros((4,), jnp.int32))
        f64 = jax.make_jaxpr(lambda v: v * 2.5)(jnp.zeros((4,), jnp.float64))
    assert rules_of(check_jaxpr(i64, "int32", "fixture")) == ["GL203"]
    assert "GL201" in rules_of(check_jaxpr(f64, "int32", "fixture"))


def test_envelope_recurses_into_nested_jaxprs():
    # the creep hides inside a scan body — the walk must find it
    def body(c, x):
        return c, x.astype(jnp.float32) * 0.5

    closed = jax.make_jaxpr(
        lambda xs: jax.lax.scan(body, jnp.int32(0), xs)
    )(jnp.zeros((4,), jnp.int32))
    assert "GL202" in rules_of(check_jaxpr(closed, "int32", "nested"))


def test_envelope_int64_engine_allows_int64():
    with jax.experimental.enable_x64():
        i64 = jax.make_jaxpr(
            lambda v: v + 1
        )(jnp.zeros((4,), jnp.int64))
    assert check_jaxpr(i64, "int64", "fixture") == []


@pytest.mark.parametrize("dtype", ["int32", "int64"])
def test_engine_envelope_clean(dtype):
    """The real engine graphs — step, batch, dense, compaction, scatter,
    pallas-interpret — audited in the dtype's native x64 mode."""
    assert check_engine_envelope(dtype) == []


# --- GL3xx recompile-hazard ----------------------------------------------


BAD_RECOMPILE = '''
import functools
import jax

def make(n):
    @jax.jit
    def f(x):
        return x * n
    return f

def run(x):
    return jax.jit(lambda v: v + 1)(x)

class Engine:
    @jax.jit
    def step(self, x):
        return x

y = jax.jit(lambda x: x, static_argnums=(0,))([1, 2])
'''


def test_recompile_flags_bad_fixture():
    assert rules_of(run_source(BAD_RECOMPILE)) == [
        "GL301", "GL302", "GL303", "GL304",
    ]


GOOD_RECOMPILE = '''
import functools
import jax

@functools.lru_cache(maxsize=256)
def make(n):                     # the engine/frames.py factory idiom
    @jax.jit
    def f(x):
        return x * n
    return f

@jax.jit
def top(x):
    return x

step = functools.partial(jax.jit, static_argnums=0)(top)
'''


def test_recompile_good_twin_is_clean():
    assert run_source(GOOD_RECOMPILE) == []


# --- GL4xx lock-discipline -----------------------------------------------


BAD_LOCKS = '''
import threading

class Batcher:
    def __init__(self):
        self._lock = threading.Lock()
        self._buf = []          # guarded by self._lock
        self.total = 0          # guarded by self._lock
        self._ghost = 0         # guarded by self._missing

    def submit(self, o):
        with self._lock:
            self._buf.append(o)
        self.total += 1

    def peek(self):
        return len(self._buf)

    def escape(self):
        with self._lock:
            return lambda: self._buf.pop()
'''


def test_locks_flags_bad_fixture():
    findings = run_source(BAD_LOCKS)
    assert rules_of(findings) == ["GL401", "GL402", "GL403"]
    lines = {f.rule: f.line for f in findings}
    assert lines["GL401"] == 14  # self.total += 1 off-lock
    # the closure escaping the with-block is an off-lock read
    assert any(f.rule == "GL402" and f.line == 21 for f in findings)


GOOD_LOCKS = '''
import threading

class Batcher:
    def __init__(self):
        self._lock = threading.Lock()
        self._buf = []          # guarded by self._lock
        self.total = 0          # guarded by self._lock

    def submit(self, o):
        with self._lock:
            self._buf.append(o)
            self.total += 1

    def _flush_locked(self):
        batch, self._buf = self._buf, []
        return batch

    # holds: self._lock
    def annotated(self):
        return list(self._buf)

    def flush(self):
        with self._lock:
            return self._flush_locked()
'''


def test_locks_good_twin_is_clean():
    assert run_source(GOOD_LOCKS) == []


def test_locks_condition_counts_as_lock():
    src = '''
import threading

class Q:
    def __init__(self):
        self._cond = threading.Condition()
        self._n = 0  # guarded by self._cond

    def bump(self):
        with self._cond:
            self._n += 1
            self._cond.notify_all()
'''
    assert run_source(src) == []


# --- GL4xx runtime assertion mode ----------------------------------------


class _Thing:
    def __init__(self):
        self._lock = threading.Lock()
        self.counter = 0

    def bump(self):
        with self._lock:
            self.counter += 1

    def racy_bump(self):
        self.counter += 1


def test_runtime_instrument_catches_off_lock_write():
    t = _Thing()
    lock = instrument(t, ("counter",))
    t.bump()  # disciplined write: fine
    assert t.counter == 1
    with pytest.raises(LockDisciplineError):
        t.racy_bump()
    # the violating write did not land
    assert t.counter == 1
    assert isinstance(lock, OwnedLock)


def test_runtime_owned_lock_tracks_owner():
    lock = OwnedLock()
    assert not lock.held_by_me()
    with lock:
        assert lock.held_by_me()
        seen = []
        th = threading.Thread(target=lambda: seen.append(lock.held_by_me()))
        th.start()
        th.join()
        assert seen == [False]
    assert not lock.held_by_me()


def test_runtime_instrument_on_real_batcher():
    """The production FrameBatcher under runtime assertions: a full
    submit/flush cycle never writes its guarded state off-lock."""
    from gome_tpu.bus.memory import MemoryQueue
    from gome_tpu.service.batcher import FrameBatcher
    from gome_tpu.types import Action, Order, OrderType, Side

    b = FrameBatcher(MemoryQueue("doOrder"), max_n=2, max_wait_s=60)
    try:
        instrument(b, ("_buf", "_spill", "_oldest", "_degraded_since"))
        for i in range(4):
            b.submit(Order(
                uuid="u", oid=f"o{i}", symbol="S", side=Side.BUY,
                price=100, volume=1, action=Action.ADD,
                order_type=OrderType.LIMIT,
            ))
        b.flush()
    finally:
        b.close()


# --- whole-tree clean runs (the CI gate) ---------------------------------


def test_whole_tree_is_clean():
    findings = run_paths([os.path.join(ROOT, "gome_tpu")])
    assert findings == [], "\n".join(f.format() for f in findings)


def test_cli_exits_zero_on_tree_and_lists_rules():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "gomelint.py"),
         os.path.join(ROOT, "gome_tpu"), "--report",
         os.path.join(ROOT, ".gomelint-test-report.json")],
        capture_output=True, text=True, env=env, cwd=ROOT,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "0 finding(s)" in out.stdout
    import json
    with open(os.path.join(ROOT, ".gomelint-test-report.json")) as fh:
        report = json.load(fh)
    assert report["count"] == 0
    os.unlink(os.path.join(ROOT, ".gomelint-test-report.json"))

    rules = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "gomelint.py"),
         "--list-rules"],
        capture_output=True, text=True, env=env, cwd=ROOT,
    )
    assert rules.returncode == 0
    for rule in ("GL101", "GL201", "GL301", "GL401"):
        assert rule in rules.stdout


def test_rule_catalogue_covers_all_families():
    from gome_tpu.analysis import envelope  # noqa: F401 — registers GL2xx
    cat = rule_catalogue()
    for family in ("GL1", "GL2", "GL3", "GL4"):
        assert any(r.startswith(family) for r in cat), family

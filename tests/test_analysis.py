"""gomelint golden fixtures: every rule family fires on seeded-bad input,
stays silent on the idiomatic good twin, honors suppressions — and the
whole tree comes back clean (the same gate CI's analysis job enforces)."""

from __future__ import annotations

import os
import subprocess
import sys
import threading

import jax
import jax.numpy as jnp
import pytest

from gome_tpu.analysis import run_source
from gome_tpu.analysis.core import rule_catalogue, run_paths
from gome_tpu.analysis.envelope import check_engine_envelope, check_jaxpr
from gome_tpu.analysis.runtime import (
    LockDisciplineError,
    OwnedLock,
    instrument,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def rules_of(findings):
    return sorted({f.rule for f in findings})


# --- GL1xx trace-safety ---------------------------------------------------


BAD_TRACE = '''
import jax
import numpy as np

@jax.jit
def f(x, y):
    if x > 0:
        y = y + 1
    v = float(x)
    w = x.item()
    z = np.asarray(y)
    for row in x:
        z = z + 1
    return v + w
'''


def test_trace_safety_flags_bad_fixture():
    findings = run_source(BAD_TRACE)
    assert rules_of(findings) == ["GL101", "GL102", "GL103", "GL104"]
    # the `if` and the `for` are two distinct GL103 sites
    assert sum(f.rule == "GL103" for f in findings) == 2


def test_trace_safety_propagates_through_call_graph():
    src = '''
import jax

def helper(a):
    return int(a)

@jax.jit
def g(x):
    return helper(x)
'''
    findings = run_source(src)
    assert rules_of(findings) == ["GL101"]
    assert "helper" in findings[0].message


def test_trace_safety_scan_body_is_traced():
    src = '''
import jax

@jax.jit
def g(xs):
    def body(carry, x):
        return carry, float(x)
    return jax.lax.scan(body, 0, xs)
'''
    assert rules_of(run_source(src)) == ["GL101"]


GOOD_TRACE = '''
import functools
import jax
import jax.numpy as jnp

@functools.partial(jax.jit, static_argnums=0)
def f(config, x):
    n = x.shape[-1]
    if config.cap > 4:          # static arg: host branching is fine
        x = x + 1
    k = 1
    while k < n:                # shape-derived bound: static under trace
        x = x + jnp.pad(x[..., :-k], [(0, 0)] * (x.ndim - 1) + [(k, 0)])
        k *= 2
    if jnp.dtype(x.dtype).itemsize <= 4:
        x = jnp.minimum(x, 7)
    return x

def host_only(a):
    return float(a.sum())       # not reachable from any jit entry
'''


def test_trace_safety_good_twin_is_clean():
    assert run_source(GOOD_TRACE) == []


def test_trace_safety_identity_test_is_static():
    # `x is None` never concretizes a tracer — branching on it is host-
    # static (the bench's mixed full/dense round-chain relies on this)
    src = '''
import jax

@jax.jit
def f(x, ids):
    if ids is None:
        return x
    return x + 1
'''
    assert run_source(src) == []


def test_trace_safety_namedtuple_unroll_idiom_is_clean():
    # the engine/step.py idiom: iterate a host container of tracers
    src = '''
import jax

@jax.jit
def f(own, entry):
    out = list(own)
    for a in out:
        a = a + 1
    pairs = [a + v for a, v in zip(own, entry)]
    return pairs
'''
    assert run_source(src) == []


def test_trace_safety_line_suppression():
    src = '''
import jax

@jax.jit
def f(x):
    return float(x)  # gomelint: disable=GL101 — fixture-sanctioned
'''
    assert run_source(src) == []
    assert rules_of(run_source(src, keep_suppressed=True)) == ["GL101"]


def test_file_suppression():
    src = '''
# gomelint: disable-file=GL101
import jax

@jax.jit
def f(x):
    return float(x)
'''
    assert run_source(src) == []


# --- GL2xx int32-envelope (jaxpr) ----------------------------------------


def test_envelope_flags_float_and_width_creep():
    x = jnp.zeros((4,), jnp.int32)
    f32 = jax.make_jaxpr(lambda v: v.astype(jnp.float32) * 2.5)(x)
    assert rules_of(check_jaxpr(f32, "int32", "fixture")) == ["GL202"]

    with jax.experimental.enable_x64():
        i64 = jax.make_jaxpr(
            lambda v: v.astype(jnp.int64) + 1
        )(jnp.zeros((4,), jnp.int32))
        f64 = jax.make_jaxpr(lambda v: v * 2.5)(jnp.zeros((4,), jnp.float64))
    assert rules_of(check_jaxpr(i64, "int32", "fixture")) == ["GL203"]
    assert "GL201" in rules_of(check_jaxpr(f64, "int32", "fixture"))


def test_envelope_recurses_into_nested_jaxprs():
    # the creep hides inside a scan body — the walk must find it
    def body(c, x):
        return c, x.astype(jnp.float32) * 0.5

    closed = jax.make_jaxpr(
        lambda xs: jax.lax.scan(body, jnp.int32(0), xs)
    )(jnp.zeros((4,), jnp.int32))
    assert "GL202" in rules_of(check_jaxpr(closed, "int32", "nested"))


def test_envelope_int64_engine_allows_int64():
    with jax.experimental.enable_x64():
        i64 = jax.make_jaxpr(
            lambda v: v + 1
        )(jnp.zeros((4,), jnp.int64))
    assert check_jaxpr(i64, "int64", "fixture") == []


@pytest.mark.parametrize("dtype", ["int32", "int64"])
def test_engine_envelope_clean(dtype):
    """The real engine graphs — step, batch, dense, compaction, scatter,
    pallas-interpret — audited in the dtype's native x64 mode."""
    assert check_engine_envelope(dtype) == []


@pytest.mark.parametrize("dtype", ["int32", "int64"])
def test_envelope_audits_sim_generator(dtype):
    """The sim flow generator rides the same envelope audit: its entry
    must be traced (allow_floats — the Hawkes intensities are f32 by
    design) and `test_engine_envelope_clean` above proves it clean."""
    from gome_tpu.analysis.envelope import traced_entries

    contexts = [rec["context"] for rec in traced_entries(dtype)]
    assert "sim/flow.py:gen_ops" in contexts


def test_envelope_allow_floats_still_flags_strong_f64():
    """The weak-f64 scalar exemption (jax library python literals, e.g.
    inside jax.random under x64) must not exempt STRONG float64 values
    under allow_floats."""
    with jax.experimental.enable_x64():
        strong = jax.make_jaxpr(
            lambda v: v * 2.0
        )(jnp.zeros((4,), jnp.float64))
        weak_scalar = jax.make_jaxpr(
            lambda k: jax.random.uniform(k, (), jnp.float32)
        )(jax.random.PRNGKey(0))
    assert "GL201" in rules_of(
        check_jaxpr(strong, "int64", "fixture", allow_floats=True)
    )
    assert check_jaxpr(
        weak_scalar, "int64", "fixture", allow_floats=True
    ) == []


# --- GL3xx recompile-hazard ----------------------------------------------


BAD_RECOMPILE = '''
import functools
import jax

def make(n):
    @jax.jit
    def f(x):
        return x * n
    return f

def run(x):
    return jax.jit(lambda v: v + 1)(x)

class Engine:
    @jax.jit
    def step(self, x):
        return x

y = jax.jit(lambda x: x, static_argnums=(0,))([1, 2])
'''


def test_recompile_flags_bad_fixture():
    assert rules_of(run_source(BAD_RECOMPILE)) == [
        "GL301", "GL302", "GL303", "GL304",
    ]


GOOD_RECOMPILE = '''
import functools
import jax

@functools.lru_cache(maxsize=256)
def make(n):                     # the engine/frames.py factory idiom
    @jax.jit
    def f(x):
        return x * n
    return f

@jax.jit
def top(x):
    return x

step = functools.partial(jax.jit, static_argnums=0)(top)
'''


def test_recompile_good_twin_is_clean():
    assert run_source(GOOD_RECOMPILE) == []


# --- GL4xx lock-discipline -----------------------------------------------


BAD_LOCKS = '''
import threading

class Batcher:
    def __init__(self):
        self._lock = threading.Lock()
        self._buf = []          # guarded by self._lock
        self.total = 0          # guarded by self._lock
        self._ghost = 0         # guarded by self._missing

    def submit(self, o):
        with self._lock:
            self._buf.append(o)
        self.total += 1

    def peek(self):
        return len(self._buf)

    def escape(self):
        with self._lock:
            return lambda: self._buf.pop()
'''


def test_locks_flags_bad_fixture():
    findings = run_source(BAD_LOCKS)
    assert rules_of(findings) == ["GL401", "GL402", "GL403"]
    lines = {f.rule: f.line for f in findings}
    assert lines["GL401"] == 14  # self.total += 1 off-lock
    # the closure escaping the with-block is an off-lock read
    assert any(f.rule == "GL402" and f.line == 21 for f in findings)


GOOD_LOCKS = '''
import threading

class Batcher:
    def __init__(self):
        self._lock = threading.Lock()
        self._buf = []          # guarded by self._lock
        self.total = 0          # guarded by self._lock

    def submit(self, o):
        with self._lock:
            self._buf.append(o)
            self.total += 1

    def _flush_locked(self):
        batch, self._buf = self._buf, []
        return batch

    # holds: self._lock
    def annotated(self):
        return list(self._buf)

    def flush(self):
        with self._lock:
            return self._flush_locked()
'''


def test_locks_good_twin_is_clean():
    assert run_source(GOOD_LOCKS) == []


def test_locks_condition_counts_as_lock():
    src = '''
import threading

class Q:
    def __init__(self):
        self._cond = threading.Condition()
        self._n = 0  # guarded by self._cond

    def bump(self):
        with self._cond:
            self._n += 1
            self._cond.notify_all()
'''
    assert run_source(src) == []


# --- GL4xx runtime assertion mode ----------------------------------------


class _Thing:
    def __init__(self):
        self._lock = threading.Lock()
        self.counter = 0

    def bump(self):
        with self._lock:
            self.counter += 1

    def racy_bump(self):
        self.counter += 1


def test_runtime_instrument_catches_off_lock_write():
    t = _Thing()
    lock = instrument(t, ("counter",))
    t.bump()  # disciplined write: fine
    assert t.counter == 1
    with pytest.raises(LockDisciplineError):
        t.racy_bump()
    # the violating write did not land
    assert t.counter == 1
    assert isinstance(lock, OwnedLock)


def test_runtime_owned_lock_tracks_owner():
    lock = OwnedLock()
    assert not lock.held_by_me()
    with lock:
        assert lock.held_by_me()
        seen = []
        th = threading.Thread(target=lambda: seen.append(lock.held_by_me()))
        th.start()
        th.join()
        assert seen == [False]
    assert not lock.held_by_me()


def test_runtime_instrument_on_real_batcher():
    """The production FrameBatcher under runtime assertions: a full
    submit/flush cycle never writes its guarded state off-lock."""
    from gome_tpu.bus.memory import MemoryQueue
    from gome_tpu.service.batcher import FrameBatcher
    from gome_tpu.types import Action, Order, OrderType, Side

    b = FrameBatcher(MemoryQueue("doOrder"), max_n=2, max_wait_s=60)
    try:
        instrument(b, ("_buf", "_spill", "_oldest", "_degraded_since"))
        for i in range(4):
            b.submit(Order(
                uuid="u", oid=f"o{i}", symbol="S", side=Side.BUY,
                price=100, volume=1, action=Action.ADD,
                order_type=OrderType.LIMIT,
            ))
        b.flush()
    finally:
        b.close()


# --- GL7xx thread-escape analysis -----------------------------------------


BAD_THREADS = '''
import threading

class Feed:
    def __init__(self):
        self._lock = threading.Lock()
        self.events = 0
        self.state = "idle"
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        self.events += 1

    def set_state(self, s):
        with self._lock:
            self.state = s
'''


def test_threads_flags_bad_fixture():
    findings = run_source(BAD_THREADS)
    assert rules_of(findings) == ["GL701", "GL702"]
    lines = {f.rule: f.line for f in findings}
    assert lines["GL701"] == 12  # self.events += 1, no lock, no contract
    assert lines["GL702"] == 16  # self.state under an undeclared lock
    assert "owns a thread" in findings[0].message


GOOD_THREADS = '''
import threading

class Feed:
    def __init__(self):
        self._lock = threading.Lock()
        self.events = 0  # guarded by self._lock
        self.state = "idle"  # single-writer: the fan-out loop
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        with self._lock:
            self.events += 1
        self.state = "running"
'''


def test_threads_good_twin_is_clean():
    assert run_source(GOOD_THREADS) == []


def test_threads_singleton_escape_root():
    """A module-level ALL-CAPS singleton escapes its class even with no
    thread of its own — every importing thread can reach it."""
    src = '''
class Registry:
    def __init__(self):
        self.installed = False

    def install(self):
        self.installed = True

REGISTRY = Registry()
'''
    findings = run_source(src)
    assert rules_of(findings) == ["GL701"]
    assert "module-level singleton REGISTRY" in findings[0].message
    # lowercase module assignment is NOT an escape root
    assert run_source(src.replace("REGISTRY", "_registry")) == []


def test_threads_transitive_construction_escapes():
    """`self.seq = SeqTracker()` inside an escaped class escapes
    SeqTracker too (its instance rides the shared object)."""
    src = '''
import threading

class Tracker:
    def __init__(self):
        self.seen = 0

    def observe(self):
        self.seen += 1

class Feed:
    def __init__(self):
        self.seq = Tracker()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        self.seq.observe()
'''
    findings = run_source(src)
    assert rules_of(findings) == ["GL701"]
    assert "constructed into escaped Feed" in findings[0].message


def test_threads_gl703_contradictory_contracts():
    src = '''
import threading

class Both:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0  # guarded by self._lock; also  # single-writer: loop
'''
    findings = run_source(src)
    # flagged even though Both never escapes: the annotation is
    # self-contradictory wherever it lives
    assert rules_of(findings) == ["GL703"]


def test_threads_gl704_second_writer_outside_thread():
    src = '''
import threading

class Sampler:
    def __init__(self):
        self.count = 0  # single-writer: the tick thread
        self._thread = threading.Thread(target=self._tick, daemon=True)

    def _tick(self):
        self.count += 1

    def reset(self):
        self.count = 0
'''
    findings = run_source(src)
    assert rules_of(findings) == ["GL704"]
    assert findings[0].line == 13  # reported at the OUTSIDE site
    assert "line 10" in findings[0].message  # with the thread-side witness


def test_threads_gl704_suppression_with_justification():
    src = '''
import threading

class Sampler:
    def __init__(self):
        self.count = 0  # single-writer: the tick thread
        self._thread = threading.Thread(target=self._tick, daemon=True)

    def _tick(self):
        self.count += 1

    def reset(self):
        self.count = 0  # gomelint: disable=GL704 — called before start()
'''
    assert run_source(src) == []


def test_threads_class_level_single_writer_claim():
    """A `# single-writer` on the class line covers every attribute —
    the whole-object claim (SeqTracker, HostSampler idiom)."""
    src = '''
class Tracker:  # single-writer: the observe() caller
    def __init__(self):
        self.seen = 0

    def observe(self):
        self.seen += 1

TRACKER = Tracker()
'''
    assert run_source(src) == []


def test_threads_guarded_contract_hands_off_to_gl4():
    """A declared guard makes GL7xx stand down — and GL4xx take over:
    the same off-lock mutation now fires GL401 instead of GL70x."""
    src = '''
import threading

class Feed:
    def __init__(self):
        self._lock = threading.Lock()
        self.events = 0  # guarded by self._lock
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        self.events += 1
'''
    findings = run_source(src)
    assert rules_of(findings) == ["GL401"]


# --- whole-tree clean runs (the CI gate) ---------------------------------


def test_whole_tree_is_clean():
    findings = run_paths([os.path.join(ROOT, "gome_tpu")])
    assert findings == [], "\n".join(f.format() for f in findings)


def test_cli_exits_zero_on_tree_and_lists_rules():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "gomelint.py"),
         os.path.join(ROOT, "gome_tpu"), "--report",
         os.path.join(ROOT, ".gomelint-test-report.json")],
        capture_output=True, text=True, env=env, cwd=ROOT,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "0 finding(s)" in out.stdout
    import json
    with open(os.path.join(ROOT, ".gomelint-test-report.json")) as fh:
        report = json.load(fh)
    assert report["count"] == 0
    os.unlink(os.path.join(ROOT, ".gomelint-test-report.json"))

    rules = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "gomelint.py"),
         "--list-rules"],
        capture_output=True, text=True, env=env, cwd=ROOT,
    )
    assert rules.returncode == 0
    for rule in ("GL101", "GL201", "GL301", "GL401"):
        assert rule in rules.stdout


def test_rule_catalogue_covers_all_families():
    from gome_tpu.analysis import envelope  # noqa: F401 — registers GL2xx
    cat = rule_catalogue()
    for family in ("GL1", "GL2", "GL3", "GL4", "GL5", "GL6", "GL7",
                   "GL8", "GL9"):
        assert any(r.startswith(family) for r in cat), family


# --- GL5xx transfer-hygiene (hot-path engine) ----------------------------


HOT_PREAMBLE = '''
import jax
import jax.numpy as jnp
import numpy as np

@jax.jit
def device_step(x):
    return x * 2
'''

BAD_TRANSFERS = HOT_PREAMBLE + '''
def hot(engine, orders):  # gomelint: hotpath
    outs = device_step(orders)
    total = outs[0].item()                      # GL501
    host = np.asarray(outs)                     # GL502
    if outs.sum() > 0:                          # GL503
        total += 1
    for i in range(4):
        jax.block_until_ready(outs)             # GL504
        up = jnp.asarray(np.zeros(8))           # GL505
    return total, host, up
'''


def test_transfers_flags_every_rule():
    findings = run_source(BAD_TRANSFERS)
    assert rules_of(findings) == [
        "GL501", "GL502", "GL503", "GL504", "GL505",
    ]


GOOD_TRANSFERS = HOT_PREAMBLE + '''
def hot(engine, orders):  # gomelint: hotpath
    grid = jnp.asarray(np.zeros(8))             # transfer OUTSIDE the loop
    outs = device_step(grid)
    host = np.asarray(jax.device_get(outs))     # the sanctioned fetch
    jax.block_until_ready(outs)                 # drain once, not per-item
    if host.sum() > 0:                          # host-side branch
        return float(host[0])                   # host scalar: no sync
    return 0.0
'''


def test_transfers_good_twin_is_clean():
    assert run_source(GOOD_TRANSFERS) == []


def test_transfers_silent_off_hot_path():
    # identical body, no hotpath annotation: cold code may sync freely
    cold = BAD_TRANSFERS.replace("  # gomelint: hotpath", "")
    assert run_source(cold) == []


def test_transfers_silent_inside_jit():
    # inside traced code the same idioms are GL1xx's domain, not GL5xx's
    src = HOT_PREAMBLE + '''
def hot(x):  # gomelint: hotpath
    return traced(x)

@jax.jit
def traced(x):
    return x.item()
'''
    findings = run_source(src)
    assert not any(f.rule.startswith("GL5") for f in findings)
    assert any(f.rule == "GL102" for f in findings)  # GL1xx still covers it


def test_transfers_suppression():
    src = HOT_PREAMBLE + '''
def hot(x):  # gomelint: hotpath
    outs = device_step(x)
    return outs.item()  # gomelint: disable=GL501 — single drain point
'''
    assert run_source(src) == []


# --- hot-path reachability (analysis.callgraph) --------------------------


def test_hotpath_seed_on_preceding_line():
    src = HOT_PREAMBLE + '''
# gomelint: hotpath
def loop(x):
    outs = device_step(x)
    return float(outs)
'''
    assert rules_of(run_source(src)) == ["GL501"]


def test_hotpath_propagates_through_calls():
    src = HOT_PREAMBLE + '''
def loop(x):  # gomelint: hotpath
    return helper(x)

def helper(x):
    outs = device_step(x)
    return outs.tolist()
'''
    findings = run_source(src)
    assert rules_of(findings) == ["GL501"]
    assert "helper" in findings[0].message


def test_hotpath_callback_edge():
    # a function REFERENCED (not called) from hot code is conservatively hot
    src = HOT_PREAMBLE + '''
import threading

class Consumer:
    def start(self):  # gomelint: hotpath
        t = threading.Thread(target=self._loop)
        t.start()

    def _loop(self):
        outs = device_step(1)
        while outs.any():                        # GL503 via callback edge
            pass
'''
    assert rules_of(run_source(src)) == ["GL503"]


def test_hotpath_closure_edge():
    src = HOT_PREAMBLE + '''
def loop(x):  # gomelint: hotpath
    def inner():
        outs = device_step(x)
        return int(outs)
    return inner()
'''
    assert rules_of(run_source(src)) == ["GL501"]


def test_hotpath_cross_module():
    from gome_tpu.analysis import run_sources

    mods = {
        "svc/consumer.py": HOT_PREAMBLE + '''
from engine import apply

def run_once(x):  # gomelint: hotpath
    return apply(x)
''',
        "engine/impl.py": HOT_PREAMBLE + '''
def apply(x):
    outs = device_step(x)
    return float(outs)                           # GL501, hot via consumer
''',
    }
    findings = run_sources(mods)
    assert [f.rule for f in findings] == ["GL501"]
    assert findings[0].path == "engine/impl.py"


# --- GL6xx buffer-donation ------------------------------------------------


def _avals(*specs):
    return [tuple(s) for s in specs]


def test_donation_gl601_fires_and_donating_twin_is_silent():
    from gome_tpu.analysis.donation import audit_donation

    out = _avals(((8, 128), "int32"), ((8,), "int32"))
    args = [None, _avals(((8, 128), "int32"), ((8,), "int32"))]
    bad = audit_donation("m.py:step", args, static_argnums=(0,),
                         donate_argnums=(), out_avals=out)
    assert [f.rule for f in bad] == ["GL601"]
    good = audit_donation("m.py:step", args, static_argnums=(0,),
                          donate_argnums=(1,), out_avals=out)
    assert good == []


def test_donation_gl601_ignores_immaterial_args():
    from gome_tpu.analysis.donation import audit_donation

    out = _avals(((1024, 64), "int32"), ((8,), "int32"))
    args = [_avals(((8,), "int32"))]  # a lane-id sliver: matching but tiny
    assert audit_donation("m.py:f", args, (), (), out) == []


def test_donation_gl602_fires_on_useless_donation():
    from gome_tpu.analysis.donation import audit_donation

    out = _avals(((8, 128), "int32"))
    bad = audit_donation(
        "m.py:f", [_avals(((4, 4), "float32"))], static_argnums=(),
        donate_argnums=(0,), out_avals=out,
    )
    assert [f.rule for f in bad] == ["GL602"]
    good = audit_donation(
        "m.py:f", [_avals(((8, 128), "int32"))], static_argnums=(),
        donate_argnums=(0,), out_avals=out,
    )
    assert good == []


DONATING_DEF = '''
import functools, jax

@functools.partial(jax.jit, donate_argnums=(0,))
def stepd(state, ops):
    return state + ops, ops
'''


def test_donation_gl603_fires_on_use_after_donation():
    src = DONATING_DEF + '''
def bad_caller(state, ops):
    new, _ = stepd(state, ops)
    return state.sum() + new          # state was donated: deleted
'''
    findings = run_source(src)
    assert [f.rule for f in findings] == ["GL603"]


def test_donation_gl603_rebind_and_return_are_clean():
    src = DONATING_DEF + '''
def rebinding(state, ops):
    state, _ = stepd(state, ops)      # the rebind IS the death
    return state

def tail(state, ops):
    if ops is None:
        return stepd(state, ops)      # returns: nothing after reads state
    return state.sum()
'''
    assert run_source(src) == []


def test_engine_donation_audit_is_clean():
    """The committed donation policy (twins donated, books retained with
    justified suppressions) audits clean — the acceptance gate."""
    from gome_tpu.analysis.core import apply_file_suppressions
    from gome_tpu.analysis.donation import check_engine_donation

    findings = apply_file_suppressions(check_engine_donation("int32"), ROOT)
    assert findings == [], "\n".join(f.format() for f in findings)


# --- baseline fingerprints + ratchet -------------------------------------


def test_fingerprint_survives_line_drift_and_file_moves(tmp_path):
    from gome_tpu.analysis.baseline import fingerprint_findings
    from gome_tpu.analysis.core import Finding

    a = tmp_path / "a.py"
    a.write_text("x = 1\nbad_line = sync()\n")
    f1 = Finding("GL501", str(a), 2, 0, "sync on hot path [hot path: f]")
    [(_, fp1)] = fingerprint_findings([f1])

    # line drift: same content three lines lower
    a.write_text("# pad\n# pad\nx = 1\nbad_line = sync()\n")
    f2 = Finding("GL501", str(a), 4, 0, "sync on hot path [hot path: f]")
    [(_, fp2)] = fingerprint_findings([f2])
    assert fp1 == fp2

    # file move: same content under a new path
    b = tmp_path / "moved" ; b.mkdir()
    bb = b / "renamed.py"
    bb.write_text("bad_line = sync()\n")
    f3 = Finding("GL501", str(bb), 1, 0, "sync on hot path [hot path: f]")
    [(_, fp3)] = fingerprint_findings([f3])
    assert fp1 == fp3

    # changed code on the flagged line => new fingerprint
    bb.write_text("bad_line = other_sync()\n")
    [(_, fp4)] = fingerprint_findings([f3])
    assert fp4 != fp1


def test_fingerprint_disambiguates_identical_findings(tmp_path):
    from gome_tpu.analysis.baseline import fingerprint_findings
    from gome_tpu.analysis.core import Finding

    a = tmp_path / "a.py"
    a.write_text("v = s()\nv = s()\n")
    fs = [Finding("GL501", str(a), 1, 0, "m"),
          Finding("GL501", str(a), 2, 0, "m")]
    fps = [fp for _, fp in fingerprint_findings(fs)]
    assert len(set(fps)) == 2


def test_fingerprint_stable_under_duplicate_line_reorder(tmp_path):
    """Property: permuting identical-text duplicate lines within a file
    (moving whole statement blocks around) leaves the fingerprint
    multiset untouched — the occurrence index is an ordinal among
    interchangeable duplicates, never a position hash."""
    import itertools

    from gome_tpu.analysis.baseline import fingerprint_findings
    from gome_tpu.analysis.core import Finding

    blocks = ["v = s()", "w = t()", "v = s()", "u = r()", "v = s()"]
    a = tmp_path / "a.py"

    def fps_for(order):
        lines = [blocks[i] for i in order]
        a.write_text("\n".join(lines) + "\n")
        fs = [Finding("GL501", str(a), ln + 1, 0, "m")
              for ln, text in enumerate(lines) if text == "v = s()"]
        return sorted(fp for _, fp in fingerprint_findings(fs))

    base = fps_for(range(5))
    assert len(set(base)) == 3  # three duplicates, three distinct indices
    for order in itertools.permutations(range(5)):
        assert fps_for(order) == base, order


def test_fingerprint_occurrence_index_is_file_scoped(tmp_path):
    """Renaming one module must not renumber another module's duplicate-
    key findings ('moving a module keeps its findings baselined'). The
    pre-2.1.0 counter spanned files in path-sort order, so a rename
    upstream churned fingerprints in untouched files."""
    from gome_tpu.analysis.baseline import fingerprint_findings
    from gome_tpu.analysis.core import Finding

    a = tmp_path / "a.py"
    b = tmp_path / "b.py"
    a.write_text("v = s()\n")
    b.write_text("v = s()\n")
    fb = Finding("GL501", str(b), 1, 0, "m")
    [_, (_, fp_b)] = fingerprint_findings(
        [Finding("GL501", str(a), 1, 0, "m"), fb])

    # rename a.py so it sorts AFTER b.py: b's fingerprint must not move
    z = tmp_path / "z.py"
    a.rename(z)
    [(_, fp_b2), (_, fp_z)] = fingerprint_findings(
        [fb, Finding("GL501", str(z), 1, 0, "m")])
    assert fp_b2 == fp_b
    # identical cross-file keys share one baseline entry by design:
    # either instance matches it, and neither can churn the other
    assert fp_z == fp_b2


def test_baseline_roundtrip_and_partition(tmp_path):
    from gome_tpu.analysis.baseline import (
        fingerprint_findings, load_baseline, partition, save_baseline,
    )
    from gome_tpu.analysis.core import Finding

    a = tmp_path / "a.py"
    a.write_text("old = sync()\n")
    old = Finding("GL501", str(a), 1, 0, "old debt")
    fps = fingerprint_findings([old])
    path = tmp_path / "baseline.json"
    save_baseline(str(path), fps)
    base = load_baseline(str(path))
    assert len(base) == 1

    a.write_text("old = sync()\nnew = sync2()\n")
    new = Finding("GL502", str(a), 2, 0, "new debt")
    both = fingerprint_findings([old, new])
    fresh, known = partition(both, base)
    assert [f.rule for f, _ in known] == ["GL501"]
    assert [f.rule for f, _ in fresh] == ["GL502"]


# --- SARIF 2.1.0 ----------------------------------------------------------


def test_sarif_output_validates():
    from gome_tpu.analysis.baseline import fingerprint_findings
    from gome_tpu.analysis.core import Finding
    from gome_tpu.analysis.sarif import to_sarif, validate_sarif

    fs = [
        Finding("GL501", "gome_tpu/x.py", 10, 4, "a sync"),
        Finding("GL601", "gome_tpu/y.py", 1, 0, "a double-buffer"),
    ]
    fps = fingerprint_findings(fs)
    doc = to_sarif(fps, baselined={fps[1][1]})
    assert validate_sarif(doc) == []
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "gomelint"
    res = run["results"]
    assert res[0]["level"] == "error" and res[0]["baselineState"] == "new"
    assert res[1]["level"] == "warning"
    assert res[1]["suppressions"][0]["kind"] == "external"
    assert res[0]["locations"][0]["physicalLocation"]["region"][
        "startLine"] == 10
    # the SARIF fingerprint IS the baseline fingerprint
    assert res[0]["partialFingerprints"]["gomelint/v1"] == fps[0][1]


def test_sarif_validator_rejects_malformed():
    from gome_tpu.analysis.sarif import validate_sarif

    assert validate_sarif({"version": "2.0.0", "runs": []})
    bad_run = {
        "version": "2.1.0",
        "runs": [{"tool": {"driver": {"name": ""}},
                  "results": [{"message": {}, "level": "fatal",
                               "locations": [{"physicalLocation": {
                                   "region": {"startLine": 0}}}]}]}],
    }
    errs = validate_sarif(bad_run)
    assert any("level" in e for e in errs)
    assert any("startLine" in e for e in errs)
    assert any("message" in e for e in errs)


def test_sarif_matches_jsonschema_expectations():
    jsonschema = pytest.importorskip("jsonschema")
    # a hand-reduced slice of the official 2.1.0 schema: the properties
    # gomelint emits, with the spec's required/enum constraints
    schema = {
        "type": "object",
        "required": ["version", "runs"],
        "properties": {
            "version": {"enum": ["2.1.0"]},
            "runs": {"type": "array", "items": {
                "type": "object", "required": ["tool"],
                "properties": {
                    "tool": {"type": "object", "required": ["driver"],
                             "properties": {"driver": {
                                 "type": "object", "required": ["name"]}}},
                    "results": {"type": "array", "items": {
                        "type": "object", "required": ["message"],
                        "properties": {
                            "level": {"enum": ["none", "note", "warning",
                                               "error"]},
                            "message": {"type": "object",
                                        "required": ["text"]},
                        }}},
                }}},
        },
    }
    from gome_tpu.analysis.baseline import fingerprint_findings
    from gome_tpu.analysis.core import Finding
    from gome_tpu.analysis.sarif import to_sarif

    doc = to_sarif(fingerprint_findings(
        [Finding("GL000", "x.py", 1, 0, "m")]))
    jsonschema.validate(doc, schema)


# --- whole-tree assertions for the new families ---------------------------


def test_whole_tree_clean_for_transfer_and_donation_families():
    """Satellite guarantee: the annotated hot paths (consumer, batcher,
    engine driver, pipeline) carry no GL5xx host-sync and no GL603
    use-after-donation today — regressions fail here with the exact
    file:line."""
    findings = [
        f for f in run_paths([os.path.join(ROOT, "gome_tpu"),
                              os.path.join(ROOT, "scripts"),
                              os.path.join(ROOT, "bench.py")])
        if f.rule.startswith(("GL5", "GL6"))
    ]
    assert findings == [], "\n".join(f.format() for f in findings)


def test_hot_path_seeds_reach_the_engine():
    """The hotpath annotations must actually cover the order path: if a
    refactor renames a seed or breaks an edge, the GL5xx family would go
    silently blind — this pins the reachability of the core driver."""
    import glob

    from gome_tpu.analysis import callgraph
    from gome_tpu.analysis.core import Project, SourceModule

    mods = []
    for p in sorted(glob.glob(os.path.join(ROOT, "gome_tpu", "**", "*.py"),
                              recursive=True)):
        with open(p, encoding="utf-8") as fh:
            mods.append(SourceModule(p, fh.read()))
    graph = callgraph.build(Project(mods))
    hot = {fn.name for fn in graph.hot_functions()}
    for must in ("run_once", "_run_exact", "submit_frame", "resolve_frame",
                 "_pack_grid_vectorized", "feed"):
        assert must in hot, f"{must} fell off the hot path"


# --- CLI v2: baseline ratchet, SARIF, --version ---------------------------


def _cli(args, cwd=ROOT):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "gomelint.py"),
         *args],
        capture_output=True, text=True, env=env, cwd=cwd,
    )


def test_cli_version():
    out = _cli(["--version"])
    assert out.returncode == 0
    assert "gomelint 2." in out.stdout


def test_cli_baseline_ratchet_flow(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(HOT_PREAMBLE + '''
def hot(x):  # gomelint: hotpath
    outs = device_step(x)
    return float(outs)
''')
    base = tmp_path / "baseline.json"

    # 1. new finding, no baseline: fail
    r = _cli([str(bad), "--baseline", str(base)])
    assert r.returncode == 1 and "GL501" in r.stdout

    # 2. accept the debt: --update-baseline exits 0 and writes the file
    r = _cli([str(bad), "--baseline", str(base), "--update-baseline"])
    assert r.returncode == 0 and base.exists()

    # 3. ratchet: the same finding is baselined, exit 0
    r = _cli([str(bad), "--baseline", str(base)])
    assert r.returncode == 0 and "baselined" in r.stdout

    # 4. line drift above the finding: fingerprint stable, still 0
    bad.write_text("# moved\n# down\n" + bad.read_text())
    r = _cli([str(bad), "--baseline", str(base)])
    assert r.returncode == 0

    # 5. NEW debt fails even with the old one baselined
    bad.write_text(bad.read_text() + '''
def hot2(x):  # gomelint: hotpath
    outs = device_step(x)
    return outs.item()
''')
    r = _cli([str(bad), "--baseline", str(base)])
    assert r.returncode == 1 and "1 new" in r.stdout

    # 6. --no-baseline: everything fails again
    r = _cli([str(bad), "--no-baseline"])
    assert r.returncode == 1


def test_cli_sarif_format(tmp_path):
    import json as _json

    from gome_tpu.analysis.sarif import validate_sarif

    bad = tmp_path / "bad.py"
    bad.write_text(HOT_PREAMBLE + '''
def hot(x):  # gomelint: hotpath
    return float(device_step(x))
''')
    sarif_path = tmp_path / "out.sarif"
    r = _cli([str(bad), "--no-baseline", "--format", "sarif",
              "--sarif", str(sarif_path)])
    assert r.returncode == 1
    doc = _json.loads(r.stdout)
    assert validate_sarif(doc) == []
    on_disk = _json.loads(sarif_path.read_text())
    assert on_disk["runs"][0]["results"][0]["ruleId"] == "GL501"


def test_committed_baseline_matches_tree():
    """The acceptance command: the full run (AST families) against the
    COMMITTED baseline exits 0 — new debt anywhere fails this test before
    it fails CI."""
    r = _cli(["gome_tpu", "scripts", "bench.py"])
    assert r.returncode == 0, r.stdout + r.stderr


# --- GL8xx sharding & partition consistency -------------------------------


GL801_BAD = '''
import jax
from jax.sharding import PartitionSpec as P

step_a = jax.jit(impl_a, in_shardings=(P('sym'),), out_shardings=(P('sym'),))
step_b = jax.jit(impl_b, in_shardings=(P(None),), out_shardings=(P(None),))

def frame(x):
    y = step_a(x)
    return step_b(y)                            # GL801: P('sym') -> P(None)
'''

GL801_GOOD = GL801_BAD.replace("P(None)", "P('sym')")


def test_spec_mismatch_between_chained_entries():
    findings = run_source(GL801_BAD, select={"GL8"})
    assert rules_of(findings) == ["GL801"]
    assert "P('sym')" in findings[0].message
    assert "P(None)" in findings[0].message
    assert run_source(GL801_GOOD, select={"GL8"}) == []


GL801_FACTORY_BAD = '''
import jax
from jax.sharding import PartitionSpec as P

def make_step(impl, mesh):
    sharding = P('sym')
    return jax.jit(impl, in_shardings=(sharding, sharding),
                   out_shardings=(sharding, P(None)))

def frame(impl, mesh, books, ops):
    stepper = make_step(impl, mesh)
    books, outs = stepper(books, ops)
    books2, outs2 = stepper(books, outs)        # GL801 on arg #1
    return books2, outs2
'''

GL801_FACTORY_GOOD = GL801_FACTORY_BAD.replace(
    "(sharding, P(None))", "(sharding, sharding)")


def test_spec_mismatch_through_factory_alias():
    """The parallel/mesh.py idiom: a factory RETURNS the jitted entry,
    callers alias it (`stepper = sharded_dense_step(...)`). Spec flow
    must follow the alias and the tuple unpack; the alias-substituted
    canonical form makes `sharding` and `P('sym')` compare equal."""
    findings = run_source(GL801_FACTORY_BAD, select={"GL8"})
    assert rules_of(findings) == ["GL801"]
    assert "argument #1" in findings[0].message
    assert run_source(GL801_FACTORY_GOOD, select={"GL8"}) == []


def test_factory_call_itself_is_not_a_dispatch():
    """Calling the factory only CONSTRUCTS the entry — the construction
    call must not be treated as a sharded dispatch of its arguments."""
    src = '''
import jax
from jax.sharding import PartitionSpec as P

def make_step(impl):
    return jax.jit(impl, in_shardings=(P('sym'),), out_shardings=(P('sym'),))

def setup(impl_host):
    return make_step(impl_host)
'''
    assert run_source(src, select={"GL8"}) == []


GL802_BAD = '''
import numpy as np

class Eng:
    def geometry(self, live):
        d = self.mesh.size
        local = self.n_slots // d
        counts = np.bincount(live // local, minlength=d)
        r_s = max(8, int(counts.max()))         # GL802 anchors here
        if r_s * d >= self.n_slots:
            return self.n_slots
        n_rows = r_s * d
        return n_rows
'''

GL802_GOOD = '''
import numpy as np

class Eng:
    def geometry(self, live, shard_id):
        d = self.mesh.size
        local = self.n_slots // d
        counts = np.bincount(live // local, minlength=d)
        r_s = max(8, int(counts[shard_id]))     # per-shard, no reduction
        return r_s * d
'''


def test_global_max_padding_flagged_once_at_derivation():
    """One finding per derived block var, anchored at the derivation (the
    line a fix rewrites), even when the product appears on several
    lines; the telemetry-style inline `counts.max() * d` expression that
    never lands in a variable is not the padding decision and must not
    flag."""
    findings = run_source(GL802_BAD, select={"GL8"})
    assert rules_of(findings) == ["GL802"]
    assert len(findings) == 1
    assert findings[0].line == 9  # r_s = max(8, int(counts.max()))
    assert "MULTICHIP_r06" in findings[0].message
    assert run_source(GL802_GOOD, select={"GL8"}) == []


def test_global_max_telemetry_expression_not_flagged():
    src = '''
import numpy as np

def observe(skew, live, mesh):
    d = mesh.size
    counts = np.bincount(live, minlength=d)
    skew.observe(int(counts.max()) * d / len(live))
'''
    assert run_source(src, select={"GL8"}) == []


GL803_BAD = '''
from zlib import crc32

def route(symbol, n):
    return crc32(symbol.encode()) % n           # GL803
'''

GL803_GOOD = '''
from gome_tpu.fleet.router import partition_of

def route(symbol, n):
    return partition_of(symbol, n)
'''


def test_ad_hoc_partition_hash_flagged():
    findings = run_source(GL803_BAD, select={"GL8"})
    assert rules_of(findings) == ["GL803"]
    assert "partition_of" in findings[0].message
    assert run_source(GL803_GOOD, select={"GL8"}) == []


def test_blessed_router_modules_may_hash():
    """The one-policy rule needs an implementation somewhere: the blessed
    placement helpers themselves are exempt, everything else routes
    through them."""
    for blessed in ("gome_tpu/fleet/router.py", "gome_tpu/parallel/router.py"):
        assert run_source(GL803_BAD, path=blessed, select={"GL8"}) == []
    assert rules_of(run_source(GL803_BAD, path="gome_tpu/fleet/drill.py",
                               select={"GL8"})) == ["GL803"]


GL804_BAD = '''
import jax
from jax.sharding import PartitionSpec as P

step = jax.jit(impl, donate_argnums=(0,),
               in_shardings=(P('sym'), P(None)), out_shardings=(P(None),))
'''

GL804_GOOD = GL804_BAD.replace("out_shardings=(P(None),)",
                               "out_shardings=(P('sym'),)")


def test_donation_across_sharding_boundary():
    findings = run_source(GL804_BAD, select={"GL8"})
    assert rules_of(findings) == ["GL804"]
    assert "donated argument #0" in findings[0].message
    assert run_source(GL804_GOOD, select={"GL8"}) == []


def test_donation_without_shardings_is_gl6_territory():
    """Plain donation with no spec surface stays GL6xx's audit — GL804
    only speaks when both donation AND shardings are declared."""
    src = '''
import jax

step = jax.jit(impl, donate_argnums=(0,))
'''
    assert run_source(src, select={"GL8"}) == []


GL805_BAD = '''
import jax
import numpy as np

def frame(mesh, books):
    books = jax.device_put(books)
    host = np.asarray(jax.device_get(books))
    return shard_batch(mesh, host)              # GL805
'''

GL805_GOOD = '''
import jax
import numpy as np

def frame(mesh, books):
    books = jax.device_put(books)
    return shard_batch(mesh, books)             # on-device reshard: fine
'''


def test_host_roundtrip_into_mesh_flagged():
    findings = run_source(GL805_BAD, select={"GL8"})
    assert rules_of(findings) == ["GL805"]
    assert "round trip" in findings[0].message
    assert run_source(GL805_GOOD, select={"GL8"}) == []


def test_host_source_upload_is_clean():
    """Placing genuinely host-born data (params, numpy construction) on
    the mesh is the sanctioned upload path, not a round trip."""
    src = '''
import numpy as np

def place(mesh, lane_ids):
    ids_np = np.asarray(lane_ids)               # param: host-born
    return shard_batch(mesh, ids_np)
'''
    assert run_source(src, select={"GL8"}) == []


def test_host_roundtrip_through_factory_entry():
    src = '''
import jax
import numpy as np
from jax.sharding import PartitionSpec as P

def make_step(impl):
    return jax.jit(impl, in_shardings=(P('sym'),), out_shardings=(P('sym'),))

def frame(impl, books):
    stepper = make_step(impl)
    books = stepper(books)
    host = np.asarray(books)
    return stepper(host)                        # GL805
'''
    assert rules_of(run_source(src, select={"GL8"})) == ["GL805"]


def test_gl8_suppression_and_select_compose():
    suppressed = GL803_BAD.replace(
        "% n           # GL803",
        "% n  # gomelint: disable=GL803 — fixture")
    assert run_source(suppressed, select={"GL8"}) == []
    # family select keeps GL8 out of a GL5-only run and vice versa
    assert run_source(GL803_BAD, select={"GL5"}) == []


# --- GL806 sharding manifest ----------------------------------------------


def test_manifest_extract_is_deterministic_and_complete():
    from gome_tpu.analysis.sharding import extract_manifest

    m = extract_manifest("int32")
    assert m["dtype"] == "int32"
    e = m["entries"]
    batch = e["engine/batch.py:batch_step"]
    assert batch["kind"] == "engine_entry"
    assert batch["classification"] == "sym_sharded"
    assert batch["donation"]["batch_step_donating"] == [2]
    assert all(a.endswith(":int32") for a in batch["in_avals"])
    dense = e["parallel/mesh.py:sharded_dense_step"]
    assert dense["kind"] == "mesh_entry"
    assert dense["mesh_axes"] == ["sym"]
    assert dense["in_shardings"] == ["symbol_sharding(mesh)"] * 3
    assert dense["shard_map_in_specs"] == ["P('sym')"] * 3
    assert dense["shard_map_out_specs"] == ["P('sym')"] * 2
    assert dense["classification"] == "shard_local"
    # the best-effort pallas record must stay OUT: its presence varies
    # by environment and the manifest must diff clean across machines
    assert not any("pallas" in ctx for ctx in e)
    assert extract_manifest("int32") == m


def test_committed_manifest_matches_tree():
    """The GL806 acceptance pin: the committed shard_manifest.json equals
    the extracted spec surface — spec drift fails here (and in CI) until
    --update-manifest is run and the diff reviewed."""
    from gome_tpu.analysis.sharding import check_sharding_manifest

    findings = check_sharding_manifest("int32")
    assert findings == [], "\n".join(f.format() for f in findings)


def test_manifest_missing_drift_and_dtype_gate(tmp_path):
    from gome_tpu.analysis.sharding import (
        check_sharding_manifest,
        extract_manifest,
        load_manifest,
        save_manifest,
    )

    path = str(tmp_path / "manifest.json")
    missing = check_sharding_manifest("int32", path)
    assert rules_of(missing) == ["GL806"]
    assert "no committed sharding manifest" in missing[0].message

    save_manifest(path, extract_manifest("int32"))
    assert check_sharding_manifest("int32", path) == []

    doc = load_manifest(path)
    doc["entries"]["parallel/mesh.py:sharded_dense_step"][
        "shard_map_out_specs"] = ["P(None)", "P(None)"]
    save_manifest(path, doc)
    drift = check_sharding_manifest("int32", path)
    assert rules_of(drift) == ["GL806"]
    assert "sharded_dense_step" in drift[0].message
    assert "shard_map_out_specs" in drift[0].message

    doc["entries"].pop("engine/batch.py:batch_step")
    doc["entries"]["engine/batch.py:imaginary"] = {"kind": "engine_entry"}
    save_manifest(path, doc)
    msgs = [f.message for f in check_sharding_manifest("int32", path)]
    assert any("batch_step: entry is new" in m for m in msgs)
    assert any("imaginary: entry vanished" in m for m in msgs)

    # the manifest pins the CI dtype: audits of the OTHER dtype skip it
    assert check_sharding_manifest("int64", path) == []


def test_cli_update_manifest_requires_jaxpr():
    r = _cli(["gome_tpu", "--update-manifest"])
    assert r.returncode == 2
    assert "--jaxpr" in r.stderr


def test_cli_manifest_flow(tmp_path):
    """CLI end-to-end: a missing manifest fails the GL8 gate with GL806;
    --update-manifest writes the spec surface and exits 0 (the ratchet's
    create/repair action, symmetric with --update-baseline)."""
    path = str(tmp_path / "manifest.json")
    r = _cli(["gome_tpu/parallel", "--jaxpr", "--select", "GL8",
              "--manifest", path, "--no-baseline"])
    assert r.returncode == 1, r.stdout + r.stderr
    assert "GL806" in r.stdout

    r = _cli(["gome_tpu/parallel", "--jaxpr", "--select", "GL8",
              "--manifest", path, "--update-manifest"])
    assert r.returncode == 0, r.stdout + r.stderr
    import json as _json
    doc = _json.loads(open(path).read())
    assert "parallel/mesh.py:sharded_dense_step" in doc["entries"]
    assert doc["tool"].startswith("gomelint 2.")


def test_whole_tree_clean_for_sharding_family():
    """Satellite guarantee for GL8xx: the mesh tier, the engine geometry,
    and every script dispatch either satisfy the sharding rules or carry
    a cited suppression (the GL802 global-max block in _grid_geometry is
    owned by ROADMAP item 2) — regressions fail here with file:line."""
    findings = [
        f for f in run_paths([os.path.join(ROOT, "gome_tpu"),
                              os.path.join(ROOT, "scripts"),
                              os.path.join(ROOT, "bench.py")])
        if f.rule.startswith("GL8")
    ]
    assert findings == [], "\n".join(f.format() for f in findings)


def test_mesh_overhead_keeps_lane_ids_resident():
    """Regression for the GL805 the tree sweep found: part_a built its
    mesh lane ids by np.asarray(device_array) — a device->host->device
    round trip on the setup path. The fix shards the host-born numpy
    original; the file must stay GL805-clean."""
    path = os.path.join(ROOT, "scripts", "mesh_overhead.py")
    findings = [f for f in run_paths([path]) if f.rule == "GL805"]
    assert findings == [], "\n".join(f.format() for f in findings)
    # and the scan is not blind there: the old shape still fires
    bad = '''
import jax
import numpy as np

def part(mesh, R):
    lane_ids = jax.device_put(np.arange(R, dtype=np.int32))
    return shard_batch(mesh, np.asarray(lane_ids, np.int32))
'''
    assert rules_of(run_source(bad, select={"GL8"})) == ["GL805"]


# --- GL9xx compile surface -------------------------------------------------


SURFACE_OK = '''
import jax
from functools import lru_cache

# gomesurface: quantizer
def _pow2(n):
    return 1 << max(n - 1, 0).bit_length()

# gomesurface: quantizer
def _pow4(n):
    v = 1
    while v < n:
        v *= 4
    return v

COMBO_FIELDS = ("n_rows", "cap_g")

@lru_cache(maxsize=None)
def make_step(n_rows, cap_g):
    @jax.jit
    def step(x):
        return x[:n_rows, :cap_g]
    return step

# gomesurface: combo(build)
def submit(eng, ops, counts):  # gomelint: hotpath
    rows = _pow2(len(ops))
    cap = _pow2(counts.max())
    combo = (rows, cap)
    eng.record_combo(combo)
    return make_step(rows, cap)(ops)

# gomesurface: combo(replay), precompile
def boot_replay(eng):
    for combo in eng.combos():
        (n_rows, cap_g) = combo
        make_step(n_rows, cap_g)

# gomesurface: combo(persist)
def manifest(eng):
    return {"combos": sorted(eng.combos())}
'''


def _gl9(src, **kw):
    return run_source(src, select={"GL9"}, **kw)


def test_surface_complete_fixture_is_clean():
    """The whole contract composed: quantized build, agreeing replay
    unpack, persist through combos(), precompile covering the factory —
    every GL901-GL904 check stays silent at once."""
    assert _gl9(SURFACE_OK) == []


def test_gl901_raw_reduction_to_combo_and_factory():
    bad = SURFACE_OK.replace("_pow2(len(ops))", "len(ops)")
    findings = _gl9(bad)
    assert rules_of(findings) == ["GL901"]
    msgs = "\n".join(f.message for f in findings)
    assert "combo dimension 'n_rows'" in msgs
    assert "shape argument #0 of jit factory make_step()" in msgs


def test_gl901_attribute_reduction_is_a_source():
    bad = SURFACE_OK.replace("_pow2(counts.max())", "counts.max()")
    findings = _gl9(bad)
    assert rules_of(findings) == ["GL901"]
    assert any("combo dimension 'cap_g'" in f.message for f in findings)


def test_gl901_quantizer_alias_launders():
    """`bucket = _pow2 if first else _pow4; bucket(len(ops))` — an alias
    of a quantizer is a quantizer (the batch.py first-grow idiom)."""
    src = SURFACE_OK + '''
def resize(eng, ops, first):  # gomelint: hotpath
    bucket = _pow2 if first else _pow4
    m = bucket(len(ops))
    return make_step(m, 8)(ops)
'''
    assert _gl9(src) == []
    # and the scan is not blind: drop the laundering call, it fires
    raw = src.replace("bucket(len(ops))", "len(ops)")
    assert rules_of(_gl9(raw)) == ["GL901"]


def test_gl902_build_arity_drift():
    bad = SURFACE_OK.replace("combo = (rows, cap)", "combo = (rows, cap, 7)")
    findings = _gl9(bad)
    assert rules_of(findings) == ["GL902"]
    assert "3 element(s)" in findings[0].message
    assert "COMBO_FIELDS declares 2" in findings[0].message


def test_gl902_build_order_drift_via_provenance():
    bad = SURFACE_OK.replace("combo = (rows, cap)", "combo = (cap, rows)")
    findings = _gl9(bad)
    assert rules_of(findings) == ["GL902"]
    assert all("drifted" in f.message for f in findings)


def test_gl902_replay_unpack_drift_and_oob_subscript():
    bad = SURFACE_OK.replace("(n_rows, cap_g) = combo",
                             "(cap_g, n_rows) = combo")
    findings = _gl9(bad)
    assert rules_of(findings) == ["GL902"]
    assert "replay unpack binds (cap_g, n_rows)" in findings[0].message

    oob = SURFACE_OK.replace("        make_step(n_rows, cap_g)",
                             "        make_step(n_rows, combo[5])")
    findings = _gl9(oob)
    assert rules_of(findings) == ["GL902"]
    assert "combo[5] is outside the 2-field combo layout" \
        in findings[0].message


def test_gl902_persist_must_read_the_combo_set():
    bad = SURFACE_OK.replace('{"combos": sorted(eng.combos())}', "{}")
    findings = _gl9(bad)
    assert rules_of(findings) == ["GL902"]
    assert "never reads the recorded combo set" in findings[0].message


def test_gl902_missing_role_annotation():
    bad = SURFACE_OK.replace("# gomesurface: combo(persist)\n", "")
    findings = _gl9(bad)
    assert rules_of(findings) == ["GL902"]
    assert "combo(persist)" in findings[0].message


def test_gl902_seen_combos_reach_through_regression():
    """Regression pin for the sweep's chokepoint refactor: the
    obs/timeline.py rollup used to read `len(eng._seen_combos)` directly;
    it now goes through combo_count(). The OLD shape must keep firing
    anywhere outside the chokepoint's home module..."""
    reach = '''
def rollup(eng):
    return {"combos": len(eng._seen_combos)}
'''
    findings = _gl9(reach, path="obs/timeline.py")
    assert rules_of(findings) == ["GL902"]
    assert "record_combo" in findings[0].message
    # ...while engine/batch.py, the set's single owner, is exempt.
    assert _gl9(reach, path="engine/batch.py") == []


def test_gl903_uncovered_hot_entry():
    bad = SURFACE_OK.replace("# gomesurface: combo(replay), precompile",
                             "# gomesurface: combo(replay)")
    findings = _gl9(bad)
    assert rules_of(findings) == ["GL903"]
    # both the factory and its jitted inner are now unreachable at boot
    msgs = "\n".join(f.message for f in findings)
    assert "make_step" in msgs
    assert "precompile" in msgs


def test_gl903_silent_without_a_replay_system():
    """A project with no precompile annotation AND no COMBO_FIELDS has
    no replay system to register into — GL903 would be unactionable."""
    src = '''
import jax

@jax.jit
def step(x):
    return x

def hot(x):  # gomelint: hotpath
    return step(x)
'''
    assert _gl9(src) == []


def test_gl904_hot_path_resets():
    bad = '''
def drain(eng):  # gomelint: hotpath
    reap(eng)

def reap(eng):
    eng.reset_geometry_floors()
    eng._seen_combos.clear()
'''
    # path inside the chokepoint module isolates GL904 from the GL902
    # reach-through rule
    findings = _gl9(bad, path="engine/batch.py")
    assert rules_of(findings) == ["GL904"]
    msgs = "\n".join(f.message for f in findings)
    assert "reset_geometry_floors()" in msgs
    assert "_seen_combos.clear()" in msgs
    # the same resets in maintenance code nothing hot reaches are fine
    good = bad.replace("  # gomelint: hotpath", "")
    assert _gl9(good, path="engine/batch.py") == []


def test_gl9_suppression_composes():
    src = '''
def drain(eng):  # gomelint: hotpath
    eng.reset_geometry_floors()  # gomelint: disable=GL904 — boot drain
'''
    assert _gl9(src, path="engine/batch.py") == []


def test_whole_tree_clean_for_surface_family():
    """Satellite guarantee for GL9xx: every engine quantizer is
    annotated, the combo sites agree with COMBO_FIELDS, all hot jit
    entries replay from precompile_combos, and no reset is hot-reachable
    (the sim/replay.py record tool carries the one cited suppression)."""
    findings = [
        f for f in run_paths([os.path.join(ROOT, "gome_tpu"),
                              os.path.join(ROOT, "scripts"),
                              os.path.join(ROOT, "bench.py")])
        if f.rule.startswith("GL9")
    ]
    assert findings == [], "\n".join(f.format() for f in findings)


# --- GL905 combo universe --------------------------------------------------


def test_universe_extract_is_deterministic_and_total():
    from gome_tpu.analysis.surface import extract_universe
    from gome_tpu.engine.frames import COMBO_FIELDS

    u = extract_universe()
    assert u["fields"] == list(COMBO_FIELDS)
    assert list(u["dimensions"]) == list(COMBO_FIELDS)
    for name, dim in u["dimensions"].items():
        # no unbounded holes: every dimension has a real generator
        assert dim["cardinality"] >= 1, name
        assert "UNKNOWN" not in dim["generator"], name
    assert u["cardinality_log2_bound"] > 0
    assert u["bounds"]["max_frame_ops"] == 1 << 20
    assert extract_universe() == u


def test_committed_universe_matches_tree():
    """The GL905 acceptance pin: the committed combo_universe.json equals
    the extracted bound — a config-bound or quantizer change fails here
    (and in CI) until --update-universe is run and the diff reviewed."""
    from gome_tpu.analysis.surface import check_universe

    findings = check_universe()
    assert findings == [], "\n".join(f.format() for f in findings)


def test_universe_missing_drift_and_dimension_churn(tmp_path):
    from gome_tpu.analysis.surface import (
        check_universe,
        extract_universe,
        load_universe,
        save_universe,
    )

    path = str(tmp_path / "universe.json")
    missing = check_universe(path)
    assert rules_of(missing) == ["GL905"]
    assert "no committed combo universe" in missing[0].message

    save_universe(path, extract_universe())
    assert check_universe(path) == []

    doc = load_universe(path)
    doc["dimensions"]["t_grid"]["max"] = 2048
    save_universe(path, doc)
    drift = check_universe(path)
    assert rules_of(drift) == ["GL905"]
    assert "t_grid" in drift[0].message and "max" in drift[0].message

    doc["dimensions"]["t_grid"]["max"] = 1024
    doc["bounds"]["max_t"] = 64
    doc["dimensions"].pop("m_pad")
    doc["dimensions"]["imaginary"] = {"kind": "enum", "values": [1]}
    save_universe(path, doc)
    msgs = [f.message for f in check_universe(path)]
    assert any("bounds changed" in m for m in msgs)
    assert any("m_pad: dimension is new" in m for m in msgs)
    assert any("imaginary: dimension vanished" in m for m in msgs)


def test_cli_update_universe_requires_jaxpr():
    r = _cli(["gome_tpu", "--update-universe"])
    assert r.returncode == 2
    assert "--jaxpr" in r.stderr


def test_cli_universe_flow(tmp_path):
    """CLI end-to-end: a missing universe fails the GL9 gate with GL905;
    --update-universe writes the per-dimension bound and exits 0 (the
    ratchet's create/repair action, symmetric with --update-manifest)."""
    path = str(tmp_path / "universe.json")
    r = _cli(["gome_tpu/analysis", "--jaxpr", "--select", "GL9",
              "--universe", path, "--no-baseline"])
    assert r.returncode == 1, r.stdout + r.stderr
    assert "GL905" in r.stdout

    r = _cli(["gome_tpu/analysis", "--jaxpr", "--select", "GL9",
              "--universe", path, "--update-universe"])
    assert r.returncode == 0, r.stdout + r.stderr
    import json as _json
    doc = _json.loads(open(path).read())
    assert len(doc["dimensions"]) == 9
    assert doc["tool"].startswith("gomelint 2.")

    r = _cli(["gome_tpu/analysis", "--jaxpr", "--select", "GL9",
              "--universe", path, "--no-baseline"])
    assert r.returncode == 0, r.stdout + r.stderr


# --- GL906 runtime escape --------------------------------------------------


#: A dispatch combo from the committed universe's interior (engine
#: defaults: 8 rows, full 8-step grid, cap class 64, dense, the floors).
_COMBO_IN = (8, 8, 64, True, 64, 4, 64, 64, 8)


def test_combo_escapes_against_committed_universe():
    from gome_tpu.analysis.surface import combo_escapes, load_universe

    u = load_universe(os.path.join(ROOT, "gome_tpu", "analysis",
                                   "combo_universe.json"))
    assert u is not None
    assert combo_escapes(_COMBO_IN, u) == []

    off_lattice = (8, 48) + _COMBO_IN[2:]
    [why] = combo_escapes(off_lattice, u)
    assert "t_grid=48" in why and "pow2" in why

    # m_pad is pow4: a pow2 value off the pow4 lattice escapes
    not_pow4 = _COMBO_IN[:4] + (128,) + _COMBO_IN[5:]
    [why] = combo_escapes(not_pow4, u)
    assert "m_pad=128" in why

    assert "arity" in combo_escapes(_COMBO_IN[:3], u)[0]


def test_journal_escapes_wire_forms():
    from gome_tpu.analysis.surface import _journal_entries, journal_escapes

    entry = {"entry": "frame_dispatch", "key": list(_COMBO_IN)}
    for doc in ([entry],
                {"entries": [entry]},
                {"schema": "gome-compile-journal/1", "entries": [entry]},
                {"compile_journal": {"entries": [entry]}},
                {"journal": {"entries": [entry]}}):
        assert _journal_entries(doc) == [entry]
    assert _journal_entries({"other": 1}) == []
    assert _journal_entries("junk") == []

    u = {"fields": ["n"], "dimensions": {"n": {"kind": "pow2",
                                               "min": 8, "max": 64,
                                               "cardinality": 4}}}
    entries = [
        {"entry": "frame_dispatch", "key": [32]},       # inside
        {"entry": "frame_dispatch", "key": [48]},       # escapes
        {"entry": "frame_dispatch", "key": [48]},       # dup: reported once
        {"entry": "precompile_replay", "key": [999]},   # not a dispatch
        {"entry": "frame_dispatch", "key": "notakey"},  # malformed: skipped
    ]
    escapes = journal_escapes(entries, u)
    assert escapes == [((48,), ["n=48 outside pow2 [8..64]"])]


def test_check_journal_escape_files(tmp_path):
    import json as _json

    from gome_tpu.analysis.surface import check_journal_escape

    journal = tmp_path / "journal.json"
    journal.write_text(_json.dumps(
        {"entries": [{"entry": "frame_dispatch", "key": list(_COMBO_IN)}]}
    ))
    assert check_journal_escape(str(journal)) == []

    missing = check_journal_escape(str(journal),
                                   str(tmp_path / "absent.json"))
    assert rules_of(missing) == ["GL906"]
    assert "no committed combo universe" in missing[0].message

    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    broken = check_journal_escape(str(bad))
    assert rules_of(broken) == ["GL906"]
    assert "unreadable" in broken[0].message

    journal.write_text(_json.dumps(
        {"entries": [{"entry": "frame_dispatch",
                      "key": [8, 48] + list(_COMBO_IN[2:])}]}
    ))
    escape = check_journal_escape(str(journal))
    assert rules_of(escape) == ["GL906"]
    assert "escapes the predicted universe" in escape[0].message
    assert "t_grid=48" in escape[0].message


def test_cli_journal_flag(tmp_path):
    import json as _json

    ok = tmp_path / "ok.json"
    ok.write_text(_json.dumps(
        {"entries": [{"entry": "frame_dispatch", "key": list(_COMBO_IN)}]}
    ))
    r = _cli(["gome_tpu/analysis/surface.py", "--select", "GL9",
              "--no-baseline", "--journal", str(ok)])
    assert r.returncode == 0, r.stdout + r.stderr

    bad = tmp_path / "bad.json"
    bad.write_text(_json.dumps(
        {"entries": [{"entry": "frame_dispatch",
                      "key": [8, 48] + list(_COMBO_IN[2:])}]}
    ))
    r = _cli(["gome_tpu/analysis/surface.py", "--select", "GL9",
              "--no-baseline", "--journal", str(bad)])
    assert r.returncode == 1
    assert "GL906" in r.stdout


def test_gl906_dynamic_witness_drill():
    """The runtime half of the contract, end to end on a live engine: a
    discovery run's every recorded combo lies INSIDE the committed
    universe (the static bound is sound for real traffic), and a fresh
    engine that precompiles those combos replays the same flow with the
    compile journal armed and SILENT (zero steady-state dispatches —
    the ROADMAP item 3 property GL906 audits in CI artifacts)."""
    import numpy as np

    from gome_tpu.analysis.surface import (
        combo_escapes,
        journal_escapes,
        load_universe,
    )
    from gome_tpu.engine import frames
    from gome_tpu.engine.batch import BatchEngine
    from gome_tpu.engine.book import BookConfig
    from gome_tpu.engine.frames import precompile_combos
    from gome_tpu.obs import CompileJournal
    from gome_tpu.utils.metrics import Registry

    def mk():
        return BatchEngine(BookConfig(cap=64, max_fills=4,
                                      dtype=jnp.int32),
                           n_slots=16, max_t=8)

    def mixed_frames():
        out = []
        rng = np.random.default_rng(7)
        for i, n in enumerate((64, 17, 128)):
            action = np.ones(n, np.int64)
            action[rng.random(n) < 0.25] = 2  # mixed flow: adds + dels
            out.append(dict(
                n=n,
                action=action,
                side=rng.integers(0, 2, n).astype(np.int64),
                kind=np.zeros(n, np.int64),
                price=rng.integers(99_000, 101_000, n).astype(np.int64),
                volume=rng.integers(1, 10, n).astype(np.int64),
                symbols=[f"s{j}" for j in range(6)],
                symbol_idx=rng.integers(0, 6, n).astype(np.int64),
                uuids=["u0"],
                uuid_idx=np.zeros(n, np.int64),
                oids=np.char.add(
                    "w", np.arange(i * 4096, i * 4096 + n).astype("U8")
                ).astype("S"),
            ))
        return out

    universe = load_universe(os.path.join(
        ROOT, "gome_tpu", "analysis", "combo_universe.json"))
    assert universe is not None

    # Discovery: every combo real traffic mints is inside the bound.
    e1 = mk()
    for f in mixed_frames():
        frames.apply_frame_fast(e1, f)
    discovered = sorted(e1.combos())
    assert discovered, "discovery run recorded no combos"
    for combo in discovered:
        assert combo_escapes(combo, universe) == [], combo

    # Replay: precompile the manifest, arm the journal, re-run the flow.
    e2 = mk()
    assert precompile_combos(e2, e1.shape_manifest()["combos"]) \
        == len(discovered)
    journal = CompileJournal().install(keep_n=64, registry=Registry())
    old = frames.JOURNAL
    frames.JOURNAL = journal  # armed AFTER precompile: boot is off-book
    try:
        for f in mixed_frames():
            frames.apply_frame_fast(e2, f)
    finally:
        frames.JOURNAL = old
        journal.disable()
    dispatches = [e for e in journal.entries()
                  if e["entry"] == "frame_dispatch"]
    assert dispatches == [], dispatches  # zero compiles at steady state
    # and the export wire form the CI artifact check reads is escape-free
    assert journal_escapes(journal.export()["entries"], universe) == []

"""RESP2 wire layer (persist.resp + persist.respserver) and the RESP-backed
pre-pool (engine.prepool.RespPrePool): protocol round trips over a real
socket, pipelining, the reference's exact marker schema, wire-level book
export -> import (bit-identical, restore-then-continue oracle parity), and
admission equivalence between the local and remote pools."""

import numpy as np
import pytest

from gome_tpu.engine import BookConfig, MatchEngine
from gome_tpu.engine.prepool import RespPrePool, make_marker
from gome_tpu.oracle import OracleEngine
from gome_tpu.persist import restore_from_redis
from gome_tpu.persist.redis_schema import export_to_redis
from gome_tpu.persist.resp import RespClient, RespError
from gome_tpu.persist.respserver import FakeRedisServer
from gome_tpu.types import Action, Order, Side
from gome_tpu.utils.streams import multi_symbol_stream

from test_redis_restore import _books_semantically_equal, _run_marked


@pytest.fixture()
def server():
    with FakeRedisServer() as srv:
        yield srv


@pytest.fixture()
def client(server):
    with RespClient(port=server.port) as c:
        yield c


def test_protocol_basics(client):
    assert client.ping()
    assert client.execute_command("ECHO", "héllo") == "héllo".encode()
    assert client.hset("h", "f1", "v1") == 1
    assert client.hset("h", "f1", "v2") == 0  # overwrite, not new
    assert client.execute_command("HGET", "h", "f1") == b"v2"
    assert client.hexists("h", "f1")
    assert not client.hexists("h", "nope")
    assert client.hgetall("h") == {"f1": "v2"}
    assert client.hdel("h", "f1", "zzz") == 1
    assert client.hgetall("h") == {}
    assert client.execute_command("HGET", "h", "f1") is None
    client.execute_command("ZADD", "z", 2.5, "b", 1, "a", 10, "c")
    assert client.zrange("z", 0, -1) == ["a", "b", "c"]
    assert client.execute_command(
        "ZRANGE", "z", 0, -1, "WITHSCORES"
    ) == [b"a", b"1", b"b", b"2.5", b"c", b"10"]
    assert client.execute_command("ZRANGEBYSCORE", "z", "-inf", 2.5) == [
        b"a", b"b",
    ]
    assert client.execute_command("ZREVRANGEBYSCORE", "z", "+inf", 2) == [
        b"c", b"b",
    ]
    assert client.execute_command("ZREM", "z", "b") == 1
    assert sorted(client.keys("*")) == ["h", "z"] or sorted(
        client.keys("*")
    ) == ["z"]  # h was emptied and dropped
    assert client.execute_command("DEL", "z") == 1
    with pytest.raises(RespError):
        client.execute_command("NOSUCHCMD")
    client.flushdb()
    assert client.keys("*") == []


def test_large_values_and_pipelining(client):
    big = "x" * 300_000
    client.hset("big", "f", big)
    assert client.hgetall("big")["f"] == big
    cmds = [("HSET", "p", f"f{i}", str(i)) for i in range(5_000)]
    cmds.insert(2500, ("BADCMD",))  # error must come back in-place
    replies = client.pipeline(cmds)
    assert len(replies) == 5_001
    assert isinstance(replies[2500], RespError)
    assert sum(r == 1 for r in replies if isinstance(r, int)) == 5_000
    assert len(client.hgetall("p")) == 5_000


def test_resp_prepool_schema_and_semantics(client):
    pool = RespPrePool(client)
    k1 = ("eth2usdt", "u1", "o1")
    k2 = ("eth2usdt", "u1", "o2")
    k3 = ("btc2usdt", "u2", "o1")
    pool.add(k1)
    pool.add(k3)
    # Reference schema on the wire: S:comparison hash, S:U:O field
    # (nodepool.go:14-16, ordernode.go:89-92).
    assert client.hgetall("eth2usdt:comparison") == {"eth2usdt:u1:o1": "1"}
    assert client.hgetall("btc2usdt:comparison") == {"btc2usdt:u2:o1": "1"}
    assert k1 in pool and k3 in pool and k2 not in pool
    pool |= {k2}
    assert sorted(pool) == sorted([k1, k2, k3])
    assert len(pool) == 3
    assert pool.consume_batch([k1, k1, k2]) == [True, False, True]
    assert k1 not in pool
    pool.discard(k3)
    assert len(pool) == 0
    pool.update([k1, k2])
    pool.clear()
    assert len(pool) == 0


def _mk_engine(**kw):
    kw.setdefault("config", BookConfig(cap=32, max_fills=8))
    kw.setdefault("n_slots", 8)
    kw.setdefault("max_t", 8)
    return MatchEngine(**kw)


def test_remote_prepool_admission_matches_local(server):
    """A MatchEngine with its pre-pool in the RESP store admits identically
    to the in-process pool — including the cancel-before-consume drop —
    and the event streams match the oracle."""
    orders = multi_symbol_stream(n=200, n_symbols=4, seed=23, cancel_prob=0.2)
    local = _mk_engine()
    got_local = _run_marked(local, orders)

    remote = _mk_engine()
    remote.pre_pool = RespPrePool(RespClient(port=server.port))
    got_remote = _run_marked(remote, orders)
    assert got_remote == got_local
    oracle = OracleEngine()
    want = [r for o in orders for r in oracle.process(o)]
    assert got_remote == want
    _books_semantically_equal(remote, local)
    assert remote.stats.dropped_no_prepool == local.stats.dropped_no_prepool


def test_remote_prepool_cancel_before_consume_drop(server):
    """The reference race (SURVEY §2.3.3): a DEL consumed before its ADD
    clears the marker (engine.go:88-90), so the later ADD dies unmarked
    (engine.go:58-62). With the marker store remote, the same flow must
    drop the ADD."""
    engine = _mk_engine()
    engine.pre_pool = RespPrePool(RespClient(port=server.port))
    add = Order(uuid="u", oid="x", symbol="s", side=Side.BUY, price=100,
                volume=5)
    engine.mark(add)  # gateway accepts the ADD, marks
    delete = Order(uuid="u", oid="x", symbol="s", side=Side.BUY, price=100,
                   volume=0, action=Action.DEL)
    # Queue order raced: DEL drains first, clears the mark, misses on book.
    assert engine.process([delete]) == []
    assert engine.process([add]) == []  # dropped: marker gone
    assert engine.stats.dropped_no_prepool == 1
    books = engine.batch.lane_books()
    assert int(np.asarray(books.count).sum()) == 0  # nothing rested


def test_wire_level_export_import_round_trip(server):
    """redis_schema export and redis_restore import BOTH over the socket:
    books round-trip bit-identically and the restored engine continues
    matching with oracle parity (the round-2 gap: the schema had only ever
    been exercised against the in-memory DictRedis)."""
    stream = multi_symbol_stream(n=400, n_symbols=5, seed=31, cancel_prob=0.15)
    head, tail = stream[:300], stream[300:]
    src = _mk_engine()
    oracle = OracleEngine()
    for o in head:
        src.mark(o)
        src.process([o])
        oracle.process(o)

    with RespClient(port=server.port) as c:
        n_cmds = export_to_redis(src, client=c)
    assert n_cmds > 0

    dst = _mk_engine()
    with RespClient(port=server.port) as c2:
        imported = restore_from_redis(dst, c2)
    assert imported == int(np.asarray(src.batch.lane_books().count).sum())
    _books_semantically_equal(dst, src)
    assert set(dst.pre_pool) == set(src.pre_pool)

    # Continue the stream on the restored engine: oracle parity holds.
    got = _run_marked(dst, tail)
    want = [r for o in tail for r in oracle.process(o)]
    assert got == want


def test_resp_prepool_raises_on_store_errors():
    """An error reply (e.g. -LOADING, -WRONGTYPE) must raise, not read as
    'mark absent' — conflating the two would silently drop acknowledged
    ADDs; raising lets the at-least-once consumer replay the batch."""

    class ErrClient:
        def pipeline(self, cmds):
            return [RespError("LOADING Redis is loading the dataset")] * len(
                cmds
            )

    pool = RespPrePool(ErrClient())
    with pytest.raises(RespError):
        pool.consume_batch([("s", "u", "1")])
    with pytest.raises(RespError):
        pool.update([("s", "u", "1")])


def test_make_marker_marks_only_adds(server):
    pool = RespPrePool(RespClient(port=server.port))
    mark = make_marker(pool)
    add = Order(uuid="u", oid="1", symbol="s", side=Side.BUY, price=1,
                volume=1)
    delete = Order(uuid="u", oid="2", symbol="s", side=Side.BUY, price=1,
                   volume=0, action=Action.DEL)
    mark(add)
    mark(delete)
    assert ("s", "u", "1") in pool
    assert ("s", "u", "2") not in pool

"""gome_tpu.sim: flow-generator contract, env semantics, statistical
validation, zero-transfer rollout (the acceptance sweep), and seeded
bit-exact replay across processes."""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gome_tpu.engine.book import GRID_I32_FIELDS, BookConfig, DeviceOp, init_books
from gome_tpu.sim import (
    AgentAction,
    EnvConfig,
    FlowConfig,
    MarketEnv,
    env_reset,
    env_step,
    flow_init,
    gen_ops_jit,
    make_manifest,
    null_action,
    record_frames,
    rollout,
    run_from_manifest,
)
from gome_tpu.sim import stats as sim_stats
from gome_tpu.sim.replay import env_config_from_manifest

# A quiet flow for agent-scenario tests: rates so low that background
# events are (astronomically) improbable over a few steps, leaving the
# books entirely to the agent. Rates must be positive by contract.
QUIET = FlowConfig(
    n_lanes=4, t_bins=8, submit_rate=1e-8, cancel_rate=1e-8,
    market_rate=1e-8,
)


def small_env(n_lanes=8, **kw):
    return EnvConfig(
        flow=FlowConfig(n_lanes=n_lanes, t_bins=16),
        book=BookConfig(cap=16, max_fills=4, dtype=jnp.int32),
        **kw,
    )


# -- flow: grid contract ------------------------------------------------------

class TestFlowGrid:
    def test_grid_layout_and_dtypes(self):
        config = FlowConfig(n_lanes=8, t_bins=32)
        books = init_books(BookConfig(cap=8, max_fills=2, dtype=jnp.int32), 8)
        state = flow_init(config, jax.random.PRNGKey(0))
        state2, ops = gen_ops_jit(config, state, books)
        assert isinstance(ops, DeviceOp)
        for f in DeviceOp._fields:
            leaf = getattr(ops, f)
            assert leaf.shape == (8, 32), f
            want = jnp.int32  # book dtype is int32 here too
            assert leaf.dtype == want, f
        host = jax.device_get(ops)
        assert set(np.unique(host.action)) <= {0, 1, 2}
        # Each bin owns one grid column: at most one event per column.
        assert ((host.action != 0).sum(axis=0) <= 1).all()
        occupied = host.action != 0
        # NOP cells are fully zeroed (inert anywhere in the grid).
        for f in DeviceOp._fields:
            assert (getattr(host, f)[~occupied] == 0).all(), f
        # DELs carry volume 0; markets price 0; ADD prices >= 1.
        adds = host.action == 1
        dels = host.action == 2
        assert (host.volume[dels] == 0).all()
        assert (host.volume[adds] >= 1).all()
        mkts = host.is_market == 1
        assert (host.price[mkts & adds] == 0).all()
        assert (host.price[adds & ~mkts] >= 1).all()
        # The intensity state advanced.
        assert int(state2.next_oid) >= 1
        assert float(state2.t_model) > 0

    def test_grid_i64_book_dtype(self):
        config = FlowConfig(n_lanes=4, t_bins=8)
        books = init_books(BookConfig(cap=8, max_fills=2, dtype=jnp.int64), 4)
        state = flow_init(config, jax.random.PRNGKey(1))
        _, ops = gen_ops_jit(config, state, books)
        for f in DeviceOp._fields:
            want = jnp.int32 if f in GRID_I32_FIELDS else jnp.int64
            assert getattr(ops, f).dtype == want, f

    def test_deterministic_in_key(self):
        config = FlowConfig(n_lanes=8, t_bins=32)
        books = init_books(BookConfig(cap=8, max_fills=2, dtype=jnp.int32), 8)

        def run():
            state = flow_init(config, jax.random.PRNGKey(7))
            _, ops = gen_ops_jit(config, state, books)
            return jax.device_get(ops)

        a, b = run(), run()
        for f in DeviceOp._fields:
            np.testing.assert_array_equal(getattr(a, f), getattr(b, f))

    def test_unstable_hawkes_raises(self):
        with pytest.raises(ValueError, match="unstable"):
            FlowConfig(excite_self=0.9, excite_cross=0.2)

    def test_saturated_discretization_raises(self):
        with pytest.raises(ValueError, match="saturates"):
            FlowConfig(dt=0.5)


# -- flow: statistical validation ---------------------------------------------

class TestFlowStats:
    @pytest.fixture(scope="class")
    def sample(self):
        config = FlowConfig(n_lanes=32, t_bins=64)
        return config, sim_stats.sample_grids(config, 0, 300)

    def test_zipf_exponent(self, sample):
        config, s = sample
        fit = sim_stats.zipf_exponent(sim_stats.symbol_counts(s))
        assert abs(fit - config.zipf_a) < 0.3, fit

    def test_hawkes_branching_and_clustering(self, sample):
        config, s = sample
        per_grid = sim_stats.events_per_grid(s)
        n_hat = sim_stats.empirical_branching_ratio(
            config, int(per_grid.sum()), len(per_grid)
        )
        # Thinning discretization biases the estimate low; it must still
        # sit well above zero and below the configured spectral bound.
        assert 0.25 < n_hat < config.branching_ratio() + 0.05, n_hat
        # Self-excitation clusters events: overdispersed window counts.
        assert sim_stats.dispersion_index(per_grid) > 1.2

    def test_poisson_limit(self):
        # Near-zero excitation: a Poisson stream — dispersion ~ 1 and
        # branching estimate ~ 0.
        config = FlowConfig(
            n_lanes=32, t_bins=64, excite_self=1e-6, excite_cross=1e-6,
            excite_kind=1e-6,
        )
        s = sim_stats.sample_grids(config, 1, 300)
        per_grid = sim_stats.events_per_grid(s)
        assert abs(sim_stats.dispersion_index(per_grid) - 1.0) < 0.25
        n_hat = sim_stats.empirical_branching_ratio(
            config, int(per_grid.sum()), len(per_grid)
        )
        assert abs(n_hat) < 0.12, n_hat


# -- env: reset/step/rollout --------------------------------------------------

class TestEnv:
    def test_reset_step_shapes(self):
        config = small_env()
        s, e, ell = 8, 6, config.obs_levels
        state, obs = env_reset(config, jax.random.PRNGKey(0))
        assert obs.best_bid.shape == (s,)
        assert obs.bid_prices.shape == (s, ell)
        assert obs.counts.shape == (s, 2) and obs.counts.dtype == jnp.int32
        assert obs.mid.shape == (s,) and obs.mid.dtype == jnp.float32
        assert obs.lam.shape == (e,) and obs.lam.dtype == jnp.float32
        state2, obs2, reward, info = env_step(
            config, state, null_action(config)
        )
        assert reward.shape == () and reward.dtype == jnp.float32
        assert info.trades.dtype == jnp.int32
        assert info.checksum.shape == (4,)
        assert int(state2.t) == 1
        assert state2.inv.shape == (s,)

    def test_rollout_scan_trajectory(self):
        config = small_env()
        state, _ = env_reset(config, jax.random.PRNGKey(2))
        final, (rewards, info) = rollout(config, state, 20)
        assert rewards.shape == (20,)
        assert info.events.shape == (20,)
        assert int(final.t) == 20
        assert int(jax.device_get(info.events).sum()) > 0

    def test_market_env_wrapper(self):
        env = MarketEnv(small_env())
        state, obs = env.reset(jax.random.PRNGKey(0))
        state, obs, reward, info = env.step(state, env.null_action())
        assert int(state.t) == 1

    def test_agent_maker_taker_pnl(self):
        # Background silenced: the agent trades against itself on lane 1
        # — rest a bid, lift it with a market sale, then cancel the rest.
        config = EnvConfig(
            flow=QUIET,
            book=BookConfig(cap=8, max_fills=4, dtype=jnp.int32),
            n_agent_ops=2,
        )
        state, obs = env_reset(config, jax.random.PRNGKey(0))
        z = np.zeros(2, np.int32)
        oid = 1 << 24  # agent handles live above background oids

        def act(**kw):
            base = dict(
                lane=z, action=z, side=z, is_market=z, price=z,
                volume=z, oid=z,
            )
            base.update({
                k: np.asarray(v, np.int32) for k, v in kw.items()
            })
            return AgentAction(**base)

        # Step 1: slot 0 rests BUY 5 @ 100 on lane 1.
        state, obs, reward, info = env_step(config, state, act(
            lane=[1, 0], action=[1, 0], side=[0, 0], price=[100, 0],
            volume=[5, 0], oid=[oid, 0],
        ))
        assert int(obs.best_bid[1]) == 100
        assert int(obs.counts[1, 0]) == 1
        assert int(info.trades) == 0
        # Step 2: slot 0 market-SELLs 2 into the resting bid.
        state, obs, reward, info = env_step(config, state, act(
            lane=[1, 0], action=[1, 0], side=[1, 0], is_market=[1, 0],
            volume=[2, 0], oid=[oid + 1, 0],
        ))
        assert int(info.trades) == 1
        assert int(info.traded_qty) == 2
        assert int(info.agent_fills) == 2  # maker AND taker records
        host = jax.device_get(state)
        # Self-trade: maker +2, taker -2 inventory; cash nets to zero.
        assert int(host.inv[1]) == 0
        assert float(host.cash) == pytest.approx(0.0)
        assert int(obs.bid_lots[1, 0]) == 3  # 5 rested - 2 filled
        # Step 3: slot 0 cancels the remainder (exact resting price).
        state, obs, reward, info = env_step(config, state, act(
            lane=[1, 0], action=[2, 0], side=[0, 0], price=[100, 0],
            oid=[oid, 0],
        ))
        assert int(info.cancels_missed) == 0
        assert int(obs.counts[1, 0]) == 0

    def test_env_config_validation(self):
        with pytest.raises(ValueError, match="agent_uid"):
            EnvConfig(flow=FlowConfig(n_lanes=4), agent_uid=8)
        with pytest.raises(ValueError, match="obs_levels"):
            EnvConfig(
                book=BookConfig(cap=4, max_fills=2, dtype=jnp.int32),
                obs_levels=9,
            )


# -- acceptance: zero-transfer 1000-step rollout over 256 books ---------------

class TestZeroTransferRollout:
    CONFIG = EnvConfig(
        flow=FlowConfig(n_lanes=256),
        book=BookConfig(cap=32, max_fills=8, dtype=jnp.int32),
    )

    def test_rollout_1000_steps_no_host_transfers(self):
        config = self.CONFIG
        state0, _ = env_reset(config, jax.random.PRNGKey(3))
        # Warm the compile off the guard, on throwaway state.
        _ = rollout(config, state0, 1000)
        state, _ = env_reset(config, jax.random.PRNGKey(3))
        # Runtime assertion: the whole 1000-step scan must execute with
        # zero host<->device transfers (the GL5xx contract, enforced by
        # the runtime, not just static analysis).
        with jax.transfer_guard("disallow"):
            final, (rewards, info) = rollout(config, state, 1000)
        jax.block_until_ready(info.checksum)
        ev, tr, b_over, f_over = jax.device_get(
            (info.events, info.trades, info.book_overflow,
             info.fill_overflow)
        )
        assert ev.shape == (1000,)
        assert int(ev.sum()) > 1000  # flow actually ran
        assert int(tr.sum()) > 100  # and actually traded
        # Exactness: geometry absorbs the whole flow (no silent drops).
        assert int(b_over.sum()) == 0
        assert int(f_over.sum()) == 0

    def test_rollout_jaxpr_has_no_callbacks(self):
        config = self.CONFIG
        state, _ = env_reset(config, jax.random.PRNGKey(0))
        txt = str(jax.make_jaxpr(
            lambda st: rollout(config, st, 8)
        )(state))
        for prim in ("callback", "outside_call", "infeed", "outfeed"):
            assert prim not in txt, prim


# -- replay: manifests, two-process bit-exactness, GCO record mode ------------

REPLAY_CONFIG = EnvConfig(
    flow=FlowConfig(n_lanes=16, t_bins=32),
    book=BookConfig(cap=16, max_fills=4, dtype=jnp.int32),
)

_REPLAY_CHILD = """
import json, sys
import jax
jax.config.update("jax_enable_x64", True)
from gome_tpu.sim import run_from_manifest
print(json.dumps(run_from_manifest(json.load(open(sys.argv[1])))))
"""


class TestReplay:
    def test_manifest_roundtrip(self):
        m = make_manifest(REPLAY_CONFIG, seed=9, n_steps=12)
        blob = json.loads(json.dumps(m))  # survive serialization
        assert env_config_from_manifest(blob) == REPLAY_CONFIG

    def test_manifest_hash_mismatch_raises(self):
        m = make_manifest(REPLAY_CONFIG, seed=9, n_steps=12)
        m = json.loads(json.dumps(m))
        m["config"]["flow"]["zipf_a"] = 1.3  # hand-edited
        with pytest.raises(ValueError, match="hash mismatch"):
            env_config_from_manifest(m)
        m2 = make_manifest(REPLAY_CONFIG, seed=9, n_steps=12)
        m2["version"] = 99
        with pytest.raises(ValueError, match="version"):
            env_config_from_manifest(m2)

    def test_two_process_bit_exact_replay(self, tmp_path):
        manifest = make_manifest(REPLAY_CONFIG, seed=41, n_steps=40)
        here = run_from_manifest(manifest)
        assert here["events"] > 0
        # Same manifest, fresh interpreter: the digest covers every fill
        # record and every final book leaf, so equality is bit-exactness
        # of the whole trade sequence and book evolution.
        path = tmp_path / "manifest.json"
        path.write_text(json.dumps(manifest))
        env = dict(
            os.environ, JAX_PLATFORMS="cpu",
            PYTHONPATH=os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)
            )),
        )
        out = subprocess.run(
            [sys.executable, "-c", _REPLAY_CHILD, str(path)],
            capture_output=True, text=True, env=env, timeout=300,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        there = json.loads(out.stdout.strip().splitlines()[-1])
        assert there == here

    def test_in_process_replay_deterministic(self):
        manifest = make_manifest(REPLAY_CONFIG, seed=5, n_steps=25)
        assert run_from_manifest(manifest) == run_from_manifest(manifest)
        other = run_from_manifest(
            make_manifest(REPLAY_CONFIG, seed=6, n_steps=25)
        )
        assert other["digest"] != run_from_manifest(manifest)["digest"]

    def test_record_frames_feed_service_codec(self):
        from gome_tpu.bus.colwire import decode_order_frame
        from gome_tpu.engine.frames import orders_from_frame
        from gome_tpu.engine.orchestrator import MatchEngine

        config = EnvConfig(
            flow=FlowConfig(n_lanes=8, t_bins=32),
            book=BookConfig(cap=16, max_fills=4, dtype=jnp.int32),
        )
        frames = record_frames(config, seed=2, n_steps=10)
        assert frames, "flow produced no frames in 10 steps"
        engine = MatchEngine(
            config=BookConfig(cap=32, max_fills=8, dtype=jnp.int32),
            n_slots=8, max_t=16,
        )
        n_orders = n_events = 0
        for payload in frames:
            cols = decode_order_frame(payload)
            orders = orders_from_frame(cols)
            n_orders += len(orders)
            n_events += len(engine.process(orders))
        assert n_orders > 0
        engine.batch.verify_books()

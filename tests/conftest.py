"""Test harness configuration.

Forces JAX onto a virtual 8-device CPU platform so multi-chip sharding tests
run without TPU hardware, and enables x64 so int64 tick/lot arithmetic is
exact (SURVEY §2.2).

Note: this image's sitecustomize imports jax at interpreter startup with
JAX_PLATFORMS=axon (the tunneled real TPU), so env vars alone are too late —
the platform must be overridden via jax.config. XLA_FLAGS still works because
the CPU backend initializes lazily, after this conftest runs.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

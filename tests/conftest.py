"""Test harness configuration.

Forces JAX onto a virtual 8-device CPU platform so multi-chip sharding tests
run without TPU hardware, and enables x64 so int64 tick/lot arithmetic is
exact (SURVEY §2.2).

Note: this image's sitecustomize imports jax at interpreter startup with
JAX_PLATFORMS=axon (the tunneled real TPU), so env vars alone are too late —
platform and device count must be set via jax.config before the (lazy) first
backend initialization, which is why this conftest does it at import time.
"""

import jax

jax.config.update("jax_num_cpu_devices", 8)
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

"""Test harness configuration.

Forces JAX onto a virtual 8-device CPU platform BEFORE jax is imported so
multi-chip sharding tests run without TPU hardware, and enables x64 so
int64 tick/lot arithmetic is exact (SURVEY §2.2).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")

"""Test harness configuration.

Forces JAX onto a virtual 8-device CPU platform so multi-chip sharding tests
run without TPU hardware, and enables x64 so int64 tick/lot arithmetic is
exact (SURVEY §2.2).

Note: this image's sitecustomize imports jax at interpreter startup with
JAX_PLATFORMS=axon (the tunneled real TPU), so the platform must be forced
via jax.config before the (lazy) first backend initialization — importing
jax does NOT initialize a backend, so doing it at conftest import time is
early enough. The virtual device COUNT has two spellings across JAX
releases: newer JAX has a `jax_num_cpu_devices` config option; older
releases (0.4.37 rejects the option with AttributeError) only honor the
XLA_FLAGS --xla_force_host_platform_device_count flag, which is likewise
read at backend init, not at import. Set both, flag first.
"""

import os

_FORCE = "--xla_force_host_platform_device_count=8"
if "xla_force_host_platform_device_count" not in os.environ.get(
    "XLA_FLAGS", ""
):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " " + _FORCE
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass  # pre-0.5 JAX: the XLA_FLAGS spelling above applies instead

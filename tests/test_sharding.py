"""Multi-chip tests on the virtual 8-device CPU platform (conftest.py).

Verifies the framework's parallelism story: symbol-sharded books produce
bit-identical results to single-device execution, and the sharded step
compiles with the expected zero-collective partitioning."""

import jax
import numpy as np

from gome_tpu.engine import BatchEngine, BookConfig, batch_step, init_books
from gome_tpu.engine.book import DeviceOp
from gome_tpu.fixed import scale
from gome_tpu.oracle import OracleEngine
from gome_tpu.parallel import (
    make_mesh,
    shard_batch,
    sharded_batch_step,
    symbol_sharding,
)
from gome_tpu.types import Order, Side
from gome_tpu.utils.streams import multi_symbol_stream

CFG = BookConfig(cap=64, max_fills=16)


def _grid_from_stream(engine_like, orders, n_slots, max_t):
    """Pack a one-grid batch the way BatchEngine does (enough for tests)."""
    from gome_tpu.engine.batch import _nop_grid
    from gome_tpu.engine.host import Interner, encode_op

    grid = _nop_grid(CFG, n_slots, max_t)
    oids, uids, syms = Interner(), Interner(), Interner()
    level = {}
    for order in orders:
        lane = syms.intern(order.symbol) - 1
        t = level.get(lane, 0)
        if t >= max_t:
            continue  # single-grid helper: excess ops are simply not packed
        op = encode_op(order, oids, uids)
        for name, arr in grid.items():
            arr[lane, t] = getattr(op, name)
        level[lane] = t + 1
    return DeviceOp(**grid)


def test_eight_devices_available():
    assert len(jax.devices()) == 8


def test_sharded_step_matches_single_device():
    n_slots, max_t = 16, 4
    orders = multi_symbol_stream(n=48, n_symbols=16, seed=1)
    ops = _grid_from_stream(None, orders, n_slots, max_t)

    books0 = init_books(CFG, n_slots)
    ref_books, ref_outs = batch_step(CFG, books0, ops)

    mesh = make_mesh(8)
    stepper = sharded_batch_step(CFG, mesh)
    sh_books = shard_batch(mesh, init_books(CFG, n_slots))
    sh_ops = shard_batch(mesh, ops)
    got_books, got_outs = stepper(sh_books, sh_ops)

    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            jax.device_get(a), jax.device_get(b)
        ),
        (ref_books, ref_outs),
        (got_books, got_outs),
    )


def test_sharded_pallas_kernel_matches_scan():
    """The per-chip Pallas kernel under shard_map (interpret mode on the
    CPU mesh — the same code path the compiled kernel runs per chip on
    TPU) must equal the sharded scan step leaf-for-leaf (VERDICT r1
    missing #3 retired)."""
    import jax.numpy as jnp

    cfg32 = BookConfig(cap=32, max_fills=8, dtype=jnp.int32)
    n_slots, max_t = 16, 4
    orders = multi_symbol_stream(n=48, n_symbols=16, seed=3, cancel_prob=0.1)
    from gome_tpu.engine.batch import _nop_grid
    from gome_tpu.engine.host import Interner, encode_op

    grid = _nop_grid(cfg32, n_slots, max_t)
    oids, uids, syms = Interner(), Interner(), Interner()
    level = {}
    for order in orders:
        lane = syms.intern(order.symbol) - 1
        t = level.get(lane, 0)
        if t >= max_t:
            continue
        op = encode_op(order, oids, uids, dtype=np.int32)
        for name, arr in grid.items():
            arr[lane, t] = getattr(op, name)
        level[lane] = t + 1
    ops = DeviceOp(**grid)

    mesh = make_mesh(8)
    sh_books = shard_batch(mesh, init_books(cfg32, n_slots))
    sh_ops = shard_batch(mesh, ops)
    scan_books, scan_outs = sharded_batch_step(cfg32, mesh)(sh_books, sh_ops)
    k_books, k_outs = sharded_batch_step(
        cfg32, mesh, kernel="pallas", pallas_interpret=True
    )(shard_batch(mesh, init_books(cfg32, n_slots)), sh_ops)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            jax.device_get(a), jax.device_get(b)
        ),
        (scan_books, scan_outs),
        (k_books, k_outs),
    )
    # the sharding survives the shard_map round trip
    assert k_books.price.sharding.is_equivalent_to(
        symbol_sharding(mesh), k_books.price.ndim
    )


def test_batch_engine_mesh_pallas_end_to_end():
    """BatchEngine(mesh=..., kernel='pallas', pallas_interpret=True) runs
    the kernel per chip and matches the oracle end to end."""
    import jax.numpy as jnp

    orders = multi_symbol_stream(n=200, n_symbols=8, seed=12, cancel_prob=0.2)
    oracle = OracleEngine()
    expected = []
    for o in orders:
        expected.extend(oracle.process(o))
    mesh = make_mesh(8)
    eng = BatchEngine(
        BookConfig(cap=32, max_fills=8, dtype=jnp.int32),
        n_slots=16, max_t=8, mesh=mesh,
        kernel="pallas", pallas_interpret=True,
    )
    got = []
    for i in range(0, len(orders), 64):
        got.extend(eng.process(orders[i : i + 64]))
    assert got == expected
    eng.verify_books()


def test_sharded_output_is_actually_sharded():
    mesh = make_mesh(8)
    stepper = sharded_batch_step(CFG, mesh)
    books = shard_batch(mesh, init_books(CFG, 16))
    ops = shard_batch(
        mesh, _grid_from_stream(None, multi_symbol_stream(24, 16, seed=2), 16, 4)
    )
    new_books, outs = stepper(books, ops)
    assert new_books.price.sharding.is_equivalent_to(
        symbol_sharding(mesh), new_books.price.ndim
    )
    # 8 shards -> each device holds 2 of 16 lanes.
    shard_shapes = {s.data.shape for s in new_books.price.addressable_shards}
    assert shard_shapes == {(2, 2, CFG.cap)}


def test_mesh_sizes_1_2_4_8():
    orders = multi_symbol_stream(n=32, n_symbols=8, seed=3)
    ops = _grid_from_stream(None, orders, 8, 8)
    ref = None
    for n in (1, 2, 4, 8):
        mesh = make_mesh(n)
        stepper = sharded_batch_step(CFG, mesh)
        books, outs = stepper(
            shard_batch(mesh, init_books(CFG, 8)), shard_batch(mesh, ops)
        )
        flat = jax.device_get(jax.tree.leaves((books, outs)))
        if ref is None:
            ref = flat
        else:
            for a, b in zip(ref, flat):
                np.testing.assert_array_equal(a, b)


def test_batch_engine_end_to_end_parity_on_8_devices():
    """Full BatchEngine parity run with device-sharded books."""
    orders = multi_symbol_stream(n=400, n_symbols=32, seed=5, cancel_prob=0.1)
    oracle = OracleEngine()
    expected = []
    for order in orders:
        expected.extend(oracle.process(order))

    engine = BatchEngine(CFG, n_slots=32, max_t=8)
    mesh = make_mesh(8)
    engine.books = shard_batch(mesh, engine.books)
    got = engine.process(orders)
    assert got == expected


def test_batch_engine_mesh_param_matches_oracle():
    """BatchEngine(mesh=...) — books pinned to the mesh through init, lane
    growth (rounded to mesh multiples), and steps; same events as the
    oracle."""
    import jax

    from gome_tpu.utils.streams import multi_symbol_stream

    mesh = make_mesh(8)
    engine = BatchEngine(CFG, n_slots=8, max_t=8, mesh=mesh)
    orders = multi_symbol_stream(n=300, n_symbols=20, seed=9, cancel_prob=0.1)
    oracle = OracleEngine()
    expected = []
    for order in orders:
        expected.extend(oracle.process(order))
    got = []
    for i in range(0, len(orders), 64):
        got.extend(engine.process(orders[i : i + 64]))
    assert got == expected
    assert engine.n_slots % mesh.size == 0 and engine.n_slots >= 20
    shardings = {
        str(getattr(l.sharding, "spec", None))
        for l in jax.tree.leaves(engine.books)
    }
    assert "PartitionSpec('sym',)" in shardings


# ---- dense live-lane grids under the mesh (round-4) -----------------------


def _skewed_stream(n, n_symbols, seed, hot_share=0.4, cancel_prob=0.1):
    """Zipf-ish flow: `hot_share` of ops hit symbol 0, the rest spread
    uniformly — the config-4 shape at test scale."""
    rng = np.random.default_rng(seed)
    from gome_tpu.types import Action, OrderType

    orders = []
    live = []
    for i in range(n):
        if live and rng.random() < cancel_prob:
            sym, oid, price = live.pop(int(rng.integers(len(live))))
            orders.append(
                Order(
                    uuid="u", oid=oid, symbol=sym, side=Side.BUY,
                    price=price, volume=1, action=Action.DEL,
                    order_type=OrderType.LIMIT,
                )
            )
            continue
        k = 0 if rng.random() < hot_share else int(rng.integers(n_symbols))
        price = int(rng.integers(995, 1005))
        oid = f"o{i}"
        orders.append(
            Order(
                uuid="u", oid=oid, symbol=f"s{k}",
                side=Side(int(rng.integers(2))), price=price,
                volume=int(rng.integers(1, 4)), action=Action.ADD,
                order_type=OrderType.LIMIT,
            )
        )
        live.append((f"s{k}", oid, price))
    return orders


def test_dense_grids_under_mesh_match_oracle():
    """Config-4-like skewed flow on the 8-device mesh with n_slots large
    enough that the per-shard dense packing engages (the round-3 gap: the
    dense path silently reverted to full NOP-padded grids under a mesh).
    Events must equal the oracle's and the sharded dense stepper must
    actually have run."""
    mesh = make_mesh(8)
    eng = BatchEngine(CFG, n_slots=128, max_t=8, mesh=mesh)
    orders = _skewed_stream(400, 40, seed=21)
    oracle = OracleEngine()
    expected = []
    for o in orders:
        expected.extend(oracle.process(o))
    got = []
    for i in range(0, len(orders), 100):
        got.extend(eng.process_columnar(orders[i : i + 100]).to_results())
    assert got == expected
    assert eng._sharded_dense_steppers, "dense-under-mesh path never ran"
    eng.verify_books()


def test_dense_frame_path_under_mesh_matches_oracle():
    """The FRAME fast path (submit/compact/resolve) under the mesh with
    per-shard dense grids — the production multi-chip hot path."""
    from gome_tpu.bus import colwire
    from gome_tpu.engine.frames import apply_frame_fast

    mesh = make_mesh(8)
    eng = BatchEngine(CFG, n_slots=128, max_t=8, mesh=mesh)
    orders = _skewed_stream(400, 40, seed=22)
    oracle = OracleEngine()
    expected = []
    for o in orders:
        expected.extend(oracle.process(o))
    got = []
    for i in range(0, len(orders), 100):
        cols = colwire.decode_order_frame(
            colwire.encode_orders(orders[i : i + 100])
        )
        got.extend(apply_frame_fast(eng, cols).to_results())
    assert got == expected
    assert eng._sharded_dense_steppers, "dense-under-mesh path never ran"


def test_cap_escalation_under_mesh_dense():
    """Cap escalation (grow_books -> replay) while books are mesh-sharded
    AND the grid is dense — the round-3 untested corner: growth must
    re-place the stack on the mesh and the replay must stay exact."""
    mesh = make_mesh(8)
    eng = BatchEngine(
        BookConfig(cap=8, max_fills=4), n_slots=128, max_t=8, mesh=mesh
    )
    from gome_tpu.types import Action, OrderType

    # 20 resting asks at distinct prices on one symbol (cap 8 overflows),
    # spread over several other symbols so the grid stays dense.
    orders = [
        Order(
            uuid="u", oid=f"r{i}", symbol="hot", side=Side.SALE,
            price=1000 + i, volume=1, action=Action.ADD,
            order_type=OrderType.LIMIT,
        )
        for i in range(20)
    ] + [
        Order(
            uuid="u", oid=f"c{i}", symbol=f"cold{i}", side=Side.BUY,
            price=500, volume=1, action=Action.ADD,
            order_type=OrderType.LIMIT,
        )
        for i in range(10)
    ]
    oracle = OracleEngine()
    expected = []
    for o in orders:
        expected.extend(oracle.process(o))
    got = eng.process_columnar(orders).to_results()
    assert got == expected
    assert eng.stats.cap_escalations >= 1
    assert eng.config.cap >= 20
    assert eng._sharded_dense_steppers, "escalation did not use dense path"
    # Books still sharded after growth.
    shardings = {
        str(getattr(l.sharding, "spec", None))
        for l in jax.tree.leaves(eng.books)
    }
    assert "PartitionSpec('sym',)" in shardings
    eng.verify_books()


def test_fill_record_escalation_under_mesh_dense():
    """Fill-record escalation (per-row re-run with a bigger K) while
    mesh-sharded on a dense grid: one sweep crossing 12 makers with
    max_fills=4 must re-decode exactly."""
    mesh = make_mesh(8)
    eng = BatchEngine(
        BookConfig(cap=32, max_fills=4), n_slots=128, max_t=16, mesh=mesh
    )
    from gome_tpu.types import Action, OrderType

    orders = [
        Order(
            uuid="u", oid=f"r{i}", symbol="hot", side=Side.SALE,
            price=1000, volume=1, action=Action.ADD,
            order_type=OrderType.LIMIT,
        )
        for i in range(12)
    ] + [
        Order(
            uuid="u", oid="sweep", symbol="hot", side=Side.BUY,
            price=1000, volume=12, action=Action.ADD,
            order_type=OrderType.LIMIT,
        ),
        Order(
            uuid="u", oid="x1", symbol="cold1", side=Side.BUY, price=500,
            volume=1, action=Action.ADD, order_type=OrderType.LIMIT,
        ),
    ]
    oracle = OracleEngine()
    expected = []
    for o in orders:
        expected.extend(oracle.process(o))
    got = eng.process_columnar(orders).to_results()
    assert got == expected
    assert eng.stats.fill_record_escalations >= 1

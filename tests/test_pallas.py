"""Pallas match-kernel parity tests (interpret mode on CPU; the compiled
kernel runs the identical traced code on TPU — gome_tpu.ops.pallas_match).
"""

import numpy as np
import pytest

from bench import build_grids
from gome_tpu.engine import BatchEngine, BookConfig, batch_step, init_books
from gome_tpu.engine.book import DeviceOp
from gome_tpu.oracle import OracleEngine
from gome_tpu.ops import pallas_batch_step
from gome_tpu.utils.streams import mixed_stream


def assert_trees_equal(t1, t2):
    for name in t1._fields:
        np.testing.assert_array_equal(
            getattr(t1, name), getattr(t2, name), err_msg=name
        )


def test_grid_parity_vs_scan():
    """Random crossing flow: pallas kernel == scan baseline on every output
    leaf and every book leaf, across chained grids."""
    config = BookConfig(cap=32, max_fills=8)
    S, T = 16, 8
    b1 = b2 = init_books(config, S)
    for g in [DeviceOp(**d) for d in build_grids(S, T, 3, seed=5)]:
        b1, o1 = batch_step(config, b1, g)
        b2, o2 = pallas_batch_step(config, b2, g, block_s=8, interpret=True)
        assert_trees_equal(o1, o2)
    assert_trees_equal(b1, b2)


def test_grid_parity_with_cancels_markets_nops():
    """Grid containing NOPs, DELs and MARKET orders (all action paths)."""
    config = BookConfig(cap=16, max_fills=4)
    S, T = 8, 6
    rng = np.random.default_rng(0)
    d = np.int64
    grid = DeviceOp(
        action=rng.integers(0, 3, size=(S, T), dtype=np.int32),
        side=rng.integers(0, 2, size=(S, T), dtype=np.int32),
        is_market=(rng.random((S, T)) < 0.2).astype(np.int32),
        price=rng.integers(90, 111, size=(S, T)).astype(d),
        volume=rng.integers(1, 10, size=(S, T)).astype(d),
        oid=np.arange(S * T, dtype=d).reshape(S, T) % 7 + 1,
        uid=np.ones((S, T), d),
    )
    books = init_books(config, S)
    b1, o1 = batch_step(config, books, grid)
    b2, o2 = pallas_batch_step(config, books, grid, block_s=8, interpret=True)
    assert_trees_equal(o1, o2)
    assert_trees_equal(b1, b2)


def test_block_size_validation():
    config = BookConfig(cap=16, max_fills=4)
    books = init_books(config, 6)
    grid = DeviceOp(**build_grids(6, 2, 1)[0])
    with pytest.raises(ValueError, match="multiple"):
        pallas_batch_step(config, books, grid, block_s=4, interpret=True)


def test_batch_engine_pallas_kernel_oracle_parity():
    """Full BatchEngine on the pallas kernel matches the oracle on a mixed
    stream (admission, escalations, decode — everything downstream of the
    kernel is shared)."""
    orders = mixed_stream(n=150, seed=9, cancel_prob=0.2, market_prob=0.1)
    oracle = OracleEngine()
    expected = []
    for o in orders:
        expected.extend(oracle.process(o))

    engine = BatchEngine(
        BookConfig(cap=32, max_fills=8), n_slots=8, max_t=16,
        kernel="pallas", pallas_interpret=True,
    )
    got = []
    for i in range(0, len(orders), 40):
        got.extend(engine.process(orders[i : i + 40]))
    assert got == expected


def test_int32_dtype_parity():
    import jax.numpy as jnp

    config = BookConfig(cap=16, max_fills=8, dtype=jnp.int32)
    S, T = 8, 4
    grids = build_grids(S, T, 2, seed=3, dtype=np.int32)
    # keep magnitudes in int32 range: small lots
    for d in grids:
        d["volume"] = (d["volume"] // 1_000_000).astype(np.int32)
        d["price"] = (d["price"] // 1000).astype(np.int32)
    b1 = b2 = init_books(config, S)
    for g in [DeviceOp(**d) for d in grids]:
        b1, o1 = batch_step(config, b1, g)
        b2, o2 = pallas_batch_step(config, b2, g, block_s=8, interpret=True)
        assert_trees_equal(o1, o2)
    assert_trees_equal(b1, b2)
    assert b1.price.dtype == jnp.int32

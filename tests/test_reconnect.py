"""Deterministic fault-schedule drills for the supervised connection layer:
broker death mid-publish and mid-consume (bus.amqp.SupervisedAmqpQueue over
bus.fakebroker's fault modes), RESP store restart mid-mark
(persist.resp.SupervisedRespClient over persist.respserver), and the
acceptance drill — a pipelined consumer run with >= 3 scripted disconnects
whose matchOrder stream must be byte-identical to a fault-free oracle run
(at-least-once redelivery + commit-after-publish composing with reconnects
gives no lost and no duplicated fills)."""

import time

import pytest

from gome_tpu.bus.amqp import SupervisedAmqpQueue
from gome_tpu.bus.fakebroker import FakeBroker
from gome_tpu.utils.resilience import BackoffPolicy

#: Fast schedule for drills: real reconnects, no test-visible latency.
FAST = BackoffPolicy(base_s=0.005, max_s=0.05, max_retries=60, budget_s=30)


def make_queue(name, broker):
    return SupervisedAmqpQueue(name, port=broker.port, policy=FAST)


# --- connection-level drills ----------------------------------------------


def test_exact_stream_across_repeated_publish_kills():
    """close_abruptly_on_publish=5: every connection is killed at ITS 5th
    publish (the killed publish is dropped broker-side — the crash-before-
    enqueue case). 23 messages force ~5 reconnects; the consumer must see
    all 23 exactly once, in order."""
    broker = FakeBroker(close_abruptly_on_publish=5).start()
    try:
        producer = make_queue("doOrder", broker)
        consumer = make_queue("doOrder", broker)
        bodies = [f"m{i}".encode() for i in range(23)]
        for b in bodies:
            producer.publish(b)
        got = []
        deadline = time.monotonic() + 20
        while len(got) < len(bodies) and time.monotonic() < deadline:
            msgs = consumer.poll_batch(64, 0.2)
            got = [m.body for m in msgs]
        assert got == bodies  # no loss, no dup, order preserved
        snap = producer.supervisor().snapshot()
        assert snap["connects_total"] >= 4  # ≥3 disconnects survived
        producer.close()
        consumer.close()
    finally:
        broker.stop()


def test_redelivery_resumes_exact_offsets_after_consume_kill():
    """Kill the consumer's connection mid-stream: committed (acked)
    messages must NOT redeliver; everything past the committed cursor
    redelivers at the SAME wrapper offsets, in order."""
    broker = FakeBroker().start()
    try:
        producer = make_queue("doOrder", broker)
        consumer = make_queue("doOrder", broker)
        for i in range(10):
            producer.publish(f"m{i}".encode())
        msgs = consumer.poll_batch(10, 5.0)
        assert len(msgs) == 10
        consumer.commit(4)  # m0..m3 acked broker-side
        assert broker.kill_connections(consuming="doOrder") == 1
        # resume: the uncommitted tail redelivers at offsets 4..9
        deadline = time.monotonic() + 20
        tail = []
        while len(tail) < 6 and time.monotonic() < deadline:
            tail = consumer.poll_batch(16, 0.2)
        assert [(m.offset, m.body) for m in tail] == [
            (i, f"m{i}".encode()) for i in range(4, 10)
        ]
        consumer.commit(10)
        producer.publish(b"late")
        late = consumer.poll_batch(1, 5.0)
        assert [(m.offset, m.body) for m in late] == [(10, b"late")]
        assert consumer.supervisor().snapshot()["connects_total"] >= 2
        producer.close()
        consumer.close()
    finally:
        broker.stop()


def test_channel_close_fault_reconnects_and_retries():
    """Server-initiated Channel.Close (resource fault) instead of a dead
    socket: the supervised queue must also recover from protocol-level
    connection failure."""
    broker = FakeBroker(channel_close_on_publish=3).start()
    try:
        q = make_queue("doOrder", broker)
        for i in range(8):
            q.publish(f"m{i}".encode())
        msgs = q.poll_batch(8, 10.0)
        assert [m.body for m in msgs] == [f"m{i}".encode() for i in range(8)]
        assert q.supervisor().snapshot()["connects_total"] >= 2
        q.close()
    finally:
        broker.stop()


# --- RESP store drills ----------------------------------------------------


def test_resp_store_restarts_mid_mark():
    """Three server restarts interleaved with pre-pool marking: the
    supervised client reconnects + retries (HSET marking is idempotent
    under retry), and the consume pass at the end sees every mark exactly
    once."""
    from gome_tpu.engine.prepool import RespPrePool
    from gome_tpu.persist.resp import SupervisedRespClient
    from gome_tpu.persist.respserver import FakeRedisServer

    srv = FakeRedisServer()
    port = srv.start()
    try:
        client = SupervisedRespClient(
            port=port, policy=FAST, name="resp:drill"
        )
        pool = RespPrePool(client)
        keys = [("eth2usdt", "u", f"oid{i}") for i in range(12)]
        for i, k in enumerate(keys):
            if i in (3, 6, 9):  # restart schedule: mid-mark, three times
                srv.restart()
            pool.add(k)
        assert pool.resilience()["connects_total"] >= 4
        assert pool.consume_batch(keys) == [True] * len(keys)
        assert pool.consume_batch(keys) == [False] * len(keys)  # consumed
        client.close()
    finally:
        srv.stop()


# --- the acceptance drill -------------------------------------------------


def _mk_engine():
    import jax.numpy as jnp

    from gome_tpu.engine.book import BookConfig
    from gome_tpu.engine.orchestrator import MatchEngine

    return MatchEngine(
        config=BookConfig(cap=32, max_fills=8, dtype=jnp.int64),
        n_slots=16,
        max_t=8,
    )


def _run_flow(engine, bus, orders, mid_kill=None):
    """Gateway-style feed (mark ADDs, publish each order) + consumer drain.
    mid_kill(processed_so_far) is called between consumer steps so drills
    can kill connections at scripted points. Returns the matchOrder
    bodies."""
    from gome_tpu.bus import encode_order
    from gome_tpu.service.consumer import OrderConsumer
    from gome_tpu.types import Action

    for o in orders:
        if o.action is Action.ADD:
            engine.mark(o)
        bus.order_queue.publish(encode_order(o))
    consumer = OrderConsumer(engine, bus, batch_n=16, batch_wait_s=0.01)
    deadline = time.monotonic() + 60
    while (
        bus.order_queue.committed() < bus.order_queue.end_offset()
        and time.monotonic() < deadline
    ):
        consumer.step_with_policy()
        if mid_kill is not None:
            mid_kill(bus.order_queue.committed())
    assert bus.order_queue.committed() == bus.order_queue.end_offset()
    mq = bus.match_queue
    return [m.body for m in mq.read_from(0, mq.end_offset())]


def test_fault_schedule_match_stream_is_oracle_exact():
    """THE acceptance drill: >= 3 scripted broker disconnects during a
    consumer run (publish-side kills via close_abruptly_on_publish on the
    order feed AND the event publishes, plus one scripted mid-consume
    connection kill) — the resulting matchOrder stream must be
    byte-identical to a fault-free oracle run on the memory bus, and the
    supervisors must report the reconnects."""
    from gome_tpu.bus import QueueBus
    from gome_tpu.bus.memory import MemoryQueue
    from gome_tpu.utils.streams import multi_symbol_stream

    orders = list(
        multi_symbol_stream(n=120, n_symbols=4, seed=11, cancel_prob=0.2)
    )

    # Oracle: fault-free run on the in-process bus.
    oracle_engine = _mk_engine()
    oracle_bus = QueueBus(MemoryQueue("doOrder"), MemoryQueue("matchOrder"))
    oracle = _run_flow(oracle_engine, oracle_bus, orders)
    assert oracle, "oracle run produced no match events"

    # Fault run: every connection dies at its 9th publish (order feed AND
    # match-event publishes), plus one scripted consumer-connection kill
    # partway through the drain.
    broker = FakeBroker(close_abruptly_on_publish=9).start()
    try:
        bus = QueueBus(
            make_queue("doOrder", broker), make_queue("matchOrder", broker)
        )
        engine = _mk_engine()
        kills = {"consume": 0}

        def mid_kill(committed):
            if committed >= 40 and not kills["consume"]:
                kills["consume"] = broker.kill_connections(
                    consuming="doOrder"
                )

        got = _run_flow(engine, bus, orders, mid_kill=mid_kill)
        assert got == oracle  # no lost fills, no duplicated fills
        assert kills["consume"] == 1  # the mid-consume kill really fired
        reconnects = sum(
            q.supervisor().snapshot()["connects_total"] for q in
            (bus.order_queue, bus.match_queue)
        )
        assert reconnects >= 5  # >= 3 disconnects across the run
        bus.order_queue.close()
        bus.match_queue.close()
    finally:
        broker.stop()


# --- degraded mode + health/metrics surfaces ------------------------------


def test_gateway_degraded_mode_backpressure_and_recovery():
    """Bus down: accepted orders spill (bounded); when the spill cap is
    hit DoOrder answers the RETRYABLE status and unmarks; when the bus
    recovers the spill drains in order and acceptance resumes."""
    from gome_tpu.api import order_pb2 as pb
    from gome_tpu.service.batcher import FrameBatcher
    from gome_tpu.service.gateway import CODE_RETRYABLE, OrderGateway

    class FlakyQueue:
        def __init__(self):
            self.up = True
            self.published = []

        def publish(self, body):
            if not self.up:
                raise ConnectionError("bus down")
            self.published.append(body)

    q = FlakyQueue()
    batcher = FrameBatcher(
        q, max_n=4, max_wait_s=0.01, spill_max_frames=2,
        retry_interval_s=0.01,
    )
    marks = set()
    gw = OrderGateway(
        bus=None, accuracy=2,
        mark=lambda o: marks.add(o.oid),
        unmark=lambda o: marks.discard(o.oid),
        batcher=batcher,
    )

    def req(i):
        return pb.OrderRequest(
            uuid="u", oid=str(i), symbol="eth2usdt", transaction=1,
            price=1.0, volume=1.0, kind=1,
        )

    q.up = False
    i = 0
    deadline = time.monotonic() + 10
    r = None
    while time.monotonic() < deadline:
        r = gw.DoOrder(req(i), None)
        i += 1
        if r.code == CODE_RETRYABLE:
            break
        time.sleep(0.005)
    assert r is not None and r.code == CODE_RETRYABLE
    assert str(i - 1) not in marks  # rejected order was unmarked
    st = batcher.stats()
    assert st["degraded"] and st["spill_depth"] >= 2
    q.up = True  # bus recovers: spill drains, acceptance resumes
    deadline = time.monotonic() + 10
    while batcher.stats()["spill_depth"] and time.monotonic() < deadline:
        time.sleep(0.01)
    assert batcher.stats()["spill_depth"] == 0
    assert not batcher.degraded
    assert gw.DoOrder(req(999), None).code == 0
    batcher.close()
    assert q.published  # every spilled frame made it out
    from gome_tpu.utils.metrics import REGISTRY

    text = REGISTRY.render()
    assert "gome_gateway_spill_depth" in text
    assert "gome_gateway_retryable_rejects_total" in text


def test_healthz_reports_connections_and_breaker_transitions():
    """/healthz (health.HealthMonitor) folds per-connection supervisor
    state in; /metrics carries the per-connection gauges; a breaker that
    opened shows its transitions."""
    from gome_tpu.service.health import HealthMonitor
    from gome_tpu.utils.metrics import REGISTRY
    from gome_tpu.utils.resilience import CircuitBreaker, Supervised

    breaker = CircuitBreaker(failure_threshold=2, reset_timeout_s=60.0)

    def dead_factory():
        raise ConnectionRefusedError("down")

    sup = Supervised(
        "drill:conn", dead_factory,
        policy=BackoffPolicy(base_s=0.001, max_s=0.002, max_retries=3,
                             budget_s=5),
        breaker=breaker, sleep=lambda s: None,
    )
    with pytest.raises(ConnectionError):
        sup.get()
    assert breaker.state == "open"
    assert ("closed", "open") in breaker.transitions

    class _Stub:  # minimal EngineService shape for HealthMonitor
        pass

    svc = _Stub()
    svc.consumer = _Stub(); svc.consumer._thread = None
    svc.feed = _Stub(); svc.feed._thread = None

    class _Q:
        def end_offset(self): return 0
        def committed(self): return 0

    svc.bus = _Stub(); svc.bus.order_queue = _Q(); svc.bus.match_queue = _Q()
    eng = _Stub(); eng.batch = _Stub()
    eng.batch.symbols = {}; eng.batch.max_slots = 1
    stats = _Stub(); stats.orders = 0; stats.cap_escalations = 0
    stats.device_calls = 0
    eng.batch.stats = stats
    svc.engine = eng
    svc.gateway = _Stub()

    h = HealthMonitor(svc).check()
    conns = h.detail["connections"]
    assert "drill:conn" in conns
    assert conns["drill:conn"]["breaker"] == "open"
    assert h.detail["degraded"] is True
    text = REGISTRY.render()
    assert "gome_conn_breaker_state_drill_conn 2" in text
    sup.close()

"""Dense gather/scatter grids (batch.dense_batch_step): compact packing of
live lanes with row->lane indirection, deep time axes for hot symbols, and
escalation/rebasing interplay — all pinned against the oracle and the
full-grid path."""

import jax.numpy as jnp
import numpy as np

from gome_tpu.engine import BatchEngine, BookConfig
from gome_tpu.oracle import OracleEngine
from gome_tpu.types import Action, Order, Side
from gome_tpu.utils.streams import multi_symbol_stream


def _run_columnar(eng, orders, chunk=64):
    got = []
    for i in range(0, len(orders), chunk):
        got.extend(eng.process_columnar(orders[i : i + chunk]).to_results())
    return got


def _oracle_events(orders):
    oracle = OracleEngine()
    out = []
    for o in orders:
        out.extend(oracle.process(o))
    return out


def test_dense_grid_selected_and_matches_oracle():
    """Few live symbols in a wide engine: the columnar path must pick the
    dense grid (device work tracks live lanes) and reproduce the oracle's
    event stream exactly."""
    orders = multi_symbol_stream(n=300, n_symbols=5, seed=9, cancel_prob=0.2)
    eng = BatchEngine(
        BookConfig(cap=64, max_fills=8), n_slots=512, max_t=16
    )
    got = _run_columnar(eng, orders)
    assert got == _oracle_events(orders)
    eng.verify_books()


def test_dense_vs_full_grid_identical():
    """dense=True and dense=False produce byte-identical event streams and
    book state on the same stream."""
    orders = multi_symbol_stream(n=400, n_symbols=7, seed=3, cancel_prob=0.15)
    results = []
    books = []
    for dense in (True, False):
        eng = BatchEngine(
            BookConfig(cap=64, max_fills=8), n_slots=256, max_t=8,
            dense=dense,
        )
        results.append(_run_columnar(eng, orders, chunk=96))
        books.append(eng.lane_books())
    assert results[0] == results[1]
    for a, b in zip(books[0], books[1]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_dense_deep_time_axis_single_symbol():
    """One hot symbol with hundreds of ops per batch: the dense grid packs
    far deeper than max_t (one device call instead of dozens) with exact
    semantics — the config 1-2 latency path."""
    rng = np.random.default_rng(12)
    orders = []
    for i in range(600):
        orders.append(
            Order(
                uuid="u", oid=str(i), symbol="hot",
                side=Side(int(rng.integers(0, 2))),
                price=100 + int(rng.integers(-5, 6)),
                volume=int(rng.integers(1, 10)),
            )
        )
    eng = BatchEngine(BookConfig(cap=128, max_fills=16), n_slots=64, max_t=4)
    calls_before = eng.stats.device_calls
    got = _run_columnar(eng, orders, chunk=600)
    # 600 ops, one lane: full grids would need ceil(600/4)=150 device calls;
    # dense packs t_grid=min(1024, next_pow2(600))=1024 -> ONE call.
    assert eng.stats.device_calls - calls_before == 1
    assert got == _oracle_events(orders)
    eng.verify_books()


def test_dense_with_cap_escalation():
    """Book overflow inside a dense grid: cap escalation replays the dense
    grid from the snapshot; results stay exact."""
    orders = [
        Order(uuid="u", oid=str(i), symbol="s", side=Side.SALE,
              price=100 + i, volume=1)
        for i in range(40)  # 40 resting asks > cap 8
    ]
    orders.append(
        Order(uuid="u", oid="t", symbol="s", side=Side.BUY, price=200,
              volume=100)  # sweeps all 40 levels (> max_fills too)
    )
    eng = BatchEngine(BookConfig(cap=8, max_fills=4), n_slots=64, max_t=4)
    got = _run_columnar(eng, orders, chunk=len(orders))
    assert got == _oracle_events(orders)
    assert eng.stats.cap_escalations >= 1
    assert eng.stats.fill_record_escalations >= 1
    eng.verify_books()


def test_dense_int32_rebasing_btc_scale():
    """Dense grids + int32 rebasing at BTC-scale prices (1e13 ticks)."""
    BTC = 10_000_000_000_000
    rng = np.random.default_rng(7)
    orders = []
    for i in range(200):
        sym = f"sym{int(rng.integers(0, 3))}"
        is_del = i > 30 and rng.random() < 0.2
        orders.append(
            Order(
                uuid="u", oid=str(rng.integers(1, i) if is_del else i),
                symbol=sym, side=Side(int(rng.integers(0, 2))),
                price=BTC + int(rng.integers(-1000, 1000)),
                volume=int(rng.integers(1, 20)),
                action=Action.DEL if is_del else Action.ADD,
            )
        )
    eng = BatchEngine(
        BookConfig(cap=64, max_fills=8, dtype=jnp.int32),
        n_slots=128, max_t=8,
    )
    got = _run_columnar(eng, orders, chunk=70)
    assert got == _oracle_events(orders)
    eng.verify_books()


def test_small_mesh_falls_back_to_full_grid():
    """Dense grids DO run under a mesh (per-shard row blocks inside
    shard_map, parallel.mesh.sharded_dense_step) — but only when the
    per-shard row bucket is a win. Here n_slots=8 over a 4-way mesh makes
    r_s * d >= n_slots for any live set, so _grid_geometry must fall back
    to the full sharded grid; events stay oracle-exact either way."""
    from gome_tpu.parallel import make_mesh

    mesh = make_mesh(4)
    eng = BatchEngine(
        BookConfig(cap=16, max_fills=4), n_slots=8, max_t=8, mesh=mesh
    )
    orders = multi_symbol_stream(n=60, n_symbols=3, seed=2, cancel_prob=0.1)
    got = _run_columnar(eng, orders, chunk=60)
    assert got == _oracle_events(orders)


def test_grid_geometry_ratchets_are_grow_only():
    """Compiled grid shapes must not oscillate across pow2 buckets as the
    live-lane count / depth hovers at a boundary — one fresh XLA compile
    costs more than thousands of frames of matching (the service bench's
    mid-run-compile regression)."""
    import numpy as np

    from gome_tpu.engine import BatchEngine, BookConfig

    eng = BatchEngine(BookConfig(cap=16, max_fills=4), n_slots=128, max_t=8)
    shapes = []
    for live_n in (9, 17, 9, 33, 9, 17):
        use_dense, n_rows, _, _ = eng._grid_geometry(
            np.arange(live_n, dtype=np.int64)
        )
        assert use_dense
        shapes.append(n_rows)
    assert shapes == [16, 32, 32, 64, 64, 64]  # never shrinks
    # Ratchet capped below n_slots: growing past it falls back to full.
    use_dense, n_rows, _, _ = eng._grid_geometry(np.arange(127, dtype=np.int64))
    assert not use_dense and n_rows == eng.n_slots

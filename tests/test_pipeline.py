"""Cross-frame pipelining (engine.pipeline.FramePipeline + the consumer's
pipeline_depth): the pipelined executor must produce the IDENTICAL event
stream and book state as the synchronous frame path, including through
budget escalations mid-pipeline, hard failures (at-least-once replay with
pre-pool-mark restoration), and publish failures of resolved frames."""

import numpy as np
import pytest

from gome_tpu.bus import MemoryQueue, QueueBus
from gome_tpu.engine import frames as engine_frames
from gome_tpu.engine.book import BookConfig
from gome_tpu.engine.orchestrator import MatchEngine
from gome_tpu.engine.pipeline import FramePipeline
from gome_tpu.oracle import OracleEngine
from gome_tpu.service.consumer import OrderConsumer
from gome_tpu.types import Order, Side
from gome_tpu.utils.streams import multi_symbol_stream

from test_frames import orders_to_frame


def _frames_for(orders, chunk):
    from gome_tpu.bus import colwire

    payloads = []
    for i in range(0, len(orders), chunk):
        payloads.append(orders_to_frame(orders[i : i + chunk]))
        assert colwire.is_frame(payloads[-1])
    return payloads


def _make(engine_kw, depth):
    engine = MatchEngine(**engine_kw)
    bus = QueueBus(MemoryQueue("doOrder"), MemoryQueue("matchOrder"))
    consumer = OrderConsumer(
        engine, bus, batch_n=4, batch_wait_s=0, match_wire="json",
        pipeline_depth=depth,
    )
    return engine, bus, consumer


def _run(engine_kw, orders, chunk, depth):
    engine, bus, consumer = _make(engine_kw, depth)
    for o in orders:
        engine.mark(o)
    for p in _frames_for(orders, chunk):
        bus.order_queue.publish(p)
    n = consumer.drain()
    msgs = bus.match_queue.read_from(0, 1 << 20)
    return engine, n, [m.body for m in msgs]


def _assert_books_equal(a: MatchEngine, b: MatchEngine):
    ba, bb = a.batch.lane_books(), b.batch.lane_books()
    for name in ("price", "lots", "seq", "count", "next_seq"):
        np.testing.assert_array_equal(
            np.asarray(getattr(ba, name)), np.asarray(getattr(bb, name))
        )
    assert a.pre_pool == b.pre_pool


def _oracle_lines(orders):
    # The consumer stamps every published event with the matchfeed seq
    # (ISSUE 11 exactly-once), so the expected wire carries the same
    # contiguous "Seq" fields the reference-shaped body lacks.
    from dataclasses import replace

    from gome_tpu.bus import encode_match_result

    oracle = OracleEngine()
    out = []
    for o in orders:
        for r in oracle.process(o):
            out.append(encode_match_result(replace(r, seq=len(out))))
    return out


ENGINE_KW = dict(
    config=BookConfig(cap=32, max_fills=8), n_slots=16, max_t=8
)


@pytest.mark.parametrize("depth", [1, 2, 3])
def test_pipelined_consumer_matches_synchronous(depth):
    orders = multi_symbol_stream(n=300, n_symbols=5, seed=11, cancel_prob=0.2)
    sync_eng, n_sync, sync_events = _run(ENGINE_KW, orders, 40, 0)
    pipe_eng, n_pipe, pipe_events = _run(ENGINE_KW, orders, 40, depth)
    assert n_pipe == n_sync == len(orders)
    assert pipe_events == sync_events == _oracle_lines(orders)
    _assert_books_equal(pipe_eng, sync_eng)
    pipe_eng.batch.verify_books()


def test_pipelined_escalation_mid_pipeline():
    """A frame in the middle of the in-flight span trips device budgets
    (book overflow + record truncation): the pipeline must rewind, re-run
    exactly, resubmit the later frames, and still match the oracle."""
    orders = [
        Order(uuid="u", oid=str(i), symbol="s", side=Side.SALE,
              price=100 + i, volume=1)
        for i in range(40)  # overflows cap=8
    ]
    orders.append(
        Order(uuid="u", oid="sweep", symbol="s", side=Side.BUY, price=300,
              volume=1000)  # 40 fills > max_fills=4
    )
    orders += [
        Order(uuid="u", oid=f"post{i}", symbol="s2",
              side=Side(int(i % 2)), price=200 + (i % 3), volume=2)
        for i in range(30)
    ]
    kw = dict(config=BookConfig(cap=8, max_fills=4), n_slots=8, max_t=4)
    sync_eng, _, sync_events = _run(kw, orders, 10, 0)
    pipe_eng, _, pipe_events = _run(kw, orders, 10, 3)
    assert pipe_events == sync_events == _oracle_lines(orders)
    assert pipe_eng.stats.cap_escalations >= 1
    _assert_books_equal(pipe_eng, sync_eng)
    pipe_eng.batch.verify_books()


def test_pipeline_hard_failure_restores_marks_and_replays(monkeypatch):
    """A hard failure at resolve time must leave no trace: books rewound to
    the failed frame's checkpoint, its and every later in-flight frame's
    pre-pool marks restored — so the consumer's at-least-once replay from
    the uncommitted offset converges to the synchronous result."""
    orders = multi_symbol_stream(n=200, n_symbols=4, seed=3, cancel_prob=0.15)
    sync_eng, _, sync_events = _run(ENGINE_KW, orders, 25, 0)

    engine, bus, consumer = _make(ENGINE_KW, 2)
    for o in orders:
        engine.mark(o)
    for p in _frames_for(orders, 25):
        bus.order_queue.publish(p)

    real = engine_frames.resolve_frame
    fail = {"left": 2}

    def flaky(eng, pend):
        if fail["left"] > 0:
            fail["left"] -= 1
            raise RuntimeError("injected resolve failure")
        return real(eng, pend)

    monkeypatch.setattr(engine_frames, "resolve_frame", flaky)
    total = 0
    end = bus.order_queue.end_offset()
    for _ in range(200):
        total += consumer.step_with_policy()
        if bus.order_queue.committed() >= end:
            break
    assert bus.order_queue.committed() == end
    assert total == len(orders)
    msgs = bus.match_queue.read_from(0, 1 << 20)
    assert [m.body for m in msgs] == sync_events
    _assert_books_equal(engine, sync_eng)
    engine.batch.verify_books()


def test_pipeline_submit_failure_restores_own_marks(monkeypatch):
    """feed() failing at submit must restore THAT frame's consumed marks and
    leave earlier in-flight frames untouched."""
    orders = multi_symbol_stream(n=60, n_symbols=3, seed=7, cancel_prob=0.1)
    engine = MatchEngine(**ENGINE_KW)
    for o in orders:
        engine.mark(o)
    pipe = FramePipeline(engine, depth=4)
    from gome_tpu.bus import colwire

    payloads = _frames_for(orders, 20)
    cols0 = colwire.decode_order_frame(payloads[0])
    pipe.feed(cols0, token=0)
    marks_after_first = set(engine.pre_pool)

    def boom(eng, cols):
        raise RuntimeError("injected submit failure")

    monkeypatch.setattr(engine_frames, "submit_frame", boom)
    cols1 = colwire.decode_order_frame(payloads[1])
    with pytest.raises(RuntimeError):
        pipe.feed(cols1, token=1)
    # Frame 1's marks restored; frame 0 still in flight with its marks
    # consumed.
    assert engine.pre_pool == marks_after_first
    assert len(pipe) == 1


def test_pipeline_abort_restores_in_flight_span():
    orders = multi_symbol_stream(n=80, n_symbols=3, seed=9, cancel_prob=0.1)
    engine = MatchEngine(**ENGINE_KW)
    for o in orders:
        engine.mark(o)
    marks0 = set(engine.pre_pool)
    pipe = FramePipeline(engine, depth=8)
    from gome_tpu.bus import colwire

    for i, p in enumerate(_frames_for(orders, 20)):
        pipe.feed(colwire.decode_order_frame(p), token=i)
    assert len(pipe) == 4
    pipe.abort()
    assert len(pipe) == 0
    assert engine.pre_pool == marks0
    ref = MatchEngine(**ENGINE_KW)
    for o in orders:
        ref.mark(o)
    _assert_books_equal(engine, ref)


def test_pipelined_publish_failure_aborts_and_replays():
    """The match queue failing while a resolved frame publishes must not
    wedge the consumer: the in-flight span aborts (marks restored) and the
    replay converges. Events of the frame whose publish failed are lost —
    the same window the synchronous path has (publish-after-process)."""

    class FlakyQueue(MemoryQueue):
        def __init__(self, name):
            super().__init__(name)
            self.fail_left = 1

        def publish_batch(self, bodies):
            if self.fail_left > 0 and bodies:
                self.fail_left -= 1
                raise RuntimeError("injected publish failure")
            return super().publish_batch(bodies)

    orders = multi_symbol_stream(n=150, n_symbols=4, seed=5, cancel_prob=0.1)
    engine = MatchEngine(**ENGINE_KW)
    bus = QueueBus(MemoryQueue("doOrder"), FlakyQueue("matchOrder"))
    consumer = OrderConsumer(
        engine, bus, batch_n=4, batch_wait_s=0, match_wire="json",
        pipeline_depth=2,
    )
    for o in orders:
        engine.mark(o)
    for p in _frames_for(orders, 30):
        bus.order_queue.publish(p)
    end = bus.order_queue.end_offset()
    for _ in range(200):
        consumer.step_with_policy()
        if bus.order_queue.committed() >= end:
            break
    assert bus.order_queue.committed() == end
    engine.batch.verify_books()
    # Books equal the synchronous end state (the failed frame WAS applied;
    # only its events were lost to the failed publish).
    sync_eng, _, _ = _run(ENGINE_KW, orders, 30, 0)
    ba, bb = engine.batch.lane_books(), sync_eng.batch.lane_books()
    for name in ("price", "lots", "count"):
        np.testing.assert_array_equal(
            np.asarray(getattr(ba, name)), np.asarray(getattr(bb, name))
        )


def test_checkpoint_restorable_twice_after_interim_mutation():
    """FramePipeline's recovery restores the SAME checkpoint twice with an
    exact re-run mutating host rebasing state in between — the second
    restore must return the pristine snapshot, not the interim mutations
    (i.e. _restore must copy, never alias, the mutable arrays)."""
    import jax.numpy as jnp

    from gome_tpu.engine import BatchEngine

    BTC = 10_000_000_000_000
    eng = BatchEngine(
        BookConfig(cap=8, max_fills=4, dtype=jnp.int32), n_slots=4, max_t=4
    )
    cp = eng._checkpoint()
    base0 = eng.price_base.copy()
    set0 = eng._base_set.copy()
    eng._restore(cp)
    # Interim work (the exact re-run) rebases a lane in place.
    eng.process([
        Order(uuid="u", oid="1", symbol="btc", side=Side.BUY, price=BTC,
              volume=5)
    ])
    assert eng._base_set.any()
    eng._restore(cp)  # second restore of the SAME checkpoint
    np.testing.assert_array_equal(eng.price_base, base0)
    np.testing.assert_array_equal(eng._base_set, set0)


def test_pipelined_persist_hook_fires_only_at_consistent_cuts():
    """on_batch (the persist snapshot hook) must only observe states where
    the books correspond exactly to the committed offset — i.e. no frames
    in flight; counts accumulate across the in-flight span."""
    orders = multi_symbol_stream(n=200, n_symbols=4, seed=17, cancel_prob=0.1)
    engine = MatchEngine(**ENGINE_KW)
    bus = QueueBus(MemoryQueue("doOrder"), MemoryQueue("matchOrder"))
    calls = []
    consumer = OrderConsumer(
        engine, bus, batch_n=4, batch_wait_s=0, match_wire="json",
        pipeline_depth=2,
        on_batch=lambda n, e: calls.append(
            (n, e, len(consumer._pipe) if consumer._pipe else 0)
        ),
    )
    for o in orders:
        engine.mark(o)
    for p in _frames_for(orders, 25):
        bus.order_queue.publish(p)
    n = consumer.drain()
    assert n == len(orders)
    assert sum(c[0] for c in calls) == len(orders)
    assert all(c[2] == 0 for c in calls), calls


def test_pipeline_mixed_json_and_frames():
    """JSON messages interleaved with ORDER frames drain the pipeline first
    — global order preserved."""
    from gome_tpu.bus import encode_order

    orders = multi_symbol_stream(n=120, n_symbols=4, seed=13, cancel_prob=0.15)
    sync_eng, _, sync_events = _run(ENGINE_KW, orders, 24, 0)

    engine, bus, consumer = _make(ENGINE_KW, 2)
    for o in orders:
        engine.mark(o)
    # Frames for the first 96 orders, JSON for the rest, then one more frame.
    head, mid, tail = orders[:72], orders[72:96], orders[96:]
    for p in _frames_for(head, 24):
        bus.order_queue.publish(p)
    for o in mid:
        bus.order_queue.publish(encode_order(o))
    for p in _frames_for(tail, 24):
        bus.order_queue.publish(p)
    n = consumer.drain()
    assert n == len(orders)
    msgs = bus.match_queue.read_from(0, 1 << 20)
    assert [m.body for m in msgs] == sync_events
    _assert_books_equal(engine, sync_eng)


def test_pipelined_soak_with_persist_crash_restore(tmp_path):
    """The trickiest new interaction: cross-frame pipelining + the persist
    layer's consistent-cut snapshots + crash recovery. A pipelined service
    processes frames with snapshots riding on_batch; a crash (new service
    over the same dirs) restores and replays; the end-to-end match stream
    equals an uninterrupted unpipelined run byte-for-byte."""
    from gome_tpu.config import Config, EngineConfig, PersistConfig, BusConfig
    from gome_tpu.persist import Persister
    from gome_tpu.service.app import EngineService

    orders = multi_symbol_stream(n=1200, n_symbols=20, seed=41,
                                 cancel_prob=0.2)
    frames = _frames_for(orders, 150)

    def feed(svc, payloads, first_frame=0):
        for i, p in enumerate(payloads, start=first_frame):
            # Gateway role: mark THEN publish (main.go:42-48 order).
            for o in orders[i * 150 : i * 150 + 150]:
                svc.engine.mark(o)
            svc.bus.order_queue.publish(p)

    # Uninterrupted reference run (no pipeline, memory bus).
    ref = EngineService(
        Config(engine=EngineConfig(cap=32, max_fills=8, n_slots=32, max_t=8))
    )
    feed(ref, frames)
    ref.consumer.drain()
    ref_events = [
        m.body for m in ref.bus.match_queue.read_from(0, 1 << 20)
    ]

    def make_svc():
        cfg = Config(
            engine=EngineConfig(cap=32, max_fills=8, n_slots=32, max_t=8,
                                pipeline_depth=3),
            bus=BusConfig(backend="file", dir=str(tmp_path / "bus")),
            # every_n_batches=1: in pipelined mode the persist hook fires
            # once per pipeline-empty boundary (a whole drain is ONE
            # consistent cut), so any higher cadence may never snapshot.
            persist=PersistConfig(enabled=True, dir=str(tmp_path / "snap"),
                                  every_n_batches=1),
        )
        return EngineService(cfg, persist=Persister(cfg.persist))

    svc = make_svc()
    svc.persist.restore_latest()
    feed(svc, frames[:5])
    svc.consumer.drain()  # snapshots fire at pipeline-empty cuts
    feed(svc, frames[5:], first_frame=5)
    for _ in range(3):  # partially drain, leaving work + in-flight state
        svc.consumer.run_once()

    # Crash: fresh process over the same dirs.
    svc2 = make_svc()
    assert svc2.persist.restore_latest()
    svc2.consumer.drain()
    got = [m.body for m in svc2.bus.match_queue.read_from(0, 1 << 20)]
    assert got == ref_events
    svc2.engine.batch.verify_books()

"""Regression tests for the round-1 advisor findings (ADVICE.md):

  * a wrong-price cancel (in-contract — the stock delorder client hardcodes
    price 0.5, gomengine/delorder.go) must never widen an int32 lane's
    rebasing envelope or raise; it is a missed cancel (engine.go:92-98);
  * a batch aborted by CapacityError must leave no trace — neither book
    state nor the grow-only envelope;
  * the gateway rejects orders over the int32 lot ceiling at the edge
    (code 3, like volume<=0) instead of letting them poison consumer
    batches;
  * the consumer's poison-batch policy dead-letters a deterministically
    failing order after N replays instead of halting matching forever, and
    a failed batch restores the pre-pool marks it consumed so the replay
    does not drop its ADDs.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from gome_tpu.engine import BatchEngine, BookConfig
from gome_tpu.engine.batch import CapacityError
from gome_tpu.types import Action, Order, Side

BTC = 10_000_000_000_000  # 1e13 ticks = $100k at accuracy 8
WRONG = 50_000_000  # the stock delorder client's hardcoded 0.5 => 5e7 ticks


def _cfg32(**kw):
    return BookConfig(cap=32, max_fills=8, dtype=jnp.int32, **kw)


def _add(oid, price, side=Side.BUY, volume=5, symbol="btc2usdt"):
    return Order(
        uuid="u", oid=oid, symbol=symbol, side=side, price=price,
        volume=volume, action=Action.ADD,
    )


def _del(oid, price, side=Side.BUY, symbol="btc2usdt"):
    return Order(
        uuid="u", oid=oid, symbol=symbol, side=side, price=price,
        volume=0, action=Action.DEL,
    )


@pytest.mark.parametrize("columnar", [False, True])
def test_wrong_price_cancel_never_poisons_lane(columnar):
    """ADVICE #1 (high): DEL at a price unrepresentable under the lane's
    base is a missed cancel, not a CapacityError, and must not widen the
    envelope — a later recenter still succeeds."""
    eng = BatchEngine(_cfg32(), n_slots=2, max_t=32)
    run = (
        (lambda os: eng.process_columnar(os).to_results())
        if columnar
        else eng.process
    )
    # Seed the lane at BTC scale (base ~1e13).
    assert run([_add("a", BTC, side=Side.SALE)]) == []
    # The in-contract wrong-price cancel: |5e7 - 1e13| >> 2^31.
    events = run([_del("a", WRONG)])
    assert events == []
    assert eng.stats.cancels_missed == 1
    # Envelope must not contain the DEL price: a drift past REBASE_LIMIT
    # forces a recenter which would raise forever had it been admitted.
    drift = BatchEngine.REBASE_LIMIT + 100_000
    events = run([_add("b", BTC + drift, side=Side.SALE)])
    assert events == []
    # The originally rested order is still cancellable at its true price.
    events = run([_del("a", BTC, side=Side.SALE)])
    assert len(events) == 1 and events[0].match_volume == 0
    eng.batch.verify_books() if hasattr(eng, "batch") else eng.verify_books()


def test_wrong_price_cancel_mid_batch_with_adds():
    """The dropped DEL shares a batch with packable ops on the same lane —
    packing must skip only the DEL (no slot consumed, no deferral loop)."""
    eng = BatchEngine(_cfg32(), n_slots=2, max_t=32)
    events = eng.process(
        [
            _add("a", BTC, side=Side.SALE, volume=5),
            _del("a", WRONG),  # dropped: unrepresentable
            _add("b", BTC, side=Side.BUY, volume=5),  # fills against a
        ]
    )
    assert len(events) == 1 and events[0].match_volume == 5
    assert eng.stats.cancels_missed == 1
    eng.verify_books()


def test_del_on_fresh_lane_with_huge_price():
    """DEL on a lane with no base set and a price beyond int32: dropped as
    a miss (nothing can be resting), not an overflow or crash."""
    eng = BatchEngine(_cfg32(), n_slots=2, max_t=8)
    assert eng.process([_del("x", BTC)]) == []
    assert eng.stats.cancels_missed == 1


def test_capacity_error_commits_nothing():
    """ADVICE follow-through: an ADD batch that trips the span check raises
    without widening the envelope, so retrying without the offending order
    (and later recentering) succeeds."""
    eng = BatchEngine(_cfg32(), n_slots=2, max_t=8)
    eng.process([_add("a", BTC, side=Side.SALE)])
    with pytest.raises(CapacityError):
        eng.process([_add("bad", 100)])  # 1e13 span: unwindowable
    # Lane not poisoned: drift-forced recenter still succeeds.
    drift = BatchEngine.REBASE_LIMIT + 100_000
    assert eng.process([_add("b", BTC + drift, side=Side.SALE)]) == []
    events = eng.process([_del("a", BTC, side=Side.SALE)])
    assert len(events) == 1
    eng.verify_books()


def test_gateway_rejects_lot_ceiling():
    """ADVICE #2 (medium): the int32 lot ceiling is enforced at the gRPC
    edge with code 3, like the volume<=0 check."""
    from gome_tpu.api import order_pb2 as pb
    from gome_tpu.config import Config, EngineConfig, GrpcConfig
    from gome_tpu.service import EngineService

    svc = EngineService(
        Config(
            grpc=GrpcConfig(port=0),
            engine=EngineConfig(cap=16, n_slots=8, max_t=8, dtype="int32"),
        )
    )
    # accuracy=8: volume 100.0 scales to 1e10 lots > LOT_MAX32 (~1.07e9).
    resp = svc.gateway.DoOrder(
        pb.OrderRequest(
            uuid="u", oid="big", symbol="eth2usdt",
            transaction=pb.BUY, price=1.0, volume=100.0,
        ),
        None,
    )
    assert resp.code == 3 and "ceiling" in resp.message
    ok = svc.gateway.DoOrder(
        pb.OrderRequest(
            uuid="u", oid="ok", symbol="eth2usdt",
            transaction=pb.BUY, price=1.0, volume=1.0,
        ),
        None,
    )
    assert ok.code == 0


def test_consumer_poison_batch_quarantine():
    """ADVICE #2 (medium): a deterministic per-batch failure stops blocking
    after poison_threshold replays — the offending order is dead-lettered,
    healthy neighbors still match, the offset advances, and the failed
    attempts' consumed pre-pool marks are restored for the replay."""
    from gome_tpu.bus import MemoryQueue, QueueBus, encode_order
    from gome_tpu.engine.orchestrator import MatchEngine
    from gome_tpu.engine.step import LOT_MAX32
    from gome_tpu.service.consumer import OrderConsumer

    engine = MatchEngine(config=_cfg32(), n_slots=8, max_t=8)
    bus = QueueBus(MemoryQueue("doOrder"), MemoryQueue("matchOrder"))
    consumer = OrderConsumer(
        engine, bus, batch_n=16, batch_wait_s=0, poison_threshold=3
    )

    good1 = _add("g1", 100, side=Side.SALE, volume=5, symbol="eth2usdt")
    poison = _add(
        "poison", 100, side=Side.BUY, volume=LOT_MAX32 + 1, symbol="eth2usdt"
    )
    good2 = _add("g2", 100, side=Side.BUY, volume=5, symbol="eth2usdt")
    for o in (good1, poison, good2):
        engine.mark(o)
        bus.order_queue.publish(encode_order(o))

    # Two failed replays (policy not yet tripped), third triggers quarantine.
    assert consumer.step_with_policy() == 0
    assert bus.order_queue.committed() == 0
    assert consumer.step_with_policy() == 0
    n = consumer.step_with_policy()
    assert n == 2  # good1 + good2 processed individually
    assert bus.order_queue.committed() == 3  # stream advanced past poison
    # good2 crossed good1: exactly one fill event published.
    msgs = bus.match_queue.read_from(0, 10)
    assert len(msgs) == 1
    # Subsequent batches are healthy again.
    ok = _add("g3", 100, side=Side.BUY, volume=1, symbol="eth2usdt")
    engine.mark(ok)
    bus.order_queue.publish(encode_order(ok))
    assert consumer.step_with_policy() == 1


def test_failed_batch_restores_prepool_marks():
    """A batch that raises must put back the pre-pool keys it consumed so
    the at-least-once replay does not drop its ADDs as unmarked."""
    from gome_tpu.engine.orchestrator import MatchEngine

    engine = MatchEngine(config=_cfg32(), n_slots=8, max_t=8)
    good = _add("g", BTC, side=Side.SALE, volume=5)
    bad = _add("bad", 100, volume=5)  # forces CapacityError with BTC
    engine.mark(good)
    engine.mark(bad)
    with pytest.raises(CapacityError):
        engine.process([good, bad])
    # Replay without the poison order: the ADD must still be marked.
    assert engine.process([good]) == []
    assert engine.stats.dropped_no_prepool == 0
    assert len(engine.process([_del("g", BTC, side=Side.SALE)])) == 1


def test_failed_multigrid_batch_rolls_back_first_grid():
    """A batch split over several grids (max_t overflow on one lane) that
    raises on a later grid must roll the device books back past the already
    committed earlier grids — otherwise the at-least-once replay
    double-applies grid 1's orders."""
    config = BookConfig(cap=2, max_fills=8, dtype=jnp.int32)
    eng = BatchEngine(config, n_slots=2, max_t=2, max_cap=2)
    # Grid 1: two resting SALEs fill lane 0's time axis AND the cap-2 book.
    # Grid 2: the third rest overflows, cap escalation needs 4 > max_cap=2,
    # CapacityError — AFTER grid 1 already committed device books.
    batch = [
        _add("a", BTC, side=Side.SALE, volume=5),
        _add("b", BTC + 1, side=Side.SALE, volume=5),
        _add("c", BTC + 2, side=Side.SALE, volume=5),
    ]
    with pytest.raises(CapacityError):
        eng.process(batch)
    # Books rolled back: nothing rests.
    assert int(np.asarray(eng.books.count).sum()) == 0
    # Replay without the poison order applies each ADD exactly once.
    assert eng.process(batch[:2]) == []
    events = eng.process([_add("t", BTC + 1, side=Side.BUY, volume=10)])
    assert [e.match_volume for e in events] == [5, 5]
    eng.verify_books()


def test_x64_flip_refused_after_pallas_import():
    """ADVICE #4 (low): ensure_dtype_usable must not flip jax_enable_x64
    once the Pallas kernel module is loaded (mid-process flips can corrupt
    trace caches). With x64 already on (this suite's conftest), the check
    is a no-op; the refusal path is covered by a subprocess check in
    scripts/fuzz.py's docstring contract."""
    import sys

    import gome_tpu.ops.pallas_match  # noqa: F401  (ensure loaded)
    from gome_tpu.engine.book import ensure_dtype_usable

    assert "gome_tpu.ops.pallas_match" in sys.modules
    ensure_dtype_usable(jnp.int64)  # x64 already on: fine


# -- round-4 advisor findings ------------------------------------------------


def test_batcher_submit_after_close_raises():
    """ADVICE r4 (low): submit() after close() must fail loudly — the
    deadline thread is gone, so a silently buffered order below max_n
    would be stranded forever."""
    from gome_tpu.service.batcher import FrameBatcher

    class _Sink:
        def __init__(self):
            self.frames = []

        def publish(self, data):
            self.frames.append(data)

    sink = _Sink()
    b = FrameBatcher(sink, max_n=100, max_wait_s=10.0)
    b.submit(_add("a", 100))
    b.close()  # flushes the remainder
    assert len(sink.frames) == 1
    with pytest.raises(RuntimeError, match="closed"):
        b.submit(_add("b", 100))
    assert len(sink.frames) == 1  # nothing buffered, nothing stranded


def test_colwire_dict_cache_lru_not_wholesale_clear():
    """ADVICE r4 (low): >32 live dictionaries must evict one-at-a-time
    (LRU), not clear() the whole cache — a hot dictionary stays cached
    across an eviction storm."""
    from gome_tpu.bus import colwire

    colwire._dict_cache.clear()
    hot = _add("h", 100, symbol="hot2usdt")
    hot_frame = colwire.encode_orders([hot])
    colwire.decode_order_frame(hot_frame)
    hot_keys = set(colwire._dict_cache)  # symbol dict + uuid dict
    assert len(hot_keys) == 2
    # Storm: > _DICT_CACHE_MAX distinct dictionaries, re-touching the hot
    # frame after each — under LRU the hot entry survives the storm.
    for i in range(colwire._DICT_CACHE_MAX + 8):
        cold = _add("c", 100, symbol=f"cold{i}2usdt")
        colwire.decode_order_frame(colwire.encode_orders([cold]))
        colwire.decode_order_frame(hot_frame)  # refresh
    assert hot_keys <= set(colwire._dict_cache)
    assert len(colwire._dict_cache) <= colwire._DICT_CACHE_MAX


def test_amqp_send_survives_one_stalled_window():
    """ADVICE r4 (low): the heartbeat-expiry recv timeout also bounds
    writes; one zero-progress send window on a slow-but-alive link must
    NOT kill the connection — only two consecutive stalled windows do."""
    import socket as socket_mod

    from gome_tpu.bus.amqp import AmqpQueue

    class _SlowSock:
        """send() times out `stall_windows` times, then accepts bytes in
        small chunks; gettimeout() reports a tiny window so the aggregate
        deadline math runs (and, for the trickle test, expires fast)."""

        def __init__(self, stall_windows, timeout=0.05, chunk=3):
            self.sent = bytearray()
            self._stalls = stall_windows
            self._timeout = timeout
            self._chunk = chunk

        def gettimeout(self):
            return self._timeout

        def send(self, mv):
            if self._stalls > 0:
                self._stalls -= 1
                raise socket_mod.timeout("stalled window")
            n = min(self._chunk, len(mv))
            self.sent.extend(bytes(mv[:n]))
            return n

        def close(self):
            pass

    q = AmqpQueue.__new__(AmqpQueue)
    q._closed = False
    q._sock = _SlowSock(stall_windows=1)
    q._send(b"hello world payload")
    assert bytes(q._sock.sent) == b"hello world payload"
    assert not q._closed

    q2 = AmqpQueue.__new__(AmqpQueue)
    q2._closed = False
    q2._sock = _SlowSock(stall_windows=2)
    with pytest.raises(ConnectionError):
        q2._send(b"hello world payload")
    assert q2._closed  # two consecutive dead windows: connection failed


def test_amqp_send_trickle_hits_aggregate_deadline():
    """Code-review follow-up: progress must not equal liveness. A peer
    accepting one byte per (slow) window resets the stall counter every
    time, but the per-frame aggregate deadline (2 windows + 64KB/s floor)
    still fails the connection instead of wedging the write lock."""
    import socket as socket_mod
    import time as time_mod

    from gome_tpu.bus.amqp import AmqpQueue

    class _TrickleSock:
        def __init__(self):
            self.sent = 0

        def gettimeout(self):
            return 0.01  # tiny window => deadline ~0.02s + len/64K

        def send(self, mv):
            time_mod.sleep(0.005)
            self.sent += 1
            return 1  # one byte per call: "progress", never a timeout

        def close(self):
            pass

    q = AmqpQueue.__new__(AmqpQueue)
    q._closed = False
    q._sock = _TrickleSock()
    start = time_mod.monotonic()
    with pytest.raises(ConnectionError, match="floor rate"):
        q._send(b"x" * 4096)
    assert time_mod.monotonic() - start < 5.0  # bounded, not 4096 windows
    assert q._closed


def test_amqp_reader_death_preserves_delivered_reply():
    """ADVICE r4 (low): a reply stored just before the reader dies must
    survive — the failure path sets the event without nulling the slot,
    and _rpc nulls the slot before each send instead. This drives the
    REAL _read_loop over a socketpair: the broker side delivers a valid
    ConsumeOk method frame and immediately drops the connection."""
    import socket as socket_mod
    import threading
    import time as time_mod

    from gome_tpu.bus.amqp import AmqpQueue, frame, method, FRAME_METHOD

    broker_side, client_side = socket_mod.socketpair()
    q = AmqpQueue.__new__(AmqpQueue)
    q._init_wait()
    q._closed = False
    q._sock = client_side
    q._heartbeat = 0
    q._pending_deliver = None
    q._buffer, q._tags = [], []
    q._lock = threading.RLock()
    q._rpc_lock = threading.Lock()
    q._rpc_event = threading.Event()
    q._rpc_expect = ((60, 21), 7)  # an rpc (token 7) awaits ConsumeOk
    q._rpc_reply = None
    reader = threading.Thread(target=q._read_loop, daemon=True)
    reader.start()

    # Reply frame, then immediate peer death (EOF -> ConnectionError).
    broker_side.sendall(frame(FRAME_METHOD, 1, method(60, 21)))
    broker_side.close()
    reader.join(timeout=5)
    assert not reader.is_alive()
    # The delivered ConsumeOk survived the reader's death path, with the
    # waiter's correlation token echoed back.
    assert q._rpc_event.is_set()
    assert q._rpc_reply is not None
    token, reply = q._rpc_reply
    assert token == 7 and reply[:2] == (60, 21)
    assert q._closed
    client_side.close()


def test_amqp_stale_reply_never_crosses_rpcs():
    """Code-review follow-up: a late reply from a timed-out RPC must not
    be handed to the NEXT rpc as its answer — even a retry of the SAME
    method (Basic.Consume after a ConsumeOk timeout). _rpc clears
    _rpc_expect on every exit and correlates replies by per-RPC token."""
    import threading

    from gome_tpu.bus.amqp import AmqpQueue

    class _NullSock:
        def gettimeout(self):
            return None

        def send(self, mv):
            return len(mv)

        def close(self):
            pass

    q = AmqpQueue.__new__(AmqpQueue)
    q._closed = False
    q._sock = _NullSock()
    q._lock = threading.RLock()
    q._rpc_lock = threading.Lock()
    q._rpc_event = threading.Event()
    q._rpc_expect = None
    q._rpc_reply = None
    q._rpc_seq = 0
    q.SYNC_WAIT_S = 0.05

    # RPC #1 (token 1) times out. The reply is now an untracked
    # in-flight frame no tag can resynchronize, so the TIMEOUT FAILS THE
    # CONNECTION — a same-method retry on this connection is refused
    # outright instead of being allowed to adopt the late reply.
    with pytest.raises(ConnectionError, match="timeout"):
        q._rpc((60, 21), b"")
    assert q._rpc_expect is None
    assert q._closed
    with pytest.raises(ConnectionError, match="closed"):
        q._rpc((60, 21), b"")

    # Defense-in-depth: even on a live connection, a reply stored with a
    # previous RPC's token (descheduled reader racing the slot reset)
    # fails the token check instead of being returned to the wrong call.
    q._closed = False
    def _late_reply():
        q._rpc_reply = (1, (60, 21, b"stale"))
        q._rpc_event.set()

    threading.Timer(0.01, _late_reply).start()
    with pytest.raises(ConnectionError, match="stale"):
        q._rpc((60, 21), b"")


def test_gateway_rejects_when_batcher_closed_and_unmarks():
    """Code-review follow-up: a DoOrder racing FrameBatcher.close() must
    return a rejection (not crash the handler with gRPC UNKNOWN) and must
    undo its pre-pool mark — the order was never published, so nothing
    will ever clear the marker."""
    from gome_tpu.api import order_pb2 as pb
    from gome_tpu.bus import MemoryQueue, QueueBus
    from gome_tpu.service.batcher import FrameBatcher
    from gome_tpu.service.gateway import OrderGateway

    marks = []
    bus = QueueBus(MemoryQueue("doOrder"), MemoryQueue("matchOrder"))
    batcher = FrameBatcher(bus.order_queue, max_n=64, max_wait_s=10.0)
    gw = OrderGateway(
        bus,
        accuracy=8,
        mark=lambda o: marks.append(o.oid),
        unmark=lambda o: marks.remove(o.oid),
        batcher=batcher,
    )
    batcher.close()  # shutdown happened mid-flight
    resp = gw.DoOrder(
        pb.OrderRequest(
            uuid="u", oid="late", symbol="eth2usdt",
            transaction=pb.BUY, price=1.0, volume=1.0,
        ),
        None,
    )
    assert resp.code == 3 and "rejected" in resp.message
    assert marks == []  # the mark was undone, no dangling pre-pool entry
    cancel = gw.DeleteOrder(
        pb.OrderRequest(
            uuid="u", oid="late", symbol="eth2usdt",
            transaction=pb.BUY, price=1.0, volume=0.0,
        ),
        None,
    )
    assert cancel.code == 3

"""Parity tests: the JAX step function vs the Python oracle.

The oracle (tests/test_oracle.py) is the executable spec of the reference's
semantics; here identical order streams are replayed through both engines and
the full MatchResult event streams plus final book depth must agree exactly
(SURVEY §7 step 2; BASELINE metric "fill-price/qty parity").
"""

import jax
import pytest

from gome_tpu.engine import BookConfig, init_book, step
from gome_tpu.engine.book import BUY, SALE, book_depth
from gome_tpu.engine.host import Interner, OpContext, decode_events, encode_op
from gome_tpu.fixed import scale
from gome_tpu.oracle import OracleEngine
from gome_tpu.types import Action, Order, OrderType, Side
from gome_tpu.utils.streams import doorder_stream, mixed_stream


class SingleSymbolHarness:
    """Drives one symbol's device book from Python Orders (the per-test
    stand-in for the host orchestrator)."""

    def __init__(self, config: BookConfig):
        self.config = config
        self.book = init_book(config)
        self.oids = Interner()
        self.uids = Interner()
        self._step = lambda b, op: step(config, b, op)
        self.events = []

    def process(self, order: Order):
        op = encode_op(order, self.oids, self.uids)
        self.book, out = self._step(self.book, op)
        evs = decode_events(
            OpContext(order), jax.device_get(out), self.oids, self.uids
        )
        self.events.extend(evs)
        return evs

    def depth(self, side: Side, max_levels: int = 32):
        prices, volumes, n = jax.device_get(
            book_depth(self.book, int(side), max_levels)
        )
        return [(int(prices[i]), int(volumes[i])) for i in range(int(n))]


CFG = BookConfig(cap=128, max_fills=64)


def run_both(orders, config=CFG):
    oracle = OracleEngine()
    harness = SingleSymbolHarness(config)
    for i, order in enumerate(orders):
        ev_o = oracle.process(order)
        ev_j = harness.process(order)
        assert ev_j == ev_o, (
            f"event mismatch at order {i} ({order.oid}):\n"
            f"oracle: {ev_o}\njax:    {ev_j}"
        )
    sym = orders[0].symbol
    for side in (Side.BUY, Side.SALE):
        assert harness.depth(side, config.cap) == oracle.book(sym).depth(side), (
            f"final depth mismatch on {side}"
        )
    return oracle, harness


def o(oid, side, price, volume, uuid="u1", action=Action.ADD, ot=OrderType.LIMIT):
    return Order(
        uuid=uuid,
        oid=str(oid),
        symbol="s",
        side=side,
        price=scale(price),
        volume=scale(volume),
        action=action,
        order_type=ot,
    )


def test_rest_and_full_cross():
    run_both([o(1, Side.SALE, 1.00, 0.5), o(2, Side.BUY, 1.10, 0.5)])


def test_partial_fill_and_remainder_rests():
    run_both(
        [
            o(1, Side.SALE, 1.00, 0.3),
            o(2, Side.BUY, 1.05, 1.0),  # fills 0.3, rests 0.7 @ 1.05
            o(3, Side.SALE, 1.05, 0.2),  # hits the rested remainder
        ]
    )


def test_multi_level_depth_walk():
    run_both(
        [
            o(1, Side.SALE, 1.00, 0.2),
            o(2, Side.SALE, 1.01, 0.2),
            o(3, Side.SALE, 1.02, 0.2),
            o(4, Side.BUY, 1.05, 0.5),
        ]
    )


def test_fifo_within_level():
    run_both(
        [
            o(1, Side.SALE, 1.00, 0.2, uuid="a"),
            o(2, Side.SALE, 1.00, 0.2, uuid="b"),
            o(3, Side.SALE, 1.00, 0.2, uuid="c"),
            o(4, Side.BUY, 1.00, 0.5),
        ]
    )


def test_cancel_partial_then_refill():
    run_both(
        [
            o(1, Side.SALE, 1.00, 1.0),
            o(2, Side.BUY, 1.00, 0.4),
            o(1, Side.SALE, 1.00, 1.0, action=Action.DEL),
            o(3, Side.SALE, 1.00, 0.5),
            o(4, Side.BUY, 1.00, 0.5),
        ]
    )


def test_cancel_wrong_price_is_miss():
    run_both(
        [
            o(1, Side.SALE, 1.00, 1.0),
            o(1, Side.SALE, 1.01, 1.0, action=Action.DEL),
        ]
    )


def test_market_order_walks_book_and_drops_remainder():
    run_both(
        [
            o(1, Side.SALE, 1.00, 0.2),
            o(2, Side.SALE, 5.00, 0.2),
            o(3, Side.BUY, 0.0, 1.0, ot=OrderType.MARKET),
            o(4, Side.BUY, 1.00, 0.1),  # book must be empty of asks now
        ]
    )


def test_market_sell():
    run_both(
        [
            o(1, Side.BUY, 1.00, 0.2),
            o(2, Side.BUY, 0.50, 0.2),
            o(3, Side.SALE, 9.99, 0.3, ot=OrderType.MARKET),
        ]
    )


def test_doorder_stream_parity():
    """The reference's own load shape (doorder.go:37-59), 400 orders."""
    run_both(doorder_stream(n=400, seed=11), BookConfig(cap=512, max_fills=64))


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_mixed_stream_with_cancels_parity(seed):
    run_both(
        mixed_stream(n=400, seed=seed, cancel_prob=0.25),
        BookConfig(cap=512, max_fills=64),
    )


def test_mixed_stream_with_markets_parity():
    run_both(
        mixed_stream(n=300, seed=5, cancel_prob=0.15, market_prob=0.1),
        BookConfig(cap=512, max_fills=64),
    )


def test_book_overflow_flagged_not_silent():
    cfg = BookConfig(cap=4, max_fills=4)
    h = SingleSymbolHarness(cfg)
    for i in range(4):
        h.process(o(i, Side.SALE, 2.00 + i / 100, 1.0))
    op = encode_op(o(99, Side.SALE, 3.00, 1.0), h.oids, h.uids)
    h.book, out = h._step(h.book, op)
    assert int(out.book_overflow) == 1 and int(out.rested) == 0
    assert h.depth(Side.SALE, 8) == [
        (scale(2.00 + i / 100), scale(1.0)) for i in range(4)
    ]


def test_fill_overflow_reported():
    cfg = BookConfig(cap=16, max_fills=2)
    h = SingleSymbolHarness(cfg)
    for i in range(4):
        h.process(o(i, Side.SALE, 1.00, 0.1))
    op = encode_op(o(9, Side.BUY, 1.00, 0.4), h.oids, h.uids)
    h.book, out = h._step(h.book, op)
    assert int(out.n_fills) == 4 and int(out.fill_overflow) == 2
    # Book state is still exact despite the record overflow.
    assert h.depth(Side.SALE, 8) == []


def test_volume_must_be_positive():
    h = SingleSymbolHarness(CFG)
    with pytest.raises(ValueError):
        h.process(o(1, Side.BUY, 1.0, 0.0))

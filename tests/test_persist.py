"""Durability tests: snapshot/restore, crash-replay recovery (exactly-once
match stream), snapshot store atomicity/pruning, Redis-schema export."""

import json
import os

import pytest

from gome_tpu.bus import decode_match_result, encode_order, make_bus
from gome_tpu.config import BusConfig, Config, EngineConfig, PersistConfig
from gome_tpu.persist import Persister, SnapshotStore
from gome_tpu.persist.redis_schema import export_to_redis
from gome_tpu.service import EngineService
from gome_tpu.utils.streams import mixed_stream


def make_svc(tmp_path, persist=True, every_n=1, **eng):
    cfg = Config(
        bus=BusConfig(backend="file", dir=str(tmp_path / "bus")),
        engine=EngineConfig(cap=32, n_slots=8, max_t=8, **eng),
        persist=PersistConfig(
            dir=str(tmp_path / "snaps"), every_n_batches=every_n
        ),
    )
    p = Persister(cfg.persist) if persist else None
    return EngineService(cfg, persist=p)


def feed_orders(svc, orders):
    for o in orders:
        svc.engine.mark(o)
        svc.bus.order_queue.publish(encode_order(o))


def match_stream(svc):
    mq = svc.bus.match_queue
    return [decode_match_result(m.body) for m in mq.read_from(0, mq.end_offset())]


def test_crash_recovery_exactly_once(tmp_path):
    """Process half the stream, snapshot, process the rest, then 'crash'
    (new process over the same dirs) WITHOUT a newer snapshot: recovery must
    rebuild the books and regenerate the post-snapshot match tail
    byte-identically — the full stream equals an uninterrupted run."""
    orders = mixed_stream(n=200, seed=3, cancel_prob=0.25)

    # Uninterrupted reference run (memory bus).
    ref = EngineService(
        Config(engine=EngineConfig(cap=32, n_slots=8, max_t=8))
    )
    feed_orders(ref, orders)
    ref.pump()
    expected = match_stream(ref)

    # Cadence high enough that the ONLY snapshot is the explicit one below —
    # so the crash leaves a genuine post-snapshot tail to replay.
    svc = make_svc(tmp_path, every_n=10**9)
    svc.persist.restore_latest()
    feed_orders(svc, orders[:100])
    svc.consumer.drain()
    svc.persist.snapshot()
    snap_cut = svc.bus.order_queue.committed()
    snap_match_end = svc.bus.match_queue.end_offset()
    feed_orders(svc, orders[100:])
    svc.consumer.drain()  # post-snapshot work that the crash will replay
    assert svc.bus.match_queue.end_offset() > snap_match_end

    # --- crash: brand-new service over the same bus + snapshot dirs -------
    svc2 = make_svc(tmp_path, every_n=10**9)
    assert svc2.persist.restore_latest()
    # the restore rewound to the snapshot cut, leaving a real replay tail
    assert svc2.bus.order_queue.committed() == snap_cut
    assert svc2.bus.order_queue.end_offset() > snap_cut
    assert svc2.bus.match_queue.end_offset() == snap_match_end  # truncated
    # consumer replays the order-log tail from the snapshot cut
    replayed = svc2.consumer.drain()
    assert replayed == len(orders) - 100
    assert match_stream(svc2) == expected
    # book state equals the uninterrupted run's
    b1 = ref.engine.batch.export_state()
    b2 = svc2.engine.batch.export_state()
    assert b1["symbols"] == b2["symbols"]
    assert (b1["books"]["lots"] == b2["books"]["lots"]).all()
    assert (b1["books"]["count"] == b2["books"]["count"]).all()


def test_recovery_without_any_snapshot_replays_all(tmp_path):
    """Crash before the first snapshot: the durable order log is the only
    state, so recovery rewinds to offset 0 and the consumer replays the
    whole log onto fresh books — no committed book state is lost."""
    orders = mixed_stream(n=60, seed=5, cancel_prob=0.2)
    svc = make_svc(tmp_path, persist=False)
    feed_orders(svc, orders)
    svc.consumer.drain()
    expected = match_stream(svc)
    expected_books = svc.engine.batch.export_state()

    svc2 = make_svc(tmp_path)
    assert not svc2.persist.restore_latest()
    assert svc2.bus.order_queue.committed() == 0  # rewound for full replay
    svc2.consumer.drain()
    assert match_stream(svc2) == expected
    got_books = svc2.engine.batch.export_state()
    assert (expected_books["books"]["lots"] == got_books["books"]["lots"]).all()
    assert (
        expected_books["books"]["count"] == got_books["books"]["count"]
    ).all()


def test_recovery_does_not_resurrect_cancelled_order(tmp_path):
    """A DEL consumed below the snapshot cut must suppress the mark
    reconstruction for a same-key ADD in the replay tail: the cancel was
    observable (its event is below match_end), so replay must keep dropping
    the ADD rather than resurrecting a cancelled order."""
    from gome_tpu.fixed import scale
    from gome_tpu.types import Action, Order, Side

    add = Order(uuid="u", oid="x", symbol="s", side=Side.BUY,
                price=scale(1.0), volume=scale(1.0))
    dele = Order(uuid="u", oid="x", symbol="s", side=Side.BUY,
                 price=scale(1.0), volume=scale(1.0), action=Action.DEL)
    probe = Order(uuid="v", oid="probe", symbol="s", side=Side.SALE,
                  price=scale(1.0), volume=scale(1.0))

    svc = make_svc(tmp_path)
    # DEL consumed first (clears any mark for key s/u/x), then snapshot.
    svc.bus.order_queue.publish(encode_order(dele))
    svc.consumer.drain()
    svc.persist.snapshot()
    # The racing ADD lands in the queue after the cut; crash before consume.
    # (Its in-memory mark dies with the process.)
    svc.bus.order_queue.publish(encode_order(add))

    svc2 = make_svc(tmp_path)
    assert svc2.persist.restore_latest()
    svc2.consumer.drain()
    # The cancelled ADD must NOT have entered the book: a crossing probe
    # finds nothing to hit and the book holds only the probe itself.
    svc2.engine.mark(probe)
    svc2.bus.order_queue.publish(encode_order(probe))
    svc2.consumer.drain()
    events = match_stream(svc2)
    assert events == []  # no fill: resurrected ADD would have matched probe
    books = svc2.engine.batch.lane_books()
    assert int(books.count.sum()) == 1  # just the resting probe


def test_uncommitted_tail_replays_after_crash(tmp_path):
    """Crash BETWEEN publish and consume: orders in the log but never
    processed are picked up by the next process (at-least-once — the
    reference loses these outright, SURVEY §2.3.6)."""
    orders = mixed_stream(n=40, seed=7)
    svc = make_svc(tmp_path)
    feed_orders(svc, orders)  # published, never drained -> crash
    svc2 = make_svc(tmp_path)
    svc2.persist.restore_latest()
    # pre-pool marks died with process 1 (they're process state), but
    # recovery reconstructs marks for queued ADDs from the order log.
    n = svc2.consumer.drain()
    assert n == len(orders)
    ref = EngineService(Config(engine=EngineConfig(cap=32, n_slots=8, max_t=8)))
    feed_orders(ref, orders)
    ref.pump()
    assert match_stream(svc2) == match_stream(ref)


def test_recovery_readmits_consumed_add_after_old_del(tmp_path):
    """The flip side of resurrection suppression: an ADD that the crashed
    process ADMITTED (consumed after the cut) must replay as admitted even
    though an old committed DEL for the same key sits below the cut — its
    fills may already have been observed downstream."""
    from gome_tpu.fixed import scale
    from gome_tpu.types import Action, Order, Side

    key_add = Order(uuid="u", oid="x", symbol="s", side=Side.BUY,
                    price=scale(1.0), volume=scale(1.0))
    key_del = Order(uuid="u", oid="x", symbol="s", side=Side.BUY,
                    price=scale(1.0), volume=scale(1.0), action=Action.DEL)
    ask = Order(uuid="v", oid="a", symbol="s", side=Side.SALE,
                price=scale(1.0), volume=scale(1.0))

    svc = make_svc(tmp_path, every_n=10**9)
    # Old DEL consumed and committed below the cut (clears nothing).
    svc.bus.order_queue.publish(encode_order(key_del))
    svc.consumer.drain()
    svc.persist.snapshot()
    # Post-cut: resting ask, then the gateway re-accepts the same key; the
    # consumer admits it and it FILLS — an observable event.
    svc.engine.mark(ask)
    svc.bus.order_queue.publish(encode_order(ask))
    svc.engine.mark(key_add)
    svc.bus.order_queue.publish(encode_order(key_add))
    svc.consumer.drain()
    pre_crash = match_stream(svc)
    assert len(pre_crash) == 1 and pre_crash[0].match_volume == scale(1.0)

    svc2 = make_svc(tmp_path, every_n=10**9)
    assert svc2.persist.restore_latest()
    svc2.consumer.drain()
    assert match_stream(svc2) == pre_crash  # fill regenerated identically


def test_snapshot_store_atomicity_and_pruning(tmp_path):
    store = SnapshotStore(str(tmp_path / "s"), keep=2)
    import numpy as np

    for i in range(4):
        store.save({"i": i}, {"a": np.arange(i + 1)})
    ids = store._ids()
    assert len(ids) == 2  # pruned to keep=2
    manifest, books = store.load_latest()
    assert manifest["i"] == 3 and len(books["a"]) == 4

    # torn snapshot (no manifest) is skipped
    torn = tmp_path / "s" / "snap-99"
    torn.mkdir()
    (torn / "books.npz").write_bytes(b"garbage")
    manifest, _ = store.load_latest()
    assert manifest["i"] == 3


class FakeRedis:
    """Minimal execute_command target for the gated export."""

    def __init__(self):
        self.zsets: dict[str, dict[str, float]] = {}
        self.hashes: dict[str, dict[str, str]] = {}

    def execute_command(self, *args):
        cmd = args[0]
        if cmd == "ZADD":
            self.zsets.setdefault(args[1], {})[args[3]] = args[2]
        elif cmd == "HSET":
            self.hashes.setdefault(args[1], {})[args[2]] = args[3]
        elif cmd == "FLUSHDB":
            self.zsets.clear()
            self.hashes.clear()
        else:
            raise AssertionError(f"unexpected {cmd}")


def test_redis_schema_export(tmp_path):
    svc = EngineService(Config(engine=EngineConfig(cap=32, n_slots=4, max_t=8)))
    from gome_tpu.fixed import scale
    from gome_tpu.types import Order, Side

    orders = [
        Order(uuid="7", oid="a", symbol="eth2usdt", side=Side.SALE,
              price=scale(1.0), volume=scale(5.0)),
        Order(uuid="8", oid="b", symbol="eth2usdt", side=Side.SALE,
              price=scale(1.0), volume=scale(2.0)),  # same level, later FIFO
        Order(uuid="9", oid="c", symbol="eth2usdt", side=Side.BUY,
              price=scale(0.5), volume=scale(1.0)),
    ]
    feed_orders(svc, orders)
    svc.pump()

    fake = FakeRedis()
    n = export_to_redis(svc.engine, client=fake)
    assert n > 0
    # zsets: one SALE level at 1e8, one BUY level at 0.5e8 (SURVEY §2.1)
    assert fake.zsets["eth2usdt:SALE"] == {"100000000": 100000000.0}
    assert fake.zsets["eth2usdt:BUY"] == {"50000000": 50000000.0}
    # depth hash aggregates the level
    assert fake.hashes["eth2usdt:depth"]["eth2usdt:depth:100000000"] == str(
        scale(7.0)
    )
    # FIFO linked list: f -> a, l -> b, pointers chain a <-> b
    link = fake.hashes["eth2usdt:link:100000000"]
    assert link["f"] == "eth2usdt:node:a" and link["l"] == "eth2usdt:node:b"
    node_a = json.loads(link["eth2usdt:node:a"])
    node_b = json.loads(link["eth2usdt:node:b"])
    assert node_a["IsFirst"] and not node_a["IsLast"]
    assert node_a["NextNode"] == "eth2usdt:node:b"
    assert node_b["PrevNode"] == "eth2usdt:node:a" and node_b["IsLast"]
    assert node_a["Volume"] == scale(5.0)
    # pre-pool marks exported under S:comparison S:U:O (ordernode.go:89-92)
    svc.engine.pre_pool.add(("eth2usdt", "7", "zz"))
    fake2 = FakeRedis()
    export_to_redis(svc.engine, client=fake2)
    assert fake2.hashes["eth2usdt:comparison"]["eth2usdt:7:zz"] == "1"


def test_export_without_client_requires_redis():
    svc = EngineService(Config(engine=EngineConfig(cap=32, n_slots=4, max_t=8)))
    with pytest.raises(RuntimeError, match="redis-py is not installed"):
        export_to_redis(svc.engine)


def test_queue_rollback_truncate_guards(tmp_path):
    bus = make_bus(BusConfig(backend="file", dir=str(tmp_path / "b")))
    q = bus.order_queue
    for i in range(5):
        q.publish(b"%d" % i)
    q.commit(4)
    with pytest.raises(ValueError, match="forwards"):
        q.rollback(5)
    q.rollback(2)
    assert q.committed() == 2
    with pytest.raises(ValueError, match="below committed"):
        q.truncate_to(1)
    q.truncate_to(3)
    assert q.end_offset() == 3
    # truncation is durable across reopen
    from gome_tpu.bus import FileQueue

    q.close()
    q2 = FileQueue("doOrder", str(tmp_path / "b" / "doOrder"))
    assert q2.end_offset() == 3 and q2.committed() == 2


# -- mesh-sharded durability (VERDICT r4 #4) ---------------------------------


def test_snapshot_while_sharded_restores_into_same_and_smaller_mesh():
    """Snapshot a mesh-sharded engine mid-stream, restore into (a) the
    same mesh size and (b) a different divisible mesh size: the continued
    match stream must equal an unsharded engine's over the same orders."""
    from gome_tpu.engine import BookConfig
    from gome_tpu.engine.orchestrator import MatchEngine
    from gome_tpu.parallel import make_mesh
    from gome_tpu.utils.streams import multi_symbol_stream

    orders = multi_symbol_stream(
        n=240, n_symbols=8, seed=9, zipf_a=1.3, cancel_prob=0.25
    )
    head, tail = orders[:120], orders[120:]

    def run(engine, orders):
        out = []
        for o in orders:
            engine.mark(o)
        out.extend(engine.process(orders))
        return out

    cfg = lambda: BookConfig(cap=32, max_fills=8)
    ref = MatchEngine(config=cfg(), n_slots=8, max_t=8)
    ev_ref = run(ref, head) + run(ref, tail)

    sharded = MatchEngine(
        config=cfg(), n_slots=8, max_t=8, mesh=make_mesh(4)
    )
    ev_head = run(sharded, head)
    state = sharded.batch.export_state()

    for n_dev in (4, 2):  # same mesh, then a smaller divisible one
        fresh = MatchEngine(
            config=cfg(), n_slots=8, max_t=8, mesh=make_mesh(n_dev)
        )
        fresh.batch.import_state(state)
        ev = ev_head + run(fresh, tail)
        assert ev == ev_ref, f"mesh={n_dev} restore diverged"
        fresh.batch.verify_books()
        # Restored books actually live sharded on the mesh.
        import jax

        specs = {
            str(getattr(l.sharding, "spec", None))
            for l in jax.tree.leaves(fresh.books)
        }
        assert "PartitionSpec('sym',)" in specs


def test_restore_into_non_divisible_mesh_raises_documented_error():
    """A snapshot whose n_slots does not divide the target mesh must fail
    with the documented ValueError, not a silent mis-placement."""
    import pytest as _pytest

    from gome_tpu.engine import BookConfig
    from gome_tpu.engine.orchestrator import MatchEngine
    from gome_tpu.parallel import make_mesh
    from gome_tpu.utils.streams import multi_symbol_stream

    src = MatchEngine(
        config=BookConfig(cap=16, max_fills=4), n_slots=8, max_t=8
    )
    orders = multi_symbol_stream(n=40, n_symbols=4, seed=3)
    for o in orders:
        src.mark(o)
    src.process(orders)
    state = src.batch.export_state()
    assert state["n_slots"] == 8

    tgt = MatchEngine(
        config=BookConfig(cap=16, max_fills=4),
        n_slots=9, max_t=8, mesh=make_mesh(3), max_slots=12,
    )
    with _pytest.raises(ValueError, match="multiple of the mesh size"):
        tgt.batch.import_state(state)


def test_cap_escalated_snapshot_restores_into_mesh():
    """A snapshot taken AFTER cap escalation (config.cap grew past its
    boot value) must restore into a mesh-sharded engine built with the
    ORIGINAL cap: import_state adopts the escalated cap and the continued
    stream stays oracle-exact."""
    from gome_tpu.engine import BookConfig
    from gome_tpu.engine.orchestrator import MatchEngine
    from gome_tpu.oracle import OracleEngine
    from gome_tpu.parallel import make_mesh
    from gome_tpu.types import Action, Order, OrderType, Side

    rest = [
        Order(
            uuid="u", oid=f"r{i}", symbol="hot", side=Side.SALE,
            price=1000 + i, volume=1, action=Action.ADD,
            order_type=OrderType.LIMIT,
        )
        for i in range(20)  # cap 8 escalates
    ]
    taker = [
        Order(
            uuid="u", oid="t", symbol="hot", side=Side.BUY,
            price=1030, volume=25, action=Action.ADD,
            order_type=OrderType.LIMIT,
        )
    ]
    src = MatchEngine(
        config=BookConfig(cap=8, max_fills=4), n_slots=8, max_t=8
    )
    for o in rest:
        src.mark(o)
    assert src.process(rest) == []
    assert src.batch.stats.cap_escalations >= 1
    escalated = src.config.cap
    assert escalated > 8
    state = src.batch.export_state()

    tgt = MatchEngine(
        config=BookConfig(cap=8, max_fills=4),
        n_slots=8, max_t=8, mesh=make_mesh(4),
    )
    tgt.batch.import_state(state)
    assert tgt.config.cap == escalated
    oracle = OracleEngine()
    expected = []
    for o in rest + taker:
        expected.extend(oracle.process(o))
    expected = [e for e in expected if e.match_volume > 0]
    for o in taker:
        tgt.mark(o)
    got = [e for e in tgt.process(taker) if e.match_volume > 0]
    assert got == expected
    tgt.batch.verify_books()

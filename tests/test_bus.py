"""Bus backends + codec tests (gome_tpu.bus vs rabbitmq.go topology)."""

import threading
import time

import pytest

from gome_tpu.bus import (
    FileQueue,
    MemoryQueue,
    decode_match_result,
    decode_order,
    encode_match_result,
    encode_order,
    make_bus,
)
from gome_tpu.config import BusConfig
from gome_tpu.types import Action, MatchResult, Order, OrderSnapshot, OrderType, Side


def _native_queue(tmp_path):
    from gome_tpu.bus.native import NativeFileQueue, native_available

    if not native_available():
        pytest.skip("native toolchain unavailable")
    return NativeFileQueue("doOrder", str(tmp_path / "doOrder"))


@pytest.fixture(params=["memory", "file", "cfile"])
def queue(request, tmp_path):
    if request.param == "memory":
        return MemoryQueue("doOrder")
    if request.param == "cfile":
        return _native_queue(tmp_path)
    return FileQueue("doOrder", str(tmp_path / "doOrder"))


def test_publish_read_commit(queue):
    offs = [queue.publish(f"m{i}".encode()) for i in range(5)]
    assert offs == [0, 1, 2, 3, 4]
    assert queue.end_offset() == 5
    msgs = queue.read_from(0, 3)
    assert [m.body for m in msgs] == [b"m0", b"m1", b"m2"]
    assert queue.committed() == 0
    queue.commit(3)
    assert queue.committed() == 3
    # non-destructive reads: earlier offsets still readable
    assert queue.read_from(1, 1)[0].body == b"m1"
    with pytest.raises(ValueError):
        queue.commit(2)  # backwards
    with pytest.raises(ValueError):
        queue.commit(99)  # past end


def test_poll_batch_returns_early_when_full(queue):
    for i in range(4):
        queue.publish(f"m{i}".encode())
    t0 = time.monotonic()
    msgs = queue.poll_batch(4, max_wait_s=5.0)
    assert len(msgs) == 4
    assert time.monotonic() - t0 < 1.0  # did not wait for the deadline


def test_poll_batch_times_out_partial(queue):
    queue.publish(b"only")
    msgs = queue.poll_batch(8, max_wait_s=0.05)
    assert [m.body for m in msgs] == [b"only"]


def test_poll_batch_wakes_on_publish(queue):
    def later():
        time.sleep(0.05)
        queue.publish(b"late")

    t = threading.Thread(target=later)
    t.start()
    msgs = queue.poll_batch(1, max_wait_s=5.0)
    t.join()
    assert [m.body for m in msgs] == [b"late"]


def test_file_queue_survives_reopen(tmp_path):
    base = str(tmp_path / "q")
    q = FileQueue("q", base)
    for i in range(10):
        q.publish(f"msg-{i}".encode())
    q.commit(4)
    q.close()

    q2 = FileQueue("q", base)
    assert q2.end_offset() == 10
    assert q2.committed() == 4
    assert q2.read_from(4, 2)[0].body == b"msg-4"
    # and it keeps appending after the existing tail
    q2.publish(b"post-restart")
    assert q2.read_from(10, 1)[0].body == b"post-restart"


def test_file_queue_truncates_torn_tail(tmp_path):
    base = str(tmp_path / "q")
    q = FileQueue("q", base)
    q.publish(b"whole")
    q.close()
    with open(base + ".log", "ab") as f:
        f.write(b"\x00\x00\x00\xff partial")  # length says 255, body short
    q2 = FileQueue("q", base)
    assert q2.end_offset() == 1
    assert q2.read_from(0, 9)[0].body == b"whole"


def test_make_bus_topology(tmp_path):
    bus = make_bus(BusConfig(backend="file", dir=str(tmp_path / "bus")))
    assert bus.order_queue.name == "doOrder"  # rabbitmq.go queue names
    assert bus.match_queue.name == "matchOrder"
    bus.order_queue.publish(b"x")
    assert bus.match_queue.end_offset() == 0  # independent queues


def test_native_python_on_disk_interop(tmp_path):
    """The native and Python file queues share one on-disk format: a
    directory written by either reopens correctly under the other,
    including committed offsets and truncation."""
    from gome_tpu.bus.native import NativeFileQueue, native_available

    if not native_available():
        pytest.skip("native toolchain unavailable")
    base = str(tmp_path / "q")
    # Python writes -> native reads
    q = FileQueue("q", base)
    for i in range(6):
        q.publish(f"py-{i}".encode())
    q.commit(2)
    q.close()
    nq = NativeFileQueue("q", base)
    assert nq.end_offset() == 6 and nq.committed() == 2
    assert [m.body for m in nq.read_from(2, 2)] == [b"py-2", b"py-3"]
    # native appends + truncates -> Python reads
    nq.publish_batch([b"c-0", b"c-1", b"c-2"])
    nq.truncate_to(8)
    nq.close()
    q2 = FileQueue("q", base)
    assert q2.end_offset() == 8
    assert q2.read_from(6, 2)[0].body == b"c-0"
    assert q2.read_from(7, 1)[0].body == b"c-1"


def test_native_batch_publish_and_recovery(tmp_path):
    from gome_tpu.bus.native import NativeFileQueue, native_available

    if not native_available():
        pytest.skip("native toolchain unavailable")
    base = str(tmp_path / "q")
    nq = NativeFileQueue("q", base)
    first = nq.publish_batch([b"a" * 10, b"b" * 100, b"c"])
    assert first == 0 and nq.end_offset() == 3
    nq.commit(3)
    nq.close()
    # torn tail: native scanner truncates it away on reopen
    with open(base + ".log", "ab") as f:
        f.write(b"\x00\x00\x01\x00 torn")
    nq2 = NativeFileQueue("q", base)
    assert nq2.end_offset() == 3 and nq2.committed() == 3
    assert nq2.read_from(1, 1)[0].body == b"b" * 100
    nq2.close()


def test_order_codec_roundtrip():
    order = Order(
        uuid="7",
        oid="o123",
        symbol="eth2usdt",
        side=Side.SALE,
        price=99_500_000,
        volume=1_000_000,
        action=Action.DEL,
    )
    assert decode_order(encode_order(order)) == order


def test_order_codec_reference_shape():
    # Go-marshalled OrderNode JSON (exported field names, extra Redis-key
    # fields present) must decode; unknown fields ignored.
    body = (
        b'{"Action":1,"Uuid":"2","Oid":"11","Symbol":"eth2usdt",'
        b'"Transaction":0,"Price":50000000,"Volume":3000000,'
        b'"Accuracy":8,"NodeName":"eth2usdt:node:11","IsFirst":false}'
    )
    order = decode_order(body)
    assert order.action is Action.ADD
    assert order.side is Side.BUY
    assert order.price == 50_000_000
    assert order.order_type is OrderType.LIMIT  # absent Kind => LIMIT


def test_match_result_codec_roundtrip():
    snap = lambda oid, vol: OrderSnapshot(
        uuid="u", oid=oid, symbol="s", side=Side.BUY, price=100, volume=vol
    )
    mr = MatchResult(node=snap("t", 0), match_node=snap("m", 5), match_volume=5)
    rt = decode_match_result(encode_match_result(mr))
    assert rt == mr
    assert not rt.is_cancel
    cancel = MatchResult(node=snap("c", 7), match_node=snap("c", 7), match_volume=0)
    assert decode_match_result(encode_match_result(cancel)).is_cancel

"""Measured roofline (gome_tpu.obs.profiler): trace-event attribution,
the profiler capture + report join, the /profile endpoint, the per-shard
dispatch telemetry, and the committed MULTICHIP_r06 curve — the ISSUE 9
surface."""

import json
import os
import sys
import urllib.request

import numpy as np

import pytest

from gome_tpu.obs import costmodel, profiler
from gome_tpu.obs.compile_journal import JOURNAL
from gome_tpu.obs.profiler import (
    ANNOTATION_PREFIX,
    PROFILER,
    parse_trace_events,
)
from gome_tpu.obs.timeline import TIMELINE


@pytest.fixture(autouse=True)
def _profiler_disabled():
    """Every test leaves the process-global profiler disabled (the
    hot-path default other tests assume)."""
    yield
    PROFILER.disable()


# --- the pure trace-event parser ------------------------------------------


def _golden_events():
    """Hand-written Chrome trace-event list exercising every attribution
    rule at once: nested XLA ops (union, not sum), a thread-duplicated
    runtime symbol (``::`` exclusion), a ``$``-prefixed Python event, a
    host-infra prefix, a window-straddling op (clipping), a device-process
    event (counts by construction), and a bare-label window (TraceMe
    pipelines that strip the prefix at a separator)."""
    return [
        # process metadata
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        {"ph": "M", "pid": 2, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        # annotation window: gome_profile/lane_scan over [1000, 2000)
        {"ph": "X", "pid": 1, "tid": 1, "ts": 1000, "dur": 1000,
         "name": ANNOTATION_PREFIX + "lane_scan"},
        # nested compute ops: `call` CONTAINS the reduce-window it calls
        # — the union must count this region once (400), not 770
        {"ph": "X", "pid": 1, "tid": 2, "ts": 1100, "dur": 400,
         "name": "call"},
        {"ph": "X", "pid": 1, "tid": 2, "ts": 1120, "dur": 370,
         "name": "reduce-window.2.clone"},
        # runtime plumbing, duplicated across threads: excluded by `::`
        {"ph": "X", "pid": 1, "tid": 2, "ts": 1100, "dur": 800,
         "name": "TfrtCpuExecutable::Execute"},
        {"ph": "X", "pid": 1, "tid": 3, "ts": 1100, "dur": 800,
         "name": "TfrtCpuExecutable::Execute"},
        # Python-originated and host-infra events: excluded
        {"ph": "X", "pid": 1, "tid": 1, "ts": 1150, "dur": 100,
         "name": "$RunBlockHostUntilReady"},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 1050, "dur": 900,
         "name": "PjitFunction(lane_scan)"},
        # a second disjoint op (+200) and one straddling the window end
        # (300 long, only 100 inside)
        {"ph": "X", "pid": 1, "tid": 2, "ts": 1600, "dur": 200,
         "name": "fusion.1"},
        {"ph": "X", "pid": 1, "tid": 2, "ts": 1900, "dur": 300,
         "name": "fusion.2"},
        # device-process event: compute by construction even though the
        # name would fail the host heuristic; overlaps `call`, so the
        # TOTAL union is unchanged while by_device gains a row
        {"ph": "X", "pid": 2, "tid": 1, "ts": 1200, "dur": 100,
         "name": "while.5"},
        # zero-duration noise: dropped
        {"ph": "X", "pid": 1, "tid": 1, "ts": 1400, "dur": 0,
         "name": "fusion.3"},
        # bare-label window (prefix stripped upstream) + one op inside
        {"ph": "X", "pid": 1, "tid": 1, "ts": 3000, "dur": 500,
         "name": "compact_accum"},
        {"ph": "X", "pid": 1, "tid": 2, "ts": 3100, "dur": 200,
         "name": "fusion.9"},
    ]


def test_parse_golden_trace_interval_union():
    out = parse_trace_events(
        _golden_events(), ["lane_scan", "compact_accum", "missing"]
    )
    row = out["lane_scan"]
    assert row["windows"] == 1
    assert row["wall_us"] == 1000.0
    # call(400) ∪ nested reduce-window ∪ fusion.1(200) ∪ clipped
    # fusion.2(100); the device event overlaps `call` so it adds nothing
    assert row["device_us"] == 700.0
    assert row["by_device"] == {"/host:CPU": 700.0, "/device:TPU:0": 100.0}
    # call, reduce-window, fusion.1, fusion.2, while.5 — the excluded
    # runtime/Python/infra/zero-dur events never land in the hit list
    assert row["events"] == 5
    assert row["top_op"] == "call"

    bare = out["compact_accum"]
    assert bare["windows"] == 1
    assert bare["wall_us"] == 500.0
    assert bare["device_us"] == 200.0
    assert bare["top_op"] == "fusion.9"

    none = out["missing"]
    assert none["windows"] == 0
    assert none["device_us"] == 0.0
    assert none["top_op"] is None


def test_parse_merges_split_annotation_windows():
    """Two windows for one label: wall sums, ops clip to the union of
    both — an op in the gap between windows contributes nothing."""
    events = [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        {"ph": "X", "pid": 1, "ts": 100, "dur": 100,
         "name": ANNOTATION_PREFIX + "batch_step"},
        {"ph": "X", "pid": 1, "ts": 400, "dur": 100,
         "name": ANNOTATION_PREFIX + "batch_step"},
        {"ph": "X", "pid": 1, "ts": 150, "dur": 20, "name": "fusion.0"},
        {"ph": "X", "pid": 1, "ts": 250, "dur": 50, "name": "fusion.1"},
        {"ph": "X", "pid": 1, "ts": 430, "dur": 40, "name": "fusion.2"},
    ]
    row = parse_trace_events(events, ["batch_step"])["batch_step"]
    assert row["windows"] == 2
    assert row["wall_us"] == 200.0
    assert row["device_us"] == 60.0  # fusion.1 sits in the gap
    assert row["events"] == 2


# --- the measured report (one real capture) -------------------------------


def test_measured_report_joins_and_respects_peaks():
    PROFILER.install(keep_n=2)
    rep = PROFILER.capture_report("int32", repeats=2)
    assert rep["platform"] == "cpu"
    assert rep["peaks"]["peak_gflops"] > 0
    assert rep["peaks"]["peak_gbps"] > 0
    rows = [r for r in rep["entries"].values() if "error" not in r]
    assert len(rows) >= 3, rep["entries"]
    assert set(rep["entries"]) <= set(costmodel.RATCHET_ENTRIES)
    for row in rows:
        assert row["device_us_per_call"] > 0
        assert row["flops"] and row["bytes_accessed"]
        # measured rates come from ANALYTIC work over MEASURED time; a
        # tiny integer scan sits orders of magnitude under the machine
        # ceiling, so even generous calibration slack never trips this
        assert row["achieved_gflops"] <= rep["peaks"]["peak_gflops"] * 1.5
        assert row["achieved_gbps"] <= rep["peaks"]["peak_gbps"] * 1.5
        assert 0 < row["efficiency_pct"] <= 150.0
    # the capture left a loadable Perfetto artifact next to the report
    assert rep["perfetto_trace"] and os.path.exists(rep["perfetto_trace"])
    assert rep["run_dir"] and os.path.isdir(rep["run_dir"])

    # the capture rode the ring and (re)bound the per-entry gauges
    assert PROFILER.enabled
    assert PROFILER.last_report() is rep
    payload = PROFILER.payload(dtype="int32")  # reuses the ring, no capture
    assert payload["enabled"] and payload["captures"] >= 1
    assert payload["report"] is rep
    from gome_tpu.utils.metrics import REGISTRY

    metrics = REGISTRY.render()
    assert "gome_profile_captures_total" in metrics
    assert "gome_profile_device_us" in metrics
    assert 'entry="' in metrics

    # bench.py's compact measured block derives from the same machinery
    block = profiler.bench_measured("int32", repeats=2)
    assert block["dtype"] == "int32"
    assert block["entries"]
    for row in block["entries"].values():
        assert set(row) == {"device_us_per_call", "achieved_gflops",
                            "achieved_gbps", "efficiency_pct"}


# --- /profile over HTTP ---------------------------------------------------


def test_profile_endpoint_http_validity():
    from gome_tpu.config import Config, EngineConfig, OpsConfig
    from gome_tpu.engine import frames
    from gome_tpu.service.app import EngineService

    cfg = Config(
        engine=EngineConfig(cap=16, max_fills=4, n_slots=4, max_t=4,
                            dtype="int32"),
        ops=OpsConfig(port=0, enabled=True),
    )
    svc = EngineService(cfg)
    assert PROFILER.enabled  # ops.profile armed the profiler at boot
    # one fast-path frame so the capture runs against a warmed engine —
    # the "real frame drill" of the acceptance criteria
    rng = np.random.default_rng(3)
    n = 16
    frames.apply_frame_fast(svc.engine.batch, dict(
        n=n,
        action=np.ones(n, np.int64),
        side=rng.integers(0, 2, n).astype(np.int64),
        kind=np.zeros(n, np.int64),
        price=rng.integers(99_000, 101_000, n).astype(np.int64),
        volume=rng.integers(1, 10, n).astype(np.int64),
        symbols=[f"s{i}" for i in range(4)],
        symbol_idx=rng.integers(0, 4, n).astype(np.int64),
        uuids=["u0"],
        uuid_idx=np.zeros(n, np.int64),
        oids=np.char.add("p", np.arange(n).astype("U6")).astype("S"),
    ))
    svc.ops.start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{svc.ops.port}/profile", timeout=120
        ) as r:
            assert r.status == 200
            assert r.headers["Content-Type"] == "application/json"
            doc = json.loads(r.read().decode())
        assert doc["enabled"] is True
        assert doc["captures"] >= 1
        rep = doc["report"]
        assert rep and rep["entries"], "measured report empty over HTTP"
        measured = [
            row for row in rep["entries"].values()
            if "error" not in row and row["device_us_per_call"] > 0
        ]
        assert measured, rep["entries"]
        for row in measured:
            assert row["achieved_gflops"] > 0
    finally:
        svc.ops.stop()
        JOURNAL.disable()
        TIMELINE.disable()
        PROFILER.disable()


# --- disabled contract: no-op + zero hot-path allocations -----------------


def test_disabled_profiler_is_inert():
    PROFILER.disable()
    assert not PROFILER.enabled
    assert PROFILER.shard_report() == {"enabled": False}
    payload = PROFILER.payload()
    assert payload == {"enabled": False, "captures": 0, "report": None,
                       "shards": {"enabled": False}}


def test_disabled_shard_hook_allocates_nothing():
    """Same contract as TRACER/JOURNAL/TIMELINE: the dispatch-path hook
    costs one attribute check and ZERO allocations when disabled."""
    PROFILER.disable()
    counts = np.array([3, 1])

    def drill(n):
        i = 0
        while i < n:
            PROFILER.note_shard_dispatch(2, 8, counts)
            i += 1

    drill(64)  # warm any lazy caches
    before = sys.getallocatedblocks()
    drill(200)
    after = sys.getallocatedblocks()
    assert after - before <= 2, f"hot-path hook allocated {after - before}"


# --- per-shard telemetry on a 2-device mesh -------------------------------


def test_shard_telemetry_on_two_device_mesh():
    from gome_tpu.engine import BatchEngine, BookConfig
    from gome_tpu.engine.batch import _nop_grid
    from gome_tpu.engine.book import DeviceOp
    from gome_tpu.parallel import make_mesh, shard_execution_report

    cfg = BookConfig(cap=8, max_fills=4)
    mesh = make_mesh(2)
    eng = BatchEngine(cfg, n_slots=64, max_t=4, mesh=mesh)
    PROFILER.install(keep_n=2)
    # 3 live lanes on shard 0, 1 on shard 1 -> r_s buckets to the max (8)
    live = np.array([0, 1, 2, 35], dtype=np.int64)
    use_dense, n_rows, lane_ids, _ = eng._grid_geometry(live)
    assert use_dense and n_rows == 16

    rep = PROFILER.shard_report()
    assert rep["enabled"] and rep["dispatches"] == 1
    last = rep["last"]
    assert last["n_shards"] == 2
    assert last["rows_per_shard"] == 8
    assert last["dispatched_rows"] == 16
    assert last["live_per_shard"] == [3, 1]
    assert last["skew"] == pytest.approx(1.5)  # 3 * 2 / 4
    assert last["rows_per_live_lane"] == pytest.approx(4.0)
    assert rep["skew_p50"] == pytest.approx(1.5)

    # measured per-shard replay: both shards pay the SAME bucketed row
    # height (the skew tax) and report positive execution time
    ops = DeviceOp(**_nop_grid(cfg, n_rows, 4))
    per_shard = shard_execution_report(
        cfg, mesh, eng.books, lane_ids, ops, repeats=1
    )
    assert per_shard["n_shards"] == 2
    assert per_shard["rows_per_shard"] == 8
    assert [sh["live_lanes"] for sh in per_shard["shards"]] == [3, 1]
    assert all(sh["rows"] == 8 for sh in per_shard["shards"])
    assert all(sh["exec_ms"] > 0 for sh in per_shard["shards"])
    assert per_shard["live_skew"] == pytest.approx(1.5)
    assert per_shard["exec_ms_max"] >= per_shard["exec_ms_mean"]


# --- the committed multi-chip curve ---------------------------------------


def test_multichip_r06_artifact_pins_the_measured_curve():
    """MULTICHIP_r06.json is a COMMITTED artifact (scripts/mesh_overhead.py
    --curve): the first measured D=1/2/4/8 throughput curve with per-shard
    skew. This pin keeps the committed numbers structurally honest."""
    path = os.path.join(os.path.dirname(__file__), "..", "MULTICHIP_r06.json")
    with open(path) as f:
        doc = json.load(f)
    assert doc["platform"] == "cpu"
    curve = doc["curve"]
    assert [p["devices"] for p in curve] == [1, 2, 4, 8]
    for p in curve:
        assert p["live_orders_per_sec"] > 0
        assert p["step_ms"] > 0
        assert p["dispatched_rows"] >= p["live_lanes"]
        assert len(p["live_per_shard"]) == p["devices"]
        assert sum(p["live_per_shard"]) == p["live_lanes"]
        assert p["shard_skew"] >= 1.0
        if p["devices"] > 1:
            per_shard = p["per_shard"]
            assert per_shard["n_shards"] == p["devices"]
            assert len(per_shard["shards"]) == p["devices"]
            assert all(sh["exec_ms"] > 0 for sh in per_shard["shards"])
            assert per_shard["live_skew"] == pytest.approx(
                p["shard_skew"], rel=1e-3
            )
    # skew grows with shard count under a Zipf flow — the measured
    # restatement of ROADMAP open item 2
    assert curve[-1]["shard_skew"] > 2.0
    # the embedded measured-roofline block is non-empty
    prof = doc["profile"]
    assert prof["entries"]
    assert any(
        (row.get("device_us_per_call") or 0) > 0
        for row in prof["entries"].values()
    )

"""Tests for the pure-Python semantic oracle.

These encode the reference's observable behavior (gomengine/engine/engine.go
and friends — citations inline) and serve as the spec for the JAX engine.
"""

from gome_tpu.fixed import scale
from gome_tpu.oracle import OracleEngine
from gome_tpu.types import Action, Order, OrderType, Side
from gome_tpu.utils.streams import doorder_stream, mixed_stream


def o(
    oid,
    side,
    price,
    volume,
    uuid="u1",
    symbol="btc2usdt",
    action=Action.ADD,
    order_type=OrderType.LIMIT,
):
    return Order(
        uuid=uuid,
        oid=str(oid),
        symbol=symbol,
        side=side,
        price=scale(price),
        volume=scale(volume),
        action=action,
        order_type=order_type,
    )


def test_rest_then_full_cross():
    """A buy crossing one resting ask fills at the maker's price."""
    e = OracleEngine()
    e.process(o(1, Side.SALE, 1.00, 0.5))
    events = e.process(o(2, Side.BUY, 1.10, 0.5))
    assert len(events) == 1
    ev = events[0]
    assert ev.match_volume == scale(0.5)
    assert ev.match_node.oid == "1"
    assert ev.match_node.price == scale(1.00)  # fill at maker level
    assert ev.match_node.volume == scale(0.5)  # full fill: pre-fill volume
    assert ev.node.oid == "2"
    assert ev.node.volume == 0  # taker exhausted
    assert ev.node.price == scale(1.10)  # taker keeps its own limit price
    book = e.book("btc2usdt")
    assert book.depth(Side.SALE) == []
    assert book.depth(Side.BUY) == []


def test_partial_maker_fill_event_has_remaining_volume():
    """engine.go:176-194 — partial fill: MatchNode.Volume = maker remaining."""
    e = OracleEngine()
    e.process(o(1, Side.SALE, 1.00, 1.0))
    events = e.process(o(2, Side.BUY, 1.00, 0.3))
    assert len(events) == 1
    ev = events[0]
    assert ev.match_volume == scale(0.3)
    assert ev.match_node.volume == scale(0.7)  # post-fill remaining
    assert ev.node.volume == 0
    assert e.book("btc2usdt").depth(Side.SALE) == [(scale(1.00), scale(0.7))]


def test_taker_remainder_rests_at_own_price():
    """engine.go:69-83 — unfilled remainder rests at the taker's limit."""
    e = OracleEngine()
    e.process(o(1, Side.SALE, 1.00, 0.3))
    events = e.process(o(2, Side.BUY, 1.05, 1.0))
    assert len(events) == 1
    assert events[0].match_volume == scale(0.3)
    book = e.book("btc2usdt")
    assert book.depth(Side.BUY) == [(scale(1.05), scale(0.7))]
    assert book.depth(Side.SALE) == []


def test_price_priority_best_first():
    """BUY taker consumes asks lowest-price-first (nodepool.go:101-103)."""
    e = OracleEngine()
    e.process(o(1, Side.SALE, 1.02, 0.2))
    e.process(o(2, Side.SALE, 1.00, 0.2))
    e.process(o(3, Side.SALE, 1.01, 0.2))
    events = e.process(o(4, Side.BUY, 1.02, 0.6))
    assert [ev.match_node.oid for ev in events] == ["2", "3", "1"]
    assert [ev.match_node.price for ev in events] == [
        scale(1.00),
        scale(1.01),
        scale(1.02),
    ]


def test_sale_taker_consumes_bids_highest_first():
    """SALE taker consumes bids highest-price-first (nodepool.go:90-92)."""
    e = OracleEngine()
    e.process(o(1, Side.BUY, 0.98, 0.2))
    e.process(o(2, Side.BUY, 1.00, 0.2))
    e.process(o(3, Side.BUY, 0.99, 0.2))
    events = e.process(o(4, Side.SALE, 0.98, 0.6))
    assert [ev.match_node.oid for ev in events] == ["2", "3", "1"]


def test_time_priority_fifo_within_level():
    e = OracleEngine()
    e.process(o(1, Side.SALE, 1.00, 0.2, uuid="a"))
    e.process(o(2, Side.SALE, 1.00, 0.2, uuid="b"))
    events = e.process(o(3, Side.BUY, 1.00, 0.3))
    assert [ev.match_node.oid for ev in events] == ["1", "2"]
    assert events[0].match_volume == scale(0.2)  # full first maker
    assert events[1].match_volume == scale(0.1)  # partial second
    assert events[1].match_node.volume == scale(0.1)  # remaining


def test_non_crossing_price_does_not_match():
    e = OracleEngine()
    e.process(o(1, Side.SALE, 1.01, 0.5))
    events = e.process(o(2, Side.BUY, 1.00, 0.5))
    assert events == []
    book = e.book("btc2usdt")
    assert book.depth(Side.BUY) == [(scale(1.00), scale(0.5))]
    assert book.depth(Side.SALE) == [(scale(1.01), scale(0.5))]


def test_no_self_trade_prevention():
    """SURVEY §2.3.4 — same uuid happily self-matches."""
    e = OracleEngine()
    e.process(o(1, Side.SALE, 1.00, 0.5, uuid="x"))
    events = e.process(o(2, Side.BUY, 1.00, 0.5, uuid="x"))
    assert len(events) == 1 and events[0].match_volume == scale(0.5)


def test_cancel_emits_zero_volume_event_with_remaining():
    """engine.go:100,109-113 — cancel event carries remaining volume."""
    e = OracleEngine()
    e.process(o(1, Side.SALE, 1.00, 1.0))
    e.process(o(2, Side.BUY, 1.00, 0.4))  # partial fill -> 0.6 remains
    events = e.process(
        o(1, Side.SALE, 1.00, 1.0, action=Action.DEL)
    )
    assert len(events) == 1
    ev = events[0]
    assert ev.is_cancel and ev.match_volume == 0
    assert ev.node.volume == scale(0.6)
    assert ev.node == ev.match_node
    assert e.book("btc2usdt").depth(Side.SALE) == []


def test_cancel_requires_exact_price():
    """SURVEY §2.3.2 — wrong price ⇒ lookup miss, no event."""
    e = OracleEngine()
    e.process(o(1, Side.SALE, 1.00, 1.0))
    events = e.process(o(1, Side.SALE, 1.01, 1.0, action=Action.DEL))
    assert events == []
    assert e.book("btc2usdt").depth(Side.SALE) == [(scale(1.00), scale(1.0))]


def test_cancel_of_filled_order_is_noop():
    e = OracleEngine()
    e.process(o(1, Side.SALE, 1.00, 0.5))
    e.process(o(2, Side.BUY, 1.00, 0.5))
    events = e.process(o(1, Side.SALE, 1.00, 0.5, action=Action.DEL))
    assert events == []


def test_cancel_add_in_fifo_order_cancels_rested_order():
    """ADD then DEL through the FIFO queue (both ride "doOrder",
    main.go:48,60): the ADD rests, the DEL cancels it — one cancel event."""
    e = OracleEngine()
    e.submit(o(1, Side.SALE, 1.00, 1.0))
    e.submit(o(1, Side.SALE, 1.00, 1.0, action=Action.DEL))
    events = e.drain()
    assert len(events) == 1 and events[0].is_cancel
    assert e.book("btc2usdt").depth(Side.SALE) == []


def test_cancel_overtaking_add_drops_queued_add():
    """SURVEY §2.3.3 — if the DEL is consumed before the ADD (publish-time
    reordering between concurrent gRPC handlers), the DEL clears the
    pre-pool marker and the ADD is dropped at consume time
    (engine.go:58-62,88-90)."""
    e = OracleEngine()
    add = o(1, Side.SALE, 1.00, 1.0)
    e.submit(add)  # marks pre-pool, queues ADD
    e.do_order(o(1, Side.SALE, 1.00, 1.0, action=Action.DEL))  # DEL first
    events = e.drain()  # now the queued ADD is consumed
    assert events == []  # ADD dropped; DEL found nothing resting
    assert e.book("btc2usdt").depth(Side.SALE) == []
    assert e.stats.dropped_no_prepool == 1


def test_multi_level_depth_walk():
    e = OracleEngine()
    e.process(o(1, Side.SALE, 1.00, 0.2))
    e.process(o(2, Side.SALE, 1.01, 0.2))
    e.process(o(3, Side.SALE, 1.02, 0.2))
    events = e.process(o(4, Side.BUY, 1.05, 0.5))
    assert [ev.match_volume for ev in events] == [
        scale(0.2),
        scale(0.2),
        scale(0.1),
    ]
    # Taker exhausted mid-walk; level 1.02 keeps 0.1.
    assert e.book("btc2usdt").depth(Side.SALE) == [(scale(1.02), scale(0.1))]
    # Taker remaining decreases across its own event stream.
    assert [ev.node.volume for ev in events] == [scale(0.3), scale(0.1), 0]


def test_market_order_crosses_everything_and_never_rests():
    """Extension (BASELINE config 5): market buy walks all asks; remainder
    is dropped."""
    e = OracleEngine()
    e.process(o(1, Side.SALE, 1.00, 0.2))
    e.process(o(2, Side.SALE, 5.00, 0.2))
    events = e.process(
        o(3, Side.BUY, 0.0, 1.0, order_type=OrderType.MARKET)
    )
    assert [ev.match_node.price for ev in events] == [scale(1.00), scale(5.00)]
    book = e.book("btc2usdt")
    assert book.depth(Side.SALE) == []
    assert book.depth(Side.BUY) == []  # remainder did not rest


def test_symbols_are_isolated():
    """SURVEY §2.1 — symbols share nothing."""
    e = OracleEngine()
    e.process(o(1, Side.SALE, 1.00, 0.5, symbol="aaa"))
    events = e.process(o(2, Side.BUY, 1.00, 0.5, symbol="bbb"))
    assert events == []
    assert e.book("aaa").depth(Side.SALE) == [(scale(1.00), scale(0.5))]
    assert e.book("bbb").depth(Side.BUY) == [(scale(1.00), scale(0.5))]


def _invariants(e: OracleEngine):
    for book in e.books.values():
        bids = book.depth(Side.BUY)
        asks = book.depth(Side.SALE)
        if bids and asks:
            assert bids[0][0] < asks[0][0], "book crossed"
        for _, vol in bids + asks:
            assert vol > 0


def test_doorder_stream_volume_conservation_and_invariants():
    """Replay the reference's own load driver shape (doorder.go:37-59) and
    check conservation + non-crossing after every step."""
    e = OracleEngine()
    total_in = 0
    matched = 0
    for order in doorder_stream(n=500, seed=7):
        total_in += order.volume
        events = e.process(order)
        for ev in events:
            assert ev.match_volume > 0
            matched += 2 * ev.match_volume
        _invariants(e)
    book = e.book("eth2usdt")
    resting = sum(v for _, v in book.depth(Side.BUY)) + sum(
        v for _, v in book.depth(Side.SALE)
    )
    assert total_in == matched + resting


def test_mixed_stream_with_cancels_conservation():
    e = OracleEngine()
    total_in = matched = cancelled = 0
    for order in mixed_stream(n=1000, seed=3, cancel_prob=0.25):
        if order.action is Action.ADD:
            total_in += order.volume
        events = e.process(order)
        for ev in events:
            if ev.is_cancel:
                cancelled += ev.node.volume
            else:
                matched += 2 * ev.match_volume
        _invariants(e)
    book = e.book("eth2usdt")
    resting = sum(v for _, v in book.depth(Side.BUY)) + sum(
        v for _, v in book.depth(Side.SALE)
    )
    assert total_in == matched + cancelled + resting


def test_event_snapshot_symbol_and_sides():
    e = OracleEngine()
    e.process(o(1, Side.SALE, 1.00, 0.5, uuid="maker"))
    ev = e.process(o(2, Side.BUY, 1.00, 0.5, uuid="taker"))[0]
    assert ev.node.side is Side.BUY and ev.match_node.side is Side.SALE
    assert ev.node.uuid == "taker" and ev.match_node.uuid == "maker"
    assert ev.node.symbol == ev.match_node.symbol == "btc2usdt"

"""Per-grid cap classes (VERDICT r4 #2): dense/full grids run at their own
pow4 cap bucket — a re-slice of the shared storage — so 10K shallow lanes
never pay one hot lane's escalated depth. These tests pin:

  * the class ladder and partition choice (hot lanes deep, tail shallow);
  * exact parity vs the oracle while classes are heterogeneous;
  * the device-side guard: a WRONG host-side depth estimate costs a
    confined re-run (grid_cap_escalations / frame fallback), never a
    silently truncated book;
  * count_ub bookkeeping (base+extra upper bound, fetch re-anchoring).
"""

import numpy as np

from gome_tpu.engine import BatchEngine, BookConfig
from gome_tpu.engine.batch import CAP_CLASS_MIN, _cap_ladder
from gome_tpu.engine.frames import (
    _class_partitions,
    apply_frame_fast,
    pack_frame_grids,
    process_frame,
)
from gome_tpu.oracle import OracleEngine
from gome_tpu.types import Action, Order, Side

from test_frames import _oracle, run_frames


def test_cap_ladder():
    assert _cap_ladder(16) == [16]
    assert _cap_ladder(64) == [64]
    assert _cap_ladder(128) == [64, 128]
    assert _cap_ladder(256) == [64, 256]
    assert _cap_ladder(1024) == [64, 256, 1024]
    assert _cap_ladder(2048) == [64, 256, 1024, 2048]


def _hot_tail_orders(n_tail=12, hot_depth=150):
    """One hot symbol holding `hot_depth` resting bids plus shallow tail
    symbols, then a crossing burst on every symbol."""
    orders = []
    oid = 0
    for i in range(hot_depth):
        orders.append(
            Order(uuid="u", oid=f"h{oid}", symbol="hot", side=Side.BUY,
                  price=1000 - i, volume=5, action=Action.ADD)
        )
        oid += 1
    for s in range(n_tail):
        for i in range(3):
            orders.append(
                Order(uuid="u", oid=f"t{s}-{i}", symbol=f"tail{s}",
                      side=Side.BUY, price=500 + i, volume=2,
                      action=Action.ADD)
            )
    # Crossing sells drain a bit of every book (depth-walk fills).
    for s in ["hot"] + [f"tail{s}" for s in range(n_tail)]:
        orders.append(
            Order(uuid="u", oid=f"x{s}", symbol=s, side=Side.SALE,
                  price=1, volume=7, action=Action.ADD)
        )
    return orders


def test_heterogeneous_classes_parity_and_partition():
    """A hot lane (>64 resting) and shallow tail lanes must land in
    different cap classes, and the events must still match the oracle
    exactly."""
    eng = BatchEngine(
        BookConfig(cap=256, max_fills=16), n_slots=64, max_t=8,
    )
    orders = _hot_tail_orders()
    got = run_frames(eng, orders, chunk=90, fast=True)
    assert got == _oracle(orders)
    eng.verify_books()
    # After the stream, the hot lane's count_ub must class deep, tails
    # shallow: pack a probe frame touching every symbol and inspect.
    probe = [
        Order(uuid="u", oid=f"p{s}", symbol=s, side=Side.BUY, price=600,
              volume=1, action=Action.ADD)
        for s in ["hot"] + [f"tail{s}" for s in range(12)]
    ]
    from gome_tpu.bus import colwire

    cols = colwire.decode_order_frame(colwire.encode_orders(probe))
    from gome_tpu.engine.frames import _frame_arrays

    a = _frame_arrays(eng, cols)
    parts = _class_partitions(eng, a, np.nonzero(a["keep"])[0])
    caps = sorted(c for c, _ in parts)
    assert caps == [CAP_CLASS_MIN, 256]
    hot_lane = eng.symbol_lane("hot")
    deep_idx = dict(parts)[256]
    assert set(a["lanes"][deep_idx]) == {hot_lane}


def test_grids_carry_cap_class():
    eng = BatchEngine(BookConfig(cap=256, max_fills=16), n_slots=64, max_t=8)
    orders = _hot_tail_orders(hot_depth=100)
    # Seed books via the exact path, then pack (without running) a probe.
    for i in range(0, len(orders), 90):
        from gome_tpu.bus import colwire

        cols = colwire.decode_order_frame(
            colwire.encode_orders(orders[i : i + 90])
        )
        process_frame(eng, cols)
    from gome_tpu.bus import colwire
    from gome_tpu.engine.frames import _frame_arrays

    probe = [
        Order(uuid="u", oid=f"q{s}", symbol=s, side=Side.BUY, price=700,
              volume=1, action=Action.ADD)
        for s in ["hot", "tail0", "tail1", "tail2"]
    ]
    cols = colwire.decode_order_frame(colwire.encode_orders(probe))
    cp = eng._checkpoint()
    grids = pack_frame_grids(eng, _frame_arrays(eng, cols))
    eng._restore(cp)
    caps = sorted({g[3] for g in grids})
    assert caps == [64, 256]


def test_guard_catches_stale_count_ub():
    """Corrupting count_ub to zero (simulating any host-side accounting
    bug) must cost a re-run, not a truncated book: the gather guard flags
    book_overflow, the exact path deepens the grid's class CONFINED (no
    storage growth), and events stay oracle-exact."""
    eng = BatchEngine(BookConfig(cap=256, max_fills=16), n_slots=64, max_t=8)
    orders = _hot_tail_orders(hot_depth=120)
    got = run_frames(eng, orders, chunk=len(orders) - 20, fast=False)
    cap_before = eng.config.cap
    # Lie: claim every lane is shallow.
    eng._ub_base[:] = 0
    eng._ub_extra[:] = 0
    tail = orders[-20:]
    more = [
        Order(uuid="u", oid=f"z{i}", symbol="hot", side=Side.SALE,
              price=1, volume=3, action=Action.ADD)
        for i in range(6)
    ]
    from gome_tpu.bus import colwire

    cols = colwire.decode_order_frame(colwire.encode_orders(more))
    batch = process_frame(eng, cols)
    assert eng.stats.grid_cap_escalations >= 1
    assert eng.config.cap == cap_before  # storage untouched: confined
    oracle = OracleEngine()
    want = []
    for o in orders[: len(orders) - 20] + tail + more:
        want.extend(oracle.process(o))
    assert (got + batch.to_results()) == want
    eng.verify_books()
    # The escalation loop re-fetched nothing persistent; books verify and
    # a follow-up frame keeps matching.


def test_fast_path_guard_falls_back_transactionally():
    """Same lie on the FAST path: the frame must roll back and re-run
    exactly (frame_fallbacks), still oracle-exact."""
    eng = BatchEngine(BookConfig(cap=256, max_fills=16), n_slots=64, max_t=8)
    orders = _hot_tail_orders(hot_depth=120)
    got = run_frames(eng, orders, chunk=len(orders), fast=True)
    eng._ub_base[:] = 0
    eng._ub_extra[:] = 0
    more = [
        Order(uuid="u", oid=f"z{i}", symbol="hot", side=Side.SALE,
              price=1, volume=3, action=Action.ADD)
        for i in range(6)
    ]
    from gome_tpu.bus import colwire

    cols = colwire.decode_order_frame(colwire.encode_orders(more))
    batch = apply_frame_fast(eng, cols)
    assert eng.stats.frame_fallbacks >= 1
    oracle = OracleEngine()
    want = []
    for o in orders + more:
        want.extend(oracle.process(o))
    assert (got + batch.to_results()) == want
    eng.verify_books()


def test_count_ub_reanchors_on_resolve():
    """After a fast frame resolves, _ub_base must equal the true per-lane
    max-side counts and _ub_extra must drop back to zero (nothing in
    flight)."""
    eng = BatchEngine(BookConfig(cap=256, max_fills=16), n_slots=64, max_t=8)
    orders = _hot_tail_orders(hot_depth=80)
    run_frames(eng, orders, chunk=len(orders), fast=True)
    import jax

    true_counts = np.asarray(jax.device_get(eng.books.count)).max(axis=1)
    np.testing.assert_array_equal(eng._ub_base, true_counts)
    assert int(eng._ub_extra.sum()) == 0
    # And the bound property holds trivially.
    assert (eng.count_ub() >= true_counts).all()


def test_classes_under_mesh_parity():
    """Per-grid cap classes must compose with the symbol mesh: per-shard
    dense grids slice their class from sharded storage with zero
    collectives and stay oracle-exact."""
    from gome_tpu.parallel import make_mesh

    mesh = make_mesh(4)
    eng = BatchEngine(
        BookConfig(cap=256, max_fills=16), n_slots=64, max_t=8, mesh=mesh,
    )
    orders = _hot_tail_orders(hot_depth=100, n_tail=10)
    got = run_frames(eng, orders, chunk=120, fast=True)
    assert got == _oracle(orders)
    eng.verify_books()


def test_cancel_of_deep_lane_after_class_runs():
    """Cancels against a deep lane must see the full book even after
    shallow-class grids ran on other lanes (the slice never leaks)."""
    eng = BatchEngine(BookConfig(cap=256, max_fills=16), n_slots=64, max_t=8)
    orders = _hot_tail_orders(hot_depth=120)
    run_frames(eng, orders, chunk=len(orders), fast=True)
    # Cancel the DEEPEST resting bid on the hot lane (slot near cap 120)
    # plus an in-contract MISS on a drained tail lane (its book was fully
    # consumed by the crossing sell) — the shallow-class grid must handle
    # both without seeing the hot lane's depth.
    dels = [
        Order(uuid="u", oid="h119", symbol="hot", side=Side.BUY,
              price=1000 - 119, volume=0, action=Action.DEL),
        Order(uuid="u", oid="t0-0", symbol="tail0", side=Side.BUY,
              price=500, volume=0, action=Action.DEL),
    ]
    from gome_tpu.bus import colwire

    missed0 = eng.stats.cancels_missed
    cols = colwire.decode_order_frame(colwire.encode_orders(dels))
    batch = apply_frame_fast(eng, cols)
    results = batch.to_results()
    assert len(results) == 1 and results[0].is_cancel
    assert results[0].node.oid == "h119"
    assert eng.stats.cancels_missed == missed0 + 1
    eng.verify_books()

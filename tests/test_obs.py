"""Device-level observability (gome_tpu.obs): cost model attribution,
compile journal, /cost endpoint, live-buffer accounting, and the perf
ratchet CLI — the ISSUE 5 surface."""

import json
import sys
import urllib.request

import numpy as np

import jax.numpy as jnp
import pytest

from gome_tpu.engine import frames
from gome_tpu.engine.batch import BatchEngine
from gome_tpu.engine.book import BookConfig
from gome_tpu.obs import JOURNAL, CompileJournal, costmodel, live
from gome_tpu.obs.compile_journal import frame_combo_detail
from gome_tpu.utils.metrics import Registry


@pytest.fixture(autouse=True)
def _journal_disabled():
    """Every test leaves the process-global journal disabled (the
    hot-path default other tests assume)."""
    yield
    JOURNAL.disable()


def _frame(n, n_symbols=4, seed=0, oid0=0, cancels=0.0):
    rng = np.random.default_rng(seed)
    action = np.ones(n, np.int64)
    if cancels:
        action[rng.random(n) < cancels] = 2
    return dict(
        n=n,
        action=action,
        side=rng.integers(0, 2, n).astype(np.int64),
        kind=np.zeros(n, np.int64),
        price=rng.integers(99_000, 101_000, n).astype(np.int64),
        volume=rng.integers(1, 10, n).astype(np.int64),
        symbols=[f"s{i}" for i in range(n_symbols)],
        symbol_idx=rng.integers(0, n_symbols, n).astype(np.int64),
        uuids=["u0"],
        uuid_idx=np.zeros(n, np.int64),
        oids=np.char.add(
            "o", np.arange(oid0, oid0 + n).astype("U8")
        ).astype("S"),
    )


def _engine(cap=16, n_slots=8, max_t=8):
    return BatchEngine(
        BookConfig(cap=cap, max_fills=4, dtype=jnp.int32),
        n_slots=n_slots, max_t=max_t,
    )


# --- cost model -----------------------------------------------------------


def test_cost_model_keys_present_per_entry():
    """Every hot-path entry reports the attribution keys on the CPU
    backend; fields a backend declines are None (skip-safe), never
    absent."""
    rows = costmodel.entry_report("int32")
    entries = {r["entry"] for r in rows if "error" not in r}
    for want in costmodel.RATCHET_ENTRIES:
        assert want in entries, f"missing cost-model entry {want}"
    for r in rows:
        if "error" in r:
            continue
        for key in (
            "flops", "bytes_accessed", "arithmetic_intensity",
            "argument_bytes", "output_bytes", "temp_bytes", "alias_bytes",
            "peak_hbm_bytes", "jaxpr_eqns", "context",
        ):
            assert key in r, (r["entry"], key)
        if r["flops"] is None:
            pytest.skip("backend returned no cost_analysis")
        assert r["flops"] >= 0
        assert r["bytes_accessed"] > 0
        assert r["jaxpr_eqns"] > 1  # unwrapped past the pjit wrapper
        if r.get("n_ops"):
            assert r["flops_per_order"] == pytest.approx(
                r["flops"] / r["n_ops"]
            )


def test_cost_model_reports_are_memoized():
    assert costmodel.entry_report("int32") is costmodel.entry_report("int32")
    assert (
        costmodel.donation_report("int32")
        is costmodel.donation_report("int32")
    )


def test_donation_report_twin_peak_le_public():
    """The donation-effectiveness report: each _donating twin's peak HBM
    must be <= its public entry's (the footprint win PR 4 claimed; a
    backend without donation support reports equality, never worse)."""
    report = costmodel.donation_report("int32")
    assert {d["entry"] for d in report if "error" not in d} >= {
        "batch_step", "dense_batch_step", "lane_scan"
    }
    for d in report:
        if "error" in d or d["peak_hbm_saved_bytes"] is None:
            continue
        assert (
            d["donating_peak_hbm_bytes"] <= d["public_peak_hbm_bytes"]
        ), d
        # CPU XLA implements donation for these graphs: the twin really
        # aliases buffers (the report is measuring something).
        assert d["donating_alias_bytes"] >= 0


def test_ratchet_metrics_flat_and_deterministic():
    m1 = costmodel.ratchet_metrics("int32")
    assert m1, "no gated metrics produced"
    for name, v in m1.items():
        assert isinstance(v, (int, float)) and v >= 0, (name, v)
    # memoized source => identical on re-read (the determinism the CI
    # gate relies on)
    assert costmodel.ratchet_metrics("int32") == m1


def test_bench_analytics_shape():
    block = costmodel.bench_analytics("int32")
    assert block["dtype"] == "int32"
    assert "batch_step" in block["entries"]
    assert "donation" in block
    json.dumps(block)  # bench payload must be JSON-serializable


# --- compile journal ------------------------------------------------------


def test_journal_records_miss_not_hit():
    """First dispatch of a shape combo lands in the journal; replaying
    the identical frame shape (all hits) records nothing new."""
    reg = Registry()
    j = CompileJournal().install(keep_n=16, registry=reg)
    # swap the global for the engine hook's benefit
    old = frames.JOURNAL
    frames.JOURNAL = j
    try:
        eng = _engine()
        frames.apply_frame_fast(eng, _frame(32, seed=1))
        first = j.entries()
        assert first, "no journal entries after first frame"
        assert all(e["entry"] == "frame_dispatch" for e in first)
        for e in first:
            assert e["seconds"] >= 0
            assert eng.combo_seen(e["key"])
            d = e["detail"]
            for key in (
                "grid_cells", "upload_bytes", "ops_grid_bytes",
                "record_bytes", "fetch_buffer_bytes", "scatter_jaxpr_eqns",
            ):
                assert key in d and d[key] != 0, (key, d)
        frames.apply_frame_fast(eng, _frame(32, seed=2, oid0=32))
        assert len(j.entries()) == len(first), "hit recorded as miss"
        # totals agree with the ring
        assert j.summary()["frame_dispatch"]["count"] == len(first)
        assert "gome_compile_seconds" in reg.render()
    finally:
        frames.JOURNAL = old


def test_journal_ring_is_bounded_but_totals_are_not():
    j = CompileJournal().install(keep_n=4, registry=Registry())
    for i in range(10):
        j.record("e", (i,), 0.01)
    assert len(j.entries()) == 4
    assert [e["key"] for e in j.entries()] == [(6,), (7,), (8,), (9,)]
    assert j.summary()["e"]["count"] == 10
    assert j.summary()["e"]["seconds"] == pytest.approx(0.1)


def test_journal_install_validates_and_disable_clears():
    j = CompileJournal()
    with pytest.raises(ValueError):
        j.install(keep_n=0)
    j.install(keep_n=2, registry=Registry())
    j.record("e", (1,), 0.5)
    assert j.enabled and j.entries()
    j.disable()
    assert not j.enabled and j.entries() == [] and j.summary() == {}
    j.record("e", (1,), 0.5)  # no-op, no crash
    assert j.entries() == []


def test_disabled_journal_allocates_nothing():
    """The no-op-singleton guard (same pattern as tests/test_trace.py):
    a disabled journal on the frame hot path is one attribute check and
    zero allocations."""
    j = CompileJournal()  # never installed
    assert not j.enabled

    def drill(n):
        i = 0
        while i < n:
            if j.enabled:
                raise AssertionError("unreachable")
            j.record("frame_dispatch", (1, 2, 3), 0.0)
            i += 1

    drill(64)  # warm any lazy caches
    before = sys.getallocatedblocks()
    drill(200)
    after = sys.getallocatedblocks()
    assert after - before <= 2, f"hot-path hooks allocated {after - before}"


def test_frame_combo_detail_arithmetic():
    combo = (8, 16, 64, True, 256, 4, 512, 64, 8)
    d = frame_combo_detail("int32", combo)
    assert d["grid_cells"] == 128
    assert d["upload_bytes"] == 256 * (7 * 4 + 4)
    assert d["ops_grid_bytes"] == 128 * (3 * 4 + 4 * 4)
    assert d["record_bytes"] == 128 * 4 * 5 * 4
    assert d["fetch_buffer_bytes"] == (7 * 512 + 2 * 64) * 4 + 8 * 4 * 4
    assert d["dense"] is True


# --- /cost endpoint -------------------------------------------------------


def test_cost_endpoint_http_validity():
    from gome_tpu.config import Config, EngineConfig, OpsConfig
    from gome_tpu.service.app import EngineService

    cfg = Config(
        engine=EngineConfig(cap=16, max_fills=4, n_slots=4, max_t=4,
                            dtype="int32"),
        ops=OpsConfig(port=0, enabled=True),
    )
    svc = EngineService(cfg)
    assert JOURNAL.enabled  # ops.cost armed the journal at boot
    # one fast-path frame so the journal carries a real combo
    frames.apply_frame_fast(svc.engine.batch, _frame(16, seed=3))
    svc.ops.start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{svc.ops.port}/cost", timeout=30
        ) as r:
            assert r.status == 200
            assert r.headers["Content-Type"] == "application/json"
            doc = json.loads(r.read().decode())
        assert doc["compile_journal"]["enabled"] is True
        assert doc["compile_journal"]["entries"], "journal empty over HTTP"
        assert doc["live_buffers"]["total"]["count"] > 0
        assert doc["live_buffers"]["subsystems"]["engine_books"]["bytes"] > 0
        entries = {
            e["entry"] for e in doc["cost_model"]["entries"]
            if "error" not in e
        }
        assert "batch_step" in entries
        donation = {d["entry"]: d for d in doc["cost_model"]["donation"]}
        assert "batch_step" in donation
        # /metrics carries the new families too
        with urllib.request.urlopen(
            f"http://127.0.0.1:{svc.ops.port}/metrics", timeout=10
        ) as r:
            metrics = r.read().decode()
        assert "gome_compile_seconds" in metrics
        assert 'gome_hbm_resident_bytes{subsystem="engine_books"}' in metrics
        assert "gome_live_arrays" in metrics
    finally:
        svc.ops.stop()


# --- live-buffer accounting ----------------------------------------------


def test_live_array_stats_sees_allocations():
    import jax

    base = live.live_array_stats()
    held = [jnp.zeros((128,), jnp.int32) for _ in range(4)]
    jax.block_until_ready(held)
    now = live.live_array_stats()
    assert now["count"] >= base["count"] + 4
    assert now["bytes"] >= base["bytes"] + 4 * 128 * 4
    del held
    after = live.live_array_stats()
    assert after["count"] <= base["count"] + 1


def test_pytree_stats_counts_leaves():
    eng = _engine()
    s = live.pytree_stats(eng.books)
    assert s["count"] == 7  # BookState leaves
    assert s["bytes"] > 0


def test_leak_detector_on_scripted_loops():
    """A loop that retains a buffer per step is flagged; a loop whose
    allocations die each step is flat."""
    leak: list = []

    def leaking():
        leak.append(jnp.zeros((64,), jnp.int32) + 1)

    report = live.leak_report(leaking, steps=4, settle=2)
    assert report["leaked"] >= 4, report
    with pytest.raises(AssertionError):
        live.assert_steady_state(leaking, steps=3, settle=1)
    leak.clear()

    def steady():
        x = jnp.zeros((64,), jnp.int32) + 1
        x.block_until_ready()

    report = live.assert_steady_state(steady, steps=4, settle=2)
    assert report["leaked"] <= 0


def test_live_monitor_gauges():
    eng = _engine()
    reg = Registry()
    mon = live.LiveBufferMonitor().register("books", lambda: eng.books)
    mon.export(reg)
    text = reg.render()
    assert 'gome_hbm_resident_bytes{subsystem="books"}' in text
    snap = mon.snapshot()
    assert snap["subsystems"]["books"]["bytes"] == live.pytree_stats(
        eng.books
    )["bytes"]


# --- perf ratchet CLI -----------------------------------------------------


@pytest.fixture(scope="module")
def ratchet():
    sys.path.insert(
        0, str(__import__("pathlib").Path(__file__).parent.parent / "scripts")
    )
    import perf_ratchet

    return perf_ratchet


def test_perf_ratchet_end_to_end(ratchet, tmp_path, capsys):
    base = tmp_path / "PERF_BASELINE.json"
    report = tmp_path / "report.json"

    # no baseline -> explicit failure telling the operator what to do
    assert ratchet.main(["--baseline", str(base)]) == 1

    # --update-baseline writes it; the gate then passes
    assert ratchet.main(
        ["--baseline", str(base), "--update-baseline"]
    ) == 0
    doc = json.loads(base.read_text())
    assert doc["metrics"] and doc["jax"]
    assert "frame_drill.compile_count" in doc["metrics"]
    assert ratchet.main(
        ["--baseline", str(base), "--report", str(report)]
    ) == 0
    gated = json.loads(report.read_text())["gated"]
    # Same metric set; the analytic rows are bit-identical run to run,
    # the wall-clock admit rows (gated with 3x headroom) are not.
    assert set(gated) == set(doc["metrics"])
    wallclock = set(ratchet.WALLCLOCK_GATED)
    for name, v in gated.items():
        if name not in wallclock:
            assert v == doc["metrics"][name], name
    # The wall-clock rows carry their wide per-metric tolerance in the
    # committed baseline document.
    for name in wallclock & set(doc["metrics"]):
        assert doc["tolerance"][name] == ratchet.WALLCLOCK_TOLERANCE

    # deliberate fixture regression: shrink a baseline value -> the
    # current (unchanged) code now reads as regressed and the gate fails
    doc["metrics"]["batch_step.flops_per_order"] *= 0.5
    doc["metrics"]["frame_drill.compile_count"] -= 1
    base.write_text(json.dumps(doc))
    assert ratchet.main(["--baseline", str(base)]) == 1
    out = capsys.readouterr().out
    assert "REGRESSION batch_step.flops_per_order" in out
    assert "REGRESSION frame_drill.compile_count" in out


def test_perf_ratchet_jax_version_mismatch_downgrades_xla_gates(
    ratchet, tmp_path
):
    base = tmp_path / "PERF_BASELINE.json"
    assert ratchet.main(
        ["--baseline", str(base), "--update-baseline"]
    ) == 0
    doc = json.loads(base.read_text())
    doc["jax"] = "0.0.0-other"
    # an XLA metric "regression" under a DIFFERENT toolchain is advisory…
    doc["metrics"]["batch_step.flops_per_order"] *= 0.5
    base.write_text(json.dumps(doc))
    assert ratchet.main(["--baseline", str(base)]) == 0
    # …but the version-independent compile count still gates hard
    doc["metrics"]["frame_drill.compile_count"] -= 1
    base.write_text(json.dumps(doc))
    assert ratchet.main(["--baseline", str(base)]) == 1


def test_committed_baseline_gates_green():
    """The repo's committed PERF_BASELINE.json must pass against the
    current code on this toolchain — CI runs exactly this gate."""
    import pathlib
    import subprocess

    root = pathlib.Path(__file__).parent.parent
    out = subprocess.run(
        [sys.executable, str(root / "scripts" / "perf_ratchet.py")],
        capture_output=True, text=True, cwd=root,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0, out.stdout + out.stderr

"""Shard-routing tests: stable hashing, sharded-engine parity vs both the
single engine and the oracle, per-shard isolation."""

import pytest

from gome_tpu.engine import BookConfig, MatchEngine
from gome_tpu.oracle import OracleEngine
from gome_tpu.parallel import ShardedEngine, ShardRouter, fnv1a
from gome_tpu.utils.streams import multi_symbol_stream


def test_routing_is_stable_and_total():
    r = ShardRouter(8)
    for sym in ("eth2usdt", "btc2usdt", "sym123", ""):
        assert 0 <= r.route(sym) < 8
        assert r.route(sym) == r.route(sym)
    # fnv1a is the cross-process-stable hash (Python's is salted)
    assert fnv1a("eth2usdt") == fnv1a("eth2usdt")
    assert fnv1a("a") != fnv1a("b")
    with pytest.raises(ValueError):
        ShardRouter(0)


def test_sharded_engine_matches_oracle():
    """4 shards, 12 symbols, mixed flow with cancels: the merged event
    stream must equal the oracle's (global FIFO) when processed with exact
    arrival-order boundaries."""
    orders = multi_symbol_stream(
        n=400, n_symbols=12, seed=4, cancel_prob=0.2
    )
    oracle = OracleEngine()
    expected = []
    for o in orders:
        expected.extend(oracle.process(o))

    eng = ShardedEngine(
        4, config=BookConfig(cap=32, max_fills=8), n_slots=8, max_t=16
    )
    for o in orders:
        eng.mark(o)
    got = eng.process_with_arrival_order(orders)
    assert got == expected


def test_sharded_engine_batched_exact_global_order():
    """The DEFAULT batched path emits the byte-identical global event
    stream of a single engine (per-order arrival tags merge shards into
    exact single-FIFO order — VERDICT r1 weak #5 retired)."""
    orders = multi_symbol_stream(n=300, n_symbols=9, seed=6, cancel_prob=0.15)
    single = MatchEngine(config=BookConfig(cap=32, max_fills=8), n_slots=16)
    for o in orders:
        single.mark(o)
    expected = single.process(orders)

    eng = ShardedEngine(
        3, config=BookConfig(cap=32, max_fills=8), n_slots=8, max_t=16
    )
    for o in orders:
        eng.mark(o)
    got = eng.process(orders)
    assert got == expected


def test_sharded_engine_default_process_matches_oracle():
    """Sharded default process() == oracle global FIFO, including cancels
    and chunked feeding (arrival tags are per-batch, so chunk boundaries
    must not disturb the merge)."""
    orders = multi_symbol_stream(n=400, n_symbols=12, seed=11, cancel_prob=0.2)
    oracle = OracleEngine()
    expected = []
    for o in orders:
        expected.extend(oracle.process(o))

    eng = ShardedEngine(
        4, config=BookConfig(cap=32, max_fills=8), n_slots=8, max_t=16
    )
    for o in orders:
        eng.mark(o)
    got = []
    for i in range(0, len(orders), 97):
        got.extend(eng.process(orders[i : i + 97]))
    assert got == expected


def test_shards_isolated():
    eng = ShardedEngine(4, config=BookConfig(cap=16, max_fills=4), n_slots=4)
    from gome_tpu.fixed import scale
    from gome_tpu.types import Order, Side

    o = Order(uuid="u", oid="1", symbol="onlysym", side=Side.BUY,
              price=scale(1.0), volume=scale(1.0))
    eng.mark(o)
    eng.process([o])
    owner = eng.router.route("onlysym")
    for i, shard in enumerate(eng.shards):
        count = int(shard.batch.lane_books().count.sum())
        assert count == (1 if i == owner else 0)

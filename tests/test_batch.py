"""Parity tests for the batched (scan x vmap) engine vs the Python oracle.

The batched engine must reproduce the reference's sequential global event
stream exactly — including cross-symbol interleaving by arrival order —
despite executing S symbol lanes in parallel (SURVEY §5.2 serialized-
per-symbol invariant)."""

import pytest

from gome_tpu.engine import BatchEngine, BookConfig
from gome_tpu.fixed import scale
from gome_tpu.oracle import OracleEngine
from gome_tpu.types import Action, Order, Side
from gome_tpu.utils.streams import mixed_stream, multi_symbol_stream

CFG = BookConfig(cap=128, max_fills=32)


def run_parity(orders, n_slots=16, max_t=8, config=CFG, chunk=50):
    oracle = OracleEngine()
    engine = BatchEngine(config, n_slots=n_slots, max_t=max_t)
    for start in range(0, len(orders), chunk):
        batch = orders[start : start + chunk]
        expected = []
        for order in batch:
            expected.extend(oracle.process(order))
        got = engine.process(batch)
        assert got == expected, f"batch starting at {start} diverged"
    # Final per-symbol depth must also agree.
    from gome_tpu.engine.book import book_depth
    import jax

    for symbol, book in oracle.books.items():
        lane = engine.symbol_lane(symbol)
        lane_state = jax.tree.map(lambda a: a[lane], engine.books)
        for side in (Side.BUY, Side.SALE):
            prices, volumes, n = jax.device_get(
                book_depth(lane_state, int(side), config.cap)
            )
            got_depth = [(int(prices[i]), int(volumes[i])) for i in range(int(n))]
            assert got_depth == book.depth(side), f"{symbol}/{side} depth"
    return engine, oracle


def test_two_symbol_interleaved_stream():
    def o(oid, sym, side, p, v):
        return Order(
            uuid="u", oid=str(oid), symbol=sym, side=side,
            price=scale(p), volume=scale(v),
        )

    orders = [
        o(1, "aaa", Side.SALE, 1.00, 0.5),
        o(2, "bbb", Side.SALE, 2.00, 0.5),
        o(3, "aaa", Side.BUY, 1.00, 0.3),
        o(4, "bbb", Side.BUY, 2.50, 0.7),
        o(5, "aaa", Side.BUY, 1.00, 0.4),
    ]
    engine, oracle = run_parity(orders, n_slots=4, max_t=4)


def test_multi_symbol_poisson_parity():
    """BASELINE config 3 shape (downscaled): uniform multi-symbol flow."""
    orders = multi_symbol_stream(n=600, n_symbols=12, seed=4, cancel_prob=0.15)
    run_parity(orders, n_slots=16, max_t=8)


def test_multi_symbol_zipf_parity():
    """BASELINE config 4 shape (downscaled): Zipf-skewed arrival rates.
    The hot symbol overflows max_t per grid, exercising the drain loop."""
    orders = multi_symbol_stream(
        n=500, n_symbols=20, seed=9, zipf_a=1.2, cancel_prob=0.1
    )
    run_parity(orders, n_slots=24, max_t=4)


def test_single_symbol_batch_matches_sequential():
    """All orders on one lane: batch must equal pure sequential semantics."""
    orders = mixed_stream(n=300, seed=2, cancel_prob=0.2)
    run_parity(orders, n_slots=2, max_t=8, chunk=64)


def test_lane_overflow_error():
    engine = BatchEngine(CFG, n_slots=2, max_t=4)
    orders = [
        Order(
            uuid="u", oid=str(i), symbol=f"s{i}", side=Side.BUY,
            price=scale(1.0), volume=scale(1.0),
        )
        for i in range(3)
    ]
    with pytest.raises(ValueError, match="n_slots"):
        engine.process(orders)


def test_max_t_spill_preserves_fifo():
    """7 same-symbol ops with max_t=2 forces 4 grids; FIFO must hold."""
    def o(oid, side, p, v, action=Action.ADD):
        return Order(
            uuid="u", oid=str(oid), symbol="s", side=side,
            price=scale(p), volume=scale(v), action=action,
        )

    orders = [
        o(1, Side.SALE, 1.00, 0.2),
        o(2, Side.SALE, 1.00, 0.2),
        o(3, Side.SALE, 1.00, 0.2),
        o(4, Side.BUY, 1.00, 0.5),  # fills 1 fully, 2 fully, 3 partially
        o(2, Side.SALE, 1.00, 0.2, Action.DEL),  # already filled -> miss
        o(3, Side.SALE, 1.00, 0.2, Action.DEL),  # cancels remaining 0.1
        o(5, Side.BUY, 1.00, 0.3),  # book now empty -> rests
    ]
    run_parity(orders, n_slots=2, max_t=2, chunk=len(orders))


def test_int32_book_mode():
    """BookConfig(dtype=int32) must run without unsafe casts (lots/prices in
    int32 range; cumsum stays below 2^31 with small volumes)."""
    import jax.numpy as jnp

    cfg32 = BookConfig(cap=32, max_fills=8, dtype=jnp.int32)
    engine = BatchEngine(cfg32, n_slots=4, max_t=4)
    orders = [
        Order(uuid="u", oid="1", symbol="s", side=Side.SALE, price=100, volume=5),
        Order(uuid="u", oid="2", symbol="s", side=Side.BUY, price=100, volume=3),
    ]
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("error")  # unsafe-cast FutureWarning -> error
        events = engine.process(orders)
    assert len(events) == 1 and events[0].match_volume == 3
    assert engine.books.price.dtype == jnp.int32


def test_batch_overflow_collects_other_events():
    """One op overflowing max_fills must not destroy the rest of the batch's
    event stream (BatchOverflowError carries it)."""
    from gome_tpu.engine.batch import BatchOverflowError

    cfg = BookConfig(cap=32, max_fills=2)
    engine = BatchEngine(cfg, n_slots=4, max_t=8)

    def o(oid, sym, side, p, v):
        return Order(
            uuid="u", oid=str(oid), symbol=sym, side=side,
            price=scale(p), volume=scale(v),
        )

    orders = [
        # lane "a": 4 small asks then a buy crossing all 4 -> 4 fills > K=2
        o(1, "a", Side.SALE, 1.00, 0.1),
        o(2, "a", Side.SALE, 1.00, 0.1),
        o(3, "a", Side.SALE, 1.00, 0.1),
        o(4, "a", Side.SALE, 1.00, 0.1),
        o(5, "a", Side.BUY, 1.00, 0.4),
        # lane "b": a clean single fill that must survive
        o(6, "b", Side.SALE, 2.00, 0.5),
        o(7, "b", Side.BUY, 2.00, 0.5),
    ]
    with pytest.raises(BatchOverflowError) as exc_info:
        engine.process(orders)
    err = exc_info.value
    assert len(err.failures) == 1 and err.failures[0][0].oid == "5"
    b_fills = [ev for ev in err.events if ev.node.symbol == "b"]
    assert len(b_fills) == 1 and b_fills[0].match_volume == scale(0.5)

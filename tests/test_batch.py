"""Parity tests for the batched (scan x vmap) engine vs the Python oracle.

The batched engine must reproduce the reference's sequential global event
stream exactly — including cross-symbol interleaving by arrival order —
despite executing S symbol lanes in parallel (SURVEY §5.2 serialized-
per-symbol invariant)."""

import pytest

from gome_tpu.engine import BatchEngine, BookConfig, CapacityError
from gome_tpu.fixed import scale
from gome_tpu.oracle import OracleEngine
from gome_tpu.types import Action, Order, Side
from gome_tpu.utils.streams import mixed_stream, multi_symbol_stream

CFG = BookConfig(cap=128, max_fills=32)


def run_parity(orders, n_slots=16, max_t=8, config=CFG, chunk=50):
    oracle = OracleEngine()
    engine = BatchEngine(config, n_slots=n_slots, max_t=max_t)
    for start in range(0, len(orders), chunk):
        batch = orders[start : start + chunk]
        expected = []
        for order in batch:
            expected.extend(oracle.process(order))
        got = engine.process(batch)
        assert got == expected, f"batch starting at {start} diverged"
    # Final per-symbol depth must also agree.
    from gome_tpu.engine.book import book_depth
    import jax

    for symbol, book in oracle.books.items():
        lane = engine.symbol_lane(symbol)
        lane_state = jax.tree.map(lambda a: a[lane], engine.books)
        for side in (Side.BUY, Side.SALE):
            prices, volumes, n = jax.device_get(
                book_depth(lane_state, int(side), engine.config.cap)
            )
            got_depth = [(int(prices[i]), int(volumes[i])) for i in range(int(n))]
            assert got_depth == book.depth(side), f"{symbol}/{side} depth"
    return engine, oracle


def test_two_symbol_interleaved_stream():
    def o(oid, sym, side, p, v):
        return Order(
            uuid="u", oid=str(oid), symbol=sym, side=side,
            price=scale(p), volume=scale(v),
        )

    orders = [
        o(1, "aaa", Side.SALE, 1.00, 0.5),
        o(2, "bbb", Side.SALE, 2.00, 0.5),
        o(3, "aaa", Side.BUY, 1.00, 0.3),
        o(4, "bbb", Side.BUY, 2.50, 0.7),
        o(5, "aaa", Side.BUY, 1.00, 0.4),
    ]
    engine, oracle = run_parity(orders, n_slots=4, max_t=4)


def test_multi_symbol_poisson_parity():
    """BASELINE config 3 shape (downscaled): uniform multi-symbol flow."""
    orders = multi_symbol_stream(n=600, n_symbols=12, seed=4, cancel_prob=0.15)
    run_parity(orders, n_slots=16, max_t=8)


def test_multi_symbol_zipf_parity():
    """BASELINE config 4 shape (downscaled): Zipf-skewed arrival rates.
    The hot symbol overflows max_t per grid, exercising the drain loop."""
    orders = multi_symbol_stream(
        n=500, n_symbols=20, seed=9, zipf_a=1.2, cancel_prob=0.1
    )
    run_parity(orders, n_slots=24, max_t=4)


def test_single_symbol_batch_matches_sequential():
    """All orders on one lane: batch must equal pure sequential semantics."""
    orders = mixed_stream(n=300, seed=2, cancel_prob=0.2)
    run_parity(orders, n_slots=2, max_t=8, chunk=64)


def test_lane_overflow_error_when_growth_disabled():
    engine = BatchEngine(CFG, n_slots=2, max_t=4, auto_grow=False)
    orders = [
        Order(
            uuid="u", oid=str(i), symbol=f"s{i}", side=Side.BUY,
            price=scale(1.0), volume=scale(1.0),
        )
        for i in range(3)
    ]
    with pytest.raises(CapacityError, match="n_slots"):
        engine.process(orders)


def test_growth_ceilings_backpressure():
    """max_slots / max_cap bound auto-grow with a loud CapacityError instead
    of unbounded HBM growth (explicit backpressure)."""
    engine = BatchEngine(CFG, n_slots=2, max_t=4, max_slots=4)
    orders = [
        Order(
            uuid="u", oid=str(i), symbol=f"s{i}", side=Side.BUY,
            price=scale(1.0), volume=scale(1.0),
        )
        for i in range(5)
    ]
    with pytest.raises(CapacityError, match="max_slots"):
        engine.process(orders)

    # cap ceiling: CFG.cap resting orders + one more on a single side
    small = BatchEngine(CFG, n_slots=1, max_t=CFG.cap + 1, max_cap=CFG.cap)
    orders = [
        Order(
            uuid="u", oid=str(i), symbol="s", side=Side.BUY,
            price=(i + 1) * 1_000_000, volume=scale(1.0),
        )
        for i in range(CFG.cap + 1)
    ]
    with pytest.raises(CapacityError, match="max_cap"):
        small.process(orders)

    with pytest.raises(ValueError, match="max_cap"):
        BatchEngine(CFG, n_slots=1, max_cap=CFG.cap // 2)


def test_lane_auto_growth():
    """More distinct symbols than provisioned lanes: the engine grows the
    stacked book and stays exact (the reference has no lane limit because
    Redis keys are dynamic)."""
    engine = BatchEngine(CFG, n_slots=2, max_t=4)
    oracle = OracleEngine()
    orders = []
    for i in range(6):
        orders.append(
            Order(
                uuid="u", oid=f"a{i}", symbol=f"s{i}", side=Side.SALE,
                price=scale(1.0), volume=scale(0.5),
            )
        )
        orders.append(
            Order(
                uuid="u", oid=f"b{i}", symbol=f"s{i}", side=Side.BUY,
                price=scale(1.0), volume=scale(0.5),
            )
        )
    expected = []
    for o in orders:
        expected.extend(oracle.process(o))
    got = engine.process(orders)
    assert got == expected
    assert engine.n_slots >= 6 and engine.stats.lane_growths >= 1


def test_max_t_spill_preserves_fifo():
    """7 same-symbol ops with max_t=2 forces 4 grids; FIFO must hold."""
    def o(oid, side, p, v, action=Action.ADD):
        return Order(
            uuid="u", oid=str(oid), symbol="s", side=side,
            price=scale(p), volume=scale(v), action=action,
        )

    orders = [
        o(1, Side.SALE, 1.00, 0.2),
        o(2, Side.SALE, 1.00, 0.2),
        o(3, Side.SALE, 1.00, 0.2),
        o(4, Side.BUY, 1.00, 0.5),  # fills 1 fully, 2 fully, 3 partially
        o(2, Side.SALE, 1.00, 0.2, Action.DEL),  # already filled -> miss
        o(3, Side.SALE, 1.00, 0.2, Action.DEL),  # cancels remaining 0.1
        o(5, Side.BUY, 1.00, 0.3),  # book now empty -> rests
    ]
    run_parity(orders, n_slots=2, max_t=2, chunk=len(orders))


def test_int32_book_mode():
    """BookConfig(dtype=int32) must run without unsafe casts (lots/prices in
    int32 range; cumsum stays below 2^31 with small volumes)."""
    import jax.numpy as jnp

    cfg32 = BookConfig(cap=32, max_fills=8, dtype=jnp.int32)
    engine = BatchEngine(cfg32, n_slots=4, max_t=4)
    orders = [
        Order(uuid="u", oid="1", symbol="s", side=Side.SALE, price=100, volume=5),
        Order(uuid="u", oid="2", symbol="s", side=Side.BUY, price=100, volume=3),
    ]
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("error")  # unsafe-cast FutureWarning -> error
        # the donating dispatch twins deliberately accept partial buffer
        # reuse (engine/batch.py filters this globally; catch_warnings
        # resets filters, so re-declare it inside the error scope)
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable"
        )
        events = engine.process(orders)
    assert len(events) == 1 and events[0].match_volume == 3
    assert engine.books.price.dtype == jnp.int32


def test_fill_record_overflow_escalates_exactly():
    """An op crossing more resting orders than max_fills must still decode a
    complete event stream: the engine re-runs that lane with a larger record
    budget from the pre-batch snapshot (batch.py _run_exact phase 2)."""
    cfg = BookConfig(cap=32, max_fills=2)
    engine = BatchEngine(cfg, n_slots=4, max_t=8)
    oracle = OracleEngine()

    def o(oid, sym, side, p, v):
        return Order(
            uuid="u", oid=str(oid), symbol=sym, side=side,
            price=scale(p), volume=scale(v),
        )

    orders = [
        # lane "a": 4 small asks then a buy crossing all 4 -> 4 fills > K=2
        o(1, "a", Side.SALE, 1.00, 0.1),
        o(2, "a", Side.SALE, 1.00, 0.1),
        o(3, "a", Side.SALE, 1.00, 0.1),
        o(4, "a", Side.SALE, 1.00, 0.1),
        o(5, "a", Side.BUY, 1.00, 0.4),
        # lane "b": a clean single fill in the same grid
        o(6, "b", Side.SALE, 2.00, 0.5),
        o(7, "b", Side.BUY, 2.00, 0.5),
    ]
    expected = []
    for order in orders:
        expected.extend(oracle.process(order))
    got = engine.process(orders)
    assert got == expected
    assert engine.stats.fill_record_escalations == 1
    a_fills = [ev for ev in got if ev.node.symbol == "a" and not ev.is_cancel]
    assert len(a_fills) == 4


def test_book_capacity_overflow_grows_and_stays_exact():
    """Resting more orders than cap must grow the book, not drop inserts
    (batch.py _run_exact phase 1); depth afterwards matches the oracle."""
    cfg = BookConfig(cap=4, max_fills=4)
    orders = [
        Order(
            uuid="u", oid=str(i), symbol="s", side=Side.SALE,
            price=scale(1.0 + i / 100), volume=scale(1.0),
        )
        for i in range(10)
    ]
    engine, oracle = run_parity(orders, n_slots=2, max_t=4, config=cfg)
    assert engine.config.cap >= 10
    assert engine.stats.cap_escalations >= 1


def test_prepool_race_drops_unmarked_add():
    """MatchEngine facade: an ADD whose pre-pool mark was cleared by an
    earlier-processed DEL must be dropped (engine.go:58-62, SURVEY §2.3.3)."""
    from gome_tpu.engine import MatchEngine

    engine = MatchEngine(CFG, n_slots=2, max_t=4)
    add = Order(
        uuid="u", oid="1", symbol="s", side=Side.SALE,
        price=scale(1.0), volume=scale(1.0),
    )
    delete = Order(
        uuid="u", oid="1", symbol="s", side=Side.SALE,
        price=scale(1.0), volume=scale(1.0), action=Action.DEL,
    )
    engine.mark(add)
    # DEL consumed first clears the mark; cancel misses (nothing resting).
    assert engine.process([delete]) == []
    # The queued ADD is now consumed: dropped, no book mutation.
    assert engine.process([add]) == []
    assert engine.stats.dropped_no_prepool == 1
    assert engine.stats.cancels_missed == 1
    # A correctly marked ADD still rests and can be cancelled with an event.
    engine.mark(add)
    assert engine.process([add]) == []
    events = engine.process([delete])
    assert len(events) == 1 and events[0].is_cancel
    assert events[0].node.volume == scale(1.0)

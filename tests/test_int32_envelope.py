"""int32 operating-envelope guarantees: saturating depth prefix sums keep
fills exact when crossed depth exceeds 2^31, and the per-order lot ceiling
is enforced at ingestion (engine/step.py SAT32_MAX / LOT_MAX32)."""

import numpy as np
import pytest

import jax.numpy as jnp

from gome_tpu.engine import BatchEngine, BookConfig, batch_step, init_books
from gome_tpu.engine.book import DeviceOp
from gome_tpu.engine.step import LOT_MAX32
from gome_tpu.types import Order, Side


def _grid(config, rows):
    """rows: list of (action, side, price, volume, oid) on one lane."""
    t = len(rows)
    d = np.dtype(config.dtype)
    g = dict(
        action=np.zeros((1, t), np.int32),
        side=np.zeros((1, t), np.int32),
        is_market=np.zeros((1, t), np.int32),
        price=np.zeros((1, t), d), volume=np.zeros((1, t), d),
        oid=np.zeros((1, t), d), uid=np.ones((1, t), d),
    )
    for i, (a, s, p, v, o) in enumerate(rows):
        g["action"][0, i] = a
        g["side"][0, i] = s
        g["price"][0, i] = p
        g["volume"][0, i] = v
        g["oid"][0, i] = o
    return DeviceOp(**g)


def test_deep_book_prefix_sum_saturates_exactly():
    """Rest 8 asks of LOT_MAX32 lots each (total ~8.6e9, far past 2^31),
    then a taker sweeps part of it: fills must match the int64 book."""
    rows = [(1, 1, 100 + i, LOT_MAX32, i + 1) for i in range(8)]
    rows.append((1, 0, 200, LOT_MAX32, 99))  # BUY taker: crosses everything
    results = {}
    for dt in (jnp.int32, jnp.int64):
        config = BookConfig(cap=16, max_fills=16, dtype=dt)
        books = init_books(config, 1)
        books, outs = batch_step(config, books, _grid(config, rows))
        results[dt] = (
            np.asarray(outs.n_fills)[0, -1],
            np.asarray(outs.fill_qty)[0, -1],
            np.asarray(outs.taker_remaining)[0, -1],
            np.asarray(books.count)[0],
        )
    n32, q32, r32, c32 = results[jnp.int32]
    n64, q64, r64, c64 = results[jnp.int64]
    assert n32 == n64 == 1  # taker volume == one maker's lots
    np.testing.assert_array_equal(q32, q64)
    assert r32 == r64 == 0
    np.testing.assert_array_equal(c32, c64)


def test_deep_book_partial_sweep_matches_int64():
    """Taker volume lands mid-way through a >2^31 crossed prefix."""
    maker = LOT_MAX32 // 4  # 9 makers total ~2.4e9 lots > 2^31
    rows = [(1, 1, 100 + i, maker, i + 1) for i in range(9)]
    taker_vol = maker * 3 + 12345  # crosses 3 makers + part of the 4th
    rows.append((1, 0, 200, taker_vol, 99))
    results = {}
    for dt in (jnp.int32, jnp.int64):
        config = BookConfig(cap=16, max_fills=16, dtype=dt)
        books = init_books(config, 1)
        books, outs = batch_step(config, books, _grid(config, rows))
        results[dt] = (
            np.asarray(outs.n_fills)[0, -1],
            np.asarray(outs.fill_qty)[0, -1].astype(np.int64),
            np.asarray(outs.maker_remaining)[0, -1].astype(np.int64),
        )
    assert results[jnp.int32][0] == results[jnp.int64][0] == 4
    np.testing.assert_array_equal(results[jnp.int32][1], results[jnp.int64][1])
    np.testing.assert_array_equal(results[jnp.int32][2], results[jnp.int64][2])


def test_lot_ceiling_enforced_at_ingestion():
    eng = BatchEngine(BookConfig(cap=16, max_fills=4, dtype=jnp.int32), n_slots=2)
    big = Order(uuid="u", oid="o", symbol="s", side=Side.BUY,
                price=100, volume=LOT_MAX32 + 1)
    with pytest.raises(ValueError, match="lot ceiling"):
        eng.process([big])
    with pytest.raises(ValueError, match="lot ceiling"):
        eng.process_columnar([big])
    ok = Order(uuid="u", oid="o2", symbol="s", side=Side.BUY,
               price=100, volume=LOT_MAX32)
    assert eng.process([ok]) == []  # rests quietly at the ceiling

"""Order-lifecycle tracing (ISSUE 2): span propagation gateway → bus →
consumer, per-stage histograms on /metrics, the flight recorder behind
/trace, labeled metric families, the Prometheus exposition golden, and
the no-op-recorder hot-path guard.
"""

from __future__ import annotations

import itertools
import json
import sys
import time
import urllib.request

import pytest

from gome_tpu.api import order_pb2 as pb
from gome_tpu.bus import decode_orders_batch
from gome_tpu.bus.codec import decode_order, encode_order
from gome_tpu.bus.colwire import decode_order_frame, encode_orders
from gome_tpu.types import Action, Order, Side
from gome_tpu.utils.metrics import Histogram, Registry
from gome_tpu.utils.trace import (
    STAGES,
    TRACER,
    FlightRecorder,
    Tracer,
    decode_context,
    encode_context,
)


@pytest.fixture
def global_tracer():
    """Arm the process-global tracer with a scripted clock + scripted ids
    and a private registry; restore the disabled zero-overhead state (and
    the real clock) afterwards, whatever the test did."""
    ticks = itertools.count(1)
    ids = itertools.count(1)
    registry = Registry()
    recorder = FlightRecorder(keep_n=16, slow_threshold_s=5.0)
    TRACER.install(
        recorder,
        registry=registry,
        clock=lambda: next(ticks) * 1e-3,  # 1ms per reading, monotone
        new_id=lambda: f"trace-{next(ids)}",
    )
    try:
        yield TRACER, recorder, registry
    finally:
        TRACER.disable()
        TRACER.clock = time.perf_counter
        TRACER._new_id = None


def order(oid="o1", trace=None, side=Side.SALE, action=Action.ADD):
    return Order(
        uuid="u1", oid=oid, symbol="eth2usdt", side=side,
        price=100, volume=5, action=action, trace=trace,
    )


# --- trace-context + wire propagation ------------------------------------


def test_context_codec_roundtrip():
    ctx = encode_context("abc-123", 1.25)
    assert decode_context(ctx) == ("abc-123", 1.25)
    # A bare id (header written by a non-tracing producer) still decodes.
    assert decode_context("abc-123") == ("abc-123", 0.0)


def test_trace_context_roundtrips_json_codec():
    o = order(trace="tid-1@0.500000000")
    d = decode_order(encode_order(o))
    assert d == o  # trace is compare=False, but the rest is identical
    assert d.trace == "tid-1@0.500000000"
    # ...and through the batch decoder (native parsers decline unknown
    # keys and must fall back to the exact json path).
    d2 = decode_orders_batch([encode_order(o)])[0]
    assert d2.trace == "tid-1@0.500000000"


def test_untraced_json_wire_is_reference_shaped():
    body = encode_order(order())
    assert b"Trace" not in body  # reference parity: no extension field


def test_trace_context_roundtrips_order_frame():
    traced = order(oid="a", trace="tid-9@2.000000000")
    plain = order(oid="b")
    frame = encode_orders([traced, plain])
    assert frame[:4] == b"GCO3"
    cols = decode_order_frame(frame)
    assert cols["trace"].tolist() == [b"tid-9@2.000000000", b""]
    # Untraced batches stay byte-identical GCO2 (zero wire overhead).
    frame2 = encode_orders([plain])
    assert frame2[:4] == b"GCO2"
    assert "trace" not in decode_order_frame(frame2)


def test_amqp_headers_survive_broker_hop():
    from gome_tpu.bus.amqp import AmqpQueue
    from gome_tpu.bus.fakebroker import FakeBroker

    broker = FakeBroker().start()
    try:
        q = AmqpQueue("doOrder", port=broker.port)
        try:
            assert q.supports_headers
            q.publish(b"payload-0")  # no headers
            q.publish(b"payload-1", headers={"x-trace": "tid-7@1.5"})
            msgs = q.read_from(0, 10)
            assert [m.body for m in msgs] == [b"payload-0", b"payload-1"]
            assert msgs[0].headers is None
            assert msgs[1].headers == {"x-trace": "tid-7@1.5"}
        finally:
            q.close()
    finally:
        broker.stop()


# --- labeled metrics + exposition golden (satellite) ----------------------


def test_labeled_counter_family_renders_once():
    r = Registry()
    a = r.counter("reqs_total", "requests", labels={"stage": "in"})
    b = r.counter("reqs_total", "requests", labels={"stage": "out"})
    a.inc(2)
    b.inc()
    # Re-registering the same labels returns the SAME series.
    assert r.counter("reqs_total", labels={"stage": "in"}) is a
    assert r.render() == (
        "# HELP reqs_total requests\n"
        "# TYPE reqs_total counter\n"
        'reqs_total{stage="in"} 2\n'
        'reqs_total{stage="out"} 1\n'
    )


def test_flat_vs_labeled_name_conflict_raises():
    r = Registry()
    r.counter("x_total")
    with pytest.raises(ValueError, match="WITHOUT labels"):
        r.counter("x_total", labels={"k": "v"})


def test_labeled_histogram_merges_le_labels():
    r = Registry()
    h = r.histogram("lat", "l", buckets=(0.1, 1.0), labels={"stage": "s"})
    h.observe(0.05)
    lines = h.render_samples()
    assert lines[0] == 'lat_bucket{stage="s",le="0.1"} 1'
    assert 'lat_sum{stage="s"}' in lines[-2]


def test_histogram_render_golden():
    """Golden exposition for a flat histogram: empty, then one in-range
    observation, then an overflow observation — cumulative buckets, +Inf
    == count, and the exact line layout Prometheus parses."""
    h = Histogram("d_seconds", "drill", buckets=(0.001, 0.01))
    assert h.render() == (
        "# HELP d_seconds drill\n"
        "# TYPE d_seconds histogram\n"
        'd_seconds_bucket{le="0.001"} 0\n'
        'd_seconds_bucket{le="0.01"} 0\n'
        'd_seconds_bucket{le="+Inf"} 0\n'
        "d_seconds_sum 0.0\n"
        "d_seconds_count 0"
    )
    h.observe(0.005)
    h.observe(5.0)  # overflow bucket
    assert h.render() == (
        "# HELP d_seconds drill\n"
        "# TYPE d_seconds histogram\n"
        'd_seconds_bucket{le="0.001"} 0\n'
        'd_seconds_bucket{le="0.01"} 1\n'
        'd_seconds_bucket{le="+Inf"} 2\n'
        "d_seconds_sum 5.005\n"
        "d_seconds_count 2"
    )


def test_histogram_quantile_edges():
    h = Histogram("q", buckets=(0.001, 0.01))
    assert h.quantile(0.5) == 0.0  # empty
    h.observe(0.005)  # single observation: inside (0.001, 0.01]
    assert 0.001 < h.quantile(0.5) <= 0.01
    assert h.value()["count"] == 1
    h2 = Histogram("q2", buckets=(0.001, 0.01))
    for _ in range(10):
        h2.observe(99.0)  # all overflow
    # Quantiles in the overflow bucket interpolate within the documented
    # cap (2x the last finite bucket) — never 0, never unbounded.
    assert 0.01 < h2.quantile(0.99) <= 0.02
    assert h2.quantile(1.0) == pytest.approx(0.02)


# --- flight recorder ------------------------------------------------------


def test_flight_recorder_rings_and_chrome_trace():
    rec = FlightRecorder(keep_n=2, slow_threshold_s=0.5)
    for i in range(4):
        tid = f"t{i}"
        rec.record(tid, "ingress", 0.0, 0.1)
        # journey t3 is slow (2s end to end)
        rec.record(tid, "publish", 0.1, 2.0 if i == 3 else 0.2)
        rec.complete(tid)
    js = rec.journeys()
    ids = [j["trace_id"] for j in js]
    assert ids[:2] == ["t2", "t3"]  # last-N ring
    assert "t3" in ids  # slow journey pinned
    dump = rec.chrome_trace()
    json.loads(json.dumps(dump))  # valid JSON
    evs = [e for e in dump["traceEvents"] if e.get("ph") == "X"]
    assert {e["name"] for e in evs} == {"ingress", "publish"}
    assert all(
        set(e) >= {"name", "cat", "ph", "ts", "dur", "pid", "tid"}
        for e in evs
    )


def test_flight_recorder_bounds_open_journeys():
    rec = FlightRecorder(keep_n=4, max_open=8)
    for i in range(50):  # lost publishes must not leak
        rec.record(f"t{i}", "ingress", 0.0, 1.0)
    assert len(rec._open) == 8
    assert rec.dropped_open == 42


# --- the deterministic end-to-end drill (acceptance) ----------------------


def _drive_drill(bus):
    """One crossing pair through gateway → bus → consumer on the scripted
    clock; returns the consumer after both orders processed."""
    import jax.numpy as jnp

    from gome_tpu.engine.book import BookConfig
    from gome_tpu.engine.orchestrator import MatchEngine
    from gome_tpu.service.consumer import OrderConsumer
    from gome_tpu.service.gateway import OrderGateway

    engine = MatchEngine(
        config=BookConfig(cap=16, max_fills=8, dtype=jnp.int64),
        n_slots=4,
        max_t=8,
    )
    consumer = OrderConsumer(engine, bus, batch_n=16, batch_wait_s=0)
    gateway = OrderGateway(
        bus, accuracy=8, mark=engine.mark, unmark=engine.unmark
    )
    r1 = gateway.DoOrder(
        pb.OrderRequest(uuid="u1", oid="a1", symbol="eth2usdt",
                        transaction=pb.SALE, price=1.0, volume=5.0),
        None,
    )
    r2 = gateway.DoOrder(
        pb.OrderRequest(uuid="u2", oid="b1", symbol="eth2usdt",
                        transaction=pb.BUY, price=1.0, volume=3.0),
        None,
    )
    assert r1.code == 0 and r2.code == 0
    processed = 0
    deadline = time.monotonic() + 60
    while processed < 2 and time.monotonic() < deadline:
        processed += consumer.run_once()
    assert processed == 2
    return consumer


def _assert_contiguous_journey(journey, expect_stages):
    """The acceptance shape: one shared trace id, spans present for every
    expected stage, ordered and contiguous (each span starts at or after
    the previous one's start and the chain is monotone in time)."""
    spans = sorted(journey["spans"], key=lambda s: (s[1], s[2]))
    names = [s[0] for s in spans]
    for stage in expect_stages:
        assert stage in names, f"missing span {stage}: {names}"
    # Pipeline order respected for the expected subset...
    positions = [names.index(stage) for stage in expect_stages]
    assert positions == sorted(positions), names
    # ...and the chain is contiguous: monotone start times, and every
    # span starts no earlier than the journey start / ends by the end.
    starts = [s[1] for s in spans]
    assert starts == sorted(starts)
    assert all(
        journey["start"] <= s[1] <= s[2] <= journey["end"] for s in spans
    )
    # Scripted 1ms clock: every reading is distinct, so zero-length or
    # overlapping-identical spans cannot hide a broken chain.
    assert journey["end"] > journey["start"]


def test_single_order_journey_survives_amqp_hop(global_tracer):
    """ISSUE 2 acceptance: a single order's journey yields a contiguous
    span chain ingress→publish with ONE shared trace id surviving the
    AMQP hop (fake broker, real 0-9-1 framing), /trace returns valid
    Chrome trace-event JSON containing it, and the per-stage histograms
    scrape with nonzero counts."""
    tracer, recorder, registry = global_tracer
    from gome_tpu.bus import MemoryQueue, QueueBus
    from gome_tpu.bus.amqp import AmqpQueue
    from gome_tpu.bus.fakebroker import FakeBroker
    from gome_tpu.service.ops import OpsServer

    broker = FakeBroker().start()
    oq = AmqpQueue("doOrder", port=broker.port)
    bus = QueueBus(order_queue=oq, match_queue=MemoryQueue("matchOrder"))
    try:
        _drive_drill(bus)
        journeys = recorder.journeys()
        assert len(journeys) == 2  # both orders completed their journeys
        j = journeys[0]
        assert j["trace_id"] == "trace-1"
        _assert_contiguous_journey(
            j,
            ["ingress", "enqueue", "bus_transit", "pad_pack",
             "device_execute", "decode", "publish"],
        )
        # One shared trace id end to end: every span of this journey was
        # recorded under it (journeys are keyed by id, so presence of the
        # full chain IS the shared-id property), and the two journeys
        # never bled into each other.
        assert journeys[1]["trace_id"] == "trace-2"

        # Per-stage histograms on /metrics with nonzero counts.
        exposition = registry.render()
        for stage in ("ingress", "enqueue", "bus_transit", "pad_pack",
                      "device_execute", "decode", "publish"):
            val = tracer._hist[stage].value()
            assert val["count"] > 0, f"no {stage} observations"
        assert 'gome_stage_seconds_count{stage="ingress"} 2' in exposition

        # /trace over real HTTP returns valid Chrome trace-event JSON
        # containing the trace id.
        ops = OpsServer(registry=registry).start()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{ops.port}/trace"
            ) as resp:
                assert resp.status == 200
                dump = json.load(resp)
            assert isinstance(dump["traceEvents"], list)
            ids = {
                e["args"]["trace_id"]
                for e in dump["traceEvents"]
                if e.get("ph") == "X"
            }
            assert "trace-1" in ids and "trace-2" in ids
            phases = {e["ph"] for e in dump["traceEvents"]}
            assert phases <= {"X", "M"}
            # /metrics over the same endpoint shows the stage family.
            with urllib.request.urlopen(
                f"http://127.0.0.1:{ops.port}/metrics"
            ) as resp:
                assert "gome_stage_seconds" in resp.read().decode()
        finally:
            ops.stop()
    finally:
        oq.close()
        broker.stop()


def test_journey_through_batcher_frame_path(global_tracer):
    """The frame topology: gateway → FrameBatcher (GCO3 ORDER frame) →
    consumer. The journey gains a batch_wait span and the context
    survives the columnar hop."""
    tracer, recorder, registry = global_tracer
    from gome_tpu.bus import MemoryQueue, QueueBus

    import jax.numpy as jnp

    from gome_tpu.engine.book import BookConfig
    from gome_tpu.engine.orchestrator import MatchEngine
    from gome_tpu.service.batcher import FrameBatcher
    from gome_tpu.service.consumer import OrderConsumer
    from gome_tpu.service.gateway import OrderGateway

    bus = QueueBus(MemoryQueue("doOrder"), MemoryQueue("matchOrder"))
    engine = MatchEngine(
        config=BookConfig(cap=16, max_fills=8, dtype=jnp.int64),
        n_slots=4, max_t=8,
    )
    consumer = OrderConsumer(
        engine, bus, batch_n=16, batch_wait_s=0, match_wire="frame"
    )
    batcher = FrameBatcher(bus.order_queue, max_n=4096, max_wait_s=60)
    try:
        gateway = OrderGateway(
            bus, accuracy=8, mark=engine.mark, unmark=engine.unmark,
            batcher=batcher,
        )
        for uuid, oid, side in (("u1", "a1", pb.SALE), ("u2", "b1", pb.BUY)):
            r = gateway.DoOrder(
                pb.OrderRequest(uuid=uuid, oid=oid, symbol="eth2usdt",
                                transaction=side, price=1.0, volume=2.0),
                None,
            )
            assert r.code == 0
        assert batcher.flush() == 2  # one GCO3 frame for both orders
        body = bus.order_queue.read_from(0, 1)[0].body
        assert body[:4] == b"GCO3"
        processed = 0
        deadline = time.monotonic() + 60
        while processed < 2 and time.monotonic() < deadline:
            processed += consumer.run_once()
        assert processed == 2
        journeys = recorder.journeys()
        assert [j["trace_id"] for j in journeys] == ["trace-1", "trace-2"]
        _assert_contiguous_journey(
            journeys[0],
            ["ingress", "enqueue", "batch_wait", "bus_transit",
             "pad_pack", "device_execute", "decode", "publish"],
        )
        assert tracer._hist["batch_wait"].value()["count"] == 2
    finally:
        batcher.close()


# --- hot-path overhead guard (acceptance) ---------------------------------


def test_disabled_tracer_spans_allocate_nothing():
    """With the recorder disabled, the span hooks on the frame hot path
    are the SAME shared no-op object and allocate nothing — asserted via
    sys.getallocatedblocks over a tight loop (CPython exact)."""
    t = Tracer()  # never installed
    assert not t.enabled
    assert t.new_trace() is None
    s = t.span("device_execute")
    assert s is t.span("pad_pack") is t.stage("decode") is t.batch(["x"][:0])
    assert s is t.bind(None) is t.annotation("dispatch")

    def drill(n):
        i = 0
        while i < n:  # small ints are interned: the loop itself is clean
            with t.span("device_execute"):
                pass
            with t.stage("pad_pack"):
                pass
            t.observe("decode", 0.0)
            t.observe_span("publish", 0.0, 0.0)
            t.complete(None)
            i += 1

    drill(64)  # warm any lazy caches
    before = sys.getallocatedblocks()
    drill(200)
    after = sys.getallocatedblocks()
    assert after - before <= 2, f"hot-path hooks allocated {after - before}"


def test_disabled_tracer_emits_no_trace_on_wire():
    """Tracing off ⇒ orders carry no context and frames stay GCO2 — the
    wire is byte-identical to the pre-tracing build."""
    from gome_tpu.bus import MemoryQueue, QueueBus
    from gome_tpu.service.gateway import OrderGateway

    bus = QueueBus(MemoryQueue("doOrder"), MemoryQueue("matchOrder"))
    gateway = OrderGateway(bus, accuracy=8)
    r = gateway.DoOrder(
        pb.OrderRequest(uuid="u", oid="o", symbol="s",
                        transaction=pb.SALE, price=1.0, volume=1.0),
        None,
    )
    assert r.code == 0
    msg = bus.order_queue.read_from(0, 1)[0]
    assert b"Trace" not in msg.body
    assert msg.headers is None


# --- logging join (satellite) --------------------------------------------


def test_json_log_formatter_injects_trace_id():
    import logging

    from gome_tpu.utils.logging import JsonLineFormatter

    fmt = JsonLineFormatter()
    rec = logging.LogRecord(
        "gome_tpu.gateway", logging.INFO, __file__, 1,
        "accepted %s", ("a1",), None,
    )
    with TRACER.bind("tid-42"):
        line = json.loads(fmt.format(rec))
    assert line["msg"] == "accepted a1"
    assert line["trace_id"] == "tid-42"
    assert line["level"] == "INFO"
    # Outside a bound context: no trace_id key at all.
    line2 = json.loads(fmt.format(rec))
    assert "trace_id" not in line2


def test_stage_taxonomy_is_documented():
    """ARCHITECTURE.md's span table and the code must not drift."""
    import pathlib

    doc = (
        pathlib.Path(__file__).resolve().parents[1] / "ARCHITECTURE.md"
    ).read_text()
    for stage in STAGES:
        assert f"`{stage}`" in doc or stage in doc, stage

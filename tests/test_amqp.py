"""AMQP 0-9-1 transport (gome_tpu.bus.amqp) against the in-process fake
broker (gome_tpu.bus.fakebroker): the queue contract, at-least-once
redelivery, multi-connection topologies, and the reference-config boot
story (a rabbitmq: config section must boot with or without a broker)."""

import threading
import time

import pytest

from gome_tpu.bus import make_bus
from gome_tpu.bus.amqp import AmqpQueue
from gome_tpu.bus.fakebroker import FakeBroker
from gome_tpu.config import BusConfig, load_config


@pytest.fixture
def broker():
    b = FakeBroker().start()
    yield b
    b.stop()


@pytest.fixture
def queue(broker):
    q = AmqpQueue("doOrder", port=broker.port)
    yield q
    q.close()


# --- the bus contract suite (mirrors tests/test_bus.py) -------------------


def test_publish_read_commit(queue):
    offs = [queue.publish(f"m{i}".encode()) for i in range(5)]
    assert offs == [0, 1, 2, 3, 4]
    assert queue.end_offset() == 5
    msgs = queue.read_from(0, 3)
    assert [m.body for m in msgs] == [b"m0", b"m1", b"m2"]
    assert queue.committed() == 0
    queue.commit(3)
    assert queue.committed() == 3
    # non-destructive reads: earlier offsets still readable
    assert queue.read_from(1, 1)[0].body == b"m1"
    with pytest.raises(ValueError):
        queue.commit(2)  # backwards
    with pytest.raises(ValueError):
        queue.commit(99)  # past end


def test_poll_batch_returns_early_when_full(queue):
    for i in range(4):
        queue.publish(f"m{i}".encode())
    t0 = time.monotonic()
    msgs = queue.poll_batch(4, max_wait_s=5.0)
    assert len(msgs) == 4
    assert time.monotonic() - t0 < 1.0


def test_poll_batch_times_out_partial(queue):
    queue.publish(b"only")
    msgs = queue.poll_batch(8, max_wait_s=0.2)
    assert [m.body for m in msgs] == [b"only"]


def test_poll_batch_wakes_on_publish(queue):
    queue.end_offset()  # start the consume loop first

    def later():
        time.sleep(0.05)
        queue.publish(b"late")

    t = threading.Thread(target=later)
    t.start()
    msgs = queue.poll_batch(1, max_wait_s=5.0)
    t.join()
    assert [m.body for m in msgs] == [b"late"]


def test_large_bodies_split_into_frames(queue):
    big = bytes(range(256)) * 2048  # 512 KB > frame_max
    queue.publish(big)
    msgs = queue.poll_batch(1, max_wait_s=5.0)
    assert msgs[0].body == big


# --- AMQP-specific semantics ---------------------------------------------


def test_publisher_never_steals_from_consumer(broker):
    """A publish-only AmqpQueue must not register a consumer — otherwise
    it would round-robin-steal deliveries from the real consumer."""
    producer = AmqpQueue("doOrder", port=broker.port)
    consumer = AmqpQueue("doOrder", port=broker.port)
    consumer.end_offset()  # starts consuming
    for i in range(10):
        producer.publish(f"m{i}".encode())
    deadline = time.monotonic() + 5
    while consumer.end_offset() < 10 and time.monotonic() < deadline:
        time.sleep(0.01)
    msgs = consumer.read_from(0, 10)
    assert [m.body for m in msgs] == [f"m{i}".encode() for i in range(10)]
    producer.close()
    consumer.close()


def test_unacked_redelivery_on_reconnect(broker):
    """Messages consumed but never committed redeliver to the next
    consumer after the connection dies (broker-side at-least-once)."""
    producer = AmqpQueue("doOrder", port=broker.port)
    c1 = AmqpQueue("doOrder", port=broker.port)
    for i in range(4):
        producer.publish(f"m{i}".encode())
    msgs = c1.poll_batch(4, max_wait_s=5.0)
    assert len(msgs) == 4
    c1.commit(2)  # acks m0, m1; m2, m3 stay unacked
    c1.close()
    time.sleep(0.05)  # broker notices the close, requeues

    c2 = AmqpQueue("doOrder", port=broker.port)
    msgs = c2.poll_batch(2, max_wait_s=5.0)
    assert sorted(m.body for m in msgs) == [b"m2", b"m3"]
    producer.close()
    c2.close()


def test_make_bus_amqp_with_broker(broker):
    bus = make_bus(
        BusConfig(backend="amqp", host="127.0.0.1", port=broker.port)
    )
    assert bus.order_queue.name == "doOrder"
    assert bus.match_queue.name == "matchOrder"
    bus.order_queue.publish(b"x")
    assert bus.order_queue.poll_batch(1, 5.0)[0].body == b"x"
    bus.order_queue.close()
    bus.match_queue.close()


def test_make_bus_amqp_falls_back_without_broker():
    with pytest.warns(RuntimeWarning, match="falling back"):
        bus = make_bus(
            BusConfig(backend="amqp", host="127.0.0.1", port=1)  # nothing there
        )
    bus.order_queue.publish(b"x")  # memory backend works
    assert bus.order_queue.read_from(0, 1)[0].body == b"x"


REFERENCE_YAML = """\
rabbitmq:
  host: 127.0.0.1
  port: {port}
  username: guest
  password: guest
redis:
  host: 127.0.0.1
  port: 6379
  password: ""
grpc:
  host: 127.0.0.1
  port: 0
mysql:
  host: dead
gomengine:
  accuracy: 8
"""


def _write_ref_config(tmp_path, port):
    p = tmp_path / "config.yaml"
    p.write_text(REFERENCE_YAML.format(port=port))
    return str(p)


def test_reference_config_boots_without_broker(tmp_path):
    """VERDICT r1 weak #4: a reference-shaped config.yaml (rabbitmq:
    section selects the amqp backend) must BOOT and match even when no
    broker is listening."""
    from gome_tpu.service import EngineService

    cfg = load_config(_write_ref_config(tmp_path, port=1))
    assert cfg.bus.backend == "amqp"
    with pytest.warns(RuntimeWarning, match="falling back"):
        svc = EngineService(cfg)
    svc.start()
    try:
        from gome_tpu.api import order_pb2 as pb

        r = svc.gateway.DoOrder(
            pb.OrderRequest(
                uuid="u", oid="1", symbol="eth2usdt",
                transaction=pb.SALE, price=1.0, volume=2.0,
            ),
            None,
        )
        assert r.code == 0
        deadline = time.monotonic() + 120  # first CPU compile is slow
        while svc.engine.stats.orders < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert svc.engine.stats.orders == 1
    finally:
        svc.stop()


def test_reference_config_full_amqp_service(tmp_path, broker):
    """The full reference topology over real AMQP framing: gateway
    publishes to doOrder through the broker, the consumer matches, events
    land on matchOrder — with the reference's own config.yaml shape."""
    from gome_tpu.api import order_pb2 as pb
    from gome_tpu.service import EngineService

    cfg = load_config(_write_ref_config(tmp_path, port=broker.port))
    svc = EngineService(cfg)
    svc.start()
    try:
        from gome_tpu.bus.amqp import SupervisedAmqpQueue

        assert isinstance(svc.bus.order_queue, SupervisedAmqpQueue)
        r1 = svc.gateway.DoOrder(
            pb.OrderRequest(uuid="u1", oid="a", symbol="eth2usdt",
                            transaction=pb.SALE, price=1.0, volume=5.0),
            None,
        )
        r2 = svc.gateway.DoOrder(
            pb.OrderRequest(uuid="u2", oid="b", symbol="eth2usdt",
                            transaction=pb.BUY, price=1.0, volume=3.0),
            None,
        )
        assert r1.code == 0 and r2.code == 0
        deadline = time.monotonic() + 120  # first CPU compile is slow
        while svc.engine.stats.fills < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert svc.engine.stats.fills == 1
        # the fill event crossed the broker to matchOrder
        feed_deadline = time.monotonic() + 10
        while (
            svc.feed.events_seen < 1 and time.monotonic() < feed_deadline
        ):
            time.sleep(0.01)
        assert svc.feed.events_seen == 1
    finally:
        svc.stop()

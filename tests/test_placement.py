"""Placement observatory (ISSUE 20): the deterministic Space-Saving
symbol-flow sketch (error bound, exactly-associative lossless merge,
byte-stable wire form), the occupancy ledger + skew attribution, the
PLACEMENT singleton's house disabled-contract (zero-allocation hooks,
``{"enabled": False}`` payload), the /placement ops endpoint, the fleet
flow rollup, and the committed what-if verdict (PLACEMENT_r01.json,
produced by ``scripts/placement_eval.py``)."""

import importlib.util
import json
import os
import random
import struct
import sys
import urllib.request

import numpy as np
import pytest

from gome_tpu.config import Config, EngineConfig, OpsConfig
from gome_tpu.obs.placement import (
    DEFAULT_ROW_BYTES,
    PLACEMENT,
    SCHEMA,
    OccupancyLedger,
    PlacementObservatory,
    SpaceSaving,
    load_verdict,
)
from gome_tpu.utils.metrics import Registry

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _placement_disabled():
    """Every test leaves the process-global singleton unarmed — armed
    state leaking across tests would violate other files' zero-alloc
    guards (the same discipline as TIMELINE/CAPACITY/HOSTPROF)."""
    yield
    PLACEMENT.disable()


def _eval_mod():
    """scripts/placement_eval.py as a module (scripts/ is not a
    package; same importlib idiom obs_snapshot uses for capacity.py)."""
    path = os.path.join(ROOT, "scripts", "placement_eval.py")
    spec = importlib.util.spec_from_file_location("_placement_eval", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# -- SpaceSaving: the error bound ------------------------------------------


def test_sketch_error_bound_property():
    """The classic Space-Saving invariants on a skewed random stream:
    for every tracked key ``count >= true >= count - err``, the error
    never exceeds ``total / k``, every key whose true count exceeds
    ``total / k`` is tracked, and no stream mass is lost (sum of
    tracked counts == total)."""
    rng = random.Random(23)
    sk = SpaceSaving(k=16)
    true: dict[str, int] = {}
    for _ in range(5000):
        # Zipf-ish: a few heavy keys over a long tail of 200
        key = f"s{min(rng.randrange(200), rng.randrange(200))}"
        true[key] = true.get(key, 0) + 1
        sk.note(key)
    total = sk.total
    assert total == 5000
    bound = total / sk.k
    tracked_sum = 0
    for row in sk.top(sk.k):
        key, c, e = row["symbol"], row["count"], row["err"]
        tracked_sum += c
        assert c >= true.get(key, 0) >= c - e, (key, c, e, true.get(key))
        assert e <= bound
    assert tracked_sum == total  # lossless: all mass charged somewhere
    for key, t in true.items():
        if t > bound:
            assert sk.estimate(key) is not None, (key, t, bound)


def test_sketch_deterministic_eviction():
    """A full sketch meeting a new key evicts the smallest (count, key)
    — ties on count break on the key, so the same stream always leaves
    the same state. The evicted count seeds the newcomer's count AND
    its error bound."""
    sk = SpaceSaving(k=2)
    sk.note("bbb", 2)
    sk.note("aaa", 2)
    sk.note("new")  # tie at 2: "aaa" < "bbb" lexicographically, evicted
    assert sk.estimate("aaa") is None
    assert sk.estimate("bbb") == (2, 0)
    assert sk.estimate("new") == (3, 2)  # floor 2 + 1, err 2
    assert sk.total == 5


# -- merge: exactly associative + commutative ------------------------------


def _stream_sketch(seed: int, n: int, k: int = 8) -> SpaceSaving:
    rng = random.Random(seed)
    sk = SpaceSaving(k=k)
    for _ in range(n):
        sk.note(f"s{rng.randrange(40)}")
    return sk


def _clone(sk: SpaceSaving) -> SpaceSaving:
    return SpaceSaving.from_bytes(sk.to_bytes())


def test_sketch_merge_associative_commutative_byte_stable():
    """merge() is a lossless sparse add, so fold order can NEVER change
    the rollup: (a+b)+c, a+(b+c) and (b+a)+c serialize to identical
    bytes — the property the fleet flow rollup relies on."""
    a, b, c = (_stream_sketch(s, 500) for s in (1, 2, 3))

    ab_c = _clone(a); ab_c.merge(b); ab_c.merge(c)
    bc = _clone(b); bc.merge(c)
    a_bc = _clone(a); a_bc.merge(bc)
    ba_c = _clone(b); ba_c.merge(a); ba_c.merge(c)

    assert ab_c.to_bytes() == a_bc.to_bytes() == ba_c.to_bytes()
    assert ab_c.total == a.total + b.total + c.total
    # merged counters are bounded by members x k, never truncated to k
    assert ab_c.tracked <= 3 * a.k


def test_sketch_merge_rejects_capacity_mismatch():
    with pytest.raises(ValueError, match="capacities"):
        SpaceSaving(k=8).merge(SpaceSaving(k=16))


# -- wire form -------------------------------------------------------------


def test_sketch_byte_pin():
    """The wire form is a cross-version contract (fleet members on
    different builds exchange these blobs): golden bytes for a tiny
    fixed state."""
    sk = SpaceSaving(4)
    sk.note("btc2usdt", 3)
    sk.note("eth2usdt", 1)
    assert sk.to_bytes().hex() == (
        "4753533104000000040000000000000002000000"
        "08006274633275736474"
        "03000000000000000000000000000000"
        "08006574683275736474"
        "01000000000000000000000000000000"
    )
    rt = SpaceSaving.from_bytes(sk.to_bytes())
    assert rt.to_bytes() == sk.to_bytes()
    assert rt.k == 4 and rt.total == 4
    assert rt.estimate("btc2usdt") == (3, 0)


def test_sketch_from_bytes_rejects_corrupt_blobs():
    good = _stream_sketch(7, 100).to_bytes()
    with pytest.raises(ValueError, match="short"):
        SpaceSaving.from_bytes(good[:8])
    with pytest.raises(ValueError, match="magic"):
        SpaceSaving.from_bytes(b"XXXX" + good[4:])
    with pytest.raises(ValueError, match="truncated"):
        SpaceSaving.from_bytes(good[:-4])
    with pytest.raises(ValueError, match="length"):
        SpaceSaving.from_bytes(good + b"\x00")
    # header total disagreeing with the counter sum must not decode
    magic, k, total, npairs = struct.unpack_from("<4sIQI", good, 0)
    bad = struct.pack("<4sIQI", magic, k, total + 1, npairs) + good[20:]
    with pytest.raises(ValueError, match="total"):
        SpaceSaving.from_bytes(bad)


# -- OccupancyLedger -------------------------------------------------------


def test_ledger_arithmetic_goldens():
    led = OccupancyLedger()
    led.note(64, 40)  # unsharded dense frame: 64 rows, 40 live
    assert led.last == {
        "n_rows": 64, "live": 40, "rows_per_live_lane": 1.6,
    }
    led.note(2048, 411, shard_counts=[187, 52, 31, 27, 32, 31, 27, 24],
             r_s=256)  # the MULTICHIP_r06 D=8 geometry
    assert led.frames == 2
    assert led.dispatched_rows == 64 + 2048
    assert led.live_rows == 40 + 411
    assert led.padding_rows == 24 + 1637
    last = led.last
    assert last["devices"] == 8 and last["r_s"] == 256
    assert last["shard_skew"] == round(187 * 8 / 411, 4) == 3.6399
    assert last["rows_per_live_lane"] == round(2048 / 411, 4) == 4.983
    assert last["row_blocks"][0] == {
        "shard": 0, "rows": 256, "live": 187, "padding": 69,
    }
    assert sum(b["padding"] for b in last["row_blocks"]) == 2048 - 411
    doc = led.as_dict(row_bytes=448)
    assert doc["padding_bytes"] == (24 + 1637) * 448
    assert doc["rows_per_live_lane"] == round(2112 / 451, 4)


# -- the singleton's disabled contract -------------------------------------


def test_unarmed_surfaces():
    obs = PlacementObservatory()
    assert not obs.enabled
    assert obs.payload() == {"enabled": False}
    assert obs.occupancy_probe() == {}
    assert obs.attribution() == {"enabled": False}


def test_disabled_hooks_allocate_nothing():
    """Same contract as TRACER/JOURNAL/TIMELINE/HOSTPROF: every unarmed
    hot-path hook is one attribute check and ZERO allocations — the
    admit hooks sit on the gateway's per-order path and note_dispatch
    on every dense frame."""
    PLACEMENT.disable()
    lanes = np.arange(5, dtype=np.int64)
    syms = ["a", "b"]
    idx = np.zeros(4, dtype=np.int64)

    def drill(n):
        i = 0
        while i < n:
            PLACEMENT.note_admit("eth2usdt")
            PLACEMENT.note_admit_frame(syms, idx)
            PLACEMENT.note_dispatch(8, lanes)
            i += 1

    drill(64)  # warm lazy caches
    before = sys.getallocatedblocks()
    drill(200)
    after = sys.getallocatedblocks()
    assert after - before <= 2, f"disabled hooks allocated {after - before}"


def test_install_validation():
    obs = PlacementObservatory()
    reg = Registry()
    with pytest.raises(ValueError, match="topk"):
        obs.install(topk=0, registry=reg)
    with pytest.raises(ValueError, match="alpha"):
        obs.install(ewma_alpha=1.5, registry=reg)
    with pytest.raises(ValueError, match="row_bytes"):
        obs.install(row_bytes=0, registry=reg)
    with pytest.raises(ValueError, match="partitions"):
        obs.install(partitions=-1, registry=reg)
    with pytest.raises(ValueError, match="schema"):
        obs.install(verdict={"schema": "nope-v0"}, registry=reg)
    assert not obs.enabled


def test_install_serves_payload_and_gauges():
    obs = PlacementObservatory()
    reg = Registry()
    obs.install(topk=8, row_bytes=100, partitions=4, registry=reg)
    try:
        obs.note_admit("eth2usdt", 3)
        obs.note_admit_frame(["btc2usdt", "eth2usdt"],
                             np.array([0, 0, 1], dtype=np.int64))
        obs.note_dispatch(8, np.array([1, 4], dtype=np.int64))
        p = obs.payload()
        assert p["enabled"] is True
        assert p["admits"] == 6
        assert p["top"][0] == {
            "symbol": "eth2usdt", "count": 4, "err": 0,
            "share": round(4 / 6, 6),
        }
        assert p["topk_share"] == 1.0
        assert p["sketch"]["k"] == 8 and p["sketch"]["tracked"] == 2
        # payload's blob decodes back to the same sketch state
        rt = SpaceSaving.from_bytes(bytes.fromhex(p["sketch"]["bytes_hex"]))
        assert rt.estimate("eth2usdt") == (4, 0)
        occ = p["occupancy"]
        assert occ["frames"] == 1 and occ["dispatched_rows"] == 8
        assert occ["padding_bytes"] == 6 * 100
        assert p["lanes"]["hot"], "EWMA recorded no hot lanes"
        assert {r["lane"] for r in p["lanes"]["hot"]} == {1, 4}
        assert obs.occupancy_probe() == {
            "frames": 1, "dispatched_rows": 8, "live_rows": 2,
            "padding_rows": 6,
        }
        text = reg.render()
        assert "gome_placement_admits_total 6" in text
        assert "gome_placement_topk_share 1" in text
        assert "gome_placement_sketch_tracked 2" in text
        assert "gome_placement_rows_per_live_lane 4" in text
    finally:
        obs.disable()
    assert obs.payload() == {"enabled": False}


# -- attribution -----------------------------------------------------------


def test_attribution_reconciles_multichip_geometry():
    """The multiplicative decomposition on the committed MULTICHIP_r06
    D=8 geometry: skew (187*8/411 = 3.6399) x padding (256/187 = 1.369)
    must land on the observed rows-per-live-lane (2048/411 = 4.9829)
    within tolerance — computed from independently recorded fields."""
    obs = PlacementObservatory()
    obs.install(topk=8, registry=Registry())
    try:
        obs.note_admit("eth2usdt", 5)
        obs.note_dispatch(
            2048, np.arange(411, dtype=np.int64),
            shard_counts=[187, 52, 31, 27, 32, 31, 27, 24], r_s=256,
        )
        a = obs.attribution()
        comp = {r["component"]: r for r in a["components"]}
        assert comp["lane_placement_skew"]["value"] == 3.6399
        assert comp["cap_class_padding"]["value"] == 1.369
        rec = a["reconciliation"]
        assert rec["within_tol"], rec
        assert rec["frac_err"] <= 0.001  # exact decomposition, not luck
        # the skew baseline cites the committed artifact, read from disk
        base = comp["lane_placement_skew"]["baseline"]
        assert base["artifact"] == "MULTICHIP_r06"
        assert base["shard_skew"] == 3.6399
        hp = a["hash_partition"]
        assert hp["partitions"] == 8
        assert sum(hp["tracked_flow_per_partition"]) == 5
        assert hp["baseline"]["artifact"] == "FLEET_r01"
    finally:
        obs.disable()


def test_attribution_unsharded_padding_carries_everything():
    obs = PlacementObservatory()
    obs.install(topk=4, registry=Registry())
    try:
        obs.note_dispatch(16, np.arange(10, dtype=np.int64))
        a = obs.attribution()
        comp = {r["component"]: r["value"] for r in a["components"]}
        assert comp["lane_placement_skew"] == 1.0
        assert comp["cap_class_padding"] == 1.6
        assert a["reconciliation"]["frac_err"] == 0.0
    finally:
        obs.disable()


# -- the what-if evaluator -------------------------------------------------


def test_evaluator_deterministic_and_anchored():
    """build_verdict() is a pure function of the committed workload: two
    calls are identical, the current_block policy reproduces the
    committed MULTICHIP_r06 skew EXACTLY (the replay's anchor), at
    least 3 alternative policies are scored, and the named winner meets
    the acceptance budget."""
    mod = _eval_mod()
    v1, v2 = mod.build_verdict(), mod.build_verdict()
    assert v1 == v2
    assert v1["schema"] == SCHEMA
    table = {r["policy"]: r for r in v1["policies"]}
    assert set(table) >= {
        "current_block", "fnv1a_mod", "consistent_hash", "greedy_lpt",
    }
    cur = table["current_block"]
    assert cur["shard_skew"] == 3.6399  # == MULTICHIP_r06 curve[-1]
    assert cur["rows_per_live_lane"] == 4.983
    assert cur["symbols_moved_vs_current"] == 0.0
    for row in v1["policies"]:
        assert sum(row["live_per_shard"]) == v1["workload"]["live_lanes"]
        assert row["dispatched_rows"] == row["r_s"] * 8
    rec = v1["attribution"]["reconciliation"]
    assert rec["within_tol"] and rec["frac_err"] <= 0.05
    w = v1["winner"]
    assert table[w["policy"]]["shard_skew"] == w["predicted_shard_skew"]
    assert w["predicted_shard_skew"] <= 1.3
    assert v1["checks"]["pass"] is True


def test_committed_placement_artifact_pin():
    """PLACEMENT_r01.json (committed, regenerated by
    ``scripts/placement_eval.py --out PLACEMENT_r01.json``) is exactly
    what the evaluator produces today — a drifted policy table or a
    hand-edited verdict fails here."""
    committed = load_verdict(os.path.join(ROOT, "PLACEMENT_r01.json"))
    regenerated = json.loads(json.dumps(_eval_mod().build_verdict()))
    assert committed == regenerated
    assert committed["checks"]["pass"] is True
    assert committed["winner"]["predicted_shard_skew"] <= 1.3
    assert len(committed["policies"]) >= 4


def test_verdict_loader_rejects_wrong_schema(tmp_path):
    p = tmp_path / "v.json"
    p.write_text(json.dumps({"schema": "gome-capacity-verdict-v1"}))
    with pytest.raises(ValueError, match="schema"):
        load_verdict(str(p))


# -- /placement over HTTP --------------------------------------------------


def test_placement_http_endpoint():
    """The full loop on a live service: boot arms PLACEMENT from the
    ops config (with the committed verdict), gateway traffic feeds the
    sketch, pump()'s dense dispatch feeds the ledger, and /placement
    serves it all as JSON."""
    from gome_tpu.api import order_pb2 as pb
    from gome_tpu.service.app import EngineService

    svc = EngineService(Config(
        engine=EngineConfig(cap=32, n_slots=16, max_t=8, dtype="int32"),
        ops=OpsConfig(enabled=True, port=0, profile=False, hostprof=False,
                      trace=False),
    ))
    assert PLACEMENT.enabled, "ops.placement did not arm at boot"
    try:
        for i in range(4):
            r = svc.gateway.DoOrder(
                pb.OrderRequest(
                    uuid=f"u{i}", oid=f"o{i}", symbol="eth2usdt",
                    transaction=pb.SALE if i % 2 else pb.BUY,
                    price=1.0, volume=2.0,
                ),
                None,
            )
            assert r.code == 0, r
        svc.pump()
        svc.ops.start()
        with urllib.request.urlopen(
            f"http://127.0.0.1:{svc.ops.port}/placement", timeout=30
        ) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"] == "application/json"
            doc = json.loads(resp.read().decode())
        assert doc["enabled"] is True
        assert doc["top"][0]["symbol"] == "eth2usdt"
        assert doc["top"][0]["count"] == 4
        assert doc["occupancy"]["frames"] >= 1
        assert doc["attribution"]["reconciliation"]["within_tol"]
        # boot served the committed what-if verdict alongside
        assert doc["verdict"]["schema"] == SCHEMA
        assert doc["verdict"]["winner"]["policy"]
        # row_bytes derived from the REAL engine geometry, not the
        # module default: int32 cell (28 B) x max_t=8
        assert doc["occupancy"]["row_bytes"] == 28 * 8
        with urllib.request.urlopen(
            f"http://127.0.0.1:{svc.ops.port}/metrics", timeout=10
        ) as resp:
            metrics = resp.read().decode()
        assert "gome_placement_admits_total" in metrics
        assert "gome_placement_topk_share" in metrics
    finally:
        svc.ops.stop()
        svc.stop()


def test_placement_config_knobs_validated():
    with pytest.raises(ValueError):
        Config(ops=OpsConfig(placement_topk=0))
    with pytest.raises(ValueError):
        Config(ops=OpsConfig(placement_alpha=0.0))
    with pytest.raises(ValueError):
        Config(ops=OpsConfig(placement_partitions=0))


# -- fleet rollup ----------------------------------------------------------


def test_fleet_placement_rollup():
    """Two members' /placement scrapes fold into one fleet flow table:
    the sketch blobs merge losslessly, per-member order shares come out
    of the admit totals, and gome_fleet_partition_imbalance reports
    max/mean. A member without the surface stays healthy."""
    from gome_tpu.obs.fleet import FleetAggregator

    def member_payload(seed: int, admits: int) -> str:
        sk = _stream_sketch(seed, admits, k=8)
        return json.dumps({
            "enabled": True,
            "admits": admits,
            "sketch": {"k": 8, "tracked": sk.tracked, "total": sk.total,
                       "bytes_hex": sk.to_bytes().hex()},
        })

    placements = {"a": member_payload(1, 300), "b": member_payload(2, 100)}

    def fetch(url, timeout_s):
        proc, _, path = url.partition("://")[2].partition("/")
        path = "/" + path
        if path == "/metrics":
            return Registry().render()
        if path == "/healthz":
            return json.dumps({"healthy": True, "detail": {}})
        if path == "/durability":
            return json.dumps({"matchfeed": {
                "last_seq": 0, "observed": 0, "dupes": 0, "gaps": 0,
            }})
        if path.startswith("/timeline"):
            return json.dumps({"samples": []})
        if path == "/placement":
            if proc == "c":  # a member predating the surface: 404s
                raise OSError("no /placement here")
            return placements[proc]
        raise AssertionError(url)

    reg = Registry()
    agg = FleetAggregator()
    agg.install(
        {"a": "inproc://a", "b": "inproc://b", "c": "inproc://c"},
        registry=reg, fetch=fetch,
    )
    try:
        snap = agg.poll()
        assert snap["c"]["healthy"], "missing /placement marked unhealthy"
        roll = agg.payload()["placement"]
        assert set(roll["members"]) == {"a", "b"}
        assert roll["members"]["a"] == {"admits": 300, "order_share": 0.75}
        assert roll["partition_imbalance_max_over_mean"] == round(
            300 / 200, 4
        )
        flow = roll["flow"]
        assert flow["total"] == 400
        # the fold is the exact sparse sum of the member sketches
        ref = _stream_sketch(1, 300, k=8)
        ref.merge(_stream_sketch(2, 100, k=8))
        assert flow["top"] == ref.top(16)
        assert "gome_fleet_partition_imbalance 1.5" in reg.render()
    finally:
        agg.disable()
    assert agg.payload() == {"enabled": False}


def test_fleet_rollup_none_without_armed_members():
    from gome_tpu.obs.fleet import FleetAggregator

    def fetch(url, timeout_s):
        if url.endswith("/healthz"):
            return json.dumps({"healthy": True, "detail": {}})
        if url.endswith("/metrics"):
            return Registry().render()
        if url.endswith("/durability"):
            return json.dumps({"matchfeed": {
                "last_seq": 0, "observed": 0, "dupes": 0, "gaps": 0,
            }})
        if "/timeline" in url:
            return json.dumps({"samples": []})
        if url.endswith("/placement"):
            return json.dumps({"enabled": False})
        raise AssertionError(url)

    agg = FleetAggregator()
    agg.install({"a": "inproc://a"}, registry=Registry(), fetch=fetch)
    try:
        agg.poll()
        assert agg.payload()["placement"] is None
        assert agg.partition_imbalance() == 0.0
    finally:
        agg.disable()

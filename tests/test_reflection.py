"""gRPC server reflection (v1alpha) parity: the reference registers
reflection (main.go:33); ours must answer list-services and
file-containing-symbol the way grpcurl asks them."""

import grpc

from gome_tpu.api import order_pb2 as pb
from gome_tpu.api.reflection import (
    REFLECTION_SERVICE,
    _field,
    _parse_fields,
    _varint,
)
from gome_tpu.api.service import SERVICE_NAME
from gome_tpu.config import Config, EngineConfig, GrpcConfig
from gome_tpu.service import EngineService


def _reflect(channel, request: bytes) -> bytes:
    call = channel.stream_stream(
        f"/{REFLECTION_SERVICE}/ServerReflectionInfo",
        request_serializer=None,
        response_deserializer=None,
    )
    return next(iter(call(iter([request]))))


def test_reflection_list_and_describe():
    svc = EngineService(
        Config(
            grpc=GrpcConfig(host="127.0.0.1", port=0),
            engine=EngineConfig(cap=16, n_slots=8, max_t=8),
        )
    )
    from concurrent import futures

    from gome_tpu.api.reflection import add_reflection_servicer
    from gome_tpu.api.service import add_order_servicer

    server = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
    add_order_servicer(server, svc.gateway)
    add_reflection_servicer(server)
    port = server.add_insecure_port("127.0.0.1:0")
    server.start()
    try:
        channel = grpc.insecure_channel(f"127.0.0.1:{port}")
        # list_services: field 7, empty string
        resp = _reflect(channel, _field(7, b""))
        fields = dict(
            (num, val) for num, _wt, val in _parse_fields(resp)
        )
        assert 6 in fields  # list_services_response
        names = [
            val
            for num, _wt, val in _parse_fields(fields[6])
            if num == 1
        ]
        svc_names = set()
        for n in names:
            for num, _wt, val in _parse_fields(n):
                if num == 1:
                    svc_names.add(val.decode())
        assert SERVICE_NAME in svc_names
        # the reflection service is deliberately NOT advertised: we cannot
        # serve its descriptor, and describe-all tools would error on it
        assert REFLECTION_SERVICE not in svc_names

        # file_containing_symbol: field 4
        resp = _reflect(channel, _field(4, SERVICE_NAME.encode()))
        fields = dict(
            (num, val) for num, _wt, val in _parse_fields(resp)
        )
        assert 4 in fields  # file_descriptor_response
        fdps = [
            val
            for num, _wt, val in _parse_fields(fields[4])
            if num == 1
        ]
        assert fdps and fdps[0] == pb.DESCRIPTOR.serialized_pb

        # unknown symbol -> error_response NOT_FOUND
        resp = _reflect(channel, _field(4, b"no.such.Service"))
        fields = dict(
            (num, val) for num, _wt, val in _parse_fields(resp)
        )
        assert 7 in fields
        channel.close()
    finally:
        server.stop(grace=None)

"""Host-path observability (gome_tpu.obs.hostprof): the in-process
sampling profiler, the stage-join arithmetic, the gateway admit drill,
the /hostprof endpoint, the disabled hot-path contract, and the
committed HOSTPROF_r01 artifact — the ISSUE 10 surface."""

import json
import os
import signal
import sys
import time
import urllib.request

import pytest

from gome_tpu.obs import hostprof
from gome_tpu.obs.hostprof import (
    ADMIT_STAGES,
    HOST_STAGES,
    HOSTPROF,
    HostSampler,
    classify_node,
    classify_stack,
    stage_join,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _hostprof_disabled():
    """Every test leaves the process-global host profiler disabled (the
    hot-path default other tests assume)."""
    yield
    HOSTPROF.disable()


def _busy(seconds: float) -> int:
    """Pure-Python spin so both sampler modes (CPU- and wall-paced)
    accumulate samples."""
    deadline = time.perf_counter() + seconds
    acc = 0
    while time.perf_counter() < deadline:
        acc += sum(range(256))
    return acc


# --- the sampler ----------------------------------------------------------


def test_thread_sampler_bounds_and_ring_limits():
    """Thread mode samples this thread at wall pace; the ring honors
    ``keep`` and the distinct-stack counter honors ``max_stacks`` (the
    overflow bucket absorbs the rest, so sample totals never lie)."""
    s = HostSampler(hz=500.0, keep=8, max_stacks=4, mode="thread")
    s.start()
    try:
        _busy(0.25)
    finally:
        s.stop()
    assert s.mode_used == "thread"
    assert s.samples > 0, "wall-paced sampler captured nothing in 250ms"
    assert len(s.ring()) <= 8
    # max_stacks distinct keys + at most the overflow bucket
    counts = s.counts()
    assert len(counts) <= 5
    assert sum(counts.values()) == s.samples
    # stopped sampler is quiescent: totals stay put
    n = s.samples
    time.sleep(0.05)
    assert s.samples == n
    collapsed = s.collapsed()
    assert collapsed and all(
        line.rsplit(" ", 1)[1].isdigit()
        for line in collapsed.splitlines()
    )
    s.reset()
    assert s.samples == 0 and not s.counts() and not s.ring()


def test_walk_caps_depth_keeping_deepest_frames():
    s = HostSampler(mode="thread", max_depth=4)

    def recurse(n):
        if n:
            return recurse(n - 1)
        return s._walk(sys._getframe())

    stack = recurse(20)
    assert len(stack) == 4
    # deepest frames survive the cap: the leaf is _walk's caller
    assert all(node.endswith(":recurse") for node in stack[:-1])


@pytest.mark.skipif(
    not hasattr(signal, "setitimer"), reason="no setitimer on platform"
)
def test_signal_sampler_smoke():
    """SIGPROF mode arms from the main thread and samples CPU-paced.
    The kernel tick bounds delivery (~CONFIG_HZ), so only a handful of
    samples is asserted, not the nominal hz."""
    s = HostSampler(hz=997.0, mode="signal")
    s.start()
    try:
        deadline = time.perf_counter() + 2.0
        while s.samples < 5 and time.perf_counter() < deadline:
            _busy(0.05)
    finally:
        s.stop()
    assert s.mode_used == "signal"
    assert s.samples >= 5, "SIGPROF delivered almost nothing in 2s of CPU"


def test_sampler_rejects_bad_args():
    with pytest.raises(ValueError):
        HostSampler(hz=0)
    with pytest.raises(ValueError):
        HostSampler(mode="perf")


# --- stage join: golden arithmetic on a scripted sample stream ------------


def test_classify_node_matches_qualname_leaf():
    # 3.11+ qualnames carry the class prefix; the rule function name
    # matches the LAST dotted component so both spellings classify.
    assert classify_node(
        "gome_tpu.service.gateway:OrderGateway._validate_add"
    ) == "validate"
    assert classify_node(
        "gome_tpu.service.gateway:_validate_add"
    ) == "validate"
    assert classify_node("gome_tpu.fixed:scale") == "order_build"
    assert classify_node("json:dumps") is None


def test_classify_stack_deepest_mapped_frame_wins():
    # json.dumps under encode_order rolls UP to codec_encode...
    assert classify_stack((
        "x:main",
        "gome_tpu.service.gateway:DoOrder",
        "gome_tpu.bus.codec:encode_order",
        "json:dumps",
    )) == "codec_encode"
    # ...while a deeper mapped frame beats the shallower ingress match
    assert classify_stack((
        "gome_tpu.service.gateway:DoOrder",
        "gome_tpu.service.gateway:_validate_add",
    )) == "validate"
    assert classify_stack(("x:main", "other:loop")) is None


def test_stage_join_golden_fixture():
    """Exact arithmetic over a hand-written sample stream: measured wall
    distributes by sampled share, stage rows + unattributed sum to the
    window, coverage is the attributed fraction."""
    counts = {
        ("x:main", "gome_tpu.service.gateway:DoOrder",
         "gome_tpu.service.gateway:_validate_add"): 10,
        ("x:main", "gome_tpu.service.gateway:DoOrder",
         "gome_tpu.service.gateway:order_from_request",
         "gome_tpu.types:__init__"): 20,
        ("x:main", "gome_tpu.service.gateway:DoOrder",
         "gome_tpu.service.gateway:order_from_request",
         "gome_tpu.fixed:scale"): 5,
        ("x:main", "gome_tpu.service.gateway:DoOrder",
         "gome_tpu.service.gateway:_traced_emit",
         "gome_tpu.bus.codec:encode_order", "json:dumps"): 25,
        ("x:main", "gome_tpu.service.gateway:DoOrder"): 30,
        ("x:main", "other:loop"): 10,
    }
    join = stage_join(counts, n_orders=1000, window_ns=1e9)
    assert join["total_samples"] == 100
    assert join["attributed_samples"] == 90
    assert join["coverage_pct"] == 90.0
    # 1e9 ns window / 1000 orders = 1e6 ns/order, split by sample share
    assert join["stages"] == {
        "ingress": {"samples": 30, "pct": 30.0, "ns_per_order": 300_000.0},
        "validate": {"samples": 10, "pct": 10.0, "ns_per_order": 100_000.0},
        "order_build": {"samples": 25, "pct": 25.0,
                        "ns_per_order": 250_000.0},
        "codec_encode": {"samples": 25, "pct": 25.0,
                         "ns_per_order": 250_000.0},
    }
    assert join["unattributed"] == {
        "samples": 10, "ns_per_order": 100_000.0,
    }
    # rows render in HOST_STAGES order (the taxonomy's pipeline order)
    order = [st for st in HOST_STAGES if st in join["stages"]]
    assert list(join["stages"]) == order
    # window identity: stage ns + unattributed ns == window / orders
    total_ns = sum(
        row["ns_per_order"] for row in join["stages"].values()
    ) + join["unattributed"]["ns_per_order"]
    assert total_ns == pytest.approx(1e6)


def test_stage_join_empty_counts():
    join = stage_join({}, n_orders=10, window_ns=1e6)
    assert join["total_samples"] == 0
    assert join["coverage_pct"] == 0.0
    assert join["stages"] == {}


# --- the gateway admit drill ----------------------------------------------


def test_gateway_drill_produces_admit_path_stages():
    """The drill splits the admit wall function-by-function. Thread mode
    (wall-paced, ~hz true cadence) keeps the sample count deterministic
    enough that every major admit stage shows up."""
    drill = hostprof.gateway_drill(
        n_orders=4000, mode="thread", hz=997.0,
        min_samples=200, max_rounds=8, seed=7,
    )
    assert drill["kind"] == "gateway_admit_drill"
    assert drill["orders"] >= 4000
    assert drill["admit_ns_per_order"] > 0
    assert drill["admit_orders_per_sec_per_core"] > 0
    assert drill["sampler"]["mode"] == "thread"
    assert drill["sampler"]["samples"] >= 200 or drill["rounds"] == 8
    for st in ("order_build", "codec_encode", "enqueue"):
        assert st in drill["stages"], (st, drill["stages"])
    assert set(drill["stages"]) <= set(HOST_STAGES)
    assert set(drill["stages"]) <= set(ADMIT_STAGES)
    # the window identity holds on real data too (0.1-rounding per row)
    rows = list(drill["stages"].values())
    total_ns = sum(r["ns_per_order"] for r in rows) + (
        drill["unattributed"]["ns_per_order"]
    )
    tol = 0.1 * (len(rows) + 1) + 0.2
    assert abs(total_ns - drill["admit_ns_per_order"]) <= tol
    assert ";" in drill["collapsed"]


def test_drill_requests_deterministic():
    a = hostprof._drill_requests(64, seed=7)
    b = hostprof._drill_requests(64, seed=7)
    assert [(r.SerializeToString(), d) for r, d in a] == [
        (r.SerializeToString(), d) for r, d in b
    ]
    assert any(is_del for _, is_del in a), "no cancels in the mix"


# --- the singleton: install / payload / gauges ----------------------------


def test_hostprof_install_drill_payload_and_gauges():
    from gome_tpu.utils.metrics import REGISTRY

    HOSTPROF.install(hz=101.0, keep_n=64)
    assert HOSTPROF.enabled
    HOSTPROF.note_admit(3)
    rep = HOSTPROF.drill(
        n_orders=1024, min_samples=16, max_rounds=2, seed=7
    )
    assert rep["stages"], "singleton drill attributed nothing"
    doc = HOSTPROF.payload()
    assert doc["enabled"] is True
    assert doc["hz"] == 101.0 and doc["keep"] == 64
    # the drill's own admits flow through note_admit too (>= the manual 3)
    assert doc["admits"] >= 3
    assert doc["drill"] is rep or doc["drill"] == rep
    assert doc["live"]["enabled"] is True
    metrics = REGISTRY.render()
    assert "gome_hostprof_samples_total" in metrics
    assert "gome_hostprof_admit_orders_per_sec_per_core" in metrics
    assert 'gome_hostprof_stage_ns_per_order{stage="validate"}' in metrics
    assert ";" in HOSTPROF.collapsed()  # drill fallback when live idle


def test_hostprof_endpoint_http_validity():
    from gome_tpu.config import Config, EngineConfig, OpsConfig
    from gome_tpu.obs.compile_journal import JOURNAL
    from gome_tpu.obs.profiler import PROFILER
    from gome_tpu.obs.timeline import TIMELINE
    from gome_tpu.service.app import EngineService

    cfg = Config(
        engine=EngineConfig(cap=16, max_fills=4, n_slots=4, max_t=4,
                            dtype="int32"),
        ops=OpsConfig(port=0, enabled=True),
    )
    svc = EngineService(cfg)
    assert HOSTPROF.enabled  # ops.hostprof armed the profiler at boot
    svc.ops.start()
    try:
        base = f"http://127.0.0.1:{svc.ops.port}"
        with urllib.request.urlopen(
            f"{base}/hostprof?drill=1", timeout=120
        ) as r:
            assert r.status == 200
            assert r.headers["Content-Type"] == "application/json"
            doc = json.loads(r.read().decode())
        assert doc["enabled"] is True
        drill = doc["drill"]
        assert drill and drill["sampler"]["samples"] > 0
        assert drill["stages"]
        with urllib.request.urlopen(
            f"{base}/hostprof?format=collapsed", timeout=30
        ) as r:
            assert r.status == 200
            assert r.headers["Content-Type"].startswith("text/plain")
            body = r.read().decode()
        assert ";" in body, f"no collapsed stacks over HTTP: {body[:120]}"
    finally:
        svc.ops.stop()
        JOURNAL.disable()
        TIMELINE.disable()
        PROFILER.disable()


# --- disabled contract: no-op + zero hot-path allocations -----------------


def test_disabled_hostprof_is_inert():
    HOSTPROF.disable()
    assert not HOSTPROF.enabled
    assert HOSTPROF.payload() == {
        "enabled": False, "live": None, "drill": None,
    }
    assert HOSTPROF.collapsed() == "# hostprof disabled\n"
    HOSTPROF.start()  # all lifecycle hooks are no-ops while disabled
    HOSTPROF.stop()
    assert HOSTPROF.last_drill() is None


def test_disabled_admit_hook_allocates_nothing():
    """Same contract as TRACER/JOURNAL/TIMELINE/PROFILER: the gateway's
    per-order hook costs one attribute check and ZERO allocations when
    disabled."""
    HOSTPROF.disable()

    def drill(n):
        i = 0
        while i < n:
            HOSTPROF.note_admit()
            i += 1

    drill(64)  # warm any lazy caches
    before = sys.getallocatedblocks()
    drill(200)
    after = sys.getallocatedblocks()
    assert after - before <= 2, f"hot-path hook allocated {after - before}"


# --- the committed HOSTPROF_r01 artifact ----------------------------------


def test_hostprof_r01_artifact_pin():
    """Schema pin for the committed host roofline: the per-stage admit
    breakdown covers >= 80% of the measured admit wall, and the
    host-vs-device table carries the front-door mismatch."""
    path = os.path.join(REPO_ROOT, "HOSTPROF_r01.json")
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    assert doc["artifact"] == "HOSTPROF_r01"
    drill = doc["drill"]
    assert drill["kind"] == "gateway_admit_drill"
    assert drill["orders"] > 0
    assert drill["admit_ns_per_order"] > 0
    assert drill["sampler"]["samples"] > 0
    assert drill["coverage_pct"] >= 80.0, (
        "stage map no longer explains the admit wall — re-run "
        "scripts/profile_consumer.py --gateway --out HOSTPROF_r01.json "
        "after extending STAGE_RULES"
    )
    # acceptance: stage ns/order rows sum to >= 80% of the admit wall
    stage_sum = sum(
        row["ns_per_order"] for row in drill["stages"].values()
    )
    assert stage_sum >= 0.8 * drill["admit_ns_per_order"]
    for st, row in drill["stages"].items():
        assert st in HOST_STAGES
        assert row["samples"] > 0 and row["ns_per_order"] >= 0
    # the function-by-function split actually split: validation and the
    # pre-pool mark are distinguishable from the handler shell
    assert "validate" in drill["stages"]
    assert "mark" in drill["stages"]
    roof = doc["roofline"]
    assert roof["host_gateway_admit"]["orders_per_sec_per_core"] > 0
    assert roof["front_door_mismatch_device_vs_gateway"] > 1
    assert roof["front_door_mismatch_consumer_vs_gateway"] > 1

"""Frame path (engine.frames + bus.colwire): wire codec round-trips and
differential parity — the vectorized frame path must produce the identical
EventBatch the object path produces for the same orders."""

import jax.numpy as jnp
import numpy as np
import pytest

from gome_tpu.bus import colwire
from gome_tpu.engine import BatchEngine, BookConfig
from gome_tpu.engine.frames import process_frame
from gome_tpu.oracle import OracleEngine
from gome_tpu.types import Action, Order, OrderType, Side
from gome_tpu.utils.streams import multi_symbol_stream


def orders_to_frame(orders):
    """Encode a list of Orders as one ORDER frame (what a batching gateway
    or the columnar load client produces) — the library implementation,
    re-exported under the name older tests import."""
    return colwire.encode_orders(orders)


def run_frames(eng, orders, chunk, fast=False):
    from gome_tpu.engine.frames import apply_frame_fast

    out = []
    for i in range(0, len(orders), chunk):
        payload = orders_to_frame(orders[i : i + chunk])
        assert colwire.is_frame(payload)
        cols = colwire.decode_order_frame(payload)
        run = (
            (lambda c: apply_frame_fast(eng, c))
            if fast
            else (lambda c: process_frame(eng, c))
        )
        out.extend(run(cols).to_results())
    return out


def run_objects(eng, orders, chunk):
    out = []
    for i in range(0, len(orders), chunk):
        out.extend(eng.process_columnar(orders[i : i + chunk]).to_results())
    return out


def _oracle(orders):
    oracle = OracleEngine()
    out = []
    for o in orders:
        out.extend(oracle.process(o))
    return out


@pytest.mark.parametrize(
    "n_slots,chunk,fast",
    [(64, 97, False), (8, 50, False), (64, 97, True), (8, 50, True)],
)
def test_frame_path_matches_object_path_and_oracle(n_slots, chunk, fast):
    orders = multi_symbol_stream(n=400, n_symbols=6, seed=21, cancel_prob=0.2)
    a = BatchEngine(BookConfig(cap=32, max_fills=8), n_slots=n_slots, max_t=8)
    b = BatchEngine(BookConfig(cap=32, max_fills=8), n_slots=n_slots, max_t=8)
    got_f = run_frames(a, orders, chunk, fast=fast)
    got_o = run_objects(b, orders, chunk)
    assert got_f == got_o == _oracle(orders)
    a.verify_books()
    ba, bb = a.lane_books(), b.lane_books()
    for name in ("price", "lots", "seq", "count", "next_seq"):
        np.testing.assert_array_equal(
            np.asarray(getattr(ba, name)), np.asarray(getattr(bb, name))
        )
    # oid/uid leaves hold interner ids, and the frame path interns in
    # sorted-unique order (np.unique) vs the object path's first-occurrence
    # order — compare through the tables.
    for leaf, ta, tb in (
        ("oid", a.oids.table, b.oids.table),
        ("uid", a.uids.table, b.uids.table),
    ):
        xa = np.asarray(getattr(ba, leaf), np.int64)
        xb = np.asarray(getattr(bb, leaf), np.int64)
        sa = np.array(ta, dtype=object)[xa]
        sb = np.array(tb, dtype=object)[xb]
        active = np.asarray(ba.lots) > 0
        assert (sa[active] == sb[active]).all(), leaf


def test_frame_path_int32_rebasing_and_dropped_dels():
    BTC = 10_000_000_000_000
    rng = np.random.default_rng(5)
    orders = []
    for i in range(250):
        is_del = i > 20 and rng.random() < 0.2
        orders.append(
            Order(
                uuid=f"u{int(rng.integers(0, 3))}",
                oid=str(int(rng.integers(1, i)) if is_del else i),
                symbol=f"sym{int(rng.integers(0, 4))}",
                side=Side(int(rng.integers(0, 2))),
                price=BTC + int(rng.integers(-2000, 2000)),
                volume=int(rng.integers(1, 30)),
                action=Action.DEL if is_del else Action.ADD,
            )
        )
    # One in-contract wrong-price cancel (the poison scenario).
    orders.append(
        Order(uuid="u0", oid="0", symbol="sym0", side=Side.BUY,
              price=50_000_000, volume=0, action=Action.DEL)
    )
    eng = BatchEngine(
        BookConfig(cap=64, max_fills=8, dtype=jnp.int32), n_slots=64, max_t=8
    )
    got = run_frames(eng, orders, 80)
    assert got == _oracle(orders)
    assert eng.stats.cancels_missed >= 1
    eng.verify_books()


def test_fast_path_falls_back_on_escalation():
    """apply_frame_fast must detect tripped budgets (book overflow, record
    truncation) via the compaction totals and re-run exactly."""
    orders = [
        Order(uuid="u", oid=str(i), symbol="s", side=Side.SALE,
              price=100 + i, volume=1)
        for i in range(40)  # overflows cap=8
    ]
    orders.append(
        Order(uuid="u", oid="sweep", symbol="s", side=Side.BUY, price=300,
              volume=1000)  # 40 fills > max_fills=4
    )
    eng = BatchEngine(BookConfig(cap=8, max_fills=4), n_slots=16, max_t=4)
    got = run_frames(eng, orders, len(orders), fast=True)
    assert got == _oracle(orders)
    assert eng.stats.cap_escalations >= 1
    eng.verify_books()


def test_frame_path_deep_single_symbol_and_escalations():
    rng = np.random.default_rng(9)
    orders = [
        Order(uuid="u", oid=str(i), symbol="hot",
              side=Side(int(rng.integers(0, 2))),
              price=100 + int(rng.integers(-3, 4)),
              volume=int(rng.integers(1, 8)))
        for i in range(500)
    ]
    # sweep order crossing far more than max_fills resting orders
    orders.append(
        Order(uuid="u", oid="sweep", symbol="hot", side=Side.BUY,
              price=200, volume=100000)
    )
    eng = BatchEngine(BookConfig(cap=16, max_fills=4), n_slots=64, max_t=4)
    got = run_frames(eng, orders, len(orders))
    assert got == _oracle(orders)
    assert eng.stats.cap_escalations >= 1
    eng.verify_books()


def test_frame_market_orders():
    orders = [
        Order(uuid="m", oid="r1", symbol="s", side=Side.SALE, price=105,
              volume=10),
        Order(uuid="m", oid="r2", symbol="s", side=Side.SALE, price=110,
              volume=10),
        Order(uuid="t", oid="mkt", symbol="s", side=Side.BUY, price=0,
              volume=15, order_type=OrderType.MARKET),
    ]
    eng = BatchEngine(BookConfig(cap=16, max_fills=8), n_slots=16, max_t=8)
    got = run_frames(eng, orders, 3)
    assert got == _oracle(orders)
    assert [e.match_volume for e in got] == [10, 5]


def test_event_frame_round_trip():
    """EventBatch -> EVENT frame -> EventBatch: identical events and
    identical reference-JSON serialization."""
    orders = multi_symbol_stream(n=200, n_symbols=4, seed=2, cancel_prob=0.2)
    eng = BatchEngine(BookConfig(cap=32, max_fills=8), n_slots=32, max_t=8)
    batch = eng.process_columnar(orders)
    payload = colwire.encode_event_frame(batch)
    assert colwire.is_frame(payload)
    back = colwire.decode_event_frame(payload)
    assert back.to_results() == batch.to_results()
    assert back.to_json_lines() == batch.to_json_lines()


def test_service_frame_path_end_to_end():
    """ORDER frames through the real consumer (admission incl. the
    cancel-before-consume race) with EVENT-frame publishing, decoded by
    the match feed — parity with the oracle."""
    from gome_tpu.bus import MemoryQueue, QueueBus
    from gome_tpu.engine.orchestrator import MatchEngine
    from gome_tpu.service.consumer import OrderConsumer
    from gome_tpu.service.matchfeed import MatchFeed

    orders = multi_symbol_stream(n=300, n_symbols=5, seed=13, cancel_prob=0.2)
    engine = MatchEngine(
        config=BookConfig(cap=32, max_fills=8), n_slots=64, max_t=8
    )
    bus = QueueBus(MemoryQueue("doOrder"), MemoryQueue("matchOrder"))
    consumer = OrderConsumer(
        engine, bus, batch_n=64, batch_wait_s=0, match_wire="frame"
    )
    feed = MatchFeed(bus, log_events=False)
    for o in orders:
        engine.mark(o)
    for i in range(0, len(orders), 70):
        bus.order_queue.publish(orders_to_frame(orders[i : i + 70]))
    n = consumer.drain()
    assert n == len(orders)
    # decode the EVENT frames back to MatchResults
    got = []
    from gome_tpu.bus.colwire import decode_event_frame

    for m in bus.match_queue.read_from(0, 10000):
        got.extend(decode_event_frame(m.body).to_results())
    assert got == _oracle(orders)
    feed.drain()
    assert feed.events_seen == len(got)


def test_frame_admission_cancel_race():
    """An ADD whose mark was cleared by an earlier cancel must drop at
    frame admission (engine.go:58-62 semantics)."""
    from gome_tpu.engine.orchestrator import MatchEngine

    engine = MatchEngine(
        config=BookConfig(cap=16, max_fills=4), n_slots=16, max_t=8
    )
    add = Order(uuid="u", oid="1", symbol="s", side=Side.BUY, price=100,
                volume=5)
    kill = Order(uuid="u", oid="1", symbol="s", side=Side.BUY, price=100,
                 volume=0, action=Action.DEL)
    engine.mark(add)
    # cancel consumed first clears the mark; the queued ADD then dies
    from gome_tpu.bus import colwire

    batch = engine.process_frame(
        colwire.decode_order_frame(orders_to_frame([kill, add]))
    )
    assert len(batch) == 0
    assert engine.stats.dropped_no_prepool == 1
    assert int(np.asarray(engine.books.count).sum()) == 0


def test_event_frame_non_ascii_ids():
    """UTF-8 ids survive both frame codecs (np 'S' conversion is
    ASCII-only on str inputs; the packers must encode first)."""
    orders = [
        Order(uuid="пользователь", oid="ордер-1", symbol="эфир2usdt",
              side=Side.SALE, price=100, volume=5),
        Order(uuid="用户", oid="订单-2", symbol="эфир2usdt",
              side=Side.BUY, price=100, volume=3),
    ]
    eng = BatchEngine(BookConfig(cap=16, max_fills=4), n_slots=16, max_t=4)
    batch = process_frame(
        eng, colwire.decode_order_frame(orders_to_frame(orders))
    )
    back = colwire.decode_event_frame(colwire.encode_event_frame(batch))
    assert back.to_results() == batch.to_results() == _oracle(orders)
    assert back.to_results()[0].match_node.uuid == "пользователь"


def test_order_frame_codec_edge_cases():
    # empty batch
    payload = orders_to_frame([])
    cols = colwire.decode_order_frame(payload)
    assert cols["n"] == 0
    # single order, long ids
    o = Order(uuid="user-" + "x" * 40, oid="order-" + "y" * 60,
              symbol="somesym2usdt", side=Side.BUY, price=123, volume=7)
    cols = colwire.decode_order_frame(orders_to_frame([o]))
    assert cols["symbols"] == ["somesym2usdt"]
    assert cols["uuids"][cols["uuid_idx"][0]] == o.uuid
    assert cols["oids"][0].decode() == o.oid
    assert cols["price"][0] == 123 and cols["volume"][0] == 7


def test_fast_path_cap_below_max_fills():
    """cap < max_fills clamps the step's record axis K to cap (step.py's
    `rec` slice) — the fast compact path must decode with the ARRAY K and
    escalate when an op's fills exceed it, not config.max_fills
    (fuzz-found: mis-decoded fill positions and silently truncated
    records). Exercised per-frame against the oracle."""
    import jax.numpy as jnp

    orders = []
    for i in range(12):
        orders.append(
            Order(uuid="u", oid=f"r{i}", symbol="s", side=Side.SALE,
                  price=100 + i, volume=2)
        )
    # Sweeps crossing more than cap resting orders: records must escalate
    # (n_fills > K=cap) and the decoded events must still be exact.
    orders.append(
        Order(uuid="u", oid="sweep", symbol="s", side=Side.BUY, price=200,
              volume=11)
    )
    orders += [
        Order(uuid="u", oid=f"p{i}", symbol="s2", side=Side(int(i % 2)),
              price=150 + (i % 2), volume=3)
        for i in range(8)
    ]
    eng = BatchEngine(
        BookConfig(cap=4, max_fills=8, dtype=jnp.int32), n_slots=2, max_t=8
    )
    got = run_frames(eng, orders, 7, fast=True)
    assert got == _oracle(orders)
    eng.verify_books()


def test_lane_growth_survives_rollback_retry():
    """A frame that (a) auto-grows the lane axis and (b) trips the fast
    path's fills-buffer budget must still succeed via the exact fallback:
    the rollback shrinks n_slots back, and the retry's lane map must
    re-grow rather than reuse cached lane ids past the restored stack
    (regression: the identity-cached lane map skipped _lane()'s growth
    side effect after _restore)."""
    from gome_tpu.engine.frames import apply_frame_fast

    eng = BatchEngine(
        BookConfig(cap=256, max_fills=256), n_slots=2, max_t=512
    )
    # Rest 200 one-lot asks on s0 (fills floor stays minimal: no fills).
    rest = [
        Order(
            uuid="u", oid=f"a{i}", symbol="s0", side=Side.SALE,
            price=1000, volume=1, action=Action.ADD,
            order_type=OrderType.LIMIT,
        )
        for i in range(200)
    ]
    cols = colwire.decode_order_frame(orders_to_frame(rest))
    apply_frame_fast(eng, cols)
    # One frame: a 200-lot sweep on s0 (200 fills >> the 64-slot fills
    # buffer for n_ops=4 -> _NeedExact -> rollback -> exact retry) PLUS
    # three new symbols that force lane growth 2 -> 8 in the same frame.
    sweep = [
        Order(
            uuid="u", oid="big", symbol="s0", side=Side.BUY,
            price=1000, volume=200, action=Action.ADD,
            order_type=OrderType.LIMIT,
        )
    ] + [
        Order(
            uuid="u", oid=f"n{i}", symbol=f"new{i}", side=Side.BUY,
            price=1000, volume=1, action=Action.ADD,
            order_type=OrderType.LIMIT,
        )
        for i in range(3)
    ]
    cols2 = colwire.decode_order_frame(orders_to_frame(sweep))
    batch = apply_frame_fast(eng, cols2)
    fills = [e for e in batch.to_results() if not e.is_cancel]
    assert len(fills) == 200
    assert eng.n_slots >= 4  # growth stuck after the retry
    assert eng.stats.fills == 200
    # The sweep grid's op class (64) ratcheted its fills floor past 200.
    assert eng.geometry_floors()["fills_buf"][64] == 256


def test_geometry_manifest_precompile_round_trip(tmp_path):
    """VERDICT r4 #1: a persisted shape manifest (floors + dispatched
    combos) replays in a FRESH engine with all-padding inputs, leaves its
    state untouched, and makes the live flow's shapes pre-seen — then the
    same orders produce identical events to an engine without any
    precompile."""
    from gome_tpu.engine.frames import precompile_combos
    from gome_tpu.engine.orchestrator import MatchEngine

    def mk():
        return MatchEngine(
            config=BookConfig(cap=32, max_fills=8, dtype=jnp.int64),
            n_slots=64, max_t=8,
        )

    orders = multi_symbol_stream(
        n=600, n_symbols=24, seed=5, zipf_a=1.2, cancel_prob=0.3
    )

    # Run 1: record the manifest.
    e1 = mk()
    for o in orders:
        e1.mark(o)
    frame = colwire.decode_order_frame(orders_to_frame(orders))
    ev1 = e1.process_frame(frame, fast=True).to_results()
    assert e1.batch.combo_count(), "fast path recorded no shape combos"
    path = str(tmp_path / "geometry.json")
    e1.save_geometry(path)

    # Run 2: fresh engine loads + precompiles, then must (a) be unchanged
    # by the replay and (b) produce identical events.
    e2 = mk()
    n = e2.load_geometry(path)
    assert n == e1.batch.combo_count()
    assert int(np.asarray(e2.books.count).sum()) == 0  # replay mutated nothing
    assert e2.batch.stats.orders == 0
    # Floors were prewarmed: the same flow chooses the recorded shapes.
    g1, g2 = e1.batch.geometry_floors(), e2.batch.geometry_floors()
    for k in ("rows_floor", "t_floor", "fills_buf", "cancels_buf"):
        for cls, v in g1[k].items():
            assert g2[k].get(cls, 0) >= v, (k, cls)
    for o in orders:
        e2.mark(o)
    ev2 = e2.process_frame(frame, fast=True).to_results()
    assert ev1 == ev2
    # The flow minted no shapes beyond the manifest (zero first-seen
    # traces in the "timed region").
    assert set(e2.batch.combos()) <= set(
        map(tuple, e1.batch.shape_manifest()["combos"])
    )

    # Missing/corrupt files are best-effort no-ops.
    e3 = mk()
    assert e3.load_geometry(str(tmp_path / "absent.json")) == 0
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert e3.load_geometry(str(bad)) == 0
    # Direct combo replay with a dense combo on a fresh engine also works.
    assert precompile_combos(e3.batch, e1.batch.shape_manifest()["combos"]) >= 1


def test_geometry_manifest_stale_or_oversized_is_best_effort(tmp_path):
    """A readable manifest that is incompatible (combo arity from another
    version) must be a no-op, not a boot crash; and a mesh request larger
    than the device pool raises loudly instead of silently shrinking."""
    import json

    from gome_tpu.engine.orchestrator import MatchEngine
    from gome_tpu.parallel import make_mesh

    e = MatchEngine(
        config=BookConfig(cap=32, max_fills=8, dtype=jnp.int64),
        n_slots=64, max_t=8,
    )
    stale = tmp_path / "stale.json"
    stale.write_text(json.dumps({
        "floors": {"rows_floor": {"32": 8}},
        "combos": [[8, 8, 32]],  # wrong arity: an older version's layout
    }))
    assert e.load_geometry(str(stale)) == 0  # best-effort, no raise

    with pytest.raises(ValueError, match="devices"):
        make_mesh(64)  # only 8 virtual devices exist

"""Timeline sampler (gome_tpu.obs.timeline): scripted-clock series and
ring bounds, probe isolation, the disabled-no-alloc hot-path guard, the
/timeline HTTP endpoint, geometry-hash stability semantics, memory-queue
compaction, and the GOME_LOG_DIR override — the ISSUE 6 surface."""

import json
import os
import sys
import urllib.request

import numpy as np

import jax.numpy as jnp
import pytest

from gome_tpu.engine import frames
from gome_tpu.engine.batch import BatchEngine
from gome_tpu.engine.book import BookConfig
from gome_tpu.obs.timeline import (
    TIMELINE,
    TimelineSampler,
    geometry_manifest_hash,
    host_rss_bytes,
    service_timeline,
)
from gome_tpu.utils.metrics import Registry


@pytest.fixture(autouse=True)
def _timeline_disabled():
    """Every test leaves the process-global sampler disabled (the
    hot-path default other tests assume)."""
    yield
    TIMELINE.disable()
    from gome_tpu.obs.compile_journal import JOURNAL

    JOURNAL.disable()


def _engine(cap=16, n_slots=8, max_t=8):
    return BatchEngine(
        BookConfig(cap=cap, max_fills=4, dtype=jnp.int32),
        n_slots=n_slots, max_t=max_t,
    )


def _frame(n, n_symbols=4, seed=0):
    rng = np.random.default_rng(seed)
    return dict(
        n=n,
        action=np.ones(n, np.int64),
        side=rng.integers(0, 2, n).astype(np.int64),
        kind=np.zeros(n, np.int64),
        price=rng.integers(99_000, 101_000, n).astype(np.int64),
        volume=rng.integers(1, 10, n).astype(np.int64),
        symbols=[f"s{i}" for i in range(n_symbols)],
        symbol_idx=rng.integers(0, n_symbols, n).astype(np.int64),
        uuids=["u0"],
        uuid_idx=np.zeros(n, np.int64),
        oids=np.char.add("t", np.arange(n).astype("U8")).astype("S"),
    )


# --- sampler core ---------------------------------------------------------


def test_scripted_clock_series_and_ring_bound():
    """Samples carry the scripted clock, the host fields, and the flow
    counters; the ring keeps exactly the last keep_n."""
    ticks = iter(float(i) for i in range(100))
    t = TimelineSampler()
    t.install(
        interval_s=0.5, keep_n=3, registry=Registry(),
        clock=lambda: next(ticks),
    )
    t.register("probe", lambda: {"x": 1})
    t.note_frame(40)
    t.note_frame(2)
    first = t.sample()
    assert first["t"] == 0.0
    assert first["frames"] == 2 and first["orders"] == 42
    assert first["rss_bytes"] > 0
    assert first["cpu_utime_s"] >= 0.0
    for key in ("cpu_stime_s", "majflt", "nvcsw", "nivcsw", "ts"):
        assert key in first
    assert first["probe"] == {"x": 1}
    for _ in range(5):
        t.sample()
    series = t.series()
    assert len(series) == 3  # bounded ring, oldest evicted
    assert [s["t"] for s in series] == [3.0, 4.0, 5.0]
    assert t.latest()["t"] == 5.0
    d = t.as_dict()
    assert d["enabled"] is True and d["interval_s"] == 0.5
    assert len(d["samples"]) == 3


def test_disabled_sampler_is_inert():
    t = TimelineSampler()  # never installed
    assert not t.enabled
    assert t.sample() is None
    assert t.series() == []
    assert t.latest() is None
    assert t.as_dict() == {
        "enabled": False, "interval_s": 1.0, "samples": [],
    }
    with pytest.raises(RuntimeError):
        t.start()


def test_install_validation_and_disable_clears():
    t = TimelineSampler()
    with pytest.raises(ValueError):
        t.install(interval_s=0.0, registry=Registry())
    with pytest.raises(ValueError):
        t.install(keep_n=0, registry=Registry())
    t.install(registry=Registry())
    t.register("x", lambda: {})
    t.note_frame(1)
    t.sample()
    t.disable()
    assert not t.enabled
    assert t.series() == []
    assert t._probes == {}  # probe service references released


def test_probe_error_is_isolated():
    """One raising probe lands as {"error": ...}; the sample and every
    other probe survive."""
    t = TimelineSampler().install(registry=Registry())
    t.register("bad", lambda: 1 / 0)
    t.register("good", lambda: {"ok": True})
    s = t.sample()
    assert "error" in s["bad"]
    assert s["good"] == {"ok": True}


def test_timeline_gauges_exported():
    reg = Registry()
    t = TimelineSampler().install(registry=reg)
    t.note_frame(7)
    t.sample()
    text = reg.render()
    for name in (
        "gome_timeline_rss_bytes",
        "gome_timeline_cpu_seconds_total",
        "gome_timeline_involuntary_ctx_switches_total",
        "gome_timeline_major_faults_total",
        "gome_timeline_samples",
        "gome_timeline_frames_total",
        "gome_timeline_orders_total",
    ):
        assert name in text, name
    snap = reg.snapshot()
    assert snap["gome_timeline_frames_total"] == 1.0
    assert snap["gome_timeline_orders_total"] == 7.0
    assert snap["gome_timeline_samples"] == 1.0
    assert snap["gome_timeline_rss_bytes"] == pytest.approx(
        host_rss_bytes(), rel=0.5
    )


# --- hot-path overhead guard (acceptance) ---------------------------------


def test_disabled_sampler_hot_path_allocates_nothing():
    """The disabled note_frame hook on the frame hot path is one
    attribute check and zero allocations — same sys.getallocatedblocks
    guard as the tracer and compile journal."""
    t = TimelineSampler()  # never installed
    assert not t.enabled

    def drill(n):
        i = 0
        while i < n:
            if t.enabled:
                raise AssertionError("unreachable")
            t.note_frame(256)
            i += 1

    drill(64)  # warm any lazy caches
    before = sys.getallocatedblocks()
    drill(200)
    after = sys.getallocatedblocks()
    assert after - before <= 2, f"hot-path hooks allocated {after - before}"


def test_frame_path_feeds_flow_counters():
    """The engine frame path (frames._assemble) reports into an armed
    sampler — frames and orders accumulate."""
    eng = _engine()
    TIMELINE.install(registry=Registry())
    frames.apply_frame_fast(eng, _frame(32, seed=1))
    s = TIMELINE.sample()
    assert s["frames"] == 1
    assert s["orders"] == 32


# --- geometry-manifest hash ----------------------------------------------


def test_geometry_hash_stable_then_drifts_on_new_shapes():
    eng = _engine()
    h0 = geometry_manifest_hash(eng)
    assert h0 == geometry_manifest_hash(eng)  # deterministic
    frames.apply_frame_fast(eng, _frame(32, seed=2))
    h1 = geometry_manifest_hash(eng)
    assert h1 != h0  # first frame minted dispatch combos
    frames.apply_frame_fast(eng, _frame(32, seed=3))
    assert geometry_manifest_hash(eng) == h1  # same shapes: stable


# --- service probes + /timeline HTTP -------------------------------------


def test_timeline_http_validity():
    from gome_tpu.config import Config, EngineConfig, OpsConfig
    from gome_tpu.service.app import EngineService

    cfg = Config(
        engine=EngineConfig(cap=16, max_fills=4, n_slots=4, max_t=4,
                            dtype="int32"),
        ops=OpsConfig(port=0, enabled=True, timeline_interval_s=0.25),
    )
    svc = EngineService(cfg)
    assert TIMELINE.enabled  # ops.timeline armed the sampler at boot
    frames.apply_frame_fast(svc.engine.batch, _frame(16, seed=4))
    TIMELINE.sample()
    svc.ops.start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{svc.ops.port}/timeline", timeout=30
        ) as r:
            assert r.status == 200
            assert r.headers["Content-Type"] == "application/json"
            doc = json.loads(r.read().decode())
        assert doc["enabled"] is True
        assert doc["interval_s"] == 0.25
        assert doc["samples"], "no samples over HTTP"
        s = doc["samples"][-1]
        assert s["rss_bytes"] > 0
        assert s["frames"] >= 1 and s["orders"] >= 16
        assert s["engine"]["geometry_hash"]
        assert s["engine"]["cap"] == 16
        assert s["live"]["count"] > 0
        assert "compiles" in s["compile"]
        assert s["queue"]["order_backlog"] == 0
        # /metrics carries the gome_timeline_* families too
        with urllib.request.urlopen(
            f"http://127.0.0.1:{svc.ops.port}/metrics", timeout=10
        ) as r:
            metrics = r.read().decode()
        assert "gome_timeline_rss_bytes" in metrics
        assert "gome_timeline_orders_total" in metrics
    finally:
        svc.ops.stop()


def test_service_timeline_batcher_probe():
    """With a FrameBatcher on the gateway, the batcher probe reports
    queue depth + degraded state."""
    import types

    from gome_tpu.bus import MemoryQueue, QueueBus
    from gome_tpu.service.batcher import FrameBatcher

    bus = QueueBus(MemoryQueue("doOrder"), MemoryQueue("matchOrder"))
    batcher = FrameBatcher(bus.order_queue, max_n=64, max_wait_s=60)
    try:
        eng = _engine()
        t = TimelineSampler().install(registry=Registry())
        service_timeline(
            types.SimpleNamespace(
                engine=eng, bus=bus,
                gateway=types.SimpleNamespace(batcher=batcher),
            ),
            sampler=t,
        )
        from gome_tpu.types import Action, Order, OrderType, Side

        batcher.submit(Order(
            uuid="u", oid="o1", symbol="s", side=Side.BUY, price=100,
            volume=1, action=Action.ADD, order_type=OrderType.LIMIT,
        ))
        s = t.sample()
        assert s["batcher"]["buffered"] == 1
        assert s["batcher"]["degraded"] is False
        assert s["batcher"]["spill_depth"] == 0
    finally:
        batcher.close()


# --- periodic thread ------------------------------------------------------


def test_sampler_thread_collects_and_stops():
    t = TimelineSampler().install(interval_s=0.01, registry=Registry())
    t.start()
    import time as _time

    deadline = _time.monotonic() + 5.0
    while len(t.series()) < 3 and _time.monotonic() < deadline:
        _time.sleep(0.01)
    t.stop()
    n = len(t.series())
    assert n >= 3, "thread collected no samples"
    _time.sleep(0.05)
    assert len(t.series()) == n  # stopped means stopped


# --- memory-queue compaction (the soak harness's bounded-bus contract) ----


def test_memory_queue_compact_releases_committed_prefix():
    from gome_tpu.bus.memory import MemoryQueue

    q = MemoryQueue("x")
    for i in range(10):
        q.publish(bytes([i]))
    q.commit(6)
    assert q.compact() == 6
    assert q.end_offset() == 10
    assert q.committed() == 6
    # offsets stay absolute across compaction
    msgs = q.read_from(6, 100)
    assert [m.offset for m in msgs] == [6, 7, 8, 9]
    assert [m.body for m in msgs] == [bytes([i]) for i in range(6, 10)]
    with pytest.raises(ValueError):
        q.read_from(3, 1)  # compacted away
    with pytest.raises(ValueError):
        q.rollback(3)  # redelivery window is bounded by compaction
    assert q.compact() == 0  # idempotent at the committed offset
    q.publish(b"z")
    assert q.publish(b"z2") == 11
    q.commit(11)
    assert q.compact() == 5


# --- GOME_LOG_DIR ---------------------------------------------------------


def test_log_dir_override(tmp_path, monkeypatch):
    """configure() honors GOME_LOG_DIR — no more order.log littering the
    CWD (stray-file regression from PR 5's cleanup)."""
    import logging as _logging

    from gome_tpu.utils import logging as gl

    root = _logging.getLogger("gome_tpu")
    before = list(root.handlers)
    monkeypatch.setattr(gl, "_CONFIGURED", False)
    monkeypatch.setenv("GOME_LOG_DIR", str(tmp_path / "logs"))
    try:
        gl.configure()
        assert (tmp_path / "logs" / "order.log").exists()
    finally:
        for h in root.handlers[len(before):]:
            h.close()
        root.handlers[:] = before


def test_log_dir_default_is_tmp_under_pytest(tmp_path, monkeypatch):
    """Without an explicit override, a pytest run logs to the system tmp
    dir, never the checkout."""
    import tempfile

    from gome_tpu.utils import logging as gl

    monkeypatch.delenv("GOME_LOG_DIR", raising=False)
    assert gl._default_log_dir() == tempfile.gettempdir()
    monkeypatch.setenv("GOME_LOG_DIR", str(tmp_path))
    assert gl._default_log_dir() == str(tmp_path)


def test_log_dir_default_spares_source_checkouts(tmp_path):
    """Outside pytest, a CWD that looks like a source checkout (`.git` or
    `pyproject.toml` marker) still logs to the system tmp dir — scripts/
    entry points run from the repo root kept re-littering the checkout
    with order.log (round 9 root-cause; the pytest guard alone missed
    them). A plain working directory keeps the reference's CWD behavior.
    Subprocess: the in-process pytest branch would shadow the marker
    check."""
    import subprocess
    import sys as _sys

    prog = (
        "import tempfile\n"
        "from gome_tpu.utils.logging import _default_log_dir\n"
        "d = _default_log_dir()\n"
        "print('TMP' if d == tempfile.gettempdir() else 'CWD' if d == '' "
        "else d)\n"
    )
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("GOME_LOG_DIR", "PYTEST_CURRENT_TEST")
    }
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))

    def run_in(cwd):
        return subprocess.run(
            [_sys.executable, "-c", prog], cwd=cwd, env=env,
            capture_output=True, text=True, timeout=60,
        ).stdout.strip()

    checkout = tmp_path / "checkout"
    checkout.mkdir()
    (checkout / "pyproject.toml").write_text("")
    plain = tmp_path / "plain"
    plain.mkdir()
    assert run_in(checkout) == "TMP"
    assert run_in(plain) == "CWD"

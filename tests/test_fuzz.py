"""CI slice of the differential fuzzer (scripts/fuzz.py): randomized
streams under adversarial engine geometries vs the oracle. Run the script
directly for deeper sweeps."""

import importlib.util
import os

import pytest

_spec = importlib.util.spec_from_file_location(
    "gome_fuzz",
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts", "fuzz.py",
    ),
)
_fuzz = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_fuzz)


@pytest.mark.parametrize("seed", range(500, 512))
def test_fuzz_case(seed):
    print(_fuzz.run_case(seed))


@pytest.mark.parametrize("seed", range(7000, 7004))
def test_fuzz_sim_case(seed):
    print(_fuzz.run_sim_case(seed))

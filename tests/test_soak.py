"""Soak: a long mixed stream through the engine with mid-stream
snapshot/restore, invariant checks, and oracle parity throughout — the
closest thing to production traffic the CI budget allows."""

import numpy as np

import jax.numpy as jnp

from gome_tpu.engine import BatchEngine, BookConfig
from gome_tpu.oracle import OracleEngine
from gome_tpu.utils.streams import multi_symbol_stream


def test_soak_mixed_stream_with_restore_and_invariants():
    orders = multi_symbol_stream(
        n=3000, n_symbols=40, seed=17, cancel_prob=0.15
    )
    oracle = OracleEngine()
    expected = []
    for o in orders:
        expected.extend(oracle.process(o))

    engine = BatchEngine(
        BookConfig(cap=64, max_fills=8, dtype=jnp.int32), n_slots=8, max_t=32
    )
    got = []
    rng = np.random.default_rng(0)
    for i in range(0, len(orders), 250):
        got.extend(engine.process_columnar(orders[i : i + 250]).to_results())
        engine.verify_books()
        if rng.random() < 0.3:
            # crash/restore mid-stream: a fresh engine resumes from the
            # snapshot with identical downstream events
            state = engine.export_state()
            engine = BatchEngine(
                BookConfig(cap=64, max_fills=8, dtype=jnp.int32),
                n_slots=8,
                max_t=32,
            )
            engine.import_state(state)
    assert got == expected
    assert len(got) > 500  # the stream actually matched at volume

"""Soak: a long mixed stream through the engine with mid-stream
snapshot/restore, invariant checks, and oracle parity throughout — the
closest thing to production traffic the CI budget allows — plus the
wall-clock soak driver (scripts/soak.py) on a short budget and the
committed SOAK artifact's green-verdict pin."""

import json
import os
import subprocess
import sys

import numpy as np

import jax.numpy as jnp

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from gome_tpu.engine import BatchEngine, BookConfig
from gome_tpu.oracle import OracleEngine
from gome_tpu.utils.streams import multi_symbol_stream


def test_soak_mixed_stream_with_restore_and_invariants():
    orders = multi_symbol_stream(
        n=3000, n_symbols=40, seed=17, cancel_prob=0.15
    )
    oracle = OracleEngine()
    expected = []
    for o in orders:
        expected.extend(oracle.process(o))

    engine = BatchEngine(
        BookConfig(cap=64, max_fills=8, dtype=jnp.int32), n_slots=8, max_t=32
    )
    got = []
    rng = np.random.default_rng(0)
    for i in range(0, len(orders), 250):
        got.extend(engine.process_columnar(orders[i : i + 250]).to_results())
        engine.verify_books()
        if rng.random() < 0.3:
            # crash/restore mid-stream: a fresh engine resumes from the
            # snapshot with identical downstream events
            state = engine.export_state()
            engine = BatchEngine(
                BookConfig(cap=64, max_fills=8, dtype=jnp.int32),
                n_slots=8,
                max_t=32,
            )
            engine.import_state(state)
    assert got == expected
    assert len(got) > 500  # the stream actually matched at volume


def test_soak_steady_state_live_buffers_flat():
    """Leak detector (gome_tpu.obs.live) on real engine steps: once the
    flow's shapes and escalations have settled, N further engine steps
    must leave the live device-buffer count FLAT — a growing count is a
    leaked buffer (a retained checkpoint, an accumulator outliving its
    frame). The settle phase absorbs the legitimate allocators: first-
    seen compiles (their executables pin constant buffers) and book/cap
    growth."""
    from gome_tpu.obs import live

    engine = BatchEngine(
        BookConfig(cap=64, max_fills=8, dtype=jnp.int32), n_slots=8,
        max_t=32,
    )
    # Cancel-heavy stationary flow (resting depth stays bounded, so no
    # mid-measurement cap escalation mints fresh executables).
    orders = multi_symbol_stream(
        n=2000, n_symbols=8, seed=23, cancel_prob=0.5
    )
    chunks = [orders[i : i + 250] for i in range(0, len(orders), 250)]
    i = 0

    def step():
        nonlocal i
        engine.process_columnar(chunks[i % len(chunks)])
        i += 1

    # settle = one full pass (every chunk's shapes compile + books reach
    # steady depth), then the whole second pass must hold the baseline.
    report = live.assert_steady_state(
        step, steps=len(chunks), settle=len(chunks)
    )
    assert report["counts"], report


def test_soak_script_short_budget_smoke(tmp_path):
    """scripts/soak.py --seconds 10 end to end in a subprocess: the
    verdict block comes back green, the timeline recorded a real series,
    and the latency section is measured (tiny geometry so the CI budget
    holds; the committed SOAK_r01.json is the full-size run)."""
    out = tmp_path / "SOAK_smoke.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [
            sys.executable, "scripts/soak.py", "--seconds", "10",
            "--frame", "512", "--symbols", "16", "--cap", "512",
            "--interval", "0.5", "--latency-configs", "1x512",
            "--latency-orders", "2048", "--out", str(out),
            "--timeline-out", str(tmp_path / "timeline.json"),
        ],
        cwd=_REPO, env=env, capture_output=True, text=True, timeout=540,
    )
    assert r.returncode == 0, (r.stdout, r.stderr[-3000:])
    doc = json.loads(out.read_text())
    v = doc["soak"]["verdicts"]
    assert v["pass"] is True, v
    for name in (
        "live_buffers_flat", "rss_bounded", "geometry_stable",
        "zero_breaker_trips",
    ):
        assert v[name]["pass"] is True, (name, v[name])
    assert doc["soak"]["orders"] > 0
    series = doc["soak"]["timeline"]
    assert len(series) >= 5, "timeline recorded no real series"
    assert series[-1]["engine"]["geometry_hash"]
    assert series[-1]["orders"] > 0  # flow counters fed by the hot path
    (cfg,) = doc["latency"]["configs"]
    assert cfg["measured"] is True
    assert cfg["pipeline_depth"] == 1
    assert cfg["stages"], "no per-stage breakdown"
    assert cfg["p50_ms"] > 0 and cfg["p99_ms"] >= cfg["p50_ms"]
    tl = json.loads((tmp_path / "timeline.json").read_text())
    assert len(tl["samples"]) == len(series)


def test_committed_soak_artifact_is_green():
    """Acceptance pin: the committed SOAK_r01.json has a green verdict
    block and a MEASURED latency section covering the depth-1 and
    16K-frame configurations (no projected numbers)."""
    with open(os.path.join(_REPO, "SOAK_r01.json")) as f:
        doc = json.load(f)
    v = doc["soak"]["verdicts"]
    assert v["pass"] is True
    assert v["live_buffers_flat"]["pass"] and v["rss_bounded"]["pass"]
    assert v["geometry_stable"]["pass"] and v["zero_breaker_trips"]["pass"]
    labels = {c["label"]: c for c in doc["latency"]["configs"]}
    assert any(c["pipeline_depth"] == 1 for c in labels.values())
    assert any(c["frame_orders"] == 16384 for c in labels.values())
    for c in labels.values():
        assert c["measured"] is True
        assert c["p50_ms"] > 0 and c["p99_ms"] > 0
        for stage, row in c["stages"].items():
            assert row["count"] > 0, stage
            assert row["p50_us"] >= 0 and row["p99_us"] >= row["p50_us"]

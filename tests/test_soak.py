"""Soak: a long mixed stream through the engine with mid-stream
snapshot/restore, invariant checks, and oracle parity throughout — the
closest thing to production traffic the CI budget allows."""

import numpy as np

import jax.numpy as jnp

from gome_tpu.engine import BatchEngine, BookConfig
from gome_tpu.oracle import OracleEngine
from gome_tpu.utils.streams import multi_symbol_stream


def test_soak_mixed_stream_with_restore_and_invariants():
    orders = multi_symbol_stream(
        n=3000, n_symbols=40, seed=17, cancel_prob=0.15
    )
    oracle = OracleEngine()
    expected = []
    for o in orders:
        expected.extend(oracle.process(o))

    engine = BatchEngine(
        BookConfig(cap=64, max_fills=8, dtype=jnp.int32), n_slots=8, max_t=32
    )
    got = []
    rng = np.random.default_rng(0)
    for i in range(0, len(orders), 250):
        got.extend(engine.process_columnar(orders[i : i + 250]).to_results())
        engine.verify_books()
        if rng.random() < 0.3:
            # crash/restore mid-stream: a fresh engine resumes from the
            # snapshot with identical downstream events
            state = engine.export_state()
            engine = BatchEngine(
                BookConfig(cap=64, max_fills=8, dtype=jnp.int32),
                n_slots=8,
                max_t=32,
            )
            engine.import_state(state)
    assert got == expected
    assert len(got) > 500  # the stream actually matched at volume


def test_soak_steady_state_live_buffers_flat():
    """Leak detector (gome_tpu.obs.live) on real engine steps: once the
    flow's shapes and escalations have settled, N further engine steps
    must leave the live device-buffer count FLAT — a growing count is a
    leaked buffer (a retained checkpoint, an accumulator outliving its
    frame). The settle phase absorbs the legitimate allocators: first-
    seen compiles (their executables pin constant buffers) and book/cap
    growth."""
    from gome_tpu.obs import live

    engine = BatchEngine(
        BookConfig(cap=64, max_fills=8, dtype=jnp.int32), n_slots=8,
        max_t=32,
    )
    # Cancel-heavy stationary flow (resting depth stays bounded, so no
    # mid-measurement cap escalation mints fresh executables).
    orders = multi_symbol_stream(
        n=2000, n_symbols=8, seed=23, cancel_prob=0.5
    )
    chunks = [orders[i : i + 250] for i in range(0, len(orders), 250)]
    i = 0

    def step():
        nonlocal i
        engine.process_columnar(chunks[i % len(chunks)])
        i += 1

    # settle = one full pass (every chunk's shapes compile + books reach
    # steady depth), then the whole second pass must hold the baseline.
    report = live.assert_steady_state(
        step, steps=len(chunks), settle=len(chunks)
    )
    assert report["counts"], report

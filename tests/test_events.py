"""Columnar decode path (engine/events.py): exact equivalence with the
per-op object decoder and with the oracle, plus wire-format byte parity."""


from gome_tpu.bus.codec import encode_match_result
from gome_tpu.engine import BatchEngine, BookConfig
from gome_tpu.oracle import OracleEngine
from gome_tpu.utils.streams import mixed_stream


def _fresh_engines(**kw):
    mk = lambda: BatchEngine(
        BookConfig(cap=32, max_fills=4), n_slots=8, max_t=16, **kw
    )
    return mk(), mk()


def test_columnar_equals_object_decode():
    """Same mixed stream (fills, partial fills, cancels, market orders)
    through both decode paths -> identical MatchResult lists."""
    orders = mixed_stream(n=220, seed=13, cancel_prob=0.25, market_prob=0.1)
    obj_engine, col_engine = _fresh_engines()
    obj_events, col_events = [], []
    for i in range(0, len(orders), 50):
        chunk = orders[i : i + 50]
        obj_events.extend(obj_engine.process(chunk))
        col_events.extend(col_engine.process_columnar(chunk).to_results())
    assert obj_events == col_events
    assert len(obj_events) > 0


def test_columnar_matches_oracle():
    orders = mixed_stream(n=150, seed=4, cancel_prob=0.2)
    oracle = OracleEngine()
    expected = []
    for o in orders:
        expected.extend(oracle.process(o))
    engine = BatchEngine(BookConfig(cap=64, max_fills=8), n_slots=8, max_t=32)
    got = []
    for i in range(0, len(orders), 40):
        got.extend(engine.process_columnar(orders[i : i + 40]).to_results())
    assert got == expected


def test_columnar_survives_fill_record_escalation():
    """An op crossing more resting orders than max_fills forces the per-lane
    escalation re-run; the columnar splice must carry the wider records."""
    from gome_tpu.types import Order, Side

    engine = BatchEngine(BookConfig(cap=32, max_fills=2), n_slots=2, max_t=32)
    orders = [
        Order(uuid="m", oid=f"a{i}", symbol="s", side=Side.SALE,
              price=100 + i, volume=5)
        for i in range(8)
    ] + [
        Order(uuid="t", oid="big", symbol="s", side=Side.BUY,
              price=200, volume=38)
    ]
    batch = engine.process_columnar(orders)
    events = batch.to_results()
    assert len(events) == 8  # all eight makers filled
    assert engine.stats.fill_record_escalations >= 1
    assert [e.match_node.oid for e in events] == [f"a{i}" for i in range(8)]
    # taker remainder after each fill decreases to 38 - 40 < 0 -> last fill
    # partial? 8x5 = 40 > 38: final maker partially filled
    assert events[-1].match_volume == 3


def test_columnar_two_lanes_escalate_with_different_budgets():
    """Two lanes escalating fill records in the same grid with DIFFERENT
    grown budgets K' (regression: the override splice assumed one width)."""
    from gome_tpu.types import Order, Side

    engine = BatchEngine(BookConfig(cap=64, max_fills=2), n_slots=2, max_t=64)
    orders = []
    # lane a: 17 resting makers, taker crosses all -> K' = 32
    orders += [
        Order(uuid="m", oid=f"a{i}", symbol="a", side=Side.SALE,
              price=100 + i, volume=2)
        for i in range(17)
    ]
    # lane b: 5 resting makers, taker crosses all -> K' = 8
    orders += [
        Order(uuid="m", oid=f"b{i}", symbol="b", side=Side.SALE,
              price=100 + i, volume=2)
        for i in range(5)
    ]
    orders.append(Order(uuid="t", oid="ta", symbol="a", side=Side.BUY,
                        price=200, volume=100))
    orders.append(Order(uuid="t", oid="tb", symbol="b", side=Side.BUY,
                        price=200, volume=100))

    col = BatchEngine(BookConfig(cap=64, max_fills=2), n_slots=2, max_t=64)
    obj_events = engine.process(orders)
    col_events = col.process_columnar(orders).to_results()
    assert col_events == obj_events
    assert sum(1 for e in obj_events if e.match_node.oid.startswith("a")) == 17
    assert sum(1 for e in obj_events if e.match_node.oid.startswith("b")) == 5


def test_json_lines_byte_parity_with_codec():
    orders = mixed_stream(n=120, seed=7, cancel_prob=0.3, market_prob=0.05)
    obj_engine, col_engine = _fresh_engines()
    obj_events = obj_engine.process(orders)
    batch = col_engine.process_columnar(orders)
    expected = [encode_match_result(e) for e in obj_events]
    assert batch.to_json_lines() == expected


def test_orchestrator_columnar_admission_parity():
    """MatchEngine.process_columnar applies the same pre-pool admission as
    process (ADD dropped when cancelled-before-consume)."""
    from gome_tpu.engine.orchestrator import MatchEngine
    from gome_tpu.types import Action, Order, Side

    mk = lambda: MatchEngine(BookConfig(cap=32, max_fills=4), n_slots=4)
    a, b = mk(), mk()
    orders = mixed_stream(n=120, seed=3, cancel_prob=0.2)
    for e in (a, b):
        for o in orders:
            e.mark(o)
    # cancel-before-consume: unmark one ADD before processing
    victim = next(o for o in orders if o.action is Action.ADD)
    for e in (a, b):
        e.pre_pool.discard((victim.symbol, victim.uuid, victim.oid))
    obj = a.process(orders)
    col = b.process_columnar(orders).to_results()
    assert obj == col
    assert a.stats.dropped_no_prepool == b.stats.dropped_no_prepool == 1


def test_empty_batch():
    engine = BatchEngine(BookConfig(cap=16, max_fills=4), n_slots=2)
    batch = engine.process_columnar([])
    assert len(batch) == 0
    assert batch.to_results() == []
    assert batch.to_json_lines() == []

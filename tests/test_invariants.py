"""Property/invariant tests (SURVEY §4's prescribed strategy): volume
conservation and book non-crossing after every step, on randomized streams,
checked on BOTH the oracle and the device engine."""

import random

import numpy as np
import pytest

from gome_tpu.engine import BatchEngine, BookConfig
from gome_tpu.oracle import OracleEngine
from gome_tpu.types import Action, Side
from gome_tpu.utils.streams import mixed_stream


def book_not_crossed(books, lane):
    """best bid < best ask whenever both sides are populated (a crossed book
    after a step means matching failed to consume a crossing order)."""
    nb = int(books.count[lane, 0])
    na = int(books.count[lane, 1])
    if nb == 0 or na == 0:
        return True
    return int(books.price[lane, 0, 0]) < int(books.price[lane, 1, 0])


def engine_resting_volume(books, lane):
    nb = int(books.count[lane, 0])
    na = int(books.count[lane, 1])
    return int(books.lots[lane, 0, :nb].sum() + books.lots[lane, 1, :na].sum())


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_volume_conservation_and_non_crossing(seed):
    """For every prefix of a mixed stream:
      sum(admitted ADD volumes) ==
        2*sum(fill qty) + sum(cancelled remainders)
        + resting volume + market remainders dropped
    and the book never ends a batch crossed."""
    orders = mixed_stream(
        n=300, seed=seed, cancel_prob=0.2, market_prob=0.1
    )
    engine = BatchEngine(BookConfig(cap=64, max_fills=16), n_slots=2, max_t=8)
    oracle = OracleEngine()

    admitted_volume = 0
    filled = 0
    cancelled = 0
    for i in range(0, len(orders), 16):
        chunk = orders[i : i + 16]
        for o in chunk:
            oracle.submit(o)
        oracle_events = oracle.drain()
        events = engine.process(chunk)
        assert events == oracle_events  # parity while we're at it

        for o in chunk:
            if o.action is Action.ADD:
                admitted_volume += o.volume
        for ev in events:
            if ev.is_cancel:
                cancelled += ev.node.volume
            else:
                filled += 2 * ev.match_volume
        books = engine.lane_books()
        lane = engine.symbol_lane("eth2usdt")
        assert book_not_crossed(books, lane), f"crossed book at chunk {i}"

        # market remainders are dropped (extension semantics): recompute
        # from events — taker_remaining isn't surfaced per event, so use
        # the oracle's book as the balance reference instead.
        resting = engine_resting_volume(books, lane)
        ob = oracle.book("eth2usdt")
        oracle_resting = sum(o.volume for o in ob.orders(Side.BUY)) + sum(
            o.volume for o in ob.orders(Side.SALE)
        )
        assert resting == oracle_resting
        # full balance: admitted = taker-filled + maker-filled + cancelled
        # + resting + dropped-market-remainders (the residual)
        residual = admitted_volume - filled - cancelled - resting
        assert residual >= 0  # only market drops may remain unaccounted


def test_seq_monotonic_within_level():
    """Time-priority stamps strictly increase along every price level's FIFO
    (slot order == arrival order)."""
    rng = random.Random(7)
    from gome_tpu.fixed import scale
    from gome_tpu.types import Order

    engine = BatchEngine(BookConfig(cap=64, max_fills=8), n_slots=2, max_t=64)
    orders = [
        Order(
            uuid="u", oid=str(i), symbol="s",
            side=Side(rng.randrange(2)),
            price=scale(round(rng.uniform(0.95, 1.05), 2)),
            volume=scale(1.0),
        )
        for i in range(60)
    ]
    engine.process(orders)
    books = engine.lane_books()
    lane = engine.symbol_lane("s")
    for side in (0, 1):
        n = int(books.count[lane, side])
        prices = books.price[lane, side, :n]
        seqs = books.seq[lane, side, :n]
        for i in range(1, n):
            if prices[i] == prices[i - 1]:
                assert seqs[i] > seqs[i - 1], (side, i)


def test_priority_sorted_slots():
    """Slots are priority-sorted: bids descending, asks ascending."""
    rng = random.Random(11)
    from gome_tpu.fixed import scale
    from gome_tpu.types import Order

    engine = BatchEngine(BookConfig(cap=64, max_fills=8), n_slots=2, max_t=64)
    orders = [
        Order(
            uuid="u", oid=str(i), symbol="s",
            side=Side(rng.randrange(2)),
            price=scale(round(rng.uniform(0.90, 1.10), 2)),
            volume=scale(1.0),
        )
        for i in range(50)
    ]
    engine.process(orders)
    books = engine.lane_books()
    lane = engine.symbol_lane("s")
    nb = int(books.count[lane, 0])
    na = int(books.count[lane, 1])
    bids = books.price[lane, 0, :nb]
    asks = books.price[lane, 1, :na]
    assert (np.diff(bids) <= 0).all()
    assert (np.diff(asks) >= 0).all()
    # active slots hold positive lots; inactive slots are zeroed
    assert (books.lots[lane, 0, :nb] > 0).all()
    assert (books.lots[lane, 0, nb:] == 0).all()

"""Protocol-strictness tests for the from-scratch RESP and AMQP clients.

Both clients normally talk to fakes written by the same author
(persist/respserver.py, bus/fakebroker.py) — a shared encoding quirk would
pass every functional test. These tests inject the behaviors the fakes
never produce in healthy runs (mid-pipeline death, protocol errors,
heartbeat expiry, server-initiated channel close, tiny negotiated frame
sizes) and pin that the clients fail LOUDLY (typed exceptions, bounded
time) and recoverably (a fresh connection works; no hangs)."""

import socket
import struct
import threading
import time

import pytest

from gome_tpu.bus.amqp import AmqpQueue
from gome_tpu.bus.fakebroker import FakeBroker
from gome_tpu.persist.resp import RespClient, RespError
from gome_tpu.persist.respserver import FakeRedisServer


# --- scripted RESP server -------------------------------------------------


class _ScriptedResp:
    """One-connection TCP server that answers each received buffer flush
    with the next canned byte string (then optionally dies)."""

    def __init__(self, replies, close_after: int | None = None):
        self.replies = list(replies)
        self.close_after = close_after
        self._srv = socket.create_server(("127.0.0.1", 0))
        self.port = self._srv.getsockname()[1]
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        conn, _ = self._srv.accept()
        with conn:
            served = 0
            while self.replies:
                data = conn.recv(65536)
                if not data:
                    return
                conn.sendall(self.replies.pop(0))
                served += 1
                if self.close_after is not None and served >= self.close_after:
                    return  # abrupt close

    def stop(self):
        self._srv.close()


class TestRespFaults:
    def test_server_close_mid_pipeline_raises(self):
        # 3 commands pipelined; the server answers only one reply's worth
        # of bytes then closes. The client must raise ConnectionError, not
        # hang or fabricate replies.
        srv = _ScriptedResp([b":1\r\n"], close_after=1)
        try:
            c = RespClient(port=srv.port, timeout_s=5)
            with pytest.raises(ConnectionError):
                c.pipeline([("HDEL", "k", "a"), ("HDEL", "k", "b"),
                            ("HDEL", "k", "c")])
            c.close()
        finally:
            srv.stop()

    def test_malformed_reply_type_raises_resp_error(self):
        srv = _ScriptedResp([b"?what\r\n"])
        try:
            c = RespClient(port=srv.port, timeout_s=5)
            with pytest.raises(RespError, match="malformed"):
                c.execute_command("PING")
            c.close()
        finally:
            srv.stop()

    def test_partial_bulk_then_close_raises(self):
        # Bulk header promises 100 bytes; only 5 arrive before close.
        srv = _ScriptedResp([b"$100\r\nhello"], close_after=1)
        try:
            c = RespClient(port=srv.port, timeout_s=5)
            with pytest.raises(ConnectionError):
                c.execute_command("GET", "k")
            c.close()
        finally:
            srv.stop()

    def test_pipeline_errors_in_place_and_connection_survives(self):
        # Against the real fake server: an unknown command mid-pipeline
        # returns a RespError IN PLACE; the commands after it still get
        # their replies and the connection keeps working.
        with FakeRedisServer() as srv:
            c = RespClient(port=srv.port)
            replies = c.pipeline(
                [("HSET", "h", "f", "1"), ("NOSUCH",), ("HDEL", "h", "f")]
            )
            assert replies[0] == 1
            assert isinstance(replies[1], RespError)
            assert replies[2] == 1
            assert c.ping()
            c.close()

    def test_fake_server_accepts_inline_commands(self):
        # Real-Redis parity the RESP client never exercises: telnet-style
        # inline commands (redis-cli's bare lines).
        with FakeRedisServer() as srv:
            s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
            try:
                s.sendall(b"PING\r\nHSET h f 1\r\nHEXISTS h f\r\n")
                got = b""
                deadline = time.monotonic() + 5
                while got.count(b"\r\n") < 3:
                    assert time.monotonic() < deadline
                    got += s.recv(4096)
                assert got == b"+PONG\r\n:1\r\n:1\r\n"
            finally:
                s.close()


# --- AMQP fault modes -----------------------------------------------------


class TestAmqpFaults:
    def test_heartbeats_keep_idle_connection_alive(self):
        # Broker proposes 1s heartbeats and ENFORCES them: an idle client
        # that never sent heartbeats would be dropped within ~2.5s. Ours
        # must survive >3 idle seconds and still round-trip.
        broker = FakeBroker(heartbeat=1).start()
        try:
            q = AmqpQueue("hb", port=broker.port)
            time.sleep(3.2)  # idle: only heartbeats flow
            q.publish(b"alive")
            msgs = q.read_from(0, 10)
            assert [m.body for m in msgs] == [b"alive"]
            q.close()
        finally:
            broker.stop()

    def test_silent_broker_trips_heartbeat_expiry(self):
        # Broker negotiates 1s heartbeats but never sends traffic (fault
        # mode): the client must declare the peer dead in bounded time and
        # fail the next publish loudly instead of blocking forever.
        broker = FakeBroker(heartbeat=1, mute_heartbeats=True).start()
        try:
            q = AmqpQueue("dead", port=broker.port)
            deadline = time.monotonic() + 10
            while not q._closed:
                assert time.monotonic() < deadline, "expiry never detected"
                time.sleep(0.1)
            with pytest.raises(ConnectionError):
                q.publish(b"x")
        finally:
            broker.stop()

    def test_small_negotiated_frame_max_splits_and_reassembles(self):
        broker = FakeBroker(frame_max=4096).start()
        try:
            q = AmqpQueue("big", port=broker.port)
            assert q._frame_max == 4096
            body = bytes(range(256)) * 80  # 20480 bytes > 4 frames
            q.publish(body)
            msgs = q.read_from(0, 10)
            assert len(msgs) == 1 and msgs[0].body == body
            q.close()
        finally:
            broker.stop()

    def test_server_initiated_channel_close_fails_loudly(self):
        broker = FakeBroker(channel_close_on_publish=2).start()
        try:
            q = AmqpQueue("chan", port=broker.port)
            q.publish(b"ok")
            # The 2nd publish draws Channel.Close; the failure surfaces on
            # that call or the next (the close races the local send).
            with pytest.raises(ConnectionError):
                deadline = time.monotonic() + 10
                while True:
                    assert time.monotonic() < deadline, "never failed"
                    q.publish(b"boom")
                    time.sleep(0.05)
        finally:
            broker.stop()

    def test_abrupt_broker_death_mid_stream(self):
        # kill -9 shape: the socket just dies. Publish must raise in
        # bounded time and a FRESH connection to a healthy broker works
        # (recoverability is reconnection, not limping on).
        broker = FakeBroker(close_abruptly_on_publish=3).start()
        try:
            q = AmqpQueue("crash", port=broker.port)
            q.publish(b"a")
            q.publish(b"b")
            with pytest.raises(ConnectionError):
                deadline = time.monotonic() + 10
                while True:
                    assert time.monotonic() < deadline, "never failed"
                    q.publish(b"x")
                    time.sleep(0.05)
        finally:
            broker.stop()
        broker2 = FakeBroker().start()
        try:
            q2 = AmqpQueue("crash", port=broker2.port)
            q2.publish(b"again")
            assert [m.body for m in q2.read_from(0, 10)] == [b"again"]
            q2.close()
        finally:
            broker2.stop()

    def test_oversized_frame_header_rejected(self):
        # A corrupt size field must fail the connection, not allocate GBs.
        from gome_tpu.bus.amqp import read_frame

        a, b = socket.socketpair()
        try:
            a.sendall(struct.pack(">BHI", 1, 0, 1 << 30))
            b.settimeout(5)
            with pytest.raises(ConnectionError, match="sanity"):
                read_frame(b)
        finally:
            a.close()
            b.close()

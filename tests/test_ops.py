"""Operator HTTP endpoint (service.ops.OpsServer): /metrics serves the
Prometheus registry, /healthz reflects HealthMonitor state, both wired into
EngineService via the `ops:` config section and reachable over a real HTTP
socket."""

import json
import urllib.error
import urllib.request

from gome_tpu.config import Config, EngineConfig, OpsConfig
from gome_tpu.service.app import EngineService
from gome_tpu.types import Order, Side


def _get(port, path):
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10
        ) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def test_ops_endpoint_serves_metrics_and_health():
    cfg = Config(
        engine=EngineConfig(cap=16, max_fills=4, n_slots=4, max_t=4),
        ops=OpsConfig(port=0, enabled=True),
    )
    svc = EngineService(cfg)
    svc.ops.start()
    try:
        port = svc.ops.port
        # Some traffic so counters move.
        o = Order(uuid="u", oid="1", symbol="s", side=Side.BUY, price=100,
                  volume=5)
        svc.engine.mark(o)
        from gome_tpu.bus import encode_order

        svc.bus.order_queue.publish(encode_order(o))
        svc.pump()

        status, body = _get(port, "/metrics")
        assert status == 200
        assert "gome_orders_consumed_total" in body
        assert "# TYPE" in body  # prometheus text format

        status, body = _get(port, "/healthz")
        # Threads not started (synchronous pump) => unhealthy 503, but the
        # payload is well-formed and reflects real state.
        health = json.loads(body)
        assert health["order_lag"] == 0
        assert health["detail"]["orders_processed"] >= 1
        assert status in (200, 503)

        status, _ = _get(port, "/nope")
        assert status == 404
    finally:
        svc.ops.stop()


def test_ops_endpoint_healthy_when_running():
    cfg = Config(
        engine=EngineConfig(cap=16, max_fills=4, n_slots=4, max_t=4),
        ops=OpsConfig(port=0, enabled=True),
    )
    svc = EngineService(cfg)
    svc.consumer.start()
    svc.feed.start()
    svc.ops.start()
    try:
        status, body = _get(svc.ops.port, "/healthz")
        assert status == 200, body
        assert json.loads(body)["healthy"] is True
    finally:
        svc.stop()


def test_ops_config_yaml_section(tmp_path):
    p = tmp_path / "config.yaml"
    p.write_text("ops:\n  port: 0\n")
    from gome_tpu.config import load_config

    cfg = load_config(str(p))
    assert cfg.ops.enabled and cfg.ops.port == 0
    svc = EngineService(cfg)
    assert svc.ops is not None

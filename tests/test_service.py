"""End-to-end service tests: gRPC gateway → bus → consumer → engine →
matchOrder feed, against the oracle as referee (SURVEY §3.1-3.4 call paths).
"""

import grpc
import pytest

from gome_tpu.api import order_pb2 as pb
from gome_tpu.api.service import OrderStub
from gome_tpu.bus import decode_match_result
from gome_tpu.config import Config, EngineConfig, GrpcConfig
from gome_tpu.oracle import OracleEngine
from gome_tpu.service import EngineService
from gome_tpu.types import MatchResult, Order, Side


def make_service(**engine_kw):
    cfg = Config(
        grpc=GrpcConfig(host="127.0.0.1", port=0),  # ephemeral port
        engine=EngineConfig(cap=32, n_slots=8, max_t=8, **engine_kw),
    )
    return EngineService(cfg)


class TestEndToEnd:
    def setup_method(self):
        self.svc = make_service()
        from concurrent import futures

        from gome_tpu.api.service import add_order_servicer

        self.server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
        add_order_servicer(self.server, self.svc.gateway)
        self.port = self.server.add_insecure_port("127.0.0.1:0")
        assert self.port != 0
        self.server.start()
        self.channel = grpc.insecure_channel(f"127.0.0.1:{self.port}")
        self.stub = OrderStub(self.channel)

    def teardown_method(self):
        self.channel.close()
        self.server.stop(grace=None)

    def do(self, uuid, oid, side, price, volume, kind=0):
        return self.stub.DoOrder(
            pb.OrderRequest(
                uuid=uuid, oid=oid, symbol="eth2usdt",
                transaction=side, price=price, volume=volume, kind=kind,
            )
        )

    def test_submit_match_cancel_flow(self):
        # SALE 1.00 x 5 rests; BUY 1.00 x 3 fills 3; cancel ask remainder.
        r1 = self.do("u1", "a1", pb.SALE, 1.00, 5.0)
        assert r1.code == 0 and "accepted" in r1.message
        r2 = self.do("u2", "b1", pb.BUY, 1.00, 3.0)
        assert r2.code == 0
        assert self.svc.pump() == 2

        msgs = self.svc.bus.match_queue.read_from(0, 10)
        events = [decode_match_result(m.body) for m in msgs]
        assert len(events) == 1
        ev = events[0]
        assert ev.match_volume == 3 * 10**8
        assert ev.node.oid == "b1" and ev.match_node.oid == "a1"
        assert ev.match_node.price == 10**8  # fill at maker level
        assert ev.match_node.volume == 2 * 10**8  # partial: remaining

        r3 = self.stub.DeleteOrder(
            pb.OrderRequest(
                uuid="u1", oid="a1", symbol="eth2usdt",
                transaction=pb.SALE, price=1.00, volume=5.0,
            )
        )
        assert r3.code == 0
        self.svc.pump()
        events = [
            decode_match_result(m.body)
            for m in self.svc.bus.match_queue.read_from(0, 10)
        ]
        assert len(events) == 2
        assert events[1].is_cancel
        assert events[1].node.volume == 2 * 10**8  # remaining at cancel

    def test_gateway_rejects_bad_input(self):
        r = self.do("u", "x", pb.BUY, 1.0, 0.0)
        assert r.code == 3  # volume must be positive
        r = self.do("u", "x2", pb.BUY, 0.0, 1.0)
        assert r.code == 3  # limit price must be positive
        r = self.do("u", "x3", pb.BUY, 1.000000001, 1.0)  # > accuracy=8 dp? no: 9dp
        assert r.code == 3
        self.svc.pump()
        assert self.svc.bus.match_queue.end_offset() == 0

    def test_market_order_extension(self):
        self.do("m1", "s1", pb.SALE, 1.00, 5.0)
        self.do("m2", "t1", pb.BUY, 0.0, 2.0, kind=pb.MARKET)
        self.svc.pump()
        events = [
            decode_match_result(m.body)
            for m in self.svc.bus.match_queue.read_from(0, 10)
        ]
        assert len(events) == 1
        assert events[0].match_volume == 2 * 10**8
        assert events[0].match_node.price == 10**8

    def test_cancel_before_consume_race(self):
        """SURVEY §2.3.3: DEL consumed before the queued ADD kills it via the
        pre-pool."""
        self.do("u1", "r1", pb.SALE, 1.00, 5.0)  # marked + queued
        self.stub.DeleteOrder(
            pb.OrderRequest(
                uuid="u1", oid="r1", symbol="eth2usdt",
                transaction=pb.SALE, price=1.00, volume=5.0,
            )
        )
        # Reorder delivery: consumer sees DEL first (simulates the race the
        # reference handles via the pre-pool). With FIFO bus both arrive in
        # one batch; the admission loop clears the mark on DEL only if DEL
        # precedes — here ADD precedes so it IS admitted, then DEL cancels.
        self.svc.pump()
        books = self.svc.engine.batch.lane_books()
        assert int(books.count.sum()) == 0  # nothing left resting

    def test_subscribe_stream_delivers(self):
        sub = self.stub.SubscribeMatches(pb.SubscribeRequest())
        self.do("u1", "a1", pb.SALE, 1.00, 1.0)
        self.do("u2", "b1", pb.BUY, 1.00, 1.0)
        self.svc.pump()
        ev = next(iter(sub))
        assert ev.match_volume == pytest.approx(1e8)
        assert ev.node.oid == "b1"
        sub.cancel()


def test_service_parity_vs_oracle():
    """Full mixed stream through the service loop equals the oracle's event
    stream (the §4 golden-replay strategy at the service layer)."""
    from gome_tpu.utils.streams import mixed_stream

    svc = make_service()
    oracle = OracleEngine()
    orders = mixed_stream(n=300, seed=11, cancel_prob=0.25)
    expected: list[MatchResult] = []
    for o in orders:
        expected.extend(oracle.process(o))

    got: list[MatchResult] = []
    for o in orders:
        svc.engine.mark(o)
    from gome_tpu.bus import encode_order

    for o in orders:
        svc.bus.order_queue.publish(encode_order(o))
    svc.pump()
    got = [
        decode_match_result(m.body)
        for m in svc.bus.match_queue.read_from(
            0, svc.bus.match_queue.end_offset()
        )
    ]
    assert got == expected


class TestFrameBatcher:
    """The gateway->frame batching bridge (service.batcher): per-request
    gRPC traffic leaves as columnar ORDER frames (SURVEY L4's missing
    production story: who aggregates, at what latency cost)."""

    def _orders(self, n, start=0):
        from gome_tpu.types import Action, Order, OrderType, Side

        return [
            Order(
                uuid="u", oid=f"o{start + i}", symbol="s", side=Side.BUY,
                price=100, volume=1, action=Action.ADD,
                order_type=OrderType.LIMIT,
            )
            for i in range(n)
        ]

    def test_size_bound_flush_preserves_order(self):
        from gome_tpu.bus import MemoryQueue
        from gome_tpu.bus.colwire import decode_order_frame
        from gome_tpu.service.batcher import FrameBatcher

        q = MemoryQueue("doOrder")
        b = FrameBatcher(q, max_n=16, max_wait_s=60)
        for o in self._orders(40):
            b.submit(o)
        try:
            # Two full frames flushed by size; 8 remain buffered.
            msgs = q.read_from(0, 10)
            assert len(msgs) == 2
            oids = []
            for m in msgs:
                cols = decode_order_frame(m.body)
                assert cols["n"] == 16
                oids.extend(x.decode() for x in cols["oids"])
            assert oids == [f"o{i}" for i in range(32)]
            assert b.flush() == 8
            cols = decode_order_frame(q.read_from(2, 10)[0].body)
            assert [x.decode() for x in cols["oids"]] == [
                f"o{i}" for i in range(32, 40)
            ]
        finally:
            b.close()

    def test_deadline_flush(self):
        import time

        from gome_tpu.bus import MemoryQueue
        from gome_tpu.service.batcher import FrameBatcher

        q = MemoryQueue("doOrder")
        b = FrameBatcher(q, max_n=1 << 20, max_wait_s=0.05)
        try:
            for o in self._orders(5):
                b.submit(o)
            deadline = time.monotonic() + 5
            while q.end_offset() == 0:
                assert time.monotonic() < deadline, "deadline never flushed"
                time.sleep(0.01)
            from gome_tpu.bus.colwire import decode_order_frame

            assert decode_order_frame(q.read_from(0, 1)[0].body)["n"] == 5
        finally:
            b.close()

    def test_close_flushes_remainder(self):
        from gome_tpu.bus import MemoryQueue
        from gome_tpu.service.batcher import FrameBatcher

        q = MemoryQueue("doOrder")
        b = FrameBatcher(q, max_n=100, max_wait_s=60)
        for o in self._orders(7):
            b.submit(o)
        b.close()
        assert q.end_offset() == 1


class TestGatewayBatcherEndToEnd:
    """Real channel -> OrderGateway(batcher=...) -> ORDER frames -> frame
    consumer: the gRPC-inclusive ingest path, oracle-checked."""

    def test_grpc_to_frames_to_events(self):
        from concurrent import futures

        from gome_tpu.api.service import add_order_servicer
        from gome_tpu.bus import MemoryQueue, QueueBus
        from gome_tpu.bus.colwire import decode_event_frame, is_frame
        from gome_tpu.engine import BookConfig
        from gome_tpu.engine.orchestrator import MatchEngine
        from gome_tpu.service.batcher import FrameBatcher
        from gome_tpu.service.consumer import OrderConsumer
        from gome_tpu.service.gateway import OrderGateway

        engine = MatchEngine(
            config=BookConfig(cap=32, max_fills=8), n_slots=8, max_t=8
        )
        bus = QueueBus(MemoryQueue("doOrder"), MemoryQueue("matchOrder"))
        batcher = FrameBatcher(bus.order_queue, max_n=8, max_wait_s=60)
        gw = OrderGateway(bus, accuracy=8, mark=engine.mark, batcher=batcher)
        server = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
        add_order_servicer(server, gw)
        port = server.add_insecure_port("127.0.0.1:0")
        server.start()
        oracle = OracleEngine()
        try:
            with grpc.insecure_channel(f"127.0.0.1:{port}") as ch:
                stub = OrderStub(ch)
                reqs = [
                    ("u1", "a1", pb.SALE, 1.00, 5.0),
                    ("u2", "b1", pb.BUY, 1.00, 3.0),
                    ("u1", "a2", pb.SALE, 1.01, 2.0),
                    ("u2", "b2", pb.BUY, 1.01, 4.0),
                ]
                for uuid, oid, side, price, vol in reqs:
                    r = stub.DoOrder(
                        pb.OrderRequest(
                            uuid=uuid, oid=oid, symbol="s",
                            transaction=side, price=price, volume=vol,
                        )
                    )
                    assert r.code == 0
                # Cancel b2's remainder over gRPC too.
                stub.DeleteOrder(
                    pb.OrderRequest(
                        uuid="u2", oid="b2", symbol="s",
                        transaction=pb.BUY, price=1.01, volume=0,
                    )
                )
            batcher.close()
            # Everything left as ONE frame (5 ops < max_n after close).
            msgs = bus.order_queue.read_from(0, 10)
            assert len(msgs) == 1 and is_frame(msgs[0].body)
            consumer = OrderConsumer(
                engine, bus, batch_n=8, batch_wait_s=0, match_wire="frame"
            )
            consumer.drain()
            got = []
            for m in bus.match_queue.read_from(0, 100):
                got.extend(decode_event_frame(m.body).to_results())
            from gome_tpu.types import Action, Order, OrderType, Side
            from gome_tpu.fixed import scale

            expected = []
            for uuid, oid, side, price, vol in reqs:
                expected.extend(
                    oracle.process(
                        Order(
                            uuid=uuid, oid=oid, symbol="s",
                            side=Side(side), price=scale(price, 8),
                            volume=scale(vol, 8), action=Action.ADD,
                            order_type=OrderType.LIMIT,
                        )
                    )
                )
            expected.extend(
                oracle.process(
                    Order(
                        uuid="u2", oid="b2", symbol="s", side=Side.BUY,
                        price=scale(1.01, 8), volume=0, action=Action.DEL,
                        order_type=OrderType.LIMIT,
                    )
                )
            )
            assert got == expected
        finally:
            server.stop(grace=None)


def test_engine_service_mesh_devices_config():
    """EngineConfig.mesh_devices shards the service's engine over a 1-D
    device mesh at construction — the config-level deployment knob for a
    mesh-sharded consumer (VERDICT r4 #4)."""
    import jax

    from gome_tpu.api import order_pb2 as pb
    from gome_tpu.config import Config, EngineConfig, GrpcConfig

    svc = EngineService(
        Config(
            grpc=GrpcConfig(port=0),
            engine=EngineConfig(
                cap=16, n_slots=8, max_t=8, mesh_devices=4
            ),
        )
    )
    assert svc.engine.batch.mesh is not None
    assert svc.engine.batch.mesh.size == 4
    r = svc.gateway.DoOrder(
        pb.OrderRequest(
            uuid="u", oid="a", symbol="eth2usdt",
            transaction=pb.SALE, price=2.0, volume=1.0,
        ),
        None,
    )
    assert r.code == 0
    r = svc.gateway.DoOrder(
        pb.OrderRequest(
            uuid="u", oid="b", symbol="eth2usdt",
            transaction=pb.BUY, price=2.0, volume=1.0,
        ),
        None,
    )
    assert r.code == 0
    svc.pump()
    msgs = svc.bus.match_queue.read_from(0, 100)
    assert len(msgs) == 1  # the cross matched while sharded
    specs = {
        str(getattr(l.sharding, "spec", None))
        for l in jax.tree.leaves(svc.engine.books)
    }
    assert "PartitionSpec('sym',)" in specs


class TestBatchIngestRpc:
    """DoOrderBatch / DoOrderStream (the amortized front door, VERDICT r4
    #3): same admission semantics as the unary RPCs, same event stream,
    per-order rejects reported, same-batch ADD->DEL ordering preserved."""

    def _setup(self, max_n=64):
        from gome_tpu.bus import MemoryQueue, QueueBus
        from gome_tpu.engine import BookConfig
        from gome_tpu.engine.orchestrator import MatchEngine
        from gome_tpu.service.batcher import FrameBatcher
        from gome_tpu.service.consumer import OrderConsumer
        from gome_tpu.service.gateway import OrderGateway

        engine = MatchEngine(
            config=BookConfig(cap=32, max_fills=8), n_slots=8, max_t=16
        )
        bus = QueueBus(MemoryQueue("doOrder"), MemoryQueue("matchOrder"))
        batcher = FrameBatcher(bus.order_queue, max_n=max_n, max_wait_s=60)
        gw = OrderGateway(
            bus, accuracy=8, mark=engine.mark, unmark=engine.unmark,
            batcher=batcher,
        )
        consumer = OrderConsumer(
            engine, bus, batch_n=64, batch_wait_s=0, match_wire="frame"
        )
        return engine, bus, batcher, gw, consumer

    def _req(self, uuid, oid, side, price, vol):
        return pb.OrderRequest(
            uuid=uuid, oid=oid, symbol="s", transaction=side,
            price=price, volume=vol,
        )

    def test_batch_rpc_matches_unary_semantics(self):
        from concurrent import futures

        from gome_tpu.bus.colwire import decode_event_frame

        engine, bus, batcher, gw, consumer = self._setup()
        server = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
        from gome_tpu.api.service import add_order_servicer

        add_order_servicer(server, gw)
        port = server.add_insecure_port("127.0.0.1:0")
        server.start()
        try:
            with grpc.insecure_channel(f"127.0.0.1:{port}") as ch:
                stub = OrderStub(ch)
                orders = [
                    self._req("u1", "a1", pb.SALE, 1.00, 5.0),
                    self._req("u2", "b1", pb.BUY, 1.00, 3.0),
                    self._req("u1", "a2", pb.SALE, 1.01, 2.0),
                    self._req("u2", "bad", pb.BUY, 1.00, -1.0),  # reject
                    self._req("u2", "b2", pb.BUY, 1.01, 4.0),
                    self._req("u2", "b2", pb.BUY, 1.01, 0.0),  # cancel b2
                ]
                resp = stub.DoOrderBatch(
                    pb.OrderBatchRequest(
                        orders=orders,
                        cancel=[False] * 5 + [True],
                    )
                )
                assert resp.code == 0
                assert resp.accepted == 5
                assert list(resp.reject_index) == [3]
                assert resp.rejects[0].code == 3
                batcher.flush()
                consumer.drain()
        finally:
            server.stop(0)
        # Oracle comparison: the same flow (minus the reject) unary-style.
        oracle = OracleEngine()
        expected = []
        from gome_tpu.fixed import scale
        from gome_tpu.types import Action, Order, Side

        for uuid, oid, side, price, vol, action in [
            ("u1", "a1", Side.SALE, 1.00, 5.0, Action.ADD),
            ("u2", "b1", Side.BUY, 1.00, 3.0, Action.ADD),
            ("u1", "a2", Side.SALE, 1.01, 2.0, Action.ADD),
            ("u2", "b2", Side.BUY, 1.01, 4.0, Action.ADD),
            ("u2", "b2", Side.BUY, 1.01, 0.0, Action.DEL),
        ]:
            expected.extend(
                oracle.process(
                    Order(
                        uuid=uuid, oid=oid, symbol="s", side=side,
                        price=scale(price, 8), volume=scale(vol, 8),
                        action=action,
                    )
                )
            )
        got = []
        for m in bus.match_queue.read_from(0, 100):
            got.extend(decode_event_frame(m.body).to_results())
        assert got == expected

    def test_stream_rpc_and_mask_validation(self):
        from concurrent import futures

        from gome_tpu.api.service import add_order_servicer

        engine, bus, batcher, gw, consumer = self._setup()
        server = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
        add_order_servicer(server, gw)
        port = server.add_insecure_port("127.0.0.1:0")
        server.start()
        try:
            with grpc.insecure_channel(f"127.0.0.1:{port}") as ch:
                stub = OrderStub(ch)
                resp = stub.DoOrderStream(
                    iter(
                        [
                            self._req("u1", "s1", pb.SALE, 1.0, 2.0),
                            self._req("u2", "s2", pb.BUY, 1.0, 2.0),
                        ]
                    )
                )
                assert resp.code == 0 and resp.accepted == 2
                # Mismatched cancel mask is a whole-batch code-3 reject.
                bad = stub.DoOrderBatch(
                    pb.OrderBatchRequest(
                        orders=[self._req("u1", "x", pb.BUY, 1.0, 1.0)],
                        cancel=[False, True],
                    )
                )
                assert bad.code == 3 and bad.accepted == 0
                batcher.flush()
                consumer.drain()
        finally:
            server.stop(0)
        assert len(bus.match_queue.read_from(0, 10)) == 1  # s2 crossed s1

    def test_batch_aborts_cleanly_when_batcher_closed(self):
        engine, bus, batcher, gw, consumer = self._setup()
        batcher.close()
        resp = gw.DoOrderBatch(
            pb.OrderBatchRequest(
                orders=[
                    self._req("u1", "a", pb.SALE, 1.0, 1.0),
                    self._req("u2", "b", pb.BUY, 1.0, 1.0),
                ]
            ),
            None,
        )
        assert resp.code == 3 and resp.accepted == 0
        assert "aborted at entry 0" in resp.message
        # The aborted entry's mark was undone.
        assert len(engine.pre_pool) == 0
